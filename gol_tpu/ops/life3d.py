"""3-D Life: 26-neighbor torus stencil (BASELINE.md config 5, stretch).

A capability *addition* over the reference (which is strictly 2-D,
8-neighbor: gol_kernel, gol-with-cuda.cu:189-262) demonstrating that the
framework's stencil/halo machinery generalizes by dimension.  The 2-D
kernel's separable roll-sum carries straight over: three 3-point sums, one
per axis, build the 3×3×3 cube sum in 6 rolls + 6 adds (vs 26 shifted
adds), and counts (max 27) still fit the uint8 cells.

2-D Life's B3/S23 has no canonical 3-D analog, so the rule is a
parameter: a :class:`Rule3D` of (birth, survive) neighbor-count sets.  The
default is Bays' Life 4555 (birth on 5, survive on 4-5) — the classic
"Game of Life in three dimensions" rule, which supports gliders and
oscillators the way B3/S23 does in 2-D.
"""

from __future__ import annotations

import functools
from typing import FrozenSet, NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from gol_tpu.models.state import CELL_DTYPE


class Rule3D(NamedTuple):
    """Totalistic 3-D rule: counts (of the 26 neighbors) that birth/survive."""

    birth: FrozenSet[int]
    survive: FrozenSet[int]


BAYS_4555 = Rule3D(birth=frozenset({5}), survive=frozenset({4, 5}))
BAYS_5766 = Rule3D(birth=frozenset({6}), survive=frozenset({5, 6, 7}))


def rulestring3d(rule: Rule3D) -> str:
    """Canonical ``B<counts>/S<counts>`` form (comma-separated, sorted) —
    round-trips through ``gol_tpu.cli3d.parse_rule3d``; stamped into 3-D
    checkpoints so resume can refuse a rule mismatch."""

    def fmt(counts):
        return ",".join(str(c) for c in sorted(counts))

    return f"B{fmt(rule.birth)}/S{fmt(rule.survive)}"


def _count_in(n: jax.Array, counts: FrozenSet[int]) -> jax.Array:
    hits = [n == c for c in sorted(counts)]
    # Explicit init keeps the empty set legal (an always-false predicate,
    # e.g. a pure-decay rule with no birth counts).
    return functools.reduce(jnp.logical_or, hits, jnp.zeros_like(n, bool))


def rule3d(vol: jax.Array, neighbors: jax.Array, rule: Rule3D) -> jax.Array:
    """Branchless totalistic update: born where dead, sustained where alive."""
    alive = vol == 1
    nxt = (~alive & _count_in(neighbors, rule.birth)) | (
        alive & _count_in(neighbors, rule.survive)
    )
    return nxt.astype(CELL_DTYPE)


def neighbor_count_torus3d(vol: jax.Array) -> jax.Array:
    """26-neighbor count on a fully periodic volume via separable roll-sums."""
    s = vol
    for ax in (-3, -2, -1):
        s = s + jnp.roll(s, 1, axis=ax) + jnp.roll(s, -1, axis=ax)
    return s - vol


def step3d(vol: jax.Array, rule: Rule3D = BAYS_4555) -> jax.Array:
    """One generation on a fully periodic (3-torus) volume uint8[D, H, W]."""
    return rule3d(vol, neighbor_count_torus3d(vol), rule)


def step3d_halo_full(ext: jax.Array, rule: Rule3D = BAYS_4555) -> jax.Array:
    """One generation given a fully halo-extended volume ``ext[d+2,h+2,w+2]``.

    The 3-D analog of :func:`gol_tpu.ops.stencil.step_halo_full`: no wrap is
    applied — the halo shell (faces, edges, *and* corners) carries all
    periodicity.  Returns the updated interior ``[d, h, w]``.
    """
    s = ext
    for ax in range(3):
        lo = tuple(
            slice(None, -2) if a == ax else slice(None) for a in range(3)
        )
        mid = tuple(
            slice(1, -1) if a == ax else slice(None) for a in range(3)
        )
        hi = tuple(slice(2, None) if a == ax else slice(None) for a in range(3))
        s = s[lo] + s[mid] + s[hi]
    center = ext[1:-1, 1:-1, 1:-1]
    return rule3d(center, s - center, rule)


@functools.partial(jax.jit, static_argnums=(1, 2), donate_argnums=(0,))
def run3d(vol: jax.Array, steps: int, rule: Rule3D = BAYS_4555) -> jax.Array:
    """Evolve a 3-torus volume ``steps`` generations in one compiled program."""
    return lax.fori_loop(0, steps, lambda _, v: step3d(v, rule), vol)
