"""Dense torus Game-of-Life stencil, single device.

TPU-native replacement for the CUDA kernel path of the reference:

- ``gol_kernel`` (gol-with-cuda.cu:189-262) — a grid-stride SIMT loop doing a
  per-cell 8-neighbor sum with mod-width column wrap (:210-211), ghost-row
  substitution on the first/last local rows (:224-231), and the B3/S23 rule as
  an if/else chain (:239-257) — becomes a vectorized separable roll-sum plus a
  branchless rule, fused by XLA onto the VPU.
- ``gol_kernelLaunch`` (gol-with-cuda.cu:264-284) — per-step launch +
  ``cudaDeviceSynchronize`` + pointer swap — becomes a single jitted program:
  the multi-generation loop is a ``lax.fori_loop`` *inside* the compiled fn
  (no per-step host sync), and the double buffer is XLA buffer donation.

The neighbor sum is separable: one vertical 3-row sum then one horizontal
3-column sum (4 rolls + 4 adds instead of 8 rolls + 7 adds), then subtract
the center.  Counts fit in uint8 (max 9), so everything stays 1 byte/cell in
HBM.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from gol_tpu.models.state import CELL_DTYPE


def life_rule(board: jax.Array, neighbors: jax.Array) -> jax.Array:
    """Branchless B3/S23: born on 3, survive on 2 or 3.

    Equivalent to the reference's if/else chain (gol-with-cuda.cu:239-257),
    which is only defined for 0/1 cells; we require uint8 0/1 boards.
    """
    alive = board == 1
    nxt = (neighbors == 3) | (alive & (neighbors == 2))
    return nxt.astype(CELL_DTYPE)


def neighbor_count_torus(board: jax.Array) -> jax.Array:
    """8-neighbor count on a fully periodic board via separable roll-sums.

    Columns wrap mod W and rows wrap mod H — the reference's global topology
    (x wrap at gol-with-cuda.cu:210-211; row wrap via the mod-ring rank ids,
    gol-main.c:86-87).
    """
    rows3 = board + jnp.roll(board, 1, axis=-2) + jnp.roll(board, -1, axis=-2)
    total = rows3 + jnp.roll(rows3, 1, axis=-1) + jnp.roll(rows3, -1, axis=-1)
    return total - board


def step(board: jax.Array) -> jax.Array:
    """One generation on a fully periodic (torus) board."""
    return life_rule(board, neighbor_count_torus(board))


def step_reduce_window(board: jax.Array) -> jax.Array:
    """Same semantics via wrap-pad + ``lax.reduce_window`` 3×3 add.

    Kept as an alternative lowering of the stencil (the SURVEY §7 step-1
    candidate); benchmarking picks the default — the roll-sum variant wins on
    TPU because XLA fuses the separable adds into one VPU pass.
    """
    padded = jnp.pad(board, 1, mode="wrap").astype(jnp.int32)
    total = lax.reduce_window(padded, 0, lax.add, (3, 3), (1, 1), "valid")
    return life_rule(board, (total - board).astype(CELL_DTYPE))


def step_halo_rows(block: jax.Array, top: jax.Array, bottom: jax.Array) -> jax.Array:
    """One generation of a row-sharded local block with explicit row halos.

    ``top`` is the previous rank's last row (the reference's
    ``previous_last_row``), ``bottom`` the next rank's first row
    (``next_first_row``) — the ghost rows of gol-main.c:11 /
    gol-with-cuda.cu:26-30.  Columns wrap locally mod W because the width axis
    is not sharded (gol-with-cuda.cu:210-211).
    """
    ext = jnp.concatenate([top[None, :], block, bottom[None, :]], axis=0)
    rows3 = ext[:-2] + ext[1:-1] + ext[2:]
    total = rows3 + jnp.roll(rows3, 1, axis=-1) + jnp.roll(rows3, -1, axis=-1)
    return life_rule(block, total - block)


def _row_strip(center: jax.Array, above: jax.Array, below: jax.Array):
    """Next state of one row given its vertical neighbors; columns wrap."""
    rows3 = above + center + below
    total = rows3 + jnp.roll(rows3, 1, axis=-1) + jnp.roll(rows3, -1, axis=-1)
    return life_rule(center, total - center)


def step_halo_rows_overlap(
    block: jax.Array, top: jax.Array, bottom: jax.Array
) -> jax.Array:
    """Same semantics as :func:`step_halo_rows`, structured for comm overlap.

    The interior rows (1..h-2) are computed from the local block alone — no
    data dependency on ``top``/``bottom`` — so XLA's latency-hiding
    scheduler can run the halo ppermutes concurrently with the interior
    stencil.  Only the two boundary rows wait on the exchange.  This is the
    interior-first overlap the reference *attempted* but never achieved: its
    nonblocking ``MPI_Irecv``/``Isend`` (gol-main.c:97-107) are followed by
    ``MPI_Wait`` *before* the kernel launch (gol-main.c:110-114), so
    compute never overlapped communication.
    """
    h = block.shape[0]
    if h < 3:
        # Every row is a boundary row; nothing to overlap.
        return step_halo_rows(block, top, bottom)
    rows3 = block[:-2] + block[1:-1] + block[2:]  # interior vertical sums
    total = rows3 + jnp.roll(rows3, 1, axis=-1) + jnp.roll(rows3, -1, axis=-1)
    interior = life_rule(block[1:-1], total - block[1:-1])
    row0 = _row_strip(block[0], top, block[1])
    rown = _row_strip(block[-1], block[-2], bottom)
    return jnp.concatenate([row0[None], interior, rown[None]], axis=0)


def step_halo_full(ext: jax.Array) -> jax.Array:
    """One generation given a fully halo-extended block ``ext[h+2, w+2]``.

    Used by the 2-D block decomposition (edge + corner halos already in
    place); no wrap is applied — the halo ring carries all periodicity.
    Returns the updated interior ``[h, w]``.
    """
    rows3 = ext[:-2] + ext[1:-1] + ext[2:]  # [h, w+2]
    total = rows3[:, :-2] + rows3[:, 1:-1] + rows3[:, 2:]  # [h, w]
    center = ext[1:-1, 1:-1]
    return life_rule(center, total - center)


def step_halo_full_overlap(block: jax.Array, ext: jax.Array) -> jax.Array:
    """2-D-decomposition step structured for comm/compute overlap.

    ``block`` is the shard pre-exchange, ``ext`` its halo-extended form.
    The interior cells (1..h-2, 1..w-2) — the bulk of the work — are
    computed from ``block`` alone, with no data dependency on the ppermutes
    that built ``ext``, so XLA can overlap the exchange with the interior
    stencil; only the one-cell boundary ring waits on ``ext``.
    """
    h, w = block.shape
    if h < 3 or w < 3:
        return step_halo_full(ext)  # all cells are boundary cells

    rows3 = block[:-2] + block[1:-1] + block[2:]
    total = rows3[:, :-2] + rows3[:, 1:-1] + rows3[:, 2:]
    center = block[1:-1, 1:-1]
    interior = life_rule(center, total - center)

    def edge_row(three_rows: jax.Array, center_row: jax.Array) -> jax.Array:
        r3 = three_rows[0] + three_rows[1] + three_rows[2]  # [w+2]
        tot = r3[:-2] + r3[1:-1] + r3[2:]
        return life_rule(center_row, tot - center_row)

    def edge_col(three_cols: jax.Array, center_col: jax.Array) -> jax.Array:
        c3 = three_cols[:, 0] + three_cols[:, 1] + three_cols[:, 2]  # [h+2]
        tot = c3[:-2] + c3[1:-1] + c3[2:]
        return life_rule(center_col, tot - center_col)

    row0 = edge_row(ext[0:3], block[0])
    rown = edge_row(ext[-3:], block[-1])
    left = edge_col(ext[:, 0:3], block[:, 0])[1:-1]
    right = edge_col(ext[:, -3:], block[:, -1])[1:-1]
    mid = jnp.concatenate([left[:, None], interior, right[:, None]], axis=1)
    return jnp.concatenate([row0[None], mid, rown[None]], axis=0)


@functools.partial(jax.jit, static_argnums=(1,), donate_argnums=(0,))
def run(board: jax.Array, steps: int) -> jax.Array:
    """Evolve a torus board ``steps`` generations in one compiled program.

    The host loop of gol-main.c:94-116 collapses into ``lax.fori_loop``; the
    donated argument gives the double buffer for free (no ``gol_swap``).
    """
    return lax.fori_loop(0, steps, lambda _, b: step(b), board)


@functools.partial(jax.jit, static_argnums=(1,), donate_argnums=(0,))
def run_reference_semantics(board: jax.Array, steps: int) -> jax.Array:
    """Evolve with the reference's *as-implemented* (buggy) semantics.

    Bug B1: the reference fills its halo send buffers once at t=0
    (gol-with-cuda.cu:40-47) and never refreshes them, so every step's
    exchanged ghost rows are the t=0 boundary rows.  With one rank,
    prev == next == self, so the vertical wrap neighbors are frozen at t=0.
    This single-rank compat path pins ``top``/``bottom`` to the initial last/
    first rows; the multi-rank compat engine lives in
    :mod:`gol_tpu.parallel.engine`.
    """
    top0 = board[-1]  # my_last_row at t=0 → received as previous_last_row
    bottom0 = board[0]  # my_first_row at t=0 → received as next_first_row
    return lax.fori_loop(
        0, steps, lambda _, b: step_halo_rows(b, top0, bottom0), board
    )
