"""Generalized 2-D totalistic rules: B/S rulestrings beyond B3/S23.

A capability addition over the reference, whose kernel hard-wires Conway's
rule as an if/else chain (gol-with-cuda.cu:239-257).  Here a rule is data —
a pair of neighbor-count sets parsed from the standard ``B<digits>/S<digits>``
notation — and both engines evaluate it branchlessly:

- the dense path masks the separable 8-neighbor count
  (:func:`gol_tpu.ops.stencil.neighbor_count_torus`) against the sets;
- the bit-packed path builds the 4-plane count-of-9 with the same adder
  tree as Conway's rule (:func:`gol_tpu.ops.bitlife._sum3_2bit`), borrow-
  subtracts the center bit for the count of 8 neighbors, and applies the
  plane matcher (:func:`gol_tpu.ops.bitlife._match_counts`) — any rule
  still runs at 32 cells per VPU op.

Named rules cover the classic families (HighLife's replicators, Seeds'
explosive growth, Day & Night's symmetry); ``B3/S23`` round-trips to the
exact Conway engines, pinned by tests.
"""

from __future__ import annotations

import functools
import re
from typing import FrozenSet, NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from gol_tpu.ops import bitlife, stencil


class Rule2D(NamedTuple):
    """Totalistic 2-D rule: counts (of the 8 neighbors) that birth/survive."""

    birth: FrozenSet[int]
    survive: FrozenSet[int]

    def rulestring(self) -> str:
        return "B{}/S{}".format(
            "".join(map(str, sorted(self.birth))),
            "".join(map(str, sorted(self.survive))),
        )


_RULESTRING_RE = re.compile(r"^B(\d*)/S(\d*)$", re.IGNORECASE)


def parse_rulestring(text: str) -> Rule2D:
    """``"B3/S23"`` -> Rule2D; digits 0-8, either set may be empty."""
    m = _RULESTRING_RE.match(text.strip())
    if not m:
        raise ValueError(
            f"malformed rulestring {text!r}; expected B<digits>/S<digits>"
        )
    birth = frozenset(int(d) for d in m.group(1))
    survive = frozenset(int(d) for d in m.group(2))
    if any(c > 8 for c in birth | survive):
        raise ValueError(f"rulestring {text!r} has counts > 8")
    return Rule2D(birth=birth, survive=survive)


CONWAY = Rule2D(birth=frozenset({3}), survive=frozenset({2, 3}))
HIGHLIFE = parse_rulestring("B36/S23")
SEEDS = parse_rulestring("B2/S")
DAY_AND_NIGHT = parse_rulestring("B3678/S34678")
NAMED_RULES = {
    "conway": CONWAY,
    "highlife": HIGHLIFE,
    "seeds": SEEDS,
    "day_and_night": DAY_AND_NIGHT,
}


def step_rule(board: jax.Array, rule: Rule2D) -> jax.Array:
    """One generation of ``rule`` on a fully periodic dense board.

    The branchless set-membership update is the dimension-agnostic
    :func:`gol_tpu.ops.life3d.rule3d` (counts are counts, 2-D or 3-D).
    """
    from gol_tpu.ops.life3d import rule3d

    return rule3d(board, stencil.neighbor_count_torus(board), rule)


def step_rule_packed(packed: jax.Array, rule: Rule2D) -> jax.Array:
    """One generation of ``rule`` on a packed torus board uint32[H, W//32].

    Same data flow as :func:`gol_tpu.ops.bitlife.step_packed` up to the
    4-plane count-of-9; the Conway-specific eq3/eq4 tail is replaced by the
    generic plane matcher.  The center subtraction is free via the same
    identity the hard-wired kernel uses (``t==3 | alive & t==4``): for dead
    cells count-of-9 == count-of-8, for alive cells it is count-of-8 + 1,
    so birth matches against B and survival against {s+1 for s in S}
    (still <= 9, fits the 4 planes) — no borrow ripple in the hot loop.
    """
    s = bitlife._row_hsum(packed)
    count9 = bitlife._sum3_2bit(
        tuple(jnp.roll(p, 1, axis=-2) for p in s),
        s,
        tuple(jnp.roll(p, -1, axis=-2) for p in s),
    )
    return _rule_from_count9(packed, count9, rule)


def step_rule_halo_rows(ext: jax.Array, rule: Rule2D) -> jax.Array:
    """One ``rule`` generation of a row-halo-extended block ``ext[h+2, w]``.

    Ghost rows carry the vertical periodicity; columns wrap locally (width
    axis unsharded) — the generic-rule analog of
    :func:`gol_tpu.ops.stencil.step_halo_rows`.  Shrinks by one row layer,
    so it composes with depth-k halos for temporal blocking.
    """
    from gol_tpu.ops.life3d import rule3d

    v = ext[:-2] + ext[1:-1] + ext[2:]
    h3 = v + jnp.roll(v, 1, axis=1) + jnp.roll(v, -1, axis=1)
    center = ext[1:-1]
    return rule3d(center, h3 - center, rule)


def step_rule_halo_full(ext: jax.Array, rule: Rule2D) -> jax.Array:
    """One ``rule`` generation of a fully halo-extended block ``ext[h+2, w+2]``.

    No wrap is applied — the halo ring (corners included) carries all
    periodicity; the generic-rule analog of
    :func:`gol_tpu.ops.stencil.step_halo_full`.  Shrinks by one layer on
    both axes.
    """
    from gol_tpu.ops.life3d import rule3d

    v = ext[:-2] + ext[1:-1] + ext[2:]
    h3 = v[:, :-2] + v[:, 1:-1] + v[:, 2:]
    center = ext[1:-1, 1:-1]
    return rule3d(center, h3 - center, rule)


def _rule_from_count9(packed: jax.Array, count9, rule: Rule2D) -> jax.Array:
    """Generic rule on packed words from the 4-plane count-of-9.

    Uses the +1 identity (see :func:`step_rule_packed`) so no borrow
    ripple is needed.
    """
    born = bitlife._match_counts(count9, rule.birth)
    keep = bitlife._match_counts(count9, {c + 1 for c in rule.survive})
    return (~packed & born) | (packed & keep)


def step_rule_packed_vext(ext: jax.Array, rule: Rule2D) -> jax.Array:
    """Generic-rule packed step of a row-halo-extended block ``ext[h+2, nw]``."""
    s0, s1 = bitlife._row_hsum(ext)
    count9 = bitlife._sum3_2bit(
        (s0[:-2], s1[:-2]), (s0[1:-1], s1[1:-1]), (s0[2:], s1[2:])
    )
    return _rule_from_count9(ext[1:-1], count9, rule)


def step_rule_packed_vext_nowrap(ext: jax.Array, rule: Rule2D) -> jax.Array:
    """Generic-rule packed step of a no-wrap window (width-preserving).

    The rule-generic twin of
    :func:`gol_tpu.ops.bitlife.step_packed_vext_nowrap`: shrinks one row
    layer per side, horizontal exactness shrinks one bit per side per call.
    """
    s0, s1 = bitlife._row_hsum_nowrap(ext)
    count9 = bitlife._sum3_2bit(
        (s0[:-2], s1[:-2]), (s0[1:-1], s1[1:-1]), (s0[2:], s1[2:])
    )
    return _rule_from_count9(ext[1:-1], count9, rule)


def step_rule_packed_vext_nowrap_t(ext_t: jax.Array, rule: Rule2D) -> jax.Array:
    """Transposed generic-rule no-wrap packed step (words on axis -2)."""
    s0, s1 = bitlife._row_hsum_nowrap_t(ext_t)
    count9 = bitlife._sum3_2bit(
        (s0[..., :-2], s1[..., :-2]),
        (s0[..., 1:-1], s1[..., 1:-1]),
        (s0[..., 2:], s1[..., 2:]),
    )
    return _rule_from_count9(ext_t[..., 1:-1], count9, rule)


def step_rule_packed_halo_full(ext: jax.Array, rule: Rule2D) -> jax.Array:
    """Generic-rule packed step with ghost word columns ``ext[h+2, nw+2]``."""
    s0, s1 = bitlife._row_hsum_ext(ext)
    count9 = bitlife._sum3_2bit(
        (s0[:-2], s1[:-2]), (s0[1:-1], s1[1:-1]), (s0[2:], s1[2:])
    )
    return _rule_from_count9(ext[1:-1, 1:-1], count9, rule)


@functools.partial(jax.jit, static_argnums=(1, 2), donate_argnums=(0,))
def run_rule(board: jax.Array, steps: int, rule: Rule2D) -> jax.Array:
    """Dense evolve of any rule, whole loop in one compiled program."""
    return lax.fori_loop(0, steps, lambda _, b: step_rule(b, rule), board)


@functools.partial(jax.jit, static_argnums=(1, 2), donate_argnums=(0,))
def evolve_rule_dense_io(
    board: jax.Array, steps: int, rule: Rule2D
) -> jax.Array:
    """Bit-packed evolve of any rule: pack, run packed, unpack."""
    packed = bitlife.pack(board)
    packed = lax.fori_loop(
        0, steps, lambda _, p: step_rule_packed(p, rule), packed
    )
    return bitlife.unpack(packed)
