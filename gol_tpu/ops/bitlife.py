"""Bit-packed torus Game-of-Life: 32 cells per uint32 lane word.

The performance tier of SURVEY §7 step 7.  The reference spends one CUDA
thread per cell reading 9 bytes of neighborhood per update
(gol_kernel, gol-with-cuda.cu:189-262).  Life cells are 1 bit of state, so
the dense uint8 layout wastes 8× HBM bandwidth — and on TPU the stencil is
bandwidth-bound.  Here the board is packed 32 cells per ``uint32`` along
the width axis and one generation is computed with bit-sliced carry-save
adders: every bitwise VPU op advances 32 cells, and HBM traffic drops 8×.

Counting scheme (classic bit-parallel Life):

- For each of the three stencil rows, the 3-cell horizontal sum per lane is
  a 2-bit number built with one full adder over (west, center, east)
  bitboards.  West/east bitboards are lane shifts with the carry bit taken
  from the ring-adjacent word, so the column torus wrap
  (gol-with-cuda.cu:210-211) falls out of a ``jnp.roll`` along the packed
  axis.
- The three 2-bit row sums are added into a 4-bit count-of-9 (self
  included) with two more adder layers.
- B3/S23 over count-of-9 ``t``: next = (t == 3) | (alive & t == 4) — the
  branchless form of the if/else chain at gol-with-cuda.cu:239-257.

Total: ~22 bitwise ops per word = ~0.7 ops/cell, vs ~10 byte-wide ops/cell
for the dense engine, at 1/8th the memory traffic.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from gol_tpu.models.state import CELL_DTYPE

WORD = jnp.uint32
BITS = 32
# pack/unpack build their weight planes from numpy (not jnp) scalars:
# creating a device array at import time would initialize the XLA
# backend, which must not happen before a possible
# jax.distributed.initialize (multi-host CLI path).


def packed_width(width: int) -> int:
    """Number of uint32 words per row; width must pack evenly."""
    if width % BITS != 0:
        raise ValueError(
            f"bit-packed engine needs width divisible by {BITS}, got {width}"
        )
    return width // BITS


def pack(board: jax.Array) -> jax.Array:
    """uint8[H, W] 0/1 board -> uint32[H, W//32]; bit j of word k = col 32k+j.

    Staged through uint8 bytes: the obvious one-step form (widen every
    cell to uint32, weight, reduce) materializes a 4×-board uint32
    intermediate — 17 GB at 65536², an HBM OOM on a 16 GB chip.  Packing
    8 cells per *byte* first keeps the big temporaries at board width in
    uint8; only the 4-bytes-per-word combine widens, at 1/8th the cells.
    """
    h, w = board.shape
    nw = packed_width(w)
    bits = board.reshape(h, nw, 4, 8)
    w8 = (np.uint8(1) << np.arange(8, dtype=np.uint8)).reshape(1, 1, 1, 8)
    by = jnp.sum(bits * w8, axis=-1, dtype=jnp.uint8)  # [h, nw, 4]
    shifts = (np.arange(4, dtype=np.uint32) * np.uint32(8)).reshape(1, 1, 4)
    return jnp.sum(by.astype(WORD) << shifts, axis=-1, dtype=WORD)


def unpack(packed: jax.Array) -> jax.Array:
    """Inverse of :func:`pack` (byte-staged for the same HBM reason)."""
    h, nw = packed.shape
    shifts = (np.arange(4, dtype=np.uint32) * np.uint32(8)).reshape(1, 1, 4)
    by = ((packed[:, :, None] >> shifts) & np.uint32(0xFF)).astype(jnp.uint8)
    bit_shifts = np.arange(8, dtype=np.uint8).reshape(1, 1, 1, 8)
    bits = (by[..., None] >> bit_shifts) & np.uint8(1)
    return bits.astype(CELL_DTYPE).reshape(h, nw * BITS)


def _west_east(row: jax.Array):
    """Bitboards of each cell's west / east neighbor within a packed row.

    Bit j of a word is column 32k+j, so the west neighbor (col-1) of bit j
    is bit j-1 — a left lane-shift — with bit 0 filled from the top bit of
    the ring-previous word (the torus column wrap).
    """
    prev_word = jnp.roll(row, 1, axis=-1)
    next_word = jnp.roll(row, -1, axis=-1)
    west = (row << 1) | (prev_word >> (BITS - 1))
    east = (row >> 1) | (next_word << (BITS - 1))
    return west, east


def _full_add(a: jax.Array, b: jax.Array, c: jax.Array):
    """Bitwise full adder: (sum_bit, carry_bit) of three 1-bit planes."""
    axb = a ^ b
    return axb ^ c, (a & b) | (c & axb)


def _row_hsum(row: jax.Array):
    """Per-lane 3-cell horizontal sum (west+center+east) as 2 bit-planes."""
    west, east = _west_east(row)
    return _full_add(west, row, east)


def _sub_bit(planes, bit: jax.Array):
    """Bit-plane subtraction of a 1-bit number (borrow ripple).

    Shared by the generalized-rule 2-D engine (count-of-8 from count-of-9)
    and the 3-D engine (count-of-26 from count-of-27).
    """
    out = []
    borrow = bit
    for p in planes:
        out.append(p ^ borrow)
        borrow = ~p & borrow
    return tuple(out)


def _match_counts(planes, counts) -> jax.Array:
    """Word mask of cells whose plane-encoded count is in ``counts``.

    The branchless rule evaluator for arbitrary totalistic count sets: one
    AND-chain of planes/complements per count, OR'd together — every op
    still advances 32 cells.
    """
    zero = jnp.zeros_like(planes[0])
    out = zero
    for c in sorted(counts):
        if c >= 1 << len(planes):
            raise ValueError(f"count {c} exceeds {len(planes)} planes")
        m = ~zero
        for i, p in enumerate(planes):
            m = m & (p if (c >> i) & 1 else ~p)
        out = out | m
    return out


def _sum3_2bit(sa, sc, sb):
    """Bit-plane sum of three 2-bit numbers -> 4 planes (count 0-9).

    Each argument is a (ones_plane, twos_plane) pair; the result is the
    little-endian bit-plane tuple of their sum.  Shared by the 2-D rule
    (count-of-9 from three row sums) and the 3-D engine's column stage.
    """
    (s0a, s1a), (s0c, s1c), (s0b, s1b) = sa, sc, sb
    l0, c_low = _full_add(s0a, s0c, s0b)  # ones plane + carry into twos
    u, v = _full_add(s1a, s1c, s1b)  # twos-plane sum: u ones, v twos
    t0 = u ^ c_low
    carry2 = u & c_low
    t1 = v ^ carry2
    t2 = v & carry2
    return (l0, t0, t1, t2)


def _rule_from_row_sums(center, sa, sc, sb):
    """B3/S23 from the three per-row 2-bit horizontal sums.

    ``sa``/``sc``/``sb`` are (ones_plane, twos_plane) pairs for the above /
    center / below stencil rows; builds the 4-bit count-of-9 and applies the
    branchless rule (the if/else chain of gol-with-cuda.cu:239-257).
    """
    l0, t0, t1, t2 = _sum3_2bit(sa, sc, sb)
    # t = l0 + 2*t0 + 4*t1 + 8*t2;  alive-next = (t==3) | (alive & t==4)
    eq3 = l0 & t0 & ~(t1 | t2)
    eq4 = ~l0 & ~t0 & t1 & ~t2
    return eq3 | (center & eq4)


def step_packed_rows(center: jax.Array, above: jax.Array, below: jax.Array):
    """Next generation of packed rows given packed neighbor rows.

    ``above``/``below`` are the packed analogs of the reference's
    ``previous_last_row``/``next_first_row`` ghost rows (gol-main.c:11).
    Columns wrap mod the packed width (torus, gol-with-cuda.cu:210-211).
    Each row's horizontal sum is computed afresh; callers stepping a whole
    board should prefer :func:`step_packed` / :func:`step_packed_vext`,
    which compute every row's sum exactly once.
    """
    return _rule_from_row_sums(
        center, _row_hsum(above), _row_hsum(center), _row_hsum(below)
    )


def step_packed_vext(ext: jax.Array) -> jax.Array:
    """One packed generation of a row-halo-extended block ``ext[h+2, nw]``.

    Ghost *rows* above/below carry the vertical periodicity; columns wrap
    locally (width axis unsharded) — the bit-packed analog of
    :func:`gol_tpu.ops.stencil.step_halo_rows` for the 1-D row
    decomposition.  The horizontal sum is computed once per extended row and
    its bit-planes re-sliced for the above/center/below stencil rows.
    Returns the updated interior ``[h, nw]``.
    """
    s0, s1 = _row_hsum(ext)
    return _rule_from_row_sums(
        ext[1:-1],
        (s0[:-2], s1[:-2]),
        (s0[1:-1], s1[1:-1]),
        (s0[2:], s1[2:]),
    )


def _row_hsum_ext(rows: jax.Array):
    """Per-lane 3-cell horizontal sum on word-halo-extended rows.

    ``rows[..., nw+2]`` carries one ghost *word* per side, so the west/east
    carry bits come from adjacent array words — no wrap.  Returns 2
    bit-planes of shape ``[..., nw]``.
    """
    cur = rows[..., 1:-1]
    west = (cur << 1) | (rows[..., :-2] >> (BITS - 1))
    east = (cur >> 1) | (rows[..., 2:] << (BITS - 1))
    return _full_add(west, cur, east)


def _row_hsum_nowrap(rows: jax.Array):
    """Per-lane 3-cell horizontal sums with zero edge carries (no wrap).

    The no-torus variant of :func:`_row_hsum` for windows that do *not* own
    the full board width: west/east carry bits cross adjacent array words,
    and the window's outermost bit per side reads a 0 instead of wrapping.
    Callers tolerate garbage in an edge band — each generation grows the
    band by one *bit* per side (the stencil light cone), so a window with
    ``g`` ghost bits per side keeps an exact interior for ``g`` generations.
    Width is preserved (unlike :func:`_row_hsum_ext`, which consumes a whole
    ghost word per side per call).
    """
    zero = jnp.zeros_like(rows[..., :1])
    prev_word = jnp.concatenate([zero, rows[..., :-1]], axis=-1)
    next_word = jnp.concatenate([rows[..., 1:], zero], axis=-1)
    west = (rows << 1) | (prev_word >> (BITS - 1))
    east = (rows >> 1) | (next_word << (BITS - 1))
    return _full_add(west, rows, east)


def step_packed_vext_nowrap(ext: jax.Array) -> jax.Array:
    """Packed step of a no-wrap window ``ext[r+2, nww]``: shrinks one row
    layer per side; width is preserved with horizontal exactness shrinking
    one *bit* per side per call (see :func:`_row_hsum_nowrap`).

    The building block of the 2-D-mesh sharded Pallas engine
    (:func:`gol_tpu.parallel.packed.compiled_evolve_packed_pallas`): both
    its edge-word repair strips and its remainder steps are windows onto a
    column-sharded board, where neither wrap nor whole-word halo
    consumption is wanted.
    """
    s0, s1 = _row_hsum_nowrap(ext)
    return _rule_from_row_sums(
        ext[1:-1],
        (s0[:-2], s1[:-2]),
        (s0[1:-1], s1[1:-1]),
        (s0[2:], s1[2:]),
    )


def _row_hsum_nowrap_t(cols: jax.Array):
    """Transposed twin of :func:`_row_hsum_nowrap`: packed words on axis -2,
    board rows on axis -1.

    Built for narrow strips (a few words wide, many rows tall): in the
    natural ``[rows, words]`` layout a 3-word strip wastes ~98% of each
    128-wide TPU lane tile, while transposed the long row axis fills the
    lanes.  Leading batch axes broadcast (stacked independent strips) —
    word adjacency never crosses a batch boundary because the shift is a
    zero-filled concat along axis -2 only.
    """
    zero = jnp.zeros_like(cols[..., :1, :])
    prev_word = jnp.concatenate([zero, cols[..., :-1, :]], axis=-2)
    next_word = jnp.concatenate([cols[..., 1:, :], zero], axis=-2)
    west = (cols << 1) | (prev_word >> (BITS - 1))
    east = (cols >> 1) | (next_word << (BITS - 1))
    return _full_add(west, cols, east)


def step_packed_vext_nowrap_t(ext_t: jax.Array) -> jax.Array:
    """Transposed no-wrap packed step: ``ext_t[..., nww, r+2] -> [..., nww, r]``.

    Same semantics as :func:`step_packed_vext_nowrap` with the word and row
    axes swapped (see :func:`_row_hsum_nowrap_t`).
    """
    s0, s1 = _row_hsum_nowrap_t(ext_t)
    return _rule_from_row_sums(
        ext_t[..., 1:-1],
        (s0[..., :-2], s1[..., :-2]),
        (s0[..., 1:-1], s1[..., 1:-1]),
        (s0[..., 2:], s1[..., 2:]),
    )


def step_packed_overlap_rows(
    block: jax.Array, top: jax.Array, bottom: jax.Array
) -> jax.Array:
    """Packed row-sharded step structured for comm/compute overlap.

    The packed analog of :func:`gol_tpu.ops.stencil.step_halo_rows_overlap`:
    interior rows (1..h-2) are computed from the local block alone — their
    horizontal bit-plane sums have no data dependency on the exchange that
    delivered ``top``/``bottom`` — so XLA's latency-hiding scheduler can run
    the ring ppermutes concurrently with the bulk of the adder tree; only
    the two boundary rows wait.  Local horizontal sums are computed once
    and reused by both interior and boundary rows.
    """
    h = block.shape[0]
    if h < 3:
        # Every row is a boundary row; nothing to overlap.
        ext = jnp.concatenate([top[None], block, bottom[None]], axis=0)
        return step_packed_vext(ext)
    s0, s1 = _row_hsum(block)
    t = _row_hsum(top)  # depends on the exchange
    b = _row_hsum(bottom)
    interior = _rule_from_row_sums(
        block[1:-1],
        (s0[:-2], s1[:-2]),
        (s0[1:-1], s1[1:-1]),
        (s0[2:], s1[2:]),
    )
    row0 = _rule_from_row_sums(block[0], t, (s0[0], s1[0]), (s0[1], s1[1]))
    rown = _rule_from_row_sums(
        block[-1], (s0[-2], s1[-2]), (s0[-1], s1[-1]), b
    )
    return jnp.concatenate([row0[None], interior, rown[None]], axis=0)


def step_packed_halo_full(ext: jax.Array) -> jax.Array:
    """One packed generation given a fully halo-extended block.

    ``ext[h+2, nw+2]`` has one ghost row of packed words above/below and one
    ghost *word* column left/right (corner words included) — the bit-packed
    analog of :func:`gol_tpu.ops.stencil.step_halo_full` for the 2-D block
    decomposition.  No wrap is applied; the halo ring carries all
    periodicity.  The horizontal sum is computed once per extended row and
    its bit-planes re-sliced.  Returns the updated interior ``[h, nw]``.
    """
    s0, s1 = _row_hsum_ext(ext)
    return _rule_from_row_sums(
        ext[1:-1, 1:-1],
        (s0[:-2], s1[:-2]),
        (s0[1:-1], s1[1:-1]),
        (s0[2:], s1[2:]),
    )


def step_packed(packed: jax.Array) -> jax.Array:
    """One generation on a fully periodic packed board uint32[H, W//32].

    The horizontal sum is computed once per row; the above/below stencil
    rows reuse its bit-planes via torus rolls (2 rolls per plane instead of
    re-running the ~7-op shift/adder sum on rolled boards).
    """
    s0, s1 = _row_hsum(packed)
    sa = (jnp.roll(s0, 1, axis=-2), jnp.roll(s1, 1, axis=-2))
    sb = (jnp.roll(s0, -1, axis=-2), jnp.roll(s1, -1, axis=-2))
    return _rule_from_row_sums(packed, sa, (s0, s1), sb)


@functools.partial(jax.jit, static_argnums=(1,), donate_argnums=(0,))
def run_packed(packed: jax.Array, steps: int) -> jax.Array:
    """Evolve a packed board ``steps`` generations in one compiled program."""
    return lax.fori_loop(0, steps, lambda _, b: step_packed(b), packed)


@functools.partial(jax.jit, static_argnums=(1,), donate_argnums=(0,))
def evolve_dense_io(board: jax.Array, steps: int) -> jax.Array:
    """Dense-in / dense-out evolve: pack, run packed, unpack.

    The engine entry point used by the runtime and bench: pack/unpack cost
    is paid once and amortized over the whole fori_loop, all inside a
    single compiled program (the donated input is the double buffer).
    """
    packed = pack(board)
    packed = lax.fori_loop(0, steps, lambda _, b: step_packed(b), packed)
    return unpack(packed)
