"""Process-level resilience: preemption, auto-resume, supervision, GC.

The fault-tolerance story now has two tiers (docs/RESILIENCE.md):

- the **in-run tier** (``utils/guard.py``): detects silent data
  corruption while the process lives, rolls back on device, and writes
  fingerprint-stamped checkpoints;
- the **process tier** (this package): survives the process *dying* —
  SIGTERM/SIGINT become a clean chunk-boundary checkpoint + exit 75
  (:mod:`~gol_tpu.resilience.preempt`), ``--auto-resume`` restarts from
  the newest snapshot that actually verifies, falling back past corrupt
  or torn candidates with multi-host min-generation agreement
  (:mod:`~gol_tpu.resilience.resume`), ``python -m gol_tpu.resilience
  supervise`` relaunches a crashed/preempted child under a bounded
  budget with exponential backoff + jitter
  (:mod:`~gol_tpu.resilience.supervisor`), and keep-last-K retention
  keeps week-long runs from exhausting disk
  (:mod:`~gol_tpu.resilience.retention`).

With none of it requested (no ``--auto-resume``, no supervisor, no
signal delivered) every piece is a strict no-op: the chunk programs'
jaxprs are byte-identical to the resilience-free build (pinned by the
trace-identity tests).

The fault-injection plane (:mod:`~gol_tpu.resilience.faults`) and its
containment policies (:mod:`~gol_tpu.resilience.degrade`) make every
claimed recovery path fireable from one declarative JSON plan
(``--fault-plan`` / ``GOL_FAULT_PLAN``); ``python -m gol_tpu.resilience
chaos`` executes scenario × tier × mesh grids from a plan file and
asserts detection + byte-identical recovery
(:mod:`~gol_tpu.resilience.chaos`).
"""

from gol_tpu.resilience.degrade import (  # noqa: F401
    RetryPolicy,
    write_with_retry,
)
from gol_tpu.resilience.faults import (  # noqa: F401
    FaultPlan,
    FaultPlanError,
    FaultSpec,
)
from gol_tpu.resilience.preempt import (  # noqa: F401
    EX_TEMPFAIL,
    Preempted,
    ReshardPoint,
    agreed_preempt_requested,
    clear_preemption,
    preempt_requested,
    preemption_guard,
    request_preemption,
)
from gol_tpu.resilience.reshard import (  # noqa: F401
    MeshLayout,
    ReshardError,
    ReshardPlanError,
    load_resharded,
    plan_reshard,
    topology_resume_hint,
    validate_plan,
)
from gol_tpu.resilience.resume import (  # noqa: F401
    corrupt_resume_hint,
    resolve_auto_resume,
)
from gol_tpu.resilience.retention import gc_snapshots  # noqa: F401
from gol_tpu.resilience.supervisor import supervise  # noqa: F401
