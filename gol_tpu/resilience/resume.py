"""Validated auto-resume: pick the newest snapshot that actually loads.

``--resume PATH`` trusts the caller; ``--auto-resume`` trusts nothing.
The walk (:func:`gol_tpu.utils.checkpoint.latest_valid`) goes
newest→oldest over the checkpoint directory, fully fingerprint-verifying
each candidate — single-file and sharded formats alike — and falls back
past corrupt or torn snapshots instead of dying on
``CorruptSnapshotError``: after a kill-9 mid-write or a flipped byte on
disk, the run restarts from the newest state that is *provably* intact.

Multi-host agreement: each rank validates its own view (for sharded
checkpoints, the pieces it wrote — a rank cannot vouch for bytes another
host owns), then all ranks take the **min** of their newest valid
generations.  No rank may resume ahead of another: a rank whose newest
snapshot failed validation drags the whole job back to the last
generation *every* rank can load, which is exactly the generation the
job can bit-exactly continue from.
"""

from __future__ import annotations

import os
from typing import Optional, Tuple

from gol_tpu.utils import checkpoint as ckpt_mod


def _allgather_min(value: int) -> int:
    """min over all processes of a host integer (identity single-process).

    Rides :func:`gol_tpu.parallel.multihost.allgather_host_ints` — the
    scalar replicates so every rank takes the identical resume decision
    with one collective.
    """
    from gol_tpu.parallel import multihost

    return min(multihost.allgather_host_ints(value))


def _snapshot_at(directory: str, kind: str, generation: int) -> Optional[str]:
    """The on-disk snapshot path holding ``generation``, either format."""
    if kind == "3d":
        candidates = (
            ckpt_mod.checkpoint3d_path(directory, generation),
            ckpt_mod.sharded_checkpoint3d_path(directory, generation),
        )
    elif kind == "batch":
        candidates = (ckpt_mod.batch_checkpoint_path(directory, generation),)
    else:
        candidates = (
            ckpt_mod.checkpoint_path(directory, generation),
            ckpt_mod.sharded_checkpoint_path(directory, generation),
        )
    for path in candidates:
        if os.path.exists(path):
            return path
    return None


def resolve_auto_resume(
    directory: str, kind: str = "2d"
) -> Tuple[Optional[str], dict]:
    """(resume path or None, info dict for logs + the ``resume`` event).

    ``info`` carries ``generation`` (-1 when starting fresh), ``path``,
    ``fallback`` (True when a newer candidate was skipped as invalid or
    another rank forced an earlier generation), and ``skipped`` (the
    rejected newer candidates' basenames).  Collective on multi-host
    jobs — every process must call it.
    """
    import jax

    multi = jax.process_count() > 1
    only = jax.process_index() if multi else None
    # expect_processes arms the topology check: a snapshot stamped by a
    # different job size (elastic shrink/grow) is verified in full by
    # every rank — the own-pieces shortcut would leave vanished ranks'
    # pieces vouched for by nobody (docs/RESILIENCE.md, elastic meshes).
    path, skipped = ckpt_mod.latest_valid(
        directory,
        kind,
        only_process=only,
        expect_processes=jax.process_count() if multi else None,
    )
    local_gen = -1
    if path is not None:
        gen = ckpt_mod.snapshot_generation(path)
        local_gen = -1 if gen is None else gen
    agreed = _allgather_min(local_gen) if multi else local_gen
    fallback = bool(skipped)
    if agreed != local_gen:
        # Another rank's newest valid snapshot is older (or absent):
        # fall back to the agreed generation — it verified on every rank.
        fallback = True
        path = (
            None if agreed < 0 else _snapshot_at(directory, kind, agreed)
        )
        local_gen = agreed if path is not None else -1
    if multi and agreed >= 0:
        # Everyone-or-no-one: if any rank failed to locate the agreed
        # snapshot (non-shared storage, a racing GC), all ranks start
        # fresh rather than resuming split-brained.
        if _allgather_min(0 if path is None else 1) == 0:
            path, fallback = None, True
    if path is None:
        local_gen = -1
    info = dict(
        generation=local_gen,
        path=None if path is None else os.path.abspath(path),
        fallback=fallback and path is not None,
        skipped=[os.path.basename(p) for p in skipped],
    )
    return path, info


def corrupt_resume_hint(resume_path: str, kind: str = "2d") -> Optional[str]:
    """For a failed plain ``--resume``: the newest *valid* sibling snapshot.

    Gives the error message a concrete way out ("an earlier valid
    snapshot exists at ...; or pass --auto-resume") instead of a dead
    end.  Returns None when the directory holds no valid alternative.
    """
    directory = os.path.dirname(os.path.abspath(resume_path)) or "."
    try:
        path, _ = ckpt_mod.latest_valid(directory, kind)
    except (OSError, ValueError):
        return None
    if path is None or os.path.abspath(path) == os.path.abspath(resume_path):
        return None
    return path
