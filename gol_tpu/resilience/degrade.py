"""Checkpoint-write containment: bounded retry, then shed — never die.

The policy the fault plane (:mod:`gol_tpu.resilience.faults`) exists to
exercise (docs/RESILIENCE.md "Retry and shed"):

- **Transient IO errors** (EIO, a torn write, an NFS blip) get a
  bounded retry with exponential backoff.  A snapshot that lands on
  attempt 2 is a non-event for the run; the retries are recorded and
  surface as a schema-v9 ``degraded`` telemetry event
  (``action: "retried"``).
- **Disk full** (ENOSPC) is not transient — retrying into a full disk
  burns the run's time for nothing.  The shed order is fixed:
  *telemetry before checkpoints* — the event stream is an observer, the
  snapshots are the recovery path, so the stream is sacrificed first
  (``EventLog.request_shed``) and the write retried once; if the disk
  is still full, checkpointing itself is shed (the caller disables
  further saves) and the run **continues to completion** — a computed
  result with no snapshots beats no result.
- Anything still failing after the retry budget re-raises, preserving
  the CLIs' clean-exit contract for genuinely broken storage (unwritable
  directory, permission errors).

Decisions are recorded in a thread-safe ledger (`drain_reports`) because
the write may run on the async snapshot writer's thread while telemetry
emission must stay on the main loop's.
"""

from __future__ import annotations

import dataclasses
import errno as errno_mod
import threading
import time
from typing import Callable, List, Optional

_lock = threading.Lock()
_reports: List[dict] = []


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Bounded retry + backoff for checkpoint writes."""

    retries: int = 3  # attempts AFTER the first try
    backoff_base: float = 0.05  # seconds; doubles per retry
    backoff_max: float = 2.0

    def delay(self, attempt: int) -> float:
        return min(self.backoff_base * (2 ** attempt), self.backoff_max)


DEFAULT_POLICY = RetryPolicy()


def _record(report: dict) -> None:
    with _lock:
        _reports.append(report)


def drain_reports() -> List[dict]:
    """Containment decisions since the last drain — the run loops turn
    them into schema-v9 ``degraded`` telemetry events."""
    global _reports
    with _lock:
        out, _reports = _reports, []
    return out


def write_with_retry(
    write: Callable[[], None],
    what: str = "checkpoint",
    generation: Optional[int] = None,
    policy: RetryPolicy = DEFAULT_POLICY,
    shed_telemetry: Optional[Callable[[str], None]] = None,
) -> bool:
    """Run one snapshot ``write`` under the retry/shed policy.

    Returns ``True`` when the write landed, ``False`` when it was shed
    (persistent ENOSPC — the caller must stop attempting checkpoints).
    Re-raises the last error when a non-ENOSPC failure survives the
    retry budget.  ``shed_telemetry(reason)`` is the disk-full
    first-sacrifice hook (``EventLog.request_shed`` bound by the run
    loop); called at most once.
    """
    shed_done = False
    enospc_seen = 0
    attempt = 0
    while True:
        try:
            write()
            return True
        except OSError as e:
            if e.errno == errno_mod.ENOSPC:
                enospc_seen += 1
                if enospc_seen == 1:
                    # A single ENOSPC may be transient (a neighbor's
                    # file just got GC'd): retry once before
                    # sacrificing anything.
                    _record(
                        dict(
                            resource=what,
                            action="retried",
                            generation=generation,
                            attempt=1,
                            detail=str(e),
                        )
                    )
                    continue
                if shed_telemetry is not None and not shed_done:
                    # Persistently full: telemetry before checkpoints —
                    # drop the observer stream to relieve the disk,
                    # then try the snapshot once more.
                    shed_done = True
                    shed_telemetry(f"disk full during {what} write: {e}")
                    _record(
                        dict(
                            resource="telemetry",
                            action="shed",
                            generation=generation,
                            detail=str(e),
                        )
                    )
                    continue
                # Still full: shed checkpointing, keep the run alive.
                _record(
                    dict(
                        resource="checkpoint",
                        action="shed",
                        generation=generation,
                        detail=str(e),
                    )
                )
                import sys

                print(
                    f"gol: {what} shed: disk full and telemetry already "
                    f"dropped ({e}); continuing WITHOUT further "
                    "checkpoints",
                    file=sys.stderr,
                )
                return False
            if attempt >= policy.retries:
                raise
            time.sleep(policy.delay(attempt))
            attempt += 1
            _record(
                dict(
                    resource=what,
                    action="retried",
                    generation=generation,
                    attempt=attempt,
                    detail=str(e),
                )
            )
