"""The chaos matrix: scenario × tier × mesh, detection + byte-identical
recovery, from one committed plan file.

``python -m gol_tpu.resilience chaos --plan FILE`` executes every cell
of the grid the plan describes (docs/RESILIENCE.md "The chaos matrix"):
for each *scenario* (a named list of fault-plan entries plus an
expectation class) crossed with each *tier* (dense / bitpack / pallas /
batch / activity / 3-D / serve) and *mesh* (none / 1d / 2d), the runner

1. computes the tier's **clean** final grid once (cached per cell),
2. re-runs with the scenario's faults armed through the real CLI/runtime
   surfaces (:mod:`gol_tpu.resilience.faults`),
3. asserts the scenario's **detection signal** fired — a guard failure,
   a resume-walk fallback, a v9 ``fault``/``degraded`` telemetry record —
   and that the recovered final grid is **byte-identical** to the clean
   run.

Illegal cells (an engine with no sharded path, a mesh the geometry
cannot tile, a Pallas kernel the backend lacks) are *visibly skipped*
with the refusing error as the reason — a skip is a recorded fact, not
a silent hole in the matrix.

Expectation classes (the ``kind`` field of a scenario):

- ``guard``      — guarded run; the audit must fail >= once and the
  rollback-replay must land the clean grid (``redundant: true`` arms the
  cross-engine audit — required for in-range flips).
- ``resume``     — checkpointed run whose newest snapshot the fault
  corrupts on disk; the validated resume walk must *fall back* past it
  and a resumed run must complete the clean grid.
- ``contain``    — checkpointed+telemetry run; the fault (transient IO
  error, torn tmp, rank stall) must be absorbed by retry/containment:
  the run completes, every surviving snapshot verifies, and the stream
  carries the v9 ``fault`` record.
- ``shed``       — persistent disk-full: the run must complete anyway,
  shedding telemetry before checkpoints (v9 ``degraded`` stamped).
- ``telemetry``  — failing rank-file write: the run completes, the
  stream degrades (warn once, drop, ``degraded`` stamp).
- ``elastic``    — serve-tier live elasticity (``mesh_devices`` armed):
  a ``device.loss`` / ``rank.slowdown`` fault must be absorbed
  **in-process** — no supervisor restart — with every request's board
  byte-identical to the clean run, and the stream must carry the v11
  ``health`` verdicts plus (for device loss) the live ``reshard``
  record (docs/RESILIENCE.md "Live elasticity").
- ``fleet``      — the replicated front tier (docs/SERVING.md "The
  fleet"): supervised replica subprocesses behind an in-process
  :class:`gol_tpu.serve.fleet.FleetFront`; a ``replica.kill`` /
  ``replica.stall`` fault (or, via the ``drill`` field, a front-tier
  crash+restart) must lose nothing — every request completes with
  exactly ONE journal ``complete`` across the whole fleet's folds and
  a board byte-identical to the single-replica oracle, with the
  handoff/fencing records proving how.  Restricted to the serve tier,
  mesh ``none`` (replicas are processes, not devices).

``crash.exit`` scenarios need a supervisor and real process death; they
live in the subprocess drills (tests/test_resilience_drill.py,
scripts/chaos_smoke.py) rather than this in-process matrix — a plan may
still restrict any scenario to a tier/mesh subset via per-scenario
``tiers``/``meshes`` keys, rendered as explicit skips elsewhere.
"""

from __future__ import annotations

import dataclasses
import json
import os
import tempfile
import time
from typing import List, Optional, Sequence

import numpy as np

TIERS = ("dense", "bitpack", "pallas", "batch", "activity", "3d", "serve")
MESHES = ("none", "1d", "2d")
KINDS = (
    "guard", "resume", "contain", "shed", "telemetry", "elastic",
    "fleet",
)

#: The committed grid (the acceptance surface of the chaos matrix).
DEFAULT_PLAN_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(__file__))),
    "tests", "data", "fault_plans", "chaos_matrix.json",
)


@dataclasses.dataclass(frozen=True)
class Scenario:
    name: str
    kind: str
    faults: tuple  # FaultSpec dicts, installed verbatim for the cell
    redundant: bool = False  # guard kind: arm the cross-engine audit
    tiers: Optional[tuple] = None  # per-scenario restriction (else grid)
    meshes: Optional[tuple] = None
    drill: str = ""  # fleet kind: "" (fault-driven) or "front_restart"

    def __post_init__(self) -> None:
        if self.kind not in KINDS:
            raise ValueError(
                f"scenario {self.name!r}: unknown kind {self.kind!r}; "
                f"expected one of {KINDS}"
            )


@dataclasses.dataclass(frozen=True)
class ChaosPlan:
    scenarios: tuple
    tiers: tuple = TIERS
    meshes: tuple = MESHES
    size: int = 128  # 2-D board edge (and batch world edge)
    size3d: int = 32  # 3-D cube edge
    iterations: int = 6
    guard_every: int = 2
    checkpoint_every: int = 2

    @classmethod
    def load(cls, path: str) -> "ChaosPlan":
        with open(path) as f:
            obj = json.load(f)
        scenarios = tuple(
            Scenario(
                name=s["name"],
                kind=s["kind"],
                faults=tuple(s["faults"]),
                redundant=bool(s.get("redundant", False)),
                tiers=tuple(s["tiers"]) if "tiers" in s else None,
                meshes=tuple(s["meshes"]) if "meshes" in s else None,
                drill=str(s.get("drill", "")),
            )
            for s in obj["scenarios"]
        )
        return cls(
            scenarios=scenarios,
            tiers=tuple(obj.get("tiers", TIERS)),
            meshes=tuple(obj.get("meshes", MESHES)),
            size=int(obj.get("size", 128)),
            size3d=int(obj.get("size3d", 32)),
            iterations=int(obj.get("iterations", 6)),
            guard_every=int(obj.get("guard_every", 2)),
            checkpoint_every=int(obj.get("checkpoint_every", 2)),
        )


@dataclasses.dataclass
class CellResult:
    scenario: str
    tier: str
    mesh: str
    status: str  # "ok" / "skip" / "fail"
    reason: str = ""

    @property
    def label(self) -> str:
        return f"{self.scenario} × {self.tier}/{self.mesh}"


# -- per-tier run surface -----------------------------------------------------


@dataclasses.dataclass
class _RunCfg:
    iterations: int
    guard: bool = False
    redundant: bool = False
    checkpoint_dir: Optional[str] = None
    telemetry_dir: Optional[str] = None
    run_id: Optional[str] = None
    resume: Optional[str] = None


@dataclasses.dataclass
class _Outcome:
    final: object  # np array (2-D/3-D) or list of arrays (batch)
    guard_failures: int = 0
    live_reshards: int = 0  # serve tier: in-process mesh transitions


_PATTERN = 4  # deterministic soup, every engine supports it


def _run_2d(engine: str, mesh_kind: str, plan: ChaosPlan, cfg: _RunCfg):
    from gol_tpu.models.state import Geometry
    from gol_tpu.runtime import GolRuntime, build_mesh
    from gol_tpu.utils import guard as guard_mod

    rt = GolRuntime(
        geometry=Geometry(size=plan.size, num_ranks=1),
        engine=engine,
        mesh=build_mesh(mesh_kind),
        checkpoint_every=(
            plan.checkpoint_every if cfg.checkpoint_dir else 0
        ),
        checkpoint_dir=cfg.checkpoint_dir,
        telemetry_dir=cfg.telemetry_dir,
        run_id=cfg.run_id,
    )
    if cfg.guard:
        _, state, report = guard_mod.run_guarded(
            rt,
            pattern=_PATTERN,
            iterations=cfg.iterations,
            config=guard_mod.GuardConfig(
                check_every=plan.guard_every, redundant=cfg.redundant
            ),
            resume=cfg.resume,
        )
        return _Outcome(np.asarray(state.board), report.failures)
    _, state = rt.run(
        pattern=_PATTERN, iterations=cfg.iterations, resume=cfg.resume
    )
    return _Outcome(np.asarray(state.board))


def _run_batch(mesh_kind: str, plan: ChaosPlan, cfg: _RunCfg):
    import jax

    from gol_tpu.batch import GolBatchRuntime, make_batch_mesh
    from gol_tpu.models import patterns

    # A 1-D worlds mesh only actually shards when B divides the device
    # count — size the batch so the cell exercises what it claims.
    nb = len(jax.devices()) if mesh_kind == "1d" else 3
    worlds = [
        patterns.init_global(_PATTERN, plan.size, 1) for _ in range(nb)
    ]
    brt = GolBatchRuntime(
        worlds=worlds,
        engine="auto",
        mesh=make_batch_mesh() if mesh_kind == "1d" else None,
        checkpoint_every=(
            plan.checkpoint_every if cfg.checkpoint_dir else 0
        ),
        checkpoint_dir=cfg.checkpoint_dir,
        telemetry_dir=cfg.telemetry_dir,
        run_id=cfg.run_id,
        guard_every=plan.guard_every if cfg.guard else 0,
        guard_redundant=cfg.redundant,
    )
    _, boards = brt.run(cfg.iterations, resume=cfg.resume)
    failures = brt.last_guard.failures if brt.last_guard else 0
    return _Outcome([np.asarray(b) for b in boards], failures)


def _run_3d(plan: ChaosPlan, cfg: _RunCfg, workdir: str):
    from gol_tpu import cli3d

    outdir = os.path.join(workdir, "out3d")
    os.makedirs(outdir, exist_ok=True)
    argv = [
        "2", str(plan.size3d), str(cfg.iterations), "64", "1",
        "--outdir", outdir,
    ]
    if cfg.checkpoint_dir:
        argv += [
            "--checkpoint-every", str(plan.checkpoint_every),
            "--checkpoint-dir", cfg.checkpoint_dir,
        ]
    if cfg.telemetry_dir:
        argv += ["--telemetry", cfg.telemetry_dir]
        if cfg.run_id:
            argv += ["--run-id", cfg.run_id]
    if cfg.guard:
        argv += ["--guard-every", str(plan.guard_every)]
        if cfg.redundant:
            argv += ["--guard-redundant"]
    if cfg.resume:
        argv += ["--resume", cfg.resume]
    import contextlib
    import io

    banner = io.StringIO()  # the driver's report lines, not the matrix's
    with contextlib.redirect_stdout(banner):
        rc = cli3d.main(argv)
    if rc != 0:
        raise RuntimeError(
            f"cli3d exited {rc}: {banner.getvalue().strip()}"
        )
    out = np.load(os.path.join(outdir, "World3D_of_1.npy"))
    # The in-process guard report is printed, not returned; the chaos
    # detection signal for guarded 3-D cells rides the guard_audit
    # telemetry records instead.
    return _Outcome(out)


def _run_serve(mesh_kind: str, plan: ChaosPlan, cfg: _RunCfg, workdir: str):
    """One serving-tier cell: three same-bucket requests (the fault
    plans' ``world`` axis = admission ordinal), all submitted BEFORE the
    drive loop runs — the journal record sequence and the chunk schedule
    are deterministic, so one committed plan file means one behavior.
    ``mesh_kind == "1d"`` shards the bucket groups over a 4-device
    worlds mesh and arms the health plane — the surface the ``elastic``
    scenarios drill.  Crash.exit drills need real process death and
    live in scripts/serve_smoke.py; this cell covers the in-process
    plane (board faults, journal IO faults, disk-full shedding, stalls,
    device loss, stragglers)."""
    from gol_tpu.serve.scheduler import ServeScheduler

    state_dir = cfg.checkpoint_dir or os.path.join(
        tempfile.mkdtemp(prefix="serve_", dir=workdir), "state"
    )
    sched = ServeScheduler(
        state_dir,
        slots=4,
        queue_depth=8,
        chunk=plan.guard_every,
        guard=cfg.guard,
        telemetry_dir=cfg.telemetry_dir,
        run_id=cfg.run_id,
        mesh_devices=4 if mesh_kind == "1d" else 0,
    )
    try:
        ids = []
        for i in range(3):
            st = sched.submit(
                {
                    "id": f"w{i}",
                    "pattern": _PATTERN,
                    "size": plan.size,
                    "generations": cfg.iterations,
                }
            )
            ids.append(st.request.id)
        sched.run_until_drained()
        boards = [sched.result_board(rid) for rid in ids]
        return _Outcome(
            boards, sched.guard_failures,
            live_reshards=sched.live_reshards,
        )
    finally:
        sched.close()


def _run_cell(tier: str, mesh: str, plan: ChaosPlan, cfg: _RunCfg,
              workdir: str) -> _Outcome:
    if tier == "batch":
        return _run_batch(mesh, plan, cfg)
    if tier == "3d":
        return _run_3d(plan, cfg, workdir)
    if tier == "serve":
        return _run_serve(mesh, plan, cfg, workdir)
    engine = {"dense": "dense", "bitpack": "bitpack", "pallas": "pallas",
              "activity": "activity"}[tier]
    return _run_2d(engine, mesh, plan, cfg)


def _legal(tier: str, mesh: str) -> Optional[str]:
    """Static legality of a grid cell; a string is the skip reason."""
    if tier == "pallas" and mesh != "none":
        return "engine 'pallas' (dense kernel) has no sharded path"
    if tier == "batch" and mesh == "2d":
        return "--batch shards the world axis only (a 1-D ring)"
    if tier == "3d" and mesh != "none":
        return "the 3-D driver's mesh is its own (P,R,C) grid; the " \
               "chaos matrix drives it unsharded"
    if tier == "serve" and mesh == "2d":
        return "the serve worlds axis is 1-D (a 2-D mesh has no " \
               "meaning for bucket-group sharding)"
    return None


def _equal(a, b) -> bool:
    if isinstance(a, list):
        return len(a) == len(b) and all(
            np.array_equal(x, y) for x, y in zip(a, b)
        )
    return np.array_equal(np.asarray(a), np.asarray(b))


def _events(telemetry_dir: str) -> List[dict]:
    out = []
    if not os.path.isdir(telemetry_dir):
        return out
    for name in sorted(os.listdir(telemetry_dir)):
        if not name.endswith(".jsonl"):
            continue
        with open(os.path.join(telemetry_dir, name)) as f:
            for line in f:
                line = line.strip()
                if line:
                    out.append(json.loads(line))
    return out


# -- scenario execution -------------------------------------------------------


def _guard_failures(outcome: _Outcome, telemetry_dir: Optional[str]) -> int:
    if outcome.guard_failures:
        return outcome.guard_failures
    if telemetry_dir:
        return sum(
            1
            for r in _events(telemetry_dir)
            if r.get("event") == "guard_audit" and not r.get("ok")
        )
    return 0


def _run_fleet_scenario(
    scenario: Scenario, plan: ChaosPlan, workdir: str
) -> None:
    """One fleet cell: supervised replica subprocesses behind an
    in-process :class:`FleetFront` (the chaos analogue of
    scripts/fleet_smoke.py, small enough for the matrix).

    Three requests land in one bucket — so one replica owns them all —
    with enough generations that the injected fault catches them open.
    The drill asserts the full contract: every id completes exactly
    once at the JOURNAL FOLD level across all replicas, boards are
    byte-equal to the single-replica oracle, and (for fault drills) at
    least one handoff happened.  ``drill == "front_restart"`` instead
    crashes the front tier between admission and completion and
    asserts its journal fold restores the routing epoch and route map.
    """
    import types

    from gol_tpu.resilience import faults as faults_mod
    from gol_tpu.serve import fleet as fleet_mod
    from gol_tpu.serve import journal as journal_mod
    from gol_tpu.serve.scheduler import decode_board

    cell = tempfile.mkdtemp(prefix="fleet_", dir=workdir)
    gens = plan.iterations * 50  # long enough to be mid-flight killable
    # Single-replica oracle: the in-process serve cell with the same
    # three requests (Life is deterministic — chunking cannot matter).
    faults_mod.clear()
    ref = _run_serve(
        "none", plan, _RunCfg(iterations=gens), cell
    )
    ns = types.SimpleNamespace(
        replicas=2, max_restarts=2, slots=4, queue_depth=8,
        chunk=plan.guard_every, bucket_quantum=64, engine="auto",
    )
    replicas = fleet_mod.spawn_replicas(ns, os.path.join(cell, "fleet"))
    front = None
    try:
        fleet_mod.wait_replicas_healthy(replicas, timeout_s=120.0)
        front = fleet_mod.FleetFront(
            replicas, os.path.join(cell, "fleet"),
            probe_timeout=1.0,
        )
        ids = [f"w{i}" for i in range(3)]
        for rid in ids:
            status, payload = front.submit(
                {
                    "id": rid, "pattern": _PATTERN,
                    "size": plan.size, "generations": gens,
                }
            )
            assert status in (200, 202), (
                f"fleet admission of {rid} failed ({status}): {payload}"
            )
        owner = front._routes[ids[0]]["replica"]
        epoch0 = front.epoch
        if scenario.drill == "front_restart":
            # Crash the front tier (close without drain), rebuild it
            # from the same state dir: the journal fold must restore
            # the route map, and the epoch must move FORWARD.
            front.close()
            front = fleet_mod.FleetFront(
                replicas, os.path.join(cell, "fleet"),
                probe_timeout=1.0,
            )
            assert front.epoch > epoch0, (
                "a restarted front tier must bump the routing epoch "
                f"(got {front.epoch} after {epoch0})"
            )
            for rid in ids:
                route = front._routes.get(rid)
                assert route is not None and route["replica"] == owner, (
                    f"route for {rid} not restored from the fleet "
                    f"journal fold: {route}"
                )
        else:
            # Point the armed replica faults at the owner — the plan
            # file cannot know which replica the ring picks.
            names = sorted(front.replicas)
            fault_plan = faults_mod.FaultPlan.from_obj(
                list(scenario.faults)
            )
            for spec in fault_plan.faults:
                if spec.site.startswith(("replica.", "fleet.")):
                    spec.device = names.index(owner)
            faults_mod.install(fault_plan)
        results = {}
        deadline = time.time() + 180.0
        while len(results) < len(ids) and time.time() < deadline:
            front.poll()
            for rid in ids:
                if rid in results:
                    continue
                status, payload = front.result(rid)
                if status == 200:
                    results[rid] = payload
            time.sleep(0.05)
        assert len(results) == len(ids), (
            f"only {sorted(results)} of {ids} completed — the fleet "
            "lost accepted requests"
        )
        for i, rid in enumerate(ids):
            assert np.array_equal(
                decode_board(results[rid]["board"]), ref.final[i]
            ), f"{rid}: fleet board != single-replica oracle"
        if scenario.drill != "front_restart":
            assert front.handoffs_total >= 1, (
                "no handoff fired — the fault never caught an open "
                "intent (drill timing broke)"
            )
        # Exactly-once at the fold level, fleet-wide: each id must fold
        # to completed on EXACTLY one replica (fencing arbitrates any
        # physically-duplicated writes).
        completes = {rid: 0 for rid in ids}
        for r in replicas:
            entries, _torn = journal_mod.replay(r.journal_path)
            for rid, e in entries.items():
                if rid in completes and e["status"] == "completed":
                    completes[rid] += 1
        assert all(n == 1 for n in completes.values()), (
            f"fold-level completes per id: {completes} (want all 1)"
        )
        # Let a killed/stalled owner come back and prove the fence:
        # wait for restore, then assert its fold STILL re-runs nothing.
        if scenario.drill != "front_restart" and front.handoffs_total:
            restore_deadline = time.time() + 60.0
            while (
                owner not in front.alive
                and time.time() < restore_deadline
            ):
                front.poll()
                time.sleep(0.05)
            entries, _torn = journal_mod.replay(
                front.replicas[owner].journal_path
            )
            migrated = [
                rid for rid, e in entries.items()
                if rid in completes and e["status"] == "handed_off"
            ]
            assert migrated, (
                "no handed_off entry in the original owner's fold — "
                "the both-sides handoff record is missing"
            )
    finally:
        faults_mod.clear()
        if front is not None:
            front.drain(timeout_s=60.0)
            front.close()
        for r in replicas:
            if r.proc is not None and r.proc.poll() is None:
                r.proc.kill()
        # A SIGKILLed supervisor child can leave a replica orphaned
        # only if the supervisor itself died; reap defensively.
        for r in replicas:
            if r.proc is not None:
                try:
                    r.proc.wait(timeout=10.0)
                except Exception:
                    pass


def _run_scenario(
    scenario: Scenario, tier: str, mesh: str, plan: ChaosPlan,
    clean, workdir: str,
) -> None:
    """Execute one faulted cell; raise AssertionError on any miss."""
    from gol_tpu.resilience import faults as faults_mod
    from gol_tpu.utils import checkpoint as ckpt_mod

    cell = tempfile.mkdtemp(prefix="cell_", dir=workdir)
    ck = os.path.join(cell, "ck")
    tm = os.path.join(cell, "tm")
    fault_plan = faults_mod.FaultPlan.from_obj(list(scenario.faults))

    def install():
        faults_mod.install(fault_plan)

    try:
        if scenario.kind == "guard":
            install()
            out = _run_cell(
                tier, mesh, plan,
                _RunCfg(
                    iterations=plan.iterations, guard=True,
                    redundant=scenario.redundant, telemetry_dir=tm,
                    run_id="chaos",
                ),
                cell,
            )
            failures = _guard_failures(out, tm)
            assert failures >= 1, (
                "the guard audit never failed — the injected corruption "
                "was not detected"
            )
            assert _equal(out.final, clean), (
                "rollback-replay did not recover the clean grid"
            )
        elif scenario.kind == "resume":
            install()
            out = _run_cell(
                tier, mesh, plan,
                _RunCfg(iterations=plan.iterations, checkpoint_dir=ck),
                cell,
            )
            kind = {"batch": "batch", "3d": "3d"}.get(tier, "2d")
            newest, skipped = ckpt_mod.latest_valid(ck, kind=kind)
            assert skipped, (
                "the resume walk skipped nothing — the on-disk snapshot "
                "corruption was not detected"
            )
            assert newest is not None, "no valid snapshot survived"
            gen = ckpt_mod.snapshot_generation(newest)
            remaining = plan.iterations - gen
            assert remaining > 0, (
                f"nothing left to resume (valid snapshot at {gen})"
            )
            faults_mod.clear()
            out2 = _run_cell(
                tier, mesh, plan,
                _RunCfg(iterations=remaining, resume=newest),
                cell,
            )
            assert _equal(out2.final, clean), (
                "resume past the corrupt snapshot did not recover the "
                "clean grid"
            )
        elif scenario.kind == "elastic":
            # The drill needs enough chunk boundaries for loss →
            # shrink → restore → grow to all land, so it runs its own
            # longer clean reference instead of the cached one.
            gens = plan.iterations * 4
            faults_mod.clear()
            ref = _run_cell(
                tier, mesh, plan, _RunCfg(iterations=gens), cell
            )
            install()
            out = _run_cell(
                tier, mesh, plan,
                _RunCfg(
                    iterations=gens, guard=True, telemetry_dir=tm,
                    run_id="chaos",
                ),
                cell,
            )
            assert _equal(out.final, ref.final), (
                "live elasticity changed the computed boards — the "
                "reshard/hedge path is not byte-exact"
            )
            recs = _events(tm)
            assert not any(r.get("event") == "restart" for r in recs), (
                "a restart record is on the stream — elasticity must "
                "be in-process, not supervisor-driven"
            )
            sites = {f["site"] for f in scenario.faults}
            if "device.loss" in sites:
                assert any(
                    r.get("event") == "health"
                    and r.get("verdict") == "device_loss"
                    for r in recs
                ), "no v11 device_loss verdict on the stream"
                assert any(
                    r.get("event") == "reshard" and r.get("live")
                    for r in recs
                ), "no live reshard record — the mesh never moved"
                assert out.live_reshards >= 1, "scheduler counted no reshard"
            if any(f.get("restore_after") for f in scenario.faults):
                assert any(
                    r.get("event") == "health"
                    and r.get("verdict") == "device_restore"
                    for r in recs
                ), "no device_restore verdict — capacity never grew back"
                assert out.live_reshards >= 2, (
                    "restore landed but the mesh never grew back"
                )
            if "rank.slowdown" in sites:
                assert any(
                    r.get("event") == "health"
                    and r.get("verdict") in ("straggler", "hedge")
                    for r in recs
                ), "no straggler/hedge verdict — the watchdog missed it"
        elif scenario.kind == "fleet":
            # Installs its own (owner-targeted) plan and asserts the
            # full handoff/fencing/exactly-once contract itself.
            _run_fleet_scenario(scenario, plan, workdir)
        elif scenario.kind in ("contain", "shed", "telemetry"):
            install()
            out = _run_cell(
                tier, mesh, plan,
                _RunCfg(
                    iterations=plan.iterations,
                    checkpoint_dir=(
                        ck if scenario.kind != "telemetry" else None
                    ),
                    telemetry_dir=tm, run_id="chaos",
                ),
                cell,
            )
            assert _equal(out.final, clean), (
                "the contained fault changed the computed grid"
            )
            recs = _events(tm)
            if scenario.kind == "contain":
                assert any(r.get("event") == "fault" for r in recs), (
                    "no v9 fault record — the injection left no trace"
                )
                kind = {"batch": "batch", "3d": "3d"}.get(tier, "2d")
                for path in ckpt_mod.list_snapshots(ck, kind=kind):
                    ckpt_mod.verify_snapshot(path)
            elif scenario.kind == "shed":
                assert any(
                    r.get("event") == "degraded"
                    and r.get("action") == "shed"
                    for r in recs
                ), "no v9 degraded/shed record — the shed left no trace"
            else:  # telemetry
                assert any(
                    r.get("event") == "degraded"
                    and r.get("resource") == "telemetry"
                    for r in recs
                ), (
                    "no degraded stamp — the telemetry write failure "
                    "left no trace"
                )
        else:  # pragma: no cover - Scenario.__post_init__ rejects
            raise AssertionError(f"unhandled kind {scenario.kind}")
    finally:
        faults_mod.clear()


def run_matrix(
    plan: ChaosPlan,
    only_scenarios: Optional[Sequence[str]] = None,
    out=None,
) -> List[CellResult]:
    """Execute the full grid; print one line per cell; return results."""
    import sys

    from gol_tpu.resilience import faults as faults_mod

    out = out or sys.stdout
    results: List[CellResult] = []
    clean_cache: dict = {}
    workdir = tempfile.mkdtemp(prefix="gol_chaos_")
    for scenario in plan.scenarios:
        if only_scenarios and scenario.name not in only_scenarios:
            continue
        for tier in plan.tiers:
            for mesh in plan.meshes:
                reason = _legal(tier, mesh)
                if reason is None and scenario.tiers is not None \
                        and tier not in scenario.tiers:
                    reason = f"scenario restricted to {scenario.tiers}"
                if reason is None and scenario.meshes is not None \
                        and mesh not in scenario.meshes:
                    reason = f"scenario restricted to {scenario.meshes}"
                if reason is None and (tier, mesh) not in clean_cache:
                    # Probe: the clean run decides environment-dependent
                    # legality (Pallas off-TPU, geometry×mesh limits).
                    faults_mod.clear()
                    try:
                        clean_cache[(tier, mesh)] = _run_cell(
                            tier, mesh, plan,
                            _RunCfg(iterations=plan.iterations), workdir,
                        ).final
                    except (ValueError, RuntimeError) as e:
                        clean_cache[(tier, mesh)] = CellResult(
                            "clean", tier, mesh, "skip", str(e)
                        )
                if reason is None:
                    cached = clean_cache[(tier, mesh)]
                    if isinstance(cached, CellResult):
                        reason = cached.reason
                if reason is not None:
                    res = CellResult(
                        scenario.name, tier, mesh, "skip", reason
                    )
                else:
                    try:
                        _run_scenario(
                            scenario, tier, mesh, plan,
                            clean_cache[(tier, mesh)], workdir,
                        )
                        res = CellResult(scenario.name, tier, mesh, "ok")
                    except AssertionError as e:
                        res = CellResult(
                            scenario.name, tier, mesh, "fail", str(e)
                        )
                    except Exception as e:  # noqa: BLE001 — a cell crash is a FAIL, not a crash of the matrix
                        res = CellResult(
                            scenario.name, tier, mesh, "fail",
                            f"{type(e).__name__}: {e}",
                        )
                results.append(res)
                mark = {"ok": "OK  ", "skip": "SKIP", "fail": "FAIL"}[
                    res.status
                ]
                line = f"  [{mark}] {res.label}"
                if res.reason:
                    line += f"  — {res.reason}"
                print(line, file=out)
    ok = sum(1 for r in results if r.status == "ok")
    skip = sum(1 for r in results if r.status == "skip")
    fail = sum(1 for r in results if r.status == "fail")
    print(
        f"chaos matrix: {ok} ok, {skip} skipped (visible above), "
        f"{fail} failed",
        file=out,
    )
    return results


def main(argv=None) -> int:
    """``python -m gol_tpu.resilience chaos`` entry (argv after 'chaos')."""
    import argparse

    p = argparse.ArgumentParser(
        prog="gol_tpu.resilience chaos",
        description="execute the chaos matrix from a plan file "
        "(docs/RESILIENCE.md)",
    )
    p.add_argument(
        "--plan", default=DEFAULT_PLAN_PATH, metavar="FILE",
        help="chaos plan JSON (default: the committed matrix)",
    )
    p.add_argument(
        "--scenario", action="append", metavar="NAME",
        help="restrict to named scenarios (repeatable)",
    )
    ns = p.parse_args(argv)

    # Mesh cells need a virtual device ring on bare CPU hosts — must be
    # set before the first backend touch (same move as the verifier).
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()

    plan = ChaosPlan.load(ns.plan)
    results = run_matrix(plan, only_scenarios=ns.scenario)
    return 1 if any(r.status == "fail" for r in results) else 0
