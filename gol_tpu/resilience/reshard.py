"""Elastic meshes: load any snapshot onto any other supported topology.

The PR 4 resilience tier made snapshots *survivable*; this module makes
them *portable*.  A checkpoint written by a 2-D-block pod run can resume
on a 1-D ring, a single chip, or a bigger pod — and vice versa — by
repartitioning the stored pieces onto the destination mesh's shard
boxes (docs/RESILIENCE.md, "Elastic meshes").  Three pieces:

- :class:`MeshLayout` — the portable topology descriptor (``none`` /
  ``1d`` / ``2d`` plus the rows×cols grid) stamped into sharded
  manifests by :func:`gol_tpu.utils.checkpoint.save_sharded` and
  inferred from the piece table for pre-stamp (``legacy``) snapshots.
- :class:`ReshardPlan` — the explicit src-piece → dst-shard move table.
  :func:`plan_reshard` builds it from pure geometry and
  :func:`validate_plan` proves every destination cell is covered by
  **exactly one** source intersection (the soundness property the
  static verifier's broken-fixture check keeps honest —
  ``gol_tpu/analysis/reshardcheck.py``).
- :class:`SnapshotSource` — a uniform read surface over every snapshot
  format (single-file, 1-D row-sharded, 2-D block-sharded, batch
  worlds).  Pieces are cached **bit-packed** (32 cells per uint32 word,
  the :mod:`gol_tpu.ops.bitlife` layout) so serving a full cross-read —
  every destination shard touching every source piece — holds 1 bit per
  cell, not 1 byte, and the full dense board is never assembled unless
  the destination *is* one device.  Destination column ranges that cut
  a source piece mid-word are realigned with word shifts
  (:func:`slice_packed_cols` — the roll/mask repack), not by unpacking
  whole pieces; only the requested cells ever widen back to uint8.
  This is the host-side analog of the memory-efficient redistribution
  collective (PAPERS.md): bounded transport state, piecewise moves.

Resume-on-a-different-mesh is pinned byte-identical to same-mesh resume
(tests/test_reshard.py); when source and destination topologies match,
the plan is the identity and nothing here runs at all — the
trace-identity pins still hold.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from gol_tpu.utils import checkpoint as ckpt_mod

Box = Tuple[int, int, int, int]  # (r0, r1, c0, c1), half-open

WORD_BITS = 32


class ReshardError(ValueError):
    """A snapshot cannot be repartitioned onto the requested topology."""


class ReshardPlanError(ReshardError):
    """A move table fails the exactly-once coverage property."""


# -- topology descriptor ------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class MeshLayout:
    """Portable shard-topology descriptor: how a board tiles over devices.

    ``kind`` is the CLI's mesh vocabulary (``none``/``1d``/``2d``);
    ``rows``/``cols`` the device grid.  The descriptor is deliberately
    device-free — it survives in manifests and telemetry, and two runs
    with the same layout produce identical shard boxes regardless of
    which physical chips back them.
    """

    kind: str
    rows: int = 1
    cols: int = 1

    def __post_init__(self) -> None:
        if self.kind not in ("none", "1d", "2d"):
            raise ReshardError(
                f"unknown mesh layout kind {self.kind!r}; expected "
                "'none'/'1d'/'2d'"
            )
        if self.rows < 1 or self.cols < 1:
            raise ReshardError(
                f"mesh layout needs positive grid extents, got "
                f"{self.rows}x{self.cols}"
            )
        if self.kind == "none" and (self.rows, self.cols) != (1, 1):
            raise ReshardError("layout 'none' is a 1x1 grid by definition")
        if self.kind == "1d" and self.cols != 1:
            raise ReshardError("layout '1d' shards rows only (cols must be 1)")

    @staticmethod
    def from_mesh(mesh) -> "MeshLayout":
        """The layout of a live :class:`jax.sharding.Mesh` (None = none)."""
        from gol_tpu.parallel import mesh as mesh_mod

        if mesh is None:
            return MeshLayout("none")
        rows = mesh.shape.get(mesh_mod.ROWS, 1)
        cols = mesh.shape.get(mesh_mod.COLS, 1)
        if mesh_mod.COLS in mesh.axis_names:
            return MeshLayout("2d", rows=rows, cols=cols)
        return MeshLayout("1d", rows=rows)

    @staticmethod
    def from_dict(d: Optional[dict]) -> Optional["MeshLayout"]:
        if d is None:
            return None
        return MeshLayout(
            str(d["kind"]), int(d.get("rows", 1)), int(d.get("cols", 1))
        )

    def to_dict(self) -> dict:
        return {"kind": self.kind, "rows": self.rows, "cols": self.cols}

    def describe(self) -> str:
        if self.kind == "none":
            return "unsharded (single device)"
        return f"{self.kind} mesh, {self.rows}x{self.cols} shard grid"

    def boxes(self, shape: Sequence[int]) -> List[Box]:
        """The shard boxes this layout tiles ``shape`` into (row-major).

        Shard boxes mirror the canonical ``PartitionSpec(rows, cols)``
        sharding, so they are exactly the regions
        ``jax.make_array_from_callback`` will request — which requires
        the board to divide the grid evenly.
        """
        h, w = (int(shape[0]), int(shape[1]))
        if h % self.rows or w % self.cols:
            raise ReshardError(
                f"board {h}x{w} does not divide the {self.describe()} "
                f"({self.rows} row / {self.cols} col shards)"
            )
        sh, sw = h // self.rows, w // self.cols
        return [
            (r * sh, (r + 1) * sh, c * sw, (c + 1) * sw)
            for r in range(self.rows)
            for c in range(self.cols)
        ]


def infer_layout(shape: Sequence[int], boxes: Sequence[Box]) -> MeshLayout:
    """Best-effort layout of a legacy piece table (no manifest stamp).

    A single full-board piece is ``none``; full-width row bands are a
    ``1d`` ring; a regular r×c grid is ``2d``.  Irregular covers (valid
    as checkpoints, impossible from our mesh shardings) report as a
    ``1d`` ring of their distinct row bands — the planner only needs
    *source boxes*, the layout label is telemetry.
    """
    h, w = int(shape[0]), int(shape[1])
    boxes = [tuple(int(x) for x in b) for b in boxes]
    if len(boxes) == 1 and boxes[0] == (0, h, 0, w):
        return MeshLayout("none")
    row_edges = sorted({b[0] for b in boxes})
    col_edges = sorted({b[2] for b in boxes})
    if all(b[2] == 0 and b[3] == w for b in boxes):
        return MeshLayout("1d", rows=len(row_edges))
    if len(boxes) == len(row_edges) * len(col_edges):
        return MeshLayout("2d", rows=len(row_edges), cols=len(col_edges))
    return MeshLayout("1d", rows=len(row_edges))


# -- packed-word transport ----------------------------------------------------


def pack_rows(cells: np.ndarray) -> np.ndarray:
    """uint8[h, w] 0/1 cells -> uint32[h, ceil(w/32)] words.

    Same bit order as :func:`gol_tpu.ops.bitlife.pack` (bit j of word k
    is column 32k+j), built host-side from ``np.packbits`` little-endian
    bytes so a packed piece and the device representation agree.
    """
    cells = np.asarray(cells, np.uint8)
    by = np.packbits(cells, axis=1, bitorder="little")
    pad = (-by.shape[1]) % 4
    if pad:
        by = np.pad(by, ((0, 0), (0, pad)))
    return np.ascontiguousarray(by).view("<u4")


def unpack_rows(words: np.ndarray, width: int) -> np.ndarray:
    """Inverse of :func:`pack_rows`, trimmed to ``width`` columns."""
    by = np.ascontiguousarray(words.astype("<u4")).view(np.uint8)
    return np.unpackbits(by, axis=1, count=width, bitorder="little")


def slice_packed_cols(words: np.ndarray, c0: int, c1: int) -> np.ndarray:
    """Cells ``[c0, c1)`` of packed rows, via word shifts — the seam path.

    A destination shard seam rarely lands on a source word boundary;
    instead of unpacking the whole piece, the covering words are
    realigned with a logical-shift pair (``w[k] >> s | w[k+1] << 32-s``)
    so bit 0 of the result is column ``c0``, and only the ``c1 - c0``
    requested cells are unpacked.  Word-aligned requests skip the shift
    entirely.
    """
    if not 0 <= c0 <= c1 <= words.shape[1] * WORD_BITS:
        raise ReshardError(
            f"column range [{c0}, {c1}) outside the packed width "
            f"{words.shape[1] * WORD_BITS}"
        )
    if c0 == c1:
        return np.zeros((words.shape[0], 0), np.uint8)
    k0, s = divmod(c0, WORD_BITS)
    k1 = -(-c1 // WORD_BITS)
    sel = words[:, k0:k1].astype(np.uint32, copy=bool(s))
    if s:
        hi = np.zeros_like(sel)
        hi[:, :-1] = sel[:, 1:]
        if k1 < words.shape[1]:
            # The last selected word's high bits live in the next word.
            hi[:, -1] = words[:, k1]
        sel = (sel >> np.uint32(s)) | (hi << np.uint32(WORD_BITS - s))
    return unpack_rows(sel, c1 - c0)


class PackedStore:
    """Piece cache holding boards at 1 bit/cell, serving arbitrary regions.

    ``put`` packs a piece once (host-side, vectorized); ``region``
    assembles any requested box from the intersecting pieces' packed
    rows via :func:`slice_packed_cols`.  The store is what lets a full
    cross-topology reshard run in O(board bits) transport memory plus
    one destination shard of cells at a time.
    """

    def __init__(self) -> None:
        self._pieces: Dict[Box, np.ndarray] = {}

    def __contains__(self, box: Box) -> bool:
        return tuple(box) in self._pieces

    def put(self, box: Box, cells: np.ndarray) -> None:
        box = tuple(int(x) for x in box)
        want = (box[1] - box[0], box[3] - box[2])
        if tuple(cells.shape) != want:
            raise ReshardError(
                f"piece {box} has shape {tuple(cells.shape)}, expected {want}"
            )
        self._pieces[box] = pack_rows(cells)

    def region(self, box: Box) -> np.ndarray:
        r0, r1, c0, c1 = (int(x) for x in box)
        out = np.empty((r1 - r0, c1 - c0), np.uint8)
        filled = 0
        for (pr0, pr1, pc0, pc1), words in self._pieces.items():
            ir0, ir1 = max(pr0, r0), min(pr1, r1)
            ic0, ic1 = max(pc0, c0), min(pc1, c1)
            if ir0 >= ir1 or ic0 >= ic1:
                continue
            cells = slice_packed_cols(
                words[ir0 - pr0 : ir1 - pr0], ic0 - pc0, ic1 - pc0
            )
            out[ir0 - r0 : ir1 - r0, ic0 - c0 : ic1 - c0] = cells
            filled += (ir1 - ir0) * (ic1 - ic0)
        if filled != out.size:
            raise ReshardError(
                f"region {box} only covered {filled} of {out.size} cells; "
                "the piece store does not tile it"
            )
        return out


# -- the move table -----------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class ReshardPlan:
    """Explicit src-piece → dst-shard move table for one repartition.

    ``moves`` holds one entry per destination shard box: the source
    boxes it reads from and the global-coordinate intersection each
    contributes.  Everything downstream — execution, telemetry
    accounting, the verifier's soundness check — consumes this one
    structure.
    """

    shape: Tuple[int, int]
    src: MeshLayout
    dst: MeshLayout
    # ((dst_box, ((src_box, inter_box), ...)), ...)
    moves: Tuple[Tuple[Box, Tuple[Tuple[Box, Box], ...]], ...]

    @property
    def identity(self) -> bool:
        """True when every dst shard is exactly one whole src piece."""
        return all(
            len(srcs) == 1 and srcs[0][0] == dst and srcs[0][1] == dst
            for dst, srcs in self.moves
        )

    @property
    def cells_moved(self) -> int:
        return sum(
            (i[1] - i[0]) * (i[3] - i[2])
            for _, srcs in self.moves
            for _, i in srcs
        )

    @property
    def seam_splits(self) -> int:
        """Moves whose column range starts sub-word inside its src piece
        (the intersections that exercise the shift repack)."""
        return sum(
            1
            for _, srcs in self.moves
            for sbox, i in srcs
            if (i[2] - sbox[2]) % WORD_BITS != 0
        )

    def summary(self) -> dict:
        """The telemetry block of a v7 ``reshard`` event (plus logs)."""
        return {
            "src_mesh": self.src.to_dict(),
            "dst_mesh": self.dst.to_dict(),
            "dst_shards": len(self.moves),
            "src_pieces": len({s for _, srcs in self.moves for s, _ in srcs}),
            "moves": sum(len(srcs) for _, srcs in self.moves),
            "seam_splits": self.seam_splits,
            "cells": self.cells_moved,
            # Transport bytes: pieces travel bit-packed (32 cells/word).
            "bytes_moved": self.cells_moved // 8,
        }


def _intersect(a: Box, b: Box) -> Optional[Box]:
    r0, r1 = max(a[0], b[0]), min(a[1], b[1])
    c0, c1 = max(a[2], b[2]), min(a[3], b[3])
    if r0 >= r1 or c0 >= c1:
        return None
    return (r0, r1, c0, c1)


def plan_reshard(
    shape: Sequence[int],
    src_boxes: Sequence[Box],
    src: MeshLayout,
    dst: MeshLayout,
) -> ReshardPlan:
    """Build + validate the move table from source pieces to ``dst``.

    Pure geometry — no file or device I/O — so the static verifier can
    prove plan soundness for every topology pair without a snapshot on
    disk.  The returned plan always passed :func:`validate_plan`.
    """
    shape = (int(shape[0]), int(shape[1]))
    src_boxes = [tuple(int(x) for x in b) for b in src_boxes]
    moves = []
    for dbox in dst.boxes(shape):
        srcs = []
        for sbox in src_boxes:
            inter = _intersect(dbox, sbox)
            if inter is not None:
                srcs.append((sbox, inter))
        moves.append((dbox, tuple(srcs)))
    plan = ReshardPlan(shape=shape, src=src, dst=dst, moves=tuple(moves))
    validate_plan(plan)
    return plan


def validate_plan(plan: ReshardPlan) -> None:
    """Exactly-once coverage: every destination cell has one source.

    Raises :class:`ReshardPlanError` when any dst shard is under- or
    over-covered, an intersection leaks outside its dst box or its
    claimed src box, or the dst boxes fail to tile the board.  The
    verifier's broken-fixture check feeds deliberately overlapping and
    gapped plans through here — this function failing to reject them
    fails the verify gate.
    """
    h, w = plan.shape
    try:
        ckpt_mod._validate_box_cover(
            "reshard plan (dst)", plan.shape, [d for d, _ in plan.moves]
        )
    except ckpt_mod.CorruptSnapshotError as e:
        raise ReshardPlanError(str(e)) from e
    for dbox, srcs in plan.moves:
        measure = 0
        inters = []
        for sbox, i in srcs:
            if _intersect(i, dbox) != i:
                raise ReshardPlanError(
                    f"move {i} leaks outside its dst shard {dbox}"
                )
            if _intersect(i, sbox) != i:
                raise ReshardPlanError(
                    f"move {i} claims cells outside its src piece {sbox}"
                )
            measure += (i[1] - i[0]) * (i[3] - i[2])
            inters.append(i)
        want = (dbox[1] - dbox[0]) * (dbox[3] - dbox[2])
        if measure != want:
            raise ReshardPlanError(
                f"dst shard {dbox} covered by {measure} of {want} cells; "
                "the plan is "
                + ("overlapping" if measure > want else "incomplete")
            )
        inters.sort()
        for idx, a in enumerate(inters):
            for b in inters[idx + 1 :]:
                if b[0] >= a[1]:
                    break
                if b[2] < a[3] and b[3] > a[2]:
                    raise ReshardPlanError(
                        f"dst shard {dbox}: moves {a} and {b} overlap — "
                        "a cell would be written twice"
                    )


# -- snapshot sources ---------------------------------------------------------


class SnapshotSource:
    """Uniform read surface over one snapshot, any format.

    Attributes mirror what resume needs (``shape``, ``generation``,
    ``rule``, ``num_ranks``, ``layout``, ``legacy``); ``region(box)``
    serves any rectangle of the stored board from the packed piece
    store, verifying piece fingerprints on first touch.
    """

    def __init__(
        self,
        path: str,
        shape: Tuple[int, int],
        generation: int,
        src_boxes: Sequence[Box],
        layout: MeshLayout,
        rule: Optional[str] = None,
        num_ranks: Optional[int] = None,
        legacy: bool = False,
    ) -> None:
        self.path = path
        self.shape = shape
        self.generation = generation
        self.rule = rule
        self.num_ranks = num_ranks
        self.layout = layout
        self.legacy = legacy
        self.src_boxes = [tuple(int(x) for x in b) for b in src_boxes]
        self._store = PackedStore()

    def _load_piece(self, box: Box) -> None:
        raise NotImplementedError

    def region(self, box: Box) -> np.ndarray:
        for sbox in self.src_boxes:
            if _intersect(sbox, tuple(box)) and sbox not in self._store:
                self._load_piece(sbox)
        return self._store.region(box)

    def plan_onto(self, dst: MeshLayout) -> ReshardPlan:
        return plan_reshard(self.shape, self.src_boxes, self.layout, dst)


class _WholeBoardSource(SnapshotSource):
    """Single-file formats: one piece, already verified at load."""

    def __init__(self, path, board, generation, layout, **kw):
        h, w = board.shape
        super().__init__(
            path, (h, w), generation, [(0, h, 0, w)], layout, **kw
        )
        self._store.put((0, h, 0, w), board)

    def _load_piece(self, box):  # pragma: no cover - pre-populated
        raise AssertionError(box)


class _ShardedSource(SnapshotSource):
    """Sharded checkpoint directory: pieces verified + packed on demand."""

    def __init__(self, path: str, meta: ckpt_mod.ShardedMeta) -> None:
        layout = MeshLayout.from_dict(meta.layout)
        legacy = layout is None
        if legacy:
            layout = infer_layout(meta.shape, meta.rects)
        super().__init__(
            path,
            tuple(meta.shape),
            meta.generation,
            [tuple(int(x) for x in r) for r in meta.rects],
            layout,
            rule=meta.rule,
            num_ranks=meta.num_ranks,
            legacy=legacy,
        )
        self.meta = meta
        self._proc_of = {
            tuple(int(x) for x in r): int(p)
            for r, p in zip(meta.rects, meta.procs)
        }

    def _load_piece(self, box: Box) -> None:
        # One-piece region read: the checkpoint module's existing
        # fingerprint-verified assembly, reused piece-by-piece so a
        # corrupt shard file fails with the same CorruptSnapshotError
        # wording every other load path produces.
        cells = ckpt_mod.read_sharded_region(
            self.path,
            self.meta,
            (slice(box[0], box[1]), slice(box[2], box[3])),
        )
        self._store.put(box, cells)


def open_source(
    path: str, kind: str = "2d", world: Optional[int] = None
) -> SnapshotSource:
    """A :class:`SnapshotSource` for any 2-D-board snapshot on disk.

    ``kind='2d'`` accepts single-file and sharded-directory snapshots;
    ``kind='batch'`` with ``world=i`` opens world ``i`` of a batched
    snapshot as its own (unsharded) source — a batch world resumed onto
    a mesh is a reshard like any other.  3-D volumes have no reshard
    path yet (their driver's meshes are built per-run; see
    docs/RESILIENCE.md).
    """
    name = os.path.basename(path)
    if kind == "batch" or name.endswith(ckpt_mod.BCKPT_SUFFIX):
        snap = ckpt_mod.load_batch(path)
        if world is None:
            raise ReshardError(
                f"{path}: a batch snapshot holds "
                f"{len(snap.boards)} worlds; pass world=<i> to reshard one"
            )
        if not 0 <= world < len(snap.boards):
            raise ReshardError(
                f"{path}: world {world} out of range "
                f"(snapshot holds {len(snap.boards)})"
            )
        return _WholeBoardSource(
            path, snap.boards[world], snap.generation, MeshLayout("none")
        )
    if kind == "3d" or name.endswith(ckpt_mod.CKPT3D_SUFFIX) or name.endswith(
        ckpt_mod.SHARD3D_DIR_SUFFIX
    ):
        raise ReshardError(
            f"{path}: 3-D volume snapshots have no reshard path"
        )
    if ckpt_mod.is_sharded(path):
        meta = ckpt_mod.load_sharded_meta(path)
        return _ShardedSource(path, meta)
    snap = ckpt_mod.load(path)
    if snap.top0 is not None:
        raise ReshardError(
            f"{path}: stale_t0 (reference-compat) snapshots are "
            "single-device by definition and cannot reshard"
        )
    return _WholeBoardSource(
        path,
        snap.board,
        snap.generation,
        MeshLayout("none"),
        rule=snap.rule,
        num_ranks=snap.num_ranks,
    )


def place(source: SnapshotSource, mesh, plan: ReshardPlan):
    """Materialize the snapshot's board on the destination mesh.

    Sharded destinations assemble each addressable shard directly from
    the source pieces (``make_array_from_callback`` — a multi-host
    process only ever reads the regions its own devices hold); a
    ``None`` mesh gets the whole board on one device.  ``plan`` must be
    the validated plan for this (source, mesh) pair — it is the proof
    the per-shard reads below tile the board exactly once.
    """
    import jax

    from gol_tpu.parallel import mesh as mesh_mod

    validate_plan(plan)
    h, w = source.shape
    if mesh is None:
        return jax.device_put(source.region((0, h, 0, w)))

    def read(idx):
        sl = list(idx) + [slice(None)] * (2 - len(idx))
        r0 = 0 if sl[0].start is None else sl[0].start
        r1 = h if sl[0].stop is None else sl[0].stop
        c0 = 0 if sl[1].start is None else sl[1].start
        c1 = w if sl[1].stop is None else sl[1].stop
        return source.region((r0, r1, c0, c1))

    return jax.make_array_from_callback(
        (h, w), mesh_mod.board_sharding(mesh), read
    )


def load_resharded(
    path: str,
    mesh,
    kind: str = "2d",
    world: Optional[int] = None,
):
    """One-call cross-topology load: ``(board, source, plan)``.

    The convenience surface the smoke script and tests drive; the
    runtime's resume path composes the same three steps itself so it can
    interleave its existing shape/rule/ranks validation.
    """
    source = open_source(path, kind=kind, world=world)
    plan = source.plan_onto(MeshLayout.from_mesh(mesh))
    return place(source, mesh, plan), source, plan


def topology_resume_hint(resume_path: str, kind: str = "2d") -> Optional[str]:
    """Actionable message for a plain ``--resume`` topology mismatch.

    Mirror of :func:`gol_tpu.resilience.resume.corrupt_resume_hint`: when
    the configured mesh cannot tile the board a snapshot holds, describe
    the snapshot's stamped (or inferred) topology and the ways out
    instead of leaving a raw divisibility error as the last word.  3-D
    volume snapshots have no reshard path — their hint says so and names
    the writing topology from the manifest stamp.
    """
    if kind == "3d" or os.path.basename(resume_path).endswith(
        ckpt_mod.SHARD3D_DIR_SUFFIX
    ):
        try:
            meta = ckpt_mod.load_sharded3d_meta(
                resume_path, verify_stamp=False
            )
        except (ckpt_mod.CorruptSnapshotError, OSError, ValueError):
            return None
        wrote = (
            f"written by {meta.process_count} processes"
            if meta.process_count is not None
            else f"written as {len(meta.boxes)} pieces (pre-stamp manifest)"
        )
        d, h, w = meta.shape
        return (
            f"hint: snapshot {resume_path} holds a {d}x{h}x{w} volume "
            f"{wrote}. 3-D volume snapshots have no reshard path "
            "(docs/RESILIENCE.md, elastic meshes) — relaunch on the "
            "topology that wrote it"
        )
    try:
        source = open_source(resume_path, kind=kind)
    except (ckpt_mod.CorruptSnapshotError, ReshardError, OSError, ValueError):
        return None
    h, w = source.shape
    legacy = " (legacy manifest, layout inferred)" if source.legacy else ""
    return (
        f"hint: snapshot {resume_path} holds a {h}x{w} board written as "
        f"{source.layout.describe()}{legacy}. Resume resharding is "
        "automatic on any mesh that tiles the board evenly — pick a mesh "
        "whose rows/cols divide it, or pass --allow-shrink to drop "
        "devices until the geometry divides."
    )
