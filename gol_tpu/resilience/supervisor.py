"""Process supervisor: relaunch a crashed/preempted run until it finishes.

``python -m gol_tpu.resilience supervise -- <command ...>`` runs the
child command under a bounded restart budget.  The child is expected to
be a gol driver invocation carrying ``--auto-resume`` (and a checkpoint
cadence), so every relaunch continues from the newest valid snapshot —
the supervisor itself never touches board state, it only owns the
process lifecycle:

- exit 0              → done; the supervisor exits 0.
- exit 75 (preempted) → resumable by construction; restart.
- any other exit / a signal death (kill -9 included) → crash; restart
  with exponential backoff + jitter (thundering-herd hygiene: a pod of
  supervisors must not relaunch in lockstep after a shared-storage blip).
- budget exhausted    → exit with the child's last code (a persistent
  fault; retrying cannot help — the same contract as the guard's
  restore budget, one tier up).

SIGTERM/SIGINT to the supervisor are forwarded to the child and stop the
restart loop: the operator (or the cluster scheduler) killing the
supervisor means "stop the job", not "crash worth retrying".

Every attempt is recorded in an atomically-rewritten run-manifest JSON
(attempt number, child pid, exit code, the resume generation the
checkpoint directory held at launch, timestamps) keyed by ``run_id`` —
the join handle ``python -m gol_tpu.telemetry summarize`` renders next
to the event streams (docs/RESILIENCE.md).
"""

from __future__ import annotations

import json
import os
import random
import signal
import subprocess
import sys
import time
from typing import List, Optional

from gol_tpu.resilience.preempt import EX_TEMPFAIL


def _write_manifest(path: Optional[str], manifest: dict) -> None:
    if not path:
        return
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(manifest, f, indent=2, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)


def _resume_generation(checkpoint_dir: Optional[str], kind: str):
    """Newest valid generation the next attempt would resume from."""
    if not checkpoint_dir:
        return None
    from gol_tpu.utils import checkpoint as ckpt_mod

    path, _ = ckpt_mod.latest_valid(checkpoint_dir, kind)
    return None if path is None else ckpt_mod.snapshot_generation(path)


def backoff_delay(
    attempt: int, base: float, cap: float, rng: random.Random
) -> float:
    """Exponential backoff with multiplicative jitter in [0.5, 1.5)."""
    if base <= 0:
        return 0.0
    return min(base * (2.0 ** max(attempt - 1, 0)), cap) * (
        0.5 + rng.random()
    )


def supervise(
    child_argv: List[str],
    max_restarts: int = 10,
    backoff_base: float = 1.0,
    backoff_max: float = 60.0,
    manifest_path: Optional[str] = None,
    checkpoint_dir: Optional[str] = None,
    kind: str = "2d",
    run_id: Optional[str] = None,
    backoff_seed: Optional[int] = None,
    out=None,
) -> int:
    """Run ``child_argv`` to completion under the restart budget.

    Returns the exit code the supervisor should exit with.  The attempt
    counter is exported to the child as ``GOL_RESTART_ATTEMPT`` so
    restarted runs stamp a ``restart`` telemetry event into their own
    streams (the restart-storm watchdog reads those).
    """
    if not child_argv:
        raise ValueError("supervise needs a child command after '--'")
    if max_restarts < 0:
        raise ValueError(f"max_restarts must be >= 0, got {max_restarts}")
    out = sys.stderr if out is None else out
    rng = random.Random(backoff_seed)
    stop = {"signum": None}
    child = {"proc": None}

    def forward(signum, frame):
        stop["signum"] = signum
        p = child["proc"]
        if p is not None and p.poll() is None:
            try:
                p.send_signal(signum)
            except OSError:  # pragma: no cover - child died in between
                pass

    previous = {}
    try:
        for s in (signal.SIGTERM, signal.SIGINT):
            previous[s] = signal.signal(s, forward)
    except ValueError:  # not the main thread (tests): run unforwarded
        previous = {}

    manifest = dict(
        run_id=run_id,
        child=list(child_argv),
        max_restarts=max_restarts,
        checkpoint_dir=checkpoint_dir,
        attempts=[],
        finished=False,
        final_exit=None,
    )
    try:
        rc = 1
        for attempt in range(max_restarts + 1):
            record = dict(
                attempt=attempt,
                resume_generation=_resume_generation(checkpoint_dir, kind),
                start_t=time.time(),
                pid=None,
                end_t=None,
                exit_code=None,
            )
            manifest["attempts"].append(record)
            # GOL_ALLOW_SHRINK arms the elastic shrink policy in the
            # child (docs/RESILIENCE.md): a relaunch that comes up with
            # fewer (or non-tiling) devices drops to the largest mesh
            # the board divides and reshards its resume snapshot onto
            # it, instead of burning this budget on a divisibility
            # error attempt after attempt.
            env = dict(
                os.environ,
                GOL_RESTART_ATTEMPT=str(attempt),
                GOL_ALLOW_SHRINK="1",
            )
            proc = subprocess.Popen(child_argv, env=env)
            child["proc"] = proc
            record["pid"] = proc.pid
            _write_manifest(manifest_path, manifest)
            rc = proc.wait()
            child["proc"] = None
            record["end_t"] = time.time()
            record["exit_code"] = rc
            _write_manifest(manifest_path, manifest)
            if rc == 0:
                break
            if stop["signum"] is not None:
                print(
                    f"supervisor: stopping on signal {stop['signum']} "
                    f"(child exited {rc}); not restarting",
                    file=out,
                )
                break
            if attempt == max_restarts:
                print(
                    f"supervisor: child exited {rc} and the restart "
                    f"budget ({max_restarts}) is exhausted — giving up",
                    file=out,
                )
                break
            why = "preempted" if rc == EX_TEMPFAIL else "crashed"
            delay = backoff_delay(attempt + 1, backoff_base, backoff_max, rng)
            print(
                f"supervisor: child exited {rc} ({why}); restart "
                f"{attempt + 1}/{max_restarts} in {delay:.1f}s",
                file=out,
            )
            # Sleep in small slices so a stop signal interrupts the wait.
            deadline = time.time() + delay
            while time.time() < deadline and stop["signum"] is None:
                time.sleep(min(0.1, max(deadline - time.time(), 0)))
            if stop["signum"] is not None:
                break
        manifest["finished"] = rc == 0
        manifest["final_exit"] = rc
        _write_manifest(manifest_path, manifest)
        return rc
    finally:
        for s, old in previous.items():
            try:
                signal.signal(s, old)
            except (ValueError, TypeError):  # pragma: no cover
                pass
