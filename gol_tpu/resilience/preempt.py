"""Cooperative preemption: turn SIGTERM/SIGINT into a clean chunk-boundary
exit instead of a mid-write kill.

The reference exits the process on any signal with whatever half-written
state the OS leaves behind; a scheduler preempting a pod job cannot tell
"this run can be resumed" from "this run failed".  Here the signal handler
only *sets a flag*; the chunked loops (``GolRuntime.run``, the guarded
loop, the 3-D driver) poll it at chunk boundaries — the one point where
the board is whole, fenced, and (in guarded mode) audited — write a final
fingerprinted checkpoint, emit a ``preempt`` telemetry event, and raise
:class:`Preempted`, which the CLIs map to exit code
:data:`EX_TEMPFAIL` (75): the sysexits convention for "temporary failure,
retry later", distinct from 0 (done) and 255 (error).

A second signal while the flag is already set means the operator wants
*out now*: the original disposition is restored and the signal re-raised,
so a hung chunk cannot make the process unkillable short of SIGKILL.

Everything here is host-side state; no compiled program ever sees the
flag (the trace-identity tests pin that the chunk programs are
byte-identical with the guard installed).
"""

from __future__ import annotations

import contextlib
import os
import signal
import sys
import threading
from typing import Optional

# sysexits.h EX_TEMPFAIL — "preempted, resumable", the code supervisors
# and schedulers key restart decisions on.
EX_TEMPFAIL = 75


class Preempted(Exception):
    """A chunked loop stopped cooperatively at a chunk boundary.

    Deliberately NOT a ``ValueError``: the CLIs' clean-error handlers
    (``except (ValueError, OSError)`` → exit 255) must never swallow a
    preemption — it has its own exit code.
    """

    def __init__(self, generation: int, checkpoint_dir: Optional[str] = None):
        self.generation = generation
        self.checkpoint_dir = checkpoint_dir
        where = f" (checkpoints in {checkpoint_dir})" if checkpoint_dir else ""
        super().__init__(
            f"preempted at generation {generation}{where}"
        )


class ReshardPoint(Exception):
    """An in-flight reshard stop: the chunked loop checkpointed at a chunk
    boundary so the driver can replan and reload on a different mesh.

    Rides the same chunk-boundary plumbing as :class:`Preempted` (board
    whole, fenced, snapshot durably renamed before the raise) but means
    "continue me on the new topology *now*, in this process", not "exit
    75 and wait for a relaunch".  Like ``Preempted`` it is deliberately
    not a ``ValueError`` — the CLIs' clean-error handlers must never eat
    it.
    """

    def __init__(self, generation: int, snapshot_path: str, remaining: int):
        self.generation = generation
        self.snapshot_path = snapshot_path
        self.remaining = remaining  # generations still owed after the stop
        super().__init__(
            f"reshard point at generation {generation} "
            f"({remaining} generations remaining; snapshot {snapshot_path})"
        )


_flag = threading.Event()


def preempt_requested() -> bool:
    """Host-side poll the chunked loops call at chunk boundaries.

    **Single-process view only.**  Multi-host loops must use
    :func:`agreed_preempt_requested`: signal delivery is per-process and
    asynchronous, and a rank that exits a boundary early while its peers
    enter the next chunk's collectives would deadlock the job.
    """
    return _flag.is_set()


def agreed_preempt_requested() -> bool:
    """Job-wide preemption poll: true when ANY rank saw the signal.

    On multi-host jobs this is one scalar allgather per chunk boundary
    (max over the per-rank flags) — every rank takes the same decision
    at the same boundary, so the final sharded checkpoint's barrier and
    the exit are collective too.  The chunk cadence already pays a
    checkpoint barrier at these boundaries; a scalar collective is
    noise next to it.  Single-process jobs short-circuit to the local
    flag (no collective machinery touched).
    """
    local = _flag.is_set()
    import jax

    if jax.process_count() == 1:
        return local
    from gol_tpu.parallel import multihost

    agreed = max(multihost.allgather_host_ints(int(local))) > 0
    if agreed and not local:
        # Mirror the signal so this rank's own exit path (second-signal
        # semantics, guard teardown) behaves as if it were signalled.
        _flag.set()
    return agreed


def request_preemption() -> None:
    """Set the flag programmatically (drills, tests, embedding code)."""
    _flag.set()


def clear_preemption() -> None:
    _flag.clear()


_SIGNALS = (signal.SIGTERM, signal.SIGINT)


def _handler(signum, frame) -> None:
    if _flag.is_set():
        # Second signal: the operator insists.  Restore the default
        # disposition and re-raise so the process dies with the normal
        # signal semantics (exit 128+signum), not a swallowed request.
        signal.signal(signum, signal.SIG_DFL)
        os.kill(os.getpid(), signum)
        return
    _flag.set()
    try:
        name = signal.Signals(signum).name
    except ValueError:  # pragma: no cover - unknown signal number
        name = str(signum)
    print(
        f"gol: caught {name}; finishing the current chunk, then "
        "checkpointing and exiting 75 (send again to die immediately)",
        file=sys.stderr,
    )


@contextlib.contextmanager
def preemption_guard(signals=_SIGNALS):
    """Install the cooperative handlers for the duration of a run.

    A flag already set on entry is honored (that's how drills and
    embedders use :func:`request_preemption`: "preempt at the first
    chunk boundary"); the flag is cleared on exit so one CLI invocation
    never leaks its preemption into the next.  Previous handlers are
    restored on exit, and off the main thread (where CPython forbids
    ``signal.signal``) this degrades to a no-op — worker-thread
    embedders don't get signal-driven preemption, but
    :func:`request_preemption` still works.
    """
    previous = {}
    try:
        for s in signals:
            previous[s] = signal.signal(s, _handler)
    except ValueError:  # not the main thread: no handler was installed
        previous = {}
    try:
        yield
    finally:
        for s, old in previous.items():
            try:
                signal.signal(s, old)
            except (ValueError, TypeError):  # pragma: no cover
                pass
        _flag.clear()
