"""Checkpoint retention: keep-last-K *valid* snapshots, bounded disk.

A week-long run checkpointing every few minutes writes thousands of
snapshots; without GC the checkpoint directory, not the board, becomes
the scaling limit.  The policy:

- keep the newest K snapshots **that verify** (walking newest→oldest and
  fingerprint-checking each candidate until K valid ones are found — a
  corrupt newest snapshot must not silently shrink the usable history to
  K-1);
- never delete the resume source of the current run (the one snapshot a
  rollback might still need) nor anything newer than the newest kept;
- invalid candidates are left in place — they are evidence of a fault,
  they never count toward K, and the auto-resume walk skips them anyway;
- leftover ``.tmp.npz`` files from a killed writer are removed (they can
  never be loaded; :func:`~gol_tpu.utils.checkpoint.latest` and the
  validated walk both ignore them, so deleting them is pure cleanup).

Verification cost is K full snapshot reads per GC pass — deliberate: the
only thing worse than an unbounded checkpoint directory is a GC that
deleted your last good fallback because it trusted a directory listing.
"""

from __future__ import annotations

import os
import shutil
from typing import Iterable, List

from gol_tpu.utils import checkpoint as ckpt_mod


def _remove(path: str) -> None:
    if os.path.isdir(path):
        shutil.rmtree(path, ignore_errors=True)
    else:
        try:
            os.remove(path)
        except OSError:
            pass


def gc_snapshots(
    directory: str,
    keep: int,
    kind: str = "2d",
    protect: Iterable[str] = (),
) -> List[str]:
    """Delete snapshots older than the K-th newest valid one.

    Returns the deleted paths.  ``protect`` paths (the run's resume
    source) are never deleted.  Safe to call from the async writer thread
    (it follows the queued saves, so no in-flight ``.tmp`` of this
    process is ever swept) and idempotent — a second pass deletes
    nothing.
    """
    if keep < 1:
        raise ValueError(f"keep must be >= 1, got {keep}")
    protected = {os.path.abspath(p) for p in protect if p}
    candidates = ckpt_mod.list_snapshots(directory, kind)
    valid_found = 0
    cutoff_index = None  # delete strictly-older-than list index
    for i in range(len(candidates) - 1, -1, -1):
        try:
            ckpt_mod.verify_snapshot(candidates[i])
        except (ckpt_mod.CorruptSnapshotError, OSError, ValueError):
            continue
        valid_found += 1
        if valid_found >= keep:
            cutoff_index = i
            break
    deleted: List[str] = []
    if cutoff_index is not None:
        for path in candidates[:cutoff_index]:
            if os.path.abspath(path) in protected:
                continue
            try:
                ckpt_mod.verify_snapshot(path)
            except (ckpt_mod.CorruptSnapshotError, OSError, ValueError):
                continue  # invalid: evidence, not garbage
            _remove(path)
            deleted.append(path)
    # Stale .tmp files: a killed writer's torn output, never loadable.
    prefix = "ckpt3d_" if kind == "3d" else "ckpt_"
    if os.path.isdir(directory):
        for name in os.listdir(directory):
            if name.startswith(prefix) and name.endswith(".tmp.npz"):
                tmp = os.path.join(directory, name)
                _remove(tmp)
                deleted.append(tmp)
    return deleted
