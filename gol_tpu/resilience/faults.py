"""The declarative fault-injection plane (docs/RESILIENCE.md).

Every fault the framework claims to survive must be *fireable* — "a
recovery path that has never fired is a recovery path that does not
work" (utils/guard.py).  Before this module the injection hooks were
ad-hoc and single-purpose: ``GOL_CKPT_TEST_WRITE_DELAY`` widened the
tmp→rename window for the kill-9 drill, and the guard took a Python
``fault_hook`` callable tests had to hand-build.  A :class:`FaultPlan`
replaces them with one declarative surface spanning every layer:

======================  =====================================================
site                    what fires
======================  =====================================================
``board.bitflip``       corrupt one cell of the live board at a chosen
                        generation/rank/world — ``value`` >= 0 writes that
                        byte (out-of-range values are what the guard's 0/1
                        invariant catches), ``value`` = -1 flips the cell
                        in-range (0↔1: the SDC only the redundancy audit
                        can see)
``checkpoint.io_error``  transient ``OSError(EIO)`` on a snapshot write
                        (``count`` times) — exercises the bounded
                        retry+backoff in :mod:`gol_tpu.resilience.degrade`
``checkpoint.torn_tmp``  the snapshot ``.tmp`` is written truncated and the
                        write raises — the torn file must never become a
                        resume candidate, and the retry must land a clean one
``checkpoint.disk_full`` persistent ``OSError(ENOSPC)`` on snapshot writes —
                        exercises the shed policy (telemetry first, then
                        checkpoints; the run itself never dies)
``checkpoint.rename_delay``  widen the tmp→rename window by ``delay_s``
                        (the ``GOL_CKPT_TEST_WRITE_DELAY`` back-compat
                        alias — the env var keeps working)
``snapshot.bitflip``    flip one byte of the just-renamed snapshot file ON
                        DISK — bit rot; the fingerprint verification of the
                        resume walk must refuse it
``telemetry.write_error``  ``OSError`` on the next rank-file write — the
                        stream must degrade (warn once, drop, stamp
                        ``degraded``), never kill the run
``hostcopy.error``      transient ``OSError(EIO)`` on an OOC band
                        write-back (device→host board copy, ``count``
                        times) — exercises the same bounded
                        retry+backoff containment as checkpoint writes;
                        a persistent failure surfaces (the host board IS
                        the state, there is nothing to shed)
``crash.exit``          ``os._exit`` at the first chunk boundary reaching
                        ``at`` — the supervisor-child crash; armed only on
                        restart attempt < ``attempts``, so the relaunch
                        completes
``rank.stall``          sleep ``delay_s`` at a chunk boundary on the chosen
                        rank — the slow-rank hang
``device.loss``         mark ``device`` lost at a chunk boundary — the health
                        plane (:mod:`gol_tpu.resilience.health`) turns it
                        into a live-reshard verdict; ``restore_after`` > 0
                        brings the device back that many generations later
                        (the shrink→grow→shrink drill)
``rank.slowdown``       inflate the measured chunk wall by ``delay_s`` on the
                        chosen rank — a degraded-but-alive device; the
                        straggler watchdog must flag it (the wall is
                        inflated, not slept, so drills stay fast — on real
                        hardware the measurement needs no injection)
``replica.kill``        SIGKILL the fleet replica at index ``device``
                        mid-flight — the front tier's host monitor must see
                        the missed heartbeats, hand the replica's open
                        intents off, and fence the supervisor's relaunch
                        (docs/SERVING.md, "The fleet")
``replica.stall``       freeze heartbeat responses from replica ``device``
                        for ``delay_s`` seconds — the dead-then-returns
                        drill: handoff fires, then the original comes back
                        and must find its intents owned elsewhere
``fleet.partition``     the front tier cannot reach replica ``device`` for
                        ``delay_s`` seconds (the replica itself stays
                        healthy) — a one-sided network cut; exactly-once
                        must hold even though the "dead" replica keeps
                        executing
======================  =====================================================

Plans load from JSON — ``--fault-plan PATH`` on both CLIs, or the
``GOL_FAULT_PLAN`` environment variable holding a path *or* inline JSON
(the supervisor's children inherit it, which is how the chaos drills arm
relaunches).  Everything here is host-side: with no plan installed every
hook is one ``None`` check, and the compiled chunk programs are
byte-identical either way (the trace-identity pin in
tests/test_faults.py).  Fired injections are recorded in a ledger the
run loops drain into schema-v9 ``fault`` telemetry events.
"""

from __future__ import annotations

import dataclasses
import errno as errno_mod
import json
import os
import threading
import time
from typing import List, Optional

SITES = (
    "board.bitflip",
    "checkpoint.io_error",
    "checkpoint.torn_tmp",
    "checkpoint.disk_full",
    "checkpoint.rename_delay",
    "hostcopy.error",
    "snapshot.bitflip",
    "telemetry.write_error",
    "crash.exit",
    "rank.stall",
    "device.loss",
    "rank.slowdown",
    "replica.kill",
    "replica.stall",
    "fleet.partition",
)

#: The documented back-compat alias for a
#: ``{"site": "checkpoint.rename_delay", "delay_s": S}`` plan entry.
RENAME_DELAY_ENV = "GOL_CKPT_TEST_WRITE_DELAY"
PLAN_ENV = "GOL_FAULT_PLAN"


class FaultPlanError(ValueError):
    """A fault plan fails to parse or names an unknown site/field."""


@dataclasses.dataclass
class FaultSpec:
    """One armed injection.  Fields beyond ``site`` select where/when:

    - ``at``: the generation at (or after) which the spec arms; sites
      with no generation context (telemetry writes) ignore it.
    - ``count``: how many times the spec fires (-1 = unlimited).
    - ``rank``: the ``jax.process_index`` that injects (-1 = every rank).
    - ``attempts``: arm only while ``GOL_RESTART_ATTEMPT`` < attempts
      (-1 = every supervised relaunch; the default 1 arms the first
      attempt only, so a crash spec cannot re-kill its own recovery).
    - ``world``: the batch world a ``board.bitflip`` targets (0 for
      single-world runs); ``plane``/``row``/``col`` the cell; ``value``
      the byte to write (-1 = in-range 0↔1 flip).
    - ``delay_s``: seconds for ``rank.stall`` / ``checkpoint.rename_delay``,
      or the wall inflation a ``rank.slowdown`` reports.
    - ``device``: the mesh device a ``device.loss`` takes out;
      ``restore_after`` > 0 schedules its return that many generations
      after the loss (0 = the device stays gone).  The fleet sites
      (``replica.kill`` / ``replica.stall`` / ``fleet.partition``)
      reuse ``device`` as the replica index and ``delay_s`` as the
      stall / partition window.
    """

    site: str
    at: int = 0
    count: int = 1
    rank: int = -1
    attempts: int = 1
    world: int = 0
    plane: int = 0
    row: int = 0
    col: int = 0
    value: int = -1
    delay_s: float = 0.0
    device: int = 0
    restore_after: int = 0

    def __post_init__(self) -> None:
        if self.site not in SITES:
            raise FaultPlanError(
                f"unknown fault site {self.site!r}; expected one of {SITES}"
            )
        if self.count == 0 or self.count < -1:
            raise FaultPlanError(
                f"{self.site}: count must be positive or -1 (unlimited), "
                f"got {self.count}"
            )
        if self.delay_s < 0:
            raise FaultPlanError(
                f"{self.site}: delay_s must be >= 0, got {self.delay_s}"
            )
        if self.restore_after < 0:
            raise FaultPlanError(
                f"{self.site}: restore_after must be >= 0 "
                f"(0 = permanent loss), got {self.restore_after}"
            )

    @classmethod
    def from_dict(cls, obj: dict) -> "FaultSpec":
        if not isinstance(obj, dict) or "site" not in obj:
            raise FaultPlanError(
                f"fault entry must be an object with a 'site', got {obj!r}"
            )
        known = {f.name for f in dataclasses.fields(cls)}
        extra = set(obj) - known
        if extra:
            raise FaultPlanError(
                f"{obj.get('site')}: unknown fault fields {sorted(extra)} "
                f"(known: {sorted(known)})"
            )
        return cls(**obj)

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class FaultPlan:
    """An ordered list of :class:`FaultSpec` entries.

    JSON form: either a bare list of entries or ``{"faults": [...]}``
    (the object form leaves room for chaos-matrix metadata next to the
    entries — :mod:`gol_tpu.resilience.chaos` uses it).
    """

    faults: List[FaultSpec] = dataclasses.field(default_factory=list)

    @classmethod
    def from_obj(cls, obj) -> "FaultPlan":
        if isinstance(obj, dict):
            obj = obj.get("faults", [])
        if not isinstance(obj, list):
            raise FaultPlanError(
                "a fault plan is a list of entries or {'faults': [...]}, "
                f"got {type(obj).__name__}"
            )
        return cls(faults=[FaultSpec.from_dict(e) for e in obj])

    @classmethod
    def loads(cls, text: str) -> "FaultPlan":
        try:
            return cls.from_obj(json.loads(text))
        except json.JSONDecodeError as e:
            raise FaultPlanError(f"fault plan is not valid JSON: {e}") from e

    @classmethod
    def load(cls, path_or_json: str) -> "FaultPlan":
        """A path to a JSON file, or inline JSON (starts with '[' / '{')."""
        text = path_or_json.strip()
        if text.startswith("[") or text.startswith("{"):
            return cls.loads(text)
        try:
            with open(path_or_json) as f:
                return cls.loads(f.read())
        except OSError as e:
            raise FaultPlanError(
                f"cannot read fault plan {path_or_json!r}: {e}"
            ) from e

    def to_json(self) -> str:
        return json.dumps({"faults": [s.to_dict() for s in self.faults]})


# -- the active plane --------------------------------------------------------
#
# One plan per process.  Mutable fire-count state lives in _remaining
# (parallel to the plan's specs), the fired ledger in _fired; all three
# behind one lock because checkpoint faults fire on the async writer
# thread while board faults fire on the main loop.

_lock = threading.Lock()
_plan: Optional[FaultPlan] = None
_remaining: List[int] = []
_fired: List[dict] = []


def install(plan: Optional[FaultPlan]) -> None:
    """Arm ``plan`` for this process (None = clear).  Resets fire counts
    and the fired ledger, and (un)hooks the telemetry write site."""
    global _plan, _remaining, _fired, _telemetry_writes
    from gol_tpu import telemetry as telemetry_mod

    with _lock:
        _plan = plan
        _remaining = [] if plan is None else [s.count for s in plan.faults]
        _fired = []
        _telemetry_writes = 0
    telemetry_mod._telemetry_write_hook = (
        _telemetry_hook
        if plan is not None
        and any(s.site == "telemetry.write_error" for s in plan.faults)
        else None
    )


def clear() -> None:
    install(None)


def active() -> Optional[FaultPlan]:
    return _plan


def install_from_env() -> Optional[FaultPlan]:
    """Install the plan named by ``GOL_FAULT_PLAN`` (path or inline
    JSON), if set.  Both CLIs call this at startup, so supervised
    children inherit the plan through the environment."""
    text = os.environ.get(PLAN_ENV)
    if not text:
        return None
    plan = FaultPlan.load(text)
    install(plan)
    return plan


def drain_fired() -> List[dict]:
    """Fired-injection records accumulated since the last drain — the
    run loops turn them into schema-v9 ``fault`` telemetry events."""
    global _fired
    with _lock:
        out, _fired = _fired, []
    return out


def _restart_attempt() -> int:
    try:
        return int(os.environ.get("GOL_RESTART_ATTEMPT", "0"))
    except ValueError:
        return 0


def _process_index() -> int:
    try:
        import jax

        return jax.process_index()
    except Exception:  # pragma: no cover - jax not initialized
        return 0


def _matching(site: str, generation: Optional[int]):
    """Indices of armed specs for ``site`` at ``generation`` (no consume)."""
    if _plan is None:
        return []
    out = []
    for i, spec in enumerate(_plan.faults):
        if spec.site != site or _remaining[i] == 0:
            continue
        if generation is not None and generation < spec.at:
            continue
        if spec.rank >= 0 and spec.rank != _process_index():
            continue
        if spec.attempts >= 0 and _restart_attempt() >= spec.attempts:
            continue
        out.append(i)
    return out


def _consume(i: int, generation: Optional[int], **detail) -> FaultSpec:
    spec = _plan.faults[i]
    if _remaining[i] > 0:
        _remaining[i] -= 1
    _fired.append(
        dict(site=spec.site, generation=generation, **detail)
    )
    return spec


def fire(site: str, generation: Optional[int] = None, **detail):
    """Consume the first armed spec for ``site``, or return ``None``."""
    with _lock:
        hits = _matching(site, generation)
        if not hits:
            return None
        return _consume(hits[0], generation, **detail)


# -- site: checkpoint writes -------------------------------------------------


def rename_gap() -> None:
    """The tmp→rename window hook (``checkpoint.rename_delay``).

    Honors both plan entries and the documented legacy alias
    ``GOL_CKPT_TEST_WRITE_DELAY`` (seconds), so pre-plan drills keep
    working unchanged.
    """
    delay = 0.0
    spec = fire("checkpoint.rename_delay")
    if spec is not None:
        delay = spec.delay_s
    env = os.environ.get(RENAME_DELAY_ENV)
    if env:
        try:
            delay = max(delay, float(env))
        except ValueError:
            pass
    if delay > 0:
        time.sleep(delay)


def checkpoint_write_fault(tmp_path: str, generation: Optional[int]) -> None:
    """Fire any armed checkpoint-write fault for this snapshot.

    Called by every snapshot writer immediately before the ``.tmp``
    write.  ``torn_tmp`` additionally leaves a truncated garbage tmp on
    disk — the artifact a mid-write crash produces — which must stay
    invisible to the resume walk.  Raises ``OSError`` (EIO or ENOSPC);
    the containment layer (:mod:`gol_tpu.resilience.degrade`) decides
    whether that means retry, shed, or surface.
    """
    spec = fire("checkpoint.torn_tmp", generation, path=tmp_path)
    if spec is not None:
        with open(tmp_path, "wb") as f:
            f.write(b"PK\x03\x04torn")  # a zip header, then nothing
        raise OSError(
            errno_mod.EIO, f"injected torn checkpoint write: {tmp_path}"
        )
    spec = fire("checkpoint.io_error", generation, path=tmp_path)
    if spec is not None:
        raise OSError(
            errno_mod.EIO, f"injected transient checkpoint IO error: {tmp_path}"
        )
    spec = fire("checkpoint.disk_full", generation, path=tmp_path)
    if spec is not None:
        raise OSError(
            errno_mod.ENOSPC, f"injected disk-full checkpoint write: {tmp_path}"
        )


def hostcopy_fault(generation: Optional[int]) -> None:
    """``hostcopy.error``: fire any armed fault on an OOC band
    write-back.  Called by the streaming scheduler immediately before a
    fetched band is copied into the host board; raises ``OSError(EIO)``
    and lets :func:`gol_tpu.resilience.degrade.write_with_retry` decide
    retry vs surface (never shed — the host board is the state)."""
    spec = fire("hostcopy.error", generation)
    if spec is not None:
        raise OSError(
            errno_mod.EIO, "injected host copy-back error"
        )


def corrupt_snapshot_file(path: str, generation: Optional[int]) -> None:
    """``snapshot.bitflip``: flip one byte of the just-renamed snapshot
    ON DISK (bit rot).  A corrupted archive member or zip structure —
    either way the fingerprint/readability verification of the resume
    walk must refuse the file."""
    spec = fire("snapshot.bitflip", generation, path=path)
    if spec is None:
        return
    size = os.path.getsize(path)
    if size == 0:  # pragma: no cover - snapshots are never empty
        return
    # Land in the member data, not the zip end-of-central-directory —
    # the flip should read as a corrupt *snapshot*, deterministically.
    offset = min(max(size // 2, 1), size - 1)
    with open(path, "r+b") as f:
        f.seek(offset)
        byte = f.read(1)
        f.seek(offset)
        f.write(bytes([byte[0] ^ 0xFF]))


# -- site: telemetry writes --------------------------------------------------

# Telemetry writes have no generation context, so the site's ``at``
# counts RECORDS written by this process instead (0 = the first write,
# the run_header) — a spec with ``at: 5`` lets five records land and
# fails the sixth.
_telemetry_writes = 0


def _telemetry_hook() -> None:
    global _telemetry_writes
    n = _telemetry_writes
    _telemetry_writes += 1
    spec = fire("telemetry.write_error", generation=n)
    if spec is not None:
        raise OSError(
            errno_mod.EIO
            if spec.value < 0
            else spec.value,
            "injected telemetry rank-file write error",
        )


# -- site: the live board ----------------------------------------------------


def has_board_faults() -> bool:
    """Whether any ``board.bitflip`` spec is still armed — loops check
    this once per chunk so the no-plan path never imports jax here."""
    with _lock:
        if _plan is None:
            return False
        return any(
            s.site == "board.bitflip" and _remaining[i] != 0
            for i, s in enumerate(_plan.faults)
        )


def _flip_cell(board, idx, value: int):
    import jax.numpy as jnp

    if value >= 0:
        return board.at[idx].set(jnp.uint8(value))
    # In-range flip (0↔1): the silent corruption the 0/1 invariant
    # passes and only the redundancy audit can catch.
    return board.at[idx].set(jnp.uint8(1) - board[idx])


def apply_board_faults(board, generation: int, world_ids=None):
    """Apply every armed ``board.bitflip`` due at ``generation``.

    ``board`` is the dense uint8 state every chunk boundary holds: a 2-D
    grid, a 3-D volume, or — with ``world_ids`` (the bucket's world
    indices) — a batched ``[B, H, W]`` stack, where each spec's
    ``world`` selects the stack slot (specs whose world lives in another
    bucket are left armed for it).  Functional cell updates, outside the
    chunk programs — the evolver jaxprs never see the plane.
    """
    with _lock:
        hits = _matching("board.bitflip", generation)
        todo = []
        for i in hits:
            spec = _plan.faults[i]
            if world_ids is not None:
                if spec.world not in world_ids:
                    continue
                idx = (world_ids.index(spec.world), spec.row, spec.col)
                detail = dict(world=spec.world, row=spec.row, col=spec.col)
            elif getattr(board, "ndim", 2) == 3:
                idx = (spec.plane, spec.row, spec.col)
                detail = dict(plane=spec.plane, row=spec.row, col=spec.col)
            else:
                idx = (spec.row, spec.col)
                detail = dict(row=spec.row, col=spec.col)
            detail["value"] = spec.value
            _consume(i, generation, **detail)
            todo.append((idx, spec.value))
    for idx, value in todo:
        board = _flip_cell(board, idx, value)
    return board


def board_fault_hook():
    """A guard-style ``fault_hook(board, generation) -> board`` over the
    plan's ``board.bitflip`` entries, or ``None`` when none are armed —
    what :func:`gol_tpu.utils.guard.guarded_loop` composes with any
    caller-provided hook."""
    if not has_board_faults():
        return None
    return apply_board_faults


# -- site: degraded hardware (the health plane's injection points) -----------


def device_losses(generation: int) -> List[FaultSpec]:
    """Consume every armed ``device.loss`` spec due at ``generation``.

    Polled once per chunk boundary by
    :meth:`gol_tpu.resilience.health.HealthMonitor.poll` — the verdicts
    (and the live reshard they trigger) belong to the health plane; this
    plane only decides *that* a device dies, and records it in the fired
    ledger like every other site.
    """
    out = []
    with _lock:
        for i in _matching("device.loss", generation):
            spec = _plan.faults[i]
            _consume(
                i,
                generation,
                device=spec.device,
                restore_after=spec.restore_after,
            )
            out.append(spec)
    return out


def rank_slowdown(generation: int) -> float:
    """Seconds of injected chunk-wall inflation due at ``generation``.

    The straggler drill: the watchdog compares the *reported* wall to
    its fitted baseline, so inflating the measurement (instead of
    sleeping) exercises the same verdict path without slowing the
    drill down.
    """
    with _lock:
        hits = _matching("rank.slowdown", generation)
        if not hits:
            return 0.0
        spec = _plan.faults[hits[0]]
        _consume(hits[0], generation, delay_s=spec.delay_s)
        return spec.delay_s


# -- site: the process -------------------------------------------------------


# Crash-forensics hook (gol_tpu/telemetry/blackbox.py registers the
# black-box dump here): ``os._exit`` skips flushes and atexit by
# design, so the window between firing ``crash.exit`` and dying is the
# ONLY place a flight-recorder dump can happen.  The hook must never
# raise (it runs on the death path) — failures are swallowed so the
# crash semantics stay exact.
_crash_hook = None


def register_crash_hook(hook) -> None:
    """``hook(site, generation, code)`` runs just before a
    ``crash.exit`` os._exit.  One slot — last registration wins."""
    global _crash_hook
    _crash_hook = hook


def crash_or_stall(generation: int) -> None:
    """Chunk-boundary process faults: ``rank.stall`` sleeps ``delay_s``
    (recorded, so telemetry shows the stall), ``crash.exit`` dies on the
    spot via ``os._exit`` — no flushes, no atexit: the closest
    in-process stand-in for a machine loss, and exactly what the
    supervisor's restart budget exists for.  The registered crash hook
    (black-box dump) is the one forensic exception."""
    spec = fire("rank.stall", generation)
    if spec is not None and spec.delay_s > 0:
        time.sleep(spec.delay_s)
    spec = fire("crash.exit", generation)
    if spec is not None:
        code = spec.value if spec.value >= 0 else 1
        if _crash_hook is not None:
            try:
                _crash_hook("crash.exit", generation, code)
            except Exception:
                pass
        os._exit(code)
