"""The health plane: heartbeats, straggler watchdog, loss/restore verdicts.

The fault plane (:mod:`gol_tpu.resilience.faults`) decides *that*
hardware degrades; this module decides *what the run does about it* —
and hands the serving tier the verdicts it live-reshards on
(docs/RESILIENCE.md, "Live elasticity").  Three signals, all sampled at
chunk boundaries so the compiled programs never see the plane:

- **heartbeats** — every chunk boundary reports its wall time.  The
  watchdog fits a baseline (the median of a sliding window of healthy
  walls) and flags a chunk that exceeds ``straggler_factor`` × baseline
  as a ``straggler`` verdict.  Straggler walls do not join the window,
  so one slow rank cannot drag the baseline up and mask itself.
- **device loss** — armed ``device.loss`` specs fire here; the verdict
  names the device, and a spec with ``restore_after`` schedules the
  matching ``device_restore`` verdict (the shrink→grow→shrink drill).
- **alive set** — the monitor owns which devices are usable; the serve
  scheduler maps that onto the largest worlds mesh the slot count
  divides and reshards live at the next boundary.

Every verdict lands as a schema-v11 ``health`` telemetry event and in
the ``gol_health_*`` metrics (docs/OBSERVABILITY.md).  The plane is
host-side by construction: with no monitor installed nothing runs, and
with one installed the compiled chunk programs are byte-identical (the
trace-identity pin in tests/test_health.py).

PR 19 lifts the same design one level up: :class:`HostMonitor` watches
whole *replicas* instead of devices, fed by the fleet front tier's
``/healthz`` probes (docs/SERVING.md, "The fleet").  Same shape —
missed-beat verdicts instead of device loss, a median-window latency
baseline instead of chunk walls, and flap damping (``restore_beats``
consecutive healthy probes before a dead replica is readmitted) so a
replica oscillating across the miss threshold cannot thrash the
routing epoch.  Verdicts land as schema-v14 ``fleet`` events with
``action="replica"`` and drive the ``gol_fleet_*`` gauges.
"""

from __future__ import annotations

import dataclasses
import statistics
from collections import deque
from typing import Deque, List, Optional

from gol_tpu.resilience import faults as faults_mod

#: Verdict kinds, in the order a boundary can produce them.
KINDS = ("device_loss", "device_restore", "straggler")

#: Host-level (replica) verdict kinds, PR 19's fleet plane.
HOST_KINDS = ("replica_dead", "replica_slow", "replica_restore")


@dataclasses.dataclass(frozen=True)
class Verdict:
    """One health-plane decision, ready to stamp into telemetry."""

    kind: str
    generation: int
    device: int = -1
    rank: int = -1
    wall_s: float = 0.0
    baseline_s: float = 0.0
    alive: int = 0

    def to_event(self) -> dict:
        out = {"verdict": self.kind, "alive": self.alive}
        if self.device >= 0:
            out["device"] = self.device
        if self.kind == "straggler":
            out["rank"] = self.rank
            out["wall_s"] = round(self.wall_s, 6)
            out["baseline_s"] = round(self.baseline_s, 6)
        return out

    def to_span_attrs(self) -> dict:
        """The same payload reshaped for a v12 trace span's ``attrs``
        block (gol_tpu/telemetry/trace.py): the span's ``name`` already
        says what kind of verdict it is, and the chunk span it parents
        to already carries ``wall_s`` — so the key becomes ``kind`` and
        the wall is dropped.  One source of truth with :meth:`to_event`,
        two stream shapes."""
        out = self.to_event()
        out["kind"] = out.pop("verdict")
        out.pop("wall_s", None)
        return out


class HealthMonitor:
    """Chunk-boundary health sampling over ``num_devices`` devices.

    ``events``/``registry`` mirror the serve scheduler's emission pair:
    verdicts go to the v11 stream when a log is attached, else straight
    to the metrics registry — and both stay optional so the monitor
    works bare in unit tests.
    """

    def __init__(
        self,
        num_devices: int,
        window: int = 16,
        straggler_factor: float = 4.0,
        min_samples: int = 3,
        min_wall_s: float = 0.010,
        events=None,
        registry=None,
    ) -> None:
        if num_devices < 1:
            raise ValueError(f"num_devices must be >= 1, got {num_devices}")
        if straggler_factor <= 1.0:
            raise ValueError(
                f"straggler_factor must exceed 1, got {straggler_factor}"
            )
        self.num_devices = num_devices
        self.straggler_factor = straggler_factor
        self.min_samples = min_samples
        # Sub-10ms chunks jitter by whole multiples of themselves on a
        # shared host; the watchdog only trusts walls above this floor.
        self.min_wall_s = min_wall_s
        self._walls: Deque[float] = deque(maxlen=window)
        self._alive = set(range(num_devices))
        self._restores: List[tuple] = []  # (due_generation, device)
        self._events = events
        self._registry = registry

    # -- state ----------------------------------------------------------------

    @property
    def alive(self) -> List[int]:
        return sorted(self._alive)

    def baseline(self) -> Optional[float]:
        """The fitted healthy-wall baseline (None until enough samples)."""
        if len(self._walls) < self.min_samples:
            return None
        return statistics.median(self._walls)

    # -- sampling -------------------------------------------------------------

    def poll(self, generation: int) -> List[Verdict]:
        """Device loss/restore verdicts due at this chunk boundary."""
        verdicts: List[Verdict] = []
        for spec in faults_mod.device_losses(generation):
            if spec.device not in self._alive:
                continue
            if len(self._alive) == 1:
                # The last device cannot be shed — the run would have
                # nothing to reshard onto; the loss surfaces as a crash
                # site's problem, not a live-elasticity one.
                continue
            self._alive.discard(spec.device)
            if spec.restore_after > 0:
                self._restores.append(
                    (generation + spec.restore_after, spec.device)
                )
            verdicts.append(
                Verdict(
                    "device_loss",
                    generation,
                    device=spec.device,
                    alive=len(self._alive),
                )
            )
        due = [r for r in self._restores if r[0] <= generation]
        for r in due:
            self._restores.remove(r)
            self._alive.add(r[1])
            verdicts.append(
                Verdict(
                    "device_restore",
                    generation,
                    device=r[1],
                    alive=len(self._alive),
                )
            )
        self._emit(verdicts)
        return verdicts

    def heartbeat(
        self, generation: int, wall_s: float, rank: int = 0
    ) -> List[Verdict]:
        """Report one chunk wall; returns any straggler verdict.

        An armed ``rank.slowdown`` inflates the reported wall here —
        the injection point for the watchdog drill.
        """
        wall = wall_s + faults_mod.rank_slowdown(generation)
        base = self.baseline()
        verdicts: List[Verdict] = []
        if (
            base is not None
            and wall > self.min_wall_s
            and wall > self.straggler_factor * max(base, 1e-9)
        ):
            verdicts.append(
                Verdict(
                    "straggler",
                    generation,
                    rank=rank,
                    wall_s=wall,
                    baseline_s=base,
                    alive=len(self._alive),
                )
            )
        else:
            self._walls.append(wall)
        self._emit(verdicts)
        return verdicts

    # -- emission -------------------------------------------------------------

    def _emit(self, verdicts: List[Verdict]) -> None:
        for v in verdicts:
            payload = v.to_event()
            if self._events is not None:
                self._events.health_event(generation=v.generation, **payload)
            elif self._registry is not None:
                rec = dict(event="health", generation=v.generation, **payload)
                self._registry.observe(rec)


@dataclasses.dataclass(frozen=True)
class HostVerdict:
    """One host-plane decision about a whole replica (schema v14)."""

    kind: str
    replica: str
    tick: int
    alive: int = 0
    latency_s: float = 0.0
    baseline_s: float = 0.0

    def to_event(self) -> dict:
        out = {
            "verdict": self.kind,
            "replica": self.replica,
            "alive": self.alive,
        }
        if self.kind == "replica_slow":
            out["latency_s"] = round(self.latency_s, 6)
            out["baseline_s"] = round(self.baseline_s, 6)
        return out


class HostMonitor:
    """Replica-level health from periodic ``/healthz`` probe results.

    The fleet front tier (:mod:`gol_tpu.serve.fleet`) calls
    :meth:`beat` once per probe round per replica with the probe's
    outcome; the monitor folds those into verdicts:

    - ``replica_dead`` after ``miss_threshold`` CONSECUTIVE failed
      probes — one dropped packet is noise, a run of them is a dead
      host.  The replica leaves the alive set; the front tier reacts
      by migrating its journaled open intents (the handoff).
    - ``replica_restore`` after ``restore_beats`` consecutive healthy
      probes from a replica currently considered dead — the flap
      damper: a replica oscillating around the miss threshold cannot
      re-enter (and re-bump the routing epoch) until it holds a
      streak.
    - ``replica_slow`` when a healthy probe's latency exceeds
      ``latency_factor`` × the median of the replica's recent healthy
      latencies.  Advisory only — it never changes the alive set
      (a slow host still owns its intents) but it is the early-warning
      line on the operator's dashboard.

    Same emission pair as :class:`HealthMonitor`: v14 ``fleet`` events
    when a log is attached, else straight to the metrics registry, and
    both optional so the monitor works bare in unit tests.
    """

    def __init__(
        self,
        replicas: List[str],
        miss_threshold: int = 3,
        restore_beats: int = 2,
        latency_factor: float = 8.0,
        window: int = 16,
        min_samples: int = 3,
        min_latency_s: float = 0.005,
        events=None,
        registry=None,
    ) -> None:
        if not replicas:
            raise ValueError("HostMonitor needs at least one replica")
        if miss_threshold < 1:
            raise ValueError(
                f"miss_threshold must be >= 1, got {miss_threshold}"
            )
        if restore_beats < 1:
            raise ValueError(
                f"restore_beats must be >= 1, got {restore_beats}"
            )
        if latency_factor <= 1.0:
            raise ValueError(
                f"latency_factor must exceed 1, got {latency_factor}"
            )
        self.replicas = list(replicas)
        self.miss_threshold = miss_threshold
        self.restore_beats = restore_beats
        self.latency_factor = latency_factor
        self.min_samples = min_samples
        # Loopback probes jitter by whole multiples of themselves under
        # scheduler noise; the slow verdict only trusts latencies above
        # this floor (the min_wall_s idea, one level up).
        self.min_latency_s = min_latency_s
        self._alive = set(self.replicas)
        self._misses = {r: 0 for r in self.replicas}
        self._oks = {r: 0 for r in self.replicas}
        self._latencies: dict = {
            r: deque(maxlen=window) for r in self.replicas
        }
        self._events = events
        self._registry = registry

    # -- state ----------------------------------------------------------------

    @property
    def alive(self) -> List[str]:
        return sorted(self._alive)

    def is_alive(self, replica: str) -> bool:
        return replica in self._alive

    def baseline(self, replica: str) -> Optional[float]:
        lats = self._latencies[replica]
        if len(lats) < self.min_samples:
            return None
        return statistics.median(lats)

    # -- sampling -------------------------------------------------------------

    def beat(
        self, replica: str, ok: bool, latency_s: float = 0.0, tick: int = 0
    ) -> List[HostVerdict]:
        """Fold one probe result; returns any verdicts it produced."""
        if replica not in self._misses:
            raise KeyError(f"unknown replica {replica!r}")
        verdicts: List[HostVerdict] = []
        if not ok:
            self._oks[replica] = 0
            self._misses[replica] += 1
            if (
                replica in self._alive
                and self._misses[replica] >= self.miss_threshold
            ):
                self._alive.discard(replica)
                verdicts.append(
                    HostVerdict(
                        "replica_dead", replica, tick,
                        alive=len(self._alive),
                    )
                )
        else:
            self._misses[replica] = 0
            self._oks[replica] += 1
            if (
                replica not in self._alive
                and self._oks[replica] >= self.restore_beats
            ):
                self._alive.add(replica)
                # A restored replica's latency history is stale (it
                # just rebooted); start the baseline fresh.
                self._latencies[replica].clear()
                verdicts.append(
                    HostVerdict(
                        "replica_restore", replica, tick,
                        alive=len(self._alive),
                    )
                )
            base = self.baseline(replica)
            if (
                replica in self._alive
                and base is not None
                and latency_s > self.min_latency_s
                and latency_s > self.latency_factor * max(base, 1e-9)
            ):
                verdicts.append(
                    HostVerdict(
                        "replica_slow", replica, tick,
                        alive=len(self._alive),
                        latency_s=latency_s,
                        baseline_s=base,
                    )
                )
            else:
                # Slow probes stay out of the window so a degrading
                # host cannot drag its own baseline up and mask itself.
                self._latencies[replica].append(latency_s)
        self._emit(verdicts)
        return verdicts

    # -- emission -------------------------------------------------------------

    def _emit(self, verdicts: List[HostVerdict]) -> None:
        for v in verdicts:
            payload = v.to_event()
            if self._events is not None:
                self._events.fleet_event(
                    "replica", tick=v.tick, **payload
                )
            elif self._registry is not None:
                rec = dict(event="fleet", action="replica",
                           tick=v.tick, **payload)
                self._registry.observe(rec)
