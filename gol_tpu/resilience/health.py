"""The health plane: heartbeats, straggler watchdog, loss/restore verdicts.

The fault plane (:mod:`gol_tpu.resilience.faults`) decides *that*
hardware degrades; this module decides *what the run does about it* —
and hands the serving tier the verdicts it live-reshards on
(docs/RESILIENCE.md, "Live elasticity").  Three signals, all sampled at
chunk boundaries so the compiled programs never see the plane:

- **heartbeats** — every chunk boundary reports its wall time.  The
  watchdog fits a baseline (the median of a sliding window of healthy
  walls) and flags a chunk that exceeds ``straggler_factor`` × baseline
  as a ``straggler`` verdict.  Straggler walls do not join the window,
  so one slow rank cannot drag the baseline up and mask itself.
- **device loss** — armed ``device.loss`` specs fire here; the verdict
  names the device, and a spec with ``restore_after`` schedules the
  matching ``device_restore`` verdict (the shrink→grow→shrink drill).
- **alive set** — the monitor owns which devices are usable; the serve
  scheduler maps that onto the largest worlds mesh the slot count
  divides and reshards live at the next boundary.

Every verdict lands as a schema-v11 ``health`` telemetry event and in
the ``gol_health_*`` metrics (docs/OBSERVABILITY.md).  The plane is
host-side by construction: with no monitor installed nothing runs, and
with one installed the compiled chunk programs are byte-identical (the
trace-identity pin in tests/test_health.py).
"""

from __future__ import annotations

import dataclasses
import statistics
from collections import deque
from typing import Deque, List, Optional

from gol_tpu.resilience import faults as faults_mod

#: Verdict kinds, in the order a boundary can produce them.
KINDS = ("device_loss", "device_restore", "straggler")


@dataclasses.dataclass(frozen=True)
class Verdict:
    """One health-plane decision, ready to stamp into telemetry."""

    kind: str
    generation: int
    device: int = -1
    rank: int = -1
    wall_s: float = 0.0
    baseline_s: float = 0.0
    alive: int = 0

    def to_event(self) -> dict:
        out = {"verdict": self.kind, "alive": self.alive}
        if self.device >= 0:
            out["device"] = self.device
        if self.kind == "straggler":
            out["rank"] = self.rank
            out["wall_s"] = round(self.wall_s, 6)
            out["baseline_s"] = round(self.baseline_s, 6)
        return out

    def to_span_attrs(self) -> dict:
        """The same payload reshaped for a v12 trace span's ``attrs``
        block (gol_tpu/telemetry/trace.py): the span's ``name`` already
        says what kind of verdict it is, and the chunk span it parents
        to already carries ``wall_s`` — so the key becomes ``kind`` and
        the wall is dropped.  One source of truth with :meth:`to_event`,
        two stream shapes."""
        out = self.to_event()
        out["kind"] = out.pop("verdict")
        out.pop("wall_s", None)
        return out


class HealthMonitor:
    """Chunk-boundary health sampling over ``num_devices`` devices.

    ``events``/``registry`` mirror the serve scheduler's emission pair:
    verdicts go to the v11 stream when a log is attached, else straight
    to the metrics registry — and both stay optional so the monitor
    works bare in unit tests.
    """

    def __init__(
        self,
        num_devices: int,
        window: int = 16,
        straggler_factor: float = 4.0,
        min_samples: int = 3,
        min_wall_s: float = 0.010,
        events=None,
        registry=None,
    ) -> None:
        if num_devices < 1:
            raise ValueError(f"num_devices must be >= 1, got {num_devices}")
        if straggler_factor <= 1.0:
            raise ValueError(
                f"straggler_factor must exceed 1, got {straggler_factor}"
            )
        self.num_devices = num_devices
        self.straggler_factor = straggler_factor
        self.min_samples = min_samples
        # Sub-10ms chunks jitter by whole multiples of themselves on a
        # shared host; the watchdog only trusts walls above this floor.
        self.min_wall_s = min_wall_s
        self._walls: Deque[float] = deque(maxlen=window)
        self._alive = set(range(num_devices))
        self._restores: List[tuple] = []  # (due_generation, device)
        self._events = events
        self._registry = registry

    # -- state ----------------------------------------------------------------

    @property
    def alive(self) -> List[int]:
        return sorted(self._alive)

    def baseline(self) -> Optional[float]:
        """The fitted healthy-wall baseline (None until enough samples)."""
        if len(self._walls) < self.min_samples:
            return None
        return statistics.median(self._walls)

    # -- sampling -------------------------------------------------------------

    def poll(self, generation: int) -> List[Verdict]:
        """Device loss/restore verdicts due at this chunk boundary."""
        verdicts: List[Verdict] = []
        for spec in faults_mod.device_losses(generation):
            if spec.device not in self._alive:
                continue
            if len(self._alive) == 1:
                # The last device cannot be shed — the run would have
                # nothing to reshard onto; the loss surfaces as a crash
                # site's problem, not a live-elasticity one.
                continue
            self._alive.discard(spec.device)
            if spec.restore_after > 0:
                self._restores.append(
                    (generation + spec.restore_after, spec.device)
                )
            verdicts.append(
                Verdict(
                    "device_loss",
                    generation,
                    device=spec.device,
                    alive=len(self._alive),
                )
            )
        due = [r for r in self._restores if r[0] <= generation]
        for r in due:
            self._restores.remove(r)
            self._alive.add(r[1])
            verdicts.append(
                Verdict(
                    "device_restore",
                    generation,
                    device=r[1],
                    alive=len(self._alive),
                )
            )
        self._emit(verdicts)
        return verdicts

    def heartbeat(
        self, generation: int, wall_s: float, rank: int = 0
    ) -> List[Verdict]:
        """Report one chunk wall; returns any straggler verdict.

        An armed ``rank.slowdown`` inflates the reported wall here —
        the injection point for the watchdog drill.
        """
        wall = wall_s + faults_mod.rank_slowdown(generation)
        base = self.baseline()
        verdicts: List[Verdict] = []
        if (
            base is not None
            and wall > self.min_wall_s
            and wall > self.straggler_factor * max(base, 1e-9)
        ):
            verdicts.append(
                Verdict(
                    "straggler",
                    generation,
                    rank=rank,
                    wall_s=wall,
                    baseline_s=base,
                    alive=len(self._alive),
                )
            )
        else:
            self._walls.append(wall)
        self._emit(verdicts)
        return verdicts

    # -- emission -------------------------------------------------------------

    def _emit(self, verdicts: List[Verdict]) -> None:
        for v in verdicts:
            payload = v.to_event()
            if self._events is not None:
                self._events.health_event(generation=v.generation, **payload)
            elif self._registry is not None:
                rec = dict(event="health", generation=v.generation, **payload)
                self._registry.observe(rec)
