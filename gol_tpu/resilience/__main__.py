"""``python -m gol_tpu.resilience supervise [opts] -- <command ...>``.

The process-tier entry point (docs/RESILIENCE.md).  Example:

    python -m gol_tpu.resilience supervise \\
        --max-restarts 5 --manifest runs/a/job.manifest.json \\
        --checkpoint-dir ck -- \\
        python -m gol_tpu 4 4096 10000 512 1 \\
            --checkpoint-every 200 --checkpoint-dir ck --auto-resume \\
            --telemetry runs/a --run-id a
"""

from __future__ import annotations

import argparse
import sys
from typing import Optional, Sequence

from gol_tpu.resilience import supervisor as sup_mod


def main(argv: Optional[Sequence[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="gol_tpu.resilience",
        description="Supervise a gol run: restart on crash/preemption "
        "from the latest valid checkpoint (docs/RESILIENCE.md)",
    )
    sub = p.add_subparsers(dest="command", required=True)
    ps = sub.add_parser(
        "supervise", help="run a child command under the restart budget"
    )
    ps.add_argument("--max-restarts", type=int, default=10, metavar="N")
    ps.add_argument(
        "--backoff-base", type=float, default=1.0, metavar="SECONDS"
    )
    ps.add_argument(
        "--backoff-max", type=float, default=60.0, metavar="SECONDS"
    )
    ps.add_argument(
        "--backoff-seed", type=int, default=None, metavar="I",
        help="deterministic jitter (drills/tests)",
    )
    ps.add_argument("--manifest", default=None, metavar="PATH")
    ps.add_argument(
        "--checkpoint-dir", default=None, metavar="DIR",
        help="record the resume generation per attempt in the manifest",
    )
    ps.add_argument("--kind", choices=["2d", "3d"], default="2d")
    ps.add_argument("--run-id", default=None, metavar="NAME")
    ps.add_argument(
        "child", nargs=argparse.REMAINDER,
        metavar="-- COMMAND ...",
    )
    ns = p.parse_args(list(sys.argv[1:] if argv is None else argv))
    child = list(ns.child)
    if child and child[0] == "--":
        child = child[1:]
    if not child:
        p.error("supervise needs a child command after '--'")
    try:
        return sup_mod.supervise(
            child,
            max_restarts=ns.max_restarts,
            backoff_base=ns.backoff_base,
            backoff_max=ns.backoff_max,
            manifest_path=ns.manifest,
            checkpoint_dir=ns.checkpoint_dir,
            kind=ns.kind,
            run_id=ns.run_id,
            backoff_seed=ns.backoff_seed,
        )
    except (ValueError, OSError) as e:
        print(f"supervisor: {e}", file=sys.stderr)
        return 255


if __name__ == "__main__":
    sys.exit(main())
