"""``python -m gol_tpu.resilience <supervise|chaos> ...``.

The process-tier entry points (docs/RESILIENCE.md).  Examples:

    python -m gol_tpu.resilience supervise \\
        --max-restarts 5 --manifest runs/a/job.manifest.json \\
        --checkpoint-dir ck -- \\
        python -m gol_tpu 4 4096 10000 512 1 \\
            --checkpoint-every 200 --checkpoint-dir ck --auto-resume \\
            --telemetry runs/a --run-id a

    python -m gol_tpu.resilience chaos \\
        --plan tests/data/fault_plans/chaos_matrix.json
"""

from __future__ import annotations

import argparse
import sys
from typing import Optional, Sequence


def main(argv: Optional[Sequence[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if argv and argv[0] == "chaos":
        # The chaos matrix owns its own argv (and must set XLA device
        # flags before the first backend touch).
        from gol_tpu.resilience import chaos as chaos_mod

        return chaos_mod.main(argv[1:])

    from gol_tpu.resilience import supervisor as sup_mod

    p = argparse.ArgumentParser(
        prog="gol_tpu.resilience",
        description="Supervise a gol run: restart on crash/preemption "
        "from the latest valid checkpoint (docs/RESILIENCE.md)",
    )
    sub = p.add_subparsers(dest="command", required=True)
    ps = sub.add_parser(
        "supervise", help="run a child command under the restart budget"
    )
    ps.add_argument("--max-restarts", type=int, default=10, metavar="N")
    ps.add_argument(
        "--backoff-base", type=float, default=1.0, metavar="SECONDS"
    )
    ps.add_argument(
        "--backoff-max", type=float, default=60.0, metavar="SECONDS"
    )
    ps.add_argument(
        "--backoff-seed", type=int, default=None, metavar="I",
        help="deterministic jitter (drills/tests)",
    )
    ps.add_argument("--manifest", default=None, metavar="PATH")
    ps.add_argument(
        "--checkpoint-dir", default=None, metavar="DIR",
        help="record the resume generation per attempt in the manifest",
    )
    ps.add_argument("--kind", choices=["2d", "3d"], default="2d")
    ps.add_argument("--run-id", default=None, metavar="NAME")
    ps.add_argument(
        "child", nargs=argparse.REMAINDER,
        metavar="-- COMMAND ...",
    )
    ns = p.parse_args(argv)
    child = list(ns.child)
    if child and child[0] == "--":
        child = child[1:]
    if not child:
        p.error("supervise needs a child command after '--'")
    try:
        return sup_mod.supervise(
            child,
            max_restarts=ns.max_restarts,
            backoff_base=ns.backoff_base,
            backoff_max=ns.backoff_max,
            manifest_path=ns.manifest,
            checkpoint_dir=ns.checkpoint_dir,
            kind=ns.kind,
            run_id=ns.run_id,
            backoff_seed=ns.backoff_seed,
        )
    except (ValueError, OSError) as e:
        print(f"supervisor: {e}", file=sys.stderr)
        return 255


if __name__ == "__main__":
    sys.exit(main())
