"""Host-side packed board: the numpy twin of ``ops/bitlife``.

The OOC tier keeps the whole board in host RAM in exactly the
``ops/bitlife.py`` wire layout — uint32 words, bit ``j`` of word ``k``
on a row is column ``32*k + j`` — so a band sliced out of the host
array IS a valid input to ``bitlife.step_packed_vext`` with no
translation, and a checkpoint written from the host board is
bit-identical to one written by the in-core bitpack tier.

``bitlife.pack``/``unpack`` are jnp functions: calling them on a
128 GiB board would materialize it on device, which is the one thing
this tier exists to avoid.  ``pack_np``/``unpack_np`` below are the
pure-numpy equivalents, pinned bit-identical to the jnp pair in
tests/test_ooc.py.  Both go through ``np.packbits``/``unpackbits``
with ``bitorder="little"`` and an explicit little-endian uint32 view,
which matches the byte-staged combine in ``bitlife.pack`` (byte b of a
word holds columns ``8*b .. 8*b+7``).

:class:`BufferPool` is the staging pool: reusable page-aligned-ish host
buffers for extended-band assembly so the steady-state sweep allocates
nothing per band.  (jax on CPU/TPU McJIT does not expose true pinned
allocations through the public API; the pool gives the allocation-reuse
half of "pinned buffers", and ``jax.device_put`` does the rest.)
"""

from __future__ import annotations

import numpy as np

from gol_tpu.ops import bitlife

WORD_BYTES = 4


def packed_words(width: int) -> int:
    """Words per packed row; width must be a multiple of 32 (bitlife)."""
    return bitlife.packed_width(width)


def pack_np(board: np.ndarray) -> np.ndarray:
    """Dense uint8 [h, w] (0/1) -> packed uint32 [h, w//32], host-side.

    Bit-identical to ``np.asarray(bitlife.pack(board))``.
    """
    board = np.ascontiguousarray(board, dtype=np.uint8)
    h, w = board.shape
    nw = packed_words(w)
    by = np.packbits(board, axis=-1, bitorder="little")  # [h, 4*nw]
    return np.ascontiguousarray(by).view("<u4").reshape(h, nw)


def unpack_np(packed: np.ndarray, width: int) -> np.ndarray:
    """Packed uint32 [h, w//32] -> dense uint8 [h, w], host-side."""
    packed = np.ascontiguousarray(packed, dtype=np.uint32)
    h, nw = packed.shape
    if nw != packed_words(width):
        raise ValueError(
            f"packed row has {nw} words, width {width} needs"
            f" {packed_words(width)}"
        )
    by = packed.astype("<u4").view(np.uint8).reshape(h, 4 * nw)
    return np.unpackbits(by, axis=-1, bitorder="little")[:, :width]


def popcount_np(words: np.ndarray) -> int:
    """Total set bits in a packed array, pure numpy (byte LUT)."""
    from gol_tpu.ops import stats

    return stats.popcount_words_np(words)


class BufferPool:
    """Reusable host staging buffers, keyed by (shape, dtype).

    The sweep assembles one extended band per visit (band + 2k ghost
    rows); without reuse that is a fresh multi-MB allocation per band
    per sweep.  ``take`` hands back the previously-returned buffer for
    the same shape when free, so steady state runs allocation-free.
    Buffers handed to ``jax.device_put`` are considered busy until
    ``give``n back (after the transfer is known complete).
    """

    def __init__(self) -> None:
        self._free: dict[tuple, list[np.ndarray]] = {}
        self.allocated = 0  # lifetime allocations, for tests/telemetry
        self.reused = 0

    def take(self, shape: tuple, dtype=np.uint32) -> np.ndarray:
        key = (tuple(shape), np.dtype(dtype).str)
        stack = self._free.get(key)
        if stack:
            self.reused += 1
            return stack.pop()
        self.allocated += 1
        return np.empty(shape, dtype=dtype)

    def give(self, buf: np.ndarray) -> None:
        key = (tuple(buf.shape), buf.dtype.str)
        self._free.setdefault(key, []).append(buf)
