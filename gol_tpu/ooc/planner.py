"""Band planner: split board rows into streaming bands under a budget.

The device never holds more than a fixed number of row-bands at once.
A visit to a band of ``bh`` rows at depth ``k`` moves an extended
input of ``bh + 2k`` rows up and ``bh`` rows back; with the three-deep
rotation (next band's input staged while the current computes and the
previous drains) the device-resident footprint is bounded by three
in-flight (input, output) pairs:

    footprint(bh) <= 3 * ((bh + 2k) + bh) * nw * 4 bytes
                   = (6*bh + 6*k) * row_bytes

Given ``budget_bytes`` the planner inverts that bound for the band
height; an explicit ``band_rows`` overrides the derivation but is
still validated against the budget.  The last band absorbs the
remainder (height in ``[B, 2B)``), so every row belongs to exactly one
band and no band is shorter than ``B`` — which keeps the dead-band
skip rule sound (ghost depth ``k <= B`` never spans past an immediate
neighbor band).
"""

from __future__ import annotations

import dataclasses

from gol_tpu.ooc import hostboard

# In-flight (ext input + output) pairs the rotation keeps live at once.
ROTATION_DEPTH = 3


def _footprint_bytes(band_rows: int, depth: int, row_bytes: int) -> int:
    return ROTATION_DEPTH * (2 * band_rows + 2 * depth) * row_bytes


@dataclasses.dataclass(frozen=True)
class BandPlan:
    """Immutable row-band decomposition of an ``height x width`` board."""

    height: int
    width: int
    depth: int          # generations per band visit (k)
    band_rows: int      # nominal band height B; last band is in [B, 2B)
    budget_bytes: int   # 0 = unbounded (no footprint check)
    bands: tuple[tuple[int, int], ...]  # (row_start, row_end) per band

    @property
    def num_bands(self) -> int:
        return len(self.bands)

    @property
    def words(self) -> int:
        return hostboard.packed_words(self.width)

    @property
    def row_bytes(self) -> int:
        return self.words * hostboard.WORD_BYTES

    @property
    def board_bytes(self) -> int:
        """Host-resident packed board size."""
        return self.height * self.row_bytes

    def device_bytes(self) -> int:
        """Worst-case device footprint under the rotation bound."""
        tallest = max(r1 - r0 for r0, r1 in self.bands)
        return _footprint_bytes(tallest, self.depth, self.row_bytes)

    def band_heights(self) -> tuple[int, ...]:
        return tuple(r1 - r0 for r0, r1 in self.bands)


def plan_bands(
    height: int,
    width: int,
    depth: int,
    *,
    band_rows: int = 0,
    budget_bytes: int = 0,
) -> BandPlan:
    """Build a :class:`BandPlan`; raises ValueError on impossible asks."""
    if depth < 1:
        raise ValueError(f"ooc depth must be >= 1, got {depth}")
    if height < 2 * depth + 1:
        # parallel/halo's split/ext machinery needs strictly more rows
        # than the two ghost shells it carries.
        raise ValueError(
            f"board height {height} too small for ooc depth {depth}"
            f" (need > {2 * depth} rows)"
        )
    row_bytes = hostboard.packed_words(width) * hostboard.WORD_BYTES
    if band_rows:
        if band_rows < depth:
            raise ValueError(
                f"ooc band height {band_rows} < depth {depth}: a band"
                " visit's ghost shell may not span past its immediate"
                " neighbor band (raise --ooc-band-rows or lower"
                " --halo-depth)"
            )
    else:
        if not budget_bytes:
            raise ValueError(
                "ooc needs a device budget (--ooc-budget-mb) or an"
                " explicit band height (--ooc-band-rows)"
            )
        # Invert footprint(bh) <= budget for bh; remainder absorption
        # can make the last band up to 2B-1 rows, so size B such that
        # even the absorbed band fits: footprint(2B) <= budget.
        rows = budget_bytes // (ROTATION_DEPTH * row_bytes)
        band_rows = max(depth, (rows - 2 * depth) // 4)
    band_rows = min(band_rows, height)
    num = max(1, height // band_rows)
    bands = tuple(
        (i * band_rows, (i + 1) * band_rows if i < num - 1 else height)
        for i in range(num)
    )
    plan = BandPlan(
        height=height,
        width=width,
        depth=depth,
        band_rows=band_rows,
        budget_bytes=budget_bytes,
        bands=bands,
    )
    if num > 1 and min(plan.band_heights()) < depth:
        raise ValueError(
            f"ooc band height {min(plan.band_heights())} < depth"
            f" {depth}; the planner should never produce this"
        )
    if budget_bytes and plan.device_bytes() > budget_bytes:
        raise ValueError(
            f"ooc footprint {plan.device_bytes()} B exceeds device"
            f" budget {budget_bytes} B even at band height"
            f" {plan.band_rows}; raise --ooc-budget-mb or lower"
            " --halo-depth"
        )
    return plan
