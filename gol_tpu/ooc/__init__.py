"""Out-of-core streaming tier: boards bigger than HBM (docs/STREAMING.md).

Every other engine tier requires the (bit-packed) board resident on
device, capping world size at HBM.  This tier keeps the packed board
(1 bit/cell, the :mod:`gol_tpu.ops.bitlife` layout) in host RAM and
streams horizontal row-bands through the device in a three-deep
rotation — band N+1's H2D copy and band N-1's D2H fetch overlap band
N's compute, the same carried-buffer discipline as the pipelined halo
(PR 9) with host<->device transfers taking the role of the ring
ppermutes.  Each band visit steps k generations from a 2k-row ghost
shell of its neighbors' pre-sweep state, via the depth-k
interior/boundary machinery of :mod:`gol_tpu.parallel.halo`
(``split_chunk``/``_consume_chunk`` reused, so exactness falls out of
the existing slab proof); dead bands (band and both neighbors all-zero)
are neither fetched nor stepped.

- :mod:`gol_tpu.ooc.hostboard` — host-side packed layout (numpy twin of
  ``bitlife.pack``/``unpack``) and the staging-buffer pool.
- :mod:`gol_tpu.ooc.planner` — :class:`BandPlan`: board rows into bands
  under a device-memory budget.
- :mod:`gol_tpu.ooc.scheduler` — :class:`OocScheduler`: the streaming
  sweep loop, overlap accounting, dead-band skipping, per-band stats
  partials, and the ``hostcopy.error``-contained write-back.
"""

from gol_tpu.ooc.hostboard import BufferPool, pack_np, unpack_np  # noqa: F401
from gol_tpu.ooc.planner import BandPlan, plan_bands  # noqa: F401
from gol_tpu.ooc.scheduler import OocScheduler  # noqa: F401
