"""The streaming sweep loop: bands through a fixed device footprint.

One sweep advances the whole board ``kk`` generations by visiting each
band once.  A visit assembles the extended band (band + ``kk`` ghost
rows per side) into a pooled host buffer, ships it with
``jax.device_put``, and steps it with the depth-``kk``
interior/boundary machinery of :func:`gol_tpu.parallel.halo.split_chunk`
(via ``_consume_chunk`` — the exact program the mesh tiers run per
shard, with a size-1 phase ring, so exactness falls out of the existing
slab proof).  The compiled visit donates its input, so the device never
holds more than the rotation's in-flight buffers.

**Ghost staleness.** Every ghost row must carry the neighbor's
*pre-sweep* state.  The rotation guarantees it by ordering, not by
copying: band N's write-back is deferred until after band N+1's
extended input has been assembled (the one-visit-delayed drain), the
first-visited band's far seam is saved in a ``kk``-row wrap buffer
before the sweep starts, and every other ghost read targets a band the
sweep has not reached yet.  Because no band is shorter than the plan's
depth, a ghost shell never spans past the immediate neighbor band.
Sweep direction alternates per sweep so the deferred-drain reuse
distance does not systematically favor one seam.

**Three-deep rotation.** In steady state three visits are in flight:
band N+1's H2D put and band N-1's D2H fetch + write-back bracket band
N's dispatched compute, so with jax's async dispatch the transfers run
while the device steps band N.  ``overlap_fraction`` is the measured
fraction of host-side transfer wall that elapsed while a compute was
known to be in flight — an honest lower bound on hiding, not a model.

**Dead bands.** With skipping enabled, a band is skipped when it and
both torus neighbors held no live cells at sweep start (one-band light
cone: at depth ``kk`` <= band height, liveness cannot cross a dead
band in one visit).  Skipped bands move zero bytes in either direction,
so a sparse pattern's transfer cost scales with its active bands, not
the board area.  Zero flags update from each write-back and are
snapshotted per sweep (post-visit emptiness of a neighbor says nothing
about its pre-sweep seam).
"""

from __future__ import annotations

import time
import warnings
from typing import Callable, Optional

import jax
import numpy as np

from gol_tpu.ooc import hostboard
from gol_tpu.ooc.planner import BandPlan
from gol_tpu.ops import bitlife
from gol_tpu.parallel import halo
from gol_tpu.resilience import degrade as degrade_mod
from gol_tpu.resilience import faults as faults_mod

# Row axis only; the size-1 ring is never exercised — the reused halo
# split paths (_consume_chunk/split_chunk/assemble_ext) contain no
# collectives, only slicing and stepping.
_PHASES = ((0, "rows", 1),)

_COUNTER_KEYS = (
    "sweeps",
    "visits",
    "skipped",
    "bytes_h2d",
    "bytes_d2h",
    "h2d_s",
    "d2h_s",
    "hidden_s",
)


def _zero_counters() -> dict:
    return {k: 0 if not k.endswith("_s") else 0.0 for k in _COUNTER_KEYS}


class OocScheduler:
    """Drives a :class:`~gol_tpu.ooc.planner.BandPlan` over a host board.

    The board lives in ``self.board`` as a packed uint32 array in the
    ``ops/bitlife`` layout, mutated in place; nothing here materializes
    the full board on device.  ``on_compile(info)`` (if given) is called
    once per distinct compiled visit program — the runtime binds it to
    telemetry ``compile`` events.
    """

    def __init__(
        self,
        plan: BandPlan,
        *,
        skip_dead: bool = True,
        on_compile: Optional[Callable[[dict], None]] = None,
    ) -> None:
        self.plan = plan
        self.skip_dead = skip_dead
        self.on_compile = on_compile
        self.board: Optional[np.ndarray] = None
        self.pool = hostboard.BufferPool()
        self._zero: Optional[np.ndarray] = None
        self._compiled: dict = {}
        self._sweep_parity = 0

    # -- board residency -----------------------------------------------------

    def load_board(self, packed: np.ndarray) -> None:
        """Adopt a packed host board (copied to own, mutable storage)."""
        plan = self.plan
        if packed.shape != (plan.height, plan.words):
            raise ValueError(
                f"packed board shape {packed.shape} does not match plan"
                f" ({plan.height}, {plan.words})"
            )
        self.board = np.ascontiguousarray(packed, dtype=np.uint32).copy()
        self._zero = np.array(
            [not self.board[r0:r1].any() for r0, r1 in plan.bands],
            dtype=bool,
        )

    def load_dense(self, board: np.ndarray) -> None:
        self.load_board(hostboard.pack_np(board))

    def dense(self) -> np.ndarray:
        """Unpack the host board (host-side; for checkpoints and dumps)."""
        return hostboard.unpack_np(self.board, self.plan.width)

    def population(self) -> int:
        return hostboard.popcount_np(self.board)

    # -- compiled visit programs ---------------------------------------------

    def visit_callable(self, bh: int, kk: int):
        """The pure function a ``(bh, kk)`` visit program compiles:
        ``ext[bh + 2*kk, words] -> stepped band [bh, words]``.  Exposed
        so the analysis suite (ooccheck) traces the EXACT program the
        sweep dispatches, not a reconstruction of it."""

        def visit(ext):
            block = ext[kk:kk + bh]
            bands = ((ext[:kk], ext[-kk:]),)
            return halo._consume_chunk(
                bitlife.step_packed_vext, _PHASES, block, bands, kk
            )

        return visit

    def _program(self, bh: int, kk: int):
        """AOT-compiled visit for a band of ``bh`` rows at depth ``kk``.

        At most a handful of shapes exist per run: the nominal band
        height plus the remainder-absorbing last band, times full-depth
        and remainder-sweep ``kk`` — each compiled once, donating its
        extended input.
        """
        key = (bh, kk)
        prog = self._compiled.get(key)
        if prog is not None:
            return prog
        nw = self.plan.words
        visit = self.visit_callable(bh, kk)
        spec = jax.ShapeDtypeStruct((bh + 2 * kk, nw), bitlife.WORD)
        t0 = time.perf_counter()
        with warnings.catch_warnings():
            # The CPU backend declines the donation (no aliasing there);
            # on TPU the extended input is donated as intended.
            warnings.filterwarnings(
                "ignore", message="Some donated buffers were not usable"
            )
            lowered = jax.jit(visit, donate_argnums=0).lower(spec)
            t1 = time.perf_counter()
            prog = lowered.compile()
        t2 = time.perf_counter()
        self._compiled[key] = prog
        if self.on_compile is not None:
            self.on_compile(
                dict(
                    band_rows=bh,
                    depth=kk,
                    lower_s=t1 - t0,
                    compile_s=t2 - t1,
                    executable=prog,
                )
            )
        return prog

    # -- the sweep -----------------------------------------------------------

    def _skippable(self, idx: int, zmask: Optional[np.ndarray]) -> bool:
        if zmask is None:
            return False
        nb = self.plan.num_bands
        return bool(
            zmask[idx] and zmask[(idx - 1) % nb] and zmask[(idx + 1) % nb]
        )

    def _build_ext(self, idx: int, kk: int, down: bool, wrap: np.ndarray):
        """Assemble band ``idx``'s extended input from pre-sweep rows."""
        plan = self.plan
        board = self.board
        r0, r1 = plan.bands[idx]
        bh = r1 - r0
        nb, H = plan.num_bands, plan.height
        ext = self.pool.take((bh + 2 * kk, plan.words))
        # Top ghost: rows [r0-kk, r0) mod H.  Overwritten-by-now only
        # for the upward sweep's last visit (band 0) — the wrap buffer.
        if idx == 0:
            ext[:kk] = wrap if not down else board[H - kk:]
        else:
            ext[:kk] = board[r0 - kk:r0]
        ext[kk:kk + bh] = board[r0:r1]
        # Bottom ghost: rows [r1, r1+kk) mod H — wrap for the downward
        # sweep's last visit, a not-yet-drained band otherwise.
        if idx == nb - 1:
            ext[bh + kk:] = wrap if down else board[:kk]
        else:
            ext[bh + kk:] = board[r1:r1 + kk]
        return ext

    def _drain(self, pending, c: dict, generation: int, hidden: bool):
        """Fetch a visit's output and write it back to the host board."""
        idx, out_dev, ext_buf = pending
        t0 = time.perf_counter()
        out_np = np.asarray(out_dev)  # blocks on the compute, then copies
        d2h = time.perf_counter() - t0
        c["d2h_s"] += d2h
        c["bytes_d2h"] += out_np.nbytes
        if hidden:
            c["hidden_s"] += d2h
        r0, r1 = self.plan.bands[idx]

        def write():
            faults_mod.hostcopy_fault(generation)
            self.board[r0:r1] = out_np

        # Same containment as snapshot writes — but a host-board copy
        # that stays failed is state loss, so a shed verdict (False)
        # must surface instead of silently dropping the band.
        if not degrade_mod.write_with_retry(
            write, what="hostcopy", generation=generation
        ):
            raise OSError(
                f"ooc band {idx} write-back failed permanently at"
                f" generation {generation}"
            )
        if self._zero is not None:
            self._zero[idx] = not out_np.any()
        self.pool.give(ext_buf)

    def _sweep(self, kk: int, c: dict, generation: int) -> None:
        """Advance the whole board ``kk`` generations (one band pass)."""
        plan = self.plan
        board = self.board
        nb, H = plan.num_bands, plan.height
        down = self._sweep_parity % 2 == 0
        self._sweep_parity += 1
        c["sweeps"] += 1
        order = range(nb) if down else range(nb - 1, -1, -1)
        zmask = self._zero.copy() if self.skip_dead else None
        # The first-visited band's far seam, read by the last visit
        # after the first's write-back has already landed.
        wrap = (board[:kk] if down else board[H - kk:]).copy()
        pending = None  # (band idx, device output, host ext buffer)
        for idx in order:
            if self._skippable(idx, zmask):
                c["skipped"] += 1
                continue
            ext = self._build_ext(idx, kk, down, wrap)
            t0 = time.perf_counter()
            ext_dev = jax.device_put(ext)
            put_s = time.perf_counter() - t0
            c["h2d_s"] += put_s
            c["bytes_h2d"] += ext.nbytes
            if pending is not None:
                c["hidden_s"] += put_s  # a compute was in flight
            out_dev = self._program(ext.shape[0] - 2 * kk, kk)(ext_dev)
            c["visits"] += 1
            if pending is not None:
                # Drain N-1 only now — after band N's input was built
                # from pre-sweep rows and its compute dispatched.
                self._drain(pending, c, generation, hidden=True)
            pending = (idx, out_dev, ext)
        if pending is not None:
            self._drain(pending, c, generation, hidden=False)

    # -- the chunk -----------------------------------------------------------

    def run_chunk(self, take: int, generation: int) -> dict:
        """Advance ``take`` generations from ``generation``; returns the
        chunk's streaming report (the telemetry v15 ``ooc`` block plus
        timing internals)."""
        if self.board is None:
            raise RuntimeError("ooc scheduler has no board loaded")
        k = self.plan.depth
        c = _zero_counters()
        done = 0
        while done < take:
            kk = min(k, take - done)
            self._sweep(kk, c, generation + done)
            done += kk
        transfer_s = c["h2d_s"] + c["d2h_s"]
        return dict(
            bands=self.plan.num_bands,
            visits=c["visits"],
            skipped_bands=c["skipped"],
            bytes_h2d=c["bytes_h2d"],
            bytes_d2h=c["bytes_d2h"],
            overlap_fraction=(
                c["hidden_s"] / transfer_s if transfer_s > 0 else 0.0
            ),
            sweeps=c["sweeps"],
            h2d_s=c["h2d_s"],
            d2h_s=c["d2h_s"],
            hidden_s=c["hidden_s"],
        )
