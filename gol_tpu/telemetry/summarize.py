"""Merge, render, and compare telemetry runs (the read side).

``summarize <dir>`` merges every ``<run_id>.rank<k>.jsonl`` file, validates
each record against the schema, prints a per-phase table and a per-chunk
table (with the roofline-utilization column), and flags anomalies:

- **chunk-time outliers** — a chunk wall time > 2× the median of its
  chunk-size class (same ``take``; the tail chunk is legitimately shorter,
  so classes never mix sizes);
- **utilization cliffs** — a chunk's roofline fraction < half the run's
  best;
- **audit divergence** — the same generation fingerprinted differently by
  different ranks (replicated audit scalars MUST agree everywhere; a
  divergence means a rank computed a different world — the exact
  multi-host SDC signature the guard exists for);
- **chunk/total drift** — per-chunk wall times not summing to the
  summary's total phase within 5%.

Schema-v2 ``stats`` events add the simulation watchdogs (shared with
``watch`` — a multi-hour run's extinction should be caught live, not at
the post-mortem):

- **extinction** — the population hits zero after having been nonzero;
- **all-static fixpoint** — a whole chunk changed no cell: Life is
  deterministic, so the world will never change again (an oscillator
  still flips cells every chunk — only a true fixpoint trips this);
- **cross-rank population disagreement** — ``stats`` values are global
  (psummed over the mesh), so two ranks reporting different populations
  for the same generation mean a rank computed a different world — the
  same SDC signature as audit-fingerprint divergence, caught from the
  stats stream alone.

``diff <dir_a> <dir_b>`` compares two runs phase-by-phase and
chunk-size-by-chunk-size — the missing tool behind BENCH_r* trajectory
analysis (was: eyeballing two JSON blobs).

Schema-v6 ``spans`` blocks render as a per-phase breakdown table
(dispatch / block-until-ready / checkpoint / telemetry / preempt-poll
host seconds summed over the run), and ``summarize --ledger FILE`` adds
the cross-run **regression** anomaly: a run whose summary throughput
sits more than the threshold below the perf ledger's best for its
config fingerprint (:mod:`gol_tpu.telemetry.ledger`, which also owns
the ``ledger ingest|show|check`` subcommands routed from here).

Exit codes: 0 on success (anomalies are reported, not fatal — they are
the tool's *output*), 2 on schema-invalid or unreadable input.
"""

from __future__ import annotations

import glob
import json
import os
import re
import statistics
from typing import Dict, List, Optional, Tuple

from gol_tpu.telemetry import SchemaError, validate_record

_RANK_RE = re.compile(r"^(?P<run>.+)\.rank(?P<rank>\d+)\.jsonl$")


class Run:
    """All records of one run_id, keyed by rank."""

    def __init__(self, run_id: str) -> None:
        self.run_id = run_id
        self.ranks: Dict[int, List[dict]] = {}

    def records(self, event: str, rank: Optional[int] = None) -> List[dict]:
        out = []
        for r, recs in sorted(self.ranks.items()):
            if rank is not None and r != rank:
                continue
            out.extend(rec for rec in recs if rec["event"] == event)
        return out

    @property
    def header(self) -> Optional[dict]:
        heads = self.records("run_header", rank=min(self.ranks, default=None))
        return heads[0] if heads else None

    @property
    def summary_record(self) -> Optional[dict]:
        s = self.records("summary", rank=min(self.ranks, default=None))
        return s[-1] if s else None


def load_dir(directory: str) -> Dict[str, Run]:
    """Parse + schema-validate every rank file; group by run_id.

    Raises :class:`SchemaError` (exit 2 at the CLI) on any invalid line —
    a telemetry directory that fails validation is worse than no
    telemetry, because downstream analysis would silently trust it.
    """
    if not os.path.isdir(directory):
        raise SchemaError(f"{directory}: not a directory")
    runs: Dict[str, Run] = {}
    paths = sorted(glob.glob(os.path.join(directory, "*.jsonl")))
    # Black-box crash dumps share the directory and the .jsonl suffix
    # but are a different artifact with a different reader (`telemetry
    # postmortem`) — a dir that holds both must still summarize.
    paths = [
        p for p in paths
        if ".blackbox.jsonl" not in os.path.basename(p)
    ]
    if not paths:
        raise SchemaError(f"{directory}: no .jsonl telemetry files")
    for path in paths:
        m = _RANK_RE.match(os.path.basename(path))
        if not m:
            raise SchemaError(
                f"{path}: filename is not <run_id>.rank<k>.jsonl"
            )
        run_id, rank = m.group("run"), int(m.group("rank"))
        run = runs.setdefault(run_id, Run(run_id))
        recs = run.ranks.setdefault(rank, [])
        with open(path) as f:
            for lineno, line in enumerate(f, 1):
                if not line.strip():
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError as e:
                    raise SchemaError(f"{path}:{lineno}: bad JSON ({e})")
                try:
                    validate_record(rec)
                except SchemaError as e:
                    raise SchemaError(f"{path}:{lineno}: {e}")
                recs.append(rec)
    return runs


def latest_run(runs: Dict[str, Run]) -> Run:
    """The run whose header timestamp is newest (ties: run_id order)."""

    def key(run: Run):
        head = run.header
        return (head["t"] if head else 0.0, run.run_id)

    return max(runs.values(), key=key)


# -- anomaly detection -------------------------------------------------------


def find_anomalies(run: Run) -> List[str]:
    flags: List[str] = []
    rank0 = min(run.ranks, default=0)
    chunks = run.records("chunk", rank=rank0)

    # Chunk-time outliers, per chunk-size class.  Batched runs (schema
    # v4) emit one record per bucket per chunk, so the class additionally
    # keys on the bucket — a big bucket's wall is not an outlier just
    # because a small bucket shares its take.
    by_take: Dict[tuple, List[dict]] = {}
    for c in chunks:
        b = c.get("batch") or {}
        key = (c["take"], tuple(b.get("bucket", ())), b.get("B"))
        by_take.setdefault(key, []).append(c)
    for (take, _, _), cs in sorted(by_take.items()):
        if len(cs) < 3:
            continue  # no meaningful baseline
        med = statistics.median(c["wall_s"] for c in cs)
        for c in cs:
            if med > 0 and c["wall_s"] > 2.0 * med:
                flags.append(
                    f"chunk-time outlier: chunk {c['index']} "
                    f"({take} gens) took {c['wall_s']:.4f}s, "
                    f"{c['wall_s'] / med:.1f}x the {med:.4f}s median of "
                    "its size class"
                )

    # Activity engine falling back every generation: the worklist
    # capacity is too small for this workload's active set, so the run
    # pays dense compute *plus* the gating overhead (schema v5,
    # docs/SPARSE.md — raise --activity-capacity or the tile).
    acts = [c["activity"] for c in chunks if c.get("activity")]
    if acts:
        gens = sum(c["take"] for c in chunks if c.get("activity"))
        fallbacks = sum(a.get("fallback_gens", 0) for a in acts)
        if gens and fallbacks == gens:
            flags.append(
                f"activity fallback storm: all {gens} generations "
                "overflowed the worklist capacity — the gated tier is "
                "paying dense compute plus gating overhead; raise "
                "--activity-capacity or use a dense tier for this "
                "workload"
            )

    # Utilization cliffs.
    utils = [
        (c["index"], c["roofline_util"])
        for c in chunks
        if c.get("roofline_util") is not None
    ]
    if len(utils) >= 2:
        best = max(u for _, u in utils)
        for idx, u in utils:
            if best > 0 and u < 0.5 * best:
                flags.append(
                    f"utilization cliff: chunk {idx} at "
                    f"{100 * u:.3g}% roofline vs the run's best "
                    f"{100 * best:.3g}%"
                )

    # Audit fingerprint divergence across ranks.
    by_gen: Dict[int, Dict[int, int]] = {}
    for rank in sorted(run.ranks):
        for a in run.records("guard_audit", rank=rank):
            by_gen.setdefault(a["generation"], {})[rank] = a["fingerprint"]
    for gen, fps in sorted(by_gen.items()):
        if len(set(fps.values())) > 1:
            detail = ", ".join(
                f"rank{r}={fp:#010x}" for r, fp in sorted(fps.items())
            )
            flags.append(
                f"audit fingerprint divergence at generation {gen}: "
                f"{detail} — ranks disagree about the world (SDC or a "
                "broken collective)"
            )

    # Simulation watchdogs over the --stats stream (schema v2).
    flags.extend(stats_watchdogs(run))

    # Resilience watchdog (schema v3): a resume that had to fall back
    # past newer snapshots means corruption happened — worth a flag even
    # though the run recovered.
    for r in run.records("resume"):
        if r["fallback"]:
            skipped = r.get("skipped") or []
            detail = f" (skipped {', '.join(skipped)})" if skipped else ""
            flags.append(
                f"resume fallback: resumed from generation "
                f"{r['generation']} instead of the newest snapshot"
                f"{detail} — a newer candidate was corrupt/torn or "
                "another rank forced an earlier generation"
            )

    # Containment watchdog (schema v9): a degraded run finished, but
    # something was sacrificed to get there — retried checkpoint
    # writes, a shed telemetry stream, shed checkpointing.
    for r in run.records("degraded"):
        flag = (
            f"degraded: {r['resource']} {r['action']}"
            + (
                f" at generation {r['generation']}"
                if r.get("generation") is not None
                else ""
            )
            + (f" — {r['detail']}" if r.get("detail") else "")
        )
        if r.get("dropped"):
            # Schema v13 shed census: the EventLog's close() stamps how
            # many records of each type the degrade plane dropped — the
            # only after-the-fact accounting of what the stream is
            # missing (live, gol_telemetry_shed_total carries it).
            census = ", ".join(
                f"{n} {event}"
                for event, n in sorted(r["dropped"].items())
            )
            total = r.get(
                "dropped_total", sum(r["dropped"].values())
            )
            flag += (
                f" — shed {total} record(s) after degrading "
                f"({census}); the tables above undercount by exactly "
                "this census"
            )
        flags.append(flag)

    # Per-chunk walls must account for the summary's total phase.
    summ = run.summary_record
    if summ is not None and chunks:
        total = summ["phases"].get("total", summ["duration_s"])
        acc = sum(c["wall_s"] for c in chunks)
        if total > 0 and abs(acc - total) > 0.05 * total + 1e-3:
            flags.append(
                f"chunk/total drift: per-chunk walls sum to {acc:.4f}s "
                f"but the total phase is {total:.4f}s"
            )
    return flags


def stats_watchdogs(run: Run) -> List[str]:
    """Extinction / static-fixpoint / cross-rank disagreement flags.

    Shared verbatim by ``summarize`` and the live ``watch`` dashboard so
    the two tools can never disagree about what "unhealthy" means.
    """
    flags: List[str] = []
    rank0 = min(run.ranks, default=0)
    stats = run.records("stats", rank=rank0)

    seen_alive = False
    flagged_extinct = False
    for s in stats:
        if s["population"] > 0:
            seen_alive = True
        elif seen_alive and not flagged_extinct:
            flags.append(
                f"extinction: population hit 0 at generation "
                f"{s['generation']} (was alive earlier) — the run can be "
                "stopped, nothing further will happen"
            )
            flagged_extinct = True
    for s in stats:
        if s["take"] > 0 and s["changed"] == 0:
            flags.append(
                f"all-static fixpoint at generation {s['generation']}: no "
                f"cell changed across the {s['take']}-generation chunk — "
                "the world is frozen (deterministic rule: it stays frozen)"
            )
            break  # one flag; every later chunk is the same fixpoint

    # Cross-rank disagreement: stats are global (psummed), so every
    # rank must report the identical population per generation.
    by_gen: Dict[int, Dict[int, int]] = {}
    for rank in sorted(run.ranks):
        for s in run.records("stats", rank=rank):
            by_gen.setdefault(s["generation"], {})[rank] = s["population"]
    for gen, pops in sorted(by_gen.items()):
        if len(set(pops.values())) > 1:
            detail = ", ".join(
                f"rank{r}={p}" for r, p in sorted(pops.items())
            )
            flags.append(
                f"cross-rank population disagreement at generation {gen}: "
                f"{detail} — the psummed global value must be identical "
                "everywhere; a rank computed a different world (SDC or a "
                "broken collective)"
            )
    return flags


def restart_storm_flags(
    runs: Dict[str, Run],
    max_restarts: int = 3,
    window_s: float = 300.0,
) -> List[str]:
    """Directory-level watchdog: too many supervised restarts, too fast.

    Every restarted attempt is its own run (its own rank files), so the
    per-run anomaly scan cannot see a storm; this counts ``restart``
    events (schema v3 — one per restarted attempt, stamped by the child
    from ``GOL_RESTART_ATTEMPT``) across *all* runs in the directory and
    flags more than ``max_restarts`` of them inside any ``window_s``
    sliding window: the supervisor is respawning a child that keeps
    dying — a persistent fault burning the restart budget, not a
    preemption blip.  Shared by ``summarize`` and ``watch``.
    """
    times = sorted(
        rec["t"]
        for run in runs.values()
        for rank in run.ranks.values()
        for rec in rank
        if rec["event"] == "restart"
    )
    need = max_restarts + 1
    for i in range(len(times) - need + 1):
        span = times[i + need - 1] - times[i]
        if span <= window_s:
            return [
                f"restart storm: {need} restarts within {span:.0f}s "
                f"(> {max_restarts} per {window_s:.0f}s window) — the "
                "child keeps dying; check the supervisor manifest and "
                "the last attempt's stderr"
            ]
    return []


def load_manifests(directory: str) -> List[dict]:
    """Supervisor run-manifests (``*.manifest.json``) in the directory.

    The join handle between the event streams and the process tier:
    the manifest carries attempts/exit codes/resume generations keyed
    by ``run_id`` (docs/RESILIENCE.md).  Unreadable manifests are
    skipped — they come from a different writer than the schema-gated
    rank files.
    """
    out = []
    for path in sorted(glob.glob(os.path.join(directory, "*.manifest.json"))):
        try:
            with open(path) as f:
                m = json.load(f)
        except (OSError, json.JSONDecodeError):
            continue
        if isinstance(m, dict):
            m["_path"] = path
            out.append(m)
    return out


def render_manifest(m: dict, out) -> None:
    attempts = m.get("attempts") or []
    print(
        f"supervisor manifest {os.path.basename(m['_path'])}"
        + (f" (run {m['run_id']})" if m.get("run_id") else ""),
        file=out,
    )
    print(
        f"  attempts: {len(attempts)}  budget: {m.get('max_restarts')}  "
        f"finished: {m.get('finished')}  final_exit: {m.get('final_exit')}",
        file=out,
    )
    for a in attempts:
        rc = a.get("exit_code")
        state = (
            "running" if rc is None
            else "ok" if rc == 0
            else "preempted" if rc == 75
            else f"crashed({rc})"
        )
        gen = a.get("resume_generation")
        print(
            f"    attempt {a.get('attempt')}: {state}, resumed from "
            f"{'fresh start' if gen is None else f'generation {gen}'}",
            file=out,
        )


# -- rendering ---------------------------------------------------------------


def _fmt_rate(x: float) -> str:
    return f"{x:.3e}"


def _fmt_util(u: Optional[float]) -> str:
    if u is None:
        return "-"
    pct = 100 * u
    # Sub-0.01% fractions (CPU backends vs the TPU peak) stay legible
    # instead of rounding to a meaningless 0.00%.
    return f"{pct:6.2f}%" if pct >= 0.005 else f"{pct:.1e}%"


def render_run(run: Run, out) -> None:
    head = run.header
    print(f"run {run.run_id}", file=out)
    if head is not None:
        cfg = head.get("config", {})
        print(
            f"  ranks: {len(run.ranks)}/{head['process_count']}  "
            f"backend: {head.get('backend', '?')}  "
            f"jax: {head.get('jax_version', '?')}",
            file=out,
        )
        items = ", ".join(f"{k}={v}" for k, v in sorted(cfg.items()))
        print(f"  config: {items}", file=out)

    rank0 = min(run.ranks, default=0)
    compiles = run.records("compile", rank=rank0)
    if compiles:
        print("  compile:", file=out)
        for c in compiles:
            line = (
                f"    chunk {c['chunk']:>8} gens  lower {c['lower_s']:.3f}s"
                f"  compile {c['compile_s']:.3f}s"
            )
            # Schema v13 (docs/OBSERVABILITY.md, "Compilation as a
            # first-class observable"): the persistent-cache verdict.
            # The key is stamped only on a miss — that is when the
            # entry is written and XLA names it.
            hit = c.get("cache_hit")
            if hit is True:
                line += "  [cache hit]"
            elif hit is False:
                key = c.get("cache_key")
                line += "  [cache miss" + (
                    f" -> {key}]" if key else "]"
                )
            print(line, file=out)
        stamped = [c for c in compiles if c.get("cache_hit") is not None]
        total_s = sum(c["lower_s"] + c["compile_s"] for c in compiles)
        if stamped:
            hits = sum(1 for c in stamped if c["cache_hit"])
            print(
                f"    cache: {hits}/{len(stamped)} hit(s) "
                f"({100 * hits / len(stamped):.0f}% hit rate), "
                f"{total_s:.3f}s total lower+compile",
                file=out,
            )
        else:
            print(
                f"    cache: not attached, {total_s:.3f}s total "
                "lower+compile (set --compile-cache or "
                "JAX_COMPILATION_CACHE_DIR to stamp hit/miss)",
                file=out,
            )
        if any(c.get("memory") for c in compiles):
            # Compiled-program footprint per chunk size (schema v2): the
            # argument/output/temp/peak bytes XLA reports — the number
            # that actually caps whole-board geometry, next to the
            # durations that never showed it.
            print(
                "  memory: chunk      arg_B      out_B     temp_B"
                "     peak_B    alias_B",
                file=out,
            )
            for c in compiles:
                m = c.get("memory") or {}

                def cell(key, m=m):
                    v = m.get(key)
                    return "-" if v is None else str(v)

                print(
                    f"  {c['chunk']:>12} {cell('argument_bytes'):>10} "
                    f"{cell('output_bytes'):>10} {cell('temp_bytes'):>10} "
                    f"{cell('peak_bytes'):>10} {cell('alias_bytes'):>10}",
                    file=out,
                )

    for s in run.records("storm", rank=rank0):
        # Schema v13: the scheduler's compile-storm detector fired —
        # K cold compiles inside one admission window; admissions were
        # throttled until the window drained (docs/SERVING.md).
        print(
            f"  storm: {s['kind']} — {s['count']} cold compiles within "
            f"{s['window_s']:.0f}s (threshold {s['threshold']}); "
            "admission depth halved for the window",
            file=out,
        )

    chunks = run.records("chunk", rank=rank0)
    if chunks:
        batched = any(c.get("batch") for c in chunks)
        spans_any = any(c.get("spans") for c in chunks)
        gated = any(c.get("activity") for c in chunks)
        ringed = any(c.get("halo") for c in chunks)
        streamed = any(c.get("ooc") for c in chunks)
        print(
            "  chunk     gens       gen      wall_s     updates/s  "
            "roofline"
            + ("  batch (bucket B eng per-world/s)" if batched else "")
            + ("  activity (active% skipped fallbacks)" if gated else "")
            + ("  halo (mode k exch band)" if ringed else "")
            + ("  ooc (bands skip h2d/d2h ovl%)" if streamed else ""),
            file=out,
        )
        for c in chunks:
            line = (
                f"  {c['index']:>5} {c['take']:>8} {c['generation']:>9} "
                f"{c['wall_s']:>11.4f}  {_fmt_rate(c['updates_per_sec']):>12}"
                f"  {_fmt_util(c.get('roofline_util')):>8}"
            )
            a = c.get("activity")
            if a:
                # Schema v5 (docs/SPARSE.md): the sparse tier's skip
                # accounting — what fraction of tile-generations were
                # active, how many the worklist skipped outright, and
                # how often it overflowed to the dense fallback.
                line += (
                    f"  act {100 * a['active_fraction']:.1f}%"
                    f" skip {a['skipped_tile_gens']}/{a['tile_gens']}"
                )
                if a.get("fallback_gens"):
                    line += f" fb={a['fallback_gens']}"
            hb = c.get("halo")
            if hb:
                # Schema v8 (docs/OBSERVABILITY.md): the ring program's
                # exchange accounting — band depth/mode, exchanges this
                # chunk, and the band traffic's share of the payload.
                line += (
                    f"  {hb.get('mode', '?')} k={hb.get('depth', '?')}"
                    f" x{hb.get('exchanges', '?')}"
                    f" {hb.get('band_bytes', 0)}B"
                    f" ({100 * hb.get('exchange_share', 0.0):.1f}%)"
                )
            o = c.get("ooc")
            if o:
                # Schema v15 (docs/STREAMING.md): the out-of-core tier's
                # streaming accounting — band count, dead bands that
                # moved zero bytes, the chunk's transfer volume, and the
                # measured fraction of transfer wall hidden behind
                # in-flight compute.
                line += (
                    f"  {o.get('bands', '?')}b"
                    f" skip {o.get('skipped_bands', 0)}"
                    f" {o.get('bytes_h2d', 0)}/{o.get('bytes_d2h', 0)}B"
                    f" ovl {100 * o.get('overlap_fraction', 0.0):.0f}%"
                )
            b = c.get("batch")
            if b:
                # Schema v4 (docs/BATCHING.md): one chunk record per
                # bucket; per-world throughput is the serving metric.
                shape = "x".join(str(x) for x in b.get("bucket", []))
                pw = b.get("per_world_updates_per_sec")
                line += (
                    f"  {shape} B={b.get('B')} {b.get('engine', '?')}"
                    + (f" {_fmt_rate(pw)}/world" if pw is not None else "")
                    + (" masked" if b.get("masked") else "")
                )
            print(line, file=out)
        if spans_any:
            # Schema-v6 span attribution: per-phase host seconds summed
            # over the run's chunks — "where does the non-MFU time go"
            # from the JSONL alone.  dispatch+ready partition the fenced
            # chunk walls; the rest are boundary phases between fences.
            totals: Dict[str, float] = {}
            for c in chunks:
                for phase, secs in (c.get("spans") or {}).items():
                    totals[phase] = totals.get(phase, 0.0) + secs
            span_sum = sum(totals.values())
            wall_sum = sum(c["wall_s"] for c in chunks)
            print("  spans: phase        total_s    share", file=out)
            for phase, secs in sorted(
                totals.items(), key=lambda kv: -kv[1]
            ):
                share = 100 * secs / span_sum if span_sum > 0 else 0.0
                print(
                    f"    {phase:<14} {secs:>10.4f}  {share:>6.1f}%",
                    file=out,
                )
            print(
                f"    (chunk walls sum {wall_sum:.4f}s; spans cover "
                f"{span_sum:.4f}s of host loop time)",
                file=out,
            )

    stats = run.records("stats", rank=rank0)
    if stats:
        print(
            "  stats     gen  population     births     deaths    "
            "changed  faces(t/b/l/r)",
            file=out,
        )
        for s in stats:
            f = s.get("faces") or {}
            faces = "/".join(
                str(f[k]) for k in ("top", "bottom", "left", "right")
                if k in f
            ) or "-"
            print(
                f"  {s['generation']:>11} {s['population']:>11} "
                f"{s['births']:>10} {s['deaths']:>10} {s['changed']:>10}"
                f"  {faces}",
                file=out,
            )

    audits = run.records("guard_audit", rank=rank0)
    if audits:
        failures = sum(1 for a in audits if not a["ok"])
        print(
            f"  guard: {len(audits)} audits, {failures} failures "
            f"(population {audits[-1]['population']} at gen "
            f"{audits[-1]['generation']})",
            file=out,
        )

    ckpts = run.records("checkpoint", rank=rank0)
    if ckpts:
        fenced = sum(c["wall_s"] for c in ckpts)
        nbytes = sum(c["bytes"] for c in ckpts)
        overlapped = sum(1 for c in ckpts if c["overlapped"])
        print(
            f"  checkpoints: {len(ckpts)} ({overlapped} overlapped), "
            f"{nbytes} payload bytes, {fenced:.4f}s fenced",
            file=out,
        )

    for r in run.records("restart", rank=rank0):
        print(
            f"  restart: supervised attempt {r['attempt']}",
            file=out,
        )
    for r in run.records("resume", rank=rank0):
        print(
            f"  resume: generation {r['generation']} from {r['path']}"
            + ("  [FALLBACK]" if r["fallback"] else ""),
            file=out,
        )
    for r in run.records("preempt", rank=rank0):
        print(
            f"  preempt: stopped at generation {r['generation']} "
            f"({'checkpointed' if r['checkpointed'] else 'NO checkpoint'})",
            file=out,
        )
    for r in run.records("reshard", rank=rank0):
        src, dst = r["src_mesh"], r["dst_mesh"]

        def _mesh(m):
            return (
                m["kind"]
                if m["kind"] == "none"
                else f"{m['kind']} {m['rows']}x{m['cols']}"
            )

        print(
            f"  reshard: generation {r['generation']} "
            f"{_mesh(src)} -> {_mesh(dst)}, "
            f"{r['bytes_moved']} packed bytes moved"
            + (
                f" ({r['seam_splits']} seam splits)"
                if "seam_splits" in r
                else ""
            )
            + ("  [legacy manifest]" if r.get("legacy_manifest") else ""),
            file=out,
        )

    faults_fired = run.records("fault", rank=rank0)
    if faults_fired:
        sites: Dict[str, int] = {}
        for r in faults_fired:
            sites[r["site"]] = sites.get(r["site"], 0) + 1
        detail = ", ".join(
            f"{site}×{n}" for site, n in sorted(sites.items())
        )
        print(
            f"  faults: {len(faults_fired)} injection(s) fired "
            f"({detail}) — fault plan active (docs/RESILIENCE.md)",
            file=out,
        )

    serves = run.records("serve", rank=rank0)
    if serves:
        # Schema v10 (docs/SERVING.md): the serving tier's request
        # lifecycle — distinct ids that were committed (admit/requeue)
        # next to the per-transition counts, so an exactly-once miss
        # (completes != admitted ids) is visible from the stream alone.
        by_action: Dict[str, int] = {}
        committed = set()
        for r in serves:
            by_action[r["action"]] = by_action.get(r["action"], 0) + 1
            if r["action"] in ("admit", "requeue"):
                committed.add(r["request_id"])
        detail = ", ".join(
            f"{n} {a}" for a, n in sorted(by_action.items())
        )
        lats = [
            r["latency_s"]
            for r in serves
            if r["action"] == "complete" and r.get("latency_s") is not None
        ]
        lat = (
            f"  (median latency {statistics.median(lats):.3f}s)"
            if lats
            else ""
        )
        print(
            f"  serve: {len(committed)} request(s) committed — "
            f"{detail}{lat}",
            file=out,
        )

    spans = run.records("span", rank=rank0)
    if spans:
        # Schema v12 (docs/OBSERVABILITY.md, "Request tracing & SLOs"):
        # a one-line census pointing at the real tool — root spans are
        # terminals, so traces != roots means requests still in flight
        # (or crashed: their roots live in the replaying run's file).
        traces = {r["trace_id"] for r in spans}
        roots = sum(1 for r in spans if r["span_id"] == "root")
        print(
            f"  trace: {len(spans)} span(s) across {len(traces)} "
            f"trace(s), {roots} complete — `telemetry trace` for the "
            "decomposition",
            file=out,
        )

    healths = run.records("health", rank=rank0)
    if healths:
        # Schema v11 (docs/RESILIENCE.md, "Live elasticity"): verdict
        # counts plus the final alive-device count — a health line next
        # to a reshard line above is the live-elasticity signature.
        by_kind: Dict[str, int] = {}
        for r in healths:
            by_kind[r["verdict"]] = by_kind.get(r["verdict"], 0) + 1
        detail = ", ".join(
            f"{n} {k}" for k, n in sorted(by_kind.items())
        )
        alive = [r["alive"] for r in healths if "alive" in r]
        tail = f" (alive devices now {alive[-1]})" if alive else ""
        print(f"  health: {detail}{tail}", file=out)

    fleets = run.records("fleet", rank=rank0)
    if fleets:
        # Schema v14 (docs/SERVING.md, "The fleet"): the front tier's
        # decisions — route/handoff/epoch/replica counts plus the final
        # routing epoch, so a handoff next to a replica_dead verdict is
        # the migration signature readable from the stream alone.
        by_action: Dict[str, int] = {}
        for r in fleets:
            by_action[r["action"]] = by_action.get(r["action"], 0) + 1
        detail = ", ".join(
            f"{n} {a}" for a, n in sorted(by_action.items())
        )
        epochs = [r["epoch"] for r in fleets if "epoch" in r]
        tail = f" (routing epoch now {max(epochs)})" if epochs else ""
        print(f"  fleet: {detail}{tail}", file=out)

    benches = run.records("bench_row")
    if benches:
        for b in benches:
            print(f"  bench[{b['bench']}]: {json.dumps(b['data'])}", file=out)

    summ = run.summary_record
    if summ is not None:
        print(
            f"  total: {summ['duration_s']:.5f}s  "
            f"{summ['cell_updates']} cell updates  "
            f"{_fmt_rate(summ['updates_per_sec'])} updates/s",
            file=out,
        )
        for name, secs in sorted(summ["phases"].items()):
            print(f"    phase {name:<12} {secs:>10.4f}s", file=out)

    for flag in find_anomalies(run):
        print(f"  ANOMALY: {flag}", file=out)


def summarize(
    directory: str,
    out,
    ledger_path: Optional[str] = None,
    regress_threshold: Optional[float] = None,
) -> int:
    runs = load_dir(directory)
    ledger_records = None
    if ledger_path:
        # The cross-run regression anomaly (docs/OBSERVABILITY.md): a
        # run whose summary throughput sits >threshold below the perf
        # ledger's best for the same config fingerprint gets flagged.
        from gol_tpu.telemetry import ledger as ledger_mod

        ledger_records = ledger_mod.read_ledger(ledger_path)
    for run_id in sorted(runs):
        render_run(runs[run_id], out)
        if ledger_records is not None:
            from gol_tpu.telemetry import ledger as ledger_mod

            kw = {}
            if regress_threshold is not None:
                kw["threshold"] = regress_threshold
            for flag in ledger_mod.ledger_regression_flags(
                runs[run_id], ledger_records, **kw
            ):
                print(f"  ANOMALY: {flag}", file=out)
    for m in load_manifests(directory):
        render_manifest(m, out)
    # Directory-level: supervised restarts span runs, so the storm
    # watchdog cannot live inside the per-run anomaly scan.
    for flag in restart_storm_flags(runs):
        print(f"ANOMALY: {flag}", file=out)
    return 0


# -- diff --------------------------------------------------------------------


def _phase_table(run: Run) -> Dict[str, float]:
    summ = run.summary_record
    return dict(summ["phases"]) if summ else {}


def _chunk_medians(run: Run) -> Dict[int, Tuple[float, Optional[float]]]:
    """take -> (median wall_s, median roofline_util) on rank 0."""
    rank0 = min(run.ranks, default=0)
    by_take: Dict[int, List[dict]] = {}
    for c in run.records("chunk", rank=rank0):
        by_take.setdefault(c["take"], []).append(c)
    out = {}
    for take, cs in by_take.items():
        walls = [c["wall_s"] for c in cs]
        utils = [
            c["roofline_util"]
            for c in cs
            if c.get("roofline_util") is not None
        ]
        out[take] = (
            statistics.median(walls),
            statistics.median(utils) if utils else None,
        )
    return out


def _delta(a: float, b: float) -> str:
    if a == 0:
        return "   n/a"
    return f"{100 * (b - a) / a:+6.1f}%"


def diff(dir_a: str, dir_b: str, out) -> int:
    run_a = latest_run(load_dir(dir_a))
    run_b = latest_run(load_dir(dir_b))
    print(f"A: {dir_a} run {run_a.run_id}", file=out)
    print(f"B: {dir_b} run {run_b.run_id}", file=out)

    pa, pb = _phase_table(run_a), _phase_table(run_b)
    names = sorted(set(pa) | set(pb))
    if names:
        print("  phase            A_s         B_s    delta", file=out)
        for name in names:
            a, b = pa.get(name, 0.0), pb.get(name, 0.0)
            print(
                f"  {name:<12} {a:>10.4f}  {b:>10.4f}  {_delta(a, b)}",
                file=out,
            )

    sa, sb = run_a.summary_record, run_b.summary_record
    if sa and sb:
        a, b = sa["updates_per_sec"], sb["updates_per_sec"]
        print(
            f"  updates/s    {_fmt_rate(a):>10}  {_fmt_rate(b):>10}  "
            f"{_delta(a, b)}",
            file=out,
        )

    ca, cb = _chunk_medians(run_a), _chunk_medians(run_b)
    common = sorted(set(ca) & set(cb))
    if common:
        print(
            "  chunk_gens   A_med_wall_s  B_med_wall_s    delta  "
            "A_util  B_util",
            file=out,
        )
        for take in common:
            (wa, ua), (wb, ub) = ca[take], cb[take]
            print(
                f"  {take:>10} {wa:>13.4f} {wb:>13.4f}  {_delta(wa, wb)}"
                f"  {_fmt_util(ua):>6}  {_fmt_util(ub):>6}",
                file=out,
            )
    only = sorted(set(ca) ^ set(cb))
    if only:
        print(f"  chunk sizes present in only one run: {only}", file=out)
    return 0


def main(argv=None) -> int:
    import argparse
    import sys

    p = argparse.ArgumentParser(
        prog="gol_tpu.telemetry",
        description="Summarize or diff structured run telemetry "
        "(docs/OBSERVABILITY.md)",
    )
    sub = p.add_subparsers(dest="command", required=True)
    ps = sub.add_parser("summarize", help="merge rank files, render tables")
    ps.add_argument("directory")
    ps.add_argument(
        "--ledger",
        default=None,
        metavar="FILE",
        help="flag a regression anomaly when a run's throughput sits "
        "below the perf ledger's best for its config fingerprint",
    )
    ps.add_argument(
        "--regress-threshold", type=float, default=None, metavar="FRAC"
    )
    pd = sub.add_parser("diff", help="compare two telemetry runs")
    pd.add_argument("dir_a")
    pd.add_argument("dir_b")
    pl = sub.add_parser(
        "ledger",
        help="cross-run perf ledger: ingest artifacts, show trends, "
        "gate regressions (PERF_LEDGER.jsonl)",
    )
    lsub = pl.add_subparsers(dest="ledger_command", required=True)
    pli = lsub.add_parser(
        "ingest", help="normalize artifact JSONs / telemetry dirs into "
        "the ledger (idempotent)"
    )
    pli.add_argument("paths", nargs="+", metavar="PATH")
    pls = lsub.add_parser("show", help="per-config trend tables")
    plc = lsub.add_parser(
        "check", help="exit 1 when the newest record of any config "
        "regresses past the threshold (the CI gate)"
    )
    plc.add_argument(
        "--threshold", type=float, default=None, metavar="FRAC",
        help="regression fraction (default 0.20)",
    )
    plc.add_argument(
        "--backend", default="tpu", metavar="NAME",
        help="gated backend ('all' gates everything; default tpu — "
        "CPU artifacts are curve shape only)",
    )
    for sp in (pli, pls, plc):
        sp.add_argument(
            "--ledger", dest="ledger_path", default=None, metavar="FILE"
        )
    pt = sub.add_parser(
        "trace",
        help="rebuild per-request span trees: latency decomposition, "
        "SLO burn rates, Perfetto export (docs/OBSERVABILITY.md)",
    )
    pt.add_argument("directory")
    pt.add_argument(
        "--request", default=None, metavar="ID",
        help="render one request's full span tree instead of the table",
    )
    pt.add_argument(
        "--perfetto", default=None, metavar="FILE",
        help="export Chrome-trace/Perfetto JSON (load at "
        "ui.perfetto.dev or chrome://tracing)",
    )
    pt.add_argument(
        "--slo", default=None, metavar="FILE",
        help="declarative objectives JSON (default: the built-in "
        "commit-p99 + queue-fraction objectives)",
    )
    pp = sub.add_parser(
        "postmortem",
        help="reconstruct the last seconds before a crash from the "
        "black-box dump, cross-checked against the journal "
        "(docs/OBSERVABILITY.md)",
    )
    pp.add_argument(
        "directory",
        help="directory holding *.blackbox.jsonl (the state dir or its "
        "telemetry/ subdirectory)",
    )
    pw = sub.add_parser(
        "watch", help="live dashboard tailing a run's rank files"
    )
    pw.add_argument("directory")
    pw.add_argument("--run-id", default=None, metavar="NAME")
    pw.add_argument(
        "--interval", type=float, default=2.0, metavar="SECONDS"
    )
    pw.add_argument(
        "--once",
        action="store_true",
        help="render a single frame and exit (tests, cron probes)",
    )
    ns = p.parse_args(list(sys.argv[1:] if argv is None else argv))
    try:
        if ns.command == "summarize":
            return summarize(
                ns.directory,
                sys.stdout,
                ledger_path=ns.ledger,
                regress_threshold=ns.regress_threshold,
            )
        if ns.command == "ledger":
            from gol_tpu.telemetry import ledger as ledger_mod

            path = ns.ledger_path or ledger_mod.DEFAULT_LEDGER
            if ns.ledger_command == "ingest":
                return ledger_mod.main_ingest(ns.paths, path, sys.stdout)
            if ns.ledger_command == "show":
                return ledger_mod.main_show(path, sys.stdout)
            return ledger_mod.main_check(
                path,
                ns.threshold
                if ns.threshold is not None
                else ledger_mod.DEFAULT_THRESHOLD,
                (ns.backend,),
                sys.stdout,
            )
        if ns.command == "trace":
            from gol_tpu.telemetry import trace as trace_mod

            return trace_mod.main_trace(
                ns.directory,
                sys.stdout,
                request=ns.request,
                perfetto=ns.perfetto,
                slo_path=ns.slo,
            )
        if ns.command == "postmortem":
            from gol_tpu.telemetry import blackbox as blackbox_mod

            return blackbox_mod.render_postmortem(
                ns.directory, sys.stdout
            )
        if ns.command == "watch":
            from gol_tpu.telemetry import watch as watch_mod

            return watch_mod.watch(
                ns.directory,
                sys.stdout,
                run_id=ns.run_id,
                interval=ns.interval,
                frames=1 if ns.once else None,
            )
        return diff(ns.dir_a, ns.dir_b, sys.stdout)
    except (SchemaError, OSError) as e:
        print(f"telemetry: {e}", file=sys.stderr)
        return 2
