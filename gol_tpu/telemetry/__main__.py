"""``python -m gol_tpu.telemetry
{summarize <dir> | diff <a> <b> | watch <dir> |
 ledger ingest|show|check}``."""

import sys

from gol_tpu.telemetry.summarize import main

if __name__ == "__main__":
    sys.exit(main())
