"""``python -m gol_tpu.telemetry
{summarize <dir> | diff <a> <b> | watch <dir> | postmortem <dir> |
 trace <dir> [--request ID] [--perfetto out.json] [--slo FILE] |
 ledger ingest|show|check}``."""

import sys

from gol_tpu.telemetry.summarize import main

if __name__ == "__main__":
    sys.exit(main())
