"""Always-on black-box flight recorder (docs/OBSERVABILITY.md).

A process that dies with ``--telemetry`` off leaves nothing but a
journal; one that dies mid-write leaves a stream that ends before the
interesting part.  This module keeps the last N telemetry records in a
bounded in-memory ring — every record the v13 stream would carry,
captured even when no :class:`~gol_tpu.telemetry.EventLog` file sink is
attached — and turns them into a ``<run_id>.blackbox.jsonl`` dump when
the process dies:

- **unhandled exception** — a chained ``sys.excepthook``;
- **fatal signal** — SIGTERM/SIGABRT handlers (installed only where a
  graceful handler does not own the signal; serve's drain handler
  deliberately replaces the SIGTERM one, so a drain leaves *no* dump)
  plus ``faulthandler.enable()`` for the C-level deaths Python
  handlers cannot see;
- **fault-plane crash** — :func:`gol_tpu.resilience.faults.
  crash_or_stall` invokes the registered hook between firing
  ``crash.exit`` and ``os._exit`` (the one window where "no flushes,
  no atexit" still permits forensics);
- **on demand** — serve's ``GET /debug/blackbox`` renders the same
  lines over HTTP without touching disk.

The hot path is :func:`record`: one lock acquisition and one deque
append — zero file IO, zero jax interaction (the recorder runs strictly
host-side after the ``force_ready`` fences, so recorder on/off leaves
jaxprs byte-equal; pinned by tests/test_blackbox.py).  Memory is
bounded by construction: ``deque(maxlen=capacity)`` with capacity from
``GOL_BLACKBOX_RING`` (default 512 records); ``GOL_BLACKBOX=0``
disables the recorder entirely.

``python -m gol_tpu.telemetry postmortem <dir>`` (:func:`render_
postmortem`) reconstructs the last seconds before death from a dump —
final chunks, open spans, last guard audit — cross-checks the journal
fold (open intents vs. the last recorded serve events), and renders a
one-page verdict.
"""

from __future__ import annotations

import collections
import json
import os
import sys
import threading
import time
from typing import Dict, List, Optional, Tuple

ENV_DISABLE = "GOL_BLACKBOX"       # "0"/"off" -> recorder disabled
ENV_RING = "GOL_BLACKBOX_RING"     # ring capacity (records)
DEFAULT_CAPACITY = 512
DUMP_SUFFIX = ".blackbox.jsonl"


def maybe_wrap(name: str, lock):
    """lockwatch wrap without importing the (jax-heavy) analysis
    package at telemetry-import time — the recorder must stay cheap to
    import from the summarize CLI.  Named ``maybe_wrap`` so hostwalk's
    see-through pattern still classifies the wrapped attr as a lock."""
    try:
        from gol_tpu.analysis import lockwatch
    except Exception:
        return lock
    return lockwatch.maybe_wrap(name, lock)


class FlightRecorder:
    """Bounded ring of the last N validated telemetry records.

    Threading: :meth:`record` is called from every emitting thread (the
    scheduler drive loop, HTTP handler threads, the async snapshot
    writer via the degrade plane), :meth:`snapshot`/:meth:`dump` from
    handler threads and signal/crash context — all ring and identity
    state is guarded by ``FlightRecorder._lock`` (lockcheck's
    ``lock/serve`` and ``lock/runtime`` cells cover this module).
    """

    def __init__(
        self,
        capacity: Optional[int] = None,
        run_id: Optional[str] = None,
        process_index: int = 0,
    ) -> None:
        if capacity is None:
            capacity = int(os.environ.get(ENV_RING, DEFAULT_CAPACITY))
        self.capacity = max(1, int(capacity))
        self._lock = maybe_wrap("FlightRecorder._lock", threading.Lock())
        self._ring: collections.deque = collections.deque(
            maxlen=self.capacity
        )
        self._recorded_total = 0
        self._run_id = run_id or f"p{os.getpid()}"
        self._process_index = process_index
        self._dump_dir: Optional[str] = None
        self._last_dump_path: Optional[str] = None

    # -- hot path -----------------------------------------------------------
    def record(self, rec: dict) -> None:
        with self._lock:
            self._ring.append(rec)
            self._recorded_total += 1

    # -- identity (install-time) --------------------------------------------
    def configure(
        self,
        dump_dir: Optional[str] = None,
        run_id: Optional[str] = None,
        process_index: Optional[int] = None,
        capacity: Optional[int] = None,
    ) -> None:
        """Update dump identity in place (the ring content survives —
        records emitted before install are exactly the ones a startup
        crash needs)."""
        with self._lock:
            if dump_dir is not None:
                self._dump_dir = dump_dir
            if run_id is not None:
                self._run_id = run_id
            if process_index is not None:
                self._process_index = process_index
            if capacity is not None and int(capacity) != self.capacity:
                self.capacity = max(1, int(capacity))
                self._ring = collections.deque(
                    self._ring, maxlen=self.capacity
                )

    # -- dump side ----------------------------------------------------------
    def snapshot(self) -> Tuple[List[dict], int]:
        """(records oldest-first, recorded_total) — a consistent copy."""
        with self._lock:
            return list(self._ring), self._recorded_total

    def dump_lines(self, reason: str) -> List[str]:
        """The dump as JSONL lines: a schema-v13 ``run_header`` whose
        ``config`` block carries the black-box accounting (reason,
        capacity, total recorded, how many fell off the ring), then the
        ring verbatim.  Every line passes ``validate_record`` — the
        postmortem CLI and the smoke gate re-validate them."""
        from gol_tpu import telemetry

        with self._lock:
            records = list(self._ring)
            total = self._recorded_total
            run_id = self._run_id
            process_index = self._process_index
            capacity = self.capacity
        header = {
            "event": "run_header",
            "t": time.time(),
            "schema": telemetry.SCHEMA_VERSION,
            "run_id": run_id,
            "process_index": process_index,
            "process_count": 1,
            "config": {
                "driver": "blackbox",
                "reason": reason,
                "capacity": capacity,
                "recorded_total": total,
                "dropped": max(0, total - len(records)),
                "pid": os.getpid(),
            },
        }
        return [
            json.dumps(r, sort_keys=True)
            for r in [header] + records
        ]

    def dump(
        self, reason: str, directory: Optional[str] = None
    ) -> Optional[str]:
        """Write ``<dump_dir>/<run_id>.blackbox.jsonl`` and return its
        path (rotating a pre-existing dump to ``.N``, same policy as
        the EventLog rank file).  Returns None with no directory
        configured.  Never raises — this runs inside excepthooks,
        signal handlers, and the crash.exit window."""
        with self._lock:
            directory = directory or self._dump_dir
            run_id = self._run_id
        if not directory:
            return None
        try:
            lines = self.dump_lines(reason)
            os.makedirs(directory, exist_ok=True)
            path = os.path.join(directory, f"{run_id}{DUMP_SUFFIX}")
            if os.path.exists(path):
                n = 1
                while os.path.exists(f"{path}.{n}"):
                    n += 1
                os.replace(path, f"{path}.{n}")
            with open(path, "w") as f:
                f.write("\n".join(lines) + "\n")
                f.flush()
                os.fsync(f.fileno())
            with self._lock:
                self._last_dump_path = path
            return path
        except Exception:
            return None


# -- the process-default recorder -------------------------------------------
# None = not yet created; False = disabled by GOL_BLACKBOX=0 (checked
# once); FlightRecorder otherwise.  Creation races are benign (last
# writer wins before any dump identity is configured), so the hot path
# stays a single global read.
_default = None


def enabled() -> bool:
    return os.environ.get(ENV_DISABLE, "1").lower() not in (
        "0", "off", "false", ""
    )


def recorder() -> Optional[FlightRecorder]:
    """The process-default recorder (created on first use), or None
    when ``GOL_BLACKBOX=0`` disabled it."""
    global _default
    if _default is None:
        _default = FlightRecorder() if enabled() else False
    return _default or None


def record(rec: dict) -> None:
    """Ring-record one already-validated telemetry record.  The tap
    :meth:`EventLog.emit` calls on every record — and the one emission
    sites without a file sink call directly."""
    r = _default
    if r is None:
        r = recorder()
    if r:
        r.record(rec)


def record_event(event: str, **fields) -> None:
    """Build the standard envelope and ring-record it — for emission
    sites that have no EventLog attached (the bare scheduler's serve/
    chunk/guard records, docs/SERVING.md)."""
    record({"event": event, "t": time.time(), **fields})


def reset_for_tests() -> None:
    """Drop the process-default recorder (tests only)."""
    global _default
    _default = None


# -- dump triggers -----------------------------------------------------------
_prev_excepthook = None
_hooks_installed = False


def dump_now(reason: str) -> Optional[str]:
    """Dump the default ring now; never raises.  The crash-forensics
    entry point — callable from any context."""
    r = recorder()
    if r is None:
        return None
    return r.dump(reason)


def _excepthook(tp, value, tb):
    if not issubclass(tp, KeyboardInterrupt):
        dump_now(f"exception:{tp.__name__}")
    hook = _prev_excepthook or sys.__excepthook__
    hook(tp, value, tb)


def _signal_dump_handler(signum, frame):
    import signal as signal_mod

    try:
        name = signal_mod.Signals(signum).name
    except ValueError:
        name = str(signum)
    dump_now(f"signal:{name}")
    # Re-deliver with the default disposition so the exit status still
    # says "killed by signal" — the recorder observes, never survives.
    signal_mod.signal(signum, signal_mod.SIG_DFL)
    os.kill(os.getpid(), signum)


def install(
    dump_dir: str,
    run_id: Optional[str] = None,
    process_index: Optional[int] = None,
    capacity: Optional[int] = None,
    signals: bool = False,
) -> Optional[FlightRecorder]:
    """Arm the black box: configure the default recorder's dump
    identity and install the death triggers.

    Idempotent.  ``signals=True`` additionally claims SIGTERM/SIGABRT
    and enables ``faulthandler`` — only the serve entry point asks for
    this, and it installs its *graceful* SIGTERM handler afterwards, so
    a drain never dumps.  Returns the recorder (None when disabled).
    """
    r = recorder()
    if r is None:
        return None
    r.configure(
        dump_dir=dump_dir,
        run_id=run_id,
        process_index=process_index,
        capacity=capacity,
    )
    global _prev_excepthook, _hooks_installed
    if not _hooks_installed:
        _hooks_installed = True
        _prev_excepthook = sys.excepthook
        sys.excepthook = _excepthook
        # The fault plane's crash.exit is an os._exit with no flushes
        # and no atexit — the registered hook is the only forensic
        # window (gol_tpu/resilience/faults.py).
        from gol_tpu.resilience import faults as faults_mod

        faults_mod.register_crash_hook(
            lambda site, generation, code: dump_now(
                f"{site}:gen{generation}"
            )
        )
    if signals:
        import faulthandler
        import signal as signal_mod

        try:
            faulthandler.enable()
        except Exception:
            pass
        try:
            signal_mod.signal(signal_mod.SIGTERM, _signal_dump_handler)
            signal_mod.signal(signal_mod.SIGABRT, _signal_dump_handler)
        except ValueError:
            pass  # not the main thread — triggers stay exception/crash
    return r


# -- postmortem --------------------------------------------------------------
def find_dumps(directory: str) -> List[str]:
    """``*.blackbox.jsonl`` under ``dir`` and ``dir/telemetry``,
    newest-first by mtime."""
    import glob as glob_mod

    out: List[str] = []
    for d in (directory, os.path.join(directory, "telemetry")):
        out.extend(glob_mod.glob(os.path.join(d, f"*{DUMP_SUFFIX}")))
    return sorted(out, key=lambda p: os.path.getmtime(p), reverse=True)


def load_dump(path: str) -> List[dict]:
    """Parse + schema-validate one dump.  A dump from a FUTURE schema
    refuses here with the standard "newer than this reader supports"
    SchemaError (exit 2 at the CLI)."""
    from gol_tpu import telemetry

    records = []
    with open(path) as f:
        for ln in f:
            ln = ln.strip()
            if not ln:
                continue
            rec = json.loads(ln)
            telemetry.validate_record(rec)
            records.append(rec)
    return records


def _journal_path(directory: str) -> Optional[str]:
    for d in (directory, os.path.dirname(os.path.abspath(directory))):
        p = os.path.join(d, "journal.jsonl")
        if os.path.exists(p):
            return p
    return None


def _fmt_t(t: float, t0: float) -> str:
    return f"t+{t - t0:8.3f}s"


def render_postmortem(directory: str, out=None) -> int:
    """The ``python -m gol_tpu.telemetry postmortem <dir>`` body: one
    page reconstructing the last seconds before death from the newest
    dump, cross-checked against the journal fold.  Exit 0 on a rendered
    verdict, 1 with no dump to read (a clean exit leaves none), 2 on a
    schema violation (raised as SchemaError, handled by the CLI)."""
    out = out or sys.stdout
    dumps = find_dumps(directory)
    if not dumps:
        print(
            f"postmortem: no *{DUMP_SUFFIX} dump under {directory} — "
            "either the process exited cleanly (a graceful drain leaves "
            "no dump) or the recorder was disabled (GOL_BLACKBOX=0)",
            file=out,
        )
        return 1
    path = dumps[0]
    records = load_dump(path)
    header = records[0] if records else {}
    cfg = header.get("config", {}) if header.get(
        "event"
    ) == "run_header" else {}
    body = records[1:] if cfg.get("driver") == "blackbox" else records
    t0 = body[0]["t"] if body else header.get("t", 0.0)
    t_end = body[-1]["t"] if body else t0

    print(f"postmortem: {path}", file=out)
    if len(dumps) > 1:
        print(
            f"  ({len(dumps) - 1} older dump(s) present — reading the "
            "newest)",
            file=out,
        )
    print(
        f"  reason {cfg.get('reason', '?')}   run {header.get('run_id')}"
        f"   pid {cfg.get('pid', '?')}   ring {len(body)}/"
        f"{cfg.get('capacity', '?')} records"
        f" ({cfg.get('dropped', 0)} older fell off)"
        f"   window {t_end - t0:.3f}s",
        file=out,
    )

    # -- final chunks -------------------------------------------------------
    chunks = [r for r in body if r["event"] == "chunk"]
    print("\nfinal chunks:", file=out)
    if chunks:
        for r in chunks[-3:]:
            print(
                f"  {_fmt_t(r['t'], t0)}  chunk {r['index']:>3} "
                f"(take {r['take']}) -> generation {r['generation']}, "
                f"wall {r['wall_s']:.4f}s",
                file=out,
            )
    else:
        print("  (none in the ring)", file=out)

    # -- open spans ---------------------------------------------------------
    spans = [r for r in body if r["event"] == "span"]
    closed = {
        r["trace_id"] for r in spans if r["span_id"] == "root"
    }
    open_traces: Dict[str, str] = {}
    for r in spans:
        if r["trace_id"] not in closed:
            open_traces[r["trace_id"]] = r["request_id"]
    print("open spans:", file=out)
    if open_traces:
        for tid, rid in sorted(open_traces.items()):
            names = [
                s["name"] for s in spans if s["trace_id"] == tid
            ]
            print(
                f"  {tid} (request {rid}): {', '.join(names)} — no root "
                "span committed (the request never finished)",
                file=out,
            )
    elif spans:
        print("  none — every recorded trace committed its root", file=out)
    else:
        print("  (no spans in the ring)", file=out)

    # -- last guard audit ---------------------------------------------------
    audits = [r for r in body if r["event"] == "guard_audit"]
    print("last guard audit:", file=out)
    if audits:
        a = audits[-1]
        print(
            f"  {_fmt_t(a['t'], t0)}  generation {a['generation']}: "
            f"{'ok' if a['ok'] else 'FAILED'}, population "
            f"{a['population']}, fingerprint {a['fingerprint']}",
            file=out,
        )
    else:
        print("  (none in the ring)", file=out)

    # -- journal cross-check ------------------------------------------------
    serve_recs = [r for r in body if r["event"] == "serve"]
    jpath = _journal_path(directory)
    open_ids: List[str] = []
    print("journal cross-check:", file=out)
    if jpath is None:
        print(
            "  no journal.jsonl next to the dump — skipping (a plain "
            "runtime dump has no admission intents)",
            file=out,
        )
    else:
        from gol_tpu.serve import journal as journal_mod

        entries, torn = journal_mod.replay(jpath)
        open_ids = sorted(
            rid
            for rid, e in entries.items()
            if e["status"] in ("admitted", "started")
        )
        print(
            f"  {jpath}: {len(entries)} request(s), "
            f"{len(open_ids)} open intent(s)"
            + (", torn tail healed" if torn else ""),
            file=out,
        )
        for rid in open_ids:
            last = [
                r for r in serve_recs if r["request_id"] == rid
            ]
            if last:
                r = last[-1]
                print(
                    f"  {rid}: journal {entries[rid]['status']}, last "
                    f"recorded serve event '{r['action']}' at "
                    f"{_fmt_t(r['t'], t0)} — consistent",
                    file=out,
                )
            else:
                print(
                    f"  {rid}: journal {entries[rid]['status']}, no "
                    "serve event in the ring (admitted before the "
                    "window)",
                    file=out,
                )

    # -- verdict ------------------------------------------------------------
    last_chunk = chunks[-1] if chunks else None
    where = (
        f"mid-run after chunk {last_chunk['index']} "
        f"(generation {last_chunk['generation']})"
        if last_chunk
        else "before the first recorded chunk"
    )
    if open_ids:
        print(
            f"\nverdict: died on {cfg.get('reason', '?')} {where}; "
            f"request(s) {', '.join(open_ids)} left open in the journal "
            "— a supervised replay will re-admit and complete "
            f"{'it' if len(open_ids) == 1 else 'them'} exactly once.",
            file=out,
        )
    elif jpath is not None:
        print(
            f"\nverdict: died on {cfg.get('reason', '?')} {where}; the "
            "journal is fully terminal — nothing to recover.",
            file=out,
        )
    else:
        print(
            f"\nverdict: died on {cfg.get('reason', '?')} {where}; no "
            "journal to recover from (re-run from the last checkpoint).",
            file=out,
        )
    return 0
