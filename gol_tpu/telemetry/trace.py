"""Request-scoped tracing: span trees, latency decomposition, Perfetto.

The serving tier interleaves many users' worlds in shared bucket groups,
live-reshards under device loss, and hedge-replays stragglers — so "why
was THIS request slow" is unanswerable from run-scoped telemetry alone.
This module is both sides of the answer (docs/OBSERVABILITY.md, "Request
tracing & SLOs"):

**Write side** — :class:`SpanRecorder` is the scheduler's host-side span
emitter.  One trace per request (``trace_id`` minted at admission and
carried on the journal's admit/complete records), one schema-v12
``span`` event per lifecycle phase:

- ``request`` — the root span (``span_id`` = ``"root"``), admission to
  terminal, stamped with the authoritative latency decomposition;
- ``queue`` — last-became-waiting to slot assignment (a crash-replayed
  request opens a fresh wait epoch: its pre-crash time is history, not
  queue wait);
- ``chunk`` — one per masked chunk the request rode, annotated with the
  device ``wall_s``, the ``co_resident`` count, and the chunk's
  roofline ``utilization`` (:func:`gol_tpu.utils.roofline.
  xla_flops_model` over the VPU peak);
- ``hedge`` / ``reshard`` / ``straggler`` / ``cancel`` / ``commit`` —
  event spans for the robustness plane's interventions.

All of it is host-side Python after the ``force_ready`` fences — the
trace-identity pin (tests/test_trace.py) proves tracing on/off compiles
byte-identical serve programs.

**Read side** — :func:`collect_traces` merges every rank file of every
run in a directory and regroups spans by ``trace_id`` (a crash-replayed
request's pre-crash spans live in the dead run's file; the trace_id
restored from the journal's admit record stitches them to the replay's
spans).  :func:`decompose` recomputes the five-phase latency
decomposition from the spans alone::

    queue         last-waiting -> slot assignment
    compute       this request's own share of each chunk wall (wall/co)
    interference  the co-residents' share (wall * (co-1)/co)
    hedge         straggler hedge-replay walls
    stall         everything else (scheduler overhead, guard replays,
                  reshard windows, the crash gap of a replayed request)
                  = e2e - queue - chunks - hedge, clamped at 0

The phases are disjoint wall intervals plus a residual, so they sum to
the end-to-end latency exactly (the acceptance bound is 1%; the
construction gives 0 up to rounding).  ``python -m gol_tpu.telemetry
trace <dir>`` renders the table, ``--perfetto out.json`` exports
Chrome-trace JSON (validated against the committed
``docs/schemas/perfetto_trace.schema.json``), and ``--slo`` evaluates
declarative objectives (:mod:`gol_tpu.telemetry.slo`) with burn rates.
"""

from __future__ import annotations

import json
import os
import time
from typing import Dict, List, Optional

#: The root span's well-known id: children of the request root carry
#: ``parent_id: "root"`` even when the root itself is emitted later (at
#: the terminal transition) or by a different process (crash replay).
ROOT_SPAN_ID = "root"

#: The decomposition phases, in render order.
PHASES = ("queue_s", "compute_s", "stall_s", "interference_s", "hedge_s")


def new_trace_id(request_id: str) -> str:
    """Mint one trace id at admission.  The request id alone is not
    enough: a caller may reuse an id across server lifetimes (the result
    files are GC'd), so the id carries a random suffix — while staying
    prefixed by the request id for human greppability."""
    return f"tr-{request_id}-{os.urandom(4).hex()}"


class SpanRecorder:
    """The serve scheduler's span emitter (host-side, post-fence).

    Routes through the same :class:`~gol_tpu.telemetry.EventLog` /
    :class:`~gol_tpu.telemetry.metrics.MetricsRegistry` pair as every
    other serve emission — one stream, never two sources of truth.
    ``epoch`` prefixes the generated span ids so the spans of a
    crash-replayed request (same trace, different process) can never
    collide.  With neither sink attached the recorder is "disabled":
    nothing reaches a file or the registry — but the span still rings
    in the always-on black box (v13, docs/OBSERVABILITY.md), because a
    postmortem's open-span census must exist for every process.  (With
    an EventLog attached, its own emit() taps the ring — no double
    record.)
    """

    def __init__(self, events=None, registry=None, epoch: str = "") -> None:
        self._events = events
        self._registry = registry
        self._epoch = epoch or f"p{os.getpid()}"
        self._seq = 0
        self.enabled = events is not None or registry is not None

    def span(
        self,
        trace_id: str,
        request_id: str,
        name: str,
        start_t: float,
        end_t: float,
        parent_id: Optional[str] = ROOT_SPAN_ID,
        span_id: Optional[str] = None,
        **attrs,
    ) -> Optional[str]:
        """Emit one span; returns its id."""
        if span_id is None:
            self._seq += 1
            span_id = f"{self._epoch}#{self._seq}"
        fields = dict(
            trace_id=trace_id,
            request_id=request_id,
            span_id=span_id,
            name=name,
            start_t=round(float(start_t), 6),
            end_t=round(float(end_t), 6),
        )
        if parent_id is not None:
            fields["parent_id"] = parent_id
        if attrs:
            fields["attrs"] = attrs
        if self._events is not None:
            self._events.span_event(**fields)
            return span_id
        from gol_tpu.telemetry import blackbox

        rec = {"event": "span", "t": time.time(), **fields}
        blackbox.record(rec)
        if self._registry is not None:
            self._registry.observe(rec)
        return span_id


# -- read side ---------------------------------------------------------------


class Trace:
    """One request's reconstructed span tree (spans may come from
    multiple rank files and multiple runs — crash replay)."""

    def __init__(self, trace_id: str) -> None:
        self.trace_id = trace_id
        self.spans: List[dict] = []

    @property
    def request_id(self) -> str:
        return self.spans[0]["request_id"] if self.spans else "?"

    def root(self) -> Optional[dict]:
        for s in self.spans:
            if s["span_id"] == ROOT_SPAN_ID:
                return s
        return None

    def named(self, name: str) -> List[dict]:
        return [s for s in self.spans if s["name"] == name]

    def children(self, parent_id: str) -> List[dict]:
        return [
            s for s in self.spans if s.get("parent_id") == parent_id
        ]

    def orphans(self) -> List[dict]:
        """Spans whose parent does not resolve within the trace — a
        complete tree has none (the acceptance criterion)."""
        ids = {s["span_id"] for s in self.spans}
        return [
            s
            for s in self.spans
            if s.get("parent_id") is not None
            and s["parent_id"] not in ids
        ]


def collect_traces(runs: Dict[str, "object"]) -> Dict[str, Trace]:
    """Regroup every run's ``span`` records by ``trace_id``.

    ``runs`` is :func:`gol_tpu.telemetry.summarize.load_dir` output.
    Deliberately crosses run boundaries: a crash-replayed request's
    pre-crash spans live in the dead run's rank file, and only the
    journal-restored trace_id joins them to the replaying run's spans.
    Spans are time-ordered within each trace.
    """
    traces: Dict[str, Trace] = {}
    for run in runs.values():
        for rank in sorted(run.ranks):
            for rec in run.records("span", rank=rank):
                tr = traces.setdefault(
                    rec["trace_id"], Trace(rec["trace_id"])
                )
                tr.spans.append(rec)
    for tr in traces.values():
        tr.spans.sort(key=lambda s: (s["start_t"], s["end_t"]))
    return traces


def _dur(span: dict) -> float:
    return max(span["end_t"] - span["start_t"], 0.0)


def decompose(trace: Trace) -> Optional[dict]:
    """The five-phase latency decomposition, recomputed from spans alone
    (the root span's stamped attrs are the writer's view; recomputing
    here keeps the reader honest about what the tree actually says).
    None without a root span — the request never reached a terminal."""
    root = trace.root()
    if root is None:
        return None
    e2e = _dur(root)
    queue = sum(_dur(s) for s in trace.named("queue"))
    chunk_wall = compute = 0.0
    for s in trace.named("chunk"):
        d = _dur(s)
        co = max(int((s.get("attrs") or {}).get("co_resident", 1)), 1)
        chunk_wall += d
        compute += d / co
    hedge = sum(_dur(s) for s in trace.named("hedge"))
    attrs = root.get("attrs") or {}
    return {
        "e2e_s": round(e2e, 6),
        "queue_s": round(queue, 6),
        "compute_s": round(compute, 6),
        "interference_s": round(chunk_wall - compute, 6),
        "hedge_s": round(hedge, 6),
        "stall_s": round(max(e2e - queue - chunk_wall - hedge, 0.0), 6),
        "status": attrs.get("status", "?"),
        "chunks": len(trace.named("chunk")),
        "commit_t": root["end_t"],
    }


def _percentile(sorted_vals: List[float], q: float) -> Optional[float]:
    if not sorted_vals:
        return None
    idx = min(
        len(sorted_vals) - 1, int(round(q * (len(sorted_vals) - 1)))
    )
    return sorted_vals[idx]


def decomposition_percentiles(
    decomps: List[dict], qs=(0.50, 0.99)
) -> Dict[str, dict]:
    """Per-phase percentiles over a trace set — the servebench row
    columns and the table footer share this."""
    out: Dict[str, dict] = {}
    for phase in ("e2e_s",) + PHASES:
        vals = sorted(
            d[phase] for d in decomps if isinstance(d.get(phase), float)
            or isinstance(d.get(phase), int)
        )
        out[phase] = {
            f"p{int(q * 100)}": _percentile(vals, q) for q in qs
        }
    return out


# -- rendering ---------------------------------------------------------------


def _render_tree(trace: Trace, out) -> None:
    root = trace.root()
    printed = set()

    def walk(span: dict, depth: int) -> None:
        printed.add(id(span))
        attrs = span.get("attrs") or {}
        detail = " ".join(
            f"{k}={v}" for k, v in sorted(attrs.items())
            if not isinstance(v, (dict, list))
        )
        print(
            f"    {'  ' * depth}{span['name']:<10} "
            f"{_dur(span) * 1e3:>9.3f}ms  {detail}",
            file=out,
        )
        for child in trace.children(span["span_id"]):
            walk(child, depth + 1)

    if root is not None:
        walk(root, 0)
    for span in trace.spans:  # orphans (should not exist) still show
        if id(span) not in printed:
            print(
                f"    ORPHAN {span['name']} span {span['span_id']} "
                f"(parent {span.get('parent_id')!r} unresolved)",
                file=out,
            )


def render_traces(
    traces: Dict[str, Trace], out, request: Optional[str] = None
) -> int:
    """The decomposition table (+ full tree with ``--request``).
    Returns the number of traces rendered."""
    selected = sorted(
        (
            tr for tr in traces.values()
            if request is None or tr.request_id == request
        ),
        key=lambda tr: tr.spans[0]["start_t"] if tr.spans else 0.0,
    )
    if not selected:
        what = f"request {request!r}" if request else "any request"
        print(f"trace: no spans for {what}", file=out)
        return 0
    print(
        "  request          status    e2e_s   queue_s compute_s "
        "  stall_s interf_s  hedge_s  chunks",
        file=out,
    )
    decomps = []
    for tr in selected:
        d = decompose(tr)
        if d is None:
            print(
                f"  {tr.request_id:<16} (no root span — request never "
                "reached a terminal; crashed mid-flight or still open)",
                file=out,
            )
            continue
        decomps.append(d)
        print(
            f"  {tr.request_id:<16} {d['status']:<7} {d['e2e_s']:>8.4f} "
            f"{d['queue_s']:>9.4f} {d['compute_s']:>9.4f} "
            f"{d['stall_s']:>9.4f} {d['interference_s']:>8.4f} "
            f"{d['hedge_s']:>8.4f}  {d['chunks']:>6}",
            file=out,
        )
        orphans = tr.orphans()
        if orphans:
            print(
                f"  ANOMALY: trace {tr.trace_id} has {len(orphans)} "
                "orphan span(s) — the tree is incomplete",
                file=out,
            )
        if request is not None:
            _render_tree(tr, out)
    if len(decomps) > 1:
        pct = decomposition_percentiles(decomps)
        parts = "  ".join(
            f"{phase[:-2]} p50 {pct[phase]['p50']:.4f}s "
            f"p99 {pct[phase]['p99']:.4f}s"
            for phase in ("e2e_s", "queue_s", "stall_s")
        )
        print(f"  ({len(decomps)} committed trace(s))  {parts}", file=out)
    return len(selected)


# -- Perfetto / Chrome-trace export ------------------------------------------


def perfetto_trace(traces: Dict[str, Trace]) -> dict:
    """Chrome-trace JSON (``chrome://tracing`` / ui.perfetto.dev): one
    thread track per trace, complete (``ph: "X"``) events in
    microseconds relative to the earliest span.  The shape is pinned by
    the committed ``docs/schemas/perfetto_trace.schema.json``."""
    events: List[dict] = []
    starts = [
        s["start_t"] for tr in traces.values() for s in tr.spans
    ]
    base = min(starts) if starts else 0.0
    for tid, trace_id in enumerate(sorted(traces), start=1):
        tr = traces[trace_id]
        events.append(
            {
                "name": "thread_name",
                "ph": "M",
                "pid": 1,
                "tid": tid,
                "args": {"name": f"{tr.request_id} ({trace_id})"},
            }
        )
        for s in tr.spans:
            args = {
                "trace_id": s["trace_id"],
                "request_id": s["request_id"],
                "span_id": s["span_id"],
            }
            if s.get("parent_id") is not None:
                args["parent_id"] = s["parent_id"]
            args.update(s.get("attrs") or {})
            events.append(
                {
                    "name": s["name"],
                    "cat": "serve",
                    "ph": "X",
                    "ts": round((s["start_t"] - base) * 1e6, 3),
                    "dur": round(_dur(s) * 1e6, 3),
                    "pid": 1,
                    "tid": tid,
                    "args": args,
                }
            )
    return {
        "displayTimeUnit": "ms",
        "otherData": {"schema": "gol-trace-perfetto/1"},
        "traceEvents": events,
    }


def validate_json_schema(doc, schema: dict, path: str = "$") -> List[str]:
    """A dependency-free JSON-Schema subset validator (``type``,
    ``required``, ``properties``, ``items``, ``enum``) — enough to give
    the committed export schema CI teeth without adding a package the
    container may not have.  Returns human-readable violations."""
    errors: List[str] = []
    types = {
        "object": dict,
        "array": list,
        "string": str,
        "number": (int, float),
        "integer": int,
        "boolean": bool,
        "null": type(None),
    }
    expected = schema.get("type")
    if expected is not None:
        py = types.get(expected)
        ok = isinstance(doc, py) if py is not None else True
        if expected in ("number", "integer") and isinstance(doc, bool):
            ok = False
        if not ok:
            errors.append(
                f"{path}: expected {expected}, got {type(doc).__name__}"
            )
            return errors  # children would only cascade the same error
    if "enum" in schema and doc not in schema["enum"]:
        errors.append(f"{path}: {doc!r} not in {schema['enum']}")
    if isinstance(doc, dict):
        for key in schema.get("required", ()):
            if key not in doc:
                errors.append(f"{path}: missing required key {key!r}")
        for key, sub in (schema.get("properties") or {}).items():
            if key in doc:
                errors.extend(
                    validate_json_schema(doc[key], sub, f"{path}.{key}")
                )
    if isinstance(doc, list) and "items" in schema:
        for i, item in enumerate(doc):
            errors.extend(
                validate_json_schema(
                    item, schema["items"], f"{path}[{i}]"
                )
            )
    return errors


def export_perfetto(
    traces: Dict[str, Trace], path: str, schema_path: Optional[str] = None
) -> dict:
    """Write the export; with ``schema_path``, self-validate first and
    raise :class:`~gol_tpu.telemetry.SchemaError` on any violation — an
    export that fails its own committed schema must never land."""
    from gol_tpu.telemetry import SchemaError

    doc = perfetto_trace(traces)
    if schema_path is not None:
        with open(schema_path) as f:
            schema = json.load(f)
        errors = validate_json_schema(doc, schema)
        if errors:
            raise SchemaError(
                f"perfetto export violates {schema_path}: "
                + "; ".join(errors[:5])
            )
    with open(path, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write("\n")
    return doc


# -- CLI ---------------------------------------------------------------------


def main_trace(
    directory: str,
    out,
    request: Optional[str] = None,
    perfetto: Optional[str] = None,
    slo_path: Optional[str] = None,
) -> int:
    """``python -m gol_tpu.telemetry trace <dir>`` — the routed body."""
    from gol_tpu.telemetry import slo as slo_mod
    from gol_tpu.telemetry import summarize as summ_mod

    runs = summ_mod.load_dir(directory)
    traces = collect_traces(runs)
    if not traces:
        print(
            f"trace: no span events in {directory} (schema v12 — the "
            "serve scheduler emits them when telemetry is attached)",
            file=out,
        )
        return 0
    n_runs = len(runs)
    n_files = sum(len(r.ranks) for r in runs.values())
    print(
        f"trace: {len(traces)} trace(s) from {n_files} rank file(s) "
        f"across {n_runs} run(s) in {directory}",
        file=out,
    )
    render_traces(traces, out, request=request)
    decomps = [
        d
        for d in (decompose(tr) for tr in traces.values())
        if d is not None
    ]
    if decomps and request is None:
        results = slo_mod.evaluate(slo_mod.load_slos(slo_path), decomps)
        slo_mod.render(results, out)
    if perfetto:
        doc = export_perfetto(traces, perfetto)
        print(
            f"trace: wrote {len(doc['traceEvents'])} Perfetto events "
            f"to {perfetto}",
            file=out,
        )
    return 0
