"""``python -m gol_tpu.telemetry watch <dir>`` — live run dashboard.

``summarize`` is the post-mortem; ``watch`` is the same telemetry read
*while the run is alive*.  The ROADMAP north star is pod-scale multi-hour
runs, and the failure mode this tool exists for is concrete: a 65536²
run extinguishes (or freezes into a fixpoint, or a rank starts reporting
a different world) three hours in, and nobody notices until the job's
wall-clock budget is gone.  ``watch`` tails the per-rank JSONL files the
run is already writing — read-only, no coordination with the run, works
from any machine that sees the telemetry directory — and renders one
terminal frame per poll:

- progress: chunks done, current generation, last chunk wall/rate and
  roofline fraction, chunk throughput over the recent window;
- population trend: latest value plus a sparkline of the ``stats``
  stream (the extinction/divergence signal at a glance);
- anomaly flags: **exactly** ``summarize``'s rules
  (:func:`~gol_tpu.telemetry.summarize.find_anomalies`, which includes
  the stats watchdogs) — the live view and the post-mortem can never
  disagree about what "unhealthy" means.

Tailing discipline: files are read incrementally from per-file offsets,
only up to the last complete line (the writer may be mid-record), and a
torn/invalid line is counted and skipped instead of killing the watcher
— a live tool that dies on one bad record is worse than none.  This is
deliberately *weaker* than ``summarize``'s exit-2 validation: the
post-mortem gate stays strict.
"""

from __future__ import annotations

import glob
import json
import os
import time
from typing import Dict, List, Optional

from gol_tpu.telemetry import SchemaError, validate_record
from gol_tpu.telemetry import summarize as summ_mod

_BARS = "▁▂▃▄▅▆▇█"


def sparkline(values: List[int], width: int = 40) -> str:
    """Population trend as unicode block bars (min..max normalized)."""
    vals = values[-width:]
    if not vals:
        return ""
    lo, hi = min(vals), max(vals)
    if hi == lo:
        return _BARS[3] * len(vals)
    return "".join(
        _BARS[int((v - lo) * (len(_BARS) - 1) / (hi - lo))] for v in vals
    )


class _Tail:
    """Incremental reader of one rank file (offset-tracked)."""

    def __init__(self, path: str, rank: int) -> None:
        self.path = path
        self.rank = rank
        self.offset = 0

    def read_new(self) -> tuple:
        """(new valid records, invalid-line count) since the last poll."""
        recs, bad = [], 0
        try:
            size = os.path.getsize(self.path)
        except OSError:
            return recs, bad
        if size <= self.offset:
            return recs, bad
        with open(self.path) as f:
            f.seek(self.offset)
            data = f.read(size - self.offset)
        cut = data.rfind("\n")
        if cut < 0:  # no complete new line yet
            return recs, bad
        self.offset += cut + 1
        for line in data[: cut + 1].splitlines():
            if not line.strip():
                continue
            try:
                rec = json.loads(line)
                validate_record(rec)
            except (json.JSONDecodeError, SchemaError):
                bad += 1
                continue
            recs.append(rec)
        return recs, bad


class Watcher:
    """Accumulated state of one telemetry directory across polls."""

    def __init__(self, directory: str, run_id: Optional[str] = None) -> None:
        self.directory = directory
        self.run_id = run_id
        self.tails: Dict[str, _Tail] = {}
        self.runs: Dict[str, summ_mod.Run] = {}
        self.invalid_lines = 0
        self.polls = 0

    def poll(self) -> None:
        self.polls += 1
        for path in sorted(
            glob.glob(os.path.join(self.directory, "*.jsonl"))
        ):
            m = summ_mod._RANK_RE.match(os.path.basename(path))
            if not m:
                continue
            run_id, rank = m.group("run"), int(m.group("rank"))
            if self.run_id is not None and run_id != self.run_id:
                continue
            tail = self.tails.get(path)
            if tail is None:
                tail = self.tails[path] = _Tail(path, rank)
            recs, bad = tail.read_new()
            self.invalid_lines += bad
            if recs:
                run = self.runs.setdefault(run_id, summ_mod.Run(run_id))
                run.ranks.setdefault(rank, []).extend(recs)

    def current_run(self) -> Optional[summ_mod.Run]:
        if not self.runs:
            return None
        return summ_mod.latest_run(self.runs)


def _fmt_bytes(n) -> str:
    if n is None:
        return "-"
    for unit in ("B", "KiB", "MiB", "GiB"):
        if n < 1024 or unit == "GiB":
            return f"{n:.0f}{unit}" if unit == "B" else f"{n:.1f}{unit}"
        n /= 1024
    return "-"  # pragma: no cover


def render_frame(w: Watcher, out) -> None:
    stamp = time.strftime("%H:%M:%S")
    run = w.current_run()
    if run is None:
        print(
            f"watch {w.directory} @ {stamp} (poll {w.polls}): waiting for "
            "telemetry files...",
            file=out,
        )
        return
    head = run.header or {}
    cfg = head.get("config", {})
    print(
        f"watch {w.directory} — run {run.run_id} @ {stamp} "
        f"(poll {w.polls})",
        file=out,
    )
    print(
        f"  ranks: {len(run.ranks)}/{head.get('process_count', '?')}  "
        f"backend: {head.get('backend', '?')}  "
        f"engine: {cfg.get('resolved_engine', cfg.get('engine', '?'))}  "
        f"mesh: {cfg.get('mesh')}",
        file=out,
    )

    rank0 = min(run.ranks, default=0)
    chunks = run.records("chunk", rank=rank0)
    if chunks:
        last = chunks[-1]
        line = (
            f"  progress: {len(chunks)} chunks, generation "
            f"{last['generation']}; last {last['wall_s']:.4f}s "
            f"{last['updates_per_sec']:.3e} updates/s"
        )
        if last.get("roofline_util") is not None:
            line += f"  roofline {summ_mod._fmt_util(last['roofline_util']).strip()}"
        print(line, file=out)
        recent = chunks[-10:]
        span = recent[-1]["t"] - recent[0]["t"]
        if len(recent) > 1 and span > 0:
            print(
                f"  rate: {60 * (len(recent) - 1) / span:.1f} chunks/min "
                f"over the last {len(recent)}",
                file=out,
            )
        spans = last.get("spans")
        if spans:
            # Schema-v6 span attribution, live: where the last chunk's
            # host time went, as per-phase shares (same numbers
            # summarize totals post-mortem).
            total = sum(spans.values())
            if total > 0:
                parts = "  ".join(
                    f"{phase} {100 * secs / total:.0f}%"
                    for phase, secs in sorted(
                        spans.items(), key=lambda kv: -kv[1]
                    )
                )
                print(f"  spans: {parts}", file=out)

    stats = run.records("stats", rank=rank0)
    if stats:
        pops = [s["population"] for s in stats]
        last = stats[-1]
        print(
            f"  population: {last['population']} {sparkline(pops)}  "
            f"(births {last['births']} deaths {last['deaths']} changed "
            f"{last['changed']} over the last chunk)",
            file=out,
        )

    mems = [
        c.get("memory")
        for c in run.records("compile", rank=rank0)
        if c.get("memory")
    ]
    if mems:
        peak = max(
            mems, key=lambda m: m.get("peak_bytes") or m.get("temp_bytes") or 0
        )
        print(
            f"  compiled memory: peak {_fmt_bytes(peak.get('peak_bytes'))} "
            f"arg {_fmt_bytes(peak.get('argument_bytes'))} "
            f"temp {_fmt_bytes(peak.get('temp_bytes'))}",
            file=out,
        )

    restarts = run.records("restart", rank=rank0)
    if restarts:
        print(
            f"  supervised: attempt {restarts[-1]['attempt']}", file=out
        )
    for r in run.records("resume", rank=rank0):
        print(
            f"  resumed from generation {r['generation']}"
            + ("  [FALLBACK]" if r["fallback"] else ""),
            file=out,
        )
    for r in run.records("preempt", rank=rank0):
        print(
            f"  PREEMPTED at generation {r['generation']} "
            f"({'checkpointed' if r['checkpointed'] else 'NO checkpoint'})",
            file=out,
        )

    if run.summary_record is not None:
        s = run.summary_record
        print(
            f"  FINISHED: {s['duration_s']:.4f}s, "
            f"{s['updates_per_sec']:.3e} updates/s",
            file=out,
        )
    if w.invalid_lines:
        print(f"  torn/invalid lines skipped: {w.invalid_lines}", file=out)
    for flag in summ_mod.find_anomalies(run):
        print(f"  ANOMALY: {flag}", file=out)
    # Restart storms span attempts (one run each): scan every run the
    # watcher has tailed, exactly summarize's directory-level rule.
    for flag in summ_mod.restart_storm_flags(w.runs):
        print(f"  ANOMALY: {flag}", file=out)


def watch(
    directory: str,
    out,
    run_id: Optional[str] = None,
    interval: float = 2.0,
    frames: Optional[int] = None,
    clear: Optional[bool] = None,
) -> int:
    """Poll-and-render loop.  ``frames=None`` runs until Ctrl-C;
    ``frames=1`` is the ``--once`` snapshot mode (tests, cron).
    ``clear`` defaults to "is a tty" — piped output gets appended frames
    instead of ANSI clears."""
    w = Watcher(directory, run_id=run_id)
    if clear is None:
        clear = bool(getattr(out, "isatty", lambda: False)())
    n = 0
    try:
        while True:
            w.poll()
            if clear:
                out.write("\x1b[2J\x1b[H")
            render_frame(w, out)
            try:
                out.flush()
            except OSError:  # pragma: no cover - closed pipe
                return 0
            n += 1
            if frames is not None and n >= frames:
                return 0
            time.sleep(interval)
    except KeyboardInterrupt:  # pragma: no cover - interactive exit
        return 0
