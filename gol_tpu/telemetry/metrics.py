"""Live Prometheus metrics endpoint (``--metrics-port``).

The JSONL stream is great for post-mortems and ``watch``, but a serving
tier (ROADMAP item 1) needs a *scrape surface*: a long-lived process a
Prometheus/alerting stack polls, not files someone tails.  This module
ships that surface now, fed by the exact same in-process event stream
the rank files get — :class:`MetricsRegistry` is an
:attr:`~gol_tpu.telemetry.EventLog.observer`, so the counters can never
disagree with the JSONL (one emission feeds both, asserted by the
reconciliation tests).

Everything is stdlib: :class:`MetricsServer` runs an
``http.server.ThreadingHTTPServer`` on a daemon thread (rank 0 only —
callers gate on ``jax.process_index()``), serving ``GET /metrics`` in
Prometheus text exposition format (version 0.0.4).  Port 0 binds an
ephemeral port (tests, parallel smokes); the bound port is printed and
available as :attr:`MetricsServer.port`.

Exported metrics (all ``gol_``-prefixed)::

    gol_generation                current generation (gauge)
    gol_chunks_total              executed chunks (counter)
    gol_generations_total         generations stepped (counter)
    gol_generations_per_sec       last chunk's take/wall (gauge)
    gol_updates_per_sec           last chunk's cell-updates/s (gauge)
    gol_population                last --stats population (gauge)
    gol_activity_fraction         last activity block's fraction (gauge)
    gol_checkpoints_total         snapshots written (counter)
    gol_checkpoint_seconds_total  fenced checkpoint seconds (counter)
    gol_span_seconds_total{phase} per-phase host span sums (counter, v6)
    gol_preempts_total / gol_resumes_total / gol_restart_attempt
    gol_run_finished              1 after the summary record (gauge)
    gol_updates_per_sec_final     the summary's headline (gauge)

Serving-tier metrics (schema v10, emitted only once a ``serve`` event
has been observed — docs/SERVING.md)::

    gol_serve_queue_depth             queued requests, all buckets (gauge)
    gol_serve_inflight_worlds         requests in batch slots (gauge)
    gol_serve_admitted_total          journaled admissions (counter)
    gol_serve_rejected_total          429/503 rejections (counter)
    gol_serve_completed_total         results written (counter)
    gol_serve_deadline_total          chunk-boundary cancels (counter)
    gol_serve_request_seconds_*       admit→complete latency histogram
    gol_serve_queue_wait_seconds_*    queue-wait histogram, fed from v12
                                      queue spans (one source of truth
                                      with `telemetry trace`)
    gol_serve_stall_fraction_*        stall/e2e histogram from the root
                                      spans' latency decomposition

Health-plane metrics (schema v11, emitted only once a ``health`` event
has been observed — docs/RESILIENCE.md, "Live elasticity")::

    gol_health_alive_devices          devices currently usable (gauge)
    gol_health_device_loss_total      device_loss verdicts (counter)
    gol_health_device_restore_total   device_restore verdicts (counter)
    gol_health_straggler_total        straggler verdicts (counter)
    gol_health_hedge_total            hedged chunk replays (counter)
    gol_health_live_reshards_total    in-process live reshards (counter)

Compile-cache metrics (schema v13, emitted only once a ``compile``
event has been observed — docs/OBSERVABILITY.md, "Compilation as a
first-class observable")::

    gol_compile_hits_total            compiles served from the
                                      persistent cache (counter)
    gol_compile_misses_total          cold compiles that wrote a new
                                      cache entry (counter)
    gol_compile_unknown_total         compiles with no cache attached
                                      (counter)
    gol_compile_seconds_total         lower+compile wall seconds (counter)
    gol_compile_storms_total          compile-storm detections (counter)

Telemetry self-observation (schema v13)::

    gol_telemetry_shed_total          records dropped by the EventLog's
                                      degrade plane, fed by the
                                      ``on_shed`` tap — the one channel
                                      that survives when the stream
                                      itself is shed (counter)

Purity: the registry runs strictly host-side inside the emission path,
which itself runs after the ``force_ready`` fences — the trace-identity
pin covers metrics-on vs -off (tests/test_metrics.py).
"""

from __future__ import annotations

import http.server
import threading
from typing import Dict, Optional


#: Upper bounds (seconds) of the serve request-latency histogram —
#: small-world simulation requests on a warm scheduler land in the
#: sub-second buckets; the top buckets catch queueing under load.
SERVE_LATENCY_BUCKETS = (0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 30.0)

#: Upper bounds of the stall-fraction histogram (stall seconds over
#: end-to-end seconds, from the root span's decomposition) — a healthy
#: tier sits in the low buckets; a tier losing time to guard replays,
#: reshards, or scheduler overhead climbs toward 1.0.
STALL_FRACTION_BUCKETS = (0.01, 0.05, 0.1, 0.25, 0.5, 0.75, 0.9)


class MetricsRegistry:
    """Event-stream consumer maintaining the scrape counters.

    Thread-safe: ``observe`` runs on the run loop's thread, ``render``
    on HTTP handler threads.
    """

    def __init__(self) -> None:
        from gol_tpu.analysis import lockwatch

        # Identity unless GOL_LOCKWATCH=1 (the runtime lock-order
        # recorder; see gol_tpu/analysis/lockwatch.py).
        self._lock = lockwatch.maybe_wrap(
            "MetricsRegistry._lock", threading.Lock()
        )
        self.generation = 0
        self.chunks_total = 0
        self.generations_total = 0
        self.generations_per_sec = 0.0
        self.updates_per_sec = 0.0
        self.population: Optional[int] = None
        self.activity_fraction: Optional[float] = None
        self.checkpoints_total = 0
        self.checkpoint_seconds_total = 0.0
        self.span_seconds: Dict[str, float] = {}
        self.preempts_total = 0
        self.resumes_total = 0
        self.restart_attempt = 0
        self.finished = False
        self.updates_per_sec_final: Optional[float] = None
        # Serving tier (schema v10): gauges track the scheduler's own
        # queue_depth/inflight stamps (authoritative — rejects and
        # requeues make pure event counting lie), counters count
        # lifecycle transitions, and the latency histogram buckets the
        # admit→complete seconds of every completed request.
        self.serve_seen = False
        self.serve_queue_depth = 0
        self.serve_inflight = 0
        self.serve_admitted_total = 0
        self.serve_rejected_total = 0
        self.serve_completed_total = 0
        self.serve_deadline_total = 0
        self.serve_latency_buckets: Dict[float, int] = {
            le: 0 for le in SERVE_LATENCY_BUCKETS
        }
        self.serve_latency_sum = 0.0
        self.serve_latency_count = 0
        # Request tracing (schema v12): both histograms are fed from
        # the SAME span records the JSONL stream carries — the scrape
        # surface and `telemetry trace` can never disagree about queue
        # wait or stall because one emission feeds both.
        self.span_seen = False
        self.serve_queue_wait_buckets: Dict[float, int] = {
            le: 0 for le in SERVE_LATENCY_BUCKETS
        }
        self.serve_queue_wait_sum = 0.0
        self.serve_queue_wait_count = 0
        self.serve_stall_buckets: Dict[float, int] = {
            le: 0 for le in STALL_FRACTION_BUCKETS
        }
        self.serve_stall_sum = 0.0
        self.serve_stall_count = 0
        self.health_seen = False
        self.health_alive_devices: Optional[int] = None
        self.health_device_loss_total = 0
        self.health_device_restore_total = 0
        self.health_straggler_total = 0
        self.health_hedge_total = 0
        self.health_reshards_total = 0
        # Compile-cache observability (schema v13): hit/miss is the
        # compile event's cache_hit stamp (absent = no persistent cache
        # attached, counted separately so a hit rate of "0/0" is
        # distinguishable from "cache off").
        self.compile_seen = False
        self.compile_hits_total = 0
        self.compile_misses_total = 0
        self.compile_unknown_total = 0
        self.compile_seconds_total = 0.0
        self.compile_storms_total = 0
        # Fleet front tier (schema v14): routing / handoff counters and
        # the membership gauges; ``fleet_epoch`` is the pinned routing
        # epoch, bumped only on membership events (docs/SERVING.md,
        # "The fleet").
        self.fleet_seen = False
        self.fleet_epoch = 0
        self.fleet_replicas_alive: Optional[int] = None
        self.fleet_routed_total = 0
        self.fleet_handoffs_total = 0
        self.fleet_replica_dead_total = 0
        self.fleet_replica_restore_total = 0
        # Telemetry self-observation: records the EventLog's degrade
        # plane dropped, fed by the on_shed tap rather than observe()
        # (a shed record never reaches the observer — that is the
        # point of shedding).
        self.shed_total = 0
        self.shed_by_event: Dict[str, int] = {}

    # -- write side (EventLog observer) -------------------------------------
    def observe(self, rec: dict) -> None:
        with self._lock:
            event = rec.get("event")
            if event == "chunk":
                self.chunks_total += 1
                self.generations_total += rec["take"]
                self.generation = max(self.generation, rec["generation"])
                self.updates_per_sec = rec["updates_per_sec"]
                if rec["wall_s"] > 0:
                    self.generations_per_sec = rec["take"] / rec["wall_s"]
                act = rec.get("activity")
                if act:
                    self.activity_fraction = act.get("active_fraction")
                for phase, secs in (rec.get("spans") or {}).items():
                    self.span_seconds[phase] = (
                        self.span_seconds.get(phase, 0.0) + secs
                    )
            elif event == "stats":
                self.population = rec["population"]
            elif event == "checkpoint":
                self.checkpoints_total += 1
                self.checkpoint_seconds_total += rec["wall_s"]
            elif event == "preempt":
                self.preempts_total += 1
            elif event == "resume":
                self.resumes_total += 1
            elif event == "restart":
                self.restart_attempt = rec["attempt"]
            elif event == "summary":
                self.finished = True
                self.updates_per_sec_final = rec["updates_per_sec"]
            elif event == "serve":
                self.serve_seen = True
                action = rec.get("action")
                if action in ("admit", "requeue"):
                    self.serve_admitted_total += 1
                elif action == "reject":
                    self.serve_rejected_total += 1
                elif action == "complete":
                    self.serve_completed_total += 1
                    lat = rec.get("latency_s")
                    if isinstance(lat, (int, float)):
                        self.serve_latency_sum += lat
                        self.serve_latency_count += 1
                        for le in self.serve_latency_buckets:
                            if lat <= le:
                                self.serve_latency_buckets[le] += 1
                elif action == "deadline":
                    self.serve_deadline_total += 1
                if "queue_depth" in rec:
                    self.serve_queue_depth = rec["queue_depth"]
                if "inflight" in rec:
                    self.serve_inflight = rec["inflight"]
            elif event == "span":
                name = rec.get("name")
                if name == "queue":
                    self.span_seen = True
                    wait = max(rec["end_t"] - rec["start_t"], 0.0)
                    self.serve_queue_wait_sum += wait
                    self.serve_queue_wait_count += 1
                    for le in self.serve_queue_wait_buckets:
                        if wait <= le:
                            self.serve_queue_wait_buckets[le] += 1
                elif name == "request":
                    attrs = rec.get("attrs") or {}
                    e2e = attrs.get("e2e_s")
                    stall = attrs.get("stall_s")
                    if isinstance(e2e, (int, float)) and e2e > 0 and (
                        isinstance(stall, (int, float))
                    ):
                        self.span_seen = True
                        frac = min(max(stall / e2e, 0.0), 1.0)
                        self.serve_stall_sum += frac
                        self.serve_stall_count += 1
                        for le in self.serve_stall_buckets:
                            if frac <= le:
                                self.serve_stall_buckets[le] += 1
            elif event == "health":
                self.health_seen = True
                verdict = rec.get("verdict")
                if verdict == "device_loss":
                    self.health_device_loss_total += 1
                elif verdict == "device_restore":
                    self.health_device_restore_total += 1
                elif verdict == "straggler":
                    self.health_straggler_total += 1
                elif verdict == "hedge":
                    self.health_hedge_total += 1
                if "alive" in rec:
                    self.health_alive_devices = rec["alive"]
            elif event == "compile":
                self.compile_seen = True
                hit = rec.get("cache_hit")
                if hit is True:
                    self.compile_hits_total += 1
                elif hit is False:
                    self.compile_misses_total += 1
                else:
                    self.compile_unknown_total += 1
                self.compile_seconds_total += (
                    rec.get("lower_s", 0.0) + rec.get("compile_s", 0.0)
                )
            elif event == "storm":
                self.compile_seen = True
                self.compile_storms_total += 1
            elif event == "fleet":
                self.fleet_seen = True
                action = rec.get("action")
                if action == "route":
                    self.fleet_routed_total += 1
                elif action == "handoff":
                    self.fleet_handoffs_total += 1
                elif action == "replica":
                    verdict = rec.get("verdict")
                    if verdict == "replica_dead":
                        self.fleet_replica_dead_total += 1
                    elif verdict == "replica_restore":
                        self.fleet_replica_restore_total += 1
                if "epoch" in rec:
                    self.fleet_epoch = max(self.fleet_epoch, rec["epoch"])
                if "alive" in rec:
                    self.fleet_replicas_alive = rec["alive"]
            elif event == "reshard":
                if self.health_seen:
                    # A reshard on a stream that already carries health
                    # verdicts is a LIVE reshard (the elasticity pair —
                    # docs/RESILIENCE.md); restart-path reshards happen
                    # in fresh processes with fresh registries.
                    self.health_reshards_total += 1

    def count_shed(self, rec: dict) -> None:
        """The :attr:`EventLog.on_shed` tap: a record the degrade plane
        dropped on the floor.  Deliberately NOT part of :meth:`observe`
        — shed records never reach the observer, so the scrape surface
        is the only place the loss is visible live."""
        with self._lock:
            self.shed_total += 1
            event = rec.get("event", "?")
            self.shed_by_event[event] = (
                self.shed_by_event.get(event, 0) + 1
            )

    # -- read side (HTTP) ----------------------------------------------------
    def render(self) -> str:
        """Prometheus text exposition format, one scrape's worth."""
        with self._lock:
            lines = []

            def metric(name, mtype, help_, value):
                lines.append(f"# HELP {name} {help_}")
                lines.append(f"# TYPE {name} {mtype}")
                lines.append(f"{name} {value}")

            metric(
                "gol_generation", "gauge",
                "Current generation of the run.", self.generation,
            )
            metric(
                "gol_chunks_total", "counter",
                "Executed chunks (guard replays included).",
                self.chunks_total,
            )
            metric(
                "gol_generations_total", "counter",
                "Generations stepped.", self.generations_total,
            )
            metric(
                "gol_generations_per_sec", "gauge",
                "Last chunk's generations per second.",
                self.generations_per_sec,
            )
            metric(
                "gol_updates_per_sec", "gauge",
                "Last chunk's cell updates per second.",
                self.updates_per_sec,
            )
            if self.population is not None:
                metric(
                    "gol_population", "gauge",
                    "Live cells at the last --stats chunk.",
                    self.population,
                )
            if self.activity_fraction is not None:
                metric(
                    "gol_activity_fraction", "gauge",
                    "Active tile-generation fraction of the last chunk.",
                    self.activity_fraction,
                )
            metric(
                "gol_checkpoints_total", "counter",
                "Snapshots written.", self.checkpoints_total,
            )
            metric(
                "gol_checkpoint_seconds_total", "counter",
                "Fenced checkpoint seconds.",
                self.checkpoint_seconds_total,
            )
            if self.span_seconds:
                lines.append(
                    "# HELP gol_span_seconds_total Host-side span seconds "
                    "per phase (schema v6)."
                )
                lines.append("# TYPE gol_span_seconds_total counter")
                for phase, secs in sorted(self.span_seconds.items()):
                    lines.append(
                        f'gol_span_seconds_total{{phase="{phase}"}} {secs}'
                    )
            metric(
                "gol_preempts_total", "counter",
                "Cooperative preemptions.", self.preempts_total,
            )
            metric(
                "gol_resumes_total", "counter",
                "Snapshot resumes.", self.resumes_total,
            )
            metric(
                "gol_restart_attempt", "gauge",
                "Supervised restart attempt number.", self.restart_attempt,
            )
            metric(
                "gol_run_finished", "gauge",
                "1 once the summary record landed.",
                1 if self.finished else 0,
            )
            if self.updates_per_sec_final is not None:
                metric(
                    "gol_updates_per_sec_final", "gauge",
                    "The run summary's headline cell-updates/s.",
                    self.updates_per_sec_final,
                )
            if self.serve_seen:
                metric(
                    "gol_serve_queue_depth", "gauge",
                    "Queued requests across all serve buckets (v10).",
                    self.serve_queue_depth,
                )
                metric(
                    "gol_serve_inflight_worlds", "gauge",
                    "Requests currently occupying batch slots.",
                    self.serve_inflight,
                )
                metric(
                    "gol_serve_admitted_total", "counter",
                    "Journaled admissions (requeues included).",
                    self.serve_admitted_total,
                )
                metric(
                    "gol_serve_rejected_total", "counter",
                    "Requests rejected by backpressure or shed.",
                    self.serve_rejected_total,
                )
                metric(
                    "gol_serve_completed_total", "counter",
                    "Requests completed with a written result.",
                    self.serve_completed_total,
                )
                metric(
                    "gol_serve_deadline_total", "counter",
                    "Requests cancelled at a chunk boundary by deadline.",
                    self.serve_deadline_total,
                )
                lines.append(
                    "# HELP gol_serve_request_seconds Admit-to-complete "
                    "request latency (v10)."
                )
                lines.append("# TYPE gol_serve_request_seconds histogram")
                for le, n in sorted(self.serve_latency_buckets.items()):
                    lines.append(
                        f'gol_serve_request_seconds_bucket{{le="{le}"}} {n}'
                    )
                lines.append(
                    'gol_serve_request_seconds_bucket{le="+Inf"} '
                    f"{self.serve_latency_count}"
                )
                lines.append(
                    f"gol_serve_request_seconds_sum {self.serve_latency_sum}"
                )
                lines.append(
                    f"gol_serve_request_seconds_count "
                    f"{self.serve_latency_count}"
                )
            if self.span_seen:
                lines.append(
                    "# HELP gol_serve_queue_wait_seconds Queue-wait "
                    "seconds from v12 queue spans."
                )
                lines.append(
                    "# TYPE gol_serve_queue_wait_seconds histogram"
                )
                for le, n in sorted(self.serve_queue_wait_buckets.items()):
                    lines.append(
                        f'gol_serve_queue_wait_seconds_bucket{{le="{le}"}}'
                        f" {n}"
                    )
                lines.append(
                    'gol_serve_queue_wait_seconds_bucket{le="+Inf"} '
                    f"{self.serve_queue_wait_count}"
                )
                lines.append(
                    "gol_serve_queue_wait_seconds_sum "
                    f"{self.serve_queue_wait_sum}"
                )
                lines.append(
                    "gol_serve_queue_wait_seconds_count "
                    f"{self.serve_queue_wait_count}"
                )
                lines.append(
                    "# HELP gol_serve_stall_fraction Stall share of "
                    "end-to-end latency from v12 root spans."
                )
                lines.append("# TYPE gol_serve_stall_fraction histogram")
                for le, n in sorted(self.serve_stall_buckets.items()):
                    lines.append(
                        f'gol_serve_stall_fraction_bucket{{le="{le}"}} {n}'
                    )
                lines.append(
                    'gol_serve_stall_fraction_bucket{le="+Inf"} '
                    f"{self.serve_stall_count}"
                )
                lines.append(
                    f"gol_serve_stall_fraction_sum {self.serve_stall_sum}"
                )
                lines.append(
                    f"gol_serve_stall_fraction_count "
                    f"{self.serve_stall_count}"
                )
            if self.health_seen:
                if self.health_alive_devices is not None:
                    metric(
                        "gol_health_alive_devices", "gauge",
                        "Devices the health plane considers usable (v11).",
                        self.health_alive_devices,
                    )
                metric(
                    "gol_health_device_loss_total", "counter",
                    "device_loss verdicts.", self.health_device_loss_total,
                )
                metric(
                    "gol_health_device_restore_total", "counter",
                    "device_restore verdicts.",
                    self.health_device_restore_total,
                )
                metric(
                    "gol_health_straggler_total", "counter",
                    "straggler verdicts from the chunk-wall watchdog.",
                    self.health_straggler_total,
                )
                metric(
                    "gol_health_hedge_total", "counter",
                    "hedged chunk replays triggered by stragglers.",
                    self.health_hedge_total,
                )
                metric(
                    "gol_health_live_reshards_total", "counter",
                    "In-process mesh reshards taken on health verdicts.",
                    self.health_reshards_total,
                )
            if self.compile_seen:
                metric(
                    "gol_compile_hits_total", "counter",
                    "Compiles served from the persistent cache (v13).",
                    self.compile_hits_total,
                )
                metric(
                    "gol_compile_misses_total", "counter",
                    "Cold compiles that wrote a new cache entry.",
                    self.compile_misses_total,
                )
                metric(
                    "gol_compile_unknown_total", "counter",
                    "Compiles with no persistent cache attached.",
                    self.compile_unknown_total,
                )
                metric(
                    "gol_compile_seconds_total", "counter",
                    "Wall seconds spent lowering and compiling.",
                    self.compile_seconds_total,
                )
                metric(
                    "gol_compile_storms_total", "counter",
                    "Compile storms detected by the scheduler.",
                    self.compile_storms_total,
                )
            if self.fleet_seen:
                metric(
                    "gol_fleet_epoch", "gauge",
                    "Current fleet routing epoch (v14).",
                    self.fleet_epoch,
                )
                if self.fleet_replicas_alive is not None:
                    metric(
                        "gol_fleet_replicas_alive", "gauge",
                        "Replicas the host monitor considers alive.",
                        self.fleet_replicas_alive,
                    )
                metric(
                    "gol_fleet_routed_total", "counter",
                    "Requests routed through the front tier.",
                    self.fleet_routed_total,
                )
                metric(
                    "gol_fleet_handoffs_total", "counter",
                    "Open intents migrated off a dead replica.",
                    self.fleet_handoffs_total,
                )
                metric(
                    "gol_fleet_replica_dead_total", "counter",
                    "replica_dead verdicts from the host monitor.",
                    self.fleet_replica_dead_total,
                )
                metric(
                    "gol_fleet_replica_restore_total", "counter",
                    "replica_restore verdicts (flap-damped).",
                    self.fleet_replica_restore_total,
                )
            if self.shed_total > 0:
                lines.append(
                    "# HELP gol_telemetry_shed_total Records dropped by "
                    "the telemetry degrade plane (v13)."
                )
                lines.append("# TYPE gol_telemetry_shed_total counter")
                for event, n in sorted(self.shed_by_event.items()):
                    lines.append(
                        f'gol_telemetry_shed_total{{event="{event}"}} {n}'
                    )
            return "\n".join(lines) + "\n"


CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


class _Handler(http.server.BaseHTTPRequestHandler):
    registry: MetricsRegistry  # set by MetricsServer on the class copy

    def do_GET(self):  # noqa: N802 - http.server API
        if self.path.rstrip("/") not in ("", "/metrics"):
            self.send_error(404, "only /metrics is served")
            return
        body = self.registry.render().encode()
        self.send_response(200)
        self.send_header("Content-Type", CONTENT_TYPE)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *args):  # silence per-request stderr noise
        pass


class MetricsServer:
    """Threaded HTTP server bound to 127.0.0.1, serving one registry."""

    def __init__(self, registry: MetricsRegistry, port: int) -> None:
        handler = type("BoundHandler", (_Handler,), {"registry": registry})
        self._httpd = http.server.ThreadingHTTPServer(
            ("127.0.0.1", port), handler
        )
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name="gol-metrics",
            daemon=True,
        )
        self._thread.start()

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5.0)


def serve_event_metrics(events, port: int, quiet: bool = False):
    """Attach a registry + HTTP server to an :class:`EventLog`.

    The server's lifetime is the event stream's: ``events.close()``
    shuts it down.  Returns the registry (callers keep it for
    reconciliation even after the server is gone).
    """
    registry = MetricsRegistry()
    server = MetricsServer(registry, port)
    events.observer = registry.observe
    events.on_shed = registry.count_shed
    events.metrics_server = server
    if not quiet:
        print(
            f"metrics: serving http://127.0.0.1:{server.port}/metrics",
            flush=True,
        )
    return registry, server
