"""``--stats`` mode: fuse simulation statistics onto each chunk program.

:func:`build_stats_evolver` wraps a runtime's evolve program for one
chunk size in the in-graph reductions of :mod:`gol_tpu.ops.stats` /
:mod:`gol_tpu.parallel.stats`, so the compiled chunk returns
``(new_board, stats)`` in a single launch — population, births, deaths,
changed cells and the four boundary-band populations, with no extra
device→host grid pull and no second program dispatch.  Tier dispatch
mirrors the runtime's engine resolution:

- dense / Pallas-dense → :func:`~gol_tpu.ops.stats.dense_chunk_stats`;
- bitpack / pallas_bitpack → :func:`~gol_tpu.ops.stats.
  packed_chunk_stats` (popcount over packed words);
- any mesh in explicit/overlap mode → the shard-map+psum wrapper
  (:func:`gol_tpu.parallel.stats.global_stats_fn`), so every rank of a
  multi-host run reports the replicated *global* value;
- ``shard_mode='auto'`` → plain global reductions: the auto-SPMD
  philosophy (annotate shardings, let XLA derive the collectives)
  applies to the stats exactly as it does to the halo exchange.

Two invariants, both pinned by tests/test_stats.py and the analysis
suite's stats-purity check:

- **stats off is byte-identical**: the wrapper is only ever built when
  ``GolRuntime.stats`` is set — the stats-off path does not pass
  through this module at all, so PR 2's trace-identity pin holds by
  construction;
- **stats on cannot alter evolution**: the wrapped program calls the
  *unmodified* engine program and reduces its input/output values; the
  final grid is bit-equal with stats on/off for every tier × mesh.

The one real cost: the chunk-start board must stay live for the
births/deaths diff, so the wrapper does not donate its input — stats
mode holds one extra board of HBM (documented in OBSERVABILITY.md).

:func:`compiled_memory` is the compile-time half of the observability
story: ``Compiled.memory_analysis()`` / ``cost_analysis()`` distilled to
a JSON-ready dict (peak HBM, argument/output/temp bytes, flops) that
rides on ``compile`` events — the compiled program's actual HBM
footprint is the scaling limit for whole-board runs, and until now the
repo recorded compile *durations* but never compile *sizes*.
"""

from __future__ import annotations

from typing import Optional

import jax

from gol_tpu.ops import stats as ops_stats
from gol_tpu.ops.stats import STATS_FIELDS, pair_value, stats_values  # noqa: F401

_PACKED_TIERS = ("bitpack", "pallas_bitpack")


def build_stats_evolver(rt, steps: int):
    """``(jitted_fn, dynamic_args)`` for one stats-mode chunk program.

    The full call is ``fn(board, *dynamic_args)`` returning
    ``(new_board, stats)`` where ``stats`` maps
    :data:`~gol_tpu.ops.stats.STATS_FIELDS` to ``uint32[2]`` split
    accumulators (:func:`~gol_tpu.ops.stats.stats_values` reassembles
    host ints).  Statics are closed over so the runtime's AOT
    lower-from-spec path works unchanged.
    """
    fn, dynamic, static = rt._evolve_fn(steps)
    band = max(1, rt.halo_depth)
    activity = rt._resolved == "activity"
    local = (
        ops_stats.packed_chunk_stats
        if rt._resolved in _PACKED_TIERS
        or (activity and getattr(rt, "_act_packed", False))
        else ops_stats.dense_chunk_stats
    )
    if rt.mesh is not None and rt.shard_mode != "auto":
        from gol_tpu.parallel import stats as par_stats

        stats_fn = par_stats.global_stats_fn(rt.mesh, local, band)
    elif rt.mesh is not None:
        # auto-SPMD: reductions on the logically-global sharded arrays;
        # XLA's partitioner derives the all-reduces, and the scalar
        # outputs replicate (the dense reducer — auto mode is dense-only).
        stats_fn = lambda p, n: ops_stats.dense_chunk_stats(p, n, band)
    else:
        stats_fn = lambda p, n: local(p, n, band)

    if activity:
        # The activity chunk program carries the changed mask and its
        # counters; stats ride as a fourth output.  The chunk-level
        # births/deaths diff still compares chunk-start vs chunk-end
        # boards (the per-generation changed *mask* is tile-granular —
        # it gates compute, the stats need exact cell counts), but both
        # consume the same flip planes (ops.stats.flip_planes_*): the
        # mask is a byproduct of the step, not a second diff pass.
        def evolve_with_stats(board, changed, *dyn):
            new, new_changed, act = fn(board, changed, *dyn, *static)
            return new, new_changed, act, stats_fn(board, new)

        return jax.jit(evolve_with_stats), dynamic

    def evolve_with_stats(board, *dyn):
        new = fn(board, *dyn, *static)
        return new, stats_fn(board, new)

    return jax.jit(evolve_with_stats), dynamic


def wrap_evolver_3d(fn, static):
    """3-D counterpart: wrap a volume evolver in the volume reductions.

    ``fn(vol, *static)`` is one of the cli3d engine programs; the
    wrapped program returns ``(new_vol, stats)`` with the four scalar
    fields of :func:`~gol_tpu.ops.stats.dense_chunk_stats3d`.  Sharded
    volumes reduce at the global-array level (XLA inserts the
    collectives; scalars replicate to every process).
    """

    def evolve_with_stats(vol):
        new = fn(vol, *static)
        return new, ops_stats.dense_chunk_stats3d(vol, new)

    return jax.jit(evolve_with_stats)


_MEMORY_FIELDS = {
    "peak_bytes": "peak_memory_in_bytes",
    "argument_bytes": "argument_size_in_bytes",
    "output_bytes": "output_size_in_bytes",
    "temp_bytes": "temp_size_in_bytes",
    "alias_bytes": "alias_size_in_bytes",
    "generated_code_bytes": "generated_code_size_in_bytes",
}


def compiled_memory(compiled) -> Optional[dict]:
    """``memory_analysis()``/``cost_analysis()`` as a JSON-ready dict.

    Returns ``None`` when the backend exposes neither (the event then
    simply carries no memory block).  Fields absent or non-numeric on a
    backend are omitted rather than zero-filled — a missing number and a
    measured zero are different claims.
    """
    out = {}
    try:
        ma = compiled.memory_analysis()
    except Exception:
        ma = None
    if ma is not None:
        for key, attr in _MEMORY_FIELDS.items():
            val = getattr(ma, attr, None)
            if isinstance(val, (int, float)):
                out[key] = int(val)
    try:
        ca = compiled.cost_analysis()
    except Exception:
        ca = None
    if isinstance(ca, list):
        ca = ca[0] if ca else None
    if ca:
        for key, name in (("flops", "flops"), ("bytes_accessed", "bytes accessed")):
            val = dict(ca).get(name)
            if isinstance(val, (int, float)):
                out[key] = float(val)
    return out or None
