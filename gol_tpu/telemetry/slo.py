"""Declarative SLOs over trace decompositions, with burn rates.

Throughput alone cannot gate a serving tier: a rate sweep can hold
achieved RPS while p99 commit latency or queue-wait fraction quietly
degrades.  This module turns the per-request latency decompositions of
:mod:`gol_tpu.telemetry.trace` into pass/fail objectives:

- an :class:`SLO` names a metric over the decomposition (``commit_latency_s``,
  ``queue_fraction``, ``stall_fraction``), a target, and an error
  *budget* — the tolerated fraction of requests allowed to violate it;
- :func:`evaluate` scores a trace set and reports, per objective, the
  observed percentile, the violating fraction, and the **burn rate** =
  violating-fraction / budget.  Burn rate ≤ 1.0 means the objective
  holds within budget; 2.0 means the budget is being consumed twice as
  fast as tolerated (the standard SRE alerting quantity).

Objectives are data, not code: ``--slo objectives.json`` loads a list of
``{"name", "metric", "target", "budget", "percentile"}`` objects, so a
deployment tightens its targets without touching the repo.  servebench
stamps the evaluation into SERVE_r*.json rows and the perf ledger gates
on the burn-rate columns (kind ``slo``, direction ``lower``) — the
regression gate fails when an SLO starts burning, not merely when
throughput drops.
"""

from __future__ import annotations

import json
from dataclasses import dataclass
from typing import Dict, List, Optional


@dataclass(frozen=True)
class SLO:
    """One objective: ``metric`` at ``percentile`` must be ≤ ``target``,
    and at most ``budget`` of requests may individually violate it."""

    name: str
    metric: str  # commit_latency_s | queue_fraction | stall_fraction
    target: float
    budget: float  # tolerated violating fraction, in (0, 1]
    percentile: float = 0.99


#: The defaults servebench and the CLI evaluate when no objectives file
#: is given: commit p99 under the scheduler's own deadline ceiling, and
#: queue wait below half of end-to-end for the typical request.
DEFAULT_SLOS = (
    SLO(name="commit_p99", metric="commit_latency_s", target=30.0,
        budget=0.01, percentile=0.99),
    SLO(name="queue_frac_p50", metric="queue_fraction", target=0.5,
        budget=0.05, percentile=0.50),
)


def load_slos(path: Optional[str] = None) -> List[SLO]:
    """Objectives from a JSON file (list of SLO-shaped objects), or the
    defaults.  Unknown keys are rejected by the dataclass constructor —
    a typo'd objective must fail loudly, not silently never gate."""
    if path is None:
        return list(DEFAULT_SLOS)
    with open(path) as f:
        raw = json.load(f)
    if not isinstance(raw, list):
        raise ValueError(f"{path}: expected a JSON list of objectives")
    return [SLO(**obj) for obj in raw]


def _metric_value(slo: SLO, d: dict) -> Optional[float]:
    e2e = d.get("e2e_s")
    if slo.metric == "commit_latency_s":
        return e2e
    if slo.metric == "queue_fraction":
        return d["queue_s"] / e2e if e2e else 0.0
    if slo.metric == "stall_fraction":
        return d["stall_s"] / e2e if e2e else 0.0
    raise ValueError(f"SLO {slo.name!r}: unknown metric {slo.metric!r}")


def _percentile(sorted_vals: List[float], q: float) -> float:
    idx = min(len(sorted_vals) - 1, int(round(q * (len(sorted_vals) - 1))))
    return sorted_vals[idx]


def evaluate(slos: List[SLO], decomps: List[dict]) -> List[dict]:
    """Score each objective over a decomposition set.  Returns one row
    per SLO; with an empty trace set every row is vacuously ok (burn
    rate 0 — nothing served, nothing burned)."""
    rows: List[dict] = []
    for slo in slos:
        vals = sorted(_metric_value(slo, d) for d in decomps)
        violations = sum(1 for v in vals if v > slo.target)
        frac = violations / len(vals) if vals else 0.0
        burn = frac / slo.budget
        rows.append(
            {
                "name": slo.name,
                "metric": slo.metric,
                "percentile": slo.percentile,
                "target": slo.target,
                "budget": slo.budget,
                "observed": (
                    round(_percentile(vals, slo.percentile), 6)
                    if vals else None
                ),
                "violations": violations,
                "requests": len(vals),
                "violation_fraction": round(frac, 6),
                "burn_rate": round(burn, 6),
                "ok": burn <= 1.0,
            }
        )
    return rows


def render(rows: List[dict], out) -> None:
    """The burn-rate table the trace CLI prints under the decomposition."""
    if not rows:
        return
    print(
        "  slo              metric            pXX  observed   target "
        " viol  burn  ok",
        file=out,
    )
    for r in rows:
        obs = f"{r['observed']:.4f}" if r["observed"] is not None else "-"
        print(
            f"  {r['name']:<16} {r['metric']:<16} "
            f"p{int(r['percentile'] * 100):<3} {obs:>8} "
            f"{r['target']:>8.3f} {r['violations']:>4}/{r['requests']:<4}"
            f"{r['burn_rate']:>6.2f}  {'yes' if r['ok'] else 'NO'}",
            file=out,
        )
