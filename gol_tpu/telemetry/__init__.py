"""Structured run telemetry: a schema-versioned JSONL event stream.

The reference's entire observability surface is one rank-0 wall-clock pair
and a single printed line (gol-main.c:124-125); our own ``Stopwatch``/
``RunReport`` still reduces a run to a handful of phase floats while the
loops compute — and then discard — per-chunk device timings, guard audit
scalars, checkpoint latencies, and compile times.  This package keeps all
of it:

- :class:`EventLog` appends schema-versioned JSONL records to
  ``<dir>/<run_id>.rank<k>.jsonl``.  Every process writes only its own
  file, so multi-host runs never gather (the same no-gather discipline as
  the sharded checkpoint format).  Record types: ``run_header``,
  ``compile``, ``chunk``, ``guard_audit``, ``checkpoint``, ``bench_row``,
  ``summary``, and (schema v2) ``stats`` — see ``REQUIRED_FIELDS``.
  Schema v4: batched runs (:mod:`gol_tpu.batch`) stamp ``chunk`` and
  ``compile`` events with a ``batch`` block (bucket shape, B, per-world
  throughput — docs/BATCHING.md).
  ``--stats`` chunks carry in-graph simulation reductions
  (:mod:`gol_tpu.telemetry.stats`), ``compile`` events the compiled
  program's memory footprint, and ``python -m gol_tpu.telemetry watch``
  tails a live run (:mod:`gol_tpu.telemetry.watch`).
- :func:`roofline_utilization` stamps each chunk with how far the run sits
  from the VPU roofline the repo already models
  (:func:`gol_tpu.utils.roofline.xla_flops_model` per-chip FLOPs over the
  ``V5E_VPU_LANE_OPS`` peak), so utilization cliffs are visible per chunk,
  not per run.
- :func:`step_annotation` / :func:`trace_annotation` wrap the host-side
  loop bodies in ``jax.profiler`` annotations so ``--profile`` traces are
  navigable (named chunks/audits/saves) instead of anonymous.
- ``python -m gol_tpu.telemetry summarize <dir>`` merges rank files,
  renders per-phase/per-chunk tables with the roofline column, and flags
  anomalies; ``diff`` compares two runs (:mod:`gol_tpu.telemetry.
  summarize`).
- Schema v6: ``chunk`` events carry a ``spans`` block decomposing the
  host wall between force_ready fences (:class:`SpanClock`); ``python
  -m gol_tpu.telemetry ledger ingest|show|check`` maintains the
  cross-run perf ledger (:mod:`gol_tpu.telemetry.ledger`,
  ``PERF_LEDGER.jsonl``) with a >N%-regression CI gate; and
  ``--metrics-port`` serves the same in-process event stream as
  Prometheus text (:mod:`gol_tpu.telemetry.metrics`).
- Schema v12: the serving tier threads a request-scoped span tree
  through every lifecycle phase (``span`` events keyed by ``trace_id``);
  ``python -m gol_tpu.telemetry trace <dir>`` rebuilds the trees, prints
  the queue/compute/stall/interference/hedge latency decomposition,
  exports Chrome-trace/Perfetto JSON (:mod:`gol_tpu.telemetry.trace`),
  and evaluates declarative SLOs with burn rates
  (:mod:`gol_tpu.telemetry.slo`) — docs/OBSERVABILITY.md, "Request
  tracing & SLOs".

Purity invariant: everything here is host-side Python running strictly
outside compiled code, after the ``force_ready`` fences — emission can
never change a traced program (pinned by the trace-identity test in
``tests/test_telemetry.py``; the static verifier's purity check would
catch any callback that leaked inside).
"""

from __future__ import annotations

import contextlib
import json
import os
import time
from typing import Dict, Optional

# The always-on flight recorder (docs/OBSERVABILITY.md, "Black box &
# postmortems").  Imported eagerly so the ring tap in :meth:`EventLog.
# emit` is one attribute lookup; blackbox itself imports this package
# only lazily (inside its dump path), so there is no cycle.
from gol_tpu.telemetry import blackbox

# Version 15 (this round) gives the out-of-core streaming tier
# (``--engine ooc``, docs/STREAMING.md) its observability block: each
# ``chunk`` event of an ooc run carries an ``ooc`` block — ``bands``
# (the plan's band count), ``visits`` (band visits actually computed),
# ``skipped_bands`` (dead bands that moved zero bytes), ``bytes_h2d`` /
# ``bytes_d2h`` (the chunk's transfer volume), ``overlap_fraction``
# (measured fraction of host-side transfer wall hidden behind an
# in-flight compute — the number the streaming tier's whole design
# optimizes), plus the timing internals ``sweeps`` / ``h2d_s`` /
# ``d2h_s`` / ``hidden_s``.  Additive like every block before it:
# readers that don't know ``ooc`` ignore it, and ``summarize`` renders
# an ooc column only when some run carries the block.
# Version 14 lifts observability from one server to the
# fleet (docs/SERVING.md, "The fleet"): a ``fleet`` record marks one
# decision of the replicated front tier (:mod:`gol_tpu.serve.fleet`) —
# ``action`` is one of ``route`` (a request was pinned to a replica by
# consistent hash of its bucket key; carries ``request_id``, ``bucket``,
# ``replica``, ``epoch``), ``epoch`` (the routing epoch advanced on a
# membership change; carries ``epoch``, ``members``, ``reason``),
# ``handoff`` (a dead/unreachable replica's open intent was re-admitted
# to a surviving replica under the SAME id; carries ``request_id``,
# ``src``, ``dst``, ``epoch``), or ``replica`` (a HostMonitor verdict —
# ``verdict`` is ``replica_dead`` / ``replica_slow`` /
# ``replica_restore``, with ``replica``, ``alive``, and for slow
# verdicts ``latency_s``/``baseline_s``).  The ``gol_fleet_*`` metrics
# are fed from the same records.
# Version 13 made the process a black box and compilation
# a first-class observable (docs/OBSERVABILITY.md, "Black box &
# postmortems"): :mod:`gol_tpu.telemetry.blackbox` keeps a bounded
# in-memory ring of the last N records — every event the v12 stream
# would carry, captured even when no EventLog file sink is attached —
# and dumps it as a ``<run_id>.blackbox.jsonl`` file on unhandled
# exception, fatal signal, fault-plane ``crash.exit``, or on demand
# (serve's ``GET /debug/blackbox``); ``python -m gol_tpu.telemetry
# postmortem <dir>`` cross-checks a dump against the journal fold and
# renders a one-page verdict.  On the stream itself, v13 adds a
# ``storm`` record (the scheduler's compile-storm detector: K cold
# compiles inside one admission window — ``kind``, ``count``,
# ``window_s``, ``threshold``), stamps ``compile`` events with the
# persistent-cache outcome (optional ``cache_hit`` / ``cache_key``,
# :mod:`gol_tpu.batch.cache`), and lets a shedding EventLog leave one
# last best-effort ``degraded`` record carrying the per-event-type
# ``dropped`` census (today shed records vanish silently).
# Version 12 added the request-scoped tracing plane
# (docs/OBSERVABILITY.md, "Request tracing & SLOs"): a ``span`` record is
# one node of a request's span tree — ``trace_id`` (minted at admission,
# carried on the journal's admit/complete records so crash-replayed
# requests keep their pre-crash spans), ``request_id``, ``span_id`` /
# ``parent_id`` (the root span's id is the literal ``"root"``), ``name``
# (``request`` / ``queue`` / ``chunk`` / ``hedge`` / ``reshard`` /
# ``straggler`` / ``cancel`` / ``commit``), wall-clock ``start_t`` /
# ``end_t``, and an ``attrs`` block (chunk spans: device ``wall_s``,
# ``co_resident`` count, roofline ``utilization``; the root span: the
# queue/compute/interference/hedge/stall latency decomposition).
# ``python -m gol_tpu.telemetry trace`` rebuilds the trees
# (:mod:`gol_tpu.telemetry.trace`); :mod:`gol_tpu.telemetry.slo`
# evaluates declarative objectives over them.
# Version 11 added the health-plane event
# (docs/RESILIENCE.md, "Live elasticity"): a ``health`` record marks one
# verdict of :mod:`gol_tpu.resilience.health` — ``verdict`` is one of
# ``device_loss`` / ``device_restore`` (a device left or rejoined the
# usable set; carries ``device`` and the surviving ``alive`` count),
# ``straggler`` (a chunk wall exceeded the fitted baseline; carries
# ``rank``, ``wall_s``, ``baseline_s``), or ``hedge`` (the serving
# tier's hedged replay of a straggler's chunk; carries the ``winner``
# and whether the fingerprints agreed).  A serving run that live-
# reshards on a verdict stamps the existing v7 ``reshard`` record next
# to it — the pair on one stream is the proof the in-process elasticity
# path ran instead of a supervisor restart.
# Version 10 added the serving-tier event
# (docs/SERVING.md): a ``serve`` record marks one request-lifecycle
# transition of the continuous-batching scheduler
# (:mod:`gol_tpu.serve`) — ``action`` is one of ``admit`` (journaled,
# committed), ``start`` (placed into a batch slot), ``complete``
# (result written; carries ``latency_s``), ``reject`` (backpressure 429
# or admissions shed), ``deadline`` (cancelled at a chunk boundary), or
# ``requeue`` (re-admitted from the journal after a restart) — with the
# ``request_id`` and, where known, the ``bucket`` and live
# ``queue_depth``/``inflight`` the metrics registry gauges ride on.
# Version 9 added the fault-plane events
# (docs/RESILIENCE.md): a ``fault`` record marks one fired injection of
# the declarative fault plan (``--fault-plan`` / ``GOL_FAULT_PLAN``,
# :mod:`gol_tpu.resilience.faults`) — the site name, the generation it
# fired at (null for sites with no generation context), and the spec
# detail — and a ``degraded`` record marks a containment decision
# (:mod:`gol_tpu.resilience.degrade`): a checkpoint write that needed
# retries, a disk-full run shedding telemetry before checkpoints, or a
# telemetry stream that dropped events after a write failure instead of
# killing the run.
# Version 8 added the halo-exchange chunk block
# (docs/OBSERVABILITY.md): ``chunk`` events of a sharded ring-engine run
# carry a ``halo`` block — ``{depth, mode, exchanges, band_bytes,
# exchange_share}`` — the exchange depth/mode the chunk program actually
# compiled (``--shard-mode pipeline`` double-buffers the k-deep band
# across chunks), how many ring exchanges the chunk performed, and the
# band traffic in bytes with its share of the chunk's total payload (a
# traffic share: device-side exchange *time* is not host-observable —
# halobench owns time attribution).
# Version 7 added the elastic-mesh event
# (docs/RESILIENCE.md): a ``reshard`` record marks a run whose board was
# repartitioned across topologies — a cross-topology resume or an
# in-flight ``--reshard-at`` stop — carrying the source/destination mesh
# layouts (``{kind, rows, cols}``), the validated move-table accounting
# (``dst_shards``, ``src_pieces``, ``moves``, ``seam_splits``,
# ``cells``), and ``bytes_moved`` (pieces travel bit-packed, 32
# cells/word).  Version 6 added host-side span attribution
# (docs/OBSERVABILITY.md): ``chunk`` events carry a ``spans`` block —
# ``{phase: seconds, ...}`` with phases like ``dispatch``, ``ready``,
# ``checkpoint``, ``telemetry``, ``preempt_poll`` (the guard adds
# ``audit``/``redundant``/``snapshot``/``restore``) — decomposing the
# host wall between consecutive force_ready fences, so "where does the
# non-MFU time go" is answerable from the JSONL alone.  Version 5 added
# the activity-gated tier fields (docs/SPARSE.md): ``chunk`` events of
# an ``--engine activity`` run carry an ``activity`` block — ``{tile,
# tiles, tile_gens, active_tile_gens, computed_tile_gens,
# skipped_tile_gens, fallback_gens, active_fraction}`` — the skip
# accounting of the sparse worklist.  Version 4 added the batched
# multi-world fields (docs/BATCHING.md): ``chunk`` and ``compile``
# events may carry a ``batch`` block — ``{bucket: [H, W], B, masked,
# engine, per_world_updates_per_sec}`` — and a batch run's
# ``run_header.config`` records the bucket layout.  Version 3 added the
# resilience events — ``preempt``, ``resume``, ``restart``
# (docs/RESILIENCE.md); version 2 the ``stats`` event type and optional
# ``memory``/``cost`` blocks on ``compile`` events.  Older streams stay
# readable: every v1-v13 event type and field survives unchanged, so
# consumers only ever *gain* records (back-compat pinned by the
# committed v1..v14 fixture tests).
# Streams NEWER than this reader refuse loudly: ``validate_record``
# raises a "schema vN is newer than this reader supports" SchemaError
# (exit 2 at the CLI) instead of letting a consumer KeyError on a field
# it has never heard of.
SCHEMA_VERSION = 15
SUPPORTED_SCHEMAS = (1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12, 13, 14, 15)

# Required fields per event type (beyond the envelope's "event" and "t").
# Extra fields are always allowed — the schema pins what consumers may
# rely on, not everything a producer may add.
REQUIRED_FIELDS: Dict[str, frozenset] = {
    # One per rank file, first record: who ran what, where.
    "run_header": frozenset(
        {"schema", "run_id", "process_index", "process_count", "config"}
    ),
    # One per distinct chunk size: AOT lowering + compile durations.
    # v13: optionally carries the persistent-cache outcome — ``cache_hit``
    # (bool; omitted when no cache directory is configured) and
    # ``cache_key`` (the new cache entry's key on a miss; null on a hit —
    # the key is stamped when the entry is written).
    "compile": frozenset({"chunk", "lower_s", "compile_s"}),
    # One per executed chunk (including guard replays): the device wall
    # time between force_ready fences, and the roofline fraction.
    "chunk": frozenset(
        {"index", "take", "generation", "wall_s", "updates_per_sec",
         "roofline_util"}
    ),
    # One per guard audit: the detection scalars the recovery decision
    # used (fingerprints compare across ranks and across runs).
    "guard_audit": frozenset(
        {"generation", "ok", "max_cell", "population", "fingerprint"}
    ),
    # One per snapshot: fenced (non-overlapped) seconds and payload size.
    "checkpoint": frozenset(
        {"generation", "wall_s", "bytes", "overlapped"}
    ),
    # One per bench-harness measurement row (halobench/scalebench).
    "bench_row": frozenset({"bench", "data"}),
    # v2: one per executed chunk in --stats mode — in-graph simulation
    # reductions (global values on sharded runs via psum, so every
    # rank's record must agree).  "faces" is a dict of boundary-band
    # populations ({top,bottom,left,right}; empty for 3-D volumes).
    "stats": frozenset(
        {"index", "take", "generation", "population", "births", "deaths",
         "changed", "faces"}
    ),
    # v3: cooperative preemption fired — the run stopped at a chunk
    # boundary (generation) and whether a resumable snapshot was written.
    "preempt": frozenset({"generation", "checkpointed"}),
    # v3: this run started from a snapshot.  ``fallback`` is True when a
    # newer candidate was skipped as corrupt/torn or another rank forced
    # an earlier generation (the auto-resume min agreement).
    "resume": frozenset({"generation", "path", "fallback"}),
    # v3: this run is attempt N (> 0) of a supervised job — the
    # restart-storm watchdog counts these across a directory's runs.
    "restart": frozenset({"attempt"}),
    # v7: this run's board was repartitioned across mesh topologies
    # (cross-topology resume or an in-flight --reshard-at stop).
    # src_mesh/dst_mesh are {kind, rows, cols} layout dicts; bytes_moved
    # is the bit-packed transport volume of the validated move table.
    "reshard": frozenset(
        {"generation", "src_mesh", "dst_mesh", "bytes_moved"}
    ),
    # v9: one fired injection of the declarative fault plan
    # (gol_tpu/resilience/faults.py).  ``generation`` is null for sites
    # with no generation context (e.g. a telemetry write fault).
    "fault": frozenset({"site", "generation"}),
    # v9: a containment decision fired (gol_tpu/resilience/degrade.py):
    # ``resource`` names what degraded (checkpoint / telemetry),
    # ``action`` what was done about it (retried / shed / dropped).
    "degraded": frozenset({"resource", "action"}),
    # v10: one request-lifecycle transition of the serving tier
    # (gol_tpu/serve, docs/SERVING.md): ``action`` is admit / start /
    # complete / reject / deadline / requeue; extras carry bucket,
    # queue_depth, inflight, latency_s, generation.
    "serve": frozenset({"action", "request_id"}),
    # v11: one health-plane verdict (gol_tpu/resilience/health.py):
    # ``verdict`` is device_loss / device_restore / straggler / hedge;
    # extras carry device, alive, rank, wall_s, baseline_s, winner.
    # ``generation`` is the chunk boundary that produced it.
    "health": frozenset({"verdict", "generation"}),
    # v12: one node of a request's span tree (gol_tpu/telemetry/trace.py,
    # docs/OBSERVABILITY.md "Request tracing & SLOs"): ``span_id`` /
    # optional ``parent_id`` link the tree (root id = "root"); ``name``
    # is request/queue/chunk/hedge/reshard/straggler/cancel/commit;
    # ``start_t``/``end_t`` are wall-clock; extras ride in ``attrs``.
    "span": frozenset(
        {"trace_id", "request_id", "span_id", "name", "start_t", "end_t"}
    ),
    # v13: the scheduler's compile-storm detector fired — ``count`` cold
    # compiles landed inside one ``window_s`` admission window (threshold
    # K); the admission throttle engages until the window drains
    # (docs/SERVING.md, "Compile storms").
    "storm": frozenset({"kind", "count", "window_s", "threshold"}),
    # v14: one decision of the replicated front tier
    # (gol_tpu/serve/fleet.py, docs/SERVING.md "The fleet"): ``action``
    # is route / epoch / handoff / replica (a HostMonitor verdict) /
    # drain; extras carry request_id, bucket, replica, epoch, members,
    # src, dst, verdict, alive, latency_s, baseline_s.
    "fleet": frozenset({"action"}),
    # One per run, last record: matches RunReport exactly.
    "summary": frozenset(
        {"duration_s", "cell_updates", "updates_per_sec", "phases"}
    ),
}

# Injection hook for the fault plane (gol_tpu/resilience/faults.py
# installs/clears it): called before every rank-file write, may raise
# ``OSError`` to simulate a failing telemetry disk.  ``None`` (no plan
# active) costs one attribute check per record.
_telemetry_write_hook = None


class SchemaError(ValueError):
    """A telemetry record violates the JSONL schema."""


def validate_record(rec: dict) -> None:
    """Raise :class:`SchemaError` unless ``rec`` is schema-valid.

    Shared by the writer (:meth:`EventLog.emit` — an invalid record is a
    bug at the emission site, not something to discover at read time) and
    the ``summarize`` reader (whose input may come from anywhere).
    """
    if not isinstance(rec, dict):
        raise SchemaError(f"record is {type(rec).__name__}, not an object")
    event = rec.get("event")
    if event not in REQUIRED_FIELDS:
        raise SchemaError(
            f"unknown event type {event!r}; expected one of "
            f"{sorted(REQUIRED_FIELDS)}"
        )
    if not isinstance(rec.get("t"), (int, float)):
        raise SchemaError(f"{event}: missing/non-numeric timestamp 't'")
    missing = REQUIRED_FIELDS[event] - rec.keys()
    if missing:
        raise SchemaError(f"{event}: missing fields {sorted(missing)}")
    if event == "run_header" and rec["schema"] not in SUPPORTED_SCHEMAS:
        schema = rec["schema"]
        if isinstance(schema, int) and schema > SCHEMA_VERSION:
            # A future-versioned stream: fail loudly and actionably
            # (exit 2 at the CLI), never a KeyError three consumers deep
            # on a field this reader has never heard of.
            raise SchemaError(
                f"run_header: schema v{schema} is newer than this reader "
                f"supports (max v{SCHEMA_VERSION}) — upgrade gol_tpu to "
                "read this stream"
            )
        raise SchemaError(
            f"run_header: schema {schema!r} not in supported "
            f"{SUPPORTED_SCHEMAS}"
        )


def rank_file(directory: str, run_id: str, process_index: int) -> str:
    return os.path.join(directory, f"{run_id}.rank{process_index}.jsonl")


class EventLog:
    """Per-process JSONL event writer.

    ``run_id`` defaults to a wall-clock stamp — fine for single-process
    runs; multi-host jobs should pass an explicit ``--run-id`` so every
    rank's file shares one prefix (processes start at slightly different
    times, and there is deliberately no cross-host coordination here).
    Lines are flushed per record so a killed run keeps everything emitted
    up to the failure — telemetry exists precisely for runs that die.

    ``observer`` (settable after construction) is called with every
    validated record *after* it is written — the in-process tap the live
    metrics endpoint feeds from (:mod:`gol_tpu.telemetry.metrics`); a
    :class:`~gol_tpu.telemetry.metrics.MetricsServer` assigned to
    ``metrics_server`` is shut down by :meth:`close`, so the scrape
    surface lives exactly as long as the event stream.
    """

    def __init__(
        self,
        directory: str,
        run_id: Optional[str] = None,
        process_index: Optional[int] = None,
    ) -> None:
        import jax

        self.directory = directory
        self.run_id = run_id or time.strftime("run-%Y%m%dT%H%M%S")
        self.process_index = (
            jax.process_index() if process_index is None else process_index
        )
        os.makedirs(directory, exist_ok=True)
        self.path = rank_file(directory, self.run_id, self.process_index)
        # Rerunning with an existing --run-id must not clobber (or, worse,
        # interleave with) the old stream: the previous rank file is
        # rotated to ``<path>.<n>`` — a suffix the ``summarize`` glob
        # (``*.jsonl``) deliberately does not match, so rotated history
        # stays on disk without polluting the merge.
        if os.path.exists(self.path):
            n = 1
            while os.path.exists(f"{self.path}.{n}"):
                n += 1
            os.replace(self.path, f"{self.path}.{n}")
        self._f = open(self.path, "w")
        self.observer = None
        self.metrics_server = None
        # IO containment (docs/RESILIENCE.md): a failing rank-file write
        # must never kill the run — telemetry is an observer, not a
        # participant.  After the first write failure (real ENOSPC/EIO or
        # an injected ``telemetry.write_error`` fault) the stream warns
        # once on stderr, stamps a best-effort ``degraded`` record, and
        # sheds: subsequent records are dropped from the file but still
        # reach ``observer`` (the live metrics endpoint stays truthful).
        # ``degraded`` records the shed decision for the caller/tests.
        self.degraded: Optional[dict] = None
        self._shed = False
        # Thread-safe shed request (the disk-full checkpoint policy runs
        # on the async writer thread; file writes stay on this one).
        self._pending_shed: Optional[tuple] = None
        # v13: drops are counted per event type while shedding (they
        # still reach observer/on_shed — only the file write is lost),
        # and close() leaves one last best-effort ``degraded`` record
        # carrying the census.  ``on_shed`` is the live-counter tap the
        # metrics registry attaches next to ``observer``
        # (``gol_telemetry_shed_total``).
        self.shed_counts: Dict[str, int] = {}
        self.on_shed = None

    # -- envelope -----------------------------------------------------------
    def emit(self, event: str, **fields) -> None:
        rec = {"event": event, "t": time.time(), **fields}
        validate_record(rec)
        # The black-box ring sees every validated record before the file
        # does — a crash between here and the write still leaves the
        # record recoverable from the dump (zero file IO on this tap).
        blackbox.record(rec)
        self._write_contained(rec)
        if self.observer is not None:
            self.observer(rec)

    def _write_contained(self, rec: dict) -> None:
        if self._pending_shed is not None:
            resource, reason = self._pending_shed
            self._pending_shed = None
            self._stamp_degraded(resource, "shed", reason)
        if self._shed:
            event = rec["event"]
            self.shed_counts[event] = self.shed_counts.get(event, 0) + 1
            if self.on_shed is not None:
                self.on_shed(rec)
            return
        try:
            if _telemetry_write_hook is not None:
                _telemetry_write_hook()
            self._f.write(json.dumps(rec, sort_keys=True) + "\n")
            self._f.flush()
        # ValueError covers a file handle that died under us ("I/O
        # operation on closed file") — same containment as a disk error.
        except (OSError, ValueError) as e:
            import sys

            print(
                f"gol: telemetry degraded: rank-file write failed ({e}); "
                "dropping further events (the run continues)",
                file=sys.stderr,
            )
            self._stamp_degraded("telemetry", "dropped", str(e))

    def request_shed(self, resource: str, reason: str) -> None:
        """Ask the stream to shed (stop file writes) at the next emit —
        callable from any thread; the degraded stamp and the shed itself
        happen on the emitting thread (file writes are single-threaded).
        The disk-full checkpoint policy uses this: telemetry is the
        first thing sacrificed when the disk fills."""
        if not self._shed and self._pending_shed is None:
            self._pending_shed = (resource, reason)

    def _stamp_degraded(self, resource: str, action: str, detail: str) -> None:
        """Best-effort final ``degraded`` record, then shed.  The stamp
        itself may fail (the disk that broke the stream is still broken)
        — then it survives only in :attr:`degraded` and the observer."""
        rec = {
            "event": "degraded",
            "t": time.time(),
            "resource": resource,
            "action": action,
            "detail": detail,
        }
        self.degraded = rec
        self._shed = True
        blackbox.record(rec)
        try:
            self._f.write(json.dumps(rec, sort_keys=True) + "\n")
            self._f.flush()
        except (OSError, ValueError):
            pass
        if self.observer is not None:
            self.observer(rec)

    def close(self) -> None:
        if self._shed and self.shed_counts:
            # One last best-effort stamp: how much the shed actually
            # cost, per event type.  A stream shed by *policy* (disk-full
            # checkpoint priority) still has a working telemetry disk,
            # so the census usually lands; a stream shed by a broken
            # disk loses it from the file but keeps it in the ring,
            # the observer, and :attr:`degraded`.
            rec = {
                "event": "degraded",
                "t": time.time(),
                "resource": "telemetry",
                "action": "shed_summary",
                "dropped": dict(self.shed_counts),
                "dropped_total": sum(self.shed_counts.values()),
            }
            self.degraded = rec
            blackbox.record(rec)
            try:
                self._f.write(json.dumps(rec, sort_keys=True) + "\n")
                self._f.flush()
            except (OSError, ValueError):
                pass
            if self.observer is not None:
                self.observer(rec)
        if not self._f.closed:
            self._f.close()
        if self.metrics_server is not None:
            self.metrics_server.close()
            self.metrics_server = None

    def __enter__(self) -> "EventLog":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- typed convenience emitters ----------------------------------------
    def run_header(self, config: dict) -> None:
        import jax

        self.emit(
            "run_header",
            schema=SCHEMA_VERSION,
            run_id=self.run_id,
            process_index=self.process_index,
            process_count=jax.process_count(),
            jax_version=jax.__version__,
            backend=jax.default_backend(),
            device_count=len(jax.devices()),
            config=config,
        )

    def compile_event(
        self,
        chunk: int,
        lower_s: float,
        compile_s: float,
        memory: Optional[dict] = None,
        batch: Optional[dict] = None,
        cache_hit: Optional[bool] = None,
        cache_key: Optional[str] = None,
    ) -> None:
        """``memory`` (v2, optional): the compiled program's
        ``memory_analysis``/``cost_analysis`` distillation
        (:func:`gol_tpu.telemetry.stats.compiled_memory`) — peak HBM and
        argument/output/temp bytes per chunk size, the actual scaling
        limit compile *durations* never showed.  ``batch`` (v4,
        optional): the bucket this program serves (``bucket`` shape,
        ``B``, ``masked``, resolved ``engine``) — a persistent-cache hit
        shows as near-zero ``compile_s`` on the same bucket block.
        ``cache_hit``/``cache_key`` (v13, optional): the persistent
        compilation cache's verdict for this program
        (:class:`gol_tpu.batch.cache.CompileCacheProbe`) — omitted
        entirely when no cache directory is configured."""
        extra = {} if memory is None else {"memory": memory}
        if batch is not None:
            extra["batch"] = batch
        if cache_hit is not None:
            extra["cache_hit"] = cache_hit
            extra["cache_key"] = cache_key
        self.emit(
            "compile", chunk=chunk, lower_s=lower_s, compile_s=compile_s,
            **extra,
        )

    def chunk_event(
        self,
        index: int,
        take: int,
        generation: int,
        wall_s: float,
        updates: int,
        roofline_util: Optional[float],
        **extra,
    ) -> None:
        self.emit(
            "chunk",
            index=index,
            take=take,
            generation=generation,
            wall_s=wall_s,
            updates_per_sec=(updates / wall_s) if wall_s > 0 else 0.0,
            roofline_util=roofline_util,
            **extra,
        )

    def guard_event(self, audit, **extra) -> None:
        """One :class:`gol_tpu.utils.guard.Audit`'s scalars.  ``extra``
        labels batched audits (``world``/``bucket``, schema v9)."""
        self.emit(
            "guard_audit",
            generation=audit.generation,
            ok=audit.ok,
            max_cell=audit.max_cell,
            population=audit.population,
            fingerprint=audit.fingerprint,
            redundant_fingerprint=audit.redundant_fingerprint,
            **extra,
        )

    def checkpoint_event(
        self,
        generation: int,
        wall_s: float,
        nbytes: int,
        overlapped: bool,
        **extra,
    ) -> None:
        self.emit(
            "checkpoint",
            generation=generation,
            wall_s=wall_s,
            bytes=nbytes,
            overlapped=overlapped,
            **extra,
        )

    def bench_row(self, bench: str, data: dict) -> None:
        self.emit("bench_row", bench=bench, data=data)

    def preempt_event(self, generation: int, checkpointed: bool) -> None:
        """Cooperative preemption at a chunk boundary (v3; exit 75)."""
        self.emit(
            "preempt", generation=generation, checkpointed=checkpointed
        )

    def resume_event(
        self,
        generation: int,
        path: Optional[str],
        fallback: bool,
        **extra,
    ) -> None:
        """This run started from a snapshot (v3).  ``extra`` may carry
        ``skipped`` — the corrupt/torn newer candidates the validated
        walk rejected."""
        self.emit(
            "resume",
            generation=generation,
            path=path,
            fallback=fallback,
            **extra,
        )

    def restart_event(self, attempt: int, **extra) -> None:
        """Supervised restart marker (v3): this run is attempt N > 0."""
        self.emit("restart", attempt=attempt, **extra)

    def reshard_event(
        self,
        generation: int,
        src_mesh: dict,
        dst_mesh: dict,
        bytes_moved: int,
        **extra,
    ) -> None:
        """Elastic-mesh repartition marker (v7).  ``extra`` carries the
        plan accounting (``dst_shards``/``src_pieces``/``moves``/
        ``seam_splits``/``cells``), the snapshot ``path``, and
        ``legacy_manifest`` (layout was inferred, not stamped)."""
        self.emit(
            "reshard",
            generation=generation,
            src_mesh=src_mesh,
            dst_mesh=dst_mesh,
            bytes_moved=bytes_moved,
            **extra,
        )

    def fault_event(
        self, site: str, generation: Optional[int], **extra
    ) -> None:
        """One fired fault-plan injection (v9).  ``extra`` carries the
        spec detail the plane recorded (row/col/value/world/path...)."""
        self.emit("fault", site=site, generation=generation, **extra)

    def degraded_event(
        self, resource: str, action: str, **extra
    ) -> None:
        """One containment decision (v9): ``resource`` checkpoint/
        telemetry, ``action`` retried/shed/dropped; ``extra`` carries
        generation/errno/attempt detail."""
        self.emit("degraded", resource=resource, action=action, **extra)

    def serve_event(self, action: str, request_id: str, **extra) -> None:
        """One serving-tier request transition (v10): ``action`` is
        admit/start/complete/reject/deadline/requeue; ``extra`` carries
        bucket/queue_depth/inflight/latency_s/generation detail
        (docs/SERVING.md)."""
        self.emit("serve", action=action, request_id=request_id, **extra)

    def storm_event(
        self,
        kind: str,
        count: int,
        window_s: float,
        threshold: int,
        **extra,
    ) -> None:
        """The compile-storm detector fired (v13): ``count`` cold
        compiles landed inside one ``window_s`` admission window
        against a threshold of K (docs/SERVING.md, "Compile storms");
        ``extra`` carries generation/throttled detail."""
        self.emit(
            "storm",
            kind=kind,
            count=count,
            window_s=window_s,
            threshold=threshold,
            **extra,
        )

    def health_event(
        self, verdict: str, generation: int, **extra
    ) -> None:
        """One health-plane verdict (v11): ``verdict`` is device_loss/
        device_restore/straggler/hedge; ``extra`` carries device/alive/
        rank/wall_s/baseline_s/winner detail (docs/RESILIENCE.md,
        "Live elasticity")."""
        self.emit(
            "health", verdict=verdict, generation=generation, **extra
        )

    def fleet_event(self, action: str, **extra) -> None:
        """One front-tier decision (v14): ``action`` is route / epoch /
        handoff / replica / drain; ``extra`` carries request_id, bucket,
        replica, epoch, members, src, dst, verdict, alive, latency_s,
        baseline_s (docs/SERVING.md, "The fleet")."""
        self.emit("fleet", action=action, **extra)

    def span_event(
        self,
        trace_id: str,
        request_id: str,
        span_id: str,
        name: str,
        start_t: float,
        end_t: float,
        **extra,
    ) -> None:
        """One node of a request's span tree (v12): ``extra`` carries
        ``parent_id`` (absent on the root span, whose id is the literal
        ``"root"``) and the ``attrs`` block — chunk spans stamp device
        ``wall_s``/``co_resident``/``utilization``, the root span the
        latency decomposition (docs/OBSERVABILITY.md, "Request tracing
        & SLOs")."""
        self.emit(
            "span",
            trace_id=trace_id,
            request_id=request_id,
            span_id=span_id,
            name=name,
            start_t=start_t,
            end_t=end_t,
            **extra,
        )

    def stats_event(
        self, index: int, take: int, generation: int, values: dict
    ) -> None:
        """One chunk's in-graph simulation stats (v2; ``--stats`` mode).

        ``values`` maps :data:`gol_tpu.ops.stats.STATS_FIELDS` (or the
        3-D subset) to host ints; ``face_*`` entries fold into the
        ``faces`` dict.
        """
        faces = {
            k[len("face_"):]: v
            for k, v in values.items()
            if k.startswith("face_")
        }
        self.emit(
            "stats",
            index=index,
            take=take,
            generation=generation,
            population=values["population"],
            births=values["births"],
            deaths=values["deaths"],
            changed=values["changed"],
            faces=faces,
        )

    def summary(self, report) -> None:
        """The final record, mirroring :class:`~gol_tpu.utils.timing.
        RunReport` field-for-field so the JSONL stream is a superset of
        the printed report."""
        self.emit(
            "summary",
            duration_s=report.duration_s,
            cell_updates=report.cell_updates,
            updates_per_sec=report.updates_per_sec,
            phases=dict(report.phases),
        )


class SpanClock:
    """Accumulates named host-side phase seconds for ``spans`` blocks (v6).

    The chunk loops time their host phases with this: ``add`` for spans
    whose endpoints were already captured (dispatch / block-until-ready),
    ``span`` as a context manager for everything else (checkpoint save,
    telemetry write, preempt poll, guard audit...).  ``take`` drains the
    accumulator into one dict — the ``spans`` block of the next emitted
    ``chunk`` event — so the block for chunk *i* decomposes the host wall
    between the (i-1)-th and i-th ``force_ready`` fences: chunk i's own
    dispatch/ready plus the boundary phases that ran after chunk i-1's
    event was written (chunk 0 carries dispatch/ready only).  Purely
    host-side — a traced program can never see it (the trace-identity
    pin covers the spans-on path).
    """

    def __init__(self) -> None:
        self._acc: Dict[str, float] = {}

    def add(self, phase: str, seconds: float) -> None:
        self._acc[phase] = self._acc.get(phase, 0.0) + seconds

    @contextlib.contextmanager
    def span(self, phase: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.add(phase, time.perf_counter() - t0)

    def take(self) -> Dict[str, float]:
        out, self._acc = self._acc, {}
        return out


def roofline_utilization(
    engine: str,
    shard_cells: int,
    take: int,
    halo_depth: int,
    sharded: bool,
    wall_s: float,
) -> Optional[float]:
    """Per-chip roofline fraction of one executed chunk.

    ``xla_flops_model`` predicts one shard's compiled FLOPs for the chunk
    (lane-ops for the packed tiers); dividing by the chunk's wall seconds
    gives a per-chip op rate, and the fraction is that rate over the
    ``V5E_VPU_LANE_OPS`` peak.  An *estimate with the model's own ±
    caveats* (see the roofline module docstring) meant to expose
    utilization cliffs between chunks/configs — off-TPU backends report
    tiny fractions, which is itself the honest answer.
    """
    from gol_tpu.utils import roofline

    if wall_s <= 0:
        return None
    flops = roofline.xla_flops_model(
        engine, shard_cells, take, halo_depth, sharded=sharded
    )
    return (flops / wall_s) / roofline.V5E_VPU_LANE_OPS


def roofline_utilization_3d(
    engine: str, shard_cells: int, take: int, wall_s: float
) -> Optional[float]:
    """3-D counterpart for the packed volume engines (flat per-word op
    model — the tiled kernels' recompute multipliers are attribution the
    bench harnesses own; ``None`` for the dense tier, whose 26-neighbor
    FLOP count has no audited model)."""
    from gol_tpu.utils import roofline

    if wall_s <= 0 or engine not in ("bitpack", "pallas"):
        return None
    lane_ops = (
        roofline.OPS_3D_WT_PER_WORD * (shard_cells / roofline.BITS) * take
    )
    return (lane_ops / wall_s) / roofline.V5E_VPU_LANE_OPS


# -- jax.profiler annotations (host-side; no-ops unless a trace is live) ----


def step_annotation(name: str, step: int):
    """``StepTraceAnnotation`` for one chunk — numbered steps in xprof."""
    import jax

    try:
        return jax.profiler.StepTraceAnnotation(name, step_num=step)
    except AttributeError:  # pragma: no cover - profiler API absent
        return contextlib.nullcontext()


def trace_annotation(name: str):
    """Named ``TraceAnnotation`` span (compile, audit, checkpoint save)."""
    import jax

    try:
        return jax.profiler.TraceAnnotation(name)
    except AttributeError:  # pragma: no cover - profiler API absent
        return contextlib.nullcontext()
