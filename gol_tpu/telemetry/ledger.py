"""Cross-run perf ledger: ``PERF_LEDGER.jsonl`` + the regression gate.

Every round of this repo committed its perf evidence as a bespoke JSON
artifact — ``BENCH_r*.json`` (a captured bench.py tail), ``BATCH_r*``
(amortization rows), ``SPARSE_r*`` (speedup rows), ``HALO_r*``
(exchange-vs-compute sections), ``SCALE_r*`` (weak-scaling sections),
``MULTICHIP_r*`` (equivalence dryruns) — and nothing ever ingested them,
so the project's perf *trajectory* was invisible and unguarded: a 30%
regression in the 1.93e12 cell-updates/s flagship would ship silently as
one more artifact nobody diffs.  This module normalizes all of them (and
any ``--telemetry`` run directory) into one append-only record stream:

    python -m gol_tpu.telemetry ledger ingest *.json runs/exp1
    python -m gol_tpu.telemetry ledger show
    python -m gol_tpu.telemetry ledger check          # the CI gate

**Record schema** (one JSON object per line, ``LEDGER_SCHEMA`` = 1)::

    {"ledger": 1, "fingerprint": "bench:tpu:flagship_2d:16384^2x10240",
     "kind": "throughput", "backend": "tpu", "value": 1.93e12,
     "unit": "cell-updates/s", "direction": "higher", "mfu": 0.663,
     "source": "BENCH_r05.json", "tool": "bench", "round": 5,
     "ingested_t": ..., "extra": {...}}

``fingerprint`` is the config identity records trend over — it embeds
the backend, geometry, engine and workload knobs, so only genuinely
comparable measurements ever compare.  ``kind`` partitions the gate:

- ``throughput`` — a headline rate (higher is better): gated;
- ``equivalence`` — a pass/fail dryrun (1.0/0.0): gated (a flip to
  0 is a 100% regression);
- ``latency`` — a percentile in seconds (lower is better): gated —
  servebench p99, and (v12) the queue-wait p99 from the trace plane's
  latency decomposition;
- ``slo`` — an objective's burn rate (lower is better, ≤1.0 = the
  error budget holds; :mod:`gol_tpu.telemetry.slo`): gated, so the
  serving tier is held to its objectives, not just its rate;
- ``attribution`` — a phase breakdown (halobench seconds/gen): shown in
  trends, **never gated** — its measurement method legitimately evolves
  between rounds (the r5 anti-DCE rework changed ``exchange_s``
  semantics), so gating it would punish better instrumentation.

**Regression policy** (``check``): for each fingerprint, the *newest*
record is compared against the *best* record of the same fingerprint;
the gate fails when the newest is worse by more than ``--threshold``
(default 20%).  Only TPU-backend records are gated by default — the
CPU-backend artifacts are "curve shape only" by their own notes
(shared-host walls are not reproducible numbers), so gating them would
make CI flaky; ``--backend all`` opts in.  Dips *between* best and
newest don't fail (history is history); only the current state of a
config can regress.

``ingest`` is idempotent: a record whose ``(source, fingerprint)`` is
already in the ledger is skipped, so re-running ingestion over the same
artifacts appends nothing.

``summarize --ledger PATH`` wires the same comparison into the anomaly
scan: a run whose summary throughput sits >threshold below the ledger's
best for its config fingerprint gets a ``regression`` ANOMALY line.
"""

from __future__ import annotations

import json
import os
import re
import sys
import time
from typing import Dict, List, Optional, Tuple

from gol_tpu.telemetry import SchemaError

LEDGER_SCHEMA = 1
DEFAULT_LEDGER = "PERF_LEDGER.jsonl"
DEFAULT_THRESHOLD = 0.20

# Satellite: the common header block new benchmark emitters stamp so
# future artifacts ingest without bespoke sniffing (the committed
# legacy files keep their adapters below).
ARTIFACT_SCHEMA = "gol-artifact/1"

_ROUND_RE = re.compile(r"_r(\d+)\.json$")


def artifact_header(tool: str) -> dict:
    """The common ``header`` block every benchmark emitter stamps.

    ``tool`` routes the ledger's ingestion (no filename sniffing),
    ``backend`` scopes the regression gate, ``argv`` makes the artifact
    re-runnable from its own bytes.
    """
    import jax

    return {
        "schema": ARTIFACT_SCHEMA,
        "tool": tool,
        "backend": jax.default_backend(),
        "argv": list(sys.argv),
    }


def _record(
    fingerprint: str,
    value: float,
    unit: str,
    source: str,
    tool: str,
    backend: str,
    kind: str = "throughput",
    direction: str = "higher",
    mfu: Optional[float] = None,
    round_: Optional[int] = None,
    extra: Optional[dict] = None,
) -> dict:
    rec = {
        "ledger": LEDGER_SCHEMA,
        "fingerprint": fingerprint,
        "kind": kind,
        "backend": backend,
        "value": float(value),
        "unit": unit,
        "direction": direction,
        "mfu": mfu,
        "source": source,
        "tool": tool,
        "round": round_,
        "ingested_t": time.time(),
    }
    if extra:
        rec["extra"] = extra
    return rec


def validate_ledger_record(rec: dict) -> None:
    if not isinstance(rec, dict):
        raise SchemaError(f"ledger record is {type(rec).__name__}, not an object")
    missing = {
        "ledger", "fingerprint", "kind", "backend", "value", "unit",
        "direction", "source", "tool",
    } - rec.keys()
    if missing:
        raise SchemaError(f"ledger record missing fields {sorted(missing)}")
    if rec["ledger"] != LEDGER_SCHEMA:
        raise SchemaError(
            f"ledger schema {rec['ledger']!r} != supported {LEDGER_SCHEMA}"
        )
    if rec["direction"] not in ("higher", "lower"):
        raise SchemaError(f"bad direction {rec['direction']!r}")
    if not isinstance(rec["value"], (int, float)):
        raise SchemaError(f"non-numeric value {rec['value']!r}")


def read_ledger(path: str) -> List[dict]:
    """Parse + validate every ledger line, preserving file order (the
    order IS the history — the newest record per fingerprint is the
    config's current state)."""
    records = []
    with open(path) as f:
        for lineno, line in enumerate(f, 1):
            if not line.strip():
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError as e:
                raise SchemaError(f"{path}:{lineno}: bad JSON ({e})")
            try:
                validate_ledger_record(rec)
            except SchemaError as e:
                raise SchemaError(f"{path}:{lineno}: {e}")
            records.append(rec)
    return records


def append_records(path: str, records: List[dict]) -> Tuple[int, int]:
    """Append records not already present; returns (added, skipped).

    Identity is ``(source, fingerprint)`` — one artifact contributes one
    record per config, so re-ingesting the same files is a no-op.
    """
    seen = set()
    if os.path.exists(path):
        for rec in read_ledger(path):
            seen.add((rec["source"], rec["fingerprint"]))
    added = skipped = 0
    with open(path, "a") as f:
        for rec in records:
            key = (rec["source"], rec["fingerprint"])
            if key in seen:
                skipped += 1
                continue
            seen.add(key)
            f.write(json.dumps(rec, sort_keys=True) + "\n")
            added += 1
    return added, skipped


# -- artifact adapters -------------------------------------------------------


def _artifact_round(path: str) -> Optional[int]:
    m = _ROUND_RE.search(os.path.basename(path))
    return int(m.group(1)) if m else None


def _bench_records(data: dict, source: str, round_: Optional[int]) -> List[dict]:
    """BENCH_r*.json: a captured ``bench.py`` stdout tail whose last JSON
    lines are the metric report (TPU runs by construction — bench.py is
    the flagship capture).  r4+ adds a ``claims`` list with per-claim
    roofline MFU and device fits."""
    out: List[dict] = []
    payloads: List[dict] = []
    claims: List[dict] = []
    tail = data.get("tail") or ""
    for line in tail.splitlines():
        line = line.strip()
        if line.startswith("{"):
            try:
                payloads.append(json.loads(line))
            except json.JSONDecodeError:
                continue
    if not payloads and isinstance(data.get("parsed"), dict):
        payloads = [data["parsed"]]
    if not payloads:
        # Salvage pass: the captured stdout tail can be truncated
        # mid-line (BENCH_r05's opening ``{"metric": ...`` is cut off),
        # but the embedded per-claim objects are intact — scan for any
        # decodable object and keep the metric/claim-shaped ones.
        dec = json.JSONDecoder()
        idx = 0
        while True:
            start = tail.find("{", idx)
            if start < 0:
                break
            try:
                obj, end = dec.raw_decode(tail[start:])
            except json.JSONDecodeError:
                idx = start + 1
                continue
            idx = start + end
            if not isinstance(obj, dict) or "value" not in obj:
                continue
            if "name" in obj and "metric" in obj:
                claims.append(obj)
            elif "metric" in obj:
                payloads.append(obj)

    def claim_record(claim: dict) -> dict:
        fit = claim.get("device_fit") or {}
        return _record(
            f"bench:tpu:{claim['name']}:{claim['metric']}",
            claim["value"],
            claim.get("unit", "cell-updates/s"),
            source,
            "bench",
            "tpu",
            mfu=(claim.get("roofline") or {}).get("mfu"),
            round_=round_,
            extra={
                "mfu_vpu_device": fit.get("mfu_vpu_device"),
                "overhead_s_per_invocation": fit.get(
                    "overhead_s_per_invocation"
                ),
            },
        )

    for p in payloads:
        if "metric" not in p or "value" not in p:
            continue
        mfu = (p.get("mfu_vpu") or {}).get("mfu")
        out.append(
            _record(
                f"bench:tpu:{p['metric']}",
                p["value"],
                p.get("unit", "cell-updates/s"),
                source,
                "bench",
                "tpu",
                mfu=mfu,
                round_=round_,
                extra={"vs_baseline": p.get("vs_baseline")},
            )
        )
        out.extend(claim_record(c) for c in p.get("claims") or [])
    out.extend(claim_record(c) for c in claims)
    return out


def _batch_records(data: dict, source: str, round_: Optional[int]) -> List[dict]:
    backend = data.get("backend", "cpu")
    size, iters = data.get("size"), data.get("iters")
    out = []
    for row in data.get("rows") or []:
        out.append(
            _record(
                f"batch:{backend}:{size}^2x{iters}:B{row['B']}:{row['engine']}",
                row["aggregate_updates_per_sec"],
                "cell-updates/s",
                source,
                "batchbench",
                backend,
                round_=round_,
                extra={
                    "per_world_speedup_vs_sequential": row.get(
                        "per_world_speedup_vs_sequential"
                    ),
                    "per_world_updates_per_sec": row.get(
                        "per_world_updates_per_sec"
                    ),
                },
            )
        )
    return out


def _sparse_records(data: dict, source: str, round_: Optional[int]) -> List[dict]:
    backend = data.get("backend", "cpu")
    size, iters, tile = data.get("size"), data.get("iters"), data.get("tile")
    cells = (size or 0) * (size or 0) * (iters or 0)
    out = []
    for row in data.get("rows") or []:
        gated = row.get("gated_wall_s") or 0.0
        out.append(
            _record(
                f"sparse:{backend}:{size}^2x{iters}:tile{tile}:"
                f"{row['scenario']}",
                cells / gated if gated > 0 else 0.0,
                "cell-updates/s",
                source,
                "sparsebench",
                backend,
                round_=round_,
                extra={
                    "speedup_vs_dense": row.get("speedup"),
                    "active_fraction": row.get("active_fraction"),
                    "fallback_gens": row.get("fallback_gens"),
                    "live_fraction_t0": row.get("live_fraction_t0"),
                },
            )
        )
    return out


def _halo_records(data: dict, source: str, round_: Optional[int]) -> List[dict]:
    """HALO_r*.json: named sections of seconds-per-generation columns
    (attribution captures and PR 9 depth-sweep rows alike), or the bare
    module emitter's single top-level row.  Attribution records — kept
    for the trend tables, never gated (the measurement method itself
    evolves between rounds)."""
    default_backend = (data.get("header") or {}).get("backend", "cpu")

    def one(section: str, body: dict) -> dict:
        backend = (
            "tpu" if section.startswith("tpu")
            else "cpu" if section.startswith("cpu")
            else default_backend
        )
        return _record(
            f"halo:{backend}:{section}",
            body["step_s"],
            "s/gen",
            source,
            "halobench",
            backend,
            kind="attribution",
            direction="lower",
            mfu=body.get("mfu"),
            round_=round_,
            extra={
                "exchange_s": body.get("exchange_s"),
                "stencil_s": body.get("stencil_s"),
                "exposed_exchange_s": body.get("exposed_exchange_s"),
                "halo_depth": body.get("halo_depth"),
                "shard_mode": body.get("shard_mode"),
            },
        )

    if "step_s" in data:  # the bare module emitter: one flat row
        mesh_s = "x".join(str(v) for v in (data.get("mesh") or {}).values())
        return [one(f"{data.get('engine', '?')}:mesh{mesh_s or '?'}", data)]
    return [
        one(section, body)
        for section, body in data.items()
        if isinstance(body, dict) and "step_s" in body
    ]


def _scale_records(data: dict, source: str, round_: Optional[int]) -> List[dict]:
    out = []
    sections = {
        section: body
        for section, body in data.items()
        if isinstance(body, dict) and "rows" in body
    }
    if not sections and isinstance(data.get("rows"), list):
        # The bare module emitter: one flat curve, self-describing.
        sections = {
            f"{data.get('engine', '?')}_{data.get('mesh_kind', '?')}": data
        }
    for section, body in sections.items():
        backend = body.get(
            "platform", "tpu" if section.startswith("tpu") else "cpu"
        )
        for row in body["rows"]:
            if "per_chip" not in row:
                continue
            out.append(
                _record(
                    f"scale:{backend}:{section}:{row['devices']}dev",
                    row["per_chip"],
                    "cell-updates/s/chip",
                    source,
                    "scalebench",
                    backend,
                    round_=round_,
                    extra={"efficiency": row.get("efficiency")},
                )
            )
    return out


def _multichip_records(
    data: dict, source: str, round_: Optional[int]
) -> List[dict]:
    ok = bool(data.get("ok")) and not data.get("skipped")
    return [
        _record(
            f"multichip:tpu:{data.get('n_devices')}dev",
            1.0 if ok else 0.0,
            "ok",
            source,
            "dryrun_multichip",
            "tpu",
            kind="equivalence",
            round_=round_,
        )
    ]


def _serve_records(data: dict, source: str, round_: Optional[int]) -> List[dict]:
    """SERVE_r*.json (servebench): each offered-rate row lands as one
    throughput record (achieved req/s, higher), one latency record
    (p99 seconds, lower), and — since schema v12 — one queue-wait p99
    latency record plus one ``slo`` record per evaluated objective
    (burn rate, lower: ≤1.0 means the error budget holds).  ``ledger
    check`` gates every non-attribution kind, so a burn-rate regression
    fails CI exactly the way a throughput drop does — the tier is gated
    on its objectives, not just its rate."""
    backend = (data.get("header") or {}).get("backend", "cpu")
    shape = (
        f"{data.get('size')}^2x{data.get('generations')}"
        f":s{data.get('slots')}q{data.get('queue_depth')}"
    )
    out = []
    for row in data.get("rows") or []:
        label = f"serve:{backend}:{shape}:offered{row['offered_rps']:g}"
        extra = {
            "completed": row.get("completed"),
            "rejected": row.get("rejected"),
            "p50_s": row.get("p50_s"),
            "max_queue_depth": row.get("max_queue_depth"),
        }
        out.append(
            _record(
                label,
                row["achieved_rps"],
                "req/s",
                source,
                "servebench",
                backend,
                round_=round_,
                extra=extra,
            )
        )
        if row.get("p99_s") is not None:
            out.append(
                _record(
                    label + ":p99",
                    row["p99_s"],
                    "s",
                    source,
                    "servebench",
                    backend,
                    kind="latency",
                    direction="lower",
                    round_=round_,
                    extra=extra,
                )
            )
        queue_p99 = ((row.get("decomposition") or {}).get("queue_s") or {}).get(
            "p99"
        )
        if queue_p99 is not None:
            out.append(
                _record(
                    label + ":queue_p99",
                    queue_p99,
                    "s",
                    source,
                    "servebench",
                    backend,
                    kind="latency",
                    direction="lower",
                    round_=round_,
                    extra=extra,
                )
            )
        for slo_row in row.get("slo") or []:
            out.append(
                _record(
                    label + f":slo_{slo_row['name']}",
                    slo_row["burn_rate"],
                    "burn-rate",
                    source,
                    "servebench",
                    backend,
                    kind="slo",
                    direction="lower",
                    round_=round_,
                    extra={
                        "target": slo_row.get("target"),
                        "observed": slo_row.get("observed"),
                        "violations": slo_row.get("violations"),
                        "requests": slo_row.get("requests"),
                    },
                )
            )
    return out


def _fleet_records(data: dict, source: str, round_: Optional[int]) -> List[dict]:
    """FLEET_r*.json (servebench --fleet): each replica-count row lands
    as one throughput record (achieved req/s at the fixed offered rate,
    higher) plus one p99 latency record (lower).  The mid-run-kill row
    is fingerprinted separately (``:kill``) — its p99 prices a
    journaled ownership handoff, and ``ledger check`` gates it like any
    other latency: a handoff that got slower fails CI."""
    backend = (data.get("header") or {}).get("backend", "cpu")
    shape = (
        f"g{data.get('generations')}:s{data.get('slots')}"
        f"q{data.get('queue_depth')}:r{data.get('offered_rps'):g}"
    )
    out = []
    for row in data.get("rows") or []:
        label = f"fleet:{backend}:{shape}:n{row['replicas']}"
        if row.get("kill"):
            label += ":kill"
        extra = {
            "completed": row.get("completed"),
            "rejected": row.get("rejected"),
            "p50_s": row.get("p50_s"),
            "handoffs": row.get("handoffs"),
            "kill": bool(row.get("kill")),
        }
        out.append(
            _record(
                label,
                row["achieved_rps"],
                "req/s",
                source,
                "fleetbench",
                backend,
                round_=round_,
                extra=extra,
            )
        )
        if row.get("p99_s") is not None:
            out.append(
                _record(
                    label + ":p99",
                    row["p99_s"],
                    "s",
                    source,
                    "fleetbench",
                    backend,
                    kind="latency",
                    direction="lower",
                    round_=round_,
                    extra=extra,
                )
            )
    return out


def _ooc_records(data: dict, source: str, round_: Optional[int]) -> List[dict]:
    """OOC_r*.json (oocbench): each scenario×budget row lands as one
    throughput record (streamed cell-updates/s, higher) plus one
    streaming-efficiency record (fraction of in-core throughput retained
    under that budget, higher).  Efficiency is the tier's headline — a
    board that no longer fits simply cannot run in-core, so the gate
    prices how much of the chip the rotation keeps busy, and a
    regression here means the overlap stopped hiding the transfers."""
    backend = data.get("backend", "cpu")
    shape = f"{data.get('height')}x{data.get('width')}"
    depth, iters = data.get("depth"), data.get("iters")
    out = []
    for row in data.get("rows") or []:
        ratio = row.get("board_over_budget")
        label = (
            f"ooc:{backend}:{shape}:k{depth}x{iters}:{row['scenario']}:"
            + (f"r{ratio:g}" if ratio else f"b{row.get('budget_bytes')}")
        )
        extra = {
            "bands": row.get("bands"),
            "skipped_bands": row.get("skipped_bands"),
            "overlap_fraction": row.get("overlap_fraction"),
            "bytes_h2d": row.get("bytes_h2d"),
            "bytes_d2h": row.get("bytes_d2h"),
            "bit_equal": row.get("bit_equal"),
        }
        out.append(
            _record(
                label,
                row["updates_per_sec"],
                "cell-updates/s",
                source,
                "oocbench",
                backend,
                round_=round_,
                extra=extra,
            )
        )
        if row.get("efficiency") is not None:
            out.append(
                _record(
                    label + ":efficiency",
                    row["efficiency"],
                    "fraction-of-incore",
                    source,
                    "oocbench",
                    backend,
                    kind="streaming-efficiency",
                    round_=round_,
                    extra=extra,
                )
            )
    return out


_TOOL_ADAPTERS = {
    "bench": _bench_records,
    "batchbench": _batch_records,
    "sparsebench": _sparse_records,
    "halobench": _halo_records,
    "scalebench": _scale_records,
    "dryrun_multichip": _multichip_records,
    "servebench": _serve_records,
    "fleetbench": _fleet_records,
    "oocbench": _ooc_records,
}


def normalize_artifact(path: str) -> List[dict]:
    """One committed artifact JSON -> ledger records.

    New-format artifacts route by their ``header.tool`` stamp
    (:func:`artifact_header`); the already-committed legacy files are
    sniffed by structure (``tail``+``cmd`` = bench wrapper, rows with
    ``B`` = batchbench, ...), with the filename as a tie-break.
    """
    with open(path) as f:
        try:
            data = json.load(f)
        except json.JSONDecodeError as e:
            raise SchemaError(f"{path}: bad JSON ({e})")
    if not isinstance(data, dict):
        raise SchemaError(f"{path}: artifact root is not an object")
    source = os.path.basename(path)
    round_ = _artifact_round(path)

    header = data.get("header")
    if isinstance(header, dict) and header.get("tool") in _TOOL_ADAPTERS:
        return _TOOL_ADAPTERS[header["tool"]](data, source, round_)

    rows = data.get("rows")
    first_row = rows[0] if isinstance(rows, list) and rows else {}
    if "tail" in data and "cmd" in data:
        return _bench_records(data, source, round_)
    if "n_devices" in data and "ok" in data:
        return _multichip_records(data, source, round_)
    if "scenario" in first_row:
        return _sparse_records(data, source, round_)
    if "B" in first_row:
        return _batch_records(data, source, round_)
    if any(
        isinstance(v, dict) and "step_s" in v for v in data.values()
    ):
        return _halo_records(data, source, round_)
    if any(
        isinstance(v, dict) and "rows" in v for v in data.values()
    ):
        return _scale_records(data, source, round_)
    raise SchemaError(
        f"{path}: unrecognized artifact format (no header.tool stamp and "
        "no known legacy structure)"
    )


# -- telemetry-directory adapter ---------------------------------------------


def run_fingerprint(run) -> Optional[str]:
    """The config fingerprint of one telemetry run (None without a
    header).  Embeds backend + driver + resolved engine + geometry +
    mesh, so only identically-configured runs ever trend together."""
    head = run.header
    if head is None:
        return None
    cfg = head.get("config") or {}
    backend = head.get("backend", "?")
    driver = cfg.get("driver", "?")
    engine = cfg.get("resolved_engine", cfg.get("engine", "?"))
    if driver == "3d":
        geom = f"{cfg.get('size')}^3"
    elif driver == "batch":
        geom = f"{cfg.get('num_worlds')}worlds"
    else:
        geom = f"{cfg.get('height')}x{cfg.get('width')}"
    mesh = cfg.get("mesh")
    mesh_s = "none" if not mesh else "x".join(
        str(v) for v in mesh.values()
    )
    return f"telemetry:{backend}:{driver}:{engine}:{geom}:mesh{mesh_s}"


def normalize_telemetry_dir(directory: str) -> List[dict]:
    """Every finished run in a ``--telemetry`` directory -> one ledger
    record (headline = the summary's updates/s; MFU = the run's best
    per-chunk roofline fraction)."""
    from gol_tpu.telemetry import summarize as summ_mod

    out = []
    for run_id, run in sorted(summ_mod.load_dir(directory).items()):
        summ = run.summary_record
        fp = run_fingerprint(run)
        if summ is None or fp is None:
            continue
        head = run.header or {}
        rank0 = min(run.ranks, default=0)
        utils = [
            c["roofline_util"]
            for c in run.records("chunk", rank=rank0)
            if c.get("roofline_util") is not None
        ]
        out.append(
            _record(
                fp,
                summ["updates_per_sec"],
                "cell-updates/s",
                f"{os.path.basename(os.path.abspath(directory))}/{run_id}",
                "telemetry",
                head.get("backend", "?"),
                mfu=max(utils) if utils else None,
                extra={"duration_s": summ["duration_s"]},
            )
        )
    return out


def normalize(path: str) -> List[dict]:
    if os.path.isdir(path):
        return normalize_telemetry_dir(path)
    return normalize_artifact(path)


# -- the gate ----------------------------------------------------------------


def _worse(newest: dict, best: dict, threshold: float) -> bool:
    if newest["direction"] == "lower":
        return newest["value"] > best["value"] * (1.0 + threshold)
    return newest["value"] < best["value"] * (1.0 - threshold)


def _best(records: List[dict]) -> dict:
    if records[0]["direction"] == "lower":
        return min(records, key=lambda r: r["value"])
    return max(records, key=lambda r: r["value"])


def check_records(
    records: List[dict],
    threshold: float = DEFAULT_THRESHOLD,
    backends: Tuple[str, ...] = ("tpu",),
) -> List[str]:
    """Regression messages (empty = gate passes).

    Gated population: ``throughput`` + ``equivalence`` records on the
    gated backends (TPU by default — CPU walls are curve shape only).
    Per fingerprint, newest (last in file order) vs best.
    """
    by_fp: Dict[str, List[dict]] = {}
    for rec in records:
        if rec["kind"] == "attribution":
            continue
        if "all" not in backends and rec["backend"] not in backends:
            continue
        by_fp.setdefault(rec["fingerprint"], []).append(rec)
    flags = []
    for fp, recs in sorted(by_fp.items()):
        newest, best = recs[-1], _best(recs)
        if _worse(newest, best, threshold):
            sign = "-" if newest["direction"] == "higher" else "+"
            # A best of 0 is legitimate for lower-is-better kinds (an
            # SLO burn rate that never burned): any nonzero newest is a
            # regression, but the relative-percent framing has no
            # denominator — report the absolute move instead.
            if best["value"]:
                delta = (
                    100.0 * abs(newest["value"] - best["value"])
                    / best["value"]
                )
                move = f"{sign}{delta:.1f}%"
            else:
                move = f"{sign}{abs(newest['value'] - best['value']):.4g}"
            flags.append(
                f"regression: {fp}: newest {newest['value']:.4g} "
                f"{newest['unit']} ({newest['source']}) is {move} "
                f"vs best {best['value']:.4g} ({best['source']}) — "
                f"threshold {100 * threshold:.0f}%"
            )
    return flags


def ledger_regression_flags(
    run,
    records: List[dict],
    threshold: float = DEFAULT_THRESHOLD,
) -> List[str]:
    """``summarize --ledger``'s anomaly: this run's summary throughput vs
    the ledger's best for the same config fingerprint (any backend — the
    fingerprint embeds it, so a CPU run only ever compares to CPU
    history)."""
    fp = run_fingerprint(run)
    summ = run.summary_record
    if fp is None or summ is None:
        return []
    history = [
        r for r in records
        if r["fingerprint"] == fp and r["kind"] == "throughput"
    ]
    if not history:
        return []
    best = _best(history)
    mine = summ["updates_per_sec"]
    if mine < best["value"] * (1.0 - threshold):
        pct = 100.0 * (best["value"] - mine) / best["value"]
        return [
            f"regression: run {run.run_id} at {mine:.4g} cell-updates/s is "
            f"-{pct:.1f}% vs the ledger best {best['value']:.4g} for "
            f"{fp} ({best['source']}) — threshold {100 * threshold:.0f}%"
        ]
    return []


# -- rendering ---------------------------------------------------------------


def show(records: List[dict], out) -> None:
    """Per-fingerprint trend tables, file order preserved."""
    by_fp: Dict[str, List[dict]] = {}
    for rec in records:
        by_fp.setdefault(rec["fingerprint"], []).append(rec)
    for fp, recs in sorted(by_fp.items()):
        best = _best(recs)
        print(f"{fp}  [{recs[0]['kind']}, {recs[0]['unit']}]", file=out)
        for rec in recs:
            if best["value"] != 0:
                rel = (rec["value"] - best["value"]) / best["value"]
                if rec["direction"] == "lower":
                    rel = -rel
                delta = "   best" if rec is best else f"{100 * rel:+6.1f}%"
            else:
                delta = "    n/a"
            mfu = "" if rec.get("mfu") is None else f"  mfu {rec['mfu']:.3f}"
            rnd = "" if rec.get("round") is None else f"r{rec['round']:02d}  "
            print(
                f"  {rnd}{rec['value']:>12.4g}  {delta}{mfu}  "
                f"<- {rec['source']}",
                file=out,
            )


# -- CLI ---------------------------------------------------------------------


def main_ingest(paths: List[str], ledger_path: str, out) -> int:
    records: List[dict] = []
    for path in paths:
        recs = normalize(path)
        print(f"{path}: {len(recs)} record(s)", file=out)
        records.extend(recs)
    added, skipped = append_records(ledger_path, records)
    print(
        f"{ledger_path}: appended {added} record(s), {skipped} already "
        "present",
        file=out,
    )
    return 0


def main_show(ledger_path: str, out) -> int:
    show(read_ledger(ledger_path), out)
    return 0


def main_check(
    ledger_path: str, threshold: float, backends: Tuple[str, ...], out
) -> int:
    records = read_ledger(ledger_path)
    flags = check_records(records, threshold=threshold, backends=backends)
    for flag in flags:
        print(f"REGRESSION: {flag}", file=out)
    if flags:
        return 1
    gated = sum(
        1 for r in records
        if r["kind"] != "attribution"
        and ("all" in backends or r["backend"] in backends)
    )
    print(
        f"ledger check: {gated} gated record(s) across "
        f"{len({r['fingerprint'] for r in records})} fingerprint(s), no "
        f"regression > {100 * threshold:.0f}%",
        file=out,
    )
    return 0
