"""`python -m gol_tpu <pattern> <size> <iterations> <threads> <on_off>`."""

import sys

from gol_tpu.cli import main

if __name__ == "__main__":
    sys.exit(main())
