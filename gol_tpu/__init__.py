"""tpu-life: a TPU-native Game of Life framework.

A brand-new, TPU-first rebuild of the capabilities of the MPI+CUDA reference
(shoron-dutta/Game-of-Life---MPI-CUDA): torus Game of Life, five seed
patterns, spatial domain decomposition with ring halo exchange, the exact
five-argument CLI surface, per-rank world dumps, and duration/cell-update
reporting — implemented on JAX/XLA with `shard_map` + `lax.ppermute` over a
device mesh instead of MPI point-to-point, XLA stencils (with a Pallas fused
fast path and a bit-packed SWAR perf tier) instead of a CUDA kernel, and a
pure-functional double buffer via XLA input/output aliasing instead of
pointer swaps.

Layer map (mirrors SURVEY.md §1 of the reference):
  L1 CLI/driver            -> gol_tpu.cli, gol_tpu.cli3d (+ native/gol_driver.cpp)
  L2 distributed halo comm -> gol_tpu.parallel.halo (lax.ppermute rings);
                              multi-host via gol_tpu.parallel.multihost
  L3 step orchestration    -> gol_tpu.runtime / parallel.{sharded,packed,
                              ruled,sharded3d} engines (+ guarded loop in
                              utils.guard)
  L4 device memory/runtime -> XLA HBM arrays + donation (no explicit mgmt)
  L5 compute kernel        -> gol_tpu.ops.{stencil,bitlife,rules,life3d,
                              bitlife3d} with fused Pallas tiers
                              (pallas_step, pallas_bitlife, pallas_bitlife3d)
  L6 init patterns         -> gol_tpu.models.patterns (0-4 reference, 5-7 added)
  L7 observability/output  -> gol_tpu.utils.{io,timing,halobench,scalebench,
                              checkpoint,guard}
"""

__version__ = "0.1.0"

from gol_tpu.models.state import GolState
from gol_tpu.models import patterns
from gol_tpu.ops import stencil

__all__ = ["GolState", "patterns", "stencil", "__version__"]
