"""tpu-life: a TPU-native Game of Life framework.

A brand-new, TPU-first rebuild of the capabilities of the MPI+CUDA reference
(shoron-dutta/Game-of-Life---MPI-CUDA): torus Game of Life, five seed
patterns, spatial domain decomposition with ring halo exchange, the exact
five-argument CLI surface, per-rank world dumps, and duration/cell-update
reporting — implemented on JAX/XLA with `shard_map` + `lax.ppermute` over a
device mesh instead of MPI point-to-point, XLA stencils (with a Pallas fused
fast path and a bit-packed SWAR perf tier) instead of a CUDA kernel, and a
pure-functional double buffer via XLA input/output aliasing instead of
pointer swaps.

Layer map (mirrors SURVEY.md §1 of the reference):
  L1 CLI/driver            -> gol_tpu.cli (+ native/gol_driver.cpp)
  L2 distributed halo comm -> gol_tpu.parallel.halo (lax.ppermute rings)
  L3 step orchestration    -> gol_tpu.parallel.engine / gol_tpu.ops.stencil.run
  L4 device memory/runtime -> XLA HBM arrays + donation (no explicit mgmt)
  L5 compute kernel        -> gol_tpu.ops.stencil / ops.pallas_step / ops.bitlife
  L6 init patterns         -> gol_tpu.models.patterns
  L7 observability/output  -> gol_tpu.utils.io / utils.timing
"""

__version__ = "0.1.0"

from gol_tpu.models.state import GolState
from gol_tpu.models import patterns
from gol_tpu.ops import stencil

__all__ = ["GolState", "patterns", "stencil", "__version__"]
