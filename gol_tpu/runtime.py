"""Runtime orchestration: engine selection, compile/evolve phases, snapshots.

This is the TPU-native stand-in for the reference's L3/L4 layers:
``gol_initMaster``'s device binding + pattern dispatch
(gol-with-cuda.cu:286-328) becomes pattern init + ``jax.device_put``;
``gol_kernelLaunch``'s per-step launch/sync/swap (gol-with-cuda.cu:264-284)
becomes one ahead-of-time-compiled program holding the entire generation
loop; ``cuda_finalize`` (gol-with-cuda.cu:334-339) has no equivalent —
arrays are garbage-collected.

Every distinct chunk size is compiled *before* the timed loop starts and
checkpoint I/O happens outside it, so the reported ``TOTAL DURATION``
measures device execution only — matching what the reference measured (its
loop wall-clock, with the CUDA kernel already compiled by nvcc and no
mid-loop persistence).
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

from gol_tpu.models import patterns
from gol_tpu.models.state import Geometry, GolState
from gol_tpu.parallel import engine as engine_mod
from gol_tpu.parallel import mesh as mesh_mod
from gol_tpu.parallel import packed as packed_mod
from gol_tpu.parallel import sharded as sharded_mod
from gol_tpu.utils import checkpoint as ckpt_mod
from gol_tpu.utils.timing import RunReport, Stopwatch, force_ready, maybe_profile

ENGINES = (
    "auto", "dense", "bitpack", "pallas", "pallas_bitpack", "activity", "ooc"
)
MESH_CHOICES = ("none", "1d", "2d")


def build_mesh(
    kind: str,
    shape: Optional[Tuple[int, int]] = None,
    allow_shrink: bool = False,
) -> Optional[Mesh]:
    """CLI-level mesh selection: shard over all visible devices.

    With ``shape`` and ``allow_shrink`` set, applies the elastic shrink
    policy (docs/RESILIENCE.md): when the board does not tile evenly
    over every visible device — the degraded-pod case, a relaunch coming
    up with fewer (or an awkward number of) chips — drop to the largest
    device count whose mesh the geometry divides instead of refusing to
    run.  The snapshot reshards onto whatever mesh results, so a
    supervised job keeps making progress on the smaller topology rather
    than burning its restart budget on a divisibility error.  (The
    policy checks the dense cell-quantum tiling; engine-specific
    constraints — packed word widths, Pallas alignment — still resolve
    downstream exactly as on a full mesh, falling back to the dense
    engine under ``auto``.)
    """
    if kind == "none":
        return None
    if kind not in ("1d", "2d"):
        raise ValueError(
            f"unknown mesh kind {kind!r}; expected one of {MESH_CHOICES}"
        )
    devices = jax.devices()
    counts = (
        range(len(devices), 0, -1)
        if allow_shrink and shape is not None
        else (len(devices),)
    )
    last_err: Optional[ValueError] = None
    for n in counts:
        if kind == "1d":
            mesh = mesh_mod.make_mesh_1d(n, devices=devices[:n])
        else:
            mesh = mesh_mod.make_mesh_2d(devices=devices[:n])
        if shape is None:
            return mesh
        try:
            mesh_mod.validate_geometry(shape, mesh)
        except ValueError as e:
            last_err = e
            continue
        if n < len(devices):
            import warnings

            warnings.warn(
                f"elastic shrink: board {shape[0]}x{shape[1]} does not "
                f"tile all {len(devices)} devices; proceeding on "
                f"{n} ({dict(mesh.shape)})",
                stacklevel=2,
            )
        return mesh
    assert last_err is not None
    raise last_err


def chunk_schedule(iterations: int, chunk: int) -> list:
    """Full chunks of ``chunk`` generations plus one tail.

    The one schedule policy behind every chunked loop (checkpoint and
    guard cadence in :class:`GolRuntime`, the 3-D driver's checkpointing)
    — shared so tail handling cannot drift between drivers.
    """
    if iterations > 0 and chunk < 1:
        raise ValueError(
            f"chunk must be >= 1 when iterations > 0 (got chunk={chunk}, "
            f"iterations={iterations})"
        )
    chunk = min(chunk, iterations) if iterations else 0
    schedule = []
    remaining = iterations
    while remaining > 0:
        take = min(chunk, remaining)
        schedule.append(take)
        remaining -= take
    return schedule


@dataclasses.dataclass
class GolRuntime:
    geometry: Geometry
    engine: str = "auto"
    halo_mode: str = "fresh"
    tile_hint: int = 512
    checkpoint_every: int = 0
    checkpoint_dir: Optional[str] = None
    mesh: Optional[Mesh] = None
    shard_mode: str = "explicit"  # shard_map+ppermute vs XLA auto-SPMD
    halo_depth: int = 1  # temporal blocking: ghost layers shipped per exchange
    rule: Optional[str] = None  # B/S rulestring; None = B3/S23 fast paths
    # Structured telemetry (gol_tpu.telemetry): per-process JSONL event
    # stream written to telemetry_dir/<run_id>.rank<k>.jsonl.  Host-side
    # only — emission happens strictly after force_ready fences and never
    # enters a compiled program (pinned by the trace-identity test).
    telemetry_dir: Optional[str] = None
    run_id: Optional[str] = None
    # In-graph simulation statistics (--stats): each chunk program is
    # wrapped in fused device reductions (population, births/deaths,
    # changed cells, boundary-band populations — psummed to the global
    # value on sharded runs) and returns (board, stats) in one launch.
    # Off (the default), the evolve programs are byte-identical to the
    # stats-less build (pinned by the trace-identity test); on, the
    # evolution itself is untouched (final grid bit-equal, pinned per
    # tier × mesh) but the chunk-start buffer stays live for the
    # births/deaths diff, so donation is forfeited: one extra board of
    # HBM.  Stats land in telemetry `stats` events and in `last_stats`.
    stats: bool = False
    # Process-tier resilience knobs (gol_tpu/resilience/,
    # docs/RESILIENCE.md) — all host-side, none touches a traced program
    # (pinned by the trace-identity tests):
    # keep_snapshots > 0 retains only the newest K *valid* snapshots
    # after each save (never the resume source); 0 keeps everything.
    keep_snapshots: int = 0
    # restart_attempt > 0 marks this run as supervised attempt N (from
    # GOL_RESTART_ATTEMPT) — stamped as a v3 `restart` telemetry event.
    restart_attempt: int = 0
    # resume_info (the dict resilience.resolve_auto_resume returns) is
    # stamped as a v3 `resume` telemetry event by open_event_log.
    resume_info: Optional[dict] = None
    # Activity-gated tier knobs (--engine activity; gol_tpu/sparse/,
    # docs/SPARSE.md).  activity_tile is the square tile edge of the
    # changed mask (0 = auto: the largest candidate dividing the
    # board/shard); activity_capacity is the worklist size as a fraction
    # of the (per-shard) tile count — a generation whose dilated active
    # set exceeds it falls back to one dense step (never wrong, never
    # worse than O(area)).  The mask itself is NOT checkpointed: resume
    # reconstructs it as all-active, which is sound and collapses to the
    # true activity after one generation (bit-identity pinned).
    activity_tile: int = 0
    activity_capacity: float = 0.25
    # Elastic-mesh knobs (docs/RESILIENCE.md):
    # reshard_at > 0 stops the run at the first chunk boundary whose
    # generation reaches it, writes a snapshot, and raises
    # resilience.ReshardPoint so the driver can replan and reload the
    # remaining generations on a different mesh (--reshard-at /
    # --reshard-mesh; the in-flight reshard drill knob).  Requires a
    # checkpoint_dir; single-process only (a multi-host job reshapes by
    # relaunching under --auto-resume, which reshards on load).
    reshard_at: int = 0
    # sharded_snapshots writes the sharded checkpoint directory format
    # even single-process (multi-host always does): the piece-table
    # format cross-topology resume repartitions, exercisable without a
    # pod.
    sharded_snapshots: bool = False
    # Out-of-core streaming tier knobs (--engine ooc; gol_tpu/ooc/,
    # docs/STREAMING.md).  The packed board lives in host RAM and
    # row-bands stream through the device under a fixed footprint:
    # ooc_budget_mb bounds the device-resident bytes (the band planner
    # inverts the three-deep-rotation footprint for the band height);
    # ooc_band_rows overrides the derived height (still validated
    # against the budget); ooc_skip_dead gates the dead-band skip
    # (a band whose one-band light cone held no live cells at sweep
    # start is neither fetched nor stepped).  All host-side: with the
    # engine unselected nothing here is consulted and every other
    # tier's programs are byte-identical (trace-identity pin).
    ooc_budget_mb: int = 256
    ooc_band_rows: int = 0
    ooc_skip_dead: bool = True
    # Live metrics endpoint (--metrics-port; docs/OBSERVABILITY.md):
    # rank 0 serves Prometheus text on 127.0.0.1:<port> (0 = ephemeral),
    # fed by the same in-process event stream the rank files get — so
    # the scrape counters can never disagree with the JSONL.  Requires
    # telemetry (the stream is the feed); host-side only, trace-
    # identity-pinned like every other observability knob.
    metrics_port: Optional[int] = None

    def __post_init__(self) -> None:
        if self.engine not in ENGINES:
            raise ValueError(f"unknown engine {self.engine!r}; expected {ENGINES}")
        if self.halo_mode not in engine_mod.HALO_MODES:
            raise ValueError(f"unknown halo_mode {self.halo_mode!r}")
        if self.shard_mode not in sharded_mod.MODES:
            raise ValueError(
                f"unknown shard_mode {self.shard_mode!r}; expected "
                f"{sharded_mod.MODES}"
            )
        if self.checkpoint_every and not self.checkpoint_dir:
            self.checkpoint_dir = "checkpoints"
        if self.halo_depth < 1:
            raise ValueError(f"halo_depth must be >= 1, got {self.halo_depth}")
        self._rule = None
        if self.rule is not None:
            from gol_tpu.ops import rules as rules_mod

            parsed = rules_mod.parse_rulestring(self.rule)
            if parsed != rules_mod.CONWAY:
                if self.halo_mode != "fresh":
                    raise ValueError(
                        "custom rules have no stale_t0 reference-compat mode "
                        "(the reference only implements B3/S23)"
                    )
                if self.engine == "pallas":
                    raise ValueError(
                        "engine 'pallas' (dense kernel) is hard-wired to "
                        "B3/S23; use 'auto'/'dense'/'bitpack'/"
                        "'pallas_bitpack' with a custom rule"
                    )
                self._rule = parsed
        self._resolved = (
            self._resolve_auto() if self.engine == "auto" else self.engine
        )
        if self._rule is not None and self.mesh is not None:
            # B3/S23 stays on the hard-wired fast paths; other rules run
            # the generic evaluators — sharded via the explicit ring
            # engine, or the sharded Pallas engine's overlap/pipeline
            # forms (its kernel carries the generic rule tail).  Checked
            # against the *resolved* engine so 'auto' runs that resolve
            # to the Pallas engine get the same allowance as an explicit
            # choice.
            if self.shard_mode != "explicit" and not (
                self.shard_mode in ("overlap", "pipeline")
                and self._resolved == "pallas_bitpack"
            ):
                raise ValueError(
                    "custom rules shard via the explicit ring engine (any "
                    "engine) or the sharded Pallas engine's overlap/"
                    f"pipeline forms (engine 'pallas_bitpack'); shard_mode "
                    f"{self.shard_mode!r} with engine {self._resolved!r} "
                    "is a Conway-specific program"
                )
        if self._resolved == "activity":
            self._init_activity()
        if self._resolved == "ooc":
            self._init_ooc()
        # (engine, mode, depth) legality — ONE authority
        # (gol_tpu.parallel.modes; the per-combo messages are pinned by
        # tests/test_mode_plan.py).  Geometry limits follow.
        from gol_tpu.parallel import modes as modes_mod

        if self.mesh is None:
            # The ooc tier is meshless by construction and reuses
            # halo_depth as its per-visit generation depth k, so both
            # mesh-coupled rejections exempt it (_init_ooc validated
            # shard_mode already).
            if self.halo_depth > 1 and self._resolved != "ooc":
                raise ValueError(
                    "halo_depth > 1 (temporal blocking) only applies to "
                    "sharded runs; pass a mesh"
                )
            if self.shard_mode == "pipeline" and self._resolved != "ooc":
                raise ValueError(
                    "shard_mode 'pipeline' double-buffers ring exchanges "
                    "across chunks, which only exist on sharded runs; "
                    "pass a mesh"
                )
        elif self._resolved in modes_mod.ENGINE_MODES or (
            self._resolved == "ooc"
        ):
            # For 'ooc' every cell rejects with the canonical
            # mesh-none-only message (modes.mode_rejection).
            modes_mod.check_combo(
                self._resolved, self.shard_mode, self.halo_depth
            )
        if self.halo_depth > 1 and self.mesh is not None:
            rows = self.mesh.shape.get(mesh_mod.ROWS, 1)
            cols = self.mesh.shape.get(mesh_mod.COLS, 1)
            shard_h = self.geometry.global_height // rows
            shard_w = self.geometry.global_width // cols
            # A 2-D mesh halo-extends the width axis even when its cols
            # axis has size 1 (the ring degenerates to the local wrap), so
            # the depth limit applies to both shard extents.  The packed
            # engine's horizontal quantum is the 32-cell word, so its
            # width-axis extent counts in words.
            two_d = mesh_mod.COLS in self.mesh.axis_names
            units = "cells"
            if self._resolved == "bitpack":
                from gol_tpu.ops import bitlife

                shard_w //= bitlife.BITS
                units = "words"
            modes_mod.check_depth(
                self.halo_depth, shard_h, shard_w, two_d, units
            )
        if self.mesh is not None:
            if self.halo_mode != "fresh":
                raise ValueError(
                    "stale_t0 (reference-compat) runs are single-device only; "
                    "its blocks evolve independently so a mesh adds nothing"
                )
            if self.engine not in (
                "auto", "dense", "bitpack", "pallas_bitpack", "activity"
            ):
                raise ValueError(
                    f"engine {self.engine!r} has no sharded path; with a "
                    "mesh use 'dense'/'auto' (shard_map+ppermute or "
                    "auto-SPMD), 'bitpack' (packed shard_map+ppermute), "
                    "'pallas_bitpack' (fused kernel per shard), or "
                    "'activity' (gated worklist per shard)"
                )
            shape = (self.geometry.global_height, self.geometry.global_width)
            if self._resolved == "pallas_bitpack":
                if self.shard_mode in ("overlap", "pipeline"):
                    # Both split forms need the interior kernel's aligned
                    # row tile clear of the exchanged bands.
                    depth = 8 if self.halo_depth == 1 else self.halo_depth
                    shard_h = self.geometry.global_height // self.mesh.shape[
                        mesh_mod.ROWS
                    ]
                    # Narrow shards evolve lane-folded on TPU, so the
                    # interior-tile room is measured at the folded height
                    # (interpret mode falls back to fold=1 and keeps the
                    # unfolded constraint).
                    from gol_tpu.ops import bitlife, pallas_bitlife

                    cols = self.mesh.shape.get(mesh_mod.COLS, 1)
                    words = (
                        self.geometry.global_width // cols // bitlife.BITS
                    )
                    fold = (
                        pallas_bitlife.fold_factor(words)
                        if jax.default_backend() == "tpu" and words > 0
                        else 1
                    )
                    if not pallas_bitlife.fold_feasible(
                        shard_h, fold, True, depth
                    ):
                        # The shared predicate gates; the clauses below
                        # only pick the message.  A fold==1 misalignment
                        # (shard_h % 8) matches neither and falls through
                        # to the engine's own 'multiple of 8' trace-time
                        # error rather than a wrong claim here.
                        if fold > 1 and shard_h % (fold * 8):
                            raise ValueError(
                                f"narrow shards lane-fold x{fold} on TPU, "
                                f"which needs shard height ({shard_h}) "
                                f"divisible by {fold * 8}"
                            )
                        if shard_h // fold < 2 * depth + 8:
                            raise ValueError(
                                f"{self.shard_mode} mode needs shard "
                                f"height ({shard_h}"
                                + (f", folded /{fold}" if fold > 1 else "")
                                + f") >= 2*halo_depth + 8 = {2 * depth + 8}; "
                                "shrink halo_depth or use shard_mode "
                                "'explicit'"
                            )
                from gol_tpu.ops import bitlife

                if (
                    mesh_mod.COLS in self.mesh.axis_names
                    and self.halo_depth > bitlife.BITS
                ):
                    raise ValueError(
                        "on a 2-D mesh the sharded Pallas engine's 1-word "
                        f"column band supports halo_depth <= {bitlife.BITS},"
                        f" got {self.halo_depth}"
                    )
                packed_mod.validate_packed_geometry(shape, self.mesh)
            elif self._resolved == "bitpack":
                # Mode legality (incl. the auto-SPMD rejection) already
                # ran through modes.check_combo — the overlap form is no
                # longer 1-D-only: depth-k interior/boundary splits cover
                # both decompositions (gol_tpu.parallel.halo).
                packed_mod.validate_packed_geometry(shape, self.mesh)
            else:
                mesh_mod.validate_geometry(shape, self.mesh)
        # Frozen t=0 halos, populated for stale_t0 runs at board init.
        self._halos: Optional[Tuple[jax.Array, jax.Array]] = None
        if self.keep_snapshots < 0:
            raise ValueError(
                f"keep_snapshots must be >= 0, got {self.keep_snapshots} "
                "(0 keeps every snapshot)"
            )
        # Async checkpoint writer, owned by run()/run_guarded while their
        # loops are live (single-process runs only — see
        # checkpoint.AsyncSnapshotWriter).
        self._ckpt_writer = None
        # Checkpoint containment (docs/RESILIENCE.md "Retry and shed"):
        # once a snapshot write hits persistent ENOSPC past the retry
        # budget, checkpointing is shed for the rest of the run (the run
        # itself never dies for an observer/persistence failure).
        # _live_events is the run's EventLog while a loop is live — the
        # shed policy's telemetry-first sacrifice goes through it.
        self._ckpt_shed = False
        self._live_events = None
        # The snapshot this run resumed from — protected from retention
        # GC for the whole run (a rollback may still need it).
        self._resume_source: Optional[str] = None
        if self.reshard_at < 0:
            raise ValueError(
                f"reshard_at must be >= 0, got {self.reshard_at} "
                "(0 disables the in-flight reshard stop)"
            )
        if self.reshard_at > 0 and not self.checkpoint_dir:
            raise ValueError(
                "reshard_at stops through a snapshot; set checkpoint_dir "
                "(or a checkpoint cadence)"
            )
        # Cross-topology resume record (docs/RESILIENCE.md): set by
        # initial_state when the snapshot's stamped/inferred topology
        # differs from this run's mesh — the v7 `reshard` telemetry
        # event's payload, and the test surface for the planner.
        self.last_reshard: Optional[dict] = None
        # Host-int stats of the last run()'s chunks (--stats mode):
        # [{"index", "take", "generation", "population", ...}, ...].
        self.last_stats: list = []
        # Host-int activity counters of the last run()'s chunks
        # (--engine activity): [{"index", "take", "generation",
        # "active_tile_gens", "computed_tile_gens", "fallback_gens",
        # "skipped_tile_gens", ...}, ...].
        self.last_activity: list = []
        # Host-int streaming counters of the last run()'s chunks
        # (--engine ooc): [{"index", "take", "generation", "bands",
        # "visits", "skipped_bands", "bytes_h2d", "bytes_d2h",
        # "overlap_fraction", ...}, ...].
        self.last_ooc: list = []
        if self.metrics_port is not None and not self.telemetry_dir:
            raise ValueError(
                "metrics_port serves the in-process event stream, so it "
                "requires telemetry_dir (--telemetry)"
            )
        # The live run's MetricsRegistry/MetricsServer (--metrics-port);
        # the registry outlives the run for reconciliation tests, the
        # server dies with the event log.
        self.last_metrics = None
        self._metrics_server = None

    def _init_activity(self) -> None:
        """Validate + resolve the activity tier's tile/capacity/repr.

        Sets ``_act_tile`` (mask tile edge), ``_act_packed`` (bit-packed
        worklist on single-device word-aligned boards), ``_act_grid``
        (global mask grid shape) and ``_act_capacity_n`` (the per-shard
        worklist capacity K).  See docs/SPARSE.md.
        """
        from gol_tpu.ops import bitlife
        from gol_tpu.sparse import engine as sparse_engine
        from gol_tpu.sparse import mask as sparse_mask

        if self.halo_mode != "fresh":
            raise ValueError(
                "engine 'activity' implements fresh halos only (the "
                "stale_t0 compat mode reproduces a reference bug the "
                "gated tier has no analog for)"
            )
        if self.rule is not None and self._rule is not None:
            raise ValueError(
                "engine 'activity' runs the B3/S23 fast paths; use "
                "'dense'/'bitpack' with a custom rule"
            )
        if self.halo_depth != 1:
            raise ValueError(
                "engine 'activity' exchanges one-tile mask halos per "
                f"generation; halo_depth must be 1, got {self.halo_depth}"
            )
        if self.mesh is not None and self.shard_mode != "explicit":
            raise ValueError(
                "the sharded activity engine has the explicit ring "
                f"program only (got shard_mode {self.shard_mode!r})"
            )
        h, w = self.geometry.global_height, self.geometry.global_width
        if self.activity_tile:
            tile = self.activity_tile
            packed = (
                self.mesh is None
                and tile % bitlife.BITS == 0
                and w % bitlife.BITS == 0
            )
            sparse_mask.validate_tile(h, w, tile, packed)
        elif self.mesh is None:
            try:
                tile, packed = sparse_mask.pick_tile(h, w, packed=True), True
            except ValueError:
                tile, packed = sparse_mask.pick_tile(h, w, packed=False), False
        else:
            rows = self.mesh.shape[mesh_mod.ROWS]
            cols = self.mesh.shape.get(mesh_mod.COLS, 1)
            tile, packed = sparse_mask.pick_tile(h // rows, w // cols), False
        if self.mesh is not None:
            from gol_tpu.parallel import sparse as par_sparse

            par_sparse.validate_activity_geometry((h, w), self.mesh, tile)
        self._act_tile = tile
        self._act_packed = packed
        self._act_grid = sparse_mask.grid_shape(h, w, tile)
        if self.mesh is not None:
            rows = self.mesh.shape[mesh_mod.ROWS]
            cols = self.mesh.shape.get(mesh_mod.COLS, 1)
            shard_th = h // rows // tile
            shard_tw = w // cols // tile
        else:
            shard_th, shard_tw = self._act_grid
        self._act_capacity_n = sparse_engine.default_capacity(
            shard_th, shard_tw, self.activity_capacity
        )

    def _init_ooc(self) -> None:
        """Validate + resolve the out-of-core tier's plan (docs/STREAMING.md).

        Sets ``_ooc_plan`` (the :class:`gol_tpu.ooc.planner.BandPlan`).
        ``halo_depth`` is reused as the per-visit generation depth k —
        the same temporal-blocking quantum the sharded tiers ship over
        the ring, here amortizing one H2D/D2H round-trip per band.
        """
        from gol_tpu.ooc import planner as ooc_planner
        from gol_tpu.ops import bitlife
        from gol_tpu.parallel import modes as modes_mod

        if self.halo_mode != "fresh":
            raise ValueError(
                "engine 'ooc' implements fresh halos only (the stale_t0 "
                "compat mode reproduces a reference bug the streaming "
                "tier has no analog for)"
            )
        if self.rule is not None and self._rule is not None:
            raise ValueError(
                "engine 'ooc' streams the B3/S23 bit-packed band step; "
                "use 'dense'/'bitpack' with a custom rule"
            )
        if self.shard_mode != "explicit":
            # The canonical per-combo message — pinned like the rest of
            # the matrix by tests/test_mode_plan.py.
            raise ValueError(modes_mod.mode_rejection("ooc", self.shard_mode))
        w = self.geometry.global_width
        if w % bitlife.BITS != 0:
            raise ValueError(
                "engine 'ooc' streams the packed-board layout, which "
                f"needs the board width ({w}) to be a multiple of "
                f"{bitlife.BITS}; use 'dense' for unpacked widths"
            )
        if self.ooc_budget_mb < 0 or self.ooc_band_rows < 0:
            raise ValueError(
                "ooc_budget_mb and ooc_band_rows must be >= 0, got "
                f"{self.ooc_budget_mb} / {self.ooc_band_rows}"
            )
        if self.reshard_at > 0:
            raise ValueError(
                "reshard_at replans onto a different mesh; the ooc tier "
                "is meshless (its board already lives host-side — "
                "checkpoint and resume instead)"
            )
        self._ooc_plan = ooc_planner.plan_bands(
            self.geometry.global_height,
            w,
            self.halo_depth,
            band_rows=self.ooc_band_rows,
            budget_bytes=self.ooc_budget_mb << 20,
        )

    def _resolve_auto(self) -> str:
        """Pick the fastest engine this run's geometry and mode support.

        Every engine is bit-exact (pinned by the equivalence tests), so
        'auto' is purely a performance choice — the TPU analog of the
        reference hard-coding one CUDA kernel:

        - sharded explicit runs take the bit-packed ring engine when the
          shard width packs into whole words (8× less ppermute wire);
        - single-device fresh runs take the fused Pallas bit-packed kernel
          on TPU when the width fills whole lane tiles, else the XLA
          bit-packed engine when the width packs, else dense;
        - shard_mode 'overlap' prefers the sharded Pallas engine's overlap
          form, falling back to the XLA packed overlap (1-D) or dense;
        - stale_t0 (reference-compat) and shard_mode 'auto' are dense-only
          paths.
        """
        if self.halo_mode != "fresh":
            return "dense"
        geom = (self.geometry.global_height, self.geometry.global_width)
        if self.mesh is not None:
            if self.shard_mode == "auto":
                return "dense"  # auto-SPMD exists for the dense step only
            two_d = mesh_mod.COLS in self.mesh.axis_names
            # Overlap and pipeline share the Pallas engine's split
            # geometry (interior tile clear of both bands).
            split = self.shard_mode in ("overlap", "pipeline")
            try:
                packed_mod.validate_packed_geometry(geom, self.mesh)
            except ValueError:
                return "dense"
            if self.halo_depth > 1 and two_d:
                # The packed engine's horizontal ghost quantum is the
                # 32-cell word; if the shard is too narrow in words for the
                # requested depth, dense (cell-quantum halos) still works.
                from gol_tpu.ops import bitlife

                cols = self.mesh.shape.get(mesh_mod.COLS, 1)
                words = self.geometry.global_width // cols // bitlife.BITS
                if self.halo_depth > words:
                    return "dense"
            if jax.default_backend() == "tpu" and (
                self.halo_depth == 1 or self.halo_depth % 8 == 0
            ):
                # Fused kernel per shard when the shard geometry allows:
                # lane-filling shard width, aligned shard height, room for
                # the 8-deep exchanged ghost band (overlap additionally
                # needs an aligned interior tile clear of both bands), and
                # (2-D meshes) a band depth within the 1-word column halo's
                # bit light cone.
                from gol_tpu.ops import bitlife, pallas_bitlife

                rows = self.mesh.shape[mesh_mod.ROWS]
                cols = self.mesh.shape.get(mesh_mod.COLS, 1)
                shard_h = self.geometry.global_height // rows
                shard_w = self.geometry.global_width // cols
                depth = 8 if self.halo_depth == 1 else self.halo_depth
                min_h = 2 * depth + 8 if split else depth
                words = shard_w // bitlife.BITS
                fold = pallas_bitlife.fold_factor(words)
                # Narrow shards run lane-folded: f row groups side by
                # side in lanes, exact via the kernel's group-local rolls
                # — so BASELINE config 3's 16x16-mesh 32-word shards
                # resolve here too, in explicit AND overlap/pipeline
                # modes (r4: the folded interior kernel is
                # ppermute-independent like the unfolded one; it just
                # needs its aligned tile clear of both bands at the
                # *folded* height).  Sharded columns additionally need
                # >= 2 words for edge strips.
                fold_ok = fold == 1 or (
                    pallas_bitlife.fold_feasible(
                        shard_h, fold, split, depth
                    )
                    and (cols <= 1 or words >= 2)
                )
                if (
                    fold_ok
                    and shard_h % pallas_bitlife._ALIGN == 0
                    and shard_h >= min_h
                    and (not two_d or depth <= bitlife.BITS)
                ):
                    return "pallas_bitpack"
            # The XLA packed engine now covers every explicit/overlap/
            # pipeline geometry at any depth (the depth-k split lifted
            # the old 1-D-only overlap restriction), so a pod geometry
            # that misses the fused-Pallas gate above degrades to the
            # bit-packed ring — no dense cliff, no warning needed (the
            # r3/r4 silent-dense-fallback story ends here).
            return "bitpack"
        from gol_tpu.ops import bitlife

        if geom[1] % bitlife.BITS != 0:
            return "dense"
        if jax.default_backend() == "tpu":
            from gol_tpu.ops import pallas_bitlife

            if (
                geom[1] % (pallas_bitlife._LANE * bitlife.BITS) == 0
                and geom[0] % pallas_bitlife._ALIGN == 0
            ):
                return "pallas_bitpack"
        return "bitpack"

    # -- engine dispatch ----------------------------------------------------
    def _evolve_fn(self, steps: int):
        """Returns (jitted_fn, dynamic_args, static_args).

        The full call is ``fn(board, *dynamic_args, *static_args)``; after
        AOT lowering, the Compiled object is invoked with the dynamic args
        only.  Keeping the raw jitted function (rather than a closure) lets
        the compile phase lower from a ShapeDtypeStruct — compiling without
        executing a throwaway evolution.
        """
        name = self._resolved
        if name == "activity":
            # Activity-gated tier: the chunk program carries the changed
            # mask — fn(board, changed) -> (board, changed, activity) —
            # and the run loop threads it between chunks (docs/SPARSE.md).
            if self.mesh is not None:
                from gol_tpu.parallel import sparse as par_sparse

                return (
                    par_sparse.compiled_evolve_activity(
                        self.mesh, steps, self._act_tile,
                        self._act_capacity_n,
                    ),
                    (),
                    (),
                )
            from gol_tpu.sparse import engine as sparse_engine

            fn = (
                sparse_engine.evolve_gated_packed
                if self._act_packed
                else sparse_engine.evolve_gated_dense
            )
            return fn, (), (steps, self._act_tile, self._act_capacity_n)
        if name == "pallas_bitpack" and self.mesh is not None:
            # Fused kernel per shard over the ppermute ring; a custom rule
            # rides the same program via the kernel's generic tail.
            return (
                packed_mod.compiled_evolve_packed_pallas(
                    self.mesh,
                    steps,
                    8 if self.halo_depth == 1 else self.halo_depth,
                    self.tile_hint,
                    self._rule,
                    self.shard_mode == "overlap",
                    self.shard_mode == "pipeline",
                ),
                (),
                (),
            )
        if self._rule is not None:
            from gol_tpu.ops import rules as rules_mod

            if self.mesh is not None:
                from gol_tpu.parallel import ruled

                return (
                    ruled.compiled_evolve_rule(
                        self.mesh,
                        steps,
                        self._rule,
                        name == "bitpack",
                        self.halo_depth,
                    ),
                    (),
                    (),
                )
            if name == "pallas_bitpack":
                try:
                    from gol_tpu.ops import pallas_bitlife
                except ImportError as e:
                    # Same friendly-error contract as the Conway dispatch
                    # below for the identical engine selection.
                    raise ValueError(
                        f"engine {name!r} is not available: {e}"
                    ) from e

                return (
                    pallas_bitlife.evolve,
                    (),
                    (steps, self.tile_hint, self._rule),
                )
            if name == "bitpack":
                return rules_mod.evolve_rule_dense_io, (), (steps, self._rule)
            return rules_mod.run_rule, (), (steps, self._rule)
        if name == "dense":
            if self.mesh is not None:
                return (
                    sharded_mod.compiled_evolve(
                        self.mesh, steps, self.shard_mode, self.halo_depth
                    ),
                    (),
                    (),
                )
            if self.halo_mode == "fresh":
                return engine_mod.evolve_fresh, (), (steps,)
            top0, bottom0 = self._halos
            return (
                engine_mod.evolve_stale_with_halos,
                (top0, bottom0),
                (self.geometry.num_ranks, steps),
            )
        if self.halo_mode != "fresh":
            raise ValueError(f"engine {name!r} implements fresh halos only")
        try:
            if name == "bitpack":
                if self.mesh is not None:
                    if (
                        self.shard_mode == "overlap"
                        and self.halo_depth == 1
                        and mesh_mod.COLS not in self.mesh.axis_names
                    ):
                        # Depth-1 1-D overlap keeps its hand-written
                        # program (byte-identical to every prior round);
                        # deeper bands and 2-D meshes run the generic
                        # interior/boundary split below.
                        return (
                            packed_mod.compiled_evolve_packed_overlap(
                                self.mesh, steps
                            ),
                            (),
                            (),
                        )
                    if self.shard_mode in ("overlap", "pipeline"):
                        return (
                            packed_mod.compiled_evolve_packed(
                                self.mesh,
                                steps,
                                self.halo_depth,
                                mode=self.shard_mode,
                            ),
                            (),
                            (),
                        )
                    return (
                        packed_mod.compiled_evolve_packed(
                            self.mesh, steps, self.halo_depth
                        ),
                        (),
                        (),
                    )
                from gol_tpu.ops import bitlife

                return bitlife.evolve_dense_io, (), (steps,)
            if name == "pallas":
                from gol_tpu.ops import pallas_step

                return pallas_step.evolve, (), (steps, self.tile_hint)
            if name == "pallas_bitpack":
                from gol_tpu.ops import pallas_bitlife

                return pallas_bitlife.evolve, (), (steps, self.tile_hint)
        except ImportError as e:
            raise ValueError(f"engine {name!r} is not available: {e}") from e
        raise AssertionError(name)

    # -- board init ---------------------------------------------------------
    def initial_state(
        self, pattern: int, resume: Optional[str] = None
    ) -> GolState:
        """World state (board + generation), from a pattern or a checkpoint.

        For stale_t0 (reference-compat) runs the frozen t=0 halos are fixed
        here: computed from the t=0 board on a fresh start, or restored from
        the snapshot on resume (re-freezing from the resumed board would
        silently change the semantics mid-run).
        """
        self._resume_source = resume or None
        self.last_reshard = None
        if resume and ckpt_mod.is_sharded(resume):
            from gol_tpu.resilience import reshard as reshard_mod

            source = reshard_mod.open_source(resume, kind="2d")
            meta = source
            if meta.num_ranks != self.geometry.num_ranks:
                raise ValueError(
                    f"checkpoint has {meta.num_ranks} ranks, run configured "
                    f"for {self.geometry.num_ranks}"
                )
            expected = (self.geometry.global_height, self.geometry.global_width)
            if meta.shape != expected:
                raise ValueError(
                    f"checkpoint board {meta.shape} != configured {expected}"
                )
            mine = None if self._rule is None else self._rule.rulestring()
            if meta.rule != mine:
                raise ValueError(
                    f"checkpoint was written by a {meta.rule or 'B3/S23'} "
                    f"run; this run is configured for {mine or 'B3/S23'} — "
                    "pass the matching --rule to resume"
                )
            if self.halo_mode == "stale_t0":
                raise ValueError(
                    "sharded checkpoints are written by fresh-halo runs "
                    "only; a stale_t0 run cannot resume from one bit-exactly"
                )
            # Elastic resume: the plan repartitions the stored pieces
            # onto THIS run's topology — each host still reads only the
            # regions its devices own (the gather-free load).  A
            # matching topology yields the identity plan and no event.
            dst = reshard_mod.MeshLayout.from_mesh(self.mesh)
            plan = source.plan_onto(dst)
            board = reshard_mod.place(source, self.mesh, plan)
            if source.layout != dst:
                self.last_reshard = dict(
                    generation=source.generation,
                    path=os.path.abspath(resume),
                    legacy_manifest=source.legacy,
                    **plan.summary(),
                )
            return GolState.create(board, source.generation)
        if resume:
            snap = ckpt_mod.load(resume)
            if snap.num_ranks != self.geometry.num_ranks:
                raise ValueError(
                    f"checkpoint has {snap.num_ranks} ranks, run configured "
                    f"for {self.geometry.num_ranks}"
                )
            expected = (self.geometry.global_height, self.geometry.global_width)
            if snap.board.shape != expected:
                raise ValueError(
                    f"checkpoint board {snap.board.shape} != configured {expected}"
                )
            mine = None if self._rule is None else self._rule.rulestring()
            if snap.rule != mine:
                # Same semantic-drift guard as the frozen halos below: a
                # resumed world must keep evolving under the rule that
                # produced it.
                raise ValueError(
                    f"checkpoint was written by a {snap.rule or 'B3/S23'} "
                    f"run; this run is configured for {mine or 'B3/S23'} — "
                    "pass the matching --rule to resume"
                )
            if self.halo_mode == "stale_t0":
                if snap.top0 is None:
                    raise ValueError(
                        "checkpoint lacks frozen halos; it was not written by "
                        "a stale_t0 run and cannot resume one bit-exactly"
                    )
                self._halos = (
                    jax.device_put(snap.top0),
                    jax.device_put(snap.bottom0),
                )
            if self.mesh is not None:
                # A whole-board snapshot landing on a mesh is a reshard
                # too (layout none → this mesh); the placement itself is
                # unchanged (shard_board in run()), but the move is
                # planned/validated and recorded like the sharded case.
                from gol_tpu.resilience import reshard as reshard_mod

                h, w = snap.board.shape
                plan = reshard_mod.plan_reshard(
                    (h, w),
                    [(0, h, 0, w)],
                    reshard_mod.MeshLayout("none"),
                    reshard_mod.MeshLayout.from_mesh(self.mesh),
                )
                self.last_reshard = dict(
                    generation=snap.generation,
                    path=os.path.abspath(resume),
                    legacy_manifest=False,
                    **plan.summary(),
                )
            return GolState.create(jax.device_put(snap.board), snap.generation)

        board_np = patterns.init_global(
            pattern, self.geometry.size, self.geometry.num_ranks
        )
        board = jax.device_put(board_np)
        if self.halo_mode == "stale_t0":
            self._halos = engine_mod.frozen_halos(board, self.geometry.num_ranks)
        return GolState.create(board, 0)

    def _save_snapshot(
        self,
        state: GolState,
        fingerprint: Optional[int] = None,
    ) -> None:
        """Persist a snapshot.

        A device-computed ``fingerprint`` (the guard audit's) skips the
        host-side recompute and — multi-host — stamps the sharded manifest
        with the global hash no single host could compute.  Multi-host
        jobs write the sharded format (each process its own pieces) and
        fence with a global barrier so no host races into the next chunk
        while files are mid-write.

        Writes run under the containment policy
        (:func:`gol_tpu.resilience.degrade.write_with_retry`): transient
        IO errors get bounded retry+backoff; persistent disk-full sheds
        telemetry first, then checkpointing itself — never the run.
        """
        from gol_tpu.resilience import degrade as degrade_mod

        if self._ckpt_shed:
            return
        top0, bottom0 = self._halos if self._halos is not None else (None, None)
        multi = jax.process_count() > 1
        rule = None if self._rule is None else self._rule.rulestring()
        if multi or (self.sharded_snapshots and self.mesh is not None):
            # Sharded format: every process writes only the rectangles its
            # devices own — no all-gather, no host ever materializes the
            # board (VERDICT r1 #4; at 65536² the old fetch_global path
            # replicated 4 GB to every host per snapshot).  stale_t0 never
            # reaches here (multi-host runs are fresh-halo by validation).
            # The manifest stamps this run's mesh layout so a future
            # resume on another topology can name the reshard it does.
            from gol_tpu.resilience import reshard as reshard_mod

            ok = degrade_mod.write_with_retry(
                lambda: ckpt_mod.save_sharded(
                    ckpt_mod.sharded_checkpoint_path(
                        self.checkpoint_dir, int(state.generation)
                    ),
                    state.board,
                    int(state.generation),
                    self.geometry.num_ranks,
                    rule=rule,
                    fingerprint=fingerprint,
                    mesh_layout=reshard_mod.MeshLayout.from_mesh(
                        self.mesh
                    ).to_dict(),
                ),
                generation=int(state.generation),
                shed_telemetry=self._shed_telemetry,
            )
            from jax.experimental import multihost_utils

            # The barrier runs even on a shed write: a rank that stopped
            # persisting must not strand its peers in the fence.
            multihost_utils.sync_global_devices("gol_checkpoint")
            if not ok:
                self._ckpt_shed = True
                return
            # Retention: after the barrier (every host's pieces are
            # durably renamed) exactly one process sweeps old snapshots.
            if self.keep_snapshots > 0 and jax.process_index() == 0:
                from gol_tpu.resilience import retention

                retention.gc_snapshots(
                    self.checkpoint_dir,
                    self.keep_snapshots,
                    kind="2d",
                    protect=(self._resume_source,),
                )
            return
        path = ckpt_mod.checkpoint_path(
            self.checkpoint_dir, int(state.generation)
        )
        kwargs = dict(
            top0=None if top0 is None else np.asarray(top0),
            bottom0=None if bottom0 is None else np.asarray(bottom0),
            fingerprint=fingerprint,
            rule=rule,
        )
        generation = int(state.generation)
        ranks = self.geometry.num_ranks
        # The host fetch stays on this thread — it is the donation fence
        # (the next chunk consumes the device buffer) and it must NOT
        # move to the writer: a background device→host transfer contends
        # with the next chunk's device execution, silently inflating the
        # reported TOTAL DURATION (measured r4: 'total' 1.9 s → 6-7 s
        # with a device-copy fence + background fetch).  Only the
        # compressed write overlaps; on real (non-tunnel) hosts the
        # write, not the fetch, dominates the phase.
        board_np = np.asarray(state.board)

        def write():
            ok = degrade_mod.write_with_retry(
                lambda: ckpt_mod.save(
                    path, board_np, generation, ranks, **kwargs
                ),
                generation=generation,
                shed_telemetry=self._shed_telemetry,
            )
            if not ok:
                self._ckpt_shed = True
                return
            if self.keep_snapshots > 0:
                # GC rides the same thread as the save (the writer's, or
                # this one) so it always runs after the rename it follows
                # and never races an in-flight .tmp of this process.
                from gol_tpu.resilience import retention

                retention.gc_snapshots(
                    self.checkpoint_dir,
                    self.keep_snapshots,
                    kind="2d",
                    protect=(self._resume_source,),
                )

        if self._ckpt_writer is not None:
            self._ckpt_writer.submit(write)
        else:
            write()

    def _shed_telemetry(self, reason: str) -> None:
        """The disk-full first sacrifice: ask the live event stream to
        shed (thread-safe; the stamp happens on the emitting thread)."""
        events = self._live_events
        if events is not None:
            events.request_shed("telemetry", reason)

    def _preempt(
        self,
        state: GolState,
        sw: Stopwatch,
        writer,
        events,
        fingerprint: Optional[int] = None,
        already_saved: bool = False,
    ) -> None:
        """Cooperative-preemption exit path (shared by run/run_guarded).

        Persists a final fingerprinted snapshot when a checkpoint
        directory is configured (skipped when one just landed at this
        exact boundary), fences the async writer so the snapshot is
        durably renamed *before* the process exits, emits the ``preempt``
        telemetry event, and raises :class:`gol_tpu.resilience.Preempted`
        — which the CLIs map to exit code 75 (EX_TEMPFAIL).
        """
        from gol_tpu import telemetry as telemetry_mod
        from gol_tpu import resilience

        generation = int(state.generation)
        checkpointed = already_saved
        if self.checkpoint_dir and not already_saved:
            with telemetry_mod.trace_annotation("gol.checkpoint.save"):
                with sw.phase("checkpoint"):
                    self._save_snapshot(state, fingerprint=fingerprint)
            checkpointed = True
        if writer is not None and checkpointed:
            with sw.phase("checkpoint"):
                writer.flush()
        if events is not None:
            events.preempt_event(generation, checkpointed=checkpointed)
        raise resilience.Preempted(
            generation,
            checkpoint_dir=self.checkpoint_dir if checkpointed else None,
        )

    def _reshard_stop(self, state, sw: Stopwatch, writer, remaining: int) -> None:
        """In-flight reshard stop (``reshard_at``): snapshot, then raise.

        Mirrors :meth:`_preempt`'s chunk-boundary contract — the board is
        whole and fenced, the snapshot is durably renamed before the
        raise — but hands control back to the driver via
        :class:`gol_tpu.resilience.ReshardPoint` so the remaining
        generations reload on the new mesh in this same process.
        """
        from gol_tpu import resilience
        from gol_tpu import telemetry as telemetry_mod

        if jax.process_count() > 1:
            raise ValueError(
                "reshard_at is single-process (a multi-host job reshapes "
                "by relaunching under --auto-resume, which reshards on "
                "load)"
            )
        generation = int(state.generation)
        if self.checkpoint_every <= 0:
            # No cadence: this boundary has no snapshot yet — write one.
            with telemetry_mod.trace_annotation("gol.checkpoint.save"):
                with sw.phase("checkpoint"):
                    self._save_snapshot(state)
        if writer is not None:
            with sw.phase("checkpoint"):
                writer.flush()
        if self.sharded_snapshots and self.mesh is not None:
            path = ckpt_mod.sharded_checkpoint_path(
                self.checkpoint_dir, generation
            )
        else:
            path = ckpt_mod.checkpoint_path(self.checkpoint_dir, generation)
        raise resilience.ReshardPoint(generation, path, remaining)

    # -- shared compile machinery -------------------------------------------
    def chunk_schedule(self, iterations: int, chunk: int) -> list:
        """Full chunks of ``chunk`` generations plus one tail."""
        return chunk_schedule(iterations, chunk)

    def compile_evolvers(self, board, schedule, events=None) -> dict:
        """AOT-compile one evolver per distinct chunk size in ``schedule``.

        Lowers from a ShapeDtypeStruct (no execution, no throwaway board) so
        callers' timed loops measure steady-state execution only; also warms
        the ``force_ready`` readback.  Returns ``{take: (compiled, dynamic)}``
        where the full call is ``compiled(board, *dynamic)``.  Shared by
        :meth:`run` and the guarded loop (:func:`gol_tpu.utils.guard.
        run_guarded`), so engine dispatch can never diverge between them.
        An :class:`~gol_tpu.telemetry.EventLog` in ``events`` receives one
        ``compile`` record per distinct chunk size (lowering and compile
        durations separately — on TPU the XLA compile dominates and is the
        number worth tracking across rounds — plus the compiled program's
        memory/cost analysis when the backend exposes it: peak HBM and
        argument/output/temp bytes are the real scaling limit for the
        whole-board runs, and never appeared anywhere before schema v2).

        With :attr:`stats` set, each program is the stats-wrapped form
        (:func:`gol_tpu.telemetry.stats.build_stats_evolver`) returning
        ``(board, stats)``; off, this path is byte-for-byte the PR 2 one.
        """
        import time as time_mod

        from gol_tpu import telemetry as telemetry_mod

        if self.mesh is not None:
            spec = jax.ShapeDtypeStruct(
                board.shape,
                board.dtype,
                sharding=mesh_mod.board_sharding(self.mesh),
            )
        else:
            spec = jax.ShapeDtypeStruct(board.shape, board.dtype)
        specs = (spec,)
        if self._resolved == "activity":
            # The activity programs additionally take the changed-tile
            # mask (and return it — the run loop threads it through).
            import jax.numpy as jnp

            if self.mesh is not None:
                from gol_tpu.parallel import sparse as par_sparse

                mask_spec = jax.ShapeDtypeStruct(
                    self._act_grid,
                    jnp.bool_,
                    sharding=par_sparse.mask_sharding(self.mesh),
                )
            else:
                mask_spec = jax.ShapeDtypeStruct(self._act_grid, jnp.bool_)
            specs = (spec, mask_spec)
        evolvers = {}
        for take in set(schedule):
            if self.stats:
                from gol_tpu.telemetry import stats as stats_mod

                fn, dynamic = stats_mod.build_stats_evolver(self, take)
                static = ()
            else:
                fn, dynamic, static = self._evolve_fn(take)
            from gol_tpu.batch import cache as cache_mod

            probe = cache_mod.CompileCacheProbe()
            with telemetry_mod.trace_annotation(f"gol.compile.{take}"):
                t0 = time_mod.perf_counter()
                lowered = fn.lower(*specs, *dynamic, *static)
                t1 = time_mod.perf_counter()
                compiled = lowered.compile()
                t2 = time_mod.perf_counter()
            evolvers[take] = (compiled, dynamic)
            if events is not None:
                from gol_tpu.telemetry import stats as stats_mod

                cache_hit, cache_key = probe.resolve()
                events.compile_event(
                    take,
                    t1 - t0,
                    t2 - t1,
                    memory=stats_mod.compiled_memory(compiled),
                    cache_hit=cache_hit,
                    cache_key=cache_key,
                )
        force_ready(board)
        return evolvers

    # -- telemetry ----------------------------------------------------------
    def open_event_log(self):
        """A fresh :class:`~gol_tpu.telemetry.EventLog` with the run header
        emitted, or ``None`` when telemetry is off.  Callers own close()."""
        if not self.telemetry_dir:
            return None
        from gol_tpu import telemetry as telemetry_mod

        events = telemetry_mod.EventLog(self.telemetry_dir, run_id=self.run_id)
        # Arm the black box for this run: dumps land next to the stream
        # (unhandled exception, fault-plane crash.exit — the signal
        # triggers belong to entry points that own their handlers).
        telemetry_mod.blackbox.install(
            self.telemetry_dir,
            run_id=events.run_id,
            process_index=events.process_index,
        )
        if self.metrics_port is not None and jax.process_index() == 0:
            # Attach before the header emits so the registry sees every
            # record; the server rides events.close() (rank 0 only — the
            # scrape surface is one endpoint, like the printed report).
            from gol_tpu.telemetry import metrics as metrics_mod

            self.last_metrics, self._metrics_server = (
                metrics_mod.serve_event_metrics(events, self.metrics_port)
            )
        mesh_shape = None if self.mesh is None else dict(self.mesh.shape)
        events.run_header(
            dict(
                driver="2d",
                engine=self.engine,
                resolved_engine=self._resolved,
                mesh=mesh_shape,
                shard_mode=self.shard_mode,
                halo_mode=self.halo_mode,
                halo_depth=self.halo_depth,
                rule=self.rule,
                height=self.geometry.global_height,
                width=self.geometry.global_width,
                num_ranks=self.geometry.num_ranks,
                checkpoint_every=self.checkpoint_every,
            )
        )
        if self.restart_attempt > 0:
            events.restart_event(self.restart_attempt)
        if self.resume_info is not None and self.resume_info.get("path"):
            events.resume_event(
                generation=self.resume_info["generation"],
                path=self.resume_info["path"],
                fallback=bool(self.resume_info.get("fallback")),
                skipped=self.resume_info.get("skipped") or [],
            )
        if self.last_reshard is not None:
            # Cross-topology resume happened (schema v7): record the
            # src/dst topologies and the validated plan's accounting.
            events.reshard_event(**self.last_reshard)
        return events

    def _initial_activity_mask(self):
        """The all-active changed mask (run start AND resume: the mask
        is never checkpointed — all-ones is a sound superset that
        collapses to the true activity after one generation)."""
        import jax.numpy as jnp

        if self.mesh is not None:
            from gol_tpu.parallel import sparse as par_sparse

            return jax.device_put(
                np.ones(self._act_grid, bool),
                par_sparse.mask_sharding(self.mesh),
            )
        return jnp.ones(self._act_grid, jnp.bool_)

    def _activity_block(self, take: int, dev_act: dict) -> dict:
        """One chunk's activity telemetry block (schema v5) from the
        program's device counters."""
        th, tw = self._act_grid
        tiles = th * tw
        tile_gens = tiles * take
        active = int(dev_act["active_tile_gens"])
        computed = int(dev_act["computed_tile_gens"])
        return {
            "tile": self._act_tile,
            "tiles": tiles,
            "tile_gens": tile_gens,
            "active_tile_gens": active,
            "computed_tile_gens": computed,
            "skipped_tile_gens": tile_gens - computed,
            "fallback_gens": int(dev_act["fallback_gens"]),
            "active_fraction": active / tile_gens if tile_gens else 0.0,
        }

    def _halo_block(self, take: int) -> Optional[dict]:
        """One chunk's ``halo`` telemetry block (schema v8, sharded ring
        engines only): the exchange depth/mode actually compiled, the
        per-chunk exchange count, and the band traffic in bytes — so the
        k-vs-wire tradeoff the pipeline exists for is visible per chunk.

        ``exchange_share`` is the band bytes over the chunk's total
        shard-state + band payload — a *traffic* share (device-side
        exchange latency is not host-observable; time attribution is
        halobench's job, docs/OBSERVABILITY.md).
        """
        name = self._resolved
        if self.mesh is None or name not in (
            "dense", "bitpack", "pallas_bitpack"
        ):
            return None
        rows = self.mesh.shape.get(mesh_mod.ROWS, 1)
        cols = self.mesh.shape.get(mesh_mod.COLS, 1)
        two_d = mesh_mod.COLS in self.mesh.axis_names
        h = self.geometry.global_height // rows
        w = self.geometry.global_width // cols
        k = (
            8
            if name == "pallas_bitpack" and self.halo_depth == 1
            else self.halo_depth
        )
        if self.shard_mode == "auto":
            # XLA-derived exchanges: depth/count are the partitioner's
            # business; report the per-generation contract only.
            k = 1

        def band_bytes(d: int) -> int:
            if name == "dense":
                per_row = w  # uint8 cells
                col = 2 * d * (h + 2 * d) if two_d else 0
            elif name == "bitpack":
                per_row = (w // 32) * 4  # packed words
                col = 2 * d * (h + 2 * d) * 4 if two_d else 0
            else:  # pallas_bitpack: k-row packed band + 1-word column
                per_row = (w // 32) * 4
                col = 2 * (h + 2 * d) * 4 if two_d else 0
            return 2 * d * per_row + col

        full, rem = divmod(take, k)
        exchanges = full + (1 if rem else 0)
        chunk_bytes = full * band_bytes(k) + (band_bytes(rem) if rem else 0)
        state_bytes = h * w if name == "dense" else h * (w // 32) * 4
        payload = chunk_bytes + take * state_bytes
        return {
            "depth": k,
            "mode": self.shard_mode,
            "exchanges": exchanges,
            "band_bytes": chunk_bytes,
            "exchange_share": chunk_bytes / payload if payload else 0.0,
        }

    def chunk_utilization(self, take: int, wall_s: float):
        """Roofline fraction of one executed chunk (see telemetry module)."""
        from gol_tpu import telemetry as telemetry_mod

        if self._resolved in ("activity", "ooc"):
            # The flop model predicts dense device work; a program that
            # skips an activity-dependent fraction of it — or streams
            # bands with skip + transfer overlap (ooc) — has no honest
            # static roofline.  Report none rather than a wrong number.
            return None
        num_devices = 1 if self.mesh is None else self.mesh.devices.size
        cells = self.geometry.global_height * self.geometry.global_width
        return telemetry_mod.roofline_utilization(
            self._resolved,
            cells // max(num_devices, 1),
            take,
            self.halo_depth,
            sharded=self.mesh is not None,
            wall_s=wall_s,
        )

    # -- the out-of-core streaming tier (--engine ooc) ----------------------
    def _initial_board_host(
        self, pattern: int, resume: Optional[str] = None
    ) -> Tuple[np.ndarray, int]:
        """Host-resident board init for the ooc tier: same pattern and
        resume validation as :meth:`initial_state`, but the dense board
        never touches a device (``jax.device_put`` of a bigger-than-HBM
        board is the one thing this tier exists to avoid)."""
        self._resume_source = resume or None
        self.last_reshard = None
        if resume and ckpt_mod.is_sharded(resume):
            raise ValueError(
                "engine 'ooc' resumes from whole-board snapshots (its "
                "board is host-resident and meshless); a sharded "
                "checkpoint directory reshards through a mesh tier first"
            )
        if resume:
            snap = ckpt_mod.load(resume)
            if snap.num_ranks != self.geometry.num_ranks:
                raise ValueError(
                    f"checkpoint has {snap.num_ranks} ranks, run configured "
                    f"for {self.geometry.num_ranks}"
                )
            expected = (self.geometry.global_height, self.geometry.global_width)
            if snap.board.shape != expected:
                raise ValueError(
                    f"checkpoint board {snap.board.shape} != configured "
                    f"{expected}"
                )
            if snap.rule is not None:
                raise ValueError(
                    f"checkpoint was written by a {snap.rule} run; engine "
                    "'ooc' streams B3/S23 only — resume it on "
                    "'dense'/'bitpack' with the matching --rule"
                )
            return np.asarray(snap.board), int(snap.generation)
        board_np = patterns.init_global(
            pattern, self.geometry.size, self.geometry.num_ranks
        )
        return board_np, 0

    def _run_ooc(
        self,
        pattern: int,
        iterations: int,
        resume: Optional[str] = None,
        profile_dir: Optional[str] = None,
    ) -> Tuple[RunReport, GolState]:
        """The host-driven run loop behind ``--engine ooc``.

        Mirrors :meth:`run`'s chunk contract — schedule, telemetry,
        stats, checkpoint cadence, preemption, fault-plane drain — but
        the board stays in host RAM as a packed numpy array and each
        chunk streams row-bands through the device via
        :class:`gol_tpu.ooc.OocScheduler` (docs/STREAMING.md).  Chunk
        events carry the schema-v15 ``ooc`` block.
        """
        import time as time_mod
        import types

        from gol_tpu import telemetry as telemetry_mod
        from gol_tpu.ooc import OocScheduler
        from gol_tpu.resilience import degrade as degrade_mod
        from gol_tpu.resilience import faults as faults_mod
        from gol_tpu.telemetry import stats as tstats_mod

        if jax.process_count() > 1:
            raise ValueError(
                "engine 'ooc' is single-process: the board lives in one "
                "host's RAM and streams through one device"
            )
        plan_on = faults_mod.active() is not None
        plan = self._ooc_plan
        sw = Stopwatch()
        self.last_stats = []
        self.last_activity = []
        self.last_ooc = []
        self._ckpt_shed = False
        with sw.phase("init"):
            board_np, generation = self._initial_board_host(pattern, resume)
            sched = OocScheduler(plan, skip_dead=self.ooc_skip_dead)
            sched.load_dense(board_np)
            del board_np  # the packed host board is the state now

        schedule = self.chunk_schedule(
            iterations,
            self.checkpoint_every if self.checkpoint_every > 0 else iterations,
        )
        events = self.open_event_log()
        self._live_events = events
        sc = telemetry_mod.SpanClock() if events is not None else None

        def _drain_plane():
            if events is None:
                return
            for f in faults_mod.drain_fired():
                events.fault_event(**f)
            for d in degrade_mod.drain_reports():
                events.degraded_event(**d)

        def _host_state():
            # Dense unpack (host-side) for snapshot parity: an ooc
            # checkpoint is bit-identical to an in-core one, so resume
            # works across tiers in both directions.
            return types.SimpleNamespace(
                board=sched.dense(), generation=generation
            )

        try:
            with sw.phase("compile"):
                # Every (band height, visit depth) shape the schedule
                # needs, compiled before the timed loop (the same
                # steady-state contract as compile_evolvers).
                if events is not None:
                    def on_compile(info):
                        events.compile_event(
                            info["depth"],
                            info["lower_s"],
                            info["compile_s"],
                            memory=tstats_mod.compiled_memory(
                                info["executable"]
                            ),
                        )

                    sched.on_compile = on_compile
                depths = set()
                for take in set(schedule):
                    if take >= plan.depth:
                        depths.add(plan.depth)
                    if take % plan.depth:
                        depths.add(take % plan.depth)
                for bh in sorted(set(plan.band_heights())):
                    for kk in sorted(depths):
                        sched._program(bh, kk)

            writer = None
            if self.checkpoint_every > 0:
                writer = ckpt_mod.AsyncSnapshotWriter()
            self._ckpt_writer = writer
            try:
                with maybe_profile(profile_dir), telemetry_mod.trace_annotation(
                    "gol.run.evolve"
                ):
                    for i, take in enumerate(schedule):
                        # --stats forfeits in-place thrift the same way
                        # in-core stats forfeits donation: one extra
                        # packed board for the chunk-start diff.
                        prev_packed = (
                            sched.board.copy() if self.stats else None
                        )
                        with telemetry_mod.step_annotation("gol.chunk", i):
                            with sw.phase("total"):
                                t0 = time_mod.perf_counter()
                                rep = sched.run_chunk(take, generation)
                                dt = time_mod.perf_counter() - t0
                        generation += take
                        self.last_ooc.append(
                            dict(
                                index=i,
                                take=take,
                                generation=generation,
                                **rep,
                            )
                        )
                        if events is not None:
                            spans = sc.take()
                            extra = {"ooc": rep}
                            if spans:
                                extra["spans"] = spans
                            with sc.span("telemetry"):
                                events.chunk_event(
                                    i,
                                    take,
                                    generation,
                                    dt,
                                    self.geometry.cell_updates(take),
                                    self.chunk_utilization(take, dt),
                                    **extra,
                                )
                        if self.stats:
                            from gol_tpu.ops import stats as ops_stats

                            vals = ops_stats.ooc_chunk_stats_np(
                                prev_packed,
                                sched.board,
                                plan.bands,
                                plan.width,
                                max(1, self.halo_depth),
                            )
                            self.last_stats.append(
                                dict(
                                    index=i,
                                    take=take,
                                    generation=generation,
                                    **vals,
                                )
                            )
                            if events is not None:
                                with sc.span("telemetry"):
                                    events.stats_event(
                                        i, take, generation, vals
                                    )
                        if self.checkpoint_every > 0 and not self._ckpt_shed:
                            state = _host_state()
                            with telemetry_mod.trace_annotation(
                                "gol.checkpoint.save"
                            ):
                                with sw.phase("checkpoint"):
                                    t0 = time_mod.perf_counter()
                                    self._save_snapshot(state)
                                    ck = time_mod.perf_counter() - t0
                            if sc is not None:
                                sc.add("checkpoint", ck)
                            if events is not None:
                                with sc.span("telemetry"):
                                    events.checkpoint_event(
                                        generation,
                                        ck,
                                        int(state.board.size),
                                        overlapped=writer is not None,
                                    )
                        if plan_on:
                            faults_mod.crash_or_stall(generation)
                        _drain_plane()
                        if i < len(schedule) - 1:
                            from gol_tpu import resilience

                            if sc is None:
                                preempt_now = (
                                    resilience.agreed_preempt_requested()
                                )
                            else:
                                with sc.span("preempt_poll"):
                                    preempt_now = (
                                        resilience.agreed_preempt_requested()
                                    )
                            if preempt_now:
                                self._preempt(
                                    _host_state(),
                                    sw,
                                    writer,
                                    events,
                                    already_saved=self.checkpoint_every > 0,
                                )
                if writer is not None:
                    with sw.phase("checkpoint"):
                        writer.flush()
            finally:
                self._ckpt_writer = None
                if writer is not None:
                    writer.close()

            _drain_plane()
            report = sw.report(self.geometry.cell_updates(iterations))
            if events is not None:
                events.summary(report)
        finally:
            self._live_events = None
            if events is not None:
                events.close()
        # The returned state keeps the board HOST-resident on purpose —
        # GolState.create would device_put a board this tier exists to
        # keep off the device.  Consumers (dump paths, tests) treat it
        # as an array; np.asarray is a no-op.
        state = GolState(
            board=sched.dense(), generation=np.uint32(generation)
        )
        return report, state

    # -- main entry ---------------------------------------------------------
    def run(
        self,
        pattern: int,
        iterations: int,
        resume: Optional[str] = None,
        profile_dir: Optional[str] = None,
    ) -> Tuple[RunReport, GolState]:
        if self._resolved == "ooc":
            return self._run_ooc(pattern, iterations, resume, profile_dir)
        import time as time_mod

        from gol_tpu import telemetry as telemetry_mod
        from gol_tpu.resilience import degrade as degrade_mod
        from gol_tpu.resilience import faults as faults_mod

        plan_on = faults_mod.active() is not None
        sw = Stopwatch()
        self.last_stats = []
        self.last_activity = []
        self._ckpt_shed = False
        with sw.phase("init"):
            state = self.initial_state(pattern, resume)
            board = state.board
            act_mask = (
                self._initial_activity_mask()
                if self._resolved == "activity"
                else None
            )

        # Chunk schedule: full chunks of `checkpoint_every` plus one tail.
        schedule = self.chunk_schedule(
            iterations,
            self.checkpoint_every if self.checkpoint_every > 0 else iterations,
        )

        if self.mesh is not None:
            board = mesh_mod.shard_board(board, self.mesh)

        events = self.open_event_log()
        self._live_events = events
        # Span attribution (schema v6): host-phase seconds between
        # force_ready fences, emitted as the `spans` block on each chunk
        # event.  Telemetry-off runs never construct the clock, so the
        # off path stays byte-for-byte the old one.
        sc = telemetry_mod.SpanClock() if events is not None else None

        def _drain_plane():
            if events is None:
                return
            for f in faults_mod.drain_fired():
                events.fault_event(**f)
            for d in degrade_mod.drain_reports():
                events.degraded_event(**d)
        try:
            with sw.phase("compile"):
                evolvers = self.compile_evolvers(board, schedule, events)

            writer = None
            if self.checkpoint_every > 0 and jax.process_count() == 1:
                # Overlap snapshot writes with the next chunk's compute;
                # the final flush (inside the checkpoint phase, so the
                # report stays honest about I/O cost that did NOT overlap)
                # fences run completion on every snapshot being durably
                # renamed.
                writer = ckpt_mod.AsyncSnapshotWriter()
            self._ckpt_writer = writer
            try:
                with maybe_profile(profile_dir), telemetry_mod.trace_annotation(
                    "gol.run.evolve"
                ):
                    for i, take in enumerate(schedule):
                        compiled, dynamic = evolvers[take]
                        dev_stats = None
                        dev_act = None
                        with telemetry_mod.step_annotation("gol.chunk", i):
                            with sw.phase("total"):
                                t0 = time_mod.perf_counter()
                                if act_mask is not None:
                                    out = compiled(
                                        board, act_mask, *dynamic
                                    )
                                else:
                                    out = compiled(board, *dynamic)
                                t1 = time_mod.perf_counter()
                                if act_mask is not None:
                                    if self.stats:
                                        (board, act_mask, dev_act,
                                         dev_stats) = out
                                    else:
                                        board, act_mask, dev_act = out
                                else:
                                    if self.stats:
                                        board, dev_stats = out
                                    else:
                                        board = out
                                force_ready(board)
                                dt = time_mod.perf_counter() - t0
                        if sc is not None:
                            # dispatch = enqueue until the async call
                            # returns; ready = the block_until_ready
                            # fence.  Together they partition wall_s.
                            sc.add("dispatch", t1 - t0)
                            sc.add("ready", dt - (t1 - t0))
                        if plan_on:
                            # Fault-plane SDC injection (board.bitflip):
                            # a host-side functional cell update between
                            # chunk programs — the un-audited path takes
                            # the corruption silently, which is exactly
                            # what the guard-coverage matrix proves.
                            board = faults_mod.apply_board_faults(
                                board, int(state.generation) + take
                            )
                        state = GolState.create(
                            board, int(state.generation) + take
                        )
                        act_block = None
                        if dev_act is not None:
                            # Scalar fetch after the timed fence, like
                            # the stats values below.
                            act_block = self._activity_block(take, dev_act)
                            self.last_activity.append(
                                dict(
                                    index=i,
                                    take=take,
                                    generation=int(state.generation),
                                    **act_block,
                                )
                            )
                        if events is not None:
                            extra = (
                                {"activity": act_block} if act_block else {}
                            )
                            halo_blk = self._halo_block(take)
                            if halo_blk is not None:
                                # Schema v8: the exchange accounting of
                                # this chunk's compiled ring program.
                                extra["halo"] = halo_blk
                            # The drained spans cover this chunk's
                            # dispatch/ready plus the boundary phases
                            # since the previous chunk's event; writing
                            # the event itself is timed into the NEXT
                            # chunk's block.
                            spans = sc.take()
                            if spans:
                                extra["spans"] = spans
                            with sc.span("telemetry"):
                                events.chunk_event(
                                    i,
                                    take,
                                    int(state.generation),
                                    dt,
                                    self.geometry.cell_updates(take),
                                    self.chunk_utilization(take, dt),
                                    **extra,
                                )
                        if dev_stats is not None:
                            # The scalar fetch happens after the timed
                            # fence (the same program already produced
                            # them — this moves a few dozen bytes).
                            from gol_tpu.telemetry import (
                                stats as stats_mod,
                            )

                            vals = stats_mod.stats_values(dev_stats)
                            self.last_stats.append(
                                dict(
                                    index=i,
                                    take=take,
                                    generation=int(state.generation),
                                    **vals,
                                )
                            )
                            if events is not None:
                                with sc.span("telemetry"):
                                    events.stats_event(
                                        i, take, int(state.generation), vals
                                    )
                        if self.checkpoint_every > 0 and not self._ckpt_shed:
                            with telemetry_mod.trace_annotation(
                                "gol.checkpoint.save"
                            ):
                                with sw.phase("checkpoint"):
                                    t0 = time_mod.perf_counter()
                                    self._save_snapshot(state)
                                    dt = time_mod.perf_counter() - t0
                            if sc is not None:
                                sc.add("checkpoint", dt)
                            if events is not None:
                                with sc.span("telemetry"):
                                    events.checkpoint_event(
                                        int(state.generation),
                                        dt,
                                        int(state.board.size),
                                        overlapped=writer is not None,
                                    )
                        if plan_on:
                            faults_mod.crash_or_stall(
                                int(state.generation)
                            )
                        _drain_plane()
                        if i < len(schedule) - 1:
                            # Chunk-boundary preemption poll: host-side
                            # only (the compiled programs never see it).
                            # With work remaining, stop here — the board
                            # is whole and fenced; a snapshot for this
                            # boundary either just landed or is written
                            # now.
                            from gol_tpu import resilience

                            if sc is None:
                                preempt_now = (
                                    resilience.agreed_preempt_requested()
                                )
                            else:
                                with sc.span("preempt_poll"):
                                    preempt_now = (
                                        resilience.agreed_preempt_requested()
                                    )
                            if preempt_now:
                                self._preempt(
                                    state,
                                    sw,
                                    writer,
                                    events,
                                    already_saved=self.checkpoint_every > 0,
                                )
                            if (
                                self.reshard_at > 0
                                and int(state.generation) >= self.reshard_at
                            ):
                                # In-flight reshard stop: same boundary
                                # contract as preemption, but the driver
                                # continues on a new mesh immediately.
                                self._reshard_stop(
                                    state, sw, writer,
                                    remaining=sum(schedule[i + 1 :]),
                                )
                if writer is not None:
                    with sw.phase("checkpoint"):
                        writer.flush()
            finally:
                self._ckpt_writer = None
                if writer is not None:
                    writer.close()

            # Writer-thread faults fired during the final flush surface
            # before the stream closes.
            _drain_plane()
            report = sw.report(self.geometry.cell_updates(iterations))
            if events is not None:
                events.summary(report)
        finally:
            self._live_events = None
            if events is not None:
                events.close()
        return report, state
