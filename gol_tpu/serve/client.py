"""A tiny stdlib client for the serving tier (docs/SERVING.md).

``urllib`` only — the client exists for the smoke drill, the chaos
cells, and servebench, not as a product surface.  The one behavior that
matters is **idempotent resubmission**: callers pass their own request
``id``, and :meth:`SimClient.submit` retries connection errors (the
server may be mid-supervised-restart) by resubmitting the same id —
admission is exactly-once on the id, so a retry can never double-run a
request.  429/503 rejections surface as :class:`Backpressure` with the
server's ``retry_after`` hint.
"""

from __future__ import annotations

import json
import time
import urllib.error
import urllib.request
from typing import Optional


class Backpressure(RuntimeError):
    """The server explicitly rejected (429/503) — retry later."""

    def __init__(
        self, status: int, message: str, retry_after: Optional[float]
    ) -> None:
        super().__init__(message)
        self.status = status
        self.retry_after = retry_after


class SimClient:
    def __init__(self, base_url: str, timeout: float = 30.0) -> None:
        self.base_url = base_url.rstrip("/")
        self.timeout = timeout

    def _call(self, method: str, path: str, body: Optional[dict] = None):
        data = (
            json.dumps(body).encode() if body is not None else None
        )
        req = urllib.request.Request(
            self.base_url + path, data=data, method=method,
            headers={"Content-Type": "application/json"},
        )
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as r:
                return r.status, json.loads(r.read() or b"{}")
        except urllib.error.HTTPError as e:
            try:
                payload = json.loads(e.read() or b"{}")
            except json.JSONDecodeError:
                payload = {"error": str(e)}
            return e.code, payload

    def submit(
        self,
        request: dict,
        connect_retries: int = 0,
        retry_delay_s: float = 0.5,
    ) -> dict:
        """POST /simulate.  ``connect_retries`` resubmits the same id
        across connection drops (supervised restarts) — safe because
        admission is idempotent on the id.  That safety is exactly why
        retries REQUIRE a caller-supplied ``id``: without one the server
        mints a fresh id per submission, so a resubmitted retry would be
        admitted (and run) twice."""
        if connect_retries > 0 and "id" not in request:
            raise ValueError(
                "connect_retries requires a caller-supplied 'id': "
                "server-generated ids make every resubmission a NEW "
                "request, so a retry would double-run it"
            )
        attempt = 0
        while True:
            try:
                status, payload = self._call(
                    "POST", "/simulate", request
                )
            except (urllib.error.URLError, ConnectionError, OSError):
                if attempt >= connect_retries:
                    raise
                attempt += 1
                time.sleep(retry_delay_s)
                continue
            if status in (200, 202):
                return payload
            if status in (429, 503):
                raise Backpressure(
                    status, payload.get("error", "rejected"),
                    payload.get("retry_after"),
                )
            raise RuntimeError(
                f"submit failed ({status}): {payload.get('error')}"
            )

    def result(self, request_id: str):
        """GET /result/<id> -> (status_code, payload)."""
        return self._call("GET", f"/result/{request_id}")

    def wait_for(
        self,
        request_id: str,
        timeout_s: float = 60.0,
        poll_s: float = 0.05,
        connect_retries: int = 0,
    ) -> dict:
        """Poll until the request reaches a terminal payload.  Connection
        drops are tolerated up to ``connect_retries`` times total (the
        supervised server may be restarting under an armed fault plan)."""
        deadline = time.time() + timeout_s
        drops = 0
        while time.time() < deadline:
            try:
                status, payload = self.result(request_id)
            except (urllib.error.URLError, ConnectionError, OSError):
                drops += 1
                if drops > connect_retries:
                    raise
                time.sleep(max(poll_s, 0.2))
                continue
            if status == 200:
                return payload
            if status == 404:
                raise KeyError(f"server does not know {request_id!r}")
            time.sleep(poll_s)
        raise TimeoutError(
            f"request {request_id!r} not terminal after {timeout_s}s"
        )

    def trace_summary(self, request_id: str) -> dict:
        """The client-side view of its own trace (schema v12): the
        ``trace_id`` plus, once terminal, the server's latency
        decomposition — enough for a caller to log "my request spent
        X s queued, Y s computing, Z s stalled" and to hand the id to
        ``python -m gol_tpu.telemetry trace --request <id>`` for the
        full span tree.  Works mid-flight too (202 tickets carry the
        trace id; the decomposition is then empty)."""
        status, payload = self.result(request_id)
        if status == 404:
            raise KeyError(f"server does not know {request_id!r}")
        return {
            "id": request_id,
            "status": payload.get("status"),
            "trace_id": payload.get("trace_id", ""),
            "decomposition": payload.get("decomposition", {}),
        }

    def healthz(self) -> dict:
        status, payload = self._call("GET", "/healthz")
        if status != 200:
            raise RuntimeError(f"healthz returned {status}")
        return payload

    def shutdown(self) -> None:
        """Ask for a graceful drain (POST /shutdown)."""
        self._call("POST", "/shutdown")
