"""A tiny stdlib client for the serving tier (docs/SERVING.md).

``urllib`` only — the client exists for the smoke drill, the chaos
cells, and servebench, not as a product surface.  The one behavior that
matters is **idempotent resubmission**: callers pass their own request
``id``, and :meth:`SimClient.submit` retries connection errors (the
server may be mid-supervised-restart) by resubmitting the same id —
admission is exactly-once on the id, so a retry can never double-run a
request.  429/503 rejections surface as :class:`Backpressure` with the
server's ``retry_after`` hint.

The client is fleet-aware (docs/SERVING.md "The fleet"), and both
behaviors are inert against a single server:

- a 307 from a front tier in direct-to-replica mode carries the routed
  replica's base URL plus the ``owner_epoch`` to stamp; ``submit``
  re-POSTs there itself (one hop, never a loop).
- a 404 from :meth:`wait_for` that carries a ``routing_epoch`` is a
  mid-handoff window, not a verdict: the poll retries until the 404
  survives an epoch CHANGE (the fleet re-resolved membership and still
  does not know the id) — a plain 404 with no epoch stays immediately
  fatal, exactly as before.
"""

from __future__ import annotations

import json
import time
import urllib.error
import urllib.request
from typing import Optional


class Backpressure(RuntimeError):
    """The server explicitly rejected (429/503) — retry later."""

    def __init__(
        self, status: int, message: str, retry_after: Optional[float]
    ) -> None:
        super().__init__(message)
        self.status = status
        self.retry_after = retry_after


class SimClient:
    def __init__(self, base_url: str, timeout: float = 30.0) -> None:
        self.base_url = base_url.rstrip("/")
        self.timeout = timeout

    def _call(self, method: str, path: str, body: Optional[dict] = None):
        data = (
            json.dumps(body).encode() if body is not None else None
        )
        url = (
            path if path.startswith("http") else self.base_url + path
        )
        req = urllib.request.Request(
            url, data=data, method=method,
            headers={"Content-Type": "application/json"},
        )
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as r:
                return r.status, json.loads(r.read() or b"{}")
        except urllib.error.HTTPError as e:
            try:
                payload = json.loads(e.read() or b"{}")
            except json.JSONDecodeError:
                payload = {"error": str(e)}
            return e.code, payload

    def submit(
        self,
        request: dict,
        connect_retries: int = 0,
        retry_delay_s: float = 0.5,
    ) -> dict:
        """POST /simulate.  ``connect_retries`` resubmits the same id
        across connection drops (supervised restarts) — safe because
        admission is idempotent on the id.  That safety is exactly why
        retries REQUIRE a caller-supplied ``id``: without one the server
        mints a fresh id per submission, so a resubmitted retry would be
        admitted (and run) twice."""
        if connect_retries > 0 and "id" not in request:
            raise ValueError(
                "connect_retries requires a caller-supplied 'id': "
                "server-generated ids make every resubmission a NEW "
                "request, so a retry would double-run it"
            )
        attempt = 0
        while True:
            try:
                status, payload = self._call(
                    "POST", "/simulate", request
                )
            except (urllib.error.URLError, ConnectionError, OSError):
                if attempt >= connect_retries:
                    raise
                attempt += 1
                time.sleep(retry_delay_s)
                continue
            if status in (200, 202):
                return payload
            if status == 307 and "replica" in payload:
                # Fleet direct-to-replica mode: the front tier answered
                # a routing hint instead of proxying.  Re-POST the body
                # to the routed replica ourselves, stamped with the id
                # the front minted and the routing epoch it pinned —
                # one hop only (a replica never answers 307 itself).
                routed = {
                    **request,
                    "id": payload["id"],
                    "owner_epoch": payload["owner_epoch"],
                }
                try:
                    status, payload = self._call(
                        "POST",
                        payload["replica"].rstrip("/") + "/simulate",
                        routed,
                    )
                except (
                    urllib.error.URLError, ConnectionError, OSError,
                ):
                    if attempt >= connect_retries:
                        raise
                    attempt += 1
                    time.sleep(retry_delay_s)
                    continue
                if status in (200, 202):
                    return payload
            if status in (429, 503):
                raise Backpressure(
                    status, payload.get("error", "rejected"),
                    payload.get("retry_after"),
                )
            raise RuntimeError(
                f"submit failed ({status}): {payload.get('error')}"
            )

    def result(self, request_id: str):
        """GET /result/<id> -> (status_code, payload)."""
        return self._call("GET", f"/result/{request_id}")

    def wait_for(
        self,
        request_id: str,
        timeout_s: float = 60.0,
        poll_s: float = 0.05,
        connect_retries: int = 0,
    ) -> dict:
        """Poll until the request reaches a terminal payload.  Connection
        drops are tolerated up to ``connect_retries`` times total (the
        supervised server may be restarting under an armed fault plan).

        Against a fleet front tier a 404 carries the ``routing_epoch``
        it was observed under; a mid-handoff poll (the id is between
        owners) must not read as lost, so the 404 only becomes fatal
        once it survives an epoch change — the membership event
        resolved and the fleet STILL does not know the id.  A 404
        without an epoch (a single server) stays immediately fatal."""
        deadline = time.time() + timeout_s
        drops = 0
        first_404_epoch: Optional[int] = None
        while time.time() < deadline:
            try:
                status, payload = self.result(request_id)
            except (urllib.error.URLError, ConnectionError, OSError):
                drops += 1
                if drops > connect_retries:
                    raise
                time.sleep(max(poll_s, 0.2))
                continue
            if status == 200:
                return payload
            if status == 404:
                epoch = payload.get("routing_epoch")
                if epoch is None:
                    raise KeyError(
                        f"server does not know {request_id!r}"
                    )
                if first_404_epoch is None:
                    first_404_epoch = epoch
                elif epoch > first_404_epoch:
                    raise KeyError(
                        f"fleet does not know {request_id!r} "
                        f"(held across routing epoch "
                        f"{first_404_epoch} -> {epoch})"
                    )
            time.sleep(poll_s)
        raise TimeoutError(
            f"request {request_id!r} not terminal after {timeout_s}s"
        )

    def trace_summary(self, request_id: str) -> dict:
        """The client-side view of its own trace (schema v12): the
        ``trace_id`` plus, once terminal, the server's latency
        decomposition — enough for a caller to log "my request spent
        X s queued, Y s computing, Z s stalled" and to hand the id to
        ``python -m gol_tpu.telemetry trace --request <id>`` for the
        full span tree.  Works mid-flight too (202 tickets carry the
        trace id; the decomposition is then empty)."""
        status, payload = self.result(request_id)
        if status == 404:
            raise KeyError(f"server does not know {request_id!r}")
        return {
            "id": request_id,
            "status": payload.get("status"),
            "trace_id": payload.get("trace_id", ""),
            "decomposition": payload.get("decomposition", {}),
        }

    def healthz(self) -> dict:
        status, payload = self._call("GET", "/healthz")
        if status != 200:
            raise RuntimeError(f"healthz returned {status}")
        return payload

    def shutdown(self) -> None:
        """Ask for a graceful drain (POST /shutdown)."""
        self._call("POST", "/shutdown")
