"""The serving fleet: a replicated front tier (docs/SERVING.md "The fleet").

One server survives crashes (PR 12), device loss (PR 14), and dying
disks (PR 18) — but it is still one host.  The fleet lifts the same
journal-fold durability argument one level up: a stdlib front tier owns
admission for N supervised replica processes, routes every request by
consistent hash of its **bucket key** (H, W, engine) so compile caches
and bucket groups stay hot per-replica, and migrates a dead replica's
open intents instead of waiting out its supervisor restart.

Topology::

    client ──> FleetServer (front tier, 127.0.0.1)
                 │  routing: HashRing over alive replicas,
                 │  keyed by bucket (H, W, engine)
                 │  fleet journal: epoch / route / handoff records
                 ├──> replica r0  (supervise → python -m gol_tpu.serve)
                 ├──> replica r1   each with its own state dir,
                 └──> replica r2   journal, and compile caches

Three ideas carry the design:

- **Routing epoch** (the PAPERS.md "setup once, fire often" schedule,
  one level up): the consistent-hash ring is *pinned* — it only changes
  on a membership event, and every change bumps an integer epoch that
  is journaled in the front tier's own journal and stamped into every
  proxied request as ``owner_epoch``.  A front-tier crash restores its
  epoch and route map from the journal fold (:func:`fleet_replay`).
- **Handoff moves intents, never state** (the redistribution framing):
  on a ``replica_dead`` verdict from the
  :class:`gol_tpu.resilience.health.HostMonitor`, the front tier folds
  the dead replica's journal, and re-admits each open
  (admitted-but-incomplete) intent to a surviving replica under the
  SAME request id — open requests replay from their initial pattern,
  which is exact (Life is deterministic), so no board bytes move.
- **Ownership fencing** makes the migration idempotent and first-wins:
  a ``handoff`` record lands on BOTH sides (the dead replica's journal
  and the fleet's own) before the re-admit, so the original replica
  returning from supervisor restart folds its journal, finds the
  intent fenced (``owner_epoch`` < the handoff epoch), and re-runs
  nothing; a replica returning alive from a stall gets a live
  ``POST /fence`` instead.  Exactly-once holds at the *fold* level:
  even a straggler ``complete`` physically written under the old epoch
  does not count (gol_tpu/serve/journal.py).

Everything is observable: schema-v14 ``fleet`` events
(route/epoch/handoff/replica), the ``gol_fleet_*`` metrics, and
``GET /fleet/status``.  The fault sites ``replica.kill`` /
``replica.stall`` / ``fleet.partition`` fire from the front tier's
probe loop so the chaos matrix and ``scripts/fleet_smoke.py`` exercise
the real code path.  Fleet mode off changes nothing: the single-server
stack never imports this module and its journals carry no
``owner_epoch`` (the trace-identity pin in tests/test_fleet.py).
"""

from __future__ import annotations

import argparse
import bisect
import dataclasses
import hashlib
import http.server
import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional, Tuple

from gol_tpu.resilience import faults as faults_mod
from gol_tpu.resilience.health import HostMonitor
from gol_tpu.serve import journal as journal_mod
from gol_tpu.serve.client import SimClient

#: gol_tpu.ops.bitlife.BITS, restated so the front tier never imports
#: the device stack (it proxies bytes; it must start in milliseconds).
_WORD_BITS = 32


def bucket_key(
    size: int, engine: str, quantum: int
) -> Tuple[int, int, str]:
    """The routing key: the bucket the serve scheduler would group this
    request into (scheduler._group_for, restated without the device
    stack).  Serve groups always run the masked programs, so
    ``pallas_bitpack`` resolves to its documented ``bitpack`` fallback
    — identical requests land in identical groups on whichever replica
    the ring picks."""
    up = -(-size // quantum) * quantum
    packable = size % _WORD_BITS == 0
    if engine == "dense":
        name = "dense"
    elif engine == "bitpack":
        name = "bitpack"  # unpackable widths: the replica rejects (400)
    else:  # auto / pallas_bitpack — the serve fallback collapses both
        name = "bitpack" if packable else "dense"
    return (up, up, name)


class HashRing:
    """Consistent hashing over replica names (64 vnodes each).

    Rebuilt ONLY on membership change — the routing-epoch pin: between
    epochs, a bucket key always lands on the same replica, which is
    what keeps its compiled programs and bucket groups hot."""

    def __init__(self, members: List[str], vnodes: int = 64) -> None:
        ring = []
        for m in sorted(members):
            for v in range(vnodes):
                ring.append((_hash64(f"{m}#{v}"), m))
        ring.sort()
        self._hashes = [h for h, _ in ring]
        self._members = [m for _, m in ring]

    def lookup(self, key: Tuple) -> str:
        if not self._hashes:
            raise RuntimeError("hash ring is empty: no alive replicas")
        h = _hash64("|".join(str(k) for k in key))
        i = bisect.bisect_right(self._hashes, h) % len(self._hashes)
        return self._members[i]


def _hash64(s: str) -> int:
    return int.from_bytes(
        hashlib.sha256(s.encode()).digest()[:8], "big"
    )


def fleet_replay(path: str) -> Tuple[int, List[str], Dict[str, dict]]:
    """Fold the front tier's own journal: ``(epoch, members, routes)``.

    ``epoch``/``members`` come from the newest ``epoch`` record;
    ``routes`` maps request id -> ``{"replica", "bucket", "epoch"}``
    with ``handoff`` records overriding earlier routes (a handoff IS a
    re-route).  Torn lines are unacknowledged and ignored, same
    tolerance as :func:`gol_tpu.serve.journal.replay` — a front-tier
    crash+restart reconstructs its routing state from this fold."""
    epoch = 0
    members: List[str] = []
    routes: Dict[str, dict] = {}
    if not os.path.exists(path):
        return epoch, members, routes
    with open(path, encoding="utf-8", errors="replace") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            kind = rec.get("rec")
            if kind == "epoch":
                e = int(rec.get("epoch", 0) or 0)
                if e >= epoch:
                    epoch = e
                    members = list(rec.get("members", []))
            elif kind == "route":
                routes[rec["id"]] = {
                    "replica": rec.get("replica"),
                    "bucket": rec.get("bucket"),
                    "epoch": int(rec.get("epoch", 0) or 0),
                }
            elif kind == "handoff":
                e = int(rec.get("epoch", 0) or 0)
                r = routes.get(rec["id"])
                if r is None or e >= r["epoch"]:
                    routes[rec["id"]] = {
                        "replica": rec.get("dst"),
                        "bucket": (r or {}).get("bucket"),
                        "epoch": e,
                    }
    return epoch, members, routes


@dataclasses.dataclass
class ReplicaHandle:
    """One supervised replica as the front tier sees it."""

    name: str
    base_url: str
    state_dir: str  # the replica's --state-dir (its journal lives here)
    manifest: str = ""  # supervisor manifest (live attempt's pid)
    proc: Optional[subprocess.Popen] = None  # the supervisor process

    @property
    def journal_path(self) -> str:
        return os.path.join(self.state_dir, "journal.jsonl")


class FleetFront:
    """The fleet state machine (transport-free core).

    :class:`FleetServer` puts HTTP in front of it; the chaos cells and
    servebench drive it in-process.  Thread model mirrors the serve
    scheduler: handler threads call :meth:`submit` / :meth:`result`
    through the lock; the owner's main loop calls :meth:`poll`.
    """

    def __init__(
        self,
        replicas: List[ReplicaHandle],
        state_dir: str,
        quantum: int = 64,
        default_engine: str = "auto",
        events=None,
        registry=None,
        monitor: Optional[HostMonitor] = None,
        client_timeout: float = 30.0,
        probe_timeout: float = 2.0,
    ) -> None:
        if not replicas:
            raise ValueError("a fleet needs at least one replica")
        self.replicas = {r.name: r for r in replicas}
        self.state_dir = state_dir
        self.quantum = quantum
        self.default_engine = default_engine
        self._events = events
        self._registry = registry
        self._lock = threading.RLock()
        self._clients = {
            r.name: SimClient(r.base_url, timeout=client_timeout)
            for r in replicas
        }
        # Heartbeats get their own short-timeout clients: a probe into
        # a frozen replica must read as a missed beat in ~probe_timeout,
        # not hang the whole probe loop for the proxy timeout.
        self._probe_clients = {
            r.name: SimClient(r.base_url, timeout=probe_timeout)
            for r in replicas
        }
        self._monitor = monitor or HostMonitor(
            [r.name for r in replicas], events=events, registry=registry
        )
        self._journal = journal_mod.Journal(
            os.path.join(state_dir, "fleet.journal.jsonl")
        )
        # A restarted front tier restores its epoch and route map from
        # its own journal fold, then ALWAYS bumps: membership was
        # re-formed, and requests proxied before the crash must be
        # distinguishable from requests proxied after it.
        prev_epoch, _members, routes = fleet_replay(self._journal.path)
        self._routes = routes  # id -> {"replica", "bucket", "epoch"}
        self._epoch = prev_epoch
        self._ring = HashRing(self._monitor.alive)
        # Ids migrated OFF a replica while it was out, fenced live on
        # its restore (a stall survivor holds them in memory; a journal
        # fold only fences a restart).
        self._migrated: Dict[str, set] = {}
        # Re-admissions that could not land yet (target busy /
        # unreachable): retried every poll until they stick.
        self._pending: List[dict] = []
        self._partitioned_until: Dict[str, float] = {}
        self._stalled_until: Dict[str, float] = {}  # SIGCONT due times
        self._seq = 0
        self._tick = 0
        self.routed_total = 0
        self.handoffs_total = 0
        self.draining = False
        self._bump_epoch("boot")

    # -- epoch / emission -----------------------------------------------------

    def _bump_epoch(self, reason: str) -> None:
        with self._lock:
            self._epoch += 1
            members = self._monitor.alive
            self._ring = HashRing(members)
            self._journal.append(
                journal_mod.record(
                    "epoch", f"epoch-{self._epoch}",
                    epoch=self._epoch, members=members, reason=reason,
                )
            )
            self._emit(
                "epoch", epoch=self._epoch, members=members,
                reason=reason,
            )

    def _emit(self, action: str, **fields) -> None:
        if self._events is not None:
            self._events.fleet_event(action, **fields)
        elif self._registry is not None:
            self._registry.observe(
                dict(event="fleet", action=action, **fields)
            )

    @property
    def epoch(self) -> int:
        return self._epoch

    @property
    def alive(self) -> List[str]:
        return self._monitor.alive

    # -- admission ------------------------------------------------------------

    def submit(self, body, direct: bool = False) -> Tuple[int, dict]:
        """Route one request: ``(status, payload)``.

        Proxy mode forwards to the routed replica and relays its
        answer; ``direct`` mode answers 307 with the replica hint — the
        client re-POSTs there itself (one less proxy hop per request;
        the route is journaled either way).  Both stamp the current
        routing epoch into the proxied body as ``owner_epoch``."""
        if not isinstance(body, dict):
            return 400, {"error": "request body must be a JSON object"}
        if self.draining:
            return 503, {
                "error": "fleet draining", "retry_after": 5.0,
                "routing_epoch": self._epoch,
            }
        size = body.get("size")
        engine = body.get("engine", self.default_engine)
        if not isinstance(size, int) or isinstance(size, bool) or size < 1:
            return 400, {
                "error": f"'size' must be an integer >= 1, got {size!r}"
            }
        with self._lock:
            rid = body.get("id")
            if rid is None:
                # The front tier NEEDS an id (the route map keys on it),
                # so unlike the single server it mints before routing
                # and the replica sees a caller-supplied id.
                self._seq += 1
                rid = f"flt-{os.getpid()}-{self._seq:06d}"
            key = bucket_key(size, engine, self.quantum)
            bucket = f"{key[0]}x{key[1]}:{key[2]}"
            replica = self._ring.lookup(key)
            epoch = self._epoch
            self._journal.append(
                journal_mod.record(
                    "route", rid, bucket=bucket, replica=replica,
                    epoch=epoch,
                )
            )
            self._routes[rid] = {
                "replica": replica, "bucket": bucket, "epoch": epoch,
            }
            self.routed_total += 1
            self._emit(
                "route", request_id=rid, bucket=bucket,
                replica=replica, epoch=epoch,
            )
            client = self._clients[replica]
            base_url = self.replicas[replica].base_url
        out = {**body, "id": rid, "owner_epoch": epoch}
        if direct:
            return 307, {
                "replica": base_url, "id": rid,
                "owner_epoch": epoch, "routing_epoch": epoch,
                "bucket": bucket,
            }
        try:
            status, payload = client._call("POST", "/simulate", out)
        except OSError:
            # The replica died between verdicts.  The admit never
            # landed, so this is a clean backpressure reject — the
            # client resubmits the same id and the NEXT route (post
            # handoff epoch) wins.
            return 503, {
                "error": f"replica {replica} unreachable; retry",
                "retry_after": 1.0, "routing_epoch": epoch, "id": rid,
            }
        if isinstance(payload, dict):
            payload.setdefault("routing_epoch", epoch)
        return status, payload

    def result(self, request_id: str) -> Tuple[int, dict]:
        with self._lock:
            route = self._routes.get(request_id)
            epoch = self._epoch
            if route is None:
                return 404, {
                    "error": f"unknown request {request_id!r}",
                    "routing_epoch": epoch,
                }
            name = route["replica"]
            blind = (
                not self._monitor.is_alive(name)
                or self._partitioned_until.get(name, 0.0) > time.time()
            )
            client = self._clients[name]
        if blind:
            # Mid-handoff: the owner is out and the migration has not
            # (re)settled.  Never a 404 — the intent is journaled.
            return 202, {
                "id": request_id, "status": "migrating",
                "routing_epoch": epoch,
            }
        try:
            status, payload = client.result(request_id)
        except OSError:
            return 202, {
                "id": request_id, "status": "migrating",
                "routing_epoch": epoch,
            }
        if isinstance(payload, dict) and status != 200:
            payload.setdefault("routing_epoch", epoch)
        return status, payload

    # -- the probe loop -------------------------------------------------------

    def poll(self) -> None:
        """One probe round: fire armed fleet faults, heartbeat every
        replica, react to the monitor's verdicts, retry stranded
        re-admissions.  The owner calls this every probe interval."""
        self._tick += 1
        tick = self._tick
        self._fire_faults(tick)
        now = time.time()
        for name, due in list(self._stalled_until.items()):
            if now >= due:
                del self._stalled_until[name]
                self._signal_replica(name, signal.SIGCONT)
        for name in sorted(self.replicas):
            if self._partitioned_until.get(name, 0.0) > now:
                verdicts = self._monitor.beat(name, ok=False, tick=tick)
            else:
                t0 = time.time()
                try:
                    self._probe_clients[name].healthz()
                    ok, lat = True, time.time() - t0
                except Exception:
                    ok, lat = False, 0.0
                verdicts = self._monitor.beat(
                    name, ok, latency_s=lat, tick=tick
                )
            for v in verdicts:
                if v.kind == "replica_dead":
                    self._on_dead(name)
                elif v.kind == "replica_restore":
                    self._on_restore(name)
        self._retry_pending()

    def _fire_faults(self, tick: int) -> None:
        names = sorted(self.replicas)
        spec = faults_mod.fire("replica.kill", tick)
        if spec is not None:
            # Real process death: the supervisor restarts it, and the
            # restart's journal fold must find its intents fenced.
            self._signal_replica(
                names[spec.device % len(names)], signal.SIGKILL
            )
        spec = faults_mod.fire("replica.stall", tick)
        if spec is not None:
            # A real freeze (SIGSTOP, SIGCONT after delay_s): the
            # process keeps its memory, wakes mid-batch, and its late
            # journal writes must lose to the handoff at fold level.
            name = names[spec.device % len(names)]
            self._signal_replica(name, signal.SIGSTOP)
            self._stalled_until[name] = (
                time.time() + max(spec.delay_s, 0.0)
            )
        spec = faults_mod.fire("fleet.partition", tick)
        if spec is not None:
            # One-sided cut: the front goes blind for delay_s while the
            # replica stays healthy AND KEEPS EXECUTING — the hardest
            # exactly-once case (a live owner that looks dead).
            name = names[spec.device % len(names)]
            self._partitioned_until[name] = (
                time.time() + max(spec.delay_s, 0.0)
            )

    def _signal_replica(self, name: str, sig: int) -> None:
        handle = self.replicas[name]
        try:
            with open(handle.manifest) as f:
                pid = json.load(f)["attempts"][-1]["pid"]
            os.kill(pid, sig)
        except (OSError, KeyError, IndexError, ValueError,
                json.JSONDecodeError):
            pass  # already gone — the probe loop finds out either way

    # -- membership transitions ----------------------------------------------

    def _on_dead(self, name: str) -> None:
        with self._lock:
            self._bump_epoch(f"replica_dead:{name}")
            self._migrate(name)

    def _on_restore(self, name: str) -> None:
        with self._lock:
            self._bump_epoch(f"replica_restore:{name}")
            ids = sorted(self._migrated.pop(name, ()))
            epoch = self._epoch
            client = self._clients[name]
        if ids:
            # The journal fold fences a RESTARTED replica; a replica
            # back from a stall still holds the migrated intents live
            # in memory — the fence endpoint drops (and journals) them.
            try:
                client._call(
                    "POST", "/fence", {"ids": ids, "epoch": epoch}
                )
            except OSError:
                pass  # its own journal fold fences on the next restart

    def _migrate(self, name: str) -> None:
        """Move the dead replica's open intents to survivors — intent
        records only, never board state (open requests replay from
        their initial pattern, which is exact)."""
        handle = self.replicas[name]
        entries, _torn = journal_mod.replay(handle.journal_path)
        alive = self._monitor.alive
        epoch = self._epoch
        moved = self._migrated.setdefault(name, set())
        for rid, e in entries.items():
            if e["status"] not in ("admitted", "started"):
                continue  # completed results are durable — never moved
            req = dict(e["admit"].get("request") or {})
            key = bucket_key(
                int(req.get("size", 1) or 1),
                req.get("engine") or self.default_engine,
                self.quantum,
            )
            dst = self._ring.lookup(key) if alive else None
            handoff = journal_mod.record(
                "handoff", rid, epoch=epoch, src=name, dst=dst,
                by="fleet",
            )
            # Both sides, fence FIRST: the dead replica's journal (so
            # its restart fold finds ownership moved before any re-run
            # could journal), then the fleet's own (so a front restart
            # re-resolves the route).
            _append_foreign(handle.journal_path, handoff)
            self._journal.append(handoff)
            moved.add(rid)
            self.handoffs_total += 1
            bucket = f"{key[0]}x{key[1]}:{key[2]}"
            self._emit(
                "handoff", request_id=rid, src=name, dst=dst,
                epoch=epoch, bucket=bucket,
            )
            if dst is None:
                continue  # no survivors: routes stay parked on None
            self._routes[rid] = {
                "replica": dst, "bucket": bucket, "epoch": epoch,
            }
            self._pending.append(
                {
                    "id": rid, "dst": dst,
                    "body": {**req, "id": rid, "owner_epoch": epoch},
                }
            )

    def _retry_pending(self) -> None:
        with self._lock:
            pending, self._pending = self._pending, []
        for item in pending:
            dst = item["dst"]
            ok = False
            if self._monitor.is_alive(dst):
                try:
                    status, _payload = self._clients[dst]._call(
                        "POST", "/simulate", item["body"]
                    )
                    ok = status in (200, 202)
                except OSError:
                    ok = False
            else:
                # The target died too; re-route at the current epoch.
                with self._lock:
                    route = self._routes.get(item["id"])
                    if route is not None and route["replica"] != dst:
                        item["dst"] = route["replica"]
                        item["body"]["owner_epoch"] = route["epoch"]
            if not ok:
                with self._lock:
                    self._pending.append(item)

    # -- status / shutdown ----------------------------------------------------

    def status(self) -> dict:
        with self._lock:
            return {
                "epoch": self._epoch,
                "replicas": sorted(self.replicas),
                "alive": self._monitor.alive,
                "routed_total": self.routed_total,
                "handoffs_total": self.handoffs_total,
                "routes": len(self._routes),
                "pending_readmits": len(self._pending),
                "draining": self.draining,
            }

    def outstanding_pending(self) -> int:
        with self._lock:
            return len(self._pending)

    def drain(self, timeout_s: float = 60.0) -> None:
        """Graceful fleet drain: stop admitting, ask every replica to
        drain, wait for the supervisors to exit 0."""
        with self._lock:
            if self.draining:
                return
            self.draining = True
        self._emit("drain", epoch=self._epoch)
        for name in list(self._stalled_until):
            del self._stalled_until[name]
            self._signal_replica(name, signal.SIGCONT)
        for name in sorted(self.replicas):
            try:
                self._clients[name].shutdown()
            except Exception:
                pass
        deadline = time.time() + timeout_s
        for name in sorted(self.replicas):
            proc = self.replicas[name].proc
            if proc is None:
                continue
            try:
                proc.wait(timeout=max(deadline - time.time(), 0.1))
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait(timeout=5.0)

    def close(self) -> None:
        self._journal.close()


def _append_foreign(path: str, rec: dict) -> None:
    """Append one record into ANOTHER process's journal (the handoff
    write into a dead replica's file).  Heals a torn tail first — the
    replica may have died mid-append — with the same newline discipline
    as :meth:`Journal.append`, then fsyncs per record."""
    heal = False
    if os.path.exists(path) and os.path.getsize(path):
        with open(path, "rb") as f:
            f.seek(-1, os.SEEK_END)
            heal = f.read(1) != b"\n"
    line = json.dumps(rec, sort_keys=True)
    with open(path, "ab") as f:
        if heal:
            f.write(b"\n")
        f.write(line.encode() + b"\n")
        f.flush()
        os.fsync(f.fileno())


# -- HTTP ---------------------------------------------------------------------


class _FleetHandler(http.server.BaseHTTPRequestHandler):
    # Set on the per-server class copy by FleetServer:
    front: FleetFront
    registry = None
    stop_event: threading.Event
    direct: bool = False

    def _json(self, status: int, payload: dict, location=None) -> None:
        body = json.dumps(payload, sort_keys=True).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        if location is not None:
            self.send_header("Location", location)
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *args):  # silence per-request stderr noise
        pass

    def do_GET(self):  # noqa: N802 - http.server API
        path = self.path.rstrip("/")
        front = self.front
        if path == "/healthz":
            self._json(
                200,
                {
                    "ok": True,
                    "alive": len(front.alive),
                    "replicas": len(front.replicas),
                    "epoch": front.epoch,
                    "draining": front.draining,
                },
            )
        elif path == "/readyz":
            alive = len(front.alive)
            ready = alive >= 1 and not front.draining
            self._json(
                200 if ready else 503,
                {
                    "ready": ready,
                    # Degraded = serving with reduced capacity; the
                    # smoke drill asserts this flips on and back off
                    # across a replica kill.
                    "degraded": alive < len(front.replicas),
                    "alive": alive,
                    "replicas": len(front.replicas),
                    "epoch": front.epoch,
                    "draining": front.draining,
                },
            )
        elif path == "/metrics":
            if self.registry is None:
                self.send_error(404, "no metrics registry attached")
                return
            from gol_tpu.telemetry.metrics import CONTENT_TYPE

            body = self.registry.render().encode()
            self.send_response(200)
            self.send_header("Content-Type", CONTENT_TYPE)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        elif path == "/fleet/status":
            self._json(200, front.status())
        elif path.startswith("/result/"):
            status, payload = front.result(path[len("/result/"):])
            self._json(status, payload)
        else:
            self.send_error(
                404,
                "routes: /simulate /result/<id> /healthz /readyz "
                "/metrics /fleet/status",
            )

    def do_POST(self):  # noqa: N802 - http.server API
        path = self.path.rstrip("/")
        if path == "/simulate":
            try:
                length = int(self.headers.get("Content-Length", "0"))
                body = json.loads(self.rfile.read(length)) if length else {}
            except (ValueError, json.JSONDecodeError) as e:
                self._json(400, {"error": f"body is not valid JSON: {e}"})
                return
            status, payload = self.front.submit(body, direct=self.direct)
            if status == 307:
                self._json(
                    307, payload,
                    location=payload["replica"] + "/simulate",
                )
            else:
                self._json(status, payload)
        elif path == "/shutdown":
            self.stop_event.set()
            self._json(200, {"ok": True, "draining": True})
        else:
            self.send_error(404, "POST routes: /simulate /shutdown")


class FleetServer:
    """Threaded HTTP listener over one :class:`FleetFront`."""

    def __init__(
        self, front: FleetFront, port: int, registry=None,
        direct: bool = False,
    ) -> None:
        self.stop_event = threading.Event()
        handler = type(
            "BoundFleetHandler",
            (_FleetHandler,),
            {
                "front": front,
                "registry": registry,
                "stop_event": self.stop_event,
                "direct": direct,
            },
        )
        self._httpd = http.server.ThreadingHTTPServer(
            ("127.0.0.1", port), handler
        )
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name="gol-fleet-http",
            daemon=True,
        )
        self._thread.start()

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5.0)


# -- process management / CLI -------------------------------------------------


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def spawn_replicas(ns, state_dir: str) -> List[ReplicaHandle]:
    """Launch N supervised replicas (``supervise -- python -m
    gol_tpu.serve``), each with its own state dir and port.  The
    children must NOT inherit the fleet's fault plan (the fleet fires
    ``replica.*`` sites itself — an inherited plan would re-arm inside
    every replica) nor a stale restart counter."""
    env = {
        k: v
        for k, v in os.environ.items()
        if k not in ("GOL_FAULT_PLAN", "GOL_RESTART_ATTEMPT")
    }
    replicas = []
    for k in range(ns.replicas):
        name = f"r{k}"
        rdir = os.path.join(state_dir, name)
        os.makedirs(rdir, exist_ok=True)
        port = _free_port()
        manifest = os.path.join(rdir, "manifest.json")
        cmd = [
            sys.executable, "-m", "gol_tpu.resilience", "supervise",
            "--max-restarts", str(ns.max_restarts),
            "--backoff-base", "0.05",
            "--manifest", manifest,
            "--",
            sys.executable, "-m", "gol_tpu.serve",
            "--state-dir", rdir,
            "--port", str(port),
            "--slots", str(ns.slots),
            "--queue-depth", str(ns.queue_depth),
            "--chunk", str(ns.chunk),
            "--bucket-quantum", str(ns.bucket_quantum),
            "--engine", ns.engine,
        ]
        proc = subprocess.Popen(
            cmd, env=env,
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        )
        replicas.append(
            ReplicaHandle(
                name=name,
                base_url=f"http://127.0.0.1:{port}",
                state_dir=rdir,
                manifest=manifest,
                proc=proc,
            )
        )
    return replicas


def wait_replicas_healthy(
    replicas: List[ReplicaHandle], timeout_s: float = 60.0
) -> None:
    deadline = time.time() + timeout_s
    for r in replicas:
        client = SimClient(r.base_url, timeout=5.0)
        while True:
            try:
                client.healthz()
                break
            except Exception:
                if time.time() > deadline:
                    raise TimeoutError(
                        f"replica {r.name} not healthy after {timeout_s}s"
                    )
                time.sleep(0.1)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m gol_tpu.serve.fleet",
        description="replicated serving front tier "
        '(docs/SERVING.md, "The fleet")',
    )
    p.add_argument(
        "--state-dir", required=True,
        help="fleet root: the front tier's journal plus one replica "
        "state dir per replica (r0/, r1/, ...)",
    )
    p.add_argument("--port", type=int, default=0)
    p.add_argument(
        "--replicas", type=int, default=3,
        help="supervised replica processes to spawn (default 3)",
    )
    p.add_argument("--slots", type=int, default=4)
    p.add_argument("--queue-depth", type=int, default=8)
    p.add_argument("--chunk", type=int, default=4)
    p.add_argument("--bucket-quantum", type=int, default=64)
    p.add_argument(
        "--engine", default="auto",
        choices=["auto", "dense", "bitpack", "pallas_bitpack"],
    )
    p.add_argument(
        "--probe-interval", type=float, default=0.25,
        help="seconds between /healthz probe rounds (default 0.25)",
    )
    p.add_argument("--miss-threshold", type=int, default=3)
    p.add_argument("--restore-beats", type=int, default=2)
    p.add_argument("--max-restarts", type=int, default=3)
    p.add_argument(
        "--direct", action="store_true",
        help="307 direct-to-replica mode: answer routing hints instead "
        "of proxying request bodies (clients re-POST themselves)",
    )
    p.add_argument(
        "--telemetry", default=None,
        help="front-tier event stream dir (default: "
        "<state-dir>/telemetry; 'none' disables)",
    )
    p.add_argument("--run-id", default=None)
    p.add_argument(
        "--fault-plan", default=None,
        help="fault plan for the FLEET's own sites (replica.kill / "
        "replica.stall / fleet.partition); never inherited by replicas",
    )
    return p


def main(argv=None) -> int:
    ns = build_parser().parse_args(argv)

    try:
        if ns.fault_plan:
            faults_mod.install(faults_mod.FaultPlan.load(ns.fault_plan))
        else:
            faults_mod.install_from_env()
    except faults_mod.FaultPlanError as e:
        print(e)
        return 255

    from gol_tpu.telemetry.metrics import MetricsRegistry

    os.makedirs(ns.state_dir, exist_ok=True)
    telemetry_dir = ns.telemetry
    if telemetry_dir is None:
        telemetry_dir = os.path.join(ns.state_dir, "telemetry")
    elif telemetry_dir == "none":
        telemetry_dir = None

    registry = MetricsRegistry()
    events = None
    if telemetry_dir:
        from gol_tpu import telemetry as telemetry_mod

        events = telemetry_mod.EventLog(
            telemetry_dir, run_id=ns.run_id, process_index=0
        )
        events.observer = registry.observe
        events.on_shed = registry.count_shed
        events.run_header(
            {
                "driver": "fleet",
                "replicas": ns.replicas,
                "engine": ns.engine,
                "bucket_quantum": ns.bucket_quantum,
                "probe_interval_s": ns.probe_interval,
            }
        )

    replicas = spawn_replicas(ns, ns.state_dir)
    try:
        wait_replicas_healthy(replicas)
        monitor = HostMonitor(
            [r.name for r in replicas],
            miss_threshold=ns.miss_threshold,
            restore_beats=ns.restore_beats,
            events=events,
            registry=registry,
        )
        front = FleetFront(
            replicas,
            ns.state_dir,
            quantum=ns.bucket_quantum,
            default_engine=ns.engine,
            events=events,
            registry=registry,
            monitor=monitor,
        )
        server = FleetServer(
            front, ns.port, registry=registry, direct=ns.direct
        )
        stop = server.stop_event

        def _graceful(signum, frame):
            stop.set()

        signal.signal(signal.SIGTERM, _graceful)
        signal.signal(signal.SIGINT, _graceful)

        print(
            f"fleet: listening on http://127.0.0.1:{server.port} "
            f"({ns.replicas} replicas, state {ns.state_dir})",
            flush=True,
        )
        try:
            while not stop.is_set():
                front.poll()
                time.sleep(ns.probe_interval)
        finally:
            front.drain()
            server.close()
            front.close()
            if events is not None:
                events.close()
        print("fleet: drained; exiting", flush=True)
        return 0
    except BaseException:
        for r in replicas:
            if r.proc is not None and r.proc.poll() is None:
                r.proc.kill()
        raise


if __name__ == "__main__":
    sys.exit(main())
