"""The continuous-batching scheduler (docs/SERVING.md).

One scheduler owns every in-flight simulation request of a serving
process.  Requests land in *bucket groups* — the PR 5 size buckets
(:func:`gol_tpu.batch.runtime.bucket_shape`) crossed with the resolved
engine — and each group holds a fixed number of batch *slots*: one
compiled masked program per (bucket, chunk size) steps all S slots
together, empty slots carrying dead zero boards (B3/S23 keeps dead
worlds dead, so padding slots is exact, not approximate).  When a
world's generations run out, its slot is freed and **refilled from the
bucket's queue at the same chunk boundary** — continuous batching, not
drain-and-refill: a long request never holds the batch hostage for a
short one.

The robustness plane (the reason this tier exists):

- **Admission control** — a bounded queue per bucket.  A full queue is
  an explicit :class:`Rejected` (HTTP 429 + ``retry_after``), and the
  shed order is the PR 10 fixed order: stats streaming is sacrificed at
  the first backpressure signal, admissions are shed when the journal's
  disk fills (persistent ENOSPC through
  :func:`gol_tpu.resilience.degrade.write_with_retry`), and committed
  in-flight work is **never** shed.
- **Deadlines** — ``deadline_s`` is checked at chunk boundaries (queued
  and running); an expired request is cancelled, journaled, and stamped
  as a v10 ``deadline`` event.  Transient journal/result IO failures
  retry under the same bounded ``write_with_retry`` budget as
  checkpoint writes.
- **Crash safety** — every transition rides the fsync'd journal
  (:mod:`gol_tpu.serve.journal`); construction replays it and re-admits
  every admitted-but-unfinished request (v10 ``requeue`` events), so a
  supervised restart completes every accepted request exactly once.
- **Guard isolation** — with ``guard=True`` every chunk of every group
  is audited (:func:`gol_tpu.utils.guard.audit_worlds`); a failing
  world rolls back and replays **only its own bucket group** from the
  fingerprint-verified last-good stack (per-group ``replays`` counters
  pin the isolation in tests).  ``board.bitflip`` specs target requests
  by admission ordinal (``world`` = the Nth admitted request).

Threading: one lock serializes :meth:`submit`/:meth:`get_result` (HTTP
handler threads) against :meth:`run_once` (the drive loop).  The
scheduler itself is synchronous — chaos cells and tests drive
:meth:`run_until_drained` deterministically in-process; the HTTP server
runs the same loop on its main thread (:mod:`gol_tpu.serve.server`).

**Live elasticity** (``mesh_devices > 0``, docs/RESILIENCE.md "Live
elasticity"): bucket groups run sharded over a ``worlds`` mesh, and a
:class:`gol_tpu.resilience.health.HealthMonitor` samples the fault
plane at every chunk boundary.  A ``device_loss`` verdict shrinks the
mesh to the largest slot-divisible survivor set at the **next** chunk
boundary — every live group stack (and its guard last-good copy) moves
through :func:`gol_tpu.parallel.redistribute.device_reshard_worlds`
without leaving device memory, the journal is untouched (committed
requests keep their exactly-once guarantee), and admissions are
throttled proportional to the lost capacity.  ``device_restore`` grows
the mesh back the same way.  A ``straggler`` verdict triggers a hedged
replay of that bucket's chunk from the fingerprint-verified last-good
stack, with the guard's fingerprint picking the winner.  With
``mesh_devices=0`` (the default) groups run unsharded and none of this
machinery exists — the compiled chunk programs are byte-identical.

**Request tracing** (schema v12, :mod:`gol_tpu.telemetry.trace`,
docs/OBSERVABILITY.md "Request tracing & SLOs"): every admitted request
gets a ``trace_id`` stamped on the journal's admit/complete records (so
a crash-replayed request keeps its identity and the reader stitches its
pre-crash spans back on), and when telemetry is attached the scheduler
emits one span per lifecycle phase — queue wait, every masked chunk the
request rode (with device wall, co-resident count, and roofline
utilization), hedge replays, live reshards, and the terminal root span
carrying the queue/compute/interference/hedge/stall decomposition that
also rides the result payload.  All of it is host-side bookkeeping
after the ``force_ready`` fences: tracing on/off never changes the
compiled chunk programs (the trace-identity pin in tests/test_trace.py)
and the phase accumulators run unconditionally, so result payloads have
one shape regardless of whether a stream is attached.
"""

from __future__ import annotations

import collections
import dataclasses
import json
import os
import re
import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from gol_tpu.serve import journal as journal_mod
from gol_tpu.telemetry import blackbox
from gol_tpu.telemetry import trace as trace_mod

_ID_RE = re.compile(r"^[A-Za-z0-9][A-Za-z0-9._-]{0,63}$")
_ENGINES = ("auto", "dense", "bitpack", "pallas_bitpack")
_RULE = "B3/S23"

#: The 429 ``retry_after`` hint is queue-position / observed drain rate,
#: clamped to [_RETRY_AFTER_MIN, _RETRY_AFTER_MAX].  During the
#: zero-completions startup window no drain rate exists yet — the hint
#: falls back to _RETRY_AFTER_DEFAULT seconds per request ahead (the
#: documented default a well-behaved client sleeps on), never a
#: divide-by-zero guess (docs/SERVING.md "Backpressure").
_RETRY_AFTER_DEFAULT = 0.5
_RETRY_AFTER_MIN = 0.1
_RETRY_AFTER_MAX = 30.0


class ValidationError(ValueError):
    """A request body is malformed (HTTP 400)."""


class Rejected(RuntimeError):
    """A valid request was not admitted (HTTP 429/503).

    ``retry_after`` (seconds) is the backpressure hint the server
    surfaces as the ``Retry-After`` header.
    """

    def __init__(
        self, status: int, message: str,
        retry_after: Optional[float] = None,
    ) -> None:
        super().__init__(message)
        self.status = status
        self.retry_after = retry_after


@dataclasses.dataclass(frozen=True)
class Request:
    """One validated simulation request."""

    id: str
    pattern: int
    size: int
    generations: int
    engine: str = "auto"
    deadline_s: Optional[float] = None
    stream_stats: bool = False

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


class RequestState:
    """Mutable lifecycle of one admitted request."""

    def __init__(
        self, request: Request, ordinal: int, board: np.ndarray
    ) -> None:
        self.request = request
        self.ordinal = ordinal  # admission sequence — fault specs'
        # ``world`` field targets this, stable across restarts (it rides
        # the journal's admit record).
        self.board = board  # current host board (initial pattern, then
        # refreshed at membership changes / completion)
        self.status = "queued"  # queued | running | done | expired
        self.generation = 0
        self.remaining = request.generations
        self.submitted_t = time.time()
        # Tracing (schema v12): ``trace_id`` is minted at admission and
        # journal-restored on crash replay; ``queued_t`` opens the
        # CURRENT wait epoch (reset by construction at requeue, so a
        # replayed request's pre-crash time reads as stall, not queue
        # wait); ``phase_s`` accrues the latency decomposition the
        # result payload and the root span both report.  All of it is
        # maintained whether or not telemetry is attached — one payload
        # shape, one code path.
        self.trace_id = ""
        # Fleet mode (docs/SERVING.md "The fleet"): the routing epoch
        # this request was admitted under.  ``None`` outside a fleet —
        # single-server journals then carry no epoch at all, keeping
        # their bytes identical to pre-fleet behavior.
        self.owner_epoch: Optional[int] = None
        self.queued_t = self.submitted_t
        self.chunk_span_id: Optional[str] = None
        self.phase_s = {
            "queue": 0.0, "compute": 0.0, "interference": 0.0,
            "hedge": 0.0,
        }
        self.started_t: Optional[float] = None
        self.result: Optional[dict] = None
        self.stats: List[dict] = []
        self.done = threading.Event()


class _BucketGroup:
    """One (padded shape × engine) compilation unit with S batch slots."""

    def __init__(self, shape: Tuple[int, int], engine: str, slots: int):
        self.shape = shape
        self.engine = engine
        self.label = f"{shape[0]}x{shape[1]}/{engine}"
        self.slots: List[Optional[RequestState]] = [None] * slots
        self.queue: collections.deque = collections.deque()
        self.stack = None  # device [S, H, W] (None = rebuild from boards)
        self.hs = None
        self.ws = None
        self.gens = 0  # cumulative generations this group stepped —
        # the generation axis board.bitflip specs match against
        self.last_good = None  # (device stack copy, [fingerprints])
        self.replays = 0  # rollback-replays — the isolation counter


class ServeScheduler:
    """See module docstring.  ``state_dir`` holds journal + results."""

    def __init__(
        self,
        state_dir: str,
        quantum: int = 64,
        slots: int = 4,
        queue_depth: int = 8,
        chunk: int = 4,
        tile_hint: int = 512,
        guard: bool = True,
        guard_max_restores: int = 3,
        default_engine: str = "auto",
        telemetry_dir: Optional[str] = None,
        run_id: Optional[str] = None,
        registry=None,
        keep_journal_segments: int = 2,
        compact_every: int = 16,
        mesh_devices: int = 0,
        health=None,
        storm_window_s: float = 10.0,
        storm_threshold: int = 4,
    ) -> None:
        from gol_tpu.resilience import faults as faults_mod

        if slots < 1 or queue_depth < 1 or chunk < 1 or quantum < 1:
            raise ValueError(
                "slots, queue_depth, chunk, and quantum must all be >= 1"
            )
        if mesh_devices < 0:
            raise ValueError(f"mesh_devices must be >= 0, got {mesh_devices}")
        if mesh_devices and slots % mesh_devices:
            raise ValueError(
                f"slots ({slots}) must be divisible by mesh_devices "
                f"({mesh_devices}) — the worlds axis shards evenly"
            )
        self.state_dir = state_dir
        self.results_dir = os.path.join(state_dir, "results")
        os.makedirs(self.results_dir, exist_ok=True)
        self.quantum = quantum
        self.slots = slots
        self.queue_depth = queue_depth
        self.chunk = chunk
        self.tile_hint = tile_hint
        self.guard = guard
        self.guard_max_restores = guard_max_restores
        self.default_engine = default_engine
        self.keep_journal_segments = keep_journal_segments
        self.compact_every = compact_every

        from gol_tpu.analysis import lockwatch

        self._lock = lockwatch.maybe_wrap(
            "ServeScheduler._lock", threading.RLock()
        )
        self._groups: Dict[tuple, _BucketGroup] = {}
        self._requests: Dict[str, RequestState] = {}
        self._next_ordinal = 0
        self._seq = 0
        self._chunk_index = 0
        self._total_gens = 0
        self._plan_on = faults_mod.active() is not None
        self._draining = False
        self._admissions_shed = False
        self._journal_shed = False
        self._stats_shed = False
        self._completions_since_compact = 0
        self.guard_failures = 0
        self.admitted_total = 0
        self.rejected_total = 0
        self.completed_total = 0
        self.cancelled_total = 0
        self.mesh_devices = mesh_devices
        self.live_reshards = 0
        self.hedges = 0
        self._cur_mesh = None  # active worlds mesh (None = unsharded)
        self._cur_n = 0
        self._devices: list = []  # the full pool, index = monitor device id
        self._resharding = False  # readiness drops from verdict → reshard
        self._pending_resize = False
        self._health = health
        self._complete_times: collections.deque = collections.deque(maxlen=32)

        # Compile observability (docs/SERVING.md, "Compile storms"):
        # the scheduler AOT-compiles one executable per (bucket shape,
        # engine, take, mesh width) and caches it here — a cold entry
        # stamps a v13 ``compile`` event with the persistent-cache
        # verdict and feeds the storm detector: K cold compiles inside
        # one ``storm_window_s`` admission window emit a ``storm``
        # event and halve the admission queue depth until the window
        # drains (bucketed serving's classic cold-start failure mode).
        self.storm_window_s = storm_window_s
        self.storm_threshold = storm_threshold
        self._programs: Dict[tuple, object] = {}
        self._cold_compiles: collections.deque = collections.deque()
        self._storm_until = 0.0
        self.storms_total = 0

        self._registry = registry
        self._events = None
        if telemetry_dir:
            from gol_tpu import telemetry as telemetry_mod

            self._events = telemetry_mod.EventLog(
                telemetry_dir, run_id=run_id, process_index=0
            )
            if registry is not None:
                self._events.observer = registry.observe
                self._events.on_shed = registry.count_shed
            header = {
                "driver": "serve",
                "engine": default_engine,
                "bucket_quantum": quantum,
                "slots": slots,
                "queue_depth": queue_depth,
                "chunk": chunk,
                "guard": guard,
            }
            if mesh_devices > 0:
                header["mesh_devices"] = mesh_devices
            self._events.run_header(header)
            attempt = _restart_attempt()
            if attempt > 0:
                self._events.restart_event(attempt)
        # Arm the black box (docs/OBSERVABILITY.md): dumps land next to
        # the stream when telemetry is on, next to the journal when it
        # is off — the recorder itself rings either way.  Signal
        # triggers belong to the entry point (serve.__main__), which
        # owns its handlers.
        blackbox.install(
            telemetry_dir or state_dir,
            run_id=(
                self._events.run_id if self._events is not None else run_id
            ),
            process_index=0,
        )

        # Span ids are epoch-prefixed by run id so a crash-replayed
        # request's pre- and post-crash spans (same trace_id, different
        # process) can never collide.  With no telemetry attached the
        # recorder is disabled and every span call is a no-op.
        self._tracer = trace_mod.SpanRecorder(
            events=self._events,
            registry=registry,
            epoch=self._events.run_id if self._events is not None else "",
        )

        if mesh_devices > 0:
            from gol_tpu.batch import engines as batch_engines

            self._cur_mesh = batch_engines.make_batch_mesh(mesh_devices)
            self._cur_n = mesh_devices
            self._devices = list(self._cur_mesh.devices.flat)
            if self._health is None:
                from gol_tpu.resilience.health import HealthMonitor

                self._health = HealthMonitor(
                    mesh_devices, events=self._events, registry=registry
                )

        self._journal = journal_mod.Journal(
            os.path.join(state_dir, "journal.jsonl")
        )
        # Under the lock: replay mutates _requests/_groups, and a
        # supervisor may point the HTTP listener at the scheduler
        # before replay finishes (lockcheck: guarded-fields).
        with self._lock:
            self._replay_journal()

    # -- admission -----------------------------------------------------------
    def submit(self, obj: dict) -> RequestState:
        """Validate + admit one request dict; raises
        :class:`ValidationError` (400) / :class:`Rejected` (429/503).
        Re-submitting a known id is idempotent (the existing state is
        returned — exactly-once rides the request id).  A request
        WITHOUT an id gets a fresh server-minted one per call, so only
        caller-supplied ids make resubmission idempotent — the server's
        202 ticket and :meth:`SimClient.submit` both enforce/flag this."""
        req = self._validate(obj)
        with self._lock:
            existing = self._requests.get(req.id)
            if existing is not None:
                return existing
            if self._draining:
                raise Rejected(503, "server is draining; not admitting")
            if self._admissions_shed:
                raise Rejected(
                    503,
                    "admissions shed: journal storage full "
                    "(committed work still completes)",
                    retry_after=30.0,
                )
            grp = self._group_for(req)
            depth = self._effective_queue_depth()
            if len(grp.queue) >= depth:
                # PR 10 shed order: the first backpressure signal sheds
                # stats streaming before anything else.
                self._shed_stats(f"bucket {grp.label} queue full")
                self.rejected_total += 1
                self._emit(
                    "reject", req.id, bucket=grp.label,
                    reason="queue_full", **self._depths(),
                )
                raise Rejected(
                    429,
                    f"bucket {grp.label} queue full ({depth} waiting)",
                    retry_after=self._retry_after(grp),
                )
            ordinal = self._next_ordinal
            # The trace id rides the durable admit record: compaction
            # preserves admits verbatim and replay restores the id, so
            # a crash-replayed request reconstructs its pre-crash spans.
            trace_id = trace_mod.new_trace_id(req.id)
            # Fleet mode stamps the routing epoch the front tier
            # proxied this request under; the fold arbitrates
            # multi-writer journals by it.  Absent outside a fleet so
            # single-server journal bytes stay identical.
            owner_epoch = obj.get("owner_epoch")
            epoch_fields = (
                {} if owner_epoch is None
                else {"owner_epoch": owner_epoch}
            )
            rec = journal_mod.record(
                "admit", req.id, request=req.to_dict(), ordinal=ordinal,
                trace_id=trace_id, **epoch_fields,
            )
            if not self._journal_write(rec):
                # The admit could not be made durable: this request was
                # never committed, and no future one can be — shed
                # admissions (in-flight committed work is untouched).
                self._admissions_shed = True
                self.rejected_total += 1
                self._emit(
                    "reject", req.id, reason="admissions_shed",
                    **self._depths(),
                )
                raise Rejected(
                    503,
                    "admissions shed: journal storage full",
                    retry_after=30.0,
                )
            self._next_ordinal = ordinal + 1
            state = RequestState(req, ordinal, self._initial_board(req))
            state.trace_id = trace_id
            state.owner_epoch = owner_epoch
            self._requests[req.id] = state
            grp.queue.append(state)
            self.admitted_total += 1
            self._emit(
                "admit", req.id, bucket=grp.label, trace_id=trace_id,
                **self._depths(),
            )
            return state

    def get_result(self, request_id: str) -> Optional[RequestState]:
        with self._lock:
            return self._requests.get(request_id)

    def result_board(self, request_id: str) -> np.ndarray:
        """Decode a completed request's board (tests/chaos cells)."""
        state = self.get_result(request_id)
        if state is None or state.result is None:
            raise KeyError(f"no result for request {request_id!r}")
        return decode_board(state.result["board"])

    def drain(self) -> None:
        """Stop admitting; the loop finishes everything committed."""
        with self._lock:
            self._draining = True

    @staticmethod
    def _epoch_fields(state: RequestState) -> dict:
        """Journal fields for fleet ownership fencing — empty outside a
        fleet, so single-server journal bytes never change."""
        if state.owner_epoch is None:
            return {}
        return {"owner_epoch": state.owner_epoch}

    def fence(self, request_ids, epoch: int) -> int:
        """Drop ownership of open requests migrated away at ``epoch``
        (docs/SERVING.md "The fleet").

        The front tier calls this (``POST /fence``) after handing a
        stalled-but-alive replica's intents to a new owner: the request
        leaves the queue/slots WITHOUT a ``complete``/``cancel`` — a
        ``handoff`` record lands in our journal instead, so a restart's
        fold agrees with the live state.  Terminal requests are left
        alone (their completion won the race; the fold arbitrates).
        Returns how many requests were actually fenced.
        """
        fenced = 0
        with self._lock:
            for rid in request_ids:
                state = self._requests.get(rid)
                if state is None or state.status in ("done", "expired"):
                    continue
                grp = self._group_for(state.request)
                try:
                    grp.queue.remove(state)
                except ValueError:
                    pass
                occupied = [
                    k for k, s in enumerate(grp.slots) if s is state
                ]
                if occupied:
                    # Evicting a RUNNING slot drops the device stack;
                    # host-sync the co-residents first so the rebuild
                    # does not rewind them (same move as deadline
                    # cancellation).
                    if grp.stack is not None:
                        host = np.asarray(grp.stack)
                        for k, s in enumerate(grp.slots):
                            if s is not None:
                                n = s.request.size
                                s.board = host[k, :n, :n].copy()
                    for k in occupied:
                        grp.slots[k] = None
                    grp.stack = None
                    grp.last_good = None
                self._journal_write(
                    journal_mod.record(
                        "handoff", rid, epoch=epoch, by="fence",
                    )
                )
                del self._requests[rid]
                fenced += 1
                self._emit(
                    "fenced", rid, owner_epoch=state.owner_epoch,
                    fence_epoch=epoch, trace_id=state.trace_id,
                )
                state.status = "fenced"
                state.done.set()
        return fenced

    @property
    def draining(self) -> bool:
        with self._lock:
            return self._draining

    def peek(self, request_id: str) -> Optional[dict]:
        """Locked point-in-time snapshot of one request's lifecycle.

        The HTTP handlers read through this, never the live
        :class:`RequestState`: field-at-a-time reads racing the drive
        loop could observe a terminal status before its result payload
        lands (lockcheck: guarded-fields, docs/ANALYSIS.md), answering
        202 for a request that is already finished.
        """
        with self._lock:
            state = self._requests.get(request_id)
            if state is None:
                return None
            return {
                "id": state.request.id,
                "status": state.status,
                "generation": state.generation,
                "trace_id": state.trace_id,
                "result": state.result,
            }

    @property
    def ready(self) -> bool:
        """Readiness (the /readyz contract): liveness is the process
        being up; readiness additionally means the scheduler is
        admitting and not mid-transition — false while draining, while
        admissions are shed, and through a live-reshard window (from
        the health verdict until the mesh transition lands)."""
        with self._lock:
            return not (
                self._draining or self._admissions_shed or self._resharding
            )

    def outstanding(self) -> int:
        """Committed requests not yet in a terminal state."""
        with self._lock:
            return sum(
                1
                for s in self._requests.values()
                if s.status in ("queued", "running")
            )

    # -- the drive loop ------------------------------------------------------
    def run_once(self) -> bool:
        """One scheduling round: expire deadlines, refill slots, step
        every occupied group one chunk.  Returns whether device work ran
        (False = idle; callers sleep)."""
        with self._lock:
            if self._health is not None:
                if self._pending_resize:
                    # The verdict landed at the PREVIOUS boundary; this
                    # is "the next chunk boundary" the contract promises.
                    self._pending_resize = False
                    self._resize_mesh()
                    self._resharding = False
                self._poll_health()
            self._expire_deadlines()
            self._refill()
            did = False
            for grp in list(self._groups.values()):
                if any(s is not None for s in grp.slots):
                    self._step_group(grp)
                    did = True
            self._drain_plane()
            return did

    def run_until_drained(self) -> None:
        """Drive synchronously until nothing is queued or running."""
        while self.outstanding():
            if not self.run_once():
                time.sleep(0.001)

    def close(self) -> None:
        with self._lock:
            self._drain_plane()
            self._journal.close()
            if self._events is not None:
                self._events.close()
                self._events = None

    # -- internals: admission ------------------------------------------------
    def _validate(self, obj) -> Request:
        from gol_tpu.models import patterns

        if not isinstance(obj, dict):
            raise ValidationError("request body must be a JSON object")
        known = {
            "id", "pattern", "size", "generations", "engine", "rule",
            "deadline_s", "stream_stats", "wait", "owner_epoch",
        }
        unknown = set(obj) - known
        if unknown:
            raise ValidationError(
                f"unknown request fields {sorted(unknown)} "
                f"(known: {sorted(known)})"
            )

        def _int(name, minimum):
            v = obj.get(name)
            if not isinstance(v, int) or isinstance(v, bool) or v < minimum:
                raise ValidationError(
                    f"{name!r} must be an integer >= {minimum}, got {v!r}"
                )
            return v

        pattern = _int("pattern", 0)
        size = _int("size", 1)
        generations = _int("generations", 1)
        try:
            patterns.validate_pattern_size(pattern, size)
        except ValueError as e:
            raise ValidationError(str(e))
        rule = obj.get("rule", _RULE)
        if rule not in (None, _RULE):
            raise ValidationError(
                f"rule {rule!r} is not served; every engine implements "
                f"{_RULE} (Conway) only"
            )
        engine = obj.get("engine", self.default_engine)
        if engine == "ooc":
            raise ValidationError(
                "engine 'ooc' streams one bigger-than-device board and "
                "is not served (a serving tier batches many small "
                f"in-core worlds); supported engines: {_ENGINES}"
            )
        if engine not in _ENGINES:
            raise ValidationError(
                f"unknown engine {engine!r}; expected one of {_ENGINES}"
            )
        owner_epoch = obj.get("owner_epoch")
        if owner_epoch is not None and (
            not isinstance(owner_epoch, int)
            or isinstance(owner_epoch, bool)
            or owner_epoch < 0
        ):
            raise ValidationError(
                f"owner_epoch must be an integer >= 0 (the fleet "
                f"routing epoch), got {owner_epoch!r}"
            )
        deadline_s = obj.get("deadline_s")
        if deadline_s is not None and (
            not isinstance(deadline_s, (int, float))
            or isinstance(deadline_s, bool)
            or deadline_s < 0
        ):
            raise ValidationError(
                f"deadline_s must be a number >= 0, got {deadline_s!r}"
            )
        rid = obj.get("id")
        if rid is None:
            with self._lock:
                self._seq += 1
                rid = f"req-{os.getpid()}-{self._seq:06d}"
        elif not isinstance(rid, str) or not _ID_RE.match(rid):
            raise ValidationError(
                f"id {rid!r} must match {_ID_RE.pattern} (it names the "
                "journal/result entries)"
            )
        return Request(
            id=rid, pattern=pattern, size=size, generations=generations,
            engine=engine,
            deadline_s=float(deadline_s) if deadline_s is not None else None,
            stream_stats=bool(obj.get("stream_stats", False)),
        )

    def _initial_board(self, req: Request) -> np.ndarray:
        from gol_tpu.models import patterns

        return patterns.init_global(req.pattern, req.size, 1)

    def _group_for(self, req: Request) -> _BucketGroup:
        from gol_tpu.batch.runtime import (
            Bucket, bucket_shape, resolve_bucket_engine,
        )

        shape = bucket_shape(req.size, req.size, self.quantum)
        synthetic = Bucket(
            shape=shape, indices=(0,),
            masked=(req.size, req.size) != shape,
        )
        try:
            name = resolve_bucket_engine(
                req.engine, synthetic, [(req.size, req.size)]
            )
        except ValueError as e:
            raise ValidationError(str(e))
        if name == "pallas_bitpack":
            # Serve groups always run the masked programs (slots hold
            # mixed sizes and dead padding); the fused kernel has no
            # masked form — same documented fallback as batch buckets.
            name = "bitpack"
        key = (shape[0], shape[1], name)
        grp = self._groups.get(key)
        if grp is None:
            grp = _BucketGroup(shape, name, self.slots)
            self._groups[key] = grp
        return grp

    def _drain_rate(self) -> float:
        """Completions/second over the recent completion window.  0.0
        while fewer than two completions have landed — the startup
        window in which no rate can be estimated."""
        ts = self._complete_times
        if len(ts) >= 2 and ts[-1] > ts[0]:
            return (len(ts) - 1) / (ts[-1] - ts[0])
        return 0.0

    def _retry_after(self, grp: _BucketGroup) -> float:
        inflight = sum(1 for s in grp.slots if s is not None)
        ahead = len(grp.queue) + inflight
        rate = self._drain_rate()
        if rate <= 0.0:
            # Zero-completions startup window: clamp to the documented
            # per-request default rather than guessing from a rate that
            # does not exist yet.
            hint = _RETRY_AFTER_DEFAULT * max(ahead, 1)
        else:
            hint = ahead / rate
        return round(min(max(hint, _RETRY_AFTER_MIN), _RETRY_AFTER_MAX), 3)

    def _effective_queue_depth(self) -> int:
        """Admission depth, throttled proportional to lost capacity:
        with half the devices dead, each bucket accepts half its queue
        (never below one slot — the tier keeps serving).  A compile
        storm (docs/SERVING.md, "Compile storms") additionally halves
        the depth until its window drains: new bucket shapes are what
        drive cold compiles, so slowing admissions is what lets the
        warmed programs catch up."""
        depth = self.queue_depth
        if self._health is not None and self.mesh_devices > 0:
            frac = len(self._health.alive) / float(self.mesh_devices)
            depth = max(1, int(depth * frac))
        if self.storm_active():
            depth = max(1, depth // 2)
        return depth

    def _depths(self) -> dict:
        return {
            "queue_depth": sum(
                len(g.queue) for g in self._groups.values()
            ),
            "inflight": sum(
                1
                for g in self._groups.values()
                for s in g.slots
                if s is not None
            ),
        }

    # -- internals: durability ----------------------------------------------
    def _journal_write(self, rec: dict) -> bool:
        from gol_tpu.resilience import degrade as degrade_mod

        if self._journal_shed:
            return False
        ok = degrade_mod.write_with_retry(
            lambda: self._journal.append(rec),
            what="journal",
            shed_telemetry=self._shed_telemetry,
        )
        if not ok:
            # Persistent ENOSPC: the journal goes read-only.  Committed
            # requests keep running to completion (their results are
            # still written best-effort) — the shed order never touches
            # committed work.
            self._journal_shed = True
            self._admissions_shed = True
        return ok

    def _shed_telemetry(self, reason: str) -> None:
        if self._events is not None:
            self._events.request_shed("telemetry", reason)

    def _shed_stats(self, reason: str) -> None:
        if not self._stats_shed:
            self._stats_shed = True
            if self._events is not None:
                self._events.degraded_event(
                    "stats", "shed", detail=reason
                )

    def _write_result(self, payload: dict) -> None:
        from gol_tpu.resilience import degrade as degrade_mod

        path = os.path.join(
            self.results_dir, f"{payload['id']}.json"
        )

        def _write():
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                f.write(json.dumps(payload, sort_keys=True) + "\n")
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)

        # Same atomic-rename + bounded-retry discipline as snapshots; a
        # shed (full disk) keeps the result in memory — it is still
        # served, just not durable.
        degrade_mod.write_with_retry(
            _write, what="result", shed_telemetry=self._shed_telemetry
        )

    def _replay_journal(self) -> None:
        """Re-admit every admitted-but-unfinished journal entry, load
        completed results back, and never re-run a completed id.  A
        ``handed_off`` entry — the front tier migrated it to another
        replica while this process was dead — is DROPPED, not re-run:
        ownership fencing by epoch (docs/SERVING.md "The fleet")."""
        entries, torn = journal_mod.replay(self._journal.path)
        for rid, entry in entries.items():
            admit = entry["admit"]
            try:
                req = Request(**admit["request"])
            except TypeError:
                continue  # a foreign/unreadable admit record
            ordinal = int(admit.get("ordinal", self._next_ordinal))
            self._next_ordinal = max(self._next_ordinal, ordinal + 1)
            # The original trace id (if the journal predates v12, mint a
            # fresh one): pre-crash spans in the dead run's rank file
            # join the spans this process emits under one trace.
            trace_id = admit.get("trace_id") or trace_mod.new_trace_id(rid)
            if entry["status"] == "handed_off":
                self._emit(
                    "fenced", rid, trace_id=trace_id,
                    owner_epoch=admit.get("owner_epoch"),
                    fence_epoch=entry.get("fence_epoch"),
                )
                continue
            if entry["status"] in ("completed", "cancelled"):
                state = RequestState(req, ordinal, np.zeros((1, 1), np.uint8))
                state.trace_id = trace_id
                state.status = (
                    "done" if entry["status"] == "completed" else "expired"
                )
                state.result = self._load_result(rid)
                state.done.set()
                self._requests[rid] = state
                continue
            state = RequestState(req, ordinal, self._initial_board(req))
            state.trace_id = trace_id
            oe = admit.get("owner_epoch")
            state.owner_epoch = oe if isinstance(oe, int) else None
            t = admit.get("t")
            if isinstance(t, (int, float)) and not isinstance(t, bool):
                # Deadlines and latency are measured from the ORIGINAL
                # admission, not from this restart — a deadlined request
                # must not get a fresh budget every supervised restart.
                # ``queued_t`` deliberately stays at construction time:
                # the wait epoch restarts now, so the crash gap reads as
                # stall in the decomposition, never as queue wait.
                state.submitted_t = float(t)
            self._requests[rid] = state
            grp = self._group_for(req)
            grp.queue.append(state)
            self._emit(
                "requeue", rid, bucket=grp.label, trace_id=trace_id,
                **self._depths(),
            )

    def _load_result(self, rid: str) -> Optional[dict]:
        path = os.path.join(self.results_dir, f"{rid}.json")
        try:
            with open(path) as f:
                return json.load(f)
        except (OSError, json.JSONDecodeError):
            return None

    # -- internals: the chunk loop -------------------------------------------
    def _expire_deadlines(self) -> None:
        now = time.time()
        for grp in self._groups.values():
            kept = collections.deque()
            while grp.queue:
                state = grp.queue.popleft()
                if self._expired(state, now):
                    self._cancel(state, grp)
                else:
                    kept.append(state)
            grp.queue = kept
            expired = [
                k for k, s in enumerate(grp.slots)
                if s is not None and self._expired(s, now)
            ]
            if not expired:
                continue
            # Cancelling a RUNNING slot drops the device stack, and the
            # survivors' next stack is rebuilt from their host boards —
            # which are only refreshed on completion.  Host-sync every
            # occupied slot first so co-resident requests keep the
            # generations they actually ran (and the cancelled request
            # reports the board/generation it really reached).
            if grp.stack is not None:
                host = np.asarray(grp.stack)
                for k, s in enumerate(grp.slots):
                    if s is not None:
                        n = s.request.size
                        s.board = host[k, :n, :n].copy()
            for k in expired:
                state = grp.slots[k]
                grp.slots[k] = None
                self._cancel(state, grp)
            grp.stack = None
            grp.last_good = None

    @staticmethod
    def _expired(state: RequestState, now: float) -> bool:
        d = state.request.deadline_s
        return d is not None and (now - state.submitted_t) > d

    def _cancel(self, state: RequestState, grp: _BucketGroup) -> None:
        end_t = time.time()
        if state.status == "queued":
            # A never-started request spent its whole life waiting: the
            # queue span closes at cancellation, not slot assignment.
            state.phase_s["queue"] += max(end_t - state.queued_t, 0.0)
            self._tracer.span(
                state.trace_id, state.request.id, "queue",
                state.queued_t, end_t, bucket=grp.label,
            )
        decomp = self._decomposition(state, end_t)
        payload = {
            "id": state.request.id,
            "status": "expired",
            "reason": "deadline",
            "deadline_s": state.request.deadline_s,
            "generation": state.generation,
            "generations": state.request.generations,
            "trace_id": state.trace_id,
            "decomposition": decomp,
        }
        # result before status: a terminal status must never be
        # observable without its payload (same ordering as _finish).
        state.result = payload
        state.status = "expired"
        self._write_result(payload)
        self._journal_write(
            journal_mod.record(
                "cancel", state.request.id, reason="deadline",
                generation=state.generation, trace_id=state.trace_id,
                **self._epoch_fields(state),
            )
        )
        self._tracer.span(
            state.trace_id, state.request.id, "cancel", end_t,
            time.time(), bucket=grp.label, generation=state.generation,
        )
        self._tracer.span(
            state.trace_id, state.request.id, "request",
            state.submitted_t, end_t, parent_id=None,
            span_id=trace_mod.ROOT_SPAN_ID, status="expired", **decomp,
        )
        self.cancelled_total += 1
        self._emit(
            "deadline", state.request.id, bucket=grp.label,
            generation=state.generation, trace_id=state.trace_id,
            **self._depths(),
        )
        state.done.set()

    def _refill(self) -> None:
        for grp in self._groups.values():
            if not grp.queue or all(s is not None for s in grp.slots):
                continue
            # A join drops the device stack (membership changed), and
            # the next stack is rebuilt from host boards — which are
            # only refreshed on completion.  Host-sync the residents
            # first or a mid-flight join silently rewinds them to their
            # last synced board (generations run since are lost, while
            # the generation counter keeps counting).
            if grp.stack is not None:
                host = np.asarray(grp.stack)
                for k, s in enumerate(grp.slots):
                    if s is not None:
                        n = s.request.size
                        s.board = host[k, :n, :n].copy()
            for k, slot in enumerate(grp.slots):
                if slot is not None or not grp.queue:
                    continue
                state = grp.queue.popleft()
                now = time.time()
                # The queue span closes here: waiting ends at slot
                # assignment (bucket-group join), whatever happens next.
                state.phase_s["queue"] += max(now - state.queued_t, 0.0)
                self._tracer.span(
                    state.trace_id, state.request.id, "queue",
                    state.queued_t, now, bucket=grp.label,
                )
                state.status = "running"
                state.started_t = now
                grp.slots[k] = state
                grp.stack = None  # membership changed: rebuild
                grp.last_good = None
                self._journal_write(
                    journal_mod.record(
                        "start", state.request.id, ordinal=state.ordinal,
                        **self._epoch_fields(state),
                    )
                )
                self._emit(
                    "start", state.request.id, bucket=grp.label,
                    trace_id=state.trace_id, **self._depths(),
                )

    def _build_stack(self, grp: _BucketGroup) -> None:
        import jax

        from gol_tpu.batch.runtime import stack_worlds
        from gol_tpu.utils.timing import force_ready

        boards = [
            s.board if s is not None else np.zeros(grp.shape, np.uint8)
            for s in grp.slots
        ]
        stack, hs, ws = stack_worlds(boards, grp.shape)
        if self._cur_mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec

            from gol_tpu.batch.engines import WORLDS, batch_sharding

            vec = NamedSharding(self._cur_mesh, PartitionSpec(WORLDS))
            grp.stack = jax.device_put(stack, batch_sharding(self._cur_mesh))
            grp.hs = jax.device_put(hs, vec)
            grp.ws = jax.device_put(ws, vec)
        else:
            grp.stack = jax.device_put(stack)
            grp.hs = jax.device_put(hs)
            grp.ws = jax.device_put(ws)
        force_ready(grp.stack)
        if self.guard:
            from gol_tpu.utils import guard as guard_mod

            audits = guard_mod.audit_worlds(grp.stack, grp.gens)
            grp.last_good = (
                guard_mod._device_copy(grp.stack),
                [a.fingerprint for a in audits],
            )

    def _compiled_program(self, grp: _BucketGroup, take: int):
        """The AOT executable for one (bucket shape, engine, take, mesh
        width) — compilation as a first-class observable: a cold entry
        is lowered + compiled explicitly (the same AOT discipline as
        :meth:`GolBatchRuntime.compile_evolvers`, so chunk walls measure
        steady-state execution, never a hidden first-call trace), stamps
        a v13 ``compile`` event carrying the persistent-cache verdict,
        and feeds the compile-storm detector."""
        key = (grp.shape, grp.engine, len(grp.slots), take, self._cur_n)
        cached = self._programs.get(key)
        if cached is not None:
            return cached
        import jax

        from gol_tpu.batch import cache as cache_mod
        from gol_tpu.batch import engines as batch_engines
        from gol_tpu.models.state import CELL_DTYPE

        jitted = batch_engines.compiled_batch_evolver(
            grp.engine, take, True, self.tile_hint, self._cur_mesh
        )
        H, W = grp.shape
        S = len(grp.slots)
        if self._cur_mesh is not None:
            stack_spec = jax.ShapeDtypeStruct(
                (S, H, W),
                CELL_DTYPE,
                sharding=batch_engines.batch_sharding(self._cur_mesh),
            )
            vec_sharding = jax.sharding.NamedSharding(
                self._cur_mesh,
                jax.sharding.PartitionSpec(batch_engines.WORLDS),
            )
            vec_spec = jax.ShapeDtypeStruct(
                (S,), np.int32, sharding=vec_sharding
            )
        else:
            stack_spec = jax.ShapeDtypeStruct((S, H, W), CELL_DTYPE)
            vec_spec = jax.ShapeDtypeStruct((S,), np.int32)
        probe = cache_mod.CompileCacheProbe()
        t0 = time.perf_counter()
        lowered = jitted.lower(stack_spec, vec_spec, vec_spec)
        t1 = time.perf_counter()
        executable = lowered.compile()
        t2 = time.perf_counter()
        cache_hit, cache_key = probe.resolve()
        self._programs[key] = executable
        fields = dict(
            chunk=take,
            lower_s=t1 - t0,
            compile_s=t2 - t1,
            batch={
                "bucket": list(grp.shape),
                "B": S,
                "masked": True,
                "engine": grp.engine,
            },
        )
        if cache_hit is not None:
            fields["cache_hit"] = cache_hit
            fields["cache_key"] = cache_key
        if self._events is not None:
            self._events.emit("compile", **fields)
        else:
            blackbox.record_event("compile", **fields)
        if cache_hit is not True:
            # Persistent-cache hits are fast loads, not cold compiles:
            # a supervised restart against a hot cache must never read
            # as a storm (docs/SERVING.md "Compile storms").
            self._note_cold_compile()
        return executable

    def _note_cold_compile(self) -> None:
        """One cold compile landed: slide the storm window, and past K
        inside it emit the v13 ``storm`` event and engage the admission
        throttle until the window drains."""
        now = time.time()
        w = self.storm_window_s
        self._cold_compiles.append(now)
        while self._cold_compiles and self._cold_compiles[0] < now - w:
            self._cold_compiles.popleft()
        if (
            len(self._cold_compiles) >= self.storm_threshold
            and now >= self._storm_until
        ):
            self._storm_until = now + w
            self.storms_total += 1
            fields = dict(
                kind="compile",
                count=len(self._cold_compiles),
                window_s=w,
                threshold=self.storm_threshold,
                generation=self._total_gens,
                throttled=True,
            )
            if self._events is not None:
                self._events.emit("storm", **fields)
            else:
                blackbox.record_event("storm", **fields)
                if self._registry is not None:
                    self._registry.observe(
                        {"event": "storm", "t": now, **fields}
                    )

    def storm_active(self) -> bool:
        """True while the compile-storm admission throttle is engaged."""
        return time.time() < self._storm_until

    def _step_group(self, grp: _BucketGroup) -> None:
        from gol_tpu.resilience import faults as faults_mod
        from gol_tpu.utils import guard as guard_mod
        from gol_tpu.utils.timing import force_ready

        active = [
            (k, s) for k, s in enumerate(grp.slots) if s is not None
        ]
        take = min(
            self.chunk, min(s.remaining for _, s in active)
        )
        compiled = self._compiled_program(grp, take)
        if grp.stack is None:
            self._build_stack(grp)
        world_ids = tuple(
            s.ordinal if s is not None else -1 for s in grp.slots
        )
        gen_after = grp.gens + take
        restores = 0
        audits = None
        straggler = False
        straggler_verdicts: list = []
        pre_good = grp.last_good if self.guard else None
        while True:
            w0 = time.time()
            t0 = time.perf_counter()
            candidate = compiled(grp.stack, grp.hs, grp.ws)
            force_ready(candidate)
            wall = time.perf_counter() - t0
            if self._health is not None:
                hv = self._health.heartbeat(gen_after, wall)
                # Only the final (surviving) iteration's verdicts ride
                # the chunk span — earlier iterations are rolled back.
                straggler_verdicts = [
                    v for v in hv if v.kind == "straggler"
                ]
                if straggler_verdicts:
                    straggler = True
            if self._plan_on:
                candidate = faults_mod.apply_board_faults(
                    candidate, gen_after, world_ids=world_ids
                )
            if not self.guard:
                break
            audits = guard_mod.audit_worlds(candidate, gen_after)
            if self._events is not None:
                for k, s in active:
                    self._events.guard_event(
                        audits[k], world=s.ordinal, bucket=grp.label,
                        request_id=s.request.id,
                    )
            else:
                # No file sink: the audits still ring in the black box
                # (a postmortem's "last guard audit" must exist for
                # every process, not just instrumented ones).
                for k, s in active:
                    a = audits[k]
                    blackbox.record_event(
                        "guard_audit",
                        generation=a.generation, ok=a.ok,
                        max_cell=a.max_cell, population=a.population,
                        fingerprint=a.fingerprint,
                        world=s.ordinal, bucket=grp.label,
                        request_id=s.request.id,
                    )
            bad = [k for k, s in active if not audits[k].ok]
            if not bad:
                grp.last_good = (
                    guard_mod._device_copy(candidate),
                    [a.fingerprint for a in audits],
                )
                break
            # Detection: roll back THIS group only, replay the chunk.
            self.guard_failures += len(bad)
            grp.replays += 1
            restores += 1
            if restores > self.guard_max_restores:
                raise guard_mod.GuardError(
                    f"serve bucket {grp.label}: corruption persisted "
                    f"past {self.guard_max_restores} rollback-replays "
                    "(persistent fault — crash-only: the supervisor "
                    "restarts and the journal re-admits)"
                )
            base, fps = grp.last_good
            restored = guard_mod._device_copy(base)
            base_audits = guard_mod.audit_worlds(restored, grp.gens)
            if [a.fingerprint for a in base_audits] != fps:
                raise guard_mod.GuardError(
                    f"serve bucket {grp.label}: rollback base failed "
                    "fingerprint verification"
                )
            grp.stack = restored
        # Chunk attribution (host-side, post-fence — never traced): the
        # span window [w0, w1] covers the surviving iteration only; the
        # guard's rollback-replays before it land in the stall residual.
        # Each rider's own share of the chunk is wall/co_resident; the
        # rest is interference from the co-residents it shared the
        # masked program with.
        w1 = time.time()
        from gol_tpu import telemetry as telemetry_mod

        util = telemetry_mod.roofline_utilization(
            grp.engine,
            len(grp.slots) * grp.shape[0] * grp.shape[1]
            // max(self._cur_n, 1),
            take, 1, self._cur_mesh is not None, wall,
        )
        co = len(active)
        dur = max(w1 - w0, 0.0)
        for _, s in active:
            s.phase_s["compute"] += dur / co
            s.phase_s["interference"] += dur * (co - 1) / co
            s.chunk_span_id = self._tracer.span(
                s.trace_id, s.request.id, "chunk", w0, w1,
                bucket=grp.label, take=take, wall_s=round(wall, 6),
                co_resident=co, utilization=util, generation=gen_after,
            )
            for v in straggler_verdicts:
                self._tracer.span(
                    s.trace_id, s.request.id, "straggler", w0, w1,
                    parent_id=s.chunk_span_id, **v.to_span_attrs(),
                )
        if straggler and self.guard and pre_good is not None:
            h0 = time.time()
            candidate, audits = self._hedge_replay(
                grp, compiled, pre_good, candidate, audits, gen_after
            )
            h1 = time.time()
            for _, s in active:
                s.phase_s["hedge"] += h1 - h0
                self._tracer.span(
                    s.trace_id, s.request.id, "hedge", h0, h1,
                    parent_id=s.chunk_span_id or trace_mod.ROOT_SPAN_ID,
                    bucket=grp.label, generation=gen_after,
                )
        grp.gens = gen_after
        self._total_gens += take
        grp.stack = candidate
        for _, s in active:
            s.remaining -= take
            s.generation += take
        cells = sum(
            s.request.size * s.request.size for _, s in active
        )
        batch_block = {
            "bucket": list(grp.shape),
            "B": len(grp.slots),
            "masked": True,
            "engine": grp.engine,
        }
        if self._events is not None:
            self._events.chunk_event(
                self._chunk_index, take, grp.gens, wall,
                cells * take, util, batch=batch_block,
            )
        else:
            blackbox.record_event(
                "chunk",
                index=self._chunk_index, take=take, generation=grp.gens,
                wall_s=wall,
                updates_per_sec=(
                    (cells * take / wall) if wall > 0 else 0.0
                ),
                roofline_util=util, batch=batch_block,
            )
        self._chunk_index += 1
        if (
            self.guard
            and not self._stats_shed
            and audits is not None
        ):
            for k, s in active:
                if s.request.stream_stats:
                    s.stats.append(
                        {
                            "generation": s.generation,
                            "population": audits[k].population,
                        }
                    )
        done = [(k, s) for k, s in active if s.remaining <= 0]
        if done:
            host = np.asarray(candidate)
            for k, s in active:
                n = s.request.size
                s.board = host[k, :n, :n].copy()
            for k, s in done:
                grp.slots[k] = None
                self._finish(s, grp)
            grp.stack = None  # freed slots must read as dead zeros
            grp.last_good = None
        if self._plan_on:
            faults_mod.crash_or_stall(self._total_gens)

    # -- internals: live elasticity ------------------------------------------
    def _poll_health(self) -> None:
        """Sample loss/restore verdicts; a capacity change arms a mesh
        transition for the NEXT chunk boundary (readiness drops now, so
        /readyz sees the window the contract documents)."""
        verdicts = self._health.poll(self._total_gens)
        if self._cur_mesh is not None and any(
            v.kind in ("device_loss", "device_restore") for v in verdicts
        ):
            self._pending_resize = True
            self._resharding = True

    def _resize_mesh(self) -> None:
        """Move every live group stack onto the largest slot-divisible
        mesh the surviving devices support — on device, through the
        all-to-all collective, journal untouched."""
        from gol_tpu.batch import engines as batch_engines
        from gol_tpu.parallel import redistribute

        alive = self._health.alive
        n = max(
            d for d in range(1, min(len(alive), self.slots) + 1)
            if self.slots % d == 0
        )
        devices = [self._devices[i] for i in alive[:n]]
        if [d.id for d in devices] == [
            d.id for d in self._cur_mesh.devices.flat
        ]:
            return
        new_mesh = batch_engines.make_batch_mesh(devices=devices)
        moved = 0
        r0 = time.time()
        riders: List[Tuple[_BucketGroup, RequestState]] = []
        for grp in self._groups.values():
            if grp.stack is None:
                continue
            riders.extend(
                (grp, s) for s in grp.slots if s is not None
            )
            plan = redistribute.plan_worlds(
                len(grp.slots), self._cur_n, n
            )
            grp.stack = redistribute.device_reshard_worlds(
                grp.stack, self._cur_mesh, new_mesh, plan
            )
            if grp.last_good is not None:
                base, fps = grp.last_good
                grp.last_good = (
                    redistribute.device_reshard_worlds(
                        base, self._cur_mesh, new_mesh, plan
                    ),
                    fps,
                )
            # The extent vectors are tiny; re-place rather than reshard.
            self._replace_extents(grp, new_mesh)
            moved += 1
            self._emit_reshard(plan, bucket=grp.label)
        if moved == 0:
            # No stack was live at the boundary; the transition is still
            # a fact of the stream (the serve drills assert on it).
            self._emit_reshard(
                redistribute.plan_worlds(self.slots, self._cur_n, n)
            )
        # Every in-flight rider gets a reshard span over the whole
        # transition window — the time shows up in its stall phase, and
        # the span says why (docs/OBSERVABILITY.md).
        r1 = time.time()
        for grp, s in riders:
            self._tracer.span(
                s.trace_id, s.request.id, "reshard", r0, r1,
                bucket=grp.label, src_devices=self._cur_n,
                dst_devices=n,
            )
        self._cur_mesh = new_mesh
        self._cur_n = n
        self.live_reshards += 1

    @staticmethod
    def _replace_extents(grp: _BucketGroup, mesh) -> None:
        import jax
        from jax.sharding import NamedSharding, PartitionSpec

        from gol_tpu.batch.engines import WORLDS

        vec = NamedSharding(mesh, PartitionSpec(WORLDS))
        grp.hs = jax.device_put(np.asarray(grp.hs), vec)
        grp.ws = jax.device_put(np.asarray(grp.ws), vec)

    def _emit_reshard(self, plan, **extra) -> None:
        if self._events is not None:
            self._events.reshard_event(
                generation=self._total_gens, live=True,
                **plan.summary(), **extra,
            )
            return
        rec = {
            "event": "reshard", "t": time.time(),
            "generation": self._total_gens, "live": True,
            **plan.summary(), **extra,
        }
        blackbox.record(rec)
        if self._registry is not None:
            self._registry.observe(rec)

    def _hedge_replay(
        self, grp: _BucketGroup, compiled, pre_good, candidate, audits,
        gen_after: int,
    ):
        """Straggler response: recompute the chunk from the
        fingerprint-verified pre-chunk stack and let the guard's
        fingerprint pick the winner.  Agreement keeps the primary (the
        slow rank was slow, not wrong); disagreement takes the hedge
        (the replay ran on the surviving healthy state)."""
        from gol_tpu.utils import guard as guard_mod
        from gol_tpu.utils.timing import force_ready

        base, fps = pre_good
        base_audits = guard_mod.audit_worlds(base, grp.gens)
        if [a.fingerprint for a in base_audits] != fps:
            return candidate, audits  # base unusable: the primary stands
        hedge = compiled(
            guard_mod._device_copy(base), grp.hs, grp.ws
        )
        force_ready(hedge)
        h_audits = guard_mod.audit_worlds(hedge, gen_after)
        p_fps = [a.fingerprint for a in audits] if audits else None
        agree = p_fps is not None and [
            a.fingerprint for a in h_audits
        ] == p_fps
        self.hedges += 1
        payload = {
            "verdict": "hedge",
            "generation": gen_after,
            "bucket": grp.label,
            "winner": "primary" if agree else "hedge",
            "agree": agree,
        }
        if self._health is not None:
            payload["alive"] = len(self._health.alive)
        if self._events is not None:
            self._events.health_event(**payload)
        elif self._registry is not None:
            self._registry.observe(
                {"event": "health", "t": time.time(), **payload}
            )
        if agree:
            return candidate, audits
        grp.last_good = (
            guard_mod._device_copy(hedge),
            [a.fingerprint for a in h_audits],
        )
        return hedge, h_audits

    def _decomposition(self, state: RequestState, end_t: float) -> dict:
        """The five-phase latency decomposition from the accumulators.
        Stall is the residual — scheduler overhead, guard replays,
        reshard windows, and (for a crash-replayed request, whose
        accumulators restarted with the process) the crash gap — so the
        phases sum to ``e2e_s`` exactly by construction.  The read side
        (:func:`gol_tpu.telemetry.trace.decompose`) recomputes the same
        quantity from the spans alone; write and read agreeing is the
        1%-additivity acceptance check."""
        e2e = max(end_t - state.submitted_t, 0.0)
        p = state.phase_s
        accounted = (
            p["queue"] + p["compute"] + p["interference"] + p["hedge"]
        )
        return {
            "e2e_s": round(e2e, 6),
            "queue_s": round(p["queue"], 6),
            "compute_s": round(p["compute"], 6),
            "interference_s": round(p["interference"], 6),
            "hedge_s": round(p["hedge"], 6),
            "stall_s": round(max(e2e - accounted, 0.0), 6),
        }

    def _finish(self, state: RequestState, grp: _BucketGroup) -> None:
        from gol_tpu.utils import guard as guard_mod

        fp = guard_mod.fingerprint_np(state.board)
        end_t = time.time()
        latency = end_t - state.submitted_t
        decomp = self._decomposition(state, end_t)
        payload = {
            "id": state.request.id,
            "status": "done",
            "pattern": state.request.pattern,
            "size": state.request.size,
            "generations": state.request.generations,
            "generation": state.generation,
            "engine": grp.engine,
            "bucket": grp.label,
            "fingerprint": int(fp),
            "population": int(state.board.sum()),
            "latency_s": round(latency, 6),
            "trace_id": state.trace_id,
            "decomposition": decomp,
            "board": encode_board(state.board),
        }
        if state.request.stream_stats:
            payload["stats"] = state.stats
            payload["stats_shed"] = self._stats_shed
        self._write_result(payload)
        self._journal_write(
            journal_mod.record(
                "complete", state.request.id, fingerprint=int(fp),
                generation=state.generation, trace_id=state.trace_id,
                **self._epoch_fields(state),
            )
        )
        # The commit span covers making the result durable; the root
        # span ends at ``end_t``, where ``latency_s`` is measured — so
        # read-side e2e equals the payload's latency, and the commit
        # tail (fsync, journal append) shows as a child past the root's
        # edge rather than silently inflating every latency number.
        self._tracer.span(
            state.trace_id, state.request.id, "commit", end_t,
            time.time(), bucket=grp.label, fingerprint=int(fp),
        )
        self._tracer.span(
            state.trace_id, state.request.id, "request",
            state.submitted_t, end_t, parent_id=None,
            span_id=trace_mod.ROOT_SPAN_ID, status="done", **decomp,
        )
        state.result = payload
        state.status = "done"
        self.completed_total += 1
        self._complete_times.append(time.time())
        self._emit(
            "complete", state.request.id, bucket=grp.label,
            latency_s=payload["latency_s"], generation=state.generation,
            trace_id=state.trace_id, **self._depths(),
        )
        state.done.set()
        self._completions_since_compact += 1
        if (
            self._completions_since_compact >= self.compact_every
            and not self._journal_shed
        ):
            self._completions_since_compact = 0
            try:
                self._journal.compact(self.keep_journal_segments)
            except OSError:  # full disk: the live journal still works
                pass

    # -- internals: telemetry ------------------------------------------------
    def _emit(self, action: str, request_id: str, **extra) -> None:
        if self._events is not None:
            # The EventLog's own emit() taps the black-box ring.
            self._events.serve_event(action, request_id, **extra)
            return
        rec = {
            "event": "serve", "t": time.time(),
            "action": action, "request_id": request_id,
            **extra,
        }
        blackbox.record(rec)
        if self._registry is not None:
            self._registry.observe(rec)

    def _drain_plane(self) -> None:
        from gol_tpu.resilience import degrade as degrade_mod
        from gol_tpu.resilience import faults as faults_mod

        if self._events is None:
            # No file sink: the fired/degraded ledgers still ring in
            # the black box instead of vanishing.
            for f in faults_mod.drain_fired():
                blackbox.record_event("fault", **f)
            for d in degrade_mod.drain_reports():
                blackbox.record_event("degraded", **d)
            return
        for f in faults_mod.drain_fired():
            self._events.fault_event(**f)
        for d in degrade_mod.drain_reports():
            self._events.degraded_event(**d)


def encode_board(board: np.ndarray) -> List[str]:
    """Rows of '0'/'1' characters — byte-comparable across transports."""
    return ["".join("1" if c else "0" for c in row) for row in board]


def decode_board(rows: List[str]) -> np.ndarray:
    return np.array(
        [[1 if c == "1" else 0 for c in row] for row in rows], np.uint8
    )


def _restart_attempt() -> int:
    try:
        return int(os.environ.get("GOL_RESTART_ATTEMPT", "0"))
    except ValueError:
        return 0
