"""The serving tier: a long-lived simulation server with continuous
batching (docs/SERVING.md).

``python -m gol_tpu.serve --state-dir DIR [--port P]`` runs a persistent
rank-0 process that accepts simulation requests over local HTTP, admits
them into the PR 5 batch size buckets, and **refills batch slots as
individual worlds finish** — continuous batching.  The robustness plane
is the point: bounded admission queues with explicit 429 backpressure,
per-request deadlines cancelled at chunk boundaries, a crash-safe
fsync'd request journal replayed by supervised restarts
(``python -m gol_tpu.resilience supervise -- python -m gol_tpu.serve
...``) so every accepted request completes exactly once, and per-bucket
guard rollback so one poisoned request never replays another tenant's
work.

Layers: :mod:`.journal` (durability), :mod:`.scheduler` (admission +
the chunk loop), :mod:`.server` (HTTP front end), :mod:`.client`
(drill/bench client).
"""

from gol_tpu.serve.journal import Journal
from gol_tpu.serve.scheduler import (
    Rejected,
    Request,
    ServeScheduler,
    ValidationError,
    decode_board,
    encode_board,
)

__all__ = [
    "Journal",
    "Rejected",
    "Request",
    "ServeScheduler",
    "ValidationError",
    "decode_board",
    "encode_board",
]
