"""``python -m gol_tpu.serve`` — run the simulation server.

The process layout is deliberate: HTTP handler threads only touch the
scheduler's locked admission surface; the device loop runs HERE, on the
main thread, so guard escalations and injected ``crash.exit`` faults
kill the process where the supervisor
(``python -m gol_tpu.resilience supervise -- ...``) can restart it, and
the journal replay re-admits everything in flight.

Shutdown is graceful by construction: SIGTERM/SIGINT (or
``POST /shutdown``) stop admissions and the loop finishes every
committed request before exiting 0 — the supervisor reads that as a
clean finish, not a crash.
"""

from __future__ import annotations

import argparse
import os
import signal
import sys
import time


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m gol_tpu.serve",
        description="long-lived Game of Life simulation server "
        "(continuous batching; docs/SERVING.md)",
    )
    p.add_argument(
        "--state-dir", required=True,
        help="journal + results directory (the durability root; give "
        "the SAME directory to every supervised restart)",
    )
    p.add_argument(
        "--port", type=int, default=0,
        help="HTTP port on 127.0.0.1 (0 = ephemeral; printed at start)",
    )
    p.add_argument(
        "--telemetry", default=None,
        help="event-stream directory (default: <state-dir>/telemetry; "
        "'none' disables)",
    )
    p.add_argument("--run-id", default=None)
    p.add_argument(
        "--slots", type=int, default=4,
        help="batch slots per bucket group (default 4)",
    )
    p.add_argument(
        "--queue-depth", type=int, default=8,
        help="bounded admission queue per bucket; beyond this the "
        "server answers 429 + Retry-After (default 8)",
    )
    p.add_argument(
        "--chunk", type=int, default=4,
        help="generations per compiled device chunk — the deadline / "
        "refill / cancel granularity (default 4)",
    )
    p.add_argument(
        "--bucket-quantum", type=int, default=64,
        help="bucket size rounding quantum (default 64)",
    )
    p.add_argument(
        "--engine", default="auto",
        choices=["auto", "dense", "bitpack", "pallas_bitpack"],
        help="default engine for requests that do not pick one",
    )
    p.add_argument(
        "--no-guard", action="store_true",
        help="disable per-chunk integrity audits (guard is on by "
        "default: serve is multi-tenant, corruption must not cross "
        "requests)",
    )
    p.add_argument("--guard-max-restores", type=int, default=3)
    p.add_argument(
        "--keep-journal-segments", type=int, default=2,
        help="rotated journal segments kept by compaction GC",
    )
    p.add_argument(
        "--compact-every", type=int, default=16,
        help="journal compaction period, in completed requests",
    )
    p.add_argument(
        "--mesh-devices", type=int, default=0,
        help="shard bucket groups over a worlds mesh of N devices and "
        "arm the health plane: device loss / stragglers live-reshard "
        "at chunk boundaries instead of crashing (0 = unsharded; "
        "N must divide --slots)",
    )
    p.add_argument(
        "--fault-plan", default=None,
        help="fault-injection plan (path or inline JSON; default: "
        "the GOL_FAULT_PLAN environment variable)",
    )
    return p


def main(argv=None) -> int:
    ns = build_parser().parse_args(argv)

    from gol_tpu.resilience import faults as faults_mod

    try:
        if ns.fault_plan:
            faults_mod.install(faults_mod.FaultPlan.load(ns.fault_plan))
        else:
            faults_mod.install_from_env()
    except faults_mod.FaultPlanError as e:
        print(e)
        return 255

    from gol_tpu.serve.scheduler import ServeScheduler
    from gol_tpu.serve.server import ServeServer
    from gol_tpu.telemetry.metrics import MetricsRegistry

    telemetry_dir = ns.telemetry
    if telemetry_dir is None:
        telemetry_dir = os.path.join(ns.state_dir, "telemetry")
    elif telemetry_dir == "none":
        telemetry_dir = None

    registry = MetricsRegistry()
    scheduler = ServeScheduler(
        ns.state_dir,
        quantum=ns.bucket_quantum,
        slots=ns.slots,
        queue_depth=ns.queue_depth,
        chunk=ns.chunk,
        guard=not ns.no_guard,
        guard_max_restores=ns.guard_max_restores,
        default_engine=ns.engine,
        telemetry_dir=telemetry_dir,
        run_id=ns.run_id,
        registry=registry,
        keep_journal_segments=ns.keep_journal_segments,
        compact_every=ns.compact_every,
        mesh_devices=ns.mesh_devices,
    )
    server = ServeServer(scheduler, ns.port, registry=registry)
    stop = server.stop_event

    # Arm the black box's signal triggers (SIGABRT, faulthandler) BEFORE
    # installing the graceful handler: _graceful then replaces the
    # SIGTERM disposition, so a drain exits 0 with no dump while an
    # abort still leaves one (docs/OBSERVABILITY.md "Black box").
    from gol_tpu.telemetry import blackbox

    # run_id/dump-dir identity was configured by the scheduler's own
    # install; this call only arms the signal layer on top of it.
    blackbox.install(
        telemetry_dir or ns.state_dir,
        process_index=0,
        signals=True,
    )

    def _graceful(signum, frame):
        scheduler.drain()
        stop.set()

    signal.signal(signal.SIGTERM, _graceful)
    signal.signal(signal.SIGINT, _graceful)

    print(
        f"serve: listening on http://127.0.0.1:{server.port} "
        f"(state {ns.state_dir})",
        flush=True,
    )
    try:
        while True:
            if stop.is_set():
                scheduler.drain()
                if scheduler.outstanding() == 0:
                    break
            if not scheduler.run_once():
                time.sleep(0.005)
    finally:
        server.close()
        scheduler.close()
    print("serve: drained; exiting", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
