"""The serving-tier HTTP front end (docs/SERVING.md).

Stdlib only, same shape as the ``--metrics-port`` exporter
(:class:`gol_tpu.telemetry.metrics.MetricsServer`): a
``ThreadingHTTPServer`` bound to 127.0.0.1 runs on a daemon thread and
its handler threads only ever call the scheduler's locked entry points
(:meth:`submit` / :meth:`get_result`); the device loop stays on the
process's main thread (:mod:`gol_tpu.serve.__main__`), so a guard
escalation or an injected ``crash.exit`` dies where the supervisor can
see it.

Endpoints::

    POST /simulate   {"pattern": 4, "size": 96, "generations": 50, ...}
                     -> 200 result (``"wait": true``) or 202 ticket
                     -> 400 malformed, 429 queue full (Retry-After),
                        503 draining / admissions shed
    GET  /result/ID  -> 200 terminal payload | 202 progress | 404
    GET  /healthz    -> 200 {"ok": true, ready, outstanding, draining}
                        (liveness: the process is up — always 200)
    GET  /readyz     -> 200 when admitting | 503 while draining, while
                        admissions are shed, or through a live-reshard
                        window (docs/RESILIENCE.md "Live elasticity")
    GET  /metrics    -> Prometheus text (the gol_serve_* gauges)
    GET  /debug/blackbox -> ndjson snapshot of the flight-recorder
                        ring (schema v13, same bytes a crash dump
                        would write) | 404 recorder disabled
    POST /fence      -> 200 {"fenced": n}: the fleet front tier
                        migrated these ids to another replica at the
                        given routing epoch — drop them uncompleted
                        (docs/SERVING.md "The fleet")
    POST /shutdown   -> 200, then graceful drain: stop admitting,
                        finish every committed request, exit 0

Backpressure is explicit, never silent: 429/503 carry a JSON ``error``
plus ``retry_after`` (and the ``Retry-After`` header) — a well-behaved
client resubmits the SAME id later and admission stays exactly-once.
Exactly-once holds **only for caller-supplied ids**: omit ``id`` and the
server mints one per submission, so a blind retry is a new request — the
202 ticket flags this (``id_generated``) and names the id to reuse.
"""

from __future__ import annotations

import http.server
import json
import threading
from typing import Optional

from gol_tpu.serve.scheduler import (
    Rejected, ServeScheduler, ValidationError,
)


class _Handler(http.server.BaseHTTPRequestHandler):
    # Set on the per-server class copy by ServeServer:
    scheduler: ServeScheduler
    registry = None
    stop_event: threading.Event

    # -- plumbing ------------------------------------------------------------
    def _json(
        self, status: int, payload: dict,
        retry_after: Optional[float] = None,
    ) -> None:
        body = json.dumps(payload, sort_keys=True).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        if retry_after is not None:
            self.send_header("Retry-After", str(retry_after))
        self.end_headers()
        self.wfile.write(body)

    def _body(self) -> dict:
        length = int(self.headers.get("Content-Length", "0"))
        if length <= 0:
            raise ValidationError("request body required")
        raw = self.rfile.read(length)
        try:
            return json.loads(raw)
        except json.JSONDecodeError as e:
            raise ValidationError(f"body is not valid JSON: {e}")

    def log_message(self, *args):  # silence per-request stderr noise
        pass

    # -- routes --------------------------------------------------------------
    def do_GET(self):  # noqa: N802 - http.server API
        path = self.path.rstrip("/")
        if path == "/healthz":
            # Liveness: the process is up and answering.  Readiness is
            # the separate signal — a live server mid-reshard reports
            # ok=true here and 503 on /readyz, so an orchestrator
            # steers traffic away without restarting it.
            self._json(
                200,
                {
                    "ok": True,
                    "ready": self.scheduler.ready,
                    "outstanding": self.scheduler.outstanding(),
                    "draining": self.scheduler.draining,
                },
            )
        elif path == "/readyz":
            ready = self.scheduler.ready
            self._json(
                200 if ready else 503,
                {
                    "ready": ready,
                    "draining": self.scheduler.draining,
                },
            )
        elif path == "/metrics":
            if self.registry is None:
                self.send_error(404, "no metrics registry attached")
                return
            from gol_tpu.telemetry.metrics import CONTENT_TYPE

            body = self.registry.render().encode()
            self.send_response(200)
            self.send_header("Content-Type", CONTENT_TYPE)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        elif path == "/debug/blackbox":
            # On-demand flight-recorder dump: the exact lines a crash
            # would write, straight from the in-memory ring — no disk
            # IO, so it works even when the telemetry dir is shed.
            from gol_tpu.telemetry import blackbox

            rec = blackbox.recorder()
            if rec is None:
                self.send_error(404, "black-box recorder disabled")
                return
            body = (
                "\n".join(rec.dump_lines("debug.endpoint")) + "\n"
            ).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/x-ndjson")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        elif path.startswith("/result/"):
            self._result(path[len("/result/"):])
        else:
            self.send_error(
                404,
                "routes: /simulate /result/<id> /healthz /readyz "
                "/metrics /debug/blackbox",
            )

    def do_POST(self):  # noqa: N802 - http.server API
        path = self.path.rstrip("/")
        if path == "/simulate":
            self._simulate()
        elif path == "/fence":
            self._fence()
        elif path == "/shutdown":
            self.scheduler.drain()
            self.stop_event.set()
            self._json(200, {"ok": True, "draining": True})
        else:
            self.send_error(404, "POST routes: /simulate /fence /shutdown")

    def _fence(self) -> None:
        """Fleet ownership fencing (docs/SERVING.md "The fleet"): the
        front tier migrated these ids to another replica at ``epoch``;
        this replica must drop them without completing."""
        try:
            body = self._body()
        except ValidationError as e:
            self._json(400, {"error": str(e)})
            return
        ids = body.get("ids")
        epoch = body.get("epoch")
        if (
            not isinstance(ids, list)
            or not all(isinstance(i, str) for i in ids)
            or not isinstance(epoch, int)
            or isinstance(epoch, bool)
            or epoch < 0
        ):
            self._json(
                400,
                {"error": "fence body must be "
                          '{"ids": [str, ...], "epoch": int >= 0}'},
            )
            return
        fenced = self.scheduler.fence(ids, epoch)
        self._json(200, {"fenced": fenced, "epoch": epoch})

    def _simulate(self) -> None:
        try:
            body = self._body()
            wait = bool(body.get("wait", False))
            state = self.scheduler.submit(body)
        except ValidationError as e:
            self._json(400, {"error": str(e)})
            return
        except Rejected as e:
            self._json(
                e.status,
                {"error": str(e), "retry_after": e.retry_after},
                retry_after=e.retry_after,
            )
            return
        if wait:
            state.done.wait()
        # Read back through the scheduler's locked snapshot, never the
        # live RequestState: handler threads racing the drive loop can
        # otherwise observe a terminal status before its result payload
        # (lockcheck: guarded-fields, docs/ANALYSIS.md).
        snap = self.scheduler.peek(state.request.id)
        if snap is not None and snap["result"] is not None:
            self._json(200, snap["result"])
        else:
            ticket = {
                "id": state.request.id,
                "status": snap["status"] if snap else "queued",
                "generation": snap["generation"] if snap else 0,
                # The trace id rides every in-flight answer so a caller
                # can correlate its request with `telemetry trace`
                # before (or without) the terminal payload landing.
                "trace_id": snap["trace_id"] if snap else "",
            }
            if "id" not in body:
                # Exactly-once admission keys on the id.  This one was
                # minted server-side, so a connection-retry that omits
                # it is a NEW request (double-run) — say so in the
                # ticket, where the one client who can fix it reads it.
                ticket["id_generated"] = True
                ticket["note"] = (
                    "id was server-generated: retries must resubmit "
                    "with this id to stay exactly-once"
                )
            self._json(202, ticket)

    def _result(self, request_id: str) -> None:
        snap = self.scheduler.peek(request_id)
        if snap is None:
            self._json(404, {"error": f"unknown request {request_id!r}"})
        elif snap["result"] is not None:
            self._json(200, snap["result"])
        else:
            self._json(
                202,
                {
                    "id": request_id,
                    "status": snap["status"],
                    "generation": snap["generation"],
                    "trace_id": snap["trace_id"],
                },
            )


class ServeServer:
    """Threaded HTTP listener over one scheduler (127.0.0.1 only)."""

    def __init__(
        self, scheduler: ServeScheduler, port: int, registry=None
    ) -> None:
        self.stop_event = threading.Event()
        handler = type(
            "BoundHandler",
            (_Handler,),
            {
                "scheduler": scheduler,
                "registry": registry,
                "stop_event": self.stop_event,
            },
        )
        self._httpd = http.server.ThreadingHTTPServer(
            ("127.0.0.1", port), handler
        )
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name="gol-serve-http",
            daemon=True,
        )
        self._thread.start()

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5.0)
