"""The crash-safe request journal (docs/SERVING.md "The journal").

A serving process that dies mid-batch must not lose a request it said
yes to.  The journal is the serve tier's durability artifact — what the
snapshot files are to a long simulation run: an append-only JSONL intent
log in the server's state directory, one fsync'd record per lifecycle
transition:

- ``admit``    — the full request, the commitment.  Written BEFORE the
  client hears 200/202: if the admit cannot be made durable, the request
  is rejected, never half-accepted.  Since schema v12 the admit also
  carries the request's ``trace_id`` (gol_tpu/telemetry/trace.py):
  compaction preserves admits verbatim and replay restores the id, so a
  crash-replayed request keeps its trace identity and the reader
  stitches its pre-crash spans back onto the replaying run's.
- ``start``    — the request entered a batch slot (advisory: replay
  re-runs *started* work from the initial pattern, which is exact —
  Life is deterministic).
- ``complete`` — the result file landed (its fingerprint and
  ``trace_id`` ride along, cross-correlating journal and trace stream).
- ``cancel``   — a deadline expired at a chunk boundary.
- ``handoff``  — fleet ownership moved (docs/SERVING.md "The fleet"):
  the front tier migrated this intent to another replica at routing
  epoch ``epoch``.  Written on BOTH sides — the (dead or unreachable)
  owner's journal and the fleet's own — so migration is idempotent and
  first-wins: a replica returning from supervisor restart folds its
  journal, finds the intent owned elsewhere, and drops it.

Recovery is a pure fold over the records (:func:`replay`): admitted ids
without a terminal record are re-admitted, completed ids are never run
again (exactly-once), duplicate ``admit`` lines are idempotent (first
wins — the id is the identity).  A torn tail — the artifact of a crash
mid-append — is tolerated: an unparseable line was never acknowledged to
anyone, so it simply does not count; :meth:`Journal.append` self-heals
an unterminated tail before the next record so one torn write can never
corrupt its successor.

**Ownership fencing (fleet mode).**  The journal was single-writer by
assumption until the fleet: the front tier appends ``handoff`` records
into a replica's journal while that replica is dead (or blind behind a
partition), so the fold must arbitrate.  Fleet-proxied records carry an
``owner_epoch`` — the routing epoch the request was admitted under —
and a ``handoff`` at epoch E fences every later record from an epoch
< E: a stalled original that wakes and journals ``complete`` under its
old epoch loses to the handoff, the fold stays ``handed_off``, and the
replica's replay re-runs nothing.  A later ``admit`` at an epoch >= E
re-owns the id (an explicit hand-back).  Single-server journals carry
no ``owner_epoch`` at all and fold byte-for-byte as before.

Fleet-journal record kinds (``epoch``, ``route``) share the append
discipline but are folded by :func:`gol_tpu.serve.fleet.fleet_replay`;
a replica's fold ignores them (no admit, unknown id).

Fault plane: appends fire the ``checkpoint.*`` injection sites
(:mod:`gol_tpu.resilience.faults`) with the record index as the
generation axis — the same precedent as the telemetry site's
records-written counter — so one declarative plan exercises torn journal
appends, transient EIO, and disk-full shedding through the exact code
path production takes.  Callers wrap :meth:`append` in
:func:`gol_tpu.resilience.degrade.write_with_retry`.

GC rides the retention discipline of the snapshot store
(:mod:`gol_tpu.resilience.retention`): :meth:`Journal.compact` rewrites
the live file to only-open intents with the checkpoint tmp+``os.replace``
rename discipline, rotates the previous contents to ``journal.jsonl.<n>``
by hard link (the live path holds a complete journal at every crash
point), and keeps only the newest K rotated segments — never the live
file.
"""

from __future__ import annotations

import errno as errno_mod
import glob
import json
import os
import re
import time
from typing import Dict, Tuple

from gol_tpu.resilience import faults as faults_mod

RECORD_KINDS = (
    "admit", "start", "complete", "cancel",
    # Fleet kinds (docs/SERVING.md "The fleet"): ``handoff`` fences a
    # replica-journal fold; ``epoch``/``route`` live only in the front
    # tier's own journal (gol_tpu/serve/fleet.py folds them).
    "handoff", "epoch", "route",
)
_SEGMENT_RE = re.compile(r"\.(\d+)$")


class Journal:
    """Append-only fsync'd request journal (one per server process)."""

    def __init__(self, path: str) -> None:
        self.path = path
        parent = os.path.dirname(path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        self._f = open(path, "ab")
        # Count existing records so the fault sites' generation axis
        # keeps advancing across restarts, and heal a torn tail left by
        # a crash mid-append (no trailing newline).
        self._count = 0
        self._torn_tail = False
        if os.path.getsize(path):
            with open(path, "rb") as f:
                data = f.read()
            self._count = data.count(b"\n")
            self._torn_tail = not data.endswith(b"\n")

    def append(self, rec: dict) -> None:
        """Durably append one record; raises ``OSError`` on failure.

        Callers wrap this in ``degrade.write_with_retry`` — a transient
        EIO is retried under the same bounded budget as a checkpoint
        write, persistent ENOSPC sheds (the scheduler stops admitting).
        """
        if rec.get("rec") not in RECORD_KINDS:
            raise ValueError(f"unknown journal record kind: {rec!r}")
        line = json.dumps(rec, sort_keys=True)
        if self._torn_tail:
            # Terminate the torn tail so it reads as one unparseable
            # (= unacknowledged) line instead of corrupting this record.
            self._f.write(b"\n")
            self._torn_tail = False
        spec = faults_mod.fire(
            "checkpoint.torn_tmp", self._count, path=self.path
        )
        if spec is not None:
            # A torn append: half the record, no newline, then the error
            # a dying disk would raise.  The retry lands a clean record
            # after the healed tail; replay skips the torn line.
            self._f.write(line[: max(1, len(line) // 2)].encode())
            self._f.flush()
            os.fsync(self._f.fileno())
            self._torn_tail = True
            raise OSError(
                errno_mod.EIO, f"injected torn journal append: {self.path}"
            )
        spec = faults_mod.fire(
            "checkpoint.io_error", self._count, path=self.path
        )
        if spec is not None:
            raise OSError(
                errno_mod.EIO,
                f"injected transient journal IO error: {self.path}",
            )
        spec = faults_mod.fire(
            "checkpoint.disk_full", self._count, path=self.path
        )
        if spec is not None:
            raise OSError(
                errno_mod.ENOSPC,
                f"injected disk-full journal append: {self.path}",
            )
        self._f.write(line.encode() + b"\n")
        self._f.flush()
        os.fsync(self._f.fileno())
        self._count += 1

    # -- compaction / GC -----------------------------------------------------
    def compact(self, keep_segments: int = 2) -> None:
        """Rewrite the live journal to only-open intents; rotate + GC.

        The rewrite uses the checkpoint discipline (tmp + fsync +
        ``os.replace``), and the rotation to ``<path>.<n>`` is a **hard
        link**, never a rename of the live file: at every instruction
        boundary the live path holds a complete journal — the old one
        until ``os.replace`` commits the new one — so a SIGKILL anywhere
        mid-compact can never strand a restart without a journal (old or
        new, never a hybrid, never missing).  :func:`gc_segments` keeps
        the newest ``keep_segments`` rotated segments (the snapshot
        store's keep-newest-K retention, applied to journal history —
        the live file is never a GC candidate).
        """
        entries, _ = replay(self.path)
        open_lines = [
            json.dumps(e["admit"], sort_keys=True)
            for e in entries.values()
            if e["status"] in ("admitted", "started")
        ]
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            for line in open_lines:
                f.write(line + "\n")
            f.flush()
            os.fsync(f.fileno())
        # Highest existing segment + 1 — never the first free gap: GC
        # deletes low numbers, and reusing one would stamp the NEWEST
        # history with the OLDEST-looking name (and GC it next round).
        taken = [
            int(m.group(1))
            for p in glob.glob(self.path + ".*")
            if (m := _SEGMENT_RE.search(p))
        ]
        n = max(taken, default=0) + 1
        self._f.close()
        try:
            # The link and the live file share an inode until the
            # replace lands, which freezes the segment as history.  A
            # crash between the two calls leaves BOTH names pointing at
            # the full old journal — a valid state replay handles.
            os.link(self.path, f"{self.path}.{n}")
            os.replace(tmp, self.path)
            self._count = len(open_lines)
            self._torn_tail = False
        finally:
            # Reopen even on failure (full disk, interrupted rotation):
            # the live path always holds a journal we can append to.
            self._f = open(self.path, "ab")
        gc_segments(self.path, keep_segments)

    def close(self) -> None:
        if not self._f.closed:
            self._f.close()


def replay(path: str) -> Tuple[Dict[str, dict], int]:
    """Fold a journal into per-request state: ``(entries, torn_lines)``.

    ``entries`` maps request id -> ``{"admit": <admit record>,
    "status": admitted|started|completed|cancelled|handed_off,
    "terminal": <record>, "fence_epoch": <int or None>}`` in admission
    order.  Unparseable lines (torn appends — final OR healed mid-file)
    were never acknowledged, so they are counted and ignored; duplicate
    admits are idempotent; records for unknown ids (their admit was
    torn) are dropped.

    The fold arbitrates multi-writer fleet journals by epoch: a
    ``handoff`` record at epoch E marks the entry ``handed_off`` (a
    terminal state for THIS replica — ownership moved) and fences every
    subsequent record whose ``owner_epoch`` is < E, including legacy
    records with no epoch at all — the handoff is authoritative, so a
    fenced replica's late ``complete`` never counts.  An ``admit`` at
    an epoch >= the fence re-owns the id (hand-back).  A ``complete``
    already folded before the handoff wins instead (the result is
    durable; the front tier never migrates a completed intent).
    """
    entries: Dict[str, dict] = {}
    torn = 0
    if not os.path.exists(path):
        return entries, torn
    with open(path, encoding="utf-8", errors="replace") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                torn += 1
                continue
            rid = rec.get("id")
            kind = rec.get("rec")
            if kind == "admit":
                e = entries.get(rid)
                if e is None:
                    entries[rid] = {
                        "admit": rec, "status": "admitted",
                        "terminal": None, "fence_epoch": None,
                    }
                elif e["status"] == "handed_off" and int(
                    rec.get("owner_epoch", 0) or 0
                ) >= (e["fence_epoch"] or 0):
                    # Hand-back: a NEWER epoch re-owns the id here.
                    # Records older than the hand-back stay fenced.
                    entries[rid] = {
                        "admit": rec, "status": "admitted",
                        "terminal": None,
                        "fence_epoch": int(rec.get("owner_epoch", 0) or 0),
                    }
                # else: duplicate admit — first wins.
            elif rid in entries:
                e = entries[rid]
                fence = e.get("fence_epoch")
                if kind == "handoff":
                    if e["status"] not in ("completed", "cancelled"):
                        e["status"] = "handed_off"
                        e["terminal"] = rec
                        e["fence_epoch"] = int(rec.get("epoch", 0) or 0)
                elif fence is not None and int(
                    rec.get("owner_epoch", 0) or 0
                ) < fence:
                    # A record from a fenced epoch: the write lost the
                    # ownership race — it does not count.
                    continue
                elif kind == "start" and e["status"] == "admitted":
                    e["status"] = "started"
                elif kind == "complete":
                    e["status"] = "completed"
                    e["terminal"] = rec
                elif kind == "cancel":
                    e["status"] = "cancelled"
                    e["terminal"] = rec
    return entries, torn


def gc_segments(path: str, keep: int) -> None:
    """Delete rotated ``<path>.<n>`` segments beyond the newest ``keep``
    (highest n = newest; the live ``path`` itself is never touched)."""
    segs = []
    for p in glob.glob(path + ".*"):
        m = _SEGMENT_RE.search(p)
        if m:
            segs.append((int(m.group(1)), p))
    segs.sort(reverse=True)
    for _, p in segs[max(keep, 0):]:
        try:
            os.remove(p)
        except OSError:  # pragma: no cover - racing GC is best-effort
            pass


def record(kind: str, request_id: str, **fields) -> dict:
    """Build one journal record (the single stamping site for ``t``)."""
    return {"rec": kind, "id": request_id, "t": time.time(), **fields}
