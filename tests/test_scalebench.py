"""Weak-scaling harness tests on the 8-device CPU mesh."""

from __future__ import annotations

import json

import pytest

import jax

from gol_tpu.utils import scalebench

jax.config.update("jax_platforms", "cpu")


def test_device_counts_powers_of_two():
    counts = scalebench.device_counts()
    assert counts[0] == 1
    assert counts == sorted(counts)
    assert all(b == 2 * a for a, b in zip(counts, counts[1:]))
    assert counts[-1] <= len(jax.devices())
    assert scalebench.device_counts(limit=4) == [1, 2, 4]


@pytest.mark.parametrize("engine", ["dense", "bitpack"])
def test_weak_scaling_rows(engine):
    size = 128  # multiple of 32, so the same size serves the bitpack engine
    rows = scalebench.measure_weak_scaling(
        size, steps=4, engine=engine, counts=[1, 2, 4]
    )
    assert [r["devices"] for r in rows] == [1, 2, 4]
    assert rows[0]["efficiency"] == 1.0
    for r in rows:
        assert r["updates_per_s"] > 0
        assert r["per_chip"] > 0
        assert r["efficiency"] > 0
        assert r["updates_per_s"] == pytest.approx(
            r["per_chip"] * r["devices"]
        )


def test_unknown_engine_rejected():
    with pytest.raises(ValueError, match="unknown engine"):
        scalebench.measure_weak_scaling(64, 2, engine="warp")


def test_counts_must_start_at_one():
    with pytest.raises(ValueError, match="start at 1"):
        scalebench.measure_weak_scaling(64, 2, counts=[2, 4])
    with pytest.raises(ValueError, match="start at 1"):
        scalebench.measure_weak_scaling(64, 2, counts=[])


def test_main_emits_json(capsys):
    scalebench.main(["128", "2", "dense"])
    out = json.loads(capsys.readouterr().out)
    assert out["engine"] == "dense"
    assert out["rows"][0]["devices"] == 1
    assert len(out["rows"]) >= 1


def test_weak_scaling_pallas_engine():
    """The flagship sharded-Pallas program through the harness (interpret
    mode; tiny sweep — on a real pod this is the curve that matters)."""
    rows = scalebench.measure_weak_scaling(
        64, steps=8, engine="pallas", counts=[1, 2]
    )
    assert [r["devices"] for r in rows] == [1, 2]
    assert all(r["updates_per_s"] > 0 for r in rows)


# -- multi-host sweep: the config-4 curve across OS processes ----------------

_WORKER_SCALEBENCH = """
import sys
import jax
jax.config.update("jax_platforms", "cpu")
from gol_tpu import compat as _compat
_compat.set_cpu_device_count(2)
from gol_tpu.utils import scalebench
scalebench.main([
    "32", "3", "dense",
    "--coordinator", sys.argv[2],
    "--num-processes", "2", "--process-id", sys.argv[1],
])
"""


def test_two_process_weak_scaling_curve():
    """The full efficiency curve across 2 OS processes (4 global devices):
    the 1- and 2-device rows are measured by process 0 alone while
    process 1 idles at the row barrier; the 4-device row runs the real
    cross-process ring.  Only the coordinator reports."""
    import json

    from tests.test_multihost import _run_two_workers

    outs = _run_two_workers(_WORKER_SCALEBENCH, [])
    rec = json.loads(outs[0][1].strip().splitlines()[-1])
    # Process 1 emits no report (Gloo connection chatter aside).
    assert not any(
        line.startswith("{") for line in outs[1][1].strip().splitlines()
    )
    assert rec["processes"] == 2
    assert [r["devices"] for r in rec["rows"]] == [1, 2, 4]
    assert all(r["updates_per_s"] > 0 for r in rec["rows"])
    assert rec["rows"][0]["efficiency"] == 1.0
    assert all(r["efficiency"] > 0 for r in rec["rows"])


def test_pallas_overlap_engine_sweep():
    """The overlap form of the flagship engine sweeps like the serial one
    (interpret mode; shard height >= 24 for the interior/boundary split)."""
    rows = scalebench.measure_weak_scaling(
        64, steps=8, engine="pallas_overlap", counts=[1, 2]
    )
    assert [r["devices"] for r in rows] == [1, 2]
    assert all(r["updates_per_s"] > 0 for r in rows)


def test_factor_2d_near_square():
    assert scalebench.factor_2d(1) == (1, 1)
    assert scalebench.factor_2d(2) == (1, 2)
    assert scalebench.factor_2d(4) == (2, 2)
    assert scalebench.factor_2d(8) == (2, 4)
    assert scalebench.factor_2d(256) == (16, 16)  # config 3's pod mesh


def test_weak_scaling_2d_mesh_dense():
    """r5 (VERDICT r4 #3): the sweep can run the pod decomposition —
    near-square 2-D block meshes with S×S cells per device."""
    rows = scalebench.measure_weak_scaling(
        128, steps=4, engine="dense", counts=[1, 2, 4, 8], mesh_kind="2d"
    )
    assert [r["mesh"] for r in rows] == [
        {"rows": 1, "cols": 1},
        {"rows": 1, "cols": 2},
        {"rows": 2, "cols": 2},
        {"rows": 2, "cols": 4},
    ]
    assert rows[0]["efficiency"] == 1.0
    assert all(r["updates_per_s"] > 0 for r in rows)


def test_weak_scaling_2d_mesh_pallas():
    """The flagship engine over the 2-D pod mesh (two-phase exchange +
    edge-strip repair under the harness; interpret mode)."""
    rows = scalebench.measure_weak_scaling(
        64, steps=8, engine="pallas", counts=[1, 2], mesh_kind="2d"
    )
    assert [r["devices"] for r in rows] == [1, 2]
    assert all(r["updates_per_s"] > 0 for r in rows)


def test_unknown_mesh_kind_rejected():
    with pytest.raises(ValueError, match="mesh kind"):
        scalebench.measure_weak_scaling(64, 2, mesh_kind="3d")


def test_main_mesh_kind_positional(capsys):
    scalebench.main(["128", "2", "dense", "2d"])
    out = json.loads(capsys.readouterr().out)
    assert out["mesh_kind"] == "2d"
    assert out["rows"][-1]["mesh"] == {"rows": 2, "cols": 4}


def test_pallas_overlap_engine_unpackable_width_rejected():
    import pytest as _pytest

    with _pytest.raises(ValueError, match="divisible"):
        scalebench.measure_weak_scaling(
            16, steps=8, engine="pallas_overlap", counts=[1]
        )
