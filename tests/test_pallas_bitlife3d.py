"""Pallas 3-D kernel vs the XLA bit-packed 3-D engine (interpret mode on CPU)."""

from __future__ import annotations

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from gol_tpu.ops import bitlife3d, life3d, pallas_bitlife3d

jax.config.update("jax_platforms", "cpu")


def _rand_vol(d, h, w, seed=0):
    return np.random.default_rng(seed).integers(0, 2, (d, h, w), np.uint8)


@pytest.mark.parametrize("rule", [life3d.BAYS_4555, life3d.BAYS_5766])
@pytest.mark.parametrize("steps", [1, 3])
def test_matches_xla_packed(rule, steps):
    vol = _rand_vol(16, 8, 64, seed=steps + len(rule.survive))
    got = np.asarray(pallas_bitlife3d.evolve3d(jnp.asarray(vol), steps, rule))
    ref = np.asarray(bitlife3d.evolve3d_dense_io(jnp.asarray(vol), steps, rule))
    np.testing.assert_array_equal(got, ref)


def test_temporal_blocking_matches_sequential():
    vol = _rand_vol(16, 8, 32, seed=9)
    pt = jax.lax.bitcast_convert_type(
        bitlife3d.pack3d(jnp.asarray(vol)), jnp.int32
    ).transpose(0, 2, 1)
    ref = pt
    for _ in range(5):
        ref = pallas_bitlife3d.multi_step_pallas_packed3d(ref, 8, 1)
    got = pallas_bitlife3d.multi_step_pallas_packed3d(pt, 8, 5)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))


def test_remainder_path():
    vol = _rand_vol(8, 8, 32, seed=3)
    got = np.asarray(pallas_bitlife3d.evolve3d(jnp.asarray(vol), 11))
    ref = np.asarray(bitlife3d.evolve3d_dense_io(jnp.asarray(vol), 11))
    np.testing.assert_array_equal(got, ref)


def test_tile_and_depth_validation():
    pt = jnp.zeros((16, 2, 32), jnp.int32)
    with pytest.raises(ValueError, match="tile"):
        pallas_bitlife3d.multi_step_pallas_packed3d(pt, 12, 1)
    with pytest.raises(ValueError, match="pad"):
        pallas_bitlife3d.multi_step_pallas_packed3d(pt, 8, 16)
    with pytest.raises(ValueError, match=">= 1"):
        pallas_bitlife3d.multi_step_pallas_packed3d(pt, 8, 0)
    with pytest.raises(ValueError, match="divisible"):
        pallas_bitlife3d.pick_tile3d(12, 2, 32)


def test_pick_tile3d_budget():
    assert pallas_bitlife3d.pick_tile3d(512, 16, 512) == 32
    assert pallas_bitlife3d.pick_tile3d(16, 2, 32) == 16
    # A 1024-cube's (32, 1024)-word plane exceeds the scoped-VMEM window:
    # infeasible, signalled by 0 (evolve3d falls back to the XLA path).
    assert pallas_bitlife3d.pick_tile3d(1024, 32, 1024) == 0


def test_evolve3d_fallback_when_vmem_infeasible(monkeypatch):
    # Force the infeasible branch regardless of geometry and check the
    # result still matches the XLA engine.  The Pallas entry is patched to
    # raise, so a cached/alternate trace taking the kernel path cannot let
    # this test pass vacuously (both paths are bit-exact otherwise).
    monkeypatch.setattr(pallas_bitlife3d, "pick_tile3d", lambda *a, **k: 0)

    def _boom(*a, **k):
        raise AssertionError("Pallas path taken despite tile == 0")

    monkeypatch.setattr(pallas_bitlife3d, "multi_step_pallas_packed3d", _boom)
    vol = _rand_vol(8, 8, 32, seed=12)
    got = np.asarray(pallas_bitlife3d.evolve3d(jnp.asarray(vol), 4))
    ref = np.asarray(bitlife3d.evolve3d_dense_io(jnp.asarray(vol), 4))
    np.testing.assert_array_equal(got, ref)
