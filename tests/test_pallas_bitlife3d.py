"""Pallas 3-D kernel vs the XLA bit-packed 3-D engine (interpret mode on CPU)."""

from __future__ import annotations

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from gol_tpu.ops import bitlife3d, life3d, pallas_bitlife3d

jax.config.update("jax_platforms", "cpu")


def _rand_vol(d, h, w, seed=0):
    return np.random.default_rng(seed).integers(0, 2, (d, h, w), np.uint8)


@pytest.mark.parametrize("rule", [life3d.BAYS_4555, life3d.BAYS_5766])
@pytest.mark.parametrize("steps", [1, 3])
def test_matches_xla_packed(rule, steps):
    vol = _rand_vol(16, 8, 64, seed=steps + len(rule.survive))
    got = np.asarray(pallas_bitlife3d.evolve3d(jnp.asarray(vol), steps, rule))
    ref = np.asarray(bitlife3d.evolve3d_dense_io(jnp.asarray(vol), steps, rule))
    np.testing.assert_array_equal(got, ref)


def test_temporal_blocking_matches_sequential():
    vol = _rand_vol(16, 8, 32, seed=9)
    pt = jax.lax.bitcast_convert_type(
        bitlife3d.pack3d(jnp.asarray(vol)), jnp.int32
    ).transpose(0, 2, 1)
    ref = pt
    for _ in range(5):
        ref = pallas_bitlife3d.multi_step_pallas_packed3d(ref, 8, 1)
    got = pallas_bitlife3d.multi_step_pallas_packed3d(pt, 8, 5)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))


def test_remainder_path():
    vol = _rand_vol(8, 8, 32, seed=3)
    got = np.asarray(pallas_bitlife3d.evolve3d(jnp.asarray(vol), 11))
    ref = np.asarray(bitlife3d.evolve3d_dense_io(jnp.asarray(vol), 11))
    np.testing.assert_array_equal(got, ref)


@pytest.mark.parametrize("rule", [life3d.BAYS_4555, life3d.BAYS_5766])
@pytest.mark.parametrize("k", [1, 3, 8])
def test_roll_kernel_matches_xla_packed(rule, k):
    """The rolling-plane kernel (r4): per-plane fori_loop with a count9
    carry, in-place stores, manual output DMA — vs the XLA oracle."""
    vol = _rand_vol(32, 8, 64, seed=k + len(rule.birth))
    pt = jax.lax.bitcast_convert_type(
        bitlife3d.pack3d(jnp.asarray(vol)), jnp.int32
    ).transpose(0, 2, 1)
    got = bitlife3d.unpack3d(
        jax.lax.bitcast_convert_type(
            pallas_bitlife3d.multi_step_pallas_packed3d_roll(
                pt, 8, k, rule
            ).transpose(0, 2, 1),
            jnp.uint32,
        )
    )
    ref = bitlife3d.evolve3d_dense_io(jnp.asarray(vol), k, rule)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))


def test_roll_kernel_matches_monolithic_plane_kernel():
    """Bit-equality between the rolling and monolithic plane kernels on
    the same tiling — the restructure moves memory, not arithmetic."""
    vol = _rand_vol(32, 16, 32, seed=17)
    pt = jax.lax.bitcast_convert_type(
        bitlife3d.pack3d(jnp.asarray(vol)), jnp.int32
    ).transpose(0, 2, 1)
    a = pallas_bitlife3d.multi_step_pallas_packed3d_roll(pt, 8, 5)
    b = pallas_bitlife3d.multi_step_pallas_packed3d(pt, 8, 5)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_roll_kernel_single_tile_whole_volume():
    """tile == depth: grid of one, the window IS the volume (the 512³
    configuration the big-window picker produces)."""
    vol = _rand_vol(16, 8, 32, seed=23)
    pt = jax.lax.bitcast_convert_type(
        bitlife3d.pack3d(jnp.asarray(vol)), jnp.int32
    ).transpose(0, 2, 1)
    got = pallas_bitlife3d.multi_step_pallas_packed3d_roll(pt, 16, 8)
    ref = pallas_bitlife3d.multi_step_pallas_packed3d(pt, 8, 8)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))


def test_roll_kernel_validation():
    pt = jnp.zeros((16, 2, 32), jnp.int32)
    with pytest.raises(ValueError, match="tile"):
        pallas_bitlife3d.multi_step_pallas_packed3d_roll(pt, 12, 1)
    with pytest.raises(ValueError, match="pad"):
        pallas_bitlife3d.multi_step_pallas_packed3d_roll(pt, 8, 16)
    with pytest.raises(ValueError, match=">= 1"):
        pallas_bitlife3d.multi_step_pallas_packed3d_roll(pt, 8, 0)


def test_pick_tile3d_roll_big_windows():
    """The rolling model fits far larger windows than the monolithic
    one: whole-volume windows at 512³, 64-plane windows at 1024³ (where
    the monolithic plane kernel fits nothing at all)."""
    assert pallas_bitlife3d.pick_tile3d_roll(512, 16, 512) == 256
    assert pallas_bitlife3d.pick_tile3d_roll(1024, 32, 1024) == 64
    assert pallas_bitlife3d.pick_tile3d(1024, 32, 1024) == 0
    # Degenerate: a single plane larger than the whole budget.
    assert pallas_bitlife3d.pick_tile3d_roll(8, 4096, 4096) == 0


def test_tile_and_depth_validation():
    pt = jnp.zeros((16, 2, 32), jnp.int32)
    with pytest.raises(ValueError, match="tile"):
        pallas_bitlife3d.multi_step_pallas_packed3d(pt, 12, 1)
    with pytest.raises(ValueError, match="pad"):
        pallas_bitlife3d.multi_step_pallas_packed3d(pt, 8, 16)
    with pytest.raises(ValueError, match=">= 1"):
        pallas_bitlife3d.multi_step_pallas_packed3d(pt, 8, 0)
    with pytest.raises(ValueError, match="divisible"):
        pallas_bitlife3d.pick_tile3d(12, 2, 32)


def test_pick_tile3d_budget():
    assert pallas_bitlife3d.pick_tile3d(512, 16, 512) == 32
    assert pallas_bitlife3d.pick_tile3d(16, 2, 32) == 16
    # A 1024-cube's (32, 1024)-word plane exceeds the scoped-VMEM window:
    # infeasible, signalled by 0 (evolve3d falls back to the XLA path).
    assert pallas_bitlife3d.pick_tile3d(1024, 32, 1024) == 0


def test_evolve3d_fallback_when_vmem_infeasible(monkeypatch):
    # Force the infeasible branch regardless of geometry and check the
    # result still matches the XLA engine.  The Pallas entries are patched
    # to raise, so a cached/alternate trace taking a kernel path cannot let
    # this test pass vacuously (both paths are bit-exact otherwise).
    monkeypatch.setattr(pallas_bitlife3d, "pick_tile3d", lambda *a, **k: 0)
    monkeypatch.setattr(
        pallas_bitlife3d, "pick_tile3d_wt", lambda *a, **k: None
    )
    monkeypatch.setattr(
        pallas_bitlife3d, "pick_tile3d_roll", lambda *a, **k: 0
    )

    def _boom(*a, **k):
        raise AssertionError("Pallas path taken despite tile == 0")

    monkeypatch.setattr(pallas_bitlife3d, "multi_step_pallas_packed3d", _boom)
    monkeypatch.setattr(
        pallas_bitlife3d, "multi_step_pallas_packed3d_wt", _boom
    )
    vol = _rand_vol(8, 8, 32, seed=12)
    got = np.asarray(pallas_bitlife3d.evolve3d(jnp.asarray(vol), 4))
    ref = np.asarray(bitlife3d.evolve3d_dense_io(jnp.asarray(vol), 4))
    np.testing.assert_array_equal(got, ref)


# -- word-tiled kernel: the 1024³-class path ---------------------------------


def _to_word_leading(vol):
    return jax.lax.bitcast_convert_type(
        bitlife3d.pack3d(jnp.asarray(vol)), jnp.int32
    ).transpose(2, 0, 1)


def _from_word_leading(pw):
    return np.asarray(
        bitlife3d.unpack3d(
            jax.lax.bitcast_convert_type(pw.transpose(1, 2, 0), jnp.uint32)
        )
    )


@pytest.mark.parametrize("rule", [life3d.BAYS_4555, life3d.BAYS_5766])
@pytest.mark.parametrize("k", [1, 3, 8])
@pytest.mark.parametrize("tile_w", [1, 2])
def test_wt_kernel_matches_xla_packed(rule, k, tile_w):
    """tile_w=1 forces a word-chunk seam: the ghost word's bit light cone
    must carry the x neighborhood across chunks for all k generations."""
    vol = _rand_vol(16, 16, 64, seed=k + len(rule.birth))  # nw = 2
    pw = _to_word_leading(vol)
    got = pallas_bitlife3d.multi_step_pallas_packed3d_wt(
        pw, 8, tile_w, k, rule
    )
    ref = np.asarray(
        bitlife3d.evolve3d_dense_io(jnp.asarray(vol), k, rule)
    )
    np.testing.assert_array_equal(_from_word_leading(got), ref)


def test_wt_kernel_wide_volume_seams():
    """4 words × tile_w=2: seams at word 2 and at the torus x wrap."""
    vol = _rand_vol(16, 8, 128, seed=5)  # nw = 4
    pw = _to_word_leading(vol)
    got = pallas_bitlife3d.multi_step_pallas_packed3d_wt(pw, 8, 2, 4)
    ref = np.asarray(bitlife3d.evolve3d_dense_io(jnp.asarray(vol), 4))
    np.testing.assert_array_equal(_from_word_leading(got), ref)


def test_wt_kernel_validation():
    pw = jnp.zeros((2, 16, 32), jnp.int32)
    with pytest.raises(ValueError, match="tile"):
        pallas_bitlife3d.multi_step_pallas_packed3d_wt(pw, 12, 1, 1)
    with pytest.raises(ValueError, match="word tile"):
        pallas_bitlife3d.multi_step_pallas_packed3d_wt(pw, 8, 3, 1)
    with pytest.raises(ValueError, match="light cone"):
        pallas_bitlife3d.multi_step_pallas_packed3d_wt(pw, 8, 1, 33)
    with pytest.raises(ValueError, match="pad"):
        pallas_bitlife3d.multi_step_pallas_packed3d_wt(pw, 8, 1, 16)


def test_pick_tile3d_wt_covers_1024_cube():
    # The headline size: a (32, 1024)-word plane doesn't fit whole, the
    # word-tiled split does.
    got = pallas_bitlife3d.pick_tile3d_wt(1024, 32, 1024)
    assert got is not None
    tile_d, tile_w = got
    assert 1024 % tile_d == 0 and 32 % tile_w == 0
    window = (
        (tile_w + 2)
        * (tile_d + 16)
        * 1024
        * 4
        * pallas_bitlife3d._LIVE_WINDOWS_WT
    )
    assert window <= pallas_bitlife3d._SCOPED_LIMIT


def test_evolve3d_strict_raises_instead_of_fallback(monkeypatch):
    """ADVICE r1: an explicit --engine pallas run must never be silently
    relabeled as Pallas throughput while running the XLA path."""
    monkeypatch.setattr(pallas_bitlife3d, "pick_tile3d", lambda *a, **k: 0)
    monkeypatch.setattr(
        pallas_bitlife3d, "pick_tile3d_wt", lambda *a, **k: None
    )
    monkeypatch.setattr(
        pallas_bitlife3d, "pick_tile3d_roll", lambda *a, **k: 0
    )
    vol = jnp.zeros((8, 8, 32), jnp.uint8)
    with pytest.raises(ValueError, match="scoped VMEM"):
        pallas_bitlife3d.evolve3d(vol, 2, life3d.BAYS_4555, True)


def test_cli3d_explicit_pallas_fails_loud(monkeypatch, capsys):
    from gol_tpu import cli3d

    monkeypatch.setattr(pallas_bitlife3d, "pick_tile3d", lambda *a, **k: 0)
    monkeypatch.setattr(
        pallas_bitlife3d, "pick_tile3d_wt", lambda *a, **k: None
    )
    monkeypatch.setattr(
        pallas_bitlife3d, "pick_tile3d_roll", lambda *a, **k: 0
    )
    rc = cli3d.main(["2", "32", "2", "64", "0", "--engine", "pallas"])
    assert rc == 255
    assert "scoped VMEM" in capsys.readouterr().out


def test_evolve3d_dispatches_to_wt(monkeypatch):
    """When the plane window is infeasible but the word-tiled one fits,
    evolve3d must take the wt kernel (not the XLA fallback)."""
    monkeypatch.setattr(pallas_bitlife3d, "pick_tile3d", lambda *a, **k: 0)
    monkeypatch.setattr(
        pallas_bitlife3d, "pick_tile3d_roll", lambda *a, **k: 0
    )
    calls = []
    real = pallas_bitlife3d.multi_step_pallas_packed3d_wt

    def spy(*a, **kw):
        calls.append(1)
        return real(*a, **kw)

    monkeypatch.setattr(
        pallas_bitlife3d, "multi_step_pallas_packed3d_wt", spy
    )
    vol = _rand_vol(16, 8, 64, seed=21)
    got = np.asarray(pallas_bitlife3d.evolve3d(jnp.asarray(vol), 11))
    ref = np.asarray(bitlife3d.evolve3d_dense_io(jnp.asarray(vol), 11))
    np.testing.assert_array_equal(got, ref)
    assert calls  # the wt kernel actually ran (incl. the remainder launch)


def test_evolve3d_dispatches_to_roll(monkeypatch):
    """The rolling kernel wins the score dispatch when its (bigger)
    window recomputes least — the 1024³ situation, shrunk to interpret
    size: roll(96) scores 1.09 against wt (48,4)'s 1.78 and plane(8)'s
    2.13 (shrinking-window mean, pad 8)."""
    monkeypatch.setattr(pallas_bitlife3d, "pick_tile3d", lambda *a, **k: 8)
    monkeypatch.setattr(
        pallas_bitlife3d, "pick_tile3d_wt", lambda *a, **k: (48, 4)
    )
    monkeypatch.setattr(
        pallas_bitlife3d, "pick_tile3d_roll", lambda *a, **k: 96
    )
    calls = []
    real = pallas_bitlife3d.multi_step_pallas_packed3d_roll

    def spy(*a, **kw):
        calls.append(1)
        return real(*a, **kw)

    monkeypatch.setattr(
        pallas_bitlife3d, "multi_step_pallas_packed3d_roll", spy
    )
    vol = _rand_vol(96, 8, 128, seed=37)
    got = np.asarray(pallas_bitlife3d.evolve3d(jnp.asarray(vol), 11))
    ref = np.asarray(bitlife3d.evolve3d_dense_io(jnp.asarray(vol), 11))
    np.testing.assert_array_equal(got, ref)
    assert calls  # the rolling kernel won the dispatch


def test_score_dispatch_prefers_lower_recompute(monkeypatch):
    """When both kernels fit, the halo-recompute score decides: a plane
    tile of 8 (score 2.13) must lose to wt (48, 4) (score 1.78) — the
    768³ situation, shrunk to interpret-mode size (shrinking-window mean,
    pad 8)."""
    monkeypatch.setattr(pallas_bitlife3d, "pick_tile3d", lambda *a, **k: 8)
    monkeypatch.setattr(
        pallas_bitlife3d, "pick_tile3d_wt", lambda *a, **k: (48, 4)
    )
    monkeypatch.setattr(
        pallas_bitlife3d, "pick_tile3d_roll", lambda *a, **k: 0
    )
    calls = []
    real = pallas_bitlife3d.multi_step_pallas_packed3d_wt

    def spy(*a, **kw):
        calls.append(1)
        return real(*a, **kw)

    monkeypatch.setattr(
        pallas_bitlife3d, "multi_step_pallas_packed3d_wt", spy
    )
    vol = _rand_vol(96, 8, 128, seed=31)  # depth 96 % 48 == 0, nw 4 % 4 == 0
    got = np.asarray(pallas_bitlife3d.evolve3d(jnp.asarray(vol), 3))
    ref = np.asarray(bitlife3d.evolve3d_dense_io(jnp.asarray(vol), 3))
    np.testing.assert_array_equal(got, ref)
    assert calls  # the word-tiled kernel won the dispatch
