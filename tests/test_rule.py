"""Rule-table unit tests: all 2×9 alive×neighbor-count cases.

The reference encodes B3/S23 as an if/else chain (gol-with-cuda.cu:239-257);
these tests enumerate every (alive, neighbor_count) combination explicitly.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from gol_tpu.ops import stencil


@pytest.mark.parametrize("alive", [0, 1])
@pytest.mark.parametrize("n", list(range(9)))
def test_rule_table(alive, n):
    if alive:
        expected = 1 if n in (2, 3) else 0  # survive on 2 or 3
    else:
        expected = 1 if n == 3 else 0  # born on exactly 3
    board = jnp.full((1, 1), alive, jnp.uint8)
    count = jnp.full((1, 1), n, jnp.uint8)
    out = stencil.life_rule(board, count)
    assert out.dtype == jnp.uint8
    assert int(out[0, 0]) == expected


def test_rule_vectorized_matches_scalar():
    rng = np.random.default_rng(0)
    board = rng.integers(0, 2, (16, 16)).astype(np.uint8)
    counts = rng.integers(0, 9, (16, 16)).astype(np.uint8)
    out = np.asarray(stencil.life_rule(jnp.asarray(board), jnp.asarray(counts)))
    expected = ((counts == 3) | ((board == 1) & (counts == 2))).astype(np.uint8)
    np.testing.assert_array_equal(out, expected)
