"""Elastic meshes: cross-topology resume pinned byte-identical.

The acceptance surface of the reshard layer (docs/RESILIENCE.md,
``gol_tpu/resilience/reshard.py``):

- packed-word transport: host pack/unpack round-trips and agrees with
  the device packer; sub-word column slices (the seam repack) equal
  plain cell slicing;
- the planner: layouts, legacy inference, exactly-once validation with
  teeth (overlapping / gapped / src-leaking plans must be rejected);
- the pin: resume-on-a-different-mesh is **byte-identical** to
  same-mesh resume (equivalently: to the uninterrupted run) across
  engine tiers × (none, 1d, 2d) src→dst pairs, grow and shrink, batch
  snapshots included;
- topology-stamped manifests, the degraded-verify path that replaces
  the piece-count mystery, the v7 telemetry event, the ``--reshard-at``
  in-flight stop, the shrink policy, and the plain-``--resume``
  topology hint.
"""

from __future__ import annotations

import dataclasses
import json
import os

import numpy as np
import pytest

import jax

from gol_tpu.models.state import Geometry
from gol_tpu.parallel import mesh as mesh_mod
from gol_tpu.resilience import reshard as rs
from gol_tpu.runtime import GolRuntime, build_mesh
from gol_tpu.utils import checkpoint as ckpt

jax.config.update("jax_platforms", "cpu")

SIZE = 64
GENS = 16
MID = 8


def _ref_board(pattern=6, gens=GENS):
    rt = GolRuntime(geometry=Geometry(size=SIZE, num_ranks=1), engine="dense")
    _, st = rt.run(pattern=pattern, iterations=gens)
    return np.asarray(st.board)


@pytest.fixture(scope="module")
def ref():
    return _ref_board()


def _mesh_for(kind):
    if kind == "none":
        return None
    if kind == "1d":
        return mesh_mod.make_mesh_1d(8)
    return mesh_mod.make_mesh_2d((4, 2))


def _write_src_snapshot(tmp_path, kind):
    """A generation-MID snapshot written by a run on ``kind`` topology."""
    d = str(tmp_path / f"src_{kind}")
    rt = GolRuntime(
        geometry=Geometry(size=SIZE, num_ranks=1),
        engine="dense",
        mesh=_mesh_for(kind),
        checkpoint_every=MID,
        checkpoint_dir=d,
        sharded_snapshots=kind != "none",
    )
    rt.run(pattern=6, iterations=MID)
    if kind == "none":
        return ckpt.checkpoint_path(d, MID)
    return ckpt.sharded_checkpoint_path(d, MID)


# -- packed-word transport ----------------------------------------------------


def test_pack_rows_agrees_with_device_packer():
    rng = np.random.default_rng(0)
    cells = (rng.random((16, 96)) < 0.5).astype(np.uint8)
    from gol_tpu.ops import bitlife

    assert np.array_equal(rs.pack_rows(cells), np.asarray(bitlife.pack(cells)))


def test_packed_column_slices_match_cells_including_seams():
    rng = np.random.default_rng(1)
    cells = (rng.random((5, 170)) < 0.5).astype(np.uint8)
    words = rs.pack_rows(cells)
    assert np.array_equal(rs.unpack_rows(words, 170), cells)
    for c0, c1 in [(0, 170), (32, 64), (1, 170), (31, 33), (63, 65),
                   (47, 111), (169, 170), (5, 5)]:
        got = rs.slice_packed_cols(words, c0, c1)
        assert np.array_equal(got, cells[:, c0:c1]), (c0, c1)
    with pytest.raises(rs.ReshardError):
        rs.slice_packed_cols(words, 0, 200)


def test_packed_store_serves_arbitrary_regions():
    rng = np.random.default_rng(2)
    board = (rng.random((32, 96)) < 0.5).astype(np.uint8)
    store = rs.PackedStore()
    for b in rs.MeshLayout("2d", 4, 3).boxes((32, 96)):
        store.put(b, board[b[0] : b[1], b[2] : b[3]])
    assert np.array_equal(store.region((0, 32, 0, 96)), board)
    assert np.array_equal(store.region((7, 25, 13, 85)), board[7:25, 13:85])
    with pytest.raises(rs.ReshardError):
        rs.PackedStore().region((0, 1, 0, 1))  # nothing tiles it


# -- layouts + plans ----------------------------------------------------------


def test_mesh_layout_roundtrip_and_boxes():
    lay = rs.MeshLayout("2d", 2, 4)
    assert rs.MeshLayout.from_dict(lay.to_dict()) == lay
    assert lay.boxes((8, 8)) == [
        (0, 4, 0, 2), (0, 4, 2, 4), (0, 4, 4, 6), (0, 4, 6, 8),
        (4, 8, 0, 2), (4, 8, 2, 4), (4, 8, 4, 6), (4, 8, 6, 8),
    ]
    with pytest.raises(rs.ReshardError):
        lay.boxes((9, 8))  # does not divide
    with pytest.raises(rs.ReshardError):
        rs.MeshLayout("1d", rows=2, cols=2)
    with pytest.raises(rs.ReshardError):
        rs.MeshLayout("ring")


def test_layout_from_mesh():
    assert rs.MeshLayout.from_mesh(None) == rs.MeshLayout("none")
    assert rs.MeshLayout.from_mesh(mesh_mod.make_mesh_1d(8)) == rs.MeshLayout(
        "1d", rows=8
    )
    assert rs.MeshLayout.from_mesh(
        mesh_mod.make_mesh_2d((4, 2))
    ) == rs.MeshLayout("2d", rows=4, cols=2)


def test_infer_layout_legacy_tables():
    assert rs.infer_layout((8, 8), [(0, 8, 0, 8)]) == rs.MeshLayout("none")
    assert rs.infer_layout(
        (8, 8), [(0, 4, 0, 8), (4, 8, 0, 8)]
    ) == rs.MeshLayout("1d", rows=2)
    assert rs.infer_layout(
        (8, 8), [(0, 4, 0, 4), (0, 4, 4, 8), (4, 8, 0, 4), (4, 8, 4, 8)]
    ) == rs.MeshLayout("2d", rows=2, cols=2)


def test_plan_validation_teeth():
    src = rs.MeshLayout("2d", 4, 2)
    plan = rs.plan_reshard(
        (32, 64), src.boxes((32, 64)), src, rs.MeshLayout("1d", 8)
    )
    assert plan.cells_moved == 32 * 64
    dbox, srcs = plan.moves[-1]
    overlapping = dataclasses.replace(
        plan, moves=plan.moves[:-1] + ((dbox, srcs + (srcs[0],)),)
    )
    with pytest.raises(rs.ReshardPlanError, match="overlap|twice"):
        rs.validate_plan(overlapping)
    gapped = dataclasses.replace(
        plan, moves=plan.moves[:-1] + ((dbox, srcs[:-1]),)
    )
    with pytest.raises(rs.ReshardPlanError, match="incomplete"):
        rs.validate_plan(gapped)
    sbox, inter = srcs[0]
    leaking = dataclasses.replace(
        plan,
        moves=plan.moves[:-1]
        + ((dbox, (((sbox[0], inter[1] - 1, sbox[2], sbox[3]), inter),)
            + srcs[1:]),),
    )
    with pytest.raises(rs.ReshardPlanError, match="outside its src"):
        rs.validate_plan(leaking)
    identity = plan.moves and rs.plan_reshard(
        (32, 64), src.boxes((32, 64)), src, src
    )
    assert identity.identity and not plan.identity


# -- the byte-identity pin ----------------------------------------------------


SRC_KINDS = ("none", "1d", "2d")
DST = [
    ("none", "bitpack"),
    ("1d", "dense"),
    ("1d", "bitpack"),
    ("2d", "dense"),
    ("2d", "bitpack"),
]


@pytest.mark.parametrize("src_kind", SRC_KINDS)
@pytest.mark.parametrize("dst_kind,engine", DST)
def test_cross_topology_resume_bit_identical(
    tmp_path, ref, src_kind, dst_kind, engine
):
    """Any snapshot topology resumes on any mesh, grids byte-equal."""
    snap = _write_src_snapshot(tmp_path, src_kind)
    rt = GolRuntime(
        geometry=Geometry(size=SIZE, num_ranks=1),
        engine=engine,
        mesh=_mesh_for(dst_kind),
    )
    _, st = rt.run(pattern=6, iterations=GENS - MID, resume=snap)
    assert np.array_equal(np.asarray(st.board), ref)
    if src_kind == dst_kind:
        assert rt.last_reshard is None
    else:
        info = rt.last_reshard
        assert info is not None
        assert info["src_mesh"]["kind"] == src_kind
        assert info["dst_mesh"]["kind"] == dst_kind
        assert info["cells"] == SIZE * SIZE
        assert info["bytes_moved"] == SIZE * SIZE // 8


def test_batch_snapshot_world_reshards_onto_mesh(tmp_path, ref):
    """A world from a batch snapshot continues on a mesh, byte-equal."""
    # Two worlds at generation MID: world 1 is the tracked one.
    rt = GolRuntime(geometry=Geometry(size=SIZE, num_ranks=1), engine="dense")
    _, st_mid = rt.run(pattern=6, iterations=MID)
    other = np.zeros((SIZE, SIZE), np.uint8)
    path = ckpt.batch_checkpoint_path(str(tmp_path), MID)
    ckpt.save_batch(path, [other, np.asarray(st_mid.board)], MID)

    mesh = mesh_mod.make_mesh_1d(8)
    board, source, plan = rs.load_resharded(path, mesh, kind="batch", world=1)
    assert source.layout == rs.MeshLayout("none")
    assert plan.summary()["dst_shards"] == 8
    from gol_tpu.parallel import sharded as sharded_mod

    out = sharded_mod.compiled_evolve(mesh, GENS - MID, "explicit", 1)(
        mesh_mod.place_private(board, mesh_mod.board_sharding(mesh))
    )
    assert np.array_equal(np.asarray(out), ref)
    with pytest.raises(rs.ReshardError, match="world"):
        rs.open_source(path, kind="batch")
    with pytest.raises(rs.ReshardError, match="out of range"):
        rs.open_source(path, kind="batch", world=5)


# -- manifests, verification, legacy ------------------------------------------


def _strip_topology_stamp(dirpath):
    """Rewrite a manifest without the elastic-mesh fields (pre-PR 8)."""
    mpath = os.path.join(dirpath, "manifest.npz")
    with np.load(mpath) as data:
        keep = {
            k: data[k]
            for k in data.files
            if k not in ("mesh_kind", "mesh_rows", "mesh_cols",
                         "process_count")
        }
    np.savez_compressed(mpath, **keep)


def test_manifest_topology_stamp_and_legacy_inference(tmp_path, ref):
    snap = _write_src_snapshot(tmp_path, "2d")
    meta = ckpt.load_sharded_meta(snap)
    assert meta.layout == {"kind": "2d", "rows": 4, "cols": 2}
    assert meta.process_count == 1
    assert not meta.legacy
    # Legacy manifest: stamp stripped -> layout inferred, flagged.
    _strip_topology_stamp(snap)
    meta = ckpt.load_sharded_meta(snap)
    assert meta.legacy and meta.layout is None
    src = rs.open_source(snap)
    assert src.legacy
    assert src.layout == rs.MeshLayout("2d", rows=4, cols=2)
    rt = GolRuntime(
        geometry=Geometry(size=SIZE, num_ranks=1),
        engine="dense",
        mesh=mesh_mod.make_mesh_1d(8),
    )
    _, st = rt.run(pattern=6, iterations=GENS - MID, resume=snap)
    assert np.array_equal(np.asarray(st.board), ref)
    assert rt.last_reshard["legacy_manifest"] is True


def test_verify_snapshot_topology_mismatch_verifies_fully(tmp_path):
    """The own-pieces shortcut widens on a job-size mismatch — a corrupt
    piece is caught even by a rank index the writing job never had
    (previously a vacuous pass: the piece-count mystery)."""
    snap = _write_src_snapshot(tmp_path, "1d")
    # Same job size: rank 3 of a 1-process... mismatch -> full verify.
    assert ckpt.verify_snapshot(snap, only_process=3, expect_processes=4) \
        == MID
    # Corrupt one piece payload; the stamped fingerprints must catch it
    # under the widened sweep, not slide through the vacuous path.
    shard = os.path.join(snap, "shards_00000.npz")
    with np.load(shard) as data:
        arrays = {k: data[k].copy() for k in data.files}
    arrays["piece_0"] = arrays["piece_0"].copy()
    arrays["piece_0"].flat[0] ^= 1
    np.savez_compressed(shard, **arrays)
    with pytest.raises(ckpt.CorruptSnapshotError):
        ckpt.verify_snapshot(snap, only_process=3, expect_processes=4)
    # Without expect_processes the old vacuous shortcut is preserved
    # (plain callers keep their contract).
    assert ckpt.verify_snapshot(snap, only_process=3) == MID


def test_open_source_rejects_3d_and_stale(tmp_path):
    p3 = ckpt.checkpoint3d_path(str(tmp_path), 0)
    ckpt.save3d(p3, np.zeros((4, 4, 4), np.uint8), 0, "B4/S4,5")
    with pytest.raises(rs.ReshardError, match="3-D"):
        rs.open_source(p3)
    ps = ckpt.checkpoint_path(str(tmp_path), 0)
    halo = np.zeros(8, np.uint8)
    ckpt.save(ps, np.zeros((8, 8), np.uint8), 0, 1, top0=halo, bottom0=halo)
    with pytest.raises(rs.ReshardError, match="stale_t0"):
        rs.open_source(ps)


# -- v7 telemetry -------------------------------------------------------------


def test_reshard_event_emitted_on_mismatch_only(tmp_path, ref):
    snap = _write_src_snapshot(tmp_path, "2d")

    def run(dst_kind, run_id):
        rt = GolRuntime(
            geometry=Geometry(size=SIZE, num_ranks=1),
            engine="dense",
            mesh=_mesh_for(dst_kind),
            telemetry_dir=str(tmp_path / "t"),
            run_id=run_id,
        )
        rt.run(pattern=6, iterations=GENS - MID, resume=snap)
        recs = [
            json.loads(ln)
            for ln in open(tmp_path / "t" / f"{run_id}.rank0.jsonl")
        ]
        return [r for r in recs if r["event"] == "reshard"]

    events = run("1d", "mismatch")
    assert len(events) == 1
    ev = events[0]
    assert ev["src_mesh"] == {"kind": "2d", "rows": 4, "cols": 2}
    assert ev["dst_mesh"] == {"kind": "1d", "rows": 8, "cols": 1}
    assert ev["bytes_moved"] == SIZE * SIZE // 8
    assert ev["generation"] == MID
    assert not run("2d", "match"), "same-mesh resume must not stamp v7"


# -- in-flight reshard (--reshard-at) -----------------------------------------


def test_reshard_point_raised_at_chunk_boundary(tmp_path):
    from gol_tpu import resilience

    rt = GolRuntime(
        geometry=Geometry(size=SIZE, num_ranks=1),
        engine="dense",
        mesh=mesh_mod.make_mesh_2d((4, 2)),
        checkpoint_every=4,
        checkpoint_dir=str(tmp_path / "ck"),
        sharded_snapshots=True,
        reshard_at=MID,
    )
    with pytest.raises(resilience.ReshardPoint) as ei:
        rt.run(pattern=6, iterations=GENS)
    rp = ei.value
    assert rp.generation == MID and rp.remaining == GENS - MID
    assert rp.snapshot_path == ckpt.sharded_checkpoint_path(
        str(tmp_path / "ck"), MID
    )
    assert ckpt.verify_snapshot(rp.snapshot_path) == MID


def test_reshard_at_requires_checkpoint_dir():
    with pytest.raises(ValueError, match="checkpoint_dir"):
        GolRuntime(
            geometry=Geometry(size=SIZE, num_ranks=1), reshard_at=4
        )


def test_cli_inflight_reshard_bit_identical(tmp_path, ref, capsys):
    from gol_tpu import cli

    out = tmp_path / "w"
    out.mkdir()
    rc = cli.main(
        [
            "6", str(SIZE), str(GENS), "512", "1",
            "--outdir", str(out),
            "--mesh", "2d",
            "--reshard-at", str(MID),
            "--reshard-mesh", "1d",
            "--checkpoint-every", "4",
            "--checkpoint-dir", str(tmp_path / "ck"),
            "--sharded-snapshots",
        ]
    )
    assert rc == 0
    assert "reshard: generation 8, mesh 2d -> 1d" in capsys.readouterr().out
    from gol_tpu.utils import io as gol_io

    _, dumped = gol_io.read_rank_file(str(out / "Rank_0_of_1.txt"))
    assert np.array_equal(dumped, ref)


def test_cli_reshard_flag_validation(capsys):
    from gol_tpu import cli

    assert cli.main(["6", "64", "8", "512", "0", "--reshard-at", "4"]) == 255
    assert "--reshard-mesh" in capsys.readouterr().out
    assert cli.main(["6", "64", "8", "512", "0", "--reshard-mesh", "1d"]) \
        == 255
    assert "--reshard-at" in capsys.readouterr().out
    assert cli.main(
        ["6", "64", "8", "512", "0", "--reshard-at", "4", "--reshard-mesh",
         "1d", "--guard-every", "2"]
    ) == 255
    assert "unguarded" in capsys.readouterr().out
    assert cli.main(
        ["6", "64", "8", "512", "0", "--sharded-snapshots"]
    ) == 255
    assert "--mesh 1d/2d" in capsys.readouterr().out


# -- shrink policy ------------------------------------------------------------


def test_build_mesh_shrinks_to_dividing_device_count():
    with pytest.raises(ValueError, match="not divisible"):
        build_mesh("1d", shape=(4, 4))
    with pytest.warns(UserWarning, match="elastic shrink"):
        mesh = build_mesh("1d", shape=(4, 4), allow_shrink=True)
    assert mesh.shape[mesh_mod.ROWS] == 4
    # Full device count still preferred when it divides.
    mesh = build_mesh("1d", shape=(64, 64), allow_shrink=True)
    assert mesh.shape[mesh_mod.ROWS] == 8


def test_cli_allow_shrink_env_and_flag(tmp_path, monkeypatch, capsys):
    from gol_tpu import cli

    args = ["6", "4", "4", "512", "0", "--mesh", "1d",
            "--outdir", str(tmp_path)]
    assert cli.main(args) == 255  # 4 rows cannot tile 8 devices
    with pytest.warns(UserWarning, match="elastic shrink"):
        assert cli.main(args + ["--allow-shrink"]) == 0
    capsys.readouterr()
    monkeypatch.setenv("GOL_ALLOW_SHRINK", "1")
    with pytest.warns(UserWarning, match="elastic shrink"):
        assert cli.main(args) == 0  # the supervisor's env export


def test_supervisor_exports_allow_shrink(tmp_path):
    import sys

    from gol_tpu.resilience import supervisor

    probe = tmp_path / "probe.py"
    probe.write_text(
        "import os, sys\n"
        "sys.exit(0 if os.environ.get('GOL_ALLOW_SHRINK') == '1' else 3)\n"
    )
    rc = supervisor.supervise(
        [sys.executable, str(probe)], max_restarts=0, backoff_base=0
    )
    assert rc == 0


# -- plain --resume hint ------------------------------------------------------


def test_plain_resume_topology_hint(tmp_path, ref, capsys):
    """A mesh the board cannot tile prints the reshard hint, not just a
    raw divisibility error."""
    from gol_tpu import cli

    snap = _write_src_snapshot(tmp_path, "2d")
    # 7 ranks stacked: 448 rows never tile the 8-device... they do; use a
    # world whose height (4) cannot tile 8 rows instead.
    rc = cli.main(
        ["6", "4", "4", "512", "0", "--mesh", "1d", "--resume", str(snap),
         "--outdir", str(tmp_path)]
    )
    assert rc == 255
    out = capsys.readouterr().out
    assert "not divisible" in out
    assert "hint:" in out and "2d mesh, 4x2 shard grid" in out
    assert "--allow-shrink" in out


def test_topology_hint_is_none_for_garbage():
    assert rs.topology_resume_hint("/nonexistent/x.gol.npz") is None


def test_topology_hint_3d_names_writing_topology(tmp_path):
    """3-D volumes have no reshard path: the hint says so and names the
    writing job from the manifest's process-count stamp."""
    import jax.numpy as jnp

    from gol_tpu.parallel import sharded3d

    vol = (np.arange(16 * 16 * 32) % 3 == 0).reshape(16, 16, 32).astype(
        np.uint8
    )
    mesh = mesh_mod.make_mesh_3d((1, 2, 1), devices=jax.devices()[:2])
    arr = jax.device_put(jnp.asarray(vol), sharded3d.volume_sharding(mesh))
    d = ckpt.sharded_checkpoint3d_path(str(tmp_path), 5)
    ckpt.save_sharded3d(d, arr, 5, "B5/S4,5")
    assert ckpt.load_sharded3d_meta(d).process_count == 1
    hint = rs.topology_resume_hint(d, kind="3d")
    assert "16x16x32 volume" in hint
    assert "written by 1 processes" in hint
    assert "no reshard path" in hint


# -- trace identity -----------------------------------------------------------


def test_reshard_knobs_leave_jaxpr_identical():
    """reshard_at/sharded_snapshots are host-side: the compiled chunk
    program must be byte-for-byte the plain build."""
    geom = Geometry(size=SIZE, num_ranks=1)
    mesh = mesh_mod.make_mesh_1d(8)

    def jaxpr(**kw):
        rt = GolRuntime(geometry=geom, engine="dense", mesh=mesh, **kw)
        fn, dynamic, static = rt._evolve_fn(8)
        spec = jax.ShapeDtypeStruct(
            (SIZE, SIZE), np.uint8, sharding=mesh_mod.board_sharding(mesh)
        )
        return str(fn.lower(spec, *dynamic, *static).as_text())

    plain = jaxpr()
    assert jaxpr(
        reshard_at=4, checkpoint_dir="ck_unused", sharded_snapshots=True
    ) == plain
