"""Docs single-source-of-truth guard (VERDICT r3 #7).

Round 2 and round 3 both re-opened number drift between the narrative
docs and the measured record: README/DESIGN/PARITY quoted superseded
rates after a retune.  The fix is structural — raw measured rates live
ONLY in BASELINE.md (append-only, per-round sections) and in code
docstrings adjacent to the measurement they motivated; the narrative
docs cite "BASELINE.md r<N>" instead of embedding values.  This test
enforces the doc side mechanically so the drift cannot re-open.
"""

from __future__ import annotations

import pathlib
import re

REPO = pathlib.Path(__file__).resolve().parent.parent

# A measured-rate literal: decimal mantissa + two-digit exponent
# (1.93e12, 2.708e11, 8.6-9.8e11...).  Targets like "1e11" (config 4's
# pod target, defined in BASELINE.json) deliberately don't match.
RATE = re.compile(r"\d\.\d+e\d{2}", re.IGNORECASE)

NARRATIVE_DOCS = ("README.md", "docs/DESIGN.md", "docs/PARITY.md")


def test_narrative_docs_embed_no_measured_rates():
    offenders = []
    for rel in NARRATIVE_DOCS:
        text = (REPO / rel).read_text()
        for i, line in enumerate(text.splitlines(), 1):
            for m in RATE.finditer(line):
                offenders.append(f"{rel}:{i}: {m.group(0)}")
    assert not offenders, (
        "measured rates belong in BASELINE.md (docs cite the round "
        "instead):\n" + "\n".join(offenders)
    )


def test_narrative_docs_cite_baseline():
    for rel in NARRATIVE_DOCS:
        text = (REPO / rel).read_text()
        assert "BASELINE.md" in text, (
            f"{rel} should point readers at BASELINE.md"
        )


def test_baseline_has_round_sections():
    text = (REPO / "BASELINE.md").read_text()
    assert re.search(r"^## Measured, round \d+", text, re.MULTILINE), (
        "BASELINE.md must keep its per-round measured sections — they are "
        "the single source the narrative docs cite"
    )
