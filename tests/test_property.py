"""Property-based tests (Hypothesis) — SURVEY §4's prescription.

Random geometries (including non-square, the reference's blind spot — its
index math is square-only, bugs B3/B4) and random boards, checked against
the structurally-independent NumPy oracle and against algebraic properties
of the torus step itself:

- engine == oracle on arbitrary boards/steps;
- composition: ``run(b, m+n) == run(run(b, m), n)``;
- symmetry equivariance: the torus is homogeneous and isotropic, so the
  step commutes with translations (rolls), transposition, and flips;
- packed == dense wherever the width packs.

Each property is a whole family of tests the example-based suite samples
only pointwise.
"""

from __future__ import annotations

import numpy as np
import pytest

pytest.importorskip(
    "hypothesis",
    reason="property-based families need Hypothesis; the example-based "
    "suite still pins each engine pointwise",
)
from hypothesis import given, settings, strategies as st  # noqa: E402

import jax
import jax.numpy as jnp

from gol_tpu.ops import bitlife, stencil

from tests import oracle

jax.config.update("jax_platforms", "cpu")

_SETTINGS = dict(max_examples=25, deadline=None)


def _board(h, w, seed):
    return oracle.random_board(h, w, seed=seed)


dims = st.integers(min_value=4, max_value=48)
seeds = st.integers(min_value=0, max_value=2**31 - 1)
steps = st.integers(min_value=0, max_value=6)


@given(h=dims, w=dims, seed=seeds, n=steps)
@settings(**_SETTINGS)
def test_stencil_matches_oracle_any_geometry(h, w, seed, n):
    board = _board(h, w, seed)
    got = np.asarray(stencil.run(jnp.asarray(board), n))
    np.testing.assert_array_equal(got, oracle.run_torus(board, n))


@given(h=dims, w=dims, seed=seeds, m=steps, n=steps)
@settings(**_SETTINGS)
def test_step_composition(h, w, seed, m, n):
    board = jnp.asarray(_board(h, w, seed))
    a = stencil.run(jnp.array(board, copy=True), m + n)
    b = stencil.run(stencil.run(jnp.array(board, copy=True), m), n)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@given(h=dims, w=dims, seed=seeds, dy=st.integers(-8, 8), dx=st.integers(-8, 8))
@settings(**_SETTINGS)
def test_translation_equivariance(h, w, seed, dy, dx):
    """step(roll(b)) == roll(step(b)) — the torus has no special origin."""
    board = _board(h, w, seed)
    rolled = np.roll(board, (dy, dx), axis=(0, 1))
    a = np.asarray(stencil.step(jnp.asarray(rolled)))
    b = np.roll(np.asarray(stencil.step(jnp.asarray(board))), (dy, dx), (0, 1))
    np.testing.assert_array_equal(a, b)


@given(h=dims, w=dims, seed=seeds)
@settings(**_SETTINGS)
def test_symmetry_equivariance(h, w, seed):
    """The 8-neighbor rule is isotropic: step commutes with transpose/flips."""
    board = _board(h, w, seed)
    stepped = np.asarray(stencil.step(jnp.asarray(board)))
    np.testing.assert_array_equal(
        np.asarray(stencil.step(jnp.asarray(board.T))), stepped.T
    )
    for axis in (0, 1):
        np.testing.assert_array_equal(
            np.asarray(stencil.step(jnp.asarray(np.flip(board, axis)))),
            np.flip(stepped, axis),
        )


@given(h=dims, words=st.integers(1, 3), seed=seeds, n=st.integers(1, 4))
@settings(**_SETTINGS)
def test_packed_matches_dense_property(h, words, seed, n):
    board = _board(h, words * bitlife.BITS, seed)
    got = np.asarray(bitlife.evolve_dense_io(jnp.asarray(board), n))
    ref = np.asarray(stencil.run(jnp.asarray(board), n))
    np.testing.assert_array_equal(got, ref)


@given(h=dims, w=dims)
@settings(**_SETTINGS)
def test_dead_board_stays_dead(h, w):
    board = jnp.zeros((h, w), jnp.uint8)
    assert int(np.asarray(stencil.run(board, 3)).sum()) == 0


@given(h=st.integers(4, 32), w=st.integers(4, 32))
@settings(**_SETTINGS)
def test_full_board_dies_of_overpopulation(h, w):
    board = jnp.ones((h, w), jnp.uint8)
    assert int(np.asarray(stencil.step(board)).sum()) == 0


# -- 3-D families ------------------------------------------------------------

from gol_tpu.ops import bitlife3d, life3d  # noqa: E402

dims3 = st.integers(min_value=4, max_value=12)


@given(d=dims3, h=dims3, words=st.integers(1, 2), seed=seeds,
       n=st.integers(0, 3))
@settings(**_SETTINGS)
def test_packed3d_matches_dense_property(d, h, words, seed, n):
    vol = oracle.random_volume(d, h, words * bitlife.BITS, seed=seed)
    got = np.asarray(bitlife3d.evolve3d_dense_io(jnp.asarray(vol), n))
    ref = life3d.run3d(jnp.asarray(vol), n)
    np.testing.assert_array_equal(got, np.asarray(ref))


@given(d=dims3, seed=seeds)
@settings(**_SETTINGS)
def test_step3d_axis_permutation_equivariance(d, seed):
    """The 26-neighbor totalistic rule is isotropic: step commutes with any
    permutation of the volume axes (cube volumes)."""
    vol = oracle.random_volume(d, d, d, seed=seed)
    stepped = np.asarray(life3d.step3d(jnp.asarray(vol)))
    for perm in ((1, 0, 2), (2, 1, 0), (1, 2, 0)):
        np.testing.assert_array_equal(
            np.asarray(life3d.step3d(jnp.asarray(vol.transpose(perm)))),
            stepped.transpose(perm),
        )


@given(d=dims3, h=dims3, w=dims3, seed=seeds,
       shift=st.integers(-4, 4), axis=st.integers(0, 2))
@settings(**_SETTINGS)
def test_step3d_translation_equivariance(d, h, w, seed, shift, axis):
    vol = oracle.random_volume(d, h, w, seed=seed)
    a = np.asarray(life3d.step3d(jnp.asarray(np.roll(vol, shift, axis))))
    b = np.roll(np.asarray(life3d.step3d(jnp.asarray(vol))), shift, axis)
    np.testing.assert_array_equal(a, b)


# -- fingerprint algebra (the sharded checkpoint format's invariant) ---------


@settings(max_examples=40, deadline=None)
@given(
    h=st.integers(4, 40),
    w=st.integers(4, 40),
    seed=st.integers(0, 2**20),
    rs=st.integers(1, 39),
    cs=st.integers(1, 39),
)
def test_fingerprint_piece_additivity(h, w, seed, rs, cs):
    """Any 2x2 rectangle cover's global-offset piece fingerprints sum
    (mod 2^32) to the whole board's fingerprint — the property that lets
    a sharded checkpoint verify a global stamp without assembling the
    board."""
    from gol_tpu.utils.guard import fingerprint_np

    rs, cs = min(rs, h - 1), min(cs, w - 1)
    board = oracle.random_board(h, w, seed=seed)
    total = np.uint32(0)
    with np.errstate(over="ignore"):
        for r0, r1 in ((0, rs), (rs, h)):
            for c0, c1 in ((0, cs), (cs, w)):
                total = total + np.uint32(
                    fingerprint_np(board[r0:r1, c0:c1], r0, c0)
                )
    assert int(total) == fingerprint_np(board)


@settings(max_examples=20, deadline=None)
@given(
    seed=st.integers(0, 2**20),
    r0=st.integers(0, 31),
    r1=st.integers(1, 32),
    c0=st.integers(0, 63),
    c1=st.integers(1, 64),
)
def test_sharded_region_reads_any_rectangle(tmp_path_factory, seed, r0, r1, c0, c1):
    """read_sharded_region assembles arbitrary rectangles (crossing piece
    boundaries or not) byte-exactly."""
    from gol_tpu.parallel import mesh as mesh_mod
    from gol_tpu.utils import checkpoint as ckpt

    if r0 >= r1 or c0 >= c1:
        return
    tmp = tmp_path_factory.mktemp("shards")
    board = oracle.random_board(32, 64, seed=seed)
    mesh = mesh_mod.make_mesh_2d((2, 2), devices=jax.devices()[:4])
    arr = jax.device_put(jnp.asarray(board), mesh_mod.board_sharding(mesh))
    d = ckpt.sharded_checkpoint_path(str(tmp), seed)
    ckpt.save_sharded(d, arr, seed, num_ranks=1)
    meta = ckpt.load_sharded_meta(d)
    got = ckpt.read_sharded_region(d, meta, (slice(r0, r1), slice(c0, c1)))
    np.testing.assert_array_equal(got, board[r0:r1, c0:c1])


# -- r4: randomized sweep over the sharded Pallas kernel matrix --------------
#
# VERDICT r3 #5: the flagship engine's fold x band x edges x overlap x rule
# compositions were pinned only by hand-picked examples, and the
# fold/edge-repair arithmetic is exactly the kind of code a randomized
# configuration sweep breaks.  Every example compiles a fresh interpret-mode
# program (seconds each), so the family is small — but each draw comes from
# the full matrix and Hypothesis shrinks any failure to a minimal config.


@st.composite
def _flagship_configs(draw):
    kind = draw(st.sampled_from(["1d", "2d"]))
    if kind == "1d":
        rows, cols = draw(st.sampled_from([2, 4])), 1
    else:
        rows, cols = draw(st.sampled_from([(2, 2), (2, 4), (4, 1)]))
    fold = draw(st.sampled_from([2, 4, 8]))
    k = draw(st.sampled_from([8, 8, 16]))  # deep bands rarer (slower)
    overlap = draw(st.booleans())
    if overlap:
        hg = 2 * k + 8  # minimum interior-tile room, the tightest case
    else:
        hg = draw(st.sampled_from([8, 16, 24]))
    chunks = draw(st.sampled_from([1, 2]))
    rem = draw(st.sampled_from([0, 3]))
    use_rule = draw(st.sampled_from([False, False, True]))
    seed = draw(st.integers(0, 2**20))
    return kind, rows, cols, fold, hg, k, overlap, chunks, rem, use_rule, seed


@given(cfg=_flagship_configs())
@settings(max_examples=6, deadline=None)
def test_flagship_kernel_matrix_matches_oracle(cfg):
    """Random (mesh, shard words, fold, k, overlap, rule, remainder)
    configurations of the sharded Pallas engine vs the oracle."""
    from gol_tpu.ops import rules as rules_mod
    from gol_tpu.parallel import mesh as mesh_mod
    from gol_tpu.parallel import packed
    from gol_tpu.parallel.sharded import place_private

    kind, rows, cols, fold, hg, k, overlap, chunks, rem, use_rule, seed = cfg
    nw = {2: 64, 4: 32, 8: 16}[fold]  # shard words -> that lane fold
    h = rows * fold * hg
    w = cols * nw * 32
    mesh = (
        mesh_mod.make_mesh_1d(rows)
        if kind == "1d"
        else mesh_mod.make_mesh_2d(
            (rows, cols), devices=jax.devices()[: rows * cols]
        )
    )
    steps = chunks * k + rem
    rule = rules_mod.HIGHLIFE if use_rule else None
    board = oracle.random_board(h, w, seed=seed)
    fn = packed.compiled_evolve_packed_pallas(
        mesh, steps, halo_depth=k, rule=rule, overlap=overlap
    )
    got = np.asarray(fn(place_private(jnp.asarray(board), mesh)))
    if rule is None:
        ref = oracle.run_torus(board, steps)
    else:
        ref = np.asarray(
            rules_mod.run_rule(jnp.asarray(board), steps, rule)
        )
    np.testing.assert_array_equal(got, ref)


@st.composite
def _flagship3d_configs(draw):
    mesh_shape = draw(
        st.sampled_from(
            [(2, 1, 1), (1, 2, 1), (2, 1, 2), (1, 2, 2), (1, 1, 4), (4, 1, 1)]
        )
    )
    wide = mesh_shape[2] > 1 and draw(st.sampled_from([False, False, True]))
    if wide:
        # The ghosted-rolling regime (VERDICT r4 #6): a wide odd word
        # count per shard leaves tile_w=1 as the wt kernel's only word
        # tiling (word factor 3), so the dispatch provably picks
        # roll_ext_g — ghost DMA + per-plane concat + band ring jointly,
        # the composition the dryrun tier (g) pins at one hand-picked
        # shape.  Budget guard: interpret-mode volumes this wide are
        # ~0.5M cells, so the other extents stay pinned small.
        k, band_mult, lane_extent, words_per_shard = 8, 2, 16, 17
        chunks = 1
        rem = draw(st.sampled_from([0, 2]))
        rule_5766 = False
    else:
        k = draw(st.sampled_from([8, 8, 16]))
        # Shard extents: the banded axis needs >= k layers per shard.
        band_mult = draw(st.sampled_from([2, 3]))
        lane_extent = draw(st.sampled_from([16, 32]))
        words_per_shard = draw(st.sampled_from([1, 2]))
        chunks = draw(st.sampled_from([1, 2]))
        rem = draw(st.sampled_from([0, 2]))
        rule_5766 = draw(st.sampled_from([False, False, True]))
    seed = draw(st.integers(0, 2**20))
    return (
        mesh_shape, k, band_mult, lane_extent, words_per_shard, chunks,
        rem, rule_5766, seed,
    )


@given(cfg=_flagship3d_configs())
@settings(max_examples=5, deadline=None)
def test_flagship3d_kernel_matrix_matches_oracle(cfg):
    """Random (mesh, layout orientation, shard extents, k, rule,
    remainder) configurations of the sharded 3-D Pallas engine vs the
    dense oracle — the r4 counterpart of the 2-D kernel-matrix sweep,
    covering both band orientations (natural and transposed), both ext
    kernels (rolling on x-unsharded meshes, word-tiled where x is
    sharded), and the XLA remainder tail."""
    from gol_tpu.ops import life3d
    from gol_tpu.parallel import mesh as mesh_mod
    from gol_tpu.parallel import sharded3d
    from gol_tpu.parallel.mesh import place_private
    from gol_tpu.parallel.sharded3d import volume_sharding

    (mesh_shape, k, band_mult, lane_extent, words_per_shard, chunks,
     rem, rule_5766, seed) = cfg
    p, r, c = mesh_shape
    band_extent = k * band_mult
    # Natural meshes (rows == 1) band over planes with lanes = H; the
    # transposed ones (planes == 1) band over rows with lanes = D.
    if r == 1:
        d, h = p * band_extent, lane_extent
    else:
        d, h = lane_extent, r * band_extent
    w = c * words_per_shard * 32
    rule = life3d.BAYS_5766 if rule_5766 else life3d.BAYS_4555
    steps = chunks * k + rem
    rng = np.random.default_rng(seed)
    vol = (rng.random((d, h, w)) < 0.3).astype(np.uint8)
    n = p * r * c
    mesh = mesh_mod.make_mesh_3d(mesh_shape, devices=jax.devices()[:n])
    # The sweep must provably reach the ghosted rolling kernel on its
    # wide-shard draws (the engine dispatches via the same plan helper).
    if words_per_shard >= 17:
        kind, _ = sharded3d.kernel_plan3d(
            band_extent, words_per_shard, lane_extent, k, ghosted=c > 1
        )
        assert kind == "roll_g"
    got = np.asarray(
        sharded3d.compiled_evolve3d_pallas(mesh, steps, rule, k)(
            place_private(jnp.asarray(vol), volume_sharding(mesh))
        )
    )
    ref = jnp.asarray(vol)
    for _ in range(steps):
        ref = life3d.step3d(ref, rule)
    np.testing.assert_array_equal(got, np.asarray(ref))


# -- batched multi-world families (gol_tpu/batch) ----------------------------

from gol_tpu.batch import GolBatchRuntime, make_batch_mesh  # noqa: E402

batch_engines_st = st.sampled_from(["dense", "bitpack", "auto"])
batch_mesh_st = st.sampled_from(["none", "1d"])


@given(
    seed=seeds,
    n=st.integers(1, 5),
    engine=batch_engines_st,
    mesh_kind=batch_mesh_st,
    shapes=st.lists(
        st.tuples(
            st.integers(2, 6).map(lambda k: 8 * k),  # heights 16..48
            st.integers(1, 3).map(lambda k: 32 * k),  # packable widths
        ),
        min_size=2,
        max_size=6,
    ),
)
@settings(max_examples=20, deadline=None)
def test_batched_mixed_buckets_bit_equal_per_world(
    seed, n, engine, mesh_kind, shapes
):
    """A batched run of B random worlds with mixed bucket sizes is
    bit-equal per world to sequential single-world runs — across tiers
    and world-axis sharding (the tentpole's core contract)."""
    worlds = [
        oracle.random_board(h, w, seed=seed + i) for i, (h, w) in
        enumerate(shapes)
    ]
    refs = [np.asarray(stencil.run(jnp.asarray(w.copy()), n)) for w in worlds]
    brt = GolBatchRuntime(
        worlds=[w.copy() for w in worlds],
        engine=engine,
        mesh=make_batch_mesh() if mesh_kind == "1d" else None,
        bucket_quantum=32,
    )
    _, out = brt.run(n)
    for i, ref in enumerate(refs):
        np.testing.assert_array_equal(out[i], ref)


@given(
    seed=seeds,
    h=st.integers(3, 40),
    w=st.integers(3, 40),
    n=st.integers(1, 4),
)
@settings(max_examples=20, deadline=None)
def test_masked_dense_step_matches_oracle_any_geometry(seed, h, w, n):
    """The padded+masked dense step at an arbitrary (h, w) inside a
    larger bucket equals the oracle on the bare board."""
    from gol_tpu.batch.engines import step_dense_masked

    board = oracle.random_board(h, w, seed=seed)
    H, W = h + 7, w + 9  # deliberately unaligned padding
    stack = np.zeros((H, W), np.uint8)
    stack[:h, :w] = board
    out = jnp.asarray(stack)
    step = jax.jit(step_dense_masked)
    for _ in range(n):
        out = step(out, h, w)
    got = np.asarray(out)
    np.testing.assert_array_equal(got[:h, :w], oracle.run_torus(board, n))
    pad = got.copy()
    pad[:h, :w] = 0
    assert not pad.any()


# -- activity-gated tier families (gol_tpu/sparse, docs/SPARSE.md) -----------

activity_dims = st.sampled_from([16, 24, 32, 48])
activity_caps = st.integers(1, 16)


@given(h=activity_dims, w=activity_dims, seed=seeds, n=steps,
       cap=activity_caps)
@settings(**_SETTINGS)
def test_activity_gated_matches_oracle_random_soups(h, w, seed, n, cap):
    """The gated worklist — any capacity, overflow fallback included —
    equals the oracle on random soups of any density."""
    from gol_tpu.sparse import engine as sparse_engine
    from gol_tpu.sparse import mask as sparse_mask

    board = _board(h, w, seed)
    th, tw = sparse_mask.grid_shape(h, w, 8)
    out, _, _ = sparse_engine.evolve_gated_dense(
        jnp.asarray(board), sparse_mask.full_mask(th, tw), n, 8, cap
    )
    np.testing.assert_array_equal(np.asarray(out), oracle.run_torus(board, n))


@given(h=activity_dims, w=activity_dims, seed=seeds)
@settings(**_SETTINGS)
def test_activity_mask_soundness_invariant(h, w, seed):
    """No live-region tile is ever outside the dilated mask: the tiles
    that change in generation t+1 are a subset of dilate(tiles that
    changed in generation t) — the invariant that makes skipping exact
    rather than approximate."""
    from gol_tpu.sparse import mask as sparse_mask

    b0 = jnp.asarray(_board(h, w, seed))
    b1 = stencil.step(b0)
    b2 = stencil.step(b1)
    changed01 = np.asarray(sparse_mask.changed_tiles_dense(b0, b1, 8))
    changed12 = np.asarray(sparse_mask.changed_tiles_dense(b1, b2, 8))
    allowed = np.asarray(sparse_mask.dilate(jnp.asarray(changed01)))
    assert not (changed12 & ~allowed).any(), (
        "a tile changed outside the dilated active set — the light-cone "
        "invariant is broken"
    )


@given(
    dy=st.integers(0, 63),
    dx=st.integers(0, 63),
    n=st.integers(1, 48),
)
@settings(max_examples=15, deadline=None)
def test_activity_sharded_glider_any_offset(dy, dx, n):
    """A glider at ANY torus offset — wrapping edges, straddling shard
    seams — evolves bit-identically under the sharded activity engine
    (the compiled program is cached across examples; only data varies)."""
    from gol_tpu.parallel import mesh as mesh_mod
    from gol_tpu.parallel import sparse as par_sparse
    from gol_tpu.models import patterns

    mesh = mesh_mod.make_mesh_1d(4)
    board0 = patterns.init_sparse_world("glider", 64, 64, (dy, dx))
    ref = oracle.run_torus(board0, n)
    fn = par_sparse.compiled_evolve_activity(mesh, n, 8, 24)
    board = mesh_mod.shard_board(jnp.asarray(board0), mesh)
    mask = jax.device_put(
        np.ones((8, 8), bool), par_sparse.mask_sharding(mesh)
    )
    out, _, _ = fn(board, mask)
    np.testing.assert_array_equal(np.asarray(out), ref)


# -- elastic-mesh reshard families (docs/RESILIENCE.md) -----------------------

from gol_tpu.resilience import reshard as rs  # noqa: E402
from gol_tpu.utils import checkpoint as ckpt_prop  # noqa: E402


@given(
    rows=st.integers(1, 5),
    cols=st.integers(1, 5),
    drows=st.integers(1, 5),
    dcols=st.integers(1, 5),
    rh=st.integers(1, 9),
    cw=st.integers(1, 41),
    seed=seeds,
)
@settings(**_SETTINGS)
def test_reshard_repartition_matches_slicing_any_geometry(
    rows, cols, drows, dcols, rh, cw, seed
):
    """Pure-geometry pin: repartitioning a random board from any src
    grid to any dst grid through the packed piece store reproduces
    plain numpy slicing — including column seams that straddle uint32
    words (``cw`` not a multiple of 32 puts every interior seam
    sub-word, driving the shift repack)."""
    h = rows * drows * rh
    w = cols * dcols * cw
    board = _board(h, w, seed)
    src = rs.MeshLayout("2d", rows, cols) if cols > 1 else (
        rs.MeshLayout("1d", rows) if rows > 1 else rs.MeshLayout("none")
    )
    dst = rs.MeshLayout("2d", drows, dcols) if dcols > 1 else (
        rs.MeshLayout("1d", drows) if drows > 1 else rs.MeshLayout("none")
    )
    src_boxes = src.boxes((h, w))
    plan = rs.plan_reshard((h, w), src_boxes, src, dst)
    store = rs.PackedStore()
    for b in src_boxes:
        store.put(b, board[b[0] : b[1], b[2] : b[3]])
    for dbox, _ in plan.moves:
        np.testing.assert_array_equal(
            store.region(dbox), board[dbox[0] : dbox[1], dbox[2] : dbox[3]]
        )
    assert plan.cells_moved == h * w


_RESHARD_LAYOUTS = {
    "none": None,
    "1d2": ("1d", (2,)),
    "1d4": ("1d", (4,)),
    "1d8": ("1d", (8,)),
    "2d2x2": ("2d", (2, 2)),
    "2d4x2": ("2d", (4, 2)),
}


def _reshard_mesh(kind):
    from gol_tpu.parallel import mesh as mesh_mod

    if kind == "none":
        return None
    axes, shape = _RESHARD_LAYOUTS[kind]
    if axes == "1d":
        return mesh_mod.make_mesh_1d(shape[0])
    return mesh_mod.make_mesh_2d(
        shape, devices=jax.devices()[: shape[0] * shape[1]]
    )


@given(
    seed=seeds,
    src_kind=st.sampled_from(
        ["none", "1d2", "1d4", "2d2x2", "2d4x2", "batch"]
    ),
    dst_kind=st.sampled_from(
        ["none", "1d2", "1d4", "1d8", "2d2x2", "2d4x2"]
    ),
    engine=st.sampled_from(["dense", "bitpack"]),
    size=st.sampled_from([48, 64]),
    m=st.integers(1, 5),
    n=st.integers(1, 5),
)
@settings(max_examples=10, deadline=None)
def test_reshard_resume_equals_straight_run(
    seed, src_kind, dst_kind, engine, size, m, n
):
    """The acceptance pin as a family: evolve m generations, snapshot in
    a random topology's format (single-file / 1-D / 2-D sharded / batch
    world), resume-reshard onto a random destination mesh, evolve n
    more — the result must equal the straight m+n oracle run.  size=48
    puts the 2-col shard seams sub-word (24-column pieces)."""
    import tempfile

    from gol_tpu.models.state import Geometry
    from gol_tpu.parallel import mesh as mesh_mod
    from gol_tpu.runtime import GolRuntime

    if size == 48:
        engine = "dense"  # bitpack tiers need word-multiple (sub)widths
    board0 = _board(size, size, seed)
    ref = oracle.run_torus(board0, m + n)
    mid = oracle.run_torus(board0, m)
    tmp = tempfile.mkdtemp()
    src_mesh = _reshard_mesh("none" if src_kind == "batch" else src_kind)
    if src_kind == "batch":
        path = ckpt_prop.batch_checkpoint_path(tmp, m)
        ckpt_prop.save_batch(path, [np.zeros_like(mid), mid], m)
    elif src_mesh is None:
        path = ckpt_prop.checkpoint_path(tmp, m)
        ckpt_prop.save(path, mid, m, 1)
    else:
        path = ckpt_prop.sharded_checkpoint_path(tmp, m)
        arr = jax.device_put(mid, mesh_mod.board_sharding(src_mesh))
        ckpt_prop.save_sharded(
            path, arr, m, 1,
            mesh_layout=rs.MeshLayout.from_mesh(src_mesh).to_dict(),
        )
    dst_mesh = _reshard_mesh(dst_kind)
    if src_kind == "batch":
        board, _, _ = rs.load_resharded(path, dst_mesh, kind="batch", world=1)
        if dst_mesh is None:
            from gol_tpu.parallel import engine as engine_mod

            out = engine_mod.evolve_fresh(jnp.asarray(board), n)
        else:
            from gol_tpu.parallel import sharded as sharded_mod

            out = sharded_mod.compiled_evolve(dst_mesh, n, "explicit", 1)(
                mesh_mod.place_private(
                    board, mesh_mod.board_sharding(dst_mesh)
                )
            )
        np.testing.assert_array_equal(np.asarray(out), ref)
        return
    rt = GolRuntime(
        geometry=Geometry(size=size, num_ranks=1),
        engine=engine,
        mesh=dst_mesh,
    )
    _, st_out = rt.run(pattern=0, iterations=n, resume=path)
    np.testing.assert_array_equal(np.asarray(st_out.board), ref)


# -- pipelined depth-k halo families (PR 9, docs/DESIGN.md) ------------------

_halo_meshes = {}


def _halo_mesh(kind):
    """none = a degenerate 1-device ring (self-ppermute seam), 1d = 4-ring,
    2d = 2×2 block grid.  Cached so the engine builders' lru_cache hits."""
    from gol_tpu.parallel import mesh as mesh_mod

    if kind not in _halo_meshes:
        if kind == "2d":
            _halo_meshes[kind] = mesh_mod.make_mesh_2d(
                (2, 2), devices=jax.devices()[:4]
            )
        else:
            n = 1 if kind == "none" else 4
            _halo_meshes[kind] = mesh_mod.make_mesh_1d(
                n, devices=jax.devices()[:n]
            )
    return _halo_meshes[kind]


@st.composite
def _halo_cfgs(draw):
    tier = draw(st.sampled_from(["dense", "bitpack"]))
    mesh_kind = draw(st.sampled_from(["none", "1d", "2d"]))
    h = draw(st.sampled_from([8, 16, 24, 48]))
    words = draw(st.sampled_from([2, 4]))
    k = draw(st.integers(1, 6))
    n = draw(st.integers(1, 10))
    mode = draw(st.sampled_from(["overlap", "pipeline"]))
    seed = draw(seeds)
    return tier, mesh_kind, h, words, k, n, mode, seed


@given(cfg=_halo_cfgs())
@settings(max_examples=20, deadline=None)
def test_pipelined_depth_k_matches_explicit_and_oracle(cfg):
    """Pipelined/overlap depth-k == explicit depth-1 == the sequential
    oracle over random (size, k, mesh none/1d/2d, tier) — remainder
    chunks, steps < k, and tiny shards (no interior to split) included;
    a k deeper than the shard extent must raise, not corrupt (the seam
    case where the ghost shell would cross two ring hops)."""
    from gol_tpu.parallel import packed as packed_mod
    from gol_tpu.parallel import sharded as sharded_mod
    from gol_tpu.parallel import mesh as mesh_mod

    tier, mesh_kind, h, words, k, n, mode, seed = cfg
    w = 32 * words
    mesh = _halo_mesh(mesh_kind)
    rows = mesh.shape["rows"]
    cols = mesh.shape.get("cols", 1)
    two_d = "cols" in mesh.axis_names
    board = _board(h, w, seed)
    place = lambda: mesh_mod.place_private(
        jnp.asarray(board), mesh_mod.board_sharding(mesh)
    )

    if tier == "dense":
        build = lambda m, kk: sharded_mod.compiled_evolve(mesh, n, m, kk)
        limits = [h // rows] + ([w // cols] if two_d else [])
    else:
        build = lambda m, kk: packed_mod.compiled_evolve_packed(
            mesh, n, kk, mode=m
        )
        limits = [h // rows] + ([words // cols] if two_d else [])

    if k > min(limits):
        with pytest.raises(ValueError, match="exceeds shard extent"):
            build(mode, k)(place())
        return

    ref = np.asarray(build("explicit", 1)(place()))
    np.testing.assert_array_equal(ref, oracle.run_torus(board, n))
    got = np.asarray(build(mode, k)(place()))
    np.testing.assert_array_equal(got, ref)


# -- out-of-core streaming (docs/STREAMING.md) -------------------------------
#
# The ooc tier re-expresses the board as host-resident row bands pushed
# through a fixed device footprint: alternating sweep direction,
# one-visit-delayed drains, a wrap buffer for the first seam, dead-band
# skipping, and a remainder-absorbing last band.  Each of those is a
# seam a pointwise test samples once; the family drives random
# (geometry, band height, visit depth, chunk schedule, sweep parity,
# skipping) through the full scheduler against the independent oracle.


def _ooc_run(board, depth, band_rows, schedule, parity, skip):
    from gol_tpu.ooc import OocScheduler, plan_bands

    h, w = board.shape
    plan = plan_bands(h, w, depth, band_rows=band_rows)
    sched = OocScheduler(plan, skip_dead=skip)
    sched.load_dense(board)
    sched._sweep_parity = parity  # random starting sweep direction
    gen = 0
    for take in schedule:
        sched.run_chunk(take, gen)
        gen += take
    return sched.dense()


@given(
    h=st.integers(min_value=8, max_value=72),
    words=st.integers(min_value=1, max_value=2),
    seed=seeds,
    depth=st.integers(min_value=1, max_value=4),
    band=st.integers(min_value=1, max_value=24),
    schedule=st.lists(
        st.integers(min_value=1, max_value=9), min_size=1, max_size=3
    ),
    parity=st.integers(min_value=0, max_value=1),
    skip=st.booleans(),
)
@settings(**_SETTINGS)
def test_ooc_streamed_matches_oracle_any_banding(
    h, words, seed, depth, band, schedule, parity, skip
):
    """Streamed == oracle over random banding, depth, chunking, sweep
    parity and dead-band skipping — remainder bands included (any h not
    a multiple of the band height exercises the absorbing last band)."""
    w = 32 * words
    band = max(depth, min(band, h))  # planner floor: band height >= k
    board = _board(h, w, seed)
    got = _ooc_run(board, depth, band, schedule, parity, skip)
    np.testing.assert_array_equal(got, oracle.run_torus(board, sum(schedule)))


@given(
    seam=st.integers(min_value=1, max_value=4),
    dx=st.integers(min_value=0, max_value=24),
    seed=seeds,
    depth=st.integers(min_value=1, max_value=3),
    parity=st.integers(min_value=0, max_value=1),
    n=st.integers(min_value=1, max_value=8),
)
@settings(**_SETTINGS)
def test_ooc_seam_straddling_pattern_with_skipping(seam, dx, seed, depth, parity, n):
    """A lone glider straddling a random band seam on an otherwise-dead
    board: most bands are skippable, and the pattern's light cone
    crosses the seam every sweep — exactly the read the wrap buffer and
    deferred drain protect.  Skip-on must equal skip-off equal oracle."""
    h, w, band = 60, 32, 10
    board = np.zeros((h, w), dtype=np.uint8)
    glider = np.array([[0, 1, 0], [0, 0, 1], [1, 1, 1]], dtype=np.uint8)
    r = seam * band - 1 - (seed % 2)  # straddle: rows seam*B-2..seam*B+1
    board[r:r + 3, dx:dx + 3] = glider
    ref = oracle.run_torus(board, n)
    for skip in (True, False):
        got = _ooc_run(board, depth, band, (n,), parity, skip)
        np.testing.assert_array_equal(got, ref)
