"""Bit-packed sharded engine on the 8-device CPU mesh vs. the oracle.

Parity of the composed perf tiers (bit-packing × shard_map+ppermute) with
the trivially-correct NumPy torus oracle — boundary bits must survive the
packed halo exchange in both decompositions, including the corner-word
two-hop of the 2-D path.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gol_tpu.parallel import mesh as mesh_mod
from gol_tpu.parallel import packed

from tests import oracle


@pytest.mark.parametrize("num_devices", [1, 2, 4, 8])
@pytest.mark.parametrize("steps", [1, 2, 9])
def test_1d_ring_matches_oracle(num_devices, steps):
    board = oracle.random_board(16, 64, seed=num_devices * 100 + steps)
    mesh = mesh_mod.make_mesh_1d(num_devices)
    got = np.asarray(packed.evolve_sharded_packed(jnp.asarray(board), steps, mesh))
    np.testing.assert_array_equal(got, oracle.run_torus(board, steps))


@pytest.mark.parametrize("shape", [(2, 4), (4, 2), (8, 1), (1, 8), (2, 2)])
def test_2d_blocks_match_oracle(shape):
    steps = 5
    rows, cols = shape
    board = oracle.random_board(16, 32 * cols, seed=sum(shape))
    mesh = mesh_mod.make_mesh_2d(shape, devices=jax.devices()[: rows * cols])
    got = np.asarray(packed.evolve_sharded_packed(jnp.asarray(board), steps, mesh))
    np.testing.assert_array_equal(got, oracle.run_torus(board, steps))


def test_2d_corner_word_crossing():
    """A glider driven through a 2×2 shard corner junction: the diagonal
    neighbor bit rides the corner *word* through both ppermute phases."""
    board = np.zeros((64, 64), np.uint8)
    g = np.array([[0, 1, 0], [0, 0, 1], [1, 1, 1]], np.uint8)
    board[30:33, 30:33] = g  # centered at the (32, 32) shard junction
    mesh = mesh_mod.make_mesh_2d((2, 2), devices=jax.devices()[:4])
    expected = oracle.run_torus(board, 16)
    got = np.asarray(packed.evolve_sharded_packed(jnp.asarray(board), 16, mesh))
    np.testing.assert_array_equal(got, expected)
    assert got.sum() == 5  # glider survived the crossing


def test_word_boundary_wrap_on_ring():
    """Cells hugging the torus column wrap (bit 0 of word 0 / top bit of the
    last word) while rows are sharded: blinker spanning the x-wrap, the
    reference's pattern-4 probe (gol-with-cuda.cu:161-165)."""
    from gol_tpu.models import patterns

    board = patterns.init_global(4, 32, num_ranks=8)  # 256×32 world
    mesh = mesh_mod.make_mesh_1d(8)
    got2 = np.asarray(packed.evolve_sharded_packed(jnp.asarray(board), 2, mesh))
    np.testing.assert_array_equal(got2, board)  # period 2


def test_single_row_shards():
    board = oracle.random_board(8, 32, seed=3)
    mesh = mesh_mod.make_mesh_1d(8)
    got = np.asarray(packed.evolve_sharded_packed(jnp.asarray(board), 4, mesh))
    np.testing.assert_array_equal(got, oracle.run_torus(board, 4))


def test_matches_dense_sharded_long_run():
    from gol_tpu.parallel import sharded

    board = oracle.random_board(32, 64, seed=11)
    mesh = mesh_mod.make_mesh_2d((2, 2), devices=jax.devices()[:4])
    a = np.asarray(packed.evolve_sharded_packed(jnp.asarray(board), 20, mesh))
    b = np.asarray(sharded.evolve_sharded(jnp.asarray(board), 20, mesh))
    np.testing.assert_array_equal(a, b)


def test_packed_geometry_validation():
    mesh1 = mesh_mod.make_mesh_1d(8)
    # Height not divisible by mesh rows: generic geometry error.
    with pytest.raises(ValueError, match="divisible"):
        packed.evolve_sharded_packed(jnp.zeros((12, 32), jnp.uint8), 1, mesh1)
    # Shard width doesn't pack into whole 32-bit words.
    with pytest.raises(ValueError, match="shard width"):
        packed.evolve_sharded_packed(jnp.zeros((8, 16), jnp.uint8), 1, mesh1)
    mesh2 = mesh_mod.make_mesh_2d((2, 4))
    with pytest.raises(ValueError, match="shard width"):
        packed.evolve_sharded_packed(jnp.zeros((8, 64), jnp.uint8), 1, mesh2)


def test_caller_board_not_consumed():
    """Donation must never eat the caller's array (copy-on-equivalent-sharding
    contract shared with the dense sharded engine)."""
    board = jnp.asarray(oracle.random_board(8, 32, seed=5))
    mesh = mesh_mod.make_mesh_1d(2)
    packed.evolve_sharded_packed(board, 1, mesh)
    out = packed.evolve_sharded_packed(board, 1, mesh)  # reuse must still work
    np.testing.assert_array_equal(
        np.asarray(out), oracle.run_torus(np.asarray(board), 1)
    )


def test_packed_overlap_matches_oracle():
    from gol_tpu.parallel import packed

    board = oracle.random_board(32, 64, seed=21)
    mesh = mesh_mod.make_mesh_1d()
    from gol_tpu.parallel.sharded import place_private

    got = np.asarray(
        packed.compiled_evolve_packed_overlap(mesh, 6)(
            place_private(jnp.asarray(board), mesh)
        )
    )
    np.testing.assert_array_equal(got, oracle.run_torus(board, 6))


def test_packed_overlap_rejects_2d_mesh():
    from gol_tpu.parallel import packed

    with pytest.raises(ValueError, match="1-D"):
        packed.compiled_evolve_packed_overlap(mesh_mod.make_mesh_2d(), 2)


def test_runtime_packed_overlap_end_to_end():
    from gol_tpu.models import patterns
    from gol_tpu.models.state import Geometry
    from gol_tpu.runtime import GolRuntime

    geom = Geometry(size=32, num_ranks=4)
    rt = GolRuntime(
        geometry=geom,
        engine="bitpack",
        mesh=mesh_mod.make_mesh_1d(4),
        shard_mode="overlap",
    )
    _, state = rt.run(pattern=4, iterations=6)
    board0 = patterns.init_global(4, 32, 4)
    np.testing.assert_array_equal(
        np.asarray(state.board), oracle.run_torus(board0, 6)
    )
    # auto resolves to the packed overlap engine on a packable 1-D mesh.
    rt2 = GolRuntime(
        geometry=geom, mesh=mesh_mod.make_mesh_1d(4), shard_mode="overlap"
    )
    assert rt2._resolved == "bitpack"
    # ...and 2-D overlap now keeps the bit-packed ring too: the depth-k
    # interior/boundary split (gol_tpu.parallel.halo) lifted the old
    # 1-D-only restriction, so the dense cliff is gone.
    rt3 = GolRuntime(
        geometry=Geometry(size=256, num_ranks=1),
        mesh=mesh_mod.make_mesh_2d(),
        shard_mode="overlap",
    )
    assert rt3._resolved == "bitpack"
    board0 = patterns.init_global(5, 256, 1)
    _, state3 = rt3.run(pattern=5, iterations=5)
    np.testing.assert_array_equal(
        np.asarray(state3.board), oracle.run_torus(board0, 5)
    )


# -- fused Pallas kernel per shard (interpret mode on CPU) -------------------


@pytest.mark.parametrize("steps", [8, 16, 19])  # incl. a jnp remainder tail
def test_sharded_pallas_matches_oracle(steps):
    from gol_tpu.parallel import packed
    from gol_tpu.parallel.sharded import place_private

    board = oracle.random_board(64, 64, seed=33)
    mesh = mesh_mod.make_mesh_1d(4)  # shard height 16, >= the 8-deep band
    got = np.asarray(
        packed.compiled_evolve_packed_pallas(mesh, steps)(
            place_private(jnp.asarray(board), mesh)
        )
    )
    np.testing.assert_array_equal(got, oracle.run_torus(board, steps))


def test_sharded_pallas_rejects_bad_geometry():
    from gol_tpu.parallel import packed

    with pytest.raises(ValueError, match="multiple of 8"):
        packed.compiled_evolve_packed_pallas(
            mesh_mod.make_mesh_1d(4), 8, halo_depth=4
        )
    # 2-D meshes cap the band depth at the 1-word column halo's light cone.
    with pytest.raises(ValueError, match="column band"):
        packed.compiled_evolve_packed_pallas(
            mesh_mod.make_mesh_2d((2, 2), devices=jax.devices()[:4]),
            40,
            halo_depth=40,
        )


def test_sharded_pallas_custom_rule():
    from gol_tpu.ops import rules
    from gol_tpu.parallel import packed
    from gol_tpu.parallel.sharded import place_private

    board = oracle.random_board(32, 64, seed=34)
    mesh = mesh_mod.make_mesh_1d(2)  # shard height 16
    got = np.asarray(
        packed.compiled_evolve_packed_pallas(
            mesh, 8, rule=rules.HIGHLIFE
        )(place_private(jnp.asarray(board), mesh))
    )
    ref = np.asarray(rules.run_rule(jnp.asarray(board), 8, rules.HIGHLIFE))
    np.testing.assert_array_equal(got, ref)


def test_runtime_sharded_pallas_end_to_end():
    from gol_tpu.models import patterns
    from gol_tpu.models.state import Geometry
    from gol_tpu.runtime import GolRuntime

    geom = Geometry(size=32, num_ranks=4)  # 128x32, shard height 32
    rt = GolRuntime(
        geometry=geom,
        engine="pallas_bitpack",
        mesh=mesh_mod.make_mesh_1d(4),
    )
    _, state = rt.run(pattern=4, iterations=10)
    board0 = patterns.init_global(4, 32, 4)
    np.testing.assert_array_equal(
        np.asarray(state.board), oracle.run_torus(board0, 10)
    )


# -- 2-D-mesh flagship: fused kernel under the block decomposition -----------


@pytest.mark.parametrize(
    "shape,width",
    [((2, 2), 128), ((2, 4), 256), ((4, 2), 128), ((1, 4), 256), ((4, 1), 32)],
)
@pytest.mark.parametrize("steps", [8, 19])  # incl. a jnp remainder tail
def test_sharded_pallas_2d_matches_oracle(shape, width, steps):
    from gol_tpu.parallel import packed
    from gol_tpu.parallel.sharded import place_private

    rows, cols = shape
    board = oracle.random_board(32 * rows, width, seed=rows * 10 + cols + steps)
    mesh = mesh_mod.make_mesh_2d(shape, devices=jax.devices()[: rows * cols])
    got = np.asarray(
        packed.compiled_evolve_packed_pallas(mesh, steps)(
            place_private(jnp.asarray(board), mesh)
        )
    )
    np.testing.assert_array_equal(got, oracle.run_torus(board, steps))


@pytest.mark.parametrize("halo_depth", [16, 32])
@pytest.mark.slow  # minutes-scale interpret-mode sweep; run with -m slow
def test_sharded_pallas_2d_deep_band(halo_depth):
    """Deeper temporal bands stay inside the 1-word column light cone."""
    from gol_tpu.parallel import packed
    from gol_tpu.parallel.sharded import place_private

    board = oracle.random_board(64, 128, seed=77 + halo_depth)
    mesh = mesh_mod.make_mesh_2d((2, 2), devices=jax.devices()[:4])
    got = np.asarray(
        packed.compiled_evolve_packed_pallas(mesh, halo_depth, halo_depth)(
            place_private(jnp.asarray(board), mesh)
        )
    )
    np.testing.assert_array_equal(got, oracle.run_torus(board, halo_depth))


def test_sharded_pallas_2d_glider_corner_crossing():
    """A glider through the (32,64) shard junction: the diagonal bit must
    ride the corner word through both exchange phases, then survive the
    kernel's edge-word strip repair."""
    from gol_tpu.parallel import packed
    from gol_tpu.parallel.sharded import place_private

    board = np.zeros((64, 128), np.uint8)
    g = np.array([[0, 1, 0], [0, 0, 1], [1, 1, 1]], np.uint8)
    board[30:33, 62:65] = g  # centered at the (32, 64) shard junction
    mesh = mesh_mod.make_mesh_2d((2, 2), devices=jax.devices()[:4])
    got = np.asarray(
        packed.compiled_evolve_packed_pallas(mesh, 16)(
            place_private(jnp.asarray(board), mesh)
        )
    )
    np.testing.assert_array_equal(got, oracle.run_torus(board, 16))
    assert got.sum() == 5  # glider survived the crossing


def test_sharded_pallas_2d_custom_rule():
    from gol_tpu.ops import rules
    from gol_tpu.parallel import packed
    from gol_tpu.parallel.sharded import place_private

    board = oracle.random_board(32, 128, seed=88)
    mesh = mesh_mod.make_mesh_2d((2, 2), devices=jax.devices()[:4])
    got = np.asarray(
        packed.compiled_evolve_packed_pallas(mesh, 11, rule=rules.HIGHLIFE)(
            place_private(jnp.asarray(board), mesh)
        )
    )
    ref = np.asarray(rules.run_rule(jnp.asarray(board), 11, rules.HIGHLIFE))
    np.testing.assert_array_equal(got, ref)


def test_sharded_pallas_2d_narrow_shard_rejected():
    from gol_tpu.parallel import packed
    from gol_tpu.parallel.sharded import place_private

    board = jnp.zeros((64, 128), jnp.uint8)  # shard width 32 -> 1 word
    mesh = mesh_mod.make_mesh_2d((2, 4), devices=jax.devices()[:8])
    with pytest.raises(ValueError, match="2 packed words"):
        packed.compiled_evolve_packed_pallas(mesh, 8)(
            place_private(board, mesh)
        )


# -- flagship overlap mode: interior kernel under the band exchange ----------


@pytest.mark.parametrize("steps", [8, 16, 19])  # incl. a jnp remainder tail
def test_sharded_pallas_overlap_matches_oracle(steps):
    from gol_tpu.parallel.sharded import place_private

    board = oracle.random_board(128, 64, seed=41 + steps)
    mesh = mesh_mod.make_mesh_1d(4)  # shard height 32 >= 2*8 + 8
    got = np.asarray(
        packed.compiled_evolve_packed_pallas(mesh, steps, overlap=True)(
            place_private(jnp.asarray(board), mesh)
        )
    )
    np.testing.assert_array_equal(got, oracle.run_torus(board, steps))


@pytest.mark.parametrize(
    "shape,width", [((2, 2), 128), ((2, 4), 256), ((1, 4), 256), ((4, 1), 32)]
)
def test_sharded_pallas_overlap_2d_matches_oracle(shape, width):
    from gol_tpu.parallel.sharded import place_private

    rows, cols = shape
    board = oracle.random_board(32 * rows, width, seed=rows + cols)
    mesh = mesh_mod.make_mesh_2d(shape, devices=jax.devices()[: rows * cols])
    got = np.asarray(
        packed.compiled_evolve_packed_pallas(mesh, 16, overlap=True)(
            place_private(jnp.asarray(board), mesh)
        )
    )
    np.testing.assert_array_equal(got, oracle.run_torus(board, 16))


@pytest.mark.slow  # minutes-scale interpret-mode sweep; run with -m slow
def test_sharded_pallas_overlap_deep_band():
    """k=16 band: boundary kernels span [-16, 32) with a 48-row shard."""
    from gol_tpu.parallel.sharded import place_private

    board = oracle.random_board(96, 128, seed=55)
    mesh = mesh_mod.make_mesh_2d((2, 2), devices=jax.devices()[:4])
    got = np.asarray(
        packed.compiled_evolve_packed_pallas(
            mesh, 16, halo_depth=16, overlap=True
        )(place_private(jnp.asarray(board), mesh))
    )
    np.testing.assert_array_equal(got, oracle.run_torus(board, 16))


def test_sharded_pallas_overlap_glider_corner_crossing():
    from gol_tpu.parallel.sharded import place_private

    board = np.zeros((64, 128), np.uint8)
    g = np.array([[0, 1, 0], [0, 0, 1], [1, 1, 1]], np.uint8)
    board[30:33, 62:65] = g  # centered at the (32, 64) shard junction
    mesh = mesh_mod.make_mesh_2d((2, 2), devices=jax.devices()[:4])
    got = np.asarray(
        packed.compiled_evolve_packed_pallas(mesh, 16, overlap=True)(
            place_private(jnp.asarray(board), mesh)
        )
    )
    np.testing.assert_array_equal(got, oracle.run_torus(board, 16))
    assert got.sum() == 5


def test_sharded_pallas_overlap_custom_rule():
    from gol_tpu.ops import rules
    from gol_tpu.parallel.sharded import place_private

    board = oracle.random_board(64, 128, seed=66)
    mesh = mesh_mod.make_mesh_2d((2, 2), devices=jax.devices()[:4])
    got = np.asarray(
        packed.compiled_evolve_packed_pallas(
            mesh, 11, rule=rules.HIGHLIFE, overlap=True
        )(place_private(jnp.asarray(board), mesh))
    )
    ref = np.asarray(rules.run_rule(jnp.asarray(board), 11, rules.HIGHLIFE))
    np.testing.assert_array_equal(got, ref)


def test_sharded_pallas_overlap_short_shard_rejected():
    from gol_tpu.parallel.sharded import place_private

    board = jnp.zeros((64, 64), jnp.uint8)  # shard height 16 < 2*8 + 8
    mesh = mesh_mod.make_mesh_1d(4)
    with pytest.raises(ValueError, match="overlap mode needs shard height"):
        packed.compiled_evolve_packed_pallas(mesh, 8, overlap=True)(
            place_private(board, mesh)
        )


def test_overlap_interior_kernel_independent_of_exchange():
    """The overlap property itself, pinned at the jaxpr level: the interior
    (bulk) Pallas launch must not be a transitive consumer of any ppermute,
    or XLA's latency-hiding scheduler has nothing to overlap.  The serial
    form's single launch, by contrast, must depend on the exchange."""
    import jax as jax_mod
    from jax.extend import core as jex_core
    from gol_tpu.parallel.mesh import board_sharding

    def depends_on_ppermute(overlap):
        mesh = mesh_mod.make_mesh_1d(4)
        fn = packed.compiled_evolve_packed_pallas(mesh, 8, overlap=overlap)
        spec = jax_mod.ShapeDtypeStruct(
            (128, 128), jnp.uint8, sharding=board_sharding(mesh)
        )
        top = jax_mod.make_jaxpr(lambda b: fn(b))(spec).jaxpr

        def sub_jaxprs(v):
            if hasattr(v, "eqns"):  # Jaxpr
                yield v
            elif hasattr(v, "jaxpr"):  # ClosedJaxpr
                yield v.jaxpr
            elif isinstance(v, (list, tuple)):
                for x in v:
                    yield from sub_jaxprs(x)

        def collect(jpr, acc):
            acc.append(jpr)
            for eqn in jpr.eqns:
                for v in eqn.params.values():
                    for j in sub_jaxprs(v):
                        collect(j, acc)
            return acc

        # The chunk lives in one jaxpr (the fori_loop body): both the
        # ppermutes and the kernel launches of one chunk appear there in
        # topological order, so intra-jaxpr taint propagation decides the
        # dependency.
        results = []
        for jpr in collect(top, []):
            names = [e.primitive.name for e in jpr.eqns]
            if "ppermute" not in names or "pallas_call" not in names:
                continue
            tainted = set()
            for eqn in jpr.eqns:
                hit = any(
                    not isinstance(v, jex_core.Literal) and v in tainted
                    for v in eqn.invars
                )
                if eqn.primitive.name == "pallas_call":
                    results.append(hit)
                if eqn.primitive.name == "ppermute" or hit:
                    tainted.update(eqn.outvars)
        return results

    serial = depends_on_ppermute(False)
    assert serial and all(serial)  # the one serial launch waits on the band
    overlap = depends_on_ppermute(True)
    # Three launches per chunk: interior (clean) + two boundary (gated).
    assert len(overlap) == 3
    assert sorted(overlap) == [False, True, True]


@pytest.mark.slow  # minutes-scale interpret-mode sweep; run with -m slow
def test_runtime_sharded_pallas_overlap_end_to_end():
    from gol_tpu.models import patterns
    from gol_tpu.models.state import Geometry
    from gol_tpu.runtime import GolRuntime

    geom = Geometry(size=32, num_ranks=4)  # 128x32, shard height 32
    rt = GolRuntime(
        geometry=geom,
        engine="pallas_bitpack",
        mesh=mesh_mod.make_mesh_1d(4),
        shard_mode="overlap",
    )
    _, state = rt.run(pattern=4, iterations=10)
    board0 = patterns.init_global(4, 32, 4)
    np.testing.assert_array_equal(
        np.asarray(state.board), oracle.run_torus(board0, 10)
    )
    # Overlap + deep band on a 2-D mesh rides the same validation.
    rt2 = GolRuntime(
        geometry=Geometry(size=128, num_ranks=1),
        engine="pallas_bitpack",
        mesh=mesh_mod.make_mesh_2d((2, 2), devices=jax.devices()[:4]),
        shard_mode="overlap",
        halo_depth=16,
    )
    _, state2 = rt2.run(pattern=4, iterations=16)
    board0 = patterns.init_global(4, 128, 1)
    np.testing.assert_array_equal(
        np.asarray(state2.board), oracle.run_torus(board0, 16)
    )
    # Too-short shards for the interior/boundary split are rejected up front.
    with pytest.raises(ValueError, match="overlap mode needs shard height"):
        GolRuntime(
            geometry=Geometry(size=16, num_ranks=4),  # shard height 16
            engine="pallas_bitpack",
            mesh=mesh_mod.make_mesh_1d(4),
            shard_mode="overlap",
        )


def test_runtime_sharded_pallas_2d_end_to_end():
    from gol_tpu.models import patterns
    from gol_tpu.models.state import Geometry
    from gol_tpu.runtime import GolRuntime

    geom = Geometry(size=128, num_ranks=1)  # 128x128, shards 64x64
    rt = GolRuntime(
        geometry=geom,
        engine="pallas_bitpack",
        mesh=mesh_mod.make_mesh_2d((2, 2), devices=jax.devices()[:4]),
        halo_depth=8,
    )
    _, state = rt.run(pattern=4, iterations=10)
    board0 = patterns.init_global(4, 128, 1)
    np.testing.assert_array_equal(
        np.asarray(state.board), oracle.run_torus(board0, 10)
    )
    # Band depths beyond the column-word light cone are rejected up front.
    with pytest.raises(ValueError, match="column band"):
        GolRuntime(
            geometry=geom,
            engine="pallas_bitpack",
            mesh=mesh_mod.make_mesh_2d((2, 2), devices=jax.devices()[:4]),
            halo_depth=40,
        )


def test_small_tile_deep_band_takes_ext_fallback():
    """tile < halo_depth must stay correct: the banded kernel's single-
    descriptor halo segments can't span multiple neighbor tiles (the bug
    the r2 review caught on real TPU), so the engine falls back to the
    pre-extended kernel — pinned against the oracle here."""
    from gol_tpu.parallel.sharded import place_private

    board = oracle.random_board(128, 64, seed=91)
    mesh = mesh_mod.make_mesh_1d(2)  # shard height 64
    got = np.asarray(
        packed.compiled_evolve_packed_pallas(
            mesh, 16, halo_depth=16, tile_hint=8
        )(place_private(jnp.asarray(board), mesh))
    )
    np.testing.assert_array_equal(got, oracle.run_torus(board, 16))


def test_banded_kernel_rejects_small_tile():
    from gol_tpu.ops import pallas_bitlife

    blk = jnp.zeros((32, 4), jnp.int32)
    bands = jnp.zeros((32, 4), jnp.int32)  # k = 16
    with pytest.raises(ValueError, match="tile .8. >= band depth"):
        pallas_bitlife.multi_step_pallas_packed_bands(blk, bands, 8, 16)


def test_runtime_custom_rule_overlap_flagship():
    """Custom rules ride the flagship overlap form through the runtime
    (the kernel's generic tail works under the interior/boundary split)."""
    from gol_tpu.models.state import Geometry
    from gol_tpu.ops import rules
    from gol_tpu.runtime import GolRuntime

    geom = Geometry(size=32, num_ranks=4)  # 128x32, shard height 32
    rt = GolRuntime(
        geometry=geom,
        engine="pallas_bitpack",
        mesh=mesh_mod.make_mesh_1d(4),
        shard_mode="overlap",
        rule="B36/S23",
    )
    _, state = rt.run(pattern=4, iterations=9)
    from gol_tpu.models import patterns

    board0 = patterns.init_global(4, 32, 4)
    ref = np.asarray(
        rules.run_rule(jnp.asarray(board0), 9, rules.HIGHLIFE)
    )
    np.testing.assert_array_equal(np.asarray(state.board), ref)
    # Other engines still reject the combination.
    with pytest.raises(ValueError, match="Conway-specific"):
        GolRuntime(
            geometry=geom,
            engine="bitpack",
            mesh=mesh_mod.make_mesh_1d(4),
            shard_mode="overlap",
            rule="B36/S23",
        )

# -- lane-folded narrow shards: the pod-scale shard-width fix ----------------
#
# BASELINE config 3 (16384²) on a 16×16 mesh gives 1024-cell = 32-word
# shards — under the kernel's 128-lane floor.  The engine folds f row
# groups side by side in lanes ([h, nw] -> [h/f, f*nw]); the kernel's
# group-local rolls keep the fold exact, so only column-sharded meshes run
# their usual edge repair (folded to one column pair per group).  These run
# the folded path on CPU (interpret mode) — the fold decision is
# shape-driven, identical on TPU.


def _folded_evolve(board, steps, mesh, **kw):
    from gol_tpu.parallel.sharded import place_private

    return np.asarray(
        packed.compiled_evolve_packed_pallas(mesh, steps, **kw)(
            place_private(jnp.asarray(board), mesh)
        )
    )


@pytest.mark.parametrize("steps", [8, 19])  # incl. a jnp remainder tail
def test_sharded_pallas_folded_2d_matches_oracle(steps):
    """32-word shards on a 2-D mesh: fold=4, hg=8, banded kernel."""
    board = oracle.random_board(64, 4096, seed=41 + steps)
    mesh = mesh_mod.make_mesh_2d((2, 4), devices=jax.devices()[:8])
    got = _folded_evolve(board, steps, mesh)
    np.testing.assert_array_equal(got, oracle.run_torus(board, steps))


@pytest.mark.parametrize("steps", [8, 17])
def test_sharded_pallas_folded_1d_matches_oracle(steps):
    """Narrow board on a 1-D mesh: no repair path at all — the kernel's
    group-local rolls give every group its own torus column wrap."""
    board = oracle.random_board(128, 1024, seed=43 + steps)
    mesh = mesh_mod.make_mesh_1d(4)  # shard 32x1024: nw=32, fold=4
    got = _folded_evolve(board, steps, mesh)
    np.testing.assert_array_equal(got, oracle.run_torus(board, steps))


@pytest.mark.parametrize("halo_depth", [16, 32])
@pytest.mark.slow  # minutes-scale interpret-mode sweep; run with -m slow
def test_sharded_pallas_folded_deep_band_ext_fallback(halo_depth):
    """hg=8 < k: the folded ext fallback, with band slices spanning
    multiple fold groups (the k > hg case of folded_bands)."""
    board = oracle.random_board(64, 4096, seed=51 + halo_depth)
    mesh = mesh_mod.make_mesh_2d((2, 4), devices=jax.devices()[:8])
    got = _folded_evolve(board, halo_depth, mesh, halo_depth=halo_depth)
    np.testing.assert_array_equal(got, oracle.run_torus(board, halo_depth))


def test_sharded_pallas_folded_group_seam_glider():
    """A glider driven across a fold-group seam (shard row hg) and the
    torus column wrap: the folded band construction must hand each group
    its true vertical neighbors and the edge repair the true wrap."""
    board = np.zeros((128, 1024), np.uint8)
    g = np.array([[0, 1, 0], [0, 0, 1], [1, 1, 1]], np.uint8)
    board[6:9, 0:3] = g  # near the column wrap, heading down-right
    board[37:40, 500:503] = g  # will cross shard 1's group seams
    mesh = mesh_mod.make_mesh_1d(4)  # shard 32x1024, hg=8: seams every 8
    steps = 40
    got = _folded_evolve(board, steps, mesh)
    np.testing.assert_array_equal(got, oracle.run_torus(board, steps))
    assert got.sum() == 10  # both gliders survived


def test_sharded_pallas_folded_custom_rule():
    from gol_tpu.ops import rules

    board = oracle.random_board(64, 4096, seed=61)
    mesh = mesh_mod.make_mesh_2d((2, 4), devices=jax.devices()[:8])
    got = _folded_evolve(board, 11, mesh, rule=rules.HIGHLIFE)
    ref = np.asarray(rules.run_rule(jnp.asarray(board), 11, rules.HIGHLIFE))
    np.testing.assert_array_equal(got, ref)


def test_sharded_pallas_folded_matches_unfolded_bitpack():
    """Cross-engine: folded flagship == XLA packed ring, long run."""
    board = oracle.random_board(64, 4096, seed=71)
    mesh = mesh_mod.make_mesh_2d((2, 4), devices=jax.devices()[:8])
    a = _folded_evolve(board, 24, mesh)
    b = np.asarray(
        packed.evolve_sharded_packed(jnp.asarray(board), 24, mesh)
    )
    np.testing.assert_array_equal(a, b)


def test_auto_resolves_pallas_for_narrow_shards_on_tpu(monkeypatch):
    """The resolution gate accepts 32-word shards via the fold (the
    16384²/16x16 pod geometry; same arithmetic on this 2x4 stand-in)."""
    from gol_tpu.models.state import Geometry
    from gol_tpu.runtime import GolRuntime

    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    mesh = mesh_mod.make_mesh_2d((2, 4), devices=jax.devices()[:8])
    rt = GolRuntime(
        geometry=Geometry(size=4096, num_ranks=1), mesh=mesh
    )  # shard 2048x1024: nw=32, fold=4
    assert rt._resolved == "pallas_bitpack"
    rt = GolRuntime(
        geometry=Geometry(size=4096, num_ranks=1),
        mesh=mesh_mod.make_mesh_1d(8),
        shard_mode="overlap",
    )  # shard 512x4096: nw=128 fills lanes -> overlap flagship fine
    assert rt._resolved == "pallas_bitpack"
    # Overlap composes with the fold (r4): folded height 128 >= 24.
    rt = GolRuntime(
        geometry=Geometry(size=2048, num_ranks=1),
        mesh=mesh_mod.make_mesh_1d(8),
        shard_mode="overlap",
    )  # shard 256x2048: nw=64 -> fold=2, hg=128 -> folded overlap
    assert rt._resolved == "pallas_bitpack"
    # ...and the pod geometry itself (16x16 mesh, 32-word shards) gets
    # the fused kernel WITH latency hiding — the r3 verdict's headline
    # hole.  2x4 stand-in with the same shard arithmetic:
    rt = GolRuntime(
        geometry=Geometry(size=4096, num_ranks=1),
        mesh=mesh_mod.make_mesh_2d((2, 4), devices=jax.devices()[:8]),
        shard_mode="overlap",
    )  # shard 2048x1024: nw=32, fold=4, hg=512 >= 24
    assert rt._resolved == "pallas_bitpack"
    # Folded overlap without interior-tile room falls back to bitpack.
    rt = GolRuntime(
        geometry=Geometry(size=512, num_ranks=1),
        mesh=mesh_mod.make_mesh_1d(8),
        shard_mode="overlap",
    )  # shard 64x512: nw=16, fold=8, hg=8 < 24
    assert rt._resolved == "bitpack"
    # A band depth beyond the 32-bit edge-repair light cone can't fold.
    rt = GolRuntime(
        geometry=Geometry(size=2048, num_ranks=1),
        mesh=mesh_mod.make_mesh_2d((8, 1), devices=jax.devices()[:8]),
        halo_depth=40,
    )  # shard 256x2048: nw=64 -> fold=2, but depth 40 > 32
    assert rt._resolved == "bitpack"


def test_sharded_pallas_folded_infeasible_raises_on_tpu(monkeypatch):
    """On TPU an infeasible fold is a clear error, not silent wrongness.
    (The backend check sits inside the shard_map body, so drive the real
    local() via a tiny evolve with the backend name patched.)"""
    board = jnp.zeros((20, 128), jnp.uint8)  # h=20 not divisible by fold*8
    mesh = mesh_mod.make_mesh_1d(1)
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    with pytest.raises(Exception, match="lane-folding"):
        packed.compiled_evolve_packed_pallas(mesh, 8)(
            jnp.asarray(board)
        ).block_until_ready()


# -- folded overlap: the fused kernel AND latency hiding at narrow widths ----
#
# r3 verdict's top ask: BASELINE config 3 on a 16x16 pod mesh (32-word
# shards) with --shard-mode overlap used to silently resolve dense.  The
# folded layout makes every interior group seam's band a lane-shifted slice
# of the block itself, so the interior kernel stays ppermute-independent
# exactly as in the unfolded overlap form; only the two k-row boundary
# kernels wait for the ring.


@pytest.mark.parametrize("steps", [8, 19])  # incl. a jnp remainder tail
def test_sharded_pallas_folded_overlap_1d_matches_oracle(steps):
    """Narrow 1-D shards in overlap mode: fold=4, hg=32 >= 2*8+8."""
    board = oracle.random_board(512, 1024, seed=81 + steps)
    mesh = mesh_mod.make_mesh_1d(4)  # shard 128x1024: nw=32, fold=4
    got = _folded_evolve(board, steps, mesh, overlap=True)
    np.testing.assert_array_equal(got, oracle.run_torus(board, steps))


@pytest.mark.parametrize("steps", [8, 19])
def test_sharded_pallas_folded_overlap_2d_matches_oracle(steps):
    """The pod decomposition with latency hiding: folded strip repair
    spliced by per-group lane concat (shard 128x1024: nw=32, fold=4)."""
    board = oracle.random_board(256, 4096, seed=83 + steps)
    mesh = mesh_mod.make_mesh_2d((2, 4), devices=jax.devices()[:8])
    got = _folded_evolve(board, steps, mesh, overlap=True)
    np.testing.assert_array_equal(got, oracle.run_torus(board, steps))


@pytest.mark.slow  # minutes-scale interpret-mode sweep; run with -m slow
def test_sharded_pallas_folded_overlap_deep_band():
    """k=16 band folded: boundary windows span 3k=48 folded rows."""
    board = oracle.random_board(512, 4096, seed=87)
    mesh = mesh_mod.make_mesh_2d((2, 4), devices=jax.devices()[:8])
    got = _folded_evolve(board, 16, mesh, halo_depth=16, overlap=True)
    np.testing.assert_array_equal(got, oracle.run_torus(board, 16))


def test_sharded_pallas_folded_overlap_group_seam_glider():
    """Gliders across fold-group seams and the column wrap under the
    overlap split's three-piece reassembly."""
    board = np.zeros((512, 1024), np.uint8)
    g = np.array([[0, 1, 0], [0, 0, 1], [1, 1, 1]], np.uint8)
    board[30:33, 0:3] = g  # near the column wrap
    board[158:161, 500:503] = g  # will cross shard 1's group seams
    mesh = mesh_mod.make_mesh_1d(4)  # shard 128x1024, hg=32
    steps = 40
    got = _folded_evolve(board, steps, mesh, overlap=True)
    np.testing.assert_array_equal(got, oracle.run_torus(board, steps))
    assert got.sum() == 10


def test_sharded_pallas_folded_overlap_custom_rule():
    from gol_tpu.ops import rules

    board = oracle.random_board(256, 4096, seed=89)
    mesh = mesh_mod.make_mesh_2d((2, 4), devices=jax.devices()[:8])
    got = _folded_evolve(board, 11, mesh, rule=rules.HIGHLIFE, overlap=True)
    ref = np.asarray(rules.run_rule(jnp.asarray(board), 11, rules.HIGHLIFE))
    np.testing.assert_array_equal(got, ref)


def test_folded_overlap_interior_kernel_independent_of_exchange():
    """The overlap property at the jaxpr level, folded form: per chunk,
    the interior launch must not be a transitive consumer of any
    ppermute; the two boundary launches must be (same taint analysis as
    test_overlap_interior_kernel_independent_of_exchange)."""
    import jax as jax_mod
    from jax.extend import core as jex_core
    from gol_tpu.parallel.mesh import board_sharding

    mesh = mesh_mod.make_mesh_1d(4)  # shard 128x1024: nw=32, fold=4
    fn = packed.compiled_evolve_packed_pallas(mesh, 8, overlap=True)
    spec = jax_mod.ShapeDtypeStruct(
        (512, 1024), jnp.uint8, sharding=board_sharding(mesh)
    )
    top = jax_mod.make_jaxpr(lambda b: fn(b))(spec).jaxpr

    def sub_jaxprs(v):
        if hasattr(v, "eqns"):
            yield v
        elif hasattr(v, "jaxpr"):
            yield v.jaxpr
        elif isinstance(v, (list, tuple)):
            for x in v:
                yield from sub_jaxprs(x)

    def collect(jpr, acc):
        acc.append(jpr)
        for eqn in jpr.eqns:
            for v in eqn.params.values():
                for j in sub_jaxprs(v):
                    collect(j, acc)
        return acc

    results = []
    for jpr in collect(top, []):
        names = [e.primitive.name for e in jpr.eqns]
        if "ppermute" not in names or "pallas_call" not in names:
            continue
        tainted = set()
        for eqn in jpr.eqns:
            hit = any(
                not isinstance(v, jex_core.Literal) and v in tainted
                for v in eqn.invars
            )
            if eqn.primitive.name == "pallas_call":
                results.append(hit)
            if eqn.primitive.name == "ppermute" or hit:
                tainted.update(eqn.outvars)
    assert len(results) == 3
    assert sorted(results) == [False, True, True]


def test_runtime_folded_overlap_end_to_end():
    """auto + overlap at a narrow-shard geometry runs the folded flagship
    through the runtime (the r3 silent-dense-fallback fix, end to end)."""
    from gol_tpu.models import patterns as patterns_mod
    from gol_tpu.models.state import Geometry
    from gol_tpu.runtime import GolRuntime

    geom = Geometry(size=1024, num_ranks=1)
    rt = GolRuntime(
        geometry=geom,
        engine="pallas_bitpack",
        mesh=mesh_mod.make_mesh_1d(4),  # shard 256x1024: nw=32, fold=4
        shard_mode="overlap",
    )
    _, state = rt.run(pattern=4, iterations=10)
    board0 = patterns_mod.init_global(4, 1024, 1)
    np.testing.assert_array_equal(
        np.asarray(state.board), oracle.run_torus(board0, 10)
    )


def test_auto_2d_overlap_no_dense_cliff(monkeypatch):
    """PR 9 ends the r3/r4 dense-fallback story: when 2-D overlap misses
    the fused-Pallas gate, auto degrades to the BIT-PACKED ring (the
    depth-k split covers 2-D packed overlap now) — no dense cliff, no
    warning, on any backend."""
    import warnings as warnings_mod

    from gol_tpu.models.state import Geometry
    from gol_tpu.runtime import GolRuntime

    for backend in ("tpu", "cpu"):
        monkeypatch.setattr(jax, "default_backend", lambda b=backend: b)
        with warnings_mod.catch_warnings():
            warnings_mod.simplefilter("error")
            rt = GolRuntime(
                geometry=Geometry(size=128, num_ranks=1),  # 1-word shards
                mesh=mesh_mod.make_mesh_2d((2, 4), devices=jax.devices()[:8]),
                shard_mode="overlap",
            )
        assert rt._resolved == "bitpack"


def test_fold_feasible_predicate():
    """The one predicate behind the three fold-gating sites."""
    from gol_tpu.ops.pallas_bitlife import fold_feasible

    # Alignment clause: shard height must be a multiple of fold*8.
    assert fold_feasible(128, 4, False, 8)
    assert not fold_feasible(100, 4, False, 8)
    # Overlap clause: folded height must keep an aligned interior tile
    # clear of both bands (hg >= 2k + 8).
    assert fold_feasible(4 * 24, 4, True, 8)  # hg = 24 == 2*8+8
    assert not fold_feasible(4 * 16, 4, True, 8)  # hg = 16 < 24
    assert fold_feasible(4 * 16, 4, False, 8)  # explicit mode: fine
    # fold == 1 degenerates to plain 8-row alignment (+ overlap room).
    assert fold_feasible(64, 1, True, 8)
    assert not fold_feasible(20, 1, True, 8)
