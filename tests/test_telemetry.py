"""Structured-telemetry tests (gol_tpu.telemetry).

What they pin:

- the JSONL schema round-trips and the writer refuses invalid records;
- per-chunk records match the chunk schedule and their wall times sum to
  the ``RunReport`` total (the acceptance contract: the event stream is a
  superset of the printed report, never a different story);
- ``summarize``/``diff`` render the fixture run's tables (roofline
  column included) and exit 0; schema-invalid input exits 2;
- rank-file merge flags audit-fingerprint divergence across ranks;
- a real two-process run (the test_multihost.py harness) writes one rank
  file per process, gather-free, and summarize merges them;
- **trace identity**: telemetry on/off produces byte-identical jaxprs —
  emission is host-side only and can never change the compiled program.
"""

from __future__ import annotations

import io
import json

import numpy as np
import pytest

import jax

from gol_tpu import telemetry
from gol_tpu.models.state import Geometry
from gol_tpu.runtime import GolRuntime
from gol_tpu.telemetry import summarize as summ_mod

jax.config.update("jax_platforms", "cpu")


# -- schema round-trip -------------------------------------------------------


def _emit_all(ev: telemetry.EventLog) -> None:
    from gol_tpu.utils.guard import Audit
    from gol_tpu.utils.timing import RunReport

    ev.run_header({"driver": "2d", "engine": "auto"})
    ev.compile_event(8, 0.1, 0.2)
    ev.chunk_event(0, 8, 8, 0.5, 4096, 0.25)
    ev.guard_event(
        Audit(generation=8, ok=True, max_cell=1, population=3,
              fingerprint=0x1234)
    )
    ev.checkpoint_event(8, 0.01, 4096, overlapped=True)
    ev.bench_row("halobench", {"exchange_s": 1e-5})
    ev.summary(
        RunReport(duration_s=0.5, cell_updates=4096, phases={"total": 0.5})
    )


def test_schema_roundtrip(tmp_path):
    with telemetry.EventLog(str(tmp_path), run_id="rt", process_index=0) as ev:
        _emit_all(ev)
        path = ev.path
    lines = [json.loads(ln) for ln in open(path) if ln.strip()]
    assert [r["event"] for r in lines] == [
        "run_header", "compile", "chunk", "guard_audit", "checkpoint",
        "bench_row", "summary",
    ]
    for rec in lines:
        telemetry.validate_record(rec)  # must not raise
    # Fields survive the trip.
    assert lines[2]["take"] == 8 and lines[2]["roofline_util"] == 0.25
    assert lines[3]["fingerprint"] == 0x1234
    assert lines[6]["phases"] == {"total": 0.5}


@pytest.mark.parametrize(
    "rec",
    [
        {"event": "nonsense", "t": 1.0},
        {"event": "chunk", "t": 1.0, "index": 0},  # missing fields
        {"event": "run_header"},  # no timestamp
        {"event": "run_header", "t": 1.0, "schema": 99, "run_id": "x",
         "process_index": 0, "process_count": 1, "config": {}},
    ],
)
def test_validate_rejects_bad_records(rec):
    with pytest.raises(telemetry.SchemaError):
        telemetry.validate_record(rec)


def test_emitter_never_writes_invalid(tmp_path):
    ev = telemetry.EventLog(str(tmp_path), run_id="bad", process_index=0)
    try:
        with pytest.raises(telemetry.SchemaError):
            ev.emit("chunk", index=0)  # missing required fields
    finally:
        ev.close()
    assert open(ev.path).read() == ""


# -- runtime emission --------------------------------------------------------


def _run(tmp_path, name, iterations=8, checkpoint_every=3, **kw):
    rt = GolRuntime(
        geometry=Geometry(size=64, num_ranks=1),
        checkpoint_every=checkpoint_every,
        checkpoint_dir=str(tmp_path / f"{name}-ck"),
        telemetry_dir=str(tmp_path / name),
        run_id=name,
        **kw,
    )
    report, state = rt.run(pattern=4, iterations=iterations)
    recs = [
        json.loads(ln)
        for ln in open(tmp_path / name / f"{name}.rank0.jsonl")
    ]
    return rt, report, recs


def test_runtime_chunk_records_match_schedule(tmp_path):
    rt, report, recs = _run(tmp_path, "sched")
    by = {}
    for r in recs:
        by.setdefault(r["event"], []).append(r)

    # Schedule [3, 3, 2]: one chunk record each, generations cumulative.
    chunks = by["chunk"]
    assert [c["take"] for c in chunks] == rt.chunk_schedule(8, 3) == [3, 3, 2]
    assert [c["generation"] for c in chunks] == [3, 6, 8]
    assert [c["index"] for c in chunks] == [0, 1, 2]
    # One compile record per distinct chunk size, with both durations.
    assert sorted(c["chunk"] for c in by["compile"]) == [2, 3]
    assert all(c["lower_s"] > 0 and c["compile_s"] > 0 for c in by["compile"])
    # One checkpoint record per snapshot, single-process => overlapped.
    assert [c["generation"] for c in by["checkpoint"]] == [3, 6, 8]
    assert all(c["overlapped"] and c["bytes"] == 64 * 64
               for c in by["checkpoint"])
    # Per-chunk walls sum to the RunReport total (same fenced region).
    acc = sum(c["wall_s"] for c in chunks)
    assert acc == pytest.approx(report.phases["total"], rel=0.05, abs=1e-3)
    # The summary record mirrors RunReport exactly.
    (summary,) = by["summary"]
    assert summary["duration_s"] == report.duration_s
    assert summary["cell_updates"] == report.cell_updates == 64 * 64 * 8
    assert summary["phases"] == report.phases
    # Roofline column is populated (bitpack resolves, model exists).
    assert all(c["roofline_util"] > 0 for c in chunks)


def test_guarded_run_emits_audits(tmp_path):
    from gol_tpu.utils import guard as guard_mod

    rt = GolRuntime(
        geometry=Geometry(size=64, num_ranks=1),
        telemetry_dir=str(tmp_path / "g"),
        run_id="g",
    )
    report, state, greport = guard_mod.run_guarded(
        rt, pattern=4, iterations=8,
        config=guard_mod.GuardConfig(check_every=4),
    )
    recs = [json.loads(ln) for ln in open(tmp_path / "g" / "g.rank0.jsonl")]
    audits = [r for r in recs if r["event"] == "guard_audit"]
    assert len(audits) == greport.checks == 2
    assert [a["generation"] for a in audits] == [4, 8]
    assert all(a["ok"] and a["max_cell"] <= 1 for a in audits)
    # Audit scalars in the stream match the in-memory report.
    assert [a["fingerprint"] for a in audits] == [
        a.fingerprint for a in greport.audits
    ]
    chunks = [r for r in recs if r["event"] == "chunk"]
    assert [c["take"] for c in chunks] == [4, 4]


# -- trace identity ----------------------------------------------------------


def test_telemetry_never_changes_the_traced_program(tmp_path):
    """Telemetry-on and telemetry-off runtimes trace byte-identical
    jaxprs for every engine the CPU backend dispatches — emission is
    host-side, after the force_ready fences, by construction."""
    from gol_tpu.analysis import walker

    for engine in ("dense", "bitpack"):
        kw = dict(geometry=Geometry(size=64, num_ranks=1), engine=engine)
        rt_off = GolRuntime(**kw)
        rt_on = GolRuntime(
            **kw, telemetry_dir=str(tmp_path / "ti"), run_id="ti"
        )
        spec = jax.ShapeDtypeStruct((64, 64), np.uint8)
        jaxprs = []
        for rt in (rt_off, rt_on):
            fn, dynamic, static = rt._evolve_fn(4)
            jaxprs.append(str(walker.trace_jaxpr(fn, spec, *dynamic, *static)))
        assert jaxprs[0] == jaxprs[1], f"engine {engine} trace diverged"


def test_telemetry_run_bit_identical_board(tmp_path):
    _, _, recs = _run(tmp_path, "bit")
    rt_off = GolRuntime(geometry=Geometry(size=64, num_ranks=1))
    report, state = rt_off.run(pattern=4, iterations=8)
    rt_on = GolRuntime(
        geometry=Geometry(size=64, num_ranks=1),
        telemetry_dir=str(tmp_path / "bit2"),
        run_id="bit2",
    )
    _, state_on = rt_on.run(pattern=4, iterations=8)
    np.testing.assert_array_equal(
        np.asarray(state.board), np.asarray(state_on.board)
    )


# -- summarize / diff --------------------------------------------------------


def test_summarize_fixture_run(tmp_path):
    _run(tmp_path, "fix")
    out = io.StringIO()
    assert summ_mod.summarize(str(tmp_path / "fix"), out) == 0
    text = out.getvalue()
    assert "run fix" in text
    assert "roofline" in text  # the utilization column header
    assert "chunk     gens" in text
    assert text.count("\n  ") >= 5
    # 3 chunk rows with cumulative generations rendered.
    for idx, take, gen in [(0, 3, 3), (1, 3, 6), (2, 2, 8)]:
        assert f"{idx:>5} {take:>8} {gen:>9}" in text.replace("  ", "  ")
    assert "phase total" in text
    assert "checkpoints: 3" in text


def test_summarize_rejects_schema_violation(tmp_path, capsys):
    d = tmp_path / "bad"
    d.mkdir()
    (d / "x.rank0.jsonl").write_text('{"event": "chunk", "t": 1.0}\n')
    assert summ_mod.main(["summarize", str(d)]) == 2
    assert "missing fields" in capsys.readouterr().err


def test_summarize_missing_dir_exit_code(capsys):
    assert summ_mod.main(["summarize", "/nonexistent-telemetry"]) == 2


def test_diff_two_runs(tmp_path):
    _run(tmp_path, "a")
    _run(tmp_path, "b", iterations=6)
    out = io.StringIO()
    assert summ_mod.diff(str(tmp_path / "a"), str(tmp_path / "b"), out) == 0
    text = out.getvalue()
    assert "A:" in text and "B:" in text
    assert "phase" in text and "total" in text
    assert "updates/s" in text
    assert "chunk_gens" in text  # per-chunk-size comparison table
    assert "delta" in text


def test_cli_telemetry_flag(tmp_path, capsys):
    from gol_tpu import cli

    d = tmp_path / "t"
    rc = cli.main(
        ["0", "64", "8", "512", "0", "--telemetry", str(d),
         "--run-id", "clirun"]
    )
    assert rc == 0
    assert (d / "clirun.rank0.jsonl").exists()
    capsys.readouterr()
    assert summ_mod.main(["summarize", str(d)]) == 0
    assert "clirun" in capsys.readouterr().out


def test_cli3d_telemetry_flag(tmp_path, capsys):
    from gol_tpu import cli3d

    d = tmp_path / "t3"
    rc = cli3d.main(
        ["2", "32", "4", "16", "0", "--engine", "bitpack",
         "--guard-every", "2", "--telemetry", str(d), "--run-id", "v3"]
    )
    assert rc == 0
    recs = [json.loads(ln) for ln in open(d / "v3.rank0.jsonl")]
    events = [r["event"] for r in recs]
    assert events[0] == "run_header" and events[-1] == "summary"
    assert events.count("chunk") == 2 and events.count("guard_audit") == 2
    assert recs[0]["config"]["driver"] == "3d"
    capsys.readouterr()
    assert summ_mod.main(["summarize", str(d)]) == 0


# -- anomaly detection -------------------------------------------------------


def _write_rank(tmp_path, run_id, rank, records):
    path = telemetry.rank_file(str(tmp_path), run_id, rank)
    with open(path, "w") as f:
        for rec in records:
            telemetry.validate_record(rec)
            f.write(json.dumps(rec) + "\n")


def _header(run_id, rank):
    return {
        "event": "run_header", "t": 1.0, "schema": 1, "run_id": run_id,
        "process_index": rank, "process_count": 2, "config": {},
    }


def _audit(gen, fp):
    return {
        "event": "guard_audit", "t": 2.0, "generation": gen, "ok": True,
        "max_cell": 1, "population": 7, "fingerprint": fp,
    }


def test_summarize_flags_fingerprint_divergence(tmp_path, capsys):
    _write_rank(tmp_path, "m", 0, [_header("m", 0), _audit(4, 0x11)])
    _write_rank(tmp_path, "m", 1, [_header("m", 1), _audit(4, 0x22)])
    assert summ_mod.main(["summarize", str(tmp_path)]) == 0
    text = capsys.readouterr().out
    assert "ANOMALY: audit fingerprint divergence at generation 4" in text
    assert "rank0=0x00000011" in text and "rank1=0x00000022" in text


def test_summarize_flags_chunk_outlier(tmp_path, capsys):
    def chunk(i, wall):
        return {
            "event": "chunk", "t": 2.0, "index": i, "take": 4,
            "generation": 4 * (i + 1), "wall_s": wall,
            "updates_per_sec": 1e6, "roofline_util": None,
        }

    _write_rank(
        tmp_path, "o", 0,
        [_header("o", 0)] + [chunk(i, 0.1) for i in range(4)]
        + [chunk(4, 0.9)],
    )
    assert summ_mod.main(["summarize", str(tmp_path)]) == 0
    text = capsys.readouterr().out
    assert "ANOMALY: chunk-time outlier: chunk 4" in text


def test_no_false_anomalies_on_clean_fixture(tmp_path, capsys):
    """A healthy run must not cry wolf on the divergence/drift flags
    (utilization can legitimately vary chunk-to-chunk on CPU warm-up, so
    only the hard flags are asserted absent)."""
    _run(tmp_path, "clean")
    assert summ_mod.main(["summarize", str(tmp_path / "clean")]) == 0
    text = capsys.readouterr().out
    assert "divergence" not in text
    assert "chunk/total drift" not in text


# -- bench harness emission --------------------------------------------------


def test_halobench_telemetry(tmp_path, capsys):
    from gol_tpu.utils import halobench

    halobench.main(
        ["64", "4", "1d", "dense", "--telemetry", str(tmp_path),
         "--run-id", "hb"]
    )
    capsys.readouterr()
    recs = [json.loads(ln) for ln in open(tmp_path / "hb.rank0.jsonl")]
    assert [r["event"] for r in recs] == ["run_header", "bench_row"]
    assert recs[0]["config"]["tool"] == "halobench"
    assert "exchange_s" in recs[1]["data"]


def test_scalebench_telemetry(tmp_path, capsys):
    from gol_tpu.utils import scalebench

    scalebench.main(
        ["64", "2", "dense", "--telemetry", str(tmp_path),
         "--run-id", "sb"]
    )
    capsys.readouterr()
    recs = [json.loads(ln) for ln in open(tmp_path / "sb.rank0.jsonl")]
    rows = [r for r in recs if r["event"] == "bench_row"]
    assert len(rows) == len(scalebench.device_counts())
    assert rows[0]["data"]["devices"] == 1
    assert rows[0]["data"]["efficiency"] == 1.0


# -- real two-process rank-file merge (the test_multihost.py harness) --------

_WORKER_TELEMETRY = """
import sys
import jax
jax.config.update("jax_platforms", "cpu")
from gol_tpu import compat as _compat
_compat.set_cpu_device_count(2)
from gol_tpu import cli
pid = sys.argv[1]
sys.exit(cli.main([
    "4", "8", "4", "16", "0",
    "--ranks", "4", "--mesh", "1d",
    "--coordinator", sys.argv[2],
    "--num-processes", "2", "--process-id", pid,
    "--guard-every", "2",
    "--telemetry", sys.argv[3], "--run-id", "mh",
]))
"""


def test_two_process_rank_files_merge(tmp_path, capsys):
    from tests.test_multihost import _run_two_workers

    tdir = tmp_path / "mh"
    _run_two_workers(_WORKER_TELEMETRY, [str(tdir)])

    # One file per process — written gather-free by each rank.
    assert (tdir / "mh.rank0.jsonl").exists()
    assert (tdir / "mh.rank1.jsonl").exists()
    runs = summ_mod.load_dir(str(tdir))
    assert sorted(runs) == ["mh"]
    run = runs["mh"]
    assert sorted(run.ranks) == [0, 1]
    # Replicated audit scalars agree across ranks — no divergence flags.
    audits0 = run.records("guard_audit", rank=0)
    audits1 = run.records("guard_audit", rank=1)
    assert [a["fingerprint"] for a in audits0] == [
        a["fingerprint"] for a in audits1
    ]
    assert len(audits0) == 2
    assert summ_mod.main(["summarize", str(tdir)]) == 0
    text = capsys.readouterr().out
    assert "ranks: 2/2" in text
    assert "divergence" not in text
