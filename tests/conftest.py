"""Test env: force the CPU backend with 8 virtual devices.

Sharded-path tests run the exact same shard_map/ppermute programs on a
host-local 8-device mesh (the standard JAX trick), substituting for a real
pod — this covers the halo logic the reference never tested (bug B1).
Must run before the first `import jax` anywhere in the test process.
"""

import os

# Hard override, not setdefault: the ambient env pins JAX_PLATFORMS to the
# single real TPU (axon); tests must run on the deterministic 8-device CPU
# mesh regardless.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# This machine's site hook (/root/.axon_site) pre-imports jax at interpreter
# startup, so the env var above can be read too late.  The config API takes
# effect post-import; without it the first backend touch would try to claim
# the axon TPU tunnel and can hang the whole suite.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
