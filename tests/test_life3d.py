"""3-D Life (26-neighbor stencil) vs. the NumPy oracle, local and sharded.

BASELINE config 5 coverage: the single-device 3-torus step, the
halo-extended step, and the three-phase ppermute decomposition on every
mesh shape the 8-device CPU fixture can express — including meshes with
size-1 axes (whose rings degenerate to the local wrap) and the full 2×2×2
cube, where corner cells cross three mesh axes in one generation.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gol_tpu.ops import life3d
from gol_tpu.parallel import mesh as mesh_mod
from gol_tpu.parallel import sharded3d

from tests import oracle


@pytest.mark.parametrize("steps", [1, 3])
def test_step3d_matches_oracle(steps):
    vol = oracle.random_volume(6, 8, 10, seed=steps)
    got = np.asarray(life3d.run3d(jnp.asarray(vol), steps))
    np.testing.assert_array_equal(got, oracle.run_torus3d(vol, steps))


def test_step3d_custom_rule():
    vol = oracle.random_volume(6, 6, 6, seed=7, density=0.4)
    rule = life3d.BAYS_5766
    got = np.asarray(life3d.run3d(jnp.asarray(vol), 2, rule))
    np.testing.assert_array_equal(
        got,
        oracle.run_torus3d(vol, 2, birth=rule.birth, survive=rule.survive),
    )


def test_rule_table_exhaustive():
    """Every (alive, count) pair via a cell whose neighborhood is built
    directly: center of a 3×3×3 block with k live neighbors."""
    for k in range(27):
        for alive in (0, 1):
            vol = np.zeros((3, 3, 3), np.uint8)
            flat = [i for i in range(27) if i != 13][:k]
            vol.flat[flat] = 1
            vol[1, 1, 1] = alive
            # 3×3×3 torus wraps make each neighbor triple-counted; use a
            # padded 5-cube instead so the neighborhood is exact.
            big = np.zeros((5, 5, 5), np.uint8)
            big[1:4, 1:4, 1:4] = vol
            nxt = np.asarray(life3d.step3d(jnp.asarray(big)))[2, 2, 2]
            expect = (
                1
                if (alive and k in {4, 5}) or (not alive and k == 5)
                else 0
            )
            assert nxt == expect, (alive, k)


def test_empty_rule_sets_are_legal():
    """A pure-decay rule (no birth, no survive) kills everything — the empty
    frozenset must trace as an always-false predicate, not crash."""
    vol = oracle.random_volume(4, 4, 4, seed=9, density=0.5)
    rule = life3d.Rule3D(birth=frozenset(), survive=frozenset())
    got = np.asarray(life3d.step3d(jnp.asarray(vol), rule))
    assert got.sum() == 0


def test_halo_full_matches_wrap_pad():
    vol = oracle.random_volume(4, 6, 8, seed=3)
    ext = np.pad(vol, 1, mode="wrap")
    got = np.asarray(life3d.step3d_halo_full(jnp.asarray(ext)))
    np.testing.assert_array_equal(got, oracle.step_torus3d(vol))


@pytest.mark.parametrize(
    "shape", [(2, 2, 2), (8, 1, 1), (1, 8, 1), (1, 1, 8), (2, 4, 1), (1, 2, 4)]
)
def test_sharded3d_matches_oracle(shape):
    vol = oracle.random_volume(8, 8, 8, seed=sum(shape))
    mesh = mesh_mod.make_mesh_3d(shape, devices=jax.devices()[: np.prod(shape)])
    got = np.asarray(sharded3d.evolve_sharded3d(jnp.asarray(vol), 4, mesh))
    np.testing.assert_array_equal(got, oracle.run_torus3d(vol, 4))


def test_sharded3d_single_device_mesh():
    vol = oracle.random_volume(4, 4, 4, seed=1)
    mesh = mesh_mod.make_mesh_3d((1, 1, 1), devices=jax.devices()[:1])
    got = np.asarray(sharded3d.evolve_sharded3d(jnp.asarray(vol), 3, mesh))
    np.testing.assert_array_equal(got, oracle.run_torus3d(vol, 3))


def test_sharded3d_corner_crossing():
    """Live cluster straddling the junction of all 8 shards of a 2×2×2 mesh:
    its neighbors cross three mesh axes (the 3-hop corner path)."""
    vol = np.zeros((8, 8, 8), np.uint8)
    vol[3:5, 3:5, 3:5] = 1  # 2×2×2 cube at the 8-shard corner: n=7 each → dies
    mesh = mesh_mod.make_mesh_3d((2, 2, 2), devices=jax.devices()[:8])
    got = np.asarray(sharded3d.evolve_sharded3d(jnp.asarray(vol), 2, mesh))
    np.testing.assert_array_equal(got, oracle.run_torus3d(vol, 2))


def test_geometry3d_validation():
    mesh = mesh_mod.make_mesh_3d((2, 2, 2), devices=jax.devices()[:8])
    with pytest.raises(ValueError, match="divisible"):
        sharded3d.evolve_sharded3d(jnp.zeros((7, 8, 8), jnp.uint8), 1, mesh)


def test_mesh_3d_auto_factorization():
    mesh = mesh_mod.make_mesh_3d()
    assert int(np.prod(list(mesh.shape.values()))) == len(jax.devices())
    assert dict(mesh.shape) == {"planes": 2, "rows": 2, "cols": 2}


def test_mesh_3d_shape_mismatch():
    with pytest.raises(ValueError, match="device count"):
        mesh_mod.make_mesh_3d((2, 2, 3))
