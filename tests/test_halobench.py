"""Halo-latency harness: runs on the CPU mesh, returns sane numbers."""

import json

from gol_tpu.parallel import mesh as mesh_mod
from gol_tpu.utils import halobench


def test_measure_1d():
    out = halobench.measure(mesh_mod.make_mesh_1d(4), size=64, steps=4)
    assert set(out) == {
        "exchange_s",
        "step_s",
        "stencil_s",
        "exposed_exchange_s",
    }
    assert all(v >= 0 for v in out.values())
    assert out["exchange_s"] > 0 and out["step_s"] > 0


def test_measure_2d():
    out = halobench.measure(mesh_mod.make_mesh_2d((2, 4)), size=64, steps=4)
    assert out["step_s"] > 0


def test_2d_exchange_program_keeps_all_four_ppermutes():
    """The fold-in must consume every ghost side, or XLA dead-code-eliminates
    the horizontal phase and the tool silently times a 1-D exchange."""
    import jax
    from jax.sharding import PartitionSpec as P

    mesh = mesh_mod.make_mesh_2d((2, 4))
    fn = halobench._exchange_only(mesh, 1)
    spec = jax.ShapeDtypeStruct(
        (8, 64),
        "uint8",
        sharding=jax.sharding.NamedSharding(mesh, P("rows", "cols")),
    )
    hlo = fn.lower(spec).compile().as_text()
    assert hlo.count("collective-permute") >= 4


def test_stencil_baseline_is_single_device():
    """The compute-ceiling program must be unsharded (no collectives)."""
    out = halobench.measure(mesh_mod.make_mesh_2d((2, 4)), size=64, steps=2)
    assert out["stencil_s"] > 0  # measured on the 32×16 shard, device 0


def test_main_prints_json(capsys):
    halobench.main(["64", "4", "1d"])
    line = capsys.readouterr().out.strip()
    rec = json.loads(line)
    assert rec["size"] == 64 and rec["devices"] == 8
    assert rec["mesh"] == {"rows": 8}


def test_measure_bitpack_engine():
    from gol_tpu.parallel import mesh as mesh_mod
    from gol_tpu.utils import halobench

    out = halobench.measure(mesh_mod.make_mesh_1d(), 256, steps=4,
                            engine="bitpack")
    assert out["step_s"] > 0 and out["stencil_s"] > 0
    assert out["exposed_exchange_s"] >= 0


def test_measure_pallas_engines():
    """Serial and overlap forms of the flagship engine both attribute."""
    mesh = mesh_mod.make_mesh_1d(4)  # shard height 64 >= 2*8 + 8
    serial = halobench.measure(mesh, 256, steps=8, engine="pallas")
    overlap = halobench.measure(mesh, 256, steps=8, engine="pallas_overlap")
    for out in (serial, overlap):
        assert out["step_s"] > 0 and out["stencil_s"] > 0
        assert out["exposed_exchange_s"] >= 0


def test_measure_rejects_unknown_engine():
    import pytest

    from gol_tpu.parallel import mesh as mesh_mod
    from gol_tpu.utils import halobench

    with pytest.raises(ValueError, match="unknown engine"):
        halobench.measure(mesh_mod.make_mesh_1d(), 64, 2, engine="warp")


def test_measure_pallas_engine_2d_mesh():
    """The flagship engine attributes on a 2-D block mesh too (strip
    repair + corner-word path under the measurement harness)."""
    import jax

    mesh = mesh_mod.make_mesh_2d((2, 2), devices=jax.devices()[:4])
    out = halobench.measure(mesh, 128, steps=8, engine="pallas")
    assert out["step_s"] > 0 and out["exposed_exchange_s"] >= 0


def test_measure_rectangular_folded_pallas():
    """r4: rectangular sizes reach the lane-folded pod-shard geometry;
    the pallas engines attribute it (narrow widths take the folded
    1-ring compute ceiling in place of the bare kernel)."""
    out = halobench.measure(
        mesh_mod.make_mesh_1d(4), size=(512, 1024), steps=8, engine="pallas"
    )
    assert out["step_s"] > 0 and out["stencil_s"] > 0
    out2 = halobench.measure(
        mesh_mod.make_mesh_1d(4),
        size=(512, 1024),
        steps=8,
        engine="pallas_overlap",
    )
    assert out2["step_s"] > 0


def test_main_rectangular_size(capsys):
    halobench.main(["64x128", "4", "1d"])
    payload = json.loads(capsys.readouterr().out.strip())
    assert payload["size"] == [64, 128]


def test_measure3d_attributes_both_orientations():
    """r5 (VERDICT r4 #4): the 3-D flagship's exchange/step/kernel
    attribution exists, on both band orientations, with the ghost-word
    column phase live (x sharded)."""
    import jax

    for shape, size in (((2, 1, 2), (16, 16, 128)), ((1, 2, 2), (16, 16, 128))):
        mesh = mesh_mod.make_mesh_3d(shape, devices=jax.devices()[:4])
        out = halobench.measure3d(mesh, size, steps=8)
        assert out["step_s"] > 0 and out["stencil_s"] > 0
        assert out["exchange_s"] > 0 and out["exposed_exchange_s"] >= 0


def test_measure3d_one_device_flags_degenerate_ceiling():
    import jax

    mesh = mesh_mod.make_mesh_3d((1, 1, 1), devices=jax.devices()[:1])
    out = halobench.measure3d(mesh, (16, 16, 64), steps=8)
    assert "ceiling_note" in out


def test_3d_exchange_program_keeps_all_four_ppermutes():
    """Each phase's fold must feed the next iteration's shipped faces, or
    XLA dead-code-eliminates phases and the tool times a 1-axis ring.
    The harness mirrors the engine's two exchanged rings (band + word
    columns; the lane axis is unsharded by the mesh constraint)."""
    import jax
    import pytest
    from jax.sharding import PartitionSpec as P

    mesh = mesh_mod.make_mesh_3d((2, 1, 2), devices=jax.devices()[:4])
    fn = halobench._exchange_only_3d(mesh, 1)
    spec = jax.ShapeDtypeStruct(
        (8, 8, 64),
        "uint8",
        sharding=jax.sharding.NamedSharding(
            mesh, P("planes", "rows", "cols")
        ),
    )
    hlo = fn.lower(spec).compile().as_text()
    assert hlo.count("collective-permute") >= 4
    with pytest.raises(ValueError, match="planes or rows"):
        halobench._exchange_only_3d(mesh_mod.make_mesh_3d((2, 2, 2)), 1)


def test_main_3d_mode(capsys):
    halobench.main(["16x16x128", "8", "3d:2,1,2"])
    payload = json.loads(capsys.readouterr().out.strip())
    assert payload["size"] == [16, 16, 128]
    assert payload["mesh"] == {"planes": 2, "rows": 1, "cols": 2}
    assert payload["engine"] == "pallas3d"
