"""Engine semantics: fresh vs stale_t0 (reference-compat, bug B1) vs oracle."""

import jax.numpy as jnp
import numpy as np
import pytest

from gol_tpu.parallel import engine

from tests import oracle


def random_board(h, w, seed, density=0.35):
    return oracle.random_board(h, w, seed, density)


@pytest.mark.parametrize("steps", [0, 1, 7])
def test_fresh_matches_torus_oracle(steps):
    board = random_board(24, 12, 0)
    got = np.asarray(engine.evolve_fresh(jnp.asarray(board), steps))
    np.testing.assert_array_equal(got, oracle.run_torus(board, steps))


@pytest.mark.parametrize("num_ranks", [1, 2, 4])
@pytest.mark.parametrize("steps", [1, 5])
def test_stale_t0_matches_reference_oracle(num_ranks, steps):
    s = 8
    board = random_board(num_ranks * s, s, seed=num_ranks * 10 + steps)
    got = np.asarray(
        engine.evolve_stale_t0(jnp.asarray(board), num_ranks, steps)
    )
    expected = oracle.simulate_reference(board, num_ranks, steps)
    np.testing.assert_array_equal(got, expected)


def test_stale_t0_step1_equals_fresh_step1_multirank():
    """At step 1 the stale halos ARE the fresh halos (both are t=0 rows), so
    the two semantics agree; they diverge from step 2 on."""
    board = random_board(16, 8, 3)
    a = np.asarray(engine.evolve_fresh(jnp.asarray(board), 1))
    b = np.asarray(engine.evolve_stale_t0(jnp.asarray(board), 2, 1))
    np.testing.assert_array_equal(a, b)
    a2 = np.asarray(engine.evolve_fresh(jnp.asarray(board), 2))
    b2 = np.asarray(engine.evolve_stale_t0(jnp.asarray(board), 2, 2))
    assert not np.array_equal(a2, b2)


def test_evolve_dispatch():
    board = random_board(8, 8, 5)
    a = np.asarray(engine.evolve(jnp.asarray(board), 3, halo_mode="fresh"))
    np.testing.assert_array_equal(a, oracle.run_torus(board, 3))
    with pytest.raises(ValueError, match="halo_mode"):
        engine.evolve(jnp.asarray(board), 1, halo_mode="nope")
