"""Activity-gated sparse tier (``--engine activity``, docs/SPARSE.md).

The acceptance pins:

- **bit-identity**: activity runs equal the dense bitpack tier's final
  grid for every form (dense-jnp / packed worklist, Pallas gated grid)
  × mesh none/1d/2d × the sparse pattern zoo (glider, gun, LWSS,
  acorn) — the gate may only skip work, never change it;
- **soundness machinery**: the worklist-overflow ``lax.cond`` fallback
  is exercised and still bit-exact; the mask is reconstructed (all
  ones) on resume and the resumed run matches an uninterrupted one;
- **it actually skips**: sparse scenarios report skipped_tile_gens > 0
  (the whole point of the tier);
- **stats refactor**: the flip-plane helpers emit byte-identical jaxprs
  to the pre-refactor inline forms, and --stats + --engine activity
  agree with the NumPy model;
- **mode hygiene**: clean rejections for stale_t0 / custom rules /
  halo_depth / non-explicit shard modes / the guard / --batch.
"""

from __future__ import annotations

import json

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from gol_tpu.models import patterns
from gol_tpu.models.state import Geometry
from gol_tpu.ops import stencil
from gol_tpu.parallel import mesh as mesh_mod
from gol_tpu.runtime import GolRuntime
from gol_tpu.sparse import engine as sparse_engine
from gol_tpu.sparse import mask as sparse_mask

jax.config.update("jax_platforms", "cpu")


def _mesh(kind):
    if kind == "none":
        return None
    if kind == "1d":
        return mesh_mod.make_mesh_1d(4)
    return mesh_mod.make_mesh_2d((2, 2), devices=jax.devices()[:4])


# -- bit-identity: form × mesh × sparse pattern zoo --------------------------


@pytest.mark.parametrize("pattern", [5, 7, 8, 9])
@pytest.mark.parametrize(
    "mesh_kind,tile",
    [
        ("none", 16),  # dense-jnp worklist (16 is not word-aligned)
        ("none", 32),  # packed worklist
        ("1d", 8),
        ("2d", 16),
    ],
)
def test_activity_bit_equal_to_dense_bitpack(pattern, mesh_kind, tile):
    kw = dict(geometry=Geometry(size=128, num_ranks=1))
    _, ref = GolRuntime(**kw, engine="bitpack").run(
        pattern=pattern, iterations=48
    )
    rt = GolRuntime(
        **kw,
        engine="activity",
        mesh=_mesh(mesh_kind),
        activity_tile=tile,
    )
    _, got = rt.run(pattern=pattern, iterations=48)
    np.testing.assert_array_equal(
        np.asarray(ref.board), np.asarray(got.board)
    )
    assert rt._act_packed == (mesh_kind == "none" and tile % 32 == 0)
    assert rt.last_activity, "activity run recorded no counters"


def test_activity_sparse_scenarios_actually_skip():
    """Gun in a 256² arena: most tile-generations must be skipped."""
    kw = dict(geometry=Geometry(size=256, num_ranks=1))
    _, ref = GolRuntime(**kw, engine="bitpack").run(pattern=7, iterations=64)
    rt = GolRuntime(**kw, engine="activity")
    _, got = rt.run(pattern=7, iterations=64)
    np.testing.assert_array_equal(
        np.asarray(ref.board), np.asarray(got.board)
    )
    skipped = sum(a["skipped_tile_gens"] for a in rt.last_activity)
    tile_gens = sum(a["tile_gens"] for a in rt.last_activity)
    assert skipped > 0, "sparse scenario skipped nothing"
    assert skipped > tile_gens // 2, (
        f"only {skipped}/{tile_gens} skipped on a mostly-dead arena"
    )
    # Generation 0 may fall back (the all-ones start mask dilates to
    # everything — sound by construction); after that, never.
    assert sum(a["fallback_gens"] for a in rt.last_activity) <= 1


def test_activity_overflow_falls_back_and_stays_exact():
    """A dense soup overflows any small worklist: the cond must take
    the dense branch (recorded) and the result must still be exact."""
    rng = np.random.default_rng(7)
    soup = jnp.asarray((rng.random((64, 64)) < 0.35).astype(np.uint8))
    ref = np.asarray(stencil.run(jnp.array(soup, copy=True), 12))
    th, tw = sparse_mask.grid_shape(64, 64, 8)
    out, _, act = sparse_engine.evolve_gated_dense(
        jnp.array(soup, copy=True), sparse_mask.full_mask(th, tw), 12, 8, 4
    )
    np.testing.assert_array_equal(np.asarray(out), ref)
    assert int(act["fallback_gens"]) > 0
    # Fallback generations compute the full grid — the honest counter.
    assert int(act["computed_tile_gens"]) >= int(
        act["fallback_gens"]
    ) * th * tw


def test_activity_seam_crossing_glider_1d():
    """A glider seeded right at a shard seam (and wrapping the torus)
    must reactivate the neighbor shard's tiles through the mask
    exchange — bit-equality over a transit across the whole board."""
    from gol_tpu.parallel import sparse as par_sparse

    mesh = _mesh("1d")
    # Shard height 16 on a 64² board; seed straddling the rank-0/rank-1
    # seam AND the torus wrap in columns.
    board0 = patterns.init_sparse_world("glider", 64, 64, (14, 62))
    ref = np.asarray(stencil.run(jnp.asarray(board0), 96))
    fn = par_sparse.compiled_evolve_activity(mesh, 96, 8, 24)
    board = mesh_mod.shard_board(jnp.asarray(board0), mesh)
    mask = jax.device_put(
        np.ones((8, 8), bool), par_sparse.mask_sharding(mesh)
    )
    out, _, act = fn(board, mask)
    np.testing.assert_array_equal(np.asarray(out), ref)
    assert int(act["computed_tile_gens"]) < 8 * 8 * 96


def test_activity_resume_reconstructs_mask(tmp_path):
    """Kill at gen 16, resume to 48: the mask restarts all-active and
    the final grid is byte-identical to the uninterrupted run."""
    kw = dict(geometry=Geometry(size=128, num_ranks=1))
    _, ref = GolRuntime(**kw, engine="activity").run(
        pattern=7, iterations=48
    )
    d = str(tmp_path / "ck")
    GolRuntime(
        **kw, engine="activity", checkpoint_every=16, checkpoint_dir=d
    ).run(pattern=7, iterations=16)
    import os

    ck = os.path.join(d, sorted(os.listdir(d))[-1])
    _, resumed = GolRuntime(**kw, engine="activity").run(
        pattern=7, iterations=32, resume=ck
    )
    np.testing.assert_array_equal(
        np.asarray(ref.board), np.asarray(resumed.board)
    )


# -- Pallas gated grid (interpret mode off-TPU) ------------------------------


def test_pallas_gated_grid_bit_equal_and_gates():
    from gol_tpu.sparse import pallas as sparse_pallas

    board0 = patterns.init_sparse_world("gosper_gun", 128, 128, (40, 8))
    ref = np.asarray(stencil.run(jnp.asarray(board0), 30))
    out, _, act = sparse_pallas.evolve_gated_pallas(
        jnp.asarray(board0), sparse_mask.full_mask(4, 4), 30, 32
    )
    np.testing.assert_array_equal(np.asarray(out), ref)
    # Band gating: some bands were off for some generations.
    assert int(act["computed_tile_gens"]) < 4 * 4 * 30
    assert int(act["fallback_gens"]) == 0


def test_pallas_gated_grid_rejects_bad_tile():
    from gol_tpu.sparse import pallas as sparse_pallas

    with pytest.raises(ValueError, match="multiple of 32"):
        sparse_pallas.evolve_gated_pallas(
            jnp.zeros((64, 64), jnp.uint8),
            sparse_mask.full_mask(4, 4),
            4,
            16,
        )


# -- stats refactor satellite ------------------------------------------------


def test_stats_refactor_jaxpr_identical():
    """The flip-plane helpers must emit byte-for-byte the jaxpr of the
    pre-refactor inline forms — the trace-identity pin extended to the
    ops/stats refactor."""
    from gol_tpu.ops import stats as ops_stats

    def inline_dense(prev, new, band):
        h, w = new.shape
        band = max(1, min(band, h, w))
        n = new.astype(jnp.uint32)
        flips = (prev ^ new).astype(jnp.uint32)
        born = flips * n
        died = flips - born

        def rows(x):
            return jnp.sum(x, axis=1, dtype=jnp.uint32)

        return {
            "population": ops_stats.sum_pair(rows(n)),
            "births": ops_stats.sum_pair(rows(born)),
            "deaths": ops_stats.sum_pair(rows(died)),
            "changed": ops_stats.sum_pair(rows(flips)),
            "face_top": ops_stats.sum_pair(rows(n[:band])),
            "face_bottom": ops_stats.sum_pair(rows(n[-band:])),
            "face_left": ops_stats.sum_pair(rows(n[:, :band])),
            "face_right": ops_stats.sum_pair(rows(n[:, -band:])),
        }

    spec = jax.ShapeDtypeStruct((64, 64), jnp.uint8)
    got = jax.make_jaxpr(
        lambda p, n: ops_stats.dense_chunk_stats(p, n, 1)
    )(spec, spec)
    want = jax.make_jaxpr(lambda p, n: inline_dense(p, n, 1))(spec, spec)
    assert str(got) == str(want)


def test_stats_with_activity_engine_matches_numpy_model(tmp_path):
    from tests.test_stats import _np_chunk_stats

    geom = Geometry(size=128, num_ranks=1)
    rt = GolRuntime(
        geometry=geom,
        engine="activity",
        stats=True,
        telemetry_dir=str(tmp_path),
        run_id="actstats",
    )
    _, state = rt.run(pattern=7, iterations=24)
    board0 = patterns.init_global(7, 128, 1)
    expected = _np_chunk_stats(board0, np.asarray(state.board))
    (chunk_stats,) = rt.last_stats
    assert {k: chunk_stats[k] for k in expected} == expected
    # The same run also produced activity counters.
    assert rt.last_activity and rt.last_activity[0]["tile_gens"] > 0


def test_activity_knobs_leave_other_tiers_traced_identically():
    """The new runtime fields must not perturb non-activity programs —
    the PR 2 trace-identity discipline extended to this round's knobs."""
    geom = Geometry(size=64, num_ranks=1)
    a = GolRuntime(geometry=geom, engine="bitpack")
    b = GolRuntime(
        geometry=geom, engine="bitpack",
        activity_tile=16, activity_capacity=0.5,
    )
    fa, da, sa = a._evolve_fn(8)
    fb, db, sb = b._evolve_fn(8)
    spec = jax.ShapeDtypeStruct((64, 64), jnp.uint8)
    assert str(fa.trace(spec, *da, *sa).jaxpr) == str(
        fb.trace(spec, *db, *sb).jaxpr
    )


# -- telemetry / CLI ---------------------------------------------------------


def test_cli_activity_end_to_end_with_telemetry(tmp_path, capsys):
    from gol_tpu import cli
    from gol_tpu.telemetry import summarize as summ_mod

    d = tmp_path / "t"
    rc = cli.main(
        ["7", "128", "24", "512", "0", "--engine", "activity",
         "--telemetry", str(d), "--run-id", "cliact"]
    )
    assert rc == 0
    capsys.readouterr()
    recs = [json.loads(ln) for ln in open(d / "cliact.rank0.jsonl")]
    # A fresh stream stamps the CURRENT schema (the activity block
    # itself is the v5 addition under test).
    assert recs[0]["schema"] >= 5
    chunks = [r for r in recs if r["event"] == "chunk"]
    assert chunks and all("activity" in c for c in chunks)
    blk = chunks[0]["activity"]
    assert blk["tile_gens"] == blk["computed_tile_gens"] + blk[
        "skipped_tile_gens"
    ]
    # The activity tier has no honest static roofline — None, not a lie.
    assert all(c["roofline_util"] is None for c in chunks)
    assert summ_mod.main(["summarize", str(d)]) == 0
    assert "act " in capsys.readouterr().out


def test_cli_activity_flag_validation(capsys):
    from gol_tpu import cli

    assert (
        cli.main(["0", "64", "8", "512", "0", "--activity-tile", "16"])
        == 255
    )
    assert "--engine activity" in capsys.readouterr().out
    # --engine activity + --guard-every is now a supported combination
    # (PR 10, docs/RESILIENCE.md "Guard coverage"): a guarded run
    # completes with an audit trail instead of a rejection.
    assert (
        cli.main(
            ["0", "64", "8", "512", "0", "--engine", "activity",
             "--guard-every", "4"]
        )
        == 0
    )
    assert "GUARD" in capsys.readouterr().out
    assert (
        cli.main(
            ["0", "64", "8", "512", "0", "--engine", "activity",
             "--batch", "2"]
        )
        == 255
    )
    assert "no batched tier" in capsys.readouterr().out


# -- mode hygiene ------------------------------------------------------------


@pytest.mark.parametrize(
    "kw,msg",
    [
        (dict(halo_mode="stale_t0"), "fresh halos only"),
        (dict(rule="B36/S23"), "B3/S23 fast paths"),
        (dict(halo_depth=2), "halo_depth must be 1"),
        (dict(activity_tile=24), "must divide"),
        (dict(activity_tile=-3), ">= 1"),
        (dict(activity_capacity=0.0), "capacity fraction"),
    ],
)
def test_activity_runtime_rejections(kw, msg):
    with pytest.raises(ValueError, match=msg):
        GolRuntime(
            geometry=Geometry(size=64, num_ranks=1),
            engine="activity",
            **kw,
        )


def test_activity_sharded_rejections():
    with pytest.raises(ValueError, match="explicit ring program only"):
        GolRuntime(
            geometry=Geometry(size=64, num_ranks=1),
            engine="activity",
            mesh=_mesh("1d"),
            shard_mode="overlap",
        )
    # The tile must divide the *shard*, not just the board.
    with pytest.raises(ValueError, match="shard extents"):
        GolRuntime(
            geometry=Geometry(size=64, num_ranks=1),
            engine="activity",
            mesh=_mesh("1d"),
            activity_tile=32,  # shard height is 16
        )


def test_guard_composes_with_activity_runtime():
    """PR 10 lifted the activity-tier guard rejection: a guarded
    fault-free activity run audits clean and stays bit-identical to the
    dense tier (the full flip/rollback coverage lives in
    tests/test_guard_tiers.py)."""
    from gol_tpu.utils import guard as guard_mod

    ref = GolRuntime(geometry=Geometry(size=64, num_ranks=1), engine="dense")
    _, ref_state = ref.run(pattern=4, iterations=8)
    rt = GolRuntime(geometry=Geometry(size=64, num_ranks=1), engine="activity")
    _, state, report = guard_mod.run_guarded(
        rt, pattern=4, iterations=8,
        config=guard_mod.GuardConfig(check_every=4),
    )
    assert report.failures == 0 and report.checks == 2
    assert np.array_equal(np.asarray(state.board), np.asarray(ref_state.board))


# -- mask unit properties ----------------------------------------------------


def test_dilate_wraps_the_torus():
    m = np.zeros((5, 7), bool)
    m[0, 0] = True
    got = np.asarray(sparse_mask.dilate(jnp.asarray(m)))
    expect = {(0, 0), (0, 1), (1, 0), (1, 1), (4, 0), (4, 1), (0, 6),
              (1, 6), (4, 6)}
    assert {tuple(i) for i in np.argwhere(got)} == expect


def test_changed_tiles_dense_packed_agree():
    rng = np.random.default_rng(3)
    a = (rng.random((64, 64)) < 0.3).astype(np.uint8)
    b = np.asarray(stencil.step(jnp.asarray(a)))
    from gol_tpu.ops import bitlife

    dense = np.asarray(
        sparse_mask.changed_tiles_dense(jnp.asarray(a), jnp.asarray(b), 32)
    )
    packed = np.asarray(
        sparse_mask.changed_tiles_packed(
            bitlife.pack(jnp.asarray(a)), bitlife.pack(jnp.asarray(b)), 32
        )
    )
    np.testing.assert_array_equal(dense, packed)


def test_pick_tile_prefers_gating_granularity():
    assert sparse_mask.pick_tile(1024, 1024) == 64
    assert sparse_mask.pick_tile(128, 128) == 16  # 8x8 grid beats 2x2
    assert sparse_mask.pick_tile(256, 256, packed=True) == 32
    assert sparse_mask.pick_tile(64, 64, packed=True) == 32  # finest
    with pytest.raises(ValueError, match="no activity tile"):
        sparse_mask.pick_tile(7, 64, packed=True)
