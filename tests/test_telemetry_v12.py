"""Schema v12 (request-trace spans) + v1–v11 back-compat.

Companion to tests/test_telemetry.py (v1) and test_telemetry_v{2..11}.py.
Here:

- the v12 addition round-trips: ``span`` records one node of a
  request's span tree (trace_id/span_id/parent_id, name, start/end,
  attrs — docs/OBSERVABILITY.md "Request tracing & SLOs");
- the committed v12 fixture is a REAL traced serve run — three
  completed requests plus a deadline cancel, with queue/chunk/commit
  spans, root-span decompositions, and trace_ids on the serve events;
- **back-compat**: all ELEVEN committed fixtures — PR 2 (v1) through
  PR 17 (v12) — still load, merge, and render in one ``summarize``
  pass (exit 0) with the trace census line;
- a stream from a FUTURE schema fails loudly ("newer than this reader
  supports", exit 2) instead of KeyError'ing deep in a consumer;
- the ``gol_serve_queue_wait_seconds``/``gol_serve_stall_fraction``
  histograms are fed from the same span records (single source of
  truth with `telemetry trace`).
"""

from __future__ import annotations

import json
import pathlib
import shutil

import jax
import pytest

from gol_tpu import telemetry
from gol_tpu.telemetry import summarize as summ_mod

jax.config.update("jax_platforms", "cpu")

DATA = pathlib.Path(__file__).parent / "data"
FIXTURES = {
    1: DATA / "telemetry_v1" / "pr2run.rank0.jsonl",
    2: DATA / "telemetry_v2" / "pr3run.rank0.jsonl",
    3: DATA / "telemetry_v3" / "pr5run.rank0.jsonl",
    4: DATA / "telemetry_v4" / "pr6run.rank0.jsonl",
    5: DATA / "telemetry_v5" / "pr7run.rank0.jsonl",
    6: DATA / "telemetry_v6" / "pr8run.rank0.jsonl",
    7: DATA / "telemetry_v7" / "pr9run.rank0.jsonl",
    8: DATA / "telemetry_v8" / "pr10run.rank0.jsonl",
    9: DATA / "telemetry_v9" / "pr12run.rank0.jsonl",
    11: DATA / "telemetry_v11" / "pr14run.rank0.jsonl",
    12: DATA / "telemetry_v12" / "pr17run.rank0.jsonl",
}


def _v12_stream(directory, run_id="v12"):
    with telemetry.EventLog(
        str(directory), run_id=run_id, process_index=0
    ) as ev:
        ev.run_header({"driver": "serve", "engine": "auto", "slots": 4})
        ev.span_event(
            "tr-a-1", "a", "q#1", "queue", 1.0, 1.5,
            parent_id="root", attrs={"bucket": "32x32/bitpack"},
        )
        ev.span_event(
            "tr-a-1", "a", "q#2", "chunk", 1.5, 2.0,
            parent_id="root",
            attrs={"co_resident": 2, "utilization": 0.5, "take": 4},
        )
        ev.span_event(
            "tr-a-1", "a", "root", "request", 1.0, 2.0,
            attrs={
                "status": "done", "e2e_s": 1.0, "queue_s": 0.5,
                "compute_s": 0.25, "interference_s": 0.25,
                "hedge_s": 0.0, "stall_s": 0.0,
            },
        )
        return ev.path


def test_v12_span_roundtrip(tmp_path):
    path = _v12_stream(tmp_path)
    recs = [json.loads(ln) for ln in open(path)]
    assert recs[0]["schema"] == telemetry.SCHEMA_VERSION >= 12
    assert set(telemetry.SUPPORTED_SCHEMAS) >= set(range(1, 13))
    spans = [r for r in recs if r["event"] == "span"]
    assert [s["name"] for s in spans] == ["queue", "chunk", "request"]
    assert all(s["trace_id"] == "tr-a-1" for s in spans)
    assert spans[0]["parent_id"] == "root"
    assert spans[2]["span_id"] == "root"
    assert "parent_id" not in spans[2]  # the root has no parent
    assert spans[1]["attrs"]["co_resident"] == 2
    assert spans[2]["attrs"]["stall_s"] == 0.0


def test_span_event_validates_required_fields(tmp_path):
    with telemetry.EventLog(
        str(tmp_path), run_id="bad", process_index=0
    ) as ev:
        ev.run_header({})
        with pytest.raises(telemetry.SchemaError, match="span"):
            ev.emit("span", trace_id="t", request_id="r")  # no ids/times


def test_committed_fixture_schemas():
    for want, fixture in FIXTURES.items():
        head = json.loads(fixture.open().readline())
        assert head["schema"] == want, fixture


def test_v12_fixture_is_a_real_traced_serve_run():
    """The committed stream came from a real scheduler run: three
    completed requests and one deadline cancel, each with a complete
    span tree whose decomposition phases sum to its e2e latency."""
    recs = [json.loads(ln) for ln in FIXTURES[12].open()]
    assert recs[0]["config"]["driver"] == "serve"
    spans = [r for r in recs if r["event"] == "span"]
    by_trace = {}
    for s in spans:
        by_trace.setdefault(s["trace_id"], []).append(s)
    assert len(by_trace) == 4
    serve = [r for r in recs if r["event"] == "serve"]
    # Cross-correlation: every admit carries the trace_id its spans use.
    admit_tids = {
        r["trace_id"] for r in serve if r["action"] == "admit"
    }
    assert admit_tids == set(by_trace)
    statuses = []
    for tid, tree in by_trace.items():
        ids = {s["span_id"] for s in tree}
        assert "root" in ids
        # No orphans: every parent resolves within the trace.
        assert all(
            s.get("parent_id") is None or s["parent_id"] in ids
            for s in tree
        )
        root = next(s for s in tree if s["span_id"] == "root")
        a = root["attrs"]
        statuses.append(a["status"])
        parts = (
            a["queue_s"] + a["compute_s"] + a["interference_s"]
            + a["hedge_s"] + a["stall_s"]
        )
        assert parts == pytest.approx(a["e2e_s"], rel=0.01, abs=1e-5)
    assert statuses.count("done") == 3 and statuses.count("expired") == 1
    chunk_spans = [s for s in spans if s["name"] == "chunk"]
    assert chunk_spans and all(
        s["attrs"]["co_resident"] >= 1 and s["attrs"]["take"] >= 1
        for s in chunk_spans
    )
    # Chunk utilization comes from the roofline model, not a placeholder.
    assert any(
        isinstance(s["attrs"].get("utilization"), float)
        for s in chunk_spans
    )


def test_v1_to_v12_merge_renders(tmp_path, capsys):
    for fixture in FIXTURES.values():
        shutil.copy(fixture, tmp_path / fixture.name)
    _v12_stream(tmp_path)
    assert summ_mod.main(["summarize", str(tmp_path)]) == 0
    out = capsys.readouterr().out
    for run_id in (
        "pr2run", "pr3run", "pr5run", "pr6run", "pr7run", "pr8run",
        "pr9run", "pr10run", "pr12run", "pr14run", "pr17run", "v12",
    ):
        assert run_id in out
    assert "trace:" in out and "`telemetry trace`" in out


def test_future_schema_fails_loudly_not_keyerror(tmp_path, capsys):
    """A stream one schema ahead of this reader must exit 2 with a
    "newer than supported" message — never a KeyError from a consumer
    touching a field it has never heard of."""
    future = telemetry.SCHEMA_VERSION + 1
    (tmp_path / "fut.rank0.jsonl").write_text(
        json.dumps(
            {
                "event": "run_header", "t": 0.0, "schema": future,
                "run_id": "fut", "process_index": 0, "process_count": 1,
                "config": {},
            }
        )
        + "\n"
        # A record type this reader has no REQUIRED_FIELDS entry for —
        # the version check must fire before anything touches it.
        + json.dumps(
            {"event": "from_the_future", "t": 1.0, "wormhole": True}
        )
        + "\n"
    )
    assert summ_mod.main(["summarize", str(tmp_path)]) == 2
    err = capsys.readouterr().err
    assert f"schema v{future} is newer than this reader supports" in err
    assert f"max v{telemetry.SCHEMA_VERSION}" in err


def test_bogus_nonint_schema_still_exits_2(tmp_path):
    (tmp_path / "bad.rank0.jsonl").write_text(
        json.dumps(
            {"event": "run_header", "t": 0.0, "schema": "twelve",
             "run_id": "bad", "process_index": 0, "process_count": 1,
             "config": {}}
        )
        + "\n"
    )
    assert summ_mod.main(["summarize", str(tmp_path)]) == 2


def test_span_metrics_histograms(tmp_path):
    """gol_serve_queue_wait_seconds / gol_serve_stall_fraction are fed
    from the SAME span records the JSONL carries — and stay absent
    until a span is observed."""
    from gol_tpu.telemetry.metrics import MetricsRegistry

    reg = MetricsRegistry()
    assert "gol_serve_queue_wait_seconds" not in reg.render()
    assert "gol_serve_stall_fraction" not in reg.render()
    for ln in open(_v12_stream(tmp_path)):
        reg.observe(json.loads(ln))
    text = reg.render()
    # The 0.5 s queue wait lands in the first le >= 0.5 bucket.
    assert 'gol_serve_queue_wait_seconds_bucket{le="0.5"} 1' in text
    assert 'gol_serve_queue_wait_seconds_bucket{le="0.1"} 0' in text
    assert "gol_serve_queue_wait_seconds_sum 0.5" in text
    assert "gol_serve_queue_wait_seconds_count 1" in text
    # stall_s 0.0 over e2e 1.0 -> fraction 0, the lowest bucket.
    assert 'gol_serve_stall_fraction_bucket{le="0.01"} 1' in text
    assert "gol_serve_stall_fraction_count 1" in text
