"""Generalized 2-D rules: parsing, oracle parity, packed==dense, Conway round-trip."""

from __future__ import annotations

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from gol_tpu.ops import rules, stencil

from tests import oracle

jax.config.update("jax_platforms", "cpu")


def _np_rule_step(board: np.ndarray, rule: rules.Rule2D) -> np.ndarray:
    """Independent NumPy oracle: roll-sum count + set membership."""
    n = sum(
        np.roll(np.roll(board, dr, 0), dc, 1)
        for dr in (-1, 0, 1)
        for dc in (-1, 0, 1)
        if (dr, dc) != (0, 0)
    )
    alive = board == 1
    born = np.isin(n, sorted(rule.birth)) & ~alive
    keep = np.isin(n, sorted(rule.survive)) & alive
    return (born | keep).astype(np.uint8)


def test_parse_rulestring():
    r = rules.parse_rulestring("B36/S23")
    assert r.birth == frozenset({3, 6})
    assert r.survive == frozenset({2, 3})
    assert r.rulestring() == "B36/S23"
    assert rules.parse_rulestring("b2/s") == rules.SEEDS
    with pytest.raises(ValueError, match="malformed"):
        rules.parse_rulestring("36/23")
    with pytest.raises(ValueError, match="counts > 8"):
        rules.parse_rulestring("B9/S2")


@pytest.mark.parametrize("name", sorted(rules.NAMED_RULES))
@pytest.mark.parametrize("steps", [1, 4])
def test_dense_rule_matches_numpy_oracle(name, steps):
    rule = rules.NAMED_RULES[name]
    board = oracle.random_board(24, 40, seed=sum(map(ord, name)) + steps)
    expected = board
    for _ in range(steps):
        expected = _np_rule_step(expected, rule)
    got = np.asarray(rules.run_rule(jnp.asarray(board), steps, rule))
    np.testing.assert_array_equal(got, expected)


@pytest.mark.parametrize("name", sorted(rules.NAMED_RULES))
def test_packed_rule_matches_dense(name):
    rule = rules.NAMED_RULES[name]
    board = oracle.random_board(16, 96, seed=sum(map(ord, name)))
    dense = np.asarray(rules.run_rule(jnp.asarray(board), 5, rule))
    packed = np.asarray(
        rules.evolve_rule_dense_io(jnp.asarray(board), 5, rule)
    )
    np.testing.assert_array_equal(packed, dense)


def test_conway_rule_matches_native_engines():
    """B3/S23 through the generic evaluators == the hard-wired engines."""
    board = oracle.random_board(32, 64, seed=11)
    expected = np.asarray(stencil.run(jnp.asarray(board), 6))
    np.testing.assert_array_equal(
        np.asarray(rules.run_rule(jnp.asarray(board), 6, rules.CONWAY)),
        expected,
    )
    np.testing.assert_array_equal(
        np.asarray(
            rules.evolve_rule_dense_io(jnp.asarray(board), 6, rules.CONWAY)
        ),
        expected,
    )


def test_seeds_everything_dies_without_birth():
    # Seeds (B2/S): every live cell dies each generation; two isolated
    # diagonal cells birth on exactly-2 counts.
    board = np.zeros((8, 32), np.uint8)
    board[3, 3] = board[4, 4] = 1
    nxt = np.asarray(rules.run_rule(jnp.asarray(board), 1, rules.SEEDS))
    assert nxt[3, 3] == 0 and nxt[4, 4] == 0  # originals die (S empty)
    assert nxt[3, 4] == 1 and nxt[4, 3] == 1  # B2 births the off-diagonal


def test_highlife_replicator_differs_from_conway():
    board = oracle.random_board(16, 32, seed=5)
    c = np.asarray(rules.run_rule(jnp.asarray(board), 8, rules.CONWAY))
    h = np.asarray(rules.run_rule(jnp.asarray(board), 8, rules.HIGHLIFE))
    assert (c != h).any()  # B6 births must kick in on a dense random board


# -- runtime / CLI surface ---------------------------------------------------


def test_runtime_rule_matches_library():
    from gol_tpu.models.state import Geometry
    from gol_tpu.runtime import GolRuntime

    rt = GolRuntime(
        geometry=Geometry(size=32, num_ranks=1), rule="B36/S23"
    )
    assert rt._resolved == "bitpack"  # generic packed evaluator
    _, state = rt.run(pattern=6, iterations=8)
    from gol_tpu.models import patterns

    board0 = jnp.asarray(patterns.init_global(6, 32, 1))
    np.testing.assert_array_equal(
        np.asarray(state.board),
        np.asarray(rules.run_rule(board0, 8, rules.HIGHLIFE)),
    )


def test_runtime_conway_rulestring_keeps_fast_paths():
    from gol_tpu.models.state import Geometry
    from gol_tpu.runtime import GolRuntime

    rt = GolRuntime(geometry=Geometry(size=32, num_ranks=1), rule="B3/S23")
    assert rt._rule is None  # hard-wired engines still used


def test_runtime_rule_rejections():
    from gol_tpu.models.state import Geometry
    from gol_tpu.parallel import mesh as mesh_mod
    from gol_tpu.runtime import GolRuntime

    with pytest.raises(ValueError, match="explicit"):
        GolRuntime(
            geometry=Geometry(size=32, num_ranks=4),
            mesh=mesh_mod.make_mesh_1d(4),
            shard_mode="overlap",
            rule="B36/S23",
        )
    with pytest.raises(ValueError, match="hard-wired"):
        GolRuntime(
            geometry=Geometry(size=32, num_ranks=1),
            engine="pallas",
            rule="B36/S23",
        )
    with pytest.raises(ValueError, match="stale_t0|compat"):
        GolRuntime(
            geometry=Geometry(size=32, num_ranks=1),
            halo_mode="stale_t0",
            rule="B2/S",
        )
    with pytest.raises(ValueError, match="malformed"):
        GolRuntime(geometry=Geometry(size=32, num_ranks=1), rule="wat")


def test_cli_rule_flag(tmp_path, capsys, monkeypatch):
    from gol_tpu import cli

    monkeypatch.chdir(tmp_path)
    rc = cli.main(["6", "32", "8", "64", "1", "--rule", "B36/S23"])
    assert rc == 0
    assert "TOTAL DURATION" in capsys.readouterr().out
    from gol_tpu.models import patterns
    from gol_tpu.utils import io as gol_io

    _, block = gol_io.read_rank_file(str(tmp_path / "Rank_0_of_1.txt"))
    board0 = jnp.asarray(patterns.init_global(6, 32, 1))
    np.testing.assert_array_equal(
        block, np.asarray(rules.run_rule(board0, 8, rules.HIGHLIFE))
    )


def test_rule_checkpoint_resume_guard(tmp_path):
    from gol_tpu.models.state import Geometry
    from gol_tpu.runtime import GolRuntime
    from gol_tpu.utils import checkpoint as ckpt_mod

    ckdir = str(tmp_path / "ck")
    rt = GolRuntime(
        geometry=Geometry(size=32, num_ranks=1),
        rule="B36/S23",
        checkpoint_every=4,
        checkpoint_dir=ckdir,
    )
    _, state = rt.run(pattern=6, iterations=8)
    path = ckpt_mod.checkpoint_path(ckdir, 8)
    assert ckpt_mod.load(path).rule == "B36/S23"

    # Resuming without the rule (implicit B3/S23) must refuse.
    rt2 = GolRuntime(geometry=Geometry(size=32, num_ranks=1))
    with pytest.raises(ValueError, match="B36/S23"):
        rt2.run(pattern=6, iterations=1, resume=path)
    # With a different custom rule: refuse.
    rt3 = GolRuntime(geometry=Geometry(size=32, num_ranks=1), rule="B2/S")
    with pytest.raises(ValueError, match="B36/S23"):
        rt3.run(pattern=6, iterations=1, resume=path)
    # With the matching rule: resumes and continues identically.
    rt4 = GolRuntime(geometry=Geometry(size=32, num_ranks=1), rule="B36/S23")
    _, state4 = rt4.run(pattern=6, iterations=0, resume=path)
    np.testing.assert_array_equal(
        np.asarray(state4.board), np.asarray(state.board)
    )
    # And a Conway checkpoint refuses a custom-rule resume.
    rt5 = GolRuntime(
        geometry=Geometry(size=32, num_ranks=1),
        checkpoint_every=2,
        checkpoint_dir=str(tmp_path / "ck2"),
    )
    rt5.run(pattern=4, iterations=2)
    conway_path = ckpt_mod.checkpoint_path(str(tmp_path / "ck2"), 2)
    rt6 = GolRuntime(geometry=Geometry(size=32, num_ranks=1), rule="B2/S")
    with pytest.raises(ValueError, match="B3/S23"):
        rt6.run(pattern=4, iterations=1, resume=conway_path)


@pytest.mark.parametrize("packed", [False, True])
@pytest.mark.parametrize("mesh_kind", ["1d", "2d"])
@pytest.mark.parametrize("halo_depth", [1, 2])
def test_sharded_rule_matches_oracle(packed, mesh_kind, halo_depth):
    from gol_tpu.parallel import mesh as mesh_mod
    from gol_tpu.parallel import ruled

    rule = rules.HIGHLIFE
    # 256 wide: 2-D shards are 64 cells = 2 words, enough for depth-2 halos.
    board = oracle.random_board(32, 256, seed=17)
    mesh = (
        mesh_mod.make_mesh_1d() if mesh_kind == "1d" else mesh_mod.make_mesh_2d()
    )
    got = np.asarray(
        ruled.evolve_sharded_rule(
            jnp.asarray(board), 6, mesh, rule, packed=packed, halo_depth=halo_depth
        )
    )
    expected = board
    for _ in range(6):
        expected = _np_rule_step(expected, rule)
    np.testing.assert_array_equal(got, expected)


def test_runtime_sharded_rule_end_to_end():
    from gol_tpu.models import patterns
    from gol_tpu.models.state import Geometry
    from gol_tpu.parallel import mesh as mesh_mod
    from gol_tpu.runtime import GolRuntime

    geom = Geometry(size=32, num_ranks=4)
    rt = GolRuntime(
        geometry=geom,
        mesh=mesh_mod.make_mesh_1d(4),
        rule="B36/S23",
        halo_depth=2,
    )
    assert rt._resolved == "bitpack"
    _, state = rt.run(pattern=6, iterations=7)
    expected = patterns.init_global(6, 32, 4)
    for _ in range(7):
        expected = _np_rule_step(expected, rules.HIGHLIFE)
    np.testing.assert_array_equal(np.asarray(state.board), expected)


@pytest.mark.parametrize("name", ["highlife", "seeds", "day_and_night"])
def test_pallas_rule_matches_generic(name):
    """The Pallas kernel's generic tail (interpret mode on CPU) == the XLA
    generic evaluator, including temporal blocking and the remainder path."""
    from gol_tpu.ops import pallas_bitlife

    rule = rules.NAMED_RULES[name]
    board = oracle.random_board(32, 64, seed=sum(map(ord, name)) + 1)
    ref = np.asarray(rules.run_rule(jnp.asarray(board), 7, rule))
    got = np.asarray(
        pallas_bitlife.evolve(jnp.asarray(board), 7, 16, rule)
    )
    np.testing.assert_array_equal(got, ref)


def test_runtime_pallas_bitpack_accepts_rule():
    from gol_tpu.models import patterns
    from gol_tpu.models.state import Geometry
    from gol_tpu.runtime import GolRuntime

    # Explicit pallas_bitpack engine with a custom rule constructs fine
    # (kernel runs in interpret mode on CPU).
    rt = GolRuntime(
        geometry=Geometry(size=32, num_ranks=1),
        engine="pallas_bitpack",
        rule="B36/S23",
    )
    _, state = rt.run(pattern=6, iterations=4)
    board0 = jnp.asarray(patterns.init_global(6, 32, 1))
    np.testing.assert_array_equal(
        np.asarray(state.board),
        np.asarray(rules.run_rule(board0, 4, rules.HIGHLIFE)),
    )
