"""Schema v2, back-compat, rank-file rotation, and the watch dashboard.

Companion to tests/test_telemetry.py (which pins the v1-era behavior
and the trace-identity invariant).  Here:

- the v2 additions round-trip: ``stats`` events and the ``memory``
  block on ``compile`` events;
- **back-compat**: the committed PR 2 (schema v1) fixture file still
  loads, and a directory holding a v1 run *and* a freshly-written v2
  run merges and renders in one ``summarize`` pass (exit 0) — while a
  bogus schema number still takes the exit-2 validation path;
- **rank-file collision**: re-opening an ``EventLog`` with an existing
  ``--run-id`` rotates the old stream aside instead of clobbering or
  interleaving; rotated files are invisible to the ``summarize`` glob;
- the stats watchdogs flag extinction / static fixpoint / cross-rank
  population disagreement from synthetic streams;
- ``watch`` renders a frame from a finished run, survives torn lines,
  and reuses ``summarize``'s anomaly rules verbatim.
"""

from __future__ import annotations

import io
import json
import os
import pathlib
import shutil

import pytest

import jax

from gol_tpu import telemetry
from gol_tpu.telemetry import summarize as summ_mod
from gol_tpu.telemetry import watch as watch_mod

jax.config.update("jax_platforms", "cpu")

V1_FIXTURE = (
    pathlib.Path(__file__).parent / "data" / "telemetry_v1"
    / "pr2run.rank0.jsonl"
)


# -- v2 round-trip -----------------------------------------------------------


def test_stats_and_memory_events_roundtrip(tmp_path):
    with telemetry.EventLog(str(tmp_path), run_id="v2", process_index=0) as ev:
        ev.run_header({"driver": "2d"})
        ev.compile_event(
            8, 0.1, 0.2,
            memory={"argument_bytes": 4096, "output_bytes": 4096,
                    "temp_bytes": 128, "flops": 45056.0},
        )
        ev.stats_event(
            0, 8, 8,
            {"population": 7, "births": 3, "deaths": 2, "changed": 5,
             "face_top": 1, "face_bottom": 0, "face_left": 2,
             "face_right": 0},
        )
        path = ev.path
    recs = [json.loads(ln) for ln in open(path)]
    assert [r["event"] for r in recs] == ["run_header", "compile", "stats"]
    # The v2-era features ride whatever the current schema version is
    # (v3 since the resilience events landed) — additive by contract.
    assert recs[0]["schema"] == telemetry.SCHEMA_VERSION >= 2
    assert recs[1]["memory"]["argument_bytes"] == 4096
    assert recs[2]["population"] == 7
    assert recs[2]["faces"] == {"top": 1, "bottom": 0, "left": 2, "right": 0}
    for r in recs:
        telemetry.validate_record(r)  # must not raise


def test_validate_rejects_incomplete_stats_record():
    with pytest.raises(telemetry.SchemaError):
        telemetry.validate_record(
            {"event": "stats", "t": 1.0, "index": 0, "population": 3}
        )


# -- schema back-compat (v1 fixture) -----------------------------------------


def test_v1_fixture_still_loads():
    runs = summ_mod.load_dir(str(V1_FIXTURE.parent))
    assert sorted(runs) == ["pr2run"]
    run = runs["pr2run"]
    assert run.header["schema"] == 1
    assert len(run.records("chunk")) == 3
    assert run.summary_record["cell_updates"] == 32768


def test_v1_and_v2_runs_merge_in_one_summarize(tmp_path, capsys):
    """The golden back-compat pin: a directory holding a PR 2 (v1)
    stream next to a fresh v2 stream renders both runs, exit 0."""
    from gol_tpu.models.state import Geometry
    from gol_tpu.runtime import GolRuntime

    shutil.copy(V1_FIXTURE, tmp_path / V1_FIXTURE.name)
    rt = GolRuntime(
        geometry=Geometry(size=64, num_ranks=1),
        telemetry_dir=str(tmp_path),
        run_id="fresh",
        stats=True,
    )
    rt.run(pattern=4, iterations=8)
    assert summ_mod.main(["summarize", str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "run pr2run" in out and "run fresh" in out
    # v2-only tables render for the v2 run only.
    assert out.count("stats     gen") == 1
    # Both runs' chunk tables are there.
    assert out.count("chunk     gens") == 2


def test_unknown_schema_still_exits_2(tmp_path, capsys):
    bad = dict(json.loads(V1_FIXTURE.read_text().splitlines()[0]))
    bad["schema"] = 99
    (tmp_path / "x.rank0.jsonl").write_text(json.dumps(bad) + "\n")
    assert summ_mod.main(["summarize", str(tmp_path)]) == 2
    assert "schema" in capsys.readouterr().err


# -- rank-file collision rotation --------------------------------------------


def _minimal_run(directory, run_id, marker):
    with telemetry.EventLog(directory, run_id=run_id, process_index=0) as ev:
        ev.run_header({"marker": marker})
        return ev.path


def test_rerun_with_same_run_id_rotates_old_file(tmp_path):
    d = str(tmp_path)
    path = _minimal_run(d, "dup", "first")
    _minimal_run(d, "dup", "second")
    _minimal_run(d, "dup", "third")
    # The live file holds the newest stream; older ones rotated aside.
    live = json.loads(open(path).read().splitlines()[0])
    assert live["config"]["marker"] == "third"
    rot1 = json.loads(open(path + ".1").read().splitlines()[0])
    rot2 = json.loads(open(path + ".2").read().splitlines()[0])
    assert rot1["config"]["marker"] == "first"
    assert rot2["config"]["marker"] == "second"
    # summarize sees exactly one run with one header — no interleaving,
    # and the rotated files don't match the rank-file glob.
    runs = summ_mod.load_dir(d)
    assert sorted(runs) == ["dup"]
    assert len(runs["dup"].records("run_header")) == 1


# -- stats watchdogs ---------------------------------------------------------


def _write_rank(tmp_path, run_id, rank, records):
    path = telemetry.rank_file(str(tmp_path), run_id, rank)
    with open(path, "w") as f:
        for rec in records:
            telemetry.validate_record(rec)
            f.write(json.dumps(rec) + "\n")


def _header(run_id, rank, count=1):
    return {
        "event": "run_header", "t": 1.0, "schema": 2, "run_id": run_id,
        "process_index": rank, "process_count": count, "config": {},
    }


def _stats(idx, gen, pop, changed=1):
    return {
        "event": "stats", "t": 2.0 + idx, "index": idx, "take": 4,
        "generation": gen, "population": pop,
        "births": changed // 2, "deaths": changed - changed // 2,
        "changed": changed, "faces": {},
    }


def test_watchdog_flags_extinction(tmp_path, capsys):
    _write_rank(
        tmp_path, "ex", 0,
        [_header("ex", 0), _stats(0, 4, 120), _stats(1, 8, 0, changed=240)],
    )
    assert summ_mod.main(["summarize", str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "ANOMALY: extinction" in out and "generation 8" in out


def test_watchdog_flags_static_fixpoint(tmp_path, capsys):
    _write_rank(
        tmp_path, "fx", 0,
        [_header("fx", 0), _stats(0, 4, 12), _stats(1, 8, 12, changed=0)],
    )
    assert summ_mod.main(["summarize", str(tmp_path)]) == 0
    assert "ANOMALY: all-static fixpoint" in capsys.readouterr().out


def test_watchdog_flags_cross_rank_population_divergence(tmp_path, capsys):
    _write_rank(tmp_path, "dv", 0,
                [_header("dv", 0, 2), _stats(0, 4, 100)])
    _write_rank(tmp_path, "dv", 1,
                [_header("dv", 1, 2), _stats(0, 4, 101)])
    assert summ_mod.main(["summarize", str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "ANOMALY: cross-rank population disagreement" in out
    assert "rank0=100" in out and "rank1=101" in out


def test_no_watchdog_flags_on_healthy_stream(tmp_path, capsys):
    _write_rank(
        tmp_path, "ok", 0,
        [_header("ok", 0), _stats(0, 4, 100), _stats(1, 8, 90)],
    )
    assert summ_mod.main(["summarize", str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "extinction" not in out and "fixpoint" not in out
    assert "disagreement" not in out


# -- watch -------------------------------------------------------------------


def test_watch_renders_finished_run(tmp_path, capsys):
    from gol_tpu.models.state import Geometry
    from gol_tpu.runtime import GolRuntime

    rt = GolRuntime(
        geometry=Geometry(size=64, num_ranks=1),
        telemetry_dir=str(tmp_path),
        run_id="w",
        stats=True,
    )
    rt.run(pattern=4, iterations=8)
    assert summ_mod.main(["watch", str(tmp_path), "--once"]) == 0
    out = capsys.readouterr().out
    assert "run w" in out
    assert "population:" in out
    assert "FINISHED" in out


def test_watch_waits_on_empty_directory(tmp_path, capsys):
    assert summ_mod.main(["watch", str(tmp_path), "--once"]) == 0
    assert "waiting for telemetry" in capsys.readouterr().out


def test_watch_tails_incrementally_and_survives_torn_lines(tmp_path):
    path = telemetry.rank_file(str(tmp_path), "tail", 0)
    full = json.dumps(_header("tail", 0))
    torn = json.dumps(_stats(0, 4, 55))
    with open(path, "w") as f:
        f.write(full + "\n" + torn[: len(torn) // 2])  # writer mid-record
    w = watch_mod.Watcher(str(tmp_path))
    w.poll()
    run = w.current_run()
    assert len(run.records("run_header")) == 1
    assert run.records("stats") == []  # incomplete line not consumed
    with open(path, "a") as f:
        f.write(torn[len(torn) // 2 :] + "\n" + "NOT JSON\n")
    w.poll()
    run = w.current_run()
    assert [s["population"] for s in run.records("stats")] == [55]
    assert w.invalid_lines == 1  # the garbage line: counted, not fatal
    # The frame renders the accumulated state and the shared anomaly
    # rules find nothing to flag.
    out = io.StringIO()
    watch_mod.render_frame(w, out)
    assert "population: 55" in out.getvalue()


def test_watch_anomalies_match_summarize(tmp_path):
    """The dashboard's flags are summarize's flags — same function,
    same strings."""
    _write_rank(
        tmp_path, "wa", 0,
        [_header("wa", 0), _stats(0, 4, 120), _stats(1, 8, 0, changed=240)],
    )
    out = io.StringIO()
    assert watch_mod.watch(str(tmp_path), out, frames=1, clear=False) == 0
    frame = out.getvalue()
    run = summ_mod.load_dir(str(tmp_path))["wa"]
    for flag in summ_mod.find_anomalies(run):
        assert f"ANOMALY: {flag}" in frame


def test_v1_fixture_is_committed():
    """The back-compat golden test is only as good as its fixture: make
    sure the committed file is the v1 shape (schema 1, no stats)."""
    lines = [json.loads(ln) for ln in V1_FIXTURE.read_text().splitlines()]
    assert lines[0]["schema"] == 1
    assert all(r["event"] != "stats" for r in lines)
    assert os.path.basename(V1_FIXTURE.name).endswith(".rank0.jsonl")
