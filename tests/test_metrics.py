"""Live metrics endpoint (--metrics-port; docs/OBSERVABILITY.md).

- the registry is a faithful event-stream consumer (counters per type);
- the HTTP server serves parseable Prometheus text exposition format;
- a real run is scraped **mid-run** and its counters reconcile with the
  run's final JSONL telemetry (one emission feeds both — they cannot
  drift);
- trace identity: metrics-on and metrics-off runtimes trace
  byte-identical jaxprs (the knob is host-side by construction);
- the CLI rejects --metrics-port without --telemetry.
"""

from __future__ import annotations

import json
import pathlib
import re
import threading
import time
import urllib.request

import jax
import numpy as np

from gol_tpu.models.state import Geometry
from gol_tpu.runtime import GolRuntime
from gol_tpu.telemetry import metrics as metrics_mod

jax.config.update("jax_platforms", "cpu")

_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{[^}]*\})? (-?[0-9.e+-]+|NaN)$"
)


def parse_prometheus(text: str) -> dict:
    """Exposition-format parser: {metric_name[{labels}]: float}.

    Strict enough to fail on anything a real scraper would reject:
    every non-comment line must be `name[{labels}] value`.
    """
    out = {}
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        assert m, f"unparseable exposition line: {line!r}"
        out[m.group(1) + (m.group(2) or "")] = float(m.group(3))
    return out


def scrape(port: int) -> str:
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}/metrics", timeout=5.0
    ) as resp:
        assert resp.headers["Content-Type"].startswith("text/plain")
        return resp.read().decode()


# -- registry unit ------------------------------------------------------------


def test_registry_consumes_the_event_stream():
    reg = metrics_mod.MetricsRegistry()
    reg.observe(
        {"event": "chunk", "index": 0, "take": 8, "generation": 8,
         "wall_s": 0.5, "updates_per_sec": 1e6, "roofline_util": None,
         "spans": {"dispatch": 0.1, "ready": 0.4}}
    )
    reg.observe(
        {"event": "chunk", "index": 1, "take": 8, "generation": 16,
         "wall_s": 0.4, "updates_per_sec": 2e6, "roofline_util": None,
         "spans": {"dispatch": 0.1, "ready": 0.3},
         "activity": {"active_fraction": 0.25}}
    )
    reg.observe({"event": "stats", "population": 42, "take": 8,
                 "index": 1, "generation": 16})
    reg.observe({"event": "checkpoint", "generation": 16, "wall_s": 0.01})
    reg.observe({"event": "summary", "updates_per_sec": 1.5e6})
    vals = parse_prometheus(reg.render())
    assert vals["gol_generation"] == 16
    assert vals["gol_chunks_total"] == 2
    assert vals["gol_generations_total"] == 16
    assert vals["gol_generations_per_sec"] == 8 / 0.4
    assert vals["gol_population"] == 42
    assert vals["gol_activity_fraction"] == 0.25
    assert vals["gol_checkpoints_total"] == 1
    assert vals['gol_span_seconds_total{phase="dispatch"}'] == 0.2
    assert vals['gol_span_seconds_total{phase="ready"}'] == 0.7
    assert vals["gol_run_finished"] == 1
    assert vals["gol_updates_per_sec_final"] == 1.5e6


def test_server_serves_and_404s(tmp_path):
    reg = metrics_mod.MetricsRegistry()
    srv = metrics_mod.MetricsServer(reg, 0)
    try:
        vals = parse_prometheus(scrape(srv.port))
        assert vals["gol_generation"] == 0
        try:
            urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/other", timeout=5.0
            )
        except urllib.error.HTTPError as e:
            assert e.code == 404
        else:
            raise AssertionError("/other did not 404")
    finally:
        srv.close()


# -- mid-run scrape + reconciliation -----------------------------------------


def test_midrun_scrape_reconciles_with_final_jsonl(tmp_path):
    rt = GolRuntime(
        geometry=Geometry(size=64, num_ranks=1),
        engine="bitpack",
        checkpoint_every=8,
        checkpoint_dir=str(tmp_path / "ck"),
        telemetry_dir=str(tmp_path / "t"),
        run_id="mscrape",
        stats=True,
        metrics_port=0,
    )
    iterations = 4096
    done = threading.Event()
    errors = []

    def run():
        try:
            rt.run(pattern=6, iterations=iterations)
        except Exception as e:  # surfaces in the main thread's assert
            errors.append(e)
        finally:
            done.set()

    t = threading.Thread(target=run)
    t.start()
    mid = None
    while not done.is_set():
        if rt._metrics_server is None:
            time.sleep(0.005)
            continue
        try:
            vals = parse_prometheus(scrape(rt._metrics_server.port))
        except OSError:
            time.sleep(0.005)
            continue
        if vals.get("gol_generation", 0) > 0 and not vals.get(
            "gol_run_finished"
        ):
            mid = vals
            break
        time.sleep(0.005)
    t.join(timeout=300)
    assert not errors, errors
    assert mid is not None, "never scraped the endpoint mid-run"

    recs = [
        json.loads(ln)
        for ln in open(pathlib.Path(tmp_path) / "t" / "mscrape.rank0.jsonl")
    ]
    chunks = [r for r in recs if r["event"] == "chunk"]
    stats = [r for r in recs if r["event"] == "stats"]
    # The mid-run scrape saw a generation the JSONL also recorded.
    assert mid["gol_generation"] in {c["generation"] for c in chunks}
    # The registry's final state reconciles exactly with the stream.
    reg = rt.last_metrics
    assert reg is not None
    assert reg.generation == chunks[-1]["generation"] == iterations
    assert reg.chunks_total == len(chunks)
    assert reg.generations_total == sum(c["take"] for c in chunks)
    assert reg.population == stats[-1]["population"]
    assert reg.checkpoints_total == len(
        [r for r in recs if r["event"] == "checkpoint"]
    )
    assert reg.finished
    spans_total = {}
    for c in chunks:
        for phase, secs in c["spans"].items():
            spans_total[phase] = spans_total.get(phase, 0.0) + secs
    for phase, secs in spans_total.items():
        assert abs(reg.span_seconds[phase] - secs) < 1e-9
    # The server died with the event log.
    assert rt.last_metrics is not None


# -- trace identity -----------------------------------------------------------


def test_metrics_knob_never_changes_the_traced_program(tmp_path):
    from gol_tpu.analysis import walker

    for engine in ("dense", "bitpack"):
        kw = dict(geometry=Geometry(size=64, num_ranks=1), engine=engine)
        rt_off = GolRuntime(**kw)
        rt_on = GolRuntime(
            **kw,
            telemetry_dir=str(tmp_path / "ti"),
            run_id="ti",
            metrics_port=0,
        )
        spec = jax.ShapeDtypeStruct((64, 64), np.uint8)
        jaxprs = []
        for rt in (rt_off, rt_on):
            fn, dynamic, static = rt._evolve_fn(4)
            jaxprs.append(
                str(walker.trace_jaxpr(fn, spec, *dynamic, *static))
            )
        assert jaxprs[0] == jaxprs[1], f"engine {engine} trace diverged"


def test_metrics_run_bit_identical_board(tmp_path):
    kw = dict(geometry=Geometry(size=64, num_ranks=1), engine="bitpack")
    _, plain = GolRuntime(**kw).run(pattern=6, iterations=16)
    _, metered = GolRuntime(
        **kw,
        telemetry_dir=str(tmp_path / "t"),
        run_id="bits",
        metrics_port=0,
    ).run(pattern=6, iterations=16)
    assert np.array_equal(
        np.asarray(plain.board), np.asarray(metered.board)
    )


# -- CLI validation -----------------------------------------------------------


def test_cli_rejects_metrics_port_without_telemetry(capsys):
    from gol_tpu import cli

    rc = cli.main(["0", "64", "4", "512", "0", "--metrics-port", "0"])
    assert rc == 255
    assert "--telemetry" in capsys.readouterr().out


def test_cli_rejects_out_of_range_port(capsys):
    from gol_tpu import cli

    rc = cli.main(
        ["0", "64", "4", "512", "0", "--telemetry", "/tmp/x",
         "--metrics-port", "70000"]
    )
    assert rc == 255
    assert "0..65535" in capsys.readouterr().out


def test_batch_runtime_serves_metrics(tmp_path):
    from gol_tpu.batch import GolBatchRuntime

    rng = np.random.default_rng(1)
    worlds = [(rng.random((64, 64)) < 0.3).astype(np.uint8)] * 2
    brt = GolBatchRuntime(
        worlds=worlds,
        telemetry_dir=str(tmp_path / "t"),
        run_id="bmx",
        metrics_port=0,
    )
    brt.run(8)
    reg = brt.last_metrics
    assert reg is not None
    assert reg.generation == 8
    assert reg.finished
