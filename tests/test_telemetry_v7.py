"""Schema v7 (elastic-mesh reshard event) + v1–v6 back-compat.

Companion to tests/test_telemetry.py (v1) and test_telemetry_v{2..6}.py.
Here:

- the v7 addition round-trips: the ``reshard`` event (src/dst mesh
  layouts, validated-plan accounting, packed transport bytes —
  docs/RESILIENCE.md);
- **back-compat**: ALL SIX committed fixtures — PR 2 (v1), PR 3 (v2),
  PR 5 (v3), PR 6 (v4), PR 7 (v5) and PR 8 (v6) — still load, and a
  directory holding v1–v6 + a freshly-written v7 stream merges and
  renders in one ``summarize`` pass (exit 0) including the reshard
  line, while a bogus schema still exits 2.

Real-run emission (cross-topology resume stamps exactly one event,
same-mesh resume stamps none) is pinned in tests/test_reshard.py.
"""

from __future__ import annotations

import json
import pathlib
import shutil

import jax

from gol_tpu import telemetry
from gol_tpu.telemetry import summarize as summ_mod

jax.config.update("jax_platforms", "cpu")

DATA = pathlib.Path(__file__).parent / "data"
FIXTURES = {
    1: DATA / "telemetry_v1" / "pr2run.rank0.jsonl",
    2: DATA / "telemetry_v2" / "pr3run.rank0.jsonl",
    3: DATA / "telemetry_v3" / "pr5run.rank0.jsonl",
    4: DATA / "telemetry_v4" / "pr6run.rank0.jsonl",
    5: DATA / "telemetry_v5" / "pr7run.rank0.jsonl",
    6: DATA / "telemetry_v6" / "pr8run.rank0.jsonl",
}

RESHARD_FIELDS = dict(
    generation=8,
    src_mesh={"kind": "2d", "rows": 4, "cols": 2},
    dst_mesh={"kind": "1d", "rows": 8, "cols": 1},
    bytes_moved=512,
    cells=4096,
    dst_shards=8,
    src_pieces=8,
    moves=16,
    seam_splits=2,
    legacy_manifest=False,
    path="/ck/ckpt_000000000008.gol.d",
)


def _v7_stream(directory, run_id="v7"):
    with telemetry.EventLog(
        str(directory), run_id=run_id, process_index=0
    ) as ev:
        ev.run_header(
            {"driver": "2d", "engine": "auto", "resolved_engine": "bitpack",
             "height": 64, "width": 64}
        )
        ev.compile_event(8, 0.01, 0.11)
        ev.resume_event(generation=8, path="/ck/x", fallback=False)
        ev.reshard_event(**RESHARD_FIELDS)
        ev.chunk_event(0, 8, 16, 0.002, 32768, None)
        return ev.path


def test_v7_reshard_roundtrip(tmp_path):
    path = _v7_stream(tmp_path)
    recs = [json.loads(ln) for ln in open(path)]
    assert recs[0]["schema"] == telemetry.SCHEMA_VERSION >= 7
    assert set(telemetry.SUPPORTED_SCHEMAS) >= {1, 2, 3, 4, 5, 6, 7}
    reshard = recs[3]
    assert reshard["event"] == "reshard"
    assert reshard["src_mesh"]["rows"] == 4
    assert reshard["dst_mesh"]["kind"] == "1d"
    assert reshard["bytes_moved"] == 512
    assert reshard["seam_splits"] == 2


def test_reshard_event_schema_required_fields():
    import pytest

    with pytest.raises(telemetry.SchemaError, match="missing fields"):
        telemetry.validate_record(
            {"event": "reshard", "t": 0.0, "generation": 8}
        )


def test_committed_fixture_schemas_are_v1_to_v6():
    for want, fixture in FIXTURES.items():
        head = json.loads(fixture.open().readline())
        assert head["schema"] == want, fixture


def test_v6_fixture_carries_spans():
    chunks = [
        json.loads(ln)
        for ln in FIXTURES[6].open()
        if '"chunk"' in ln
    ]
    chunks = [c for c in chunks if c["event"] == "chunk"]
    assert chunks and all("spans" in c for c in chunks)


def test_v1_to_v7_merge_renders(tmp_path, capsys):
    for fixture in FIXTURES.values():
        shutil.copy(fixture, tmp_path / fixture.name)
    _v7_stream(tmp_path)
    assert summ_mod.main(["summarize", str(tmp_path)]) == 0
    out = capsys.readouterr().out
    for run_id in (
        "pr2run", "pr3run", "pr5run", "pr6run", "pr7run", "pr8run", "v7"
    ):
        assert run_id in out
    assert "reshard: generation 8 2d 4x2 -> 1d 8x1" in out
    assert "512 packed bytes moved" in out
    assert "(2 seam splits)" in out


def test_bogus_schema_still_exits_2(tmp_path):
    (tmp_path / "bad.rank0.jsonl").write_text(
        json.dumps(
            {"event": "run_header", "t": 0.0, "schema": 99, "run_id": "bad",
             "process_index": 0, "process_count": 1, "config": {}}
        )
        + "\n"
    )
    assert summ_mod.main(["summarize", str(tmp_path)]) == 2


def test_legacy_manifest_flag_renders(tmp_path, capsys):
    with telemetry.EventLog(str(tmp_path), run_id="leg", process_index=0) \
            as ev:
        ev.run_header({"driver": "2d"})
        ev.reshard_event(
            generation=4,
            src_mesh={"kind": "1d", "rows": 2, "cols": 1},
            dst_mesh={"kind": "none", "rows": 1, "cols": 1},
            bytes_moved=128,
            legacy_manifest=True,
        )
    assert summ_mod.main(["summarize", str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "[legacy manifest]" in out
    assert "1d 2x1 -> none" in out
