"""Schema v10 (serving-tier events) + v1–v9 back-compat.

Companion to tests/test_telemetry.py (v1) and test_telemetry_v{2..9}.py.
Here:

- the v10 addition round-trips: ``serve`` records one request lifecycle
  transition (admit/start/complete/reject/deadline/requeue) with its
  request id and queue-depth detail (docs/SERVING.md);
- a REAL scheduler run emits the full admit→start→complete sequence and
  the summarize pass renders the serve line;
- **back-compat**: ALL NINE committed fixtures — PR 2 (v1) through
  PR 12 (v9, a real faulted guarded batch run) — still load, and a
  directory holding v1–v9 + a fresh v10 stream merges and renders in
  one ``summarize`` pass (exit 0) with the serve line, while a bogus
  schema still exits 2.
"""

from __future__ import annotations

import json
import pathlib
import shutil

import jax

from gol_tpu import telemetry
from gol_tpu.telemetry import summarize as summ_mod

jax.config.update("jax_platforms", "cpu")

DATA = pathlib.Path(__file__).parent / "data"
FIXTURES = {
    1: DATA / "telemetry_v1" / "pr2run.rank0.jsonl",
    2: DATA / "telemetry_v2" / "pr3run.rank0.jsonl",
    3: DATA / "telemetry_v3" / "pr5run.rank0.jsonl",
    4: DATA / "telemetry_v4" / "pr6run.rank0.jsonl",
    5: DATA / "telemetry_v5" / "pr7run.rank0.jsonl",
    6: DATA / "telemetry_v6" / "pr8run.rank0.jsonl",
    7: DATA / "telemetry_v7" / "pr9run.rank0.jsonl",
    8: DATA / "telemetry_v8" / "pr10run.rank0.jsonl",
    9: DATA / "telemetry_v9" / "pr12run.rank0.jsonl",
}


def _v10_stream(directory, run_id="v10"):
    with telemetry.EventLog(
        str(directory), run_id=run_id, process_index=0
    ) as ev:
        ev.run_header(
            {"driver": "serve", "engine": "auto", "slots": 4,
             "queue_depth": 8, "chunk": 4}
        )
        ev.serve_event("admit", "req-1", bucket="64x64/bitpack",
                       queue_depth=1, inflight=0)
        ev.serve_event("start", "req-1", bucket="64x64/bitpack",
                       queue_depth=0, inflight=1)
        ev.serve_event(
            "complete", "req-1", bucket="64x64/bitpack",
            queue_depth=0, inflight=0, latency_s=0.125, generation=50,
        )
        ev.serve_event("reject", "req-2", reason="queue_full",
                       queue_depth=8, inflight=4)
        return ev.path


def test_v10_serve_roundtrip(tmp_path):
    path = _v10_stream(tmp_path)
    recs = [json.loads(ln) for ln in open(path)]
    assert recs[0]["schema"] == telemetry.SCHEMA_VERSION >= 10
    assert set(telemetry.SUPPORTED_SCHEMAS) >= set(range(1, 11))
    serves = [r for r in recs if r["event"] == "serve"]
    assert [r["action"] for r in serves] == [
        "admit", "start", "complete", "reject",
    ]
    done = serves[2]
    assert done["request_id"] == "req-1"
    assert done["latency_s"] == 0.125


def test_real_scheduler_run_stamps_v10_records(tmp_path):
    """End to end: the serve scheduler's admit→start→complete sequence
    lands in the stream and summarize renders the serve line."""
    from gol_tpu.serve.scheduler import ServeScheduler

    sched = ServeScheduler(
        str(tmp_path / "state"),
        quantum=32,
        slots=2,
        chunk=3,
        telemetry_dir=str(tmp_path / "tm"),
        run_id="served",
    )
    try:
        sched.submit(
            {"id": "a", "pattern": 4, "size": 32, "generations": 5}
        )
        sched.submit(
            {"id": "b", "pattern": 4, "size": 32, "generations": 5}
        )
        sched.run_until_drained()
    finally:
        sched.close()
    recs = [
        json.loads(ln)
        for ln in open(tmp_path / "tm" / "served.rank0.jsonl")
    ]
    actions = [
        (r["action"], r["request_id"])
        for r in recs
        if r["event"] == "serve"
    ]
    for rid in ("a", "b"):
        for action in ("admit", "start", "complete"):
            assert (action, rid) in actions
    assert summ_mod.main(["summarize", str(tmp_path / "tm")]) == 0


def test_committed_fixture_schemas_are_v1_to_v9():
    for want, fixture in FIXTURES.items():
        head = json.loads(fixture.open().readline())
        assert head["schema"] == want, fixture


def test_v9_fixture_is_a_real_faulted_guarded_run():
    recs = [json.loads(ln) for ln in FIXTURES[9].open()]
    assert recs[0]["config"]["driver"] == "batch"
    faults = [r for r in recs if r["event"] == "fault"]
    assert {f["site"] for f in faults} >= {
        "checkpoint.io_error", "board.bitflip",
    }
    assert any(
        r["event"] == "guard_audit" and not r["ok"] for r in recs
    )
    assert any(
        r["event"] == "degraded" and r["action"] == "retried"
        for r in recs
    )


def test_v1_to_v10_merge_renders(tmp_path, capsys):
    for fixture in FIXTURES.values():
        shutil.copy(fixture, tmp_path / fixture.name)
    _v10_stream(tmp_path)
    assert summ_mod.main(["summarize", str(tmp_path)]) == 0
    out = capsys.readouterr().out
    for run_id in (
        "pr2run", "pr3run", "pr5run", "pr6run", "pr7run", "pr8run",
        "pr9run", "pr10run", "pr12run", "v10",
    ):
        assert run_id in out
    assert "serve: 1 request(s) committed" in out
    assert "1 admit" in out and "1 reject" in out


def test_serve_metrics_render(tmp_path):
    """The gol_serve_* gauges appear once serve records are observed."""
    from gol_tpu.telemetry.metrics import MetricsRegistry

    reg = MetricsRegistry()
    base = reg.render()
    assert "gol_serve_" not in base  # absent until the tier is used
    for ln in open(_v10_stream(tmp_path)):
        reg.observe(json.loads(ln))
    text = reg.render()
    assert "gol_serve_admitted_total 1" in text
    assert "gol_serve_rejected_total 1" in text
    assert "gol_serve_completed_total 1" in text
    assert 'gol_serve_request_seconds_bucket{le="0.5"} 1' in text
    assert "gol_serve_request_seconds_count 1" in text


def test_bogus_schema_still_exits_2(tmp_path):
    (tmp_path / "bad.rank0.jsonl").write_text(
        json.dumps(
            {"event": "run_header", "t": 0.0, "schema": 99, "run_id": "bad",
             "process_index": 0, "process_count": 1, "config": {}}
        )
        + "\n"
    )
    assert summ_mod.main(["summarize", str(tmp_path)]) == 2
