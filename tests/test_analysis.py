"""gol_tpu.analysis: each lint must fire on a seeded-broken engine.

A verifier that has never caught a bug is a verifier that does not work
(the same doctrine as the guard's fault-injection hook).  Every check
gets a deliberately-broken fixture program carrying exactly the bug
class it pins — a shallow halo band, a wrong-neighbor ring, a float
upcast, a host callback in the loop, dropped donation, unmodeled extra
work, a builder that retraces per chunk — plus the all-green integration
pass over the full engine×mesh matrix.  All CPU-only.
"""

from __future__ import annotations

import functools

import pytest

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from gol_tpu import compat
from gol_tpu.analysis import checks, configs, walker
from gol_tpu.analysis.report import AnalysisReport, EngineReport, CheckResult, Finding
from gol_tpu.ops import stencil
from gol_tpu.parallel import mesh as mesh_mod
from gol_tpu.parallel.halo import halo_extend

MESH_N = 4


def _mesh():
    return mesh_mod.make_mesh_1d(MESH_N)


def _cfg(**kw):
    defaults = dict(name="fixture", engine="dense", mesh="1d", size=64)
    defaults.update(kw)
    return configs.EngineConfig(**defaults)


def _sharded_spec(mesh, h=64, w=64):
    return jax.ShapeDtypeStruct(
        (h, w), jnp.uint8, sharding=mesh_mod.board_sharding(mesh)
    )


# -- comm --------------------------------------------------------------------


def _ring_program(body):
    """jit(shard_map(body)) over the 4-device row ring."""
    fn = compat.shard_map(
        body, mesh=_mesh(), in_specs=P("rows", None), out_specs=P("rows", None)
    )
    return jax.jit(fn, donate_argnums=0)


def test_comm_flags_shallow_halo_band():
    """An engine shipping a (k-1)-deep band for k-generation chunks."""
    k = 4

    def local(blk):  # ships k-1, config claims k: the blocking contract bug
        def chunk(b):
            ext = halo_extend(b, ((0, "rows", MESH_N),), depth=k - 1)
            for _ in range(k - 1):
                ext = stencil.step_halo_rows(ext[1:-1], ext[0], ext[-1])
            return ext

        return lax.fori_loop(0, 2, lambda _, b: chunk(b), blk)

    jaxpr = walker.trace_jaxpr(_ring_program(local), _sharded_spec(_mesh()))
    result = checks.check_comm(jaxpr, _cfg(halo_depth=k), _mesh())
    assert result.status == "FAIL"
    assert any("exchanged halo depth" in f.message for f in result.errors)


def test_comm_flags_non_ring_permutation():
    """Halos from the wrong neighbor (a ±2 'ring') must be caught."""

    def local(blk):
        def body(_, b):
            perm = [(i, (i + 2) % MESH_N) for i in range(MESH_N)]
            top = lax.ppermute(b[-1:], "rows", perm)
            bottom = lax.ppermute(b[:1], "rows", perm)
            return stencil.step_halo_rows(b, top[0], bottom[0])

        return lax.fori_loop(0, 3, body, blk)

    jaxpr = walker.trace_jaxpr(_ring_program(local), _sharded_spec(_mesh()))
    result = checks.check_comm(jaxpr, _cfg(), _mesh())
    assert result.status == "FAIL"
    assert any("not a ±1 ring" in f.message for f in result.errors)


def test_comm_flags_missing_exchange():
    """A sharded 'engine' with no exchange at all is bug B1 forever."""

    def local(blk):
        return lax.fori_loop(0, 3, lambda _, b: stencil.step(b), blk)

    jaxpr = walker.trace_jaxpr(_ring_program(local), _sharded_spec(_mesh()))
    result = checks.check_comm(jaxpr, _cfg(), _mesh())
    assert result.status == "FAIL"
    assert any("no ppermute" in f.message for f in result.errors)


def test_comm_flags_collective_in_single_device_program():
    """A stray collective in a mesh-none program is a config/dispatch bug."""
    fn = compat.shard_map(
        lambda b: lax.ppermute(b, "rows", [(0, 0)]),
        mesh=mesh_mod.make_mesh_1d(1),
        in_specs=P(None, None),
        out_specs=P(None, None),
        check_vma=False,  # keep the trivial ppermute unrewritten
    )
    jaxpr = walker.trace_jaxpr(
        jax.jit(fn), jax.ShapeDtypeStruct((16, 16), jnp.uint8)
    )
    result = checks.check_comm(jaxpr, _cfg(mesh="none"), None)
    assert result.status == "FAIL"
    assert any("contains collectives" in f.message for f in result.errors)

    clean = walker.trace_jaxpr(
        jax.jit(lambda b: stencil.step(b)),
        jax.ShapeDtypeStruct((16, 16), jnp.uint8),
    )
    assert checks.check_comm(clean, _cfg(mesh="none"), None).status == "PASS"


def test_comm_passes_correct_ring_engine():
    from gol_tpu.parallel import sharded

    mesh = _mesh()
    jaxpr = walker.trace_jaxpr(
        sharded.compiled_evolve(mesh, 8, "explicit", 4), _sharded_spec(mesh)
    )
    result = checks.check_comm(jaxpr, _cfg(halo_depth=4), mesh)
    assert result.status == "PASS"


# -- dtype -------------------------------------------------------------------


def test_dtype_flags_float_upcast_in_loop():
    @jax.jit
    def leaky(board):
        def body(_, b):
            # The classic accidental upcast: mean-field math in f32.
            blurred = b.astype(jnp.float32) * 0.5
            return (blurred > 0.2).astype(jnp.uint8)

        return lax.fori_loop(0, 4, body, board)

    jaxpr = walker.trace_jaxpr(
        leaky, jax.ShapeDtypeStruct((16, 16), jnp.uint8)
    )
    result = checks.check_dtype(jaxpr, _cfg(mesh="none"))
    assert result.status == "FAIL"
    assert any("float leak" in f.message for f in result.errors)


def test_dtype_flags_packed_tier_alien_dtype():
    @jax.jit
    def widens(words):
        return lax.fori_loop(
            0, 2, lambda _, w: (w.astype(jnp.int16) + 1).astype(jnp.uint32), words
        )

    jaxpr = walker.trace_jaxpr(
        widens, jax.ShapeDtypeStruct((8, 4), jnp.uint32)
    )
    result = checks.check_dtype(jaxpr, _cfg(mesh="none", engine="bitpack"))
    assert result.status == "FAIL"
    assert any("packed-tier dtype leak" in f.message for f in result.errors)


def test_dtype_passes_real_packed_engine():
    from gol_tpu.ops import bitlife

    jaxpr = walker.trace_jaxpr(
        bitlife.evolve_dense_io, jax.ShapeDtypeStruct((16, 32), jnp.uint8), 3
    )
    assert checks.check_dtype(
        jaxpr, _cfg(mesh="none", engine="bitpack")
    ).status == "PASS"


# -- purity ------------------------------------------------------------------


def test_purity_flags_callback_in_generation_loop():
    @jax.jit
    def chatty(board):
        def body(_, b):
            jax.debug.callback(lambda x: None, b[0, 0])
            return stencil.step(b)

        return lax.fori_loop(0, 3, body, board)

    jaxpr = walker.trace_jaxpr(
        chatty, jax.ShapeDtypeStruct((16, 16), jnp.uint8)
    )
    result = checks.check_purity(jaxpr, _cfg(mesh="none"))
    assert result.status == "FAIL"
    assert any(
        "debug_callback" in f.message and "loop" in f.message
        for f in result.errors
    )


def test_purity_flags_pure_callback():
    @jax.jit
    def hosty(board):
        return jax.pure_callback(
            lambda x: x, jax.ShapeDtypeStruct(board.shape, board.dtype), board
        )

    jaxpr = walker.trace_jaxpr(
        hosty, jax.ShapeDtypeStruct((8, 8), jnp.uint8)
    )
    result = checks.check_purity(jaxpr, _cfg(mesh="none"))
    assert result.status == "FAIL"


# -- donation ----------------------------------------------------------------


def test_donation_flags_missing_alias():
    fn = jax.jit(lambda b: lax.fori_loop(0, 4, lambda _, x: stencil.step(x), b))
    compiled = fn.lower(jax.ShapeDtypeStruct((32, 32), jnp.uint8)).compile()
    result = checks.check_donation(compiled, _cfg(mesh="none"), 32 * 32)
    assert result.status == "FAIL"
    assert any("aliased" in f.message or "aliasing" in f.message
               for f in result.errors)


def test_donation_passes_donated_engine():
    from gol_tpu.parallel import engine as engine_mod

    compiled = engine_mod.evolve_fresh.lower(
        jax.ShapeDtypeStruct((32, 32), jnp.uint8), 4
    ).compile()
    assert checks.check_donation(
        compiled, _cfg(mesh="none"), 32 * 32
    ).status == "PASS"


# -- cost --------------------------------------------------------------------


def test_cost_flags_unmodeled_extra_work():
    """Triple-stepping per generation must blow the 2× drift gate."""

    @functools.partial(jax.jit, donate_argnums=0)
    def wasteful(board):
        def body(_, b):
            for _ in range(3):  # does 3 generations of work, reports 1
                b = stencil.step(b)
            return b

        return lax.fori_loop(0, 4, body, board)

    compiled = wasteful.lower(
        jax.ShapeDtypeStruct((64, 64), jnp.uint8)
    ).compile()
    cfg = _cfg(mesh="none", cost_gate=True, schedule=(4,))
    result = checks.check_cost(compiled, cfg, None, 1)
    assert result.status == "FAIL"
    assert any("drift exceeds" in f.message for f in result.errors)


def test_cost_passes_real_dense_engine():
    from gol_tpu.parallel import engine as engine_mod

    compiled = engine_mod.evolve_fresh.lower(
        jax.ShapeDtypeStruct((64, 64), jnp.uint8), 8
    ).compile()
    cfg = _cfg(mesh="none", cost_gate=True, schedule=(8,))
    assert checks.check_cost(compiled, cfg, None, 1).status == "PASS"


def test_xla_flops_model_matches_measured_dense():
    """The roofline XLA model is exact for the depth-1 dense engine."""
    from gol_tpu.utils import roofline
    from gol_tpu.parallel import engine as engine_mod

    compiled = engine_mod.evolve_fresh.lower(
        jax.ShapeDtypeStruct((64, 64), jnp.uint8), 8
    ).compile()
    ca = compiled.cost_analysis()
    if isinstance(ca, list):
        ca = ca[0]
    model = roofline.xla_flops_model("dense", 64 * 64, 8, 1)
    assert ca["flops"] == pytest.approx(model, rel=0.05)


# -- retrace -----------------------------------------------------------------


class _RetracingRuntime:
    """A broken 'runtime' whose builder retraces for every chunk."""

    def _evolve_fn(self, steps):
        # BUG: fresh closure per call — defeats the AOT compile cache.
        fn = jax.jit(
            lambda b: lax.fori_loop(
                0, steps, lambda _, x: stencil.step(x), b
            )
        )
        return fn, (), ()


def test_retrace_flags_uncached_builder():
    cfg = _cfg(mesh="none", schedule=(8, 8, 4))
    result = checks.check_retrace(
        _RetracingRuntime(), cfg, make_board=None, execute=False
    )
    assert result.status == "FAIL"
    assert any("retrace and recompile" in f.message for f in result.errors)


def test_retrace_passes_real_runtime():
    cfg = _cfg(mesh="none", engine="dense", schedule=(6, 6, 3))
    rt = cfg.build_runtime()

    def make_board():
        return jnp.zeros((64, 64), jnp.uint8)

    result = checks.check_retrace(rt, cfg, make_board, execute=True)
    assert result.status == "PASS"


# -- report / exit-code contract --------------------------------------------


def test_report_exit_code_nonzero_on_any_violation():
    report = AnalysisReport()
    report.engines.append(
        EngineReport(
            config_name="x",
            checks=[
                CheckResult.from_findings(
                    "comm", [Finding("error", "comm", "boom")]
                )
            ],
        )
    )
    assert report.exit_code == 1
    assert "FAIL" in report.render_text()
    assert '"ok": false' in report.to_json()


def test_report_exit_code_zero_when_clean():
    report = AnalysisReport()
    report.engines.append(
        EngineReport(
            config_name="x",
            checks=[CheckResult.from_findings("comm", [])],
        )
    )
    assert report.exit_code == 0


# -- integration: the full matrix --------------------------------------------


def test_full_matrix_verifies_clean():
    """The all-engines × all-mesh-modes pass: every invariant holds."""
    report = AnalysisReport()
    for cfg in configs.default_matrix():
        report.engines.append(checks.run_config(cfg))
    failing = [e.config_name for e in report.engines if not e.ok]
    assert not failing, f"verifier flagged: {failing}\n{report.render_text()}"
    assert report.exit_code == 0
    # The matrix genuinely covers all four engines in mesh modes none+1d.
    covered = {(c.engine, c.mesh) for c in configs.default_matrix()}
    for engine in ("dense", "bitpack", "pallas", "pallas_bitpack"):
        for mesh in ("none", "1d"):
            assert (engine, mesh) in covered or (
                engine in ("pallas",) and mesh == "1d"
            )


def test_matrix_covers_every_engine_and_mode():
    covered = {(c.engine, c.mesh) for c in configs.default_matrix()}
    for engine in ("dense", "bitpack", "pallas", "pallas_bitpack"):
        assert (engine, "none") in covered
        assert (engine, "1d") in covered  # incl. the must-reject entries


def test_cli_verify_subcommand():
    from gol_tpu import cli

    rc = cli.main(["verify", "--engine", "dense", "--mesh", "none"])
    assert rc == 0


def test_cli_verify_list():
    from gol_tpu import cli

    rc = cli.main(["verify", "--list"])
    assert rc == 0


# -- halo-pipeline matrix (PR 9) ---------------------------------------------


def test_one_exchange_flags_degenerate_double_buffer():
    """A 'pipelined' loop that exchanges twice per chunk has degenerated
    to the serial form — the check must fail it."""
    from gol_tpu.analysis import halocheck
    from gol_tpu.parallel import halo

    k = 2
    phases = ((0, "rows", MESH_N),)
    step = lambda ext: stencil.step_halo_rows(ext[1:-1], ext[0], ext[-1])

    def local(blk):
        def chunk(b):
            halo.exchange_bands(b, phases, k)  # the wasted extra exchange
            bands = halo.exchange_bands(b, phases, k)
            return halo._consume_chunk(step, phases, b, bands, k)

        return lax.fori_loop(0, 3, lambda _, b: chunk(b), blk)

    jaxpr = walker.trace_jaxpr(_ring_program(local), _sharded_spec(_mesh()))
    hcfg = halocheck.HaloConfig("fixture", "dense", "1d", halo_depth=k)
    result = halocheck.check_one_exchange_per_chunk(jaxpr, hcfg, _mesh())
    assert result.status == "FAIL"
    assert any("4 in-loop ppermutes" in f.message for f in result.errors)


def test_one_exchange_passes_real_pipeline():
    from gol_tpu.analysis import halocheck
    from gol_tpu.parallel import halo

    phases = ((0, "rows", MESH_N),)
    step = lambda ext: stencil.step_halo_rows(ext[1:-1], ext[0], ext[-1])
    local = halo.pipelined_local_loop(step, phases, 12, 4)
    jaxpr = walker.trace_jaxpr(_ring_program(local), _sharded_spec(_mesh()))
    hcfg = halocheck.HaloConfig("fixture", "dense", "1d", halo_depth=4)
    assert halocheck.check_one_exchange_per_chunk(
        jaxpr, hcfg, _mesh()
    ).status == "PASS"


def test_halo_matrix_verifies_clean():
    """The full pipeline matrix: ring soundness at depth k, one exchange
    per chunk, executed equivalence, and the shallow-band teeth."""
    from gol_tpu.analysis import halocheck

    reports = halocheck.run_halo_checks()
    failing = [r.config_name for r in reports if not r.ok]
    assert not failing, f"halo matrix flagged: {failing}"
    names = {r.config_name for r in reports}
    # The matrix genuinely spans the tiers, both 2-D meshes, and 3-D.
    assert any("pallas_bitpack" in n for n in names)
    assert any("/2d/" in n for n in names)
    assert any("3d" in n for n in names)
    # The teeth ran: the dense/1d cell carries the shallow-band witness.
    teeth = [
        c
        for r in reports
        for c in r.checks
        if c.check == "shallow-band"
    ]
    assert teeth and all(c.status == "PASS" for c in teeth)
