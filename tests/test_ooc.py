"""The out-of-core streaming tier (docs/STREAMING.md) — acceptance pins.

The tier's whole claim is "a board bigger than device memory, stepped
bit-exactly through a fixed device footprint".  Pinned here:

- **out-of-core equality** — a board >= 4x a small simulated device
  budget, streamed through the full runtime dispatch, is bit-equal to
  the in-core bitpack oracle (the budget is enforced by the planner's
  footprint bound, so the device provably never held the board);
- **layout round-trip** — the host-side pack/unpack is the exact
  ``ops/bitlife`` device layout (the checkpoint and cross-tier resume
  story depends on the two never drifting);
- **transfer scales with activity** — dead bands move zero bytes, so a
  sparse pattern's ``bytes_h2d`` collapses relative to a soup on the
  same plan;
- **checkpoint/resume** — an interrupted streamed run resumes bit-equal,
  in BOTH cross-tier directions (ooc snapshot -> bitpack resume and
  back): a snapshot is a board, not a tier;
- **write-back containment** — a transient ``hostcopy.error`` retries
  and recovers (reported as degraded events), a persistent one
  surfaces: the host board is the state, shedding it is state loss;
- **observability** — ``--stats`` folds are bit-identical to the
  in-core stats programs, and the telemetry stream carries the v15
  ``ooc`` block (tests/test_telemetry_v15.py pins the schema itself).
"""

from __future__ import annotations

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from gol_tpu.models.state import Geometry
from gol_tpu.ooc import (
    OocScheduler,
    hostboard,
    pack_np,
    plan_bands,
    unpack_np,
)
from gol_tpu.ops import bitlife
from gol_tpu.ops import stats as stats_mod
from gol_tpu.resilience import degrade as degrade_mod
from gol_tpu.resilience import faults as faults_mod
from gol_tpu.runtime import GolRuntime

from tests import oracle

jax.config.update("jax_platforms", "cpu")


def _soup(h, w, seed=33, density=0.33):
    return oracle.random_board(h, w, seed=seed, density=density)


# -- the headline: bigger than the budget, still bit-exact -------------------


def test_board_4x_budget_bit_equal_to_bitpack_oracle():
    """512x64 board under a 1 KiB simulated budget (the packed board is
    4 KiB, >= 4x the rotation footprint the planner fits under the
    budget): streamed == in-core bitpack over a multi-chunk schedule."""
    h, w, k = 512, 64, 3
    budget = 1024
    plan = plan_bands(h, w, k, budget_bytes=budget)
    assert plan.device_bytes() <= budget
    assert plan.board_bytes >= 4 * plan.device_bytes()
    assert plan.num_bands >= 4  # genuinely banded, not one tall slab

    board = _soup(h, w)
    sched = OocScheduler(plan)
    sched.load_dense(board)
    gen = 0
    for take in (7, 5, 4):  # remainder sweeps included (k=3)
        sched.run_chunk(take, gen)
        gen += take
    ref = np.asarray(bitlife.evolve_dense_io(jnp.asarray(board), gen))
    np.testing.assert_array_equal(sched.dense(), ref)


def test_runtime_dispatch_matches_bitpack_engine():
    kw = dict(geometry=Geometry(size=64, num_ranks=2))
    _, ref = GolRuntime(**kw, engine="bitpack").run(pattern=7, iterations=24)
    rt = GolRuntime(**kw, engine="ooc", halo_depth=3, ooc_band_rows=13,
                    ooc_budget_mb=0)
    _, got = rt.run(pattern=7, iterations=24)
    np.testing.assert_array_equal(np.asarray(got.board), np.asarray(ref.board))
    assert rt.last_ooc and all("overlap_fraction" in o for o in rt.last_ooc)


# -- layout: the host pack IS the device pack --------------------------------


def test_host_pack_unpack_matches_device_layout():
    board = _soup(37, 96, seed=5)
    packed = pack_np(board)
    dev = np.asarray(bitlife.pack(jnp.asarray(board)))
    np.testing.assert_array_equal(packed, dev)
    np.testing.assert_array_equal(unpack_np(packed, 96), board)
    np.testing.assert_array_equal(
        unpack_np(packed, 96),
        np.asarray(bitlife.unpack(jnp.asarray(packed))),
    )
    assert hostboard.popcount_np(packed) == int(board.sum())


# -- transfer scales with activity, not area ---------------------------------


def test_dead_bands_move_zero_bytes():
    h, w, k = 320, 64, 2
    plan = plan_bands(h, w, k, band_rows=10)

    def h2d(board, skip=True):
        sched = OocScheduler(plan, skip_dead=skip)
        sched.load_dense(board)
        rep = sched.run_chunk(4, 0)
        return rep, sched

    soup_rep, _ = h2d(_soup(h, w))
    gun = np.zeros((h, w), dtype=np.uint8)
    gun[4:13, 4:40] = _soup(9, 36, seed=1, density=0.4)  # one active corner
    gun_rep, gun_sched = h2d(gun)
    assert soup_rep["skipped_bands"] == 0
    assert gun_rep["skipped_bands"] > 0
    # The sparse run's transfer is a small fraction of the soup's.
    assert gun_rep["bytes_h2d"] < soup_rep["bytes_h2d"] / 4
    assert gun_rep["bytes_d2h"] < soup_rep["bytes_d2h"] / 4
    # And skipping never changed the answer.
    ref, _ = h2d(gun, skip=False)
    np.testing.assert_array_equal(
        gun_sched.dense(),
        np.asarray(bitlife.evolve_dense_io(jnp.asarray(gun), 4)),
    )


# -- checkpoint/resume: a snapshot is a board, not a tier --------------------


def test_checkpoint_resume_cross_tier_both_directions(tmp_path):
    kw = dict(geometry=Geometry(size=64, num_ranks=2))
    _, ref = GolRuntime(**kw, engine="bitpack").run(pattern=7, iterations=12)

    from gol_tpu import resilience

    # ooc writes the snapshot; bitpack resumes it.
    d1 = tmp_path / "ooc_ck"
    GolRuntime(
        **kw, engine="ooc", halo_depth=3, ooc_band_rows=13, ooc_budget_mb=0,
        checkpoint_every=6, checkpoint_dir=str(d1),
    ).run(pattern=7, iterations=6)
    path, info = resilience.resolve_auto_resume(str(d1))
    assert info["generation"] == 6
    _, got = GolRuntime(**kw, engine="bitpack").run(
        pattern=7, iterations=6, resume=path
    )
    np.testing.assert_array_equal(np.asarray(got.board), np.asarray(ref.board))

    # bitpack writes the snapshot; ooc resumes it.
    d2 = tmp_path / "bp_ck"
    GolRuntime(
        **kw, engine="bitpack", checkpoint_every=6, checkpoint_dir=str(d2),
    ).run(pattern=7, iterations=6)
    path2, info2 = resilience.resolve_auto_resume(str(d2))
    assert info2["generation"] == 6
    _, got2 = GolRuntime(
        **kw, engine="ooc", halo_depth=3, ooc_band_rows=13, ooc_budget_mb=0,
    ).run(pattern=7, iterations=6, resume=path2)
    np.testing.assert_array_equal(
        np.asarray(got2.board), np.asarray(ref.board)
    )


# -- write-back containment --------------------------------------------------


def _armed(count):
    return faults_mod.FaultPlan(
        [faults_mod.FaultSpec(site="hostcopy.error", count=count)]
    )


def test_transient_hostcopy_error_retries_and_recovers():
    h, w = 64, 32
    plan = plan_bands(h, w, 1, band_rows=8)
    board = _soup(h, w, seed=9)
    sched = OocScheduler(plan, skip_dead=False)
    sched.load_dense(board)
    degrade_mod.drain_reports()
    faults_mod.install(_armed(count=2))
    try:
        sched.run_chunk(3, 0)
    finally:
        faults_mod.clear()
    np.testing.assert_array_equal(
        sched.dense(),
        np.asarray(bitlife.evolve_dense_io(jnp.asarray(board), 3)),
    )
    reports = degrade_mod.drain_reports()
    retried = [r for r in reports if r["resource"] == "hostcopy"
               and r["action"] == "retried"]
    assert len(retried) == 2  # one per injected EIO, then recovery


def test_persistent_hostcopy_error_surfaces():
    h, w = 64, 32
    plan = plan_bands(h, w, 1, band_rows=8)
    sched = OocScheduler(plan, skip_dead=False)
    sched.load_dense(_soup(h, w, seed=9))
    faults_mod.install(_armed(count=-1))
    try:
        with pytest.raises(OSError, match="injected host copy-back"):
            sched.run_chunk(1, 0)
    finally:
        faults_mod.clear()
        degrade_mod.drain_reports()


# -- observability: stats folds and the planner's refusals -------------------


def test_ooc_stats_fold_matches_packed_chunk_stats():
    h, w, band = 96, 64, 3
    prev_d, new_d = _soup(h, w, seed=2), _soup(h, w, seed=3)
    plan = plan_bands(h, w, 3, band_rows=12)
    got = stats_mod.ooc_chunk_stats_np(
        pack_np(prev_d), pack_np(new_d), plan.bands, w, band
    )
    want = stats_mod.stats_values(
        stats_mod.packed_chunk_stats(
            jnp.asarray(prev_d), jnp.asarray(new_d), band
        )
    )
    assert got == want


def test_runtime_stats_match_bitpack_engine():
    kw = dict(geometry=Geometry(size=64, num_ranks=2), stats=True)
    rt_bp = GolRuntime(**kw, engine="bitpack")
    rt_bp.run(pattern=7, iterations=12)
    rt = GolRuntime(**kw, engine="ooc", ooc_band_rows=13, ooc_budget_mb=0)
    rt.run(pattern=7, iterations=12)
    assert rt.last_stats == rt_bp.last_stats


@pytest.mark.parametrize("kwargs,match", [
    (dict(height=64, width=64, depth=0), "depth must be >= 1"),
    (dict(height=5, width=64, depth=3), "too small for ooc depth"),
    (dict(height=64, width=64, depth=4, band_rows=2), "band height 2 < depth"),
    (dict(height=64, width=64, depth=1), "needs a device budget"),
    (dict(height=4096, width=4096, depth=1, budget_bytes=64),
     "exceeds device budget"),
])
def test_planner_refusals_pin_their_message(kwargs, match):
    with pytest.raises(ValueError, match=match):
        plan_bands(**kwargs)


def test_telemetry_stream_carries_ooc_blocks(tmp_path):
    import json

    rt = GolRuntime(
        geometry=Geometry(size=64, num_ranks=2), engine="ooc",
        ooc_band_rows=13, ooc_budget_mb=0,
        telemetry_dir=str(tmp_path), run_id="oocpin",
    )
    rt.run(pattern=7, iterations=10)
    recs = [json.loads(ln) for ln in open(tmp_path / "oocpin.rank0.jsonl")]
    chunks = [r for r in recs if r["event"] == "chunk"]
    assert chunks and all("ooc" in c for c in chunks)
    assert all(
        c["ooc"]["bands"] == rt._ooc_plan.num_bands for c in chunks
    )
