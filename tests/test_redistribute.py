"""The device-side resharding collective (gol_tpu/parallel/redistribute).

The acceptance surface (docs/RESILIENCE.md, "Live elasticity"):

- **the pin** — :func:`device_reshard` executing the SAME validated
  ``ReshardPlan`` as the host path is **bit-equal** to
  ``load_resharded`` on every none/1d/2d grow+shrink pair, under the
  destination mesh's canonical sharding, from a real mid-run snapshot;
- **teeth** — broken move tables (overlap, gap), wrong-shape plans and
  wrong-layout plans handed to the collective explicitly are rejected
  before any device program is built;
- **worlds stack** — :func:`device_reshard_worlds` moves a ``[B, H, W]``
  bucket-group stack between worlds meshes bit-exactly (the serve
  tier's live-elasticity hook);
- **schedule soundness** — the compiled branch tables cover every
  destination cell exactly once;
- **trace identity** — arming the fault plane and the health plane
  leaves the lowered exchange program byte-identical (both are
  host-side by construction).
"""

from __future__ import annotations

import dataclasses
import json

import numpy as np
import pytest

import jax

from gol_tpu.models.state import Geometry
from gol_tpu.parallel import mesh as mesh_mod
from gol_tpu.parallel import redistribute as rd
from gol_tpu.resilience import faults as faults_mod
from gol_tpu.resilience import reshard as rs
from gol_tpu.runtime import GolRuntime
from gol_tpu.utils import checkpoint as ckpt

jax.config.update("jax_platforms", "cpu")

# 96 columns = 3 packed words: the 2-D column seam at 48 lands mid-word,
# so the pin exercises the in-graph seam repack, not just row splits.
SIZE = 96
MID = 8

PAIRS = [
    ("none", "1d"),
    ("none", "2d"),
    ("1d", "2d"),
    ("2d", "1d"),
    ("1d", "none"),
    ("2d", "none"),
]


def _mesh_for(kind):
    if kind == "none":
        return None
    if kind == "1d":
        return mesh_mod.make_mesh_1d(8)
    return mesh_mod.make_mesh_2d((4, 2))


@pytest.fixture(scope="module")
def snapshots(tmp_path_factory):
    """src kind -> (generation-MID snapshot path, mid-run board)."""
    out = {}
    for kind in ("none", "1d", "2d"):
        d = str(tmp_path_factory.mktemp(f"src_{kind}"))
        rt = GolRuntime(
            geometry=Geometry(size=SIZE, num_ranks=1),
            engine="dense",
            mesh=_mesh_for(kind),
            checkpoint_every=MID,
            checkpoint_dir=d,
            sharded_snapshots=kind != "none",
        )
        _, st = rt.run(pattern=6, iterations=MID)
        path = (
            ckpt.checkpoint_path(d, MID)
            if kind == "none"
            else ckpt.sharded_checkpoint_path(d, MID)
        )
        out[kind] = (path, np.asarray(st.board))
    return out


def _place(board, src_mesh):
    arr = jax.numpy.asarray(board)
    if src_mesh is None:
        return jax.device_put(arr)
    return mesh_mod.shard_board(arr, src_mesh)


# -- the pin: device collective == host load_resharded ------------------------


@pytest.mark.parametrize("src,dst", PAIRS, ids=[f"{s}-to-{d}" for s, d in PAIRS])
def test_device_reshard_bit_equal_to_host_path(snapshots, src, dst):
    snap, board = snapshots[src]
    src_mesh, dst_mesh = _mesh_for(src), _mesh_for(dst)
    host, source, plan = rs.load_resharded(snap, dst_mesh)
    assert np.array_equal(np.asarray(host), board)  # snapshot is the mid board
    out = rd.device_reshard(_place(board, src_mesh), src_mesh, dst_mesh, plan=plan)
    assert np.array_equal(np.asarray(out), np.asarray(host))
    if dst_mesh is not None:
        assert out.sharding.is_equivalent_to(
            mesh_mod.board_sharding(dst_mesh), out.ndim
        )


def test_device_reshard_default_plan_matches_explicit(snapshots):
    """Omitting the plan plans the same move table the host path builds."""
    _, board = snapshots["1d"]
    src_mesh, dst_mesh = _mesh_for("1d"), _mesh_for("2d")
    placed = _place(board, src_mesh)
    out = rd.device_reshard(placed, src_mesh, dst_mesh)
    assert np.array_equal(np.asarray(out), board)


# -- schedule soundness -------------------------------------------------------


@pytest.mark.parametrize("src,dst", PAIRS, ids=[f"{s}-to-{d}" for s, d in PAIRS])
def test_branch_tables_cover_every_cell_exactly_once(src, dst):
    src_mesh, dst_mesh = _mesh_for(src), _mesh_for(dst)
    src_l = rs.MeshLayout.from_mesh(src_mesh)
    dst_l = rs.MeshLayout.from_mesh(dst_mesh)
    shape = (SIZE, SIZE)
    plan = rs.plan_reshard(shape, src_l.boxes(shape), src_l, dst_l)
    sched = rd.board_schedule(plan, src_mesh, dst_mesh)
    canvas = rd.schedule_coverage(sched)
    assert (canvas == 1).all()


# -- teeth --------------------------------------------------------------------


def test_broken_move_tables_rejected_before_any_program():
    src_mesh, dst_mesh = _mesh_for("1d"), _mesh_for("2d")
    src_l = rs.MeshLayout.from_mesh(src_mesh)
    dst_l = rs.MeshLayout.from_mesh(dst_mesh)
    shape = (SIZE, SIZE)
    plan = rs.plan_reshard(shape, src_l.boxes(shape), src_l, dst_l)
    dbox, srcs = plan.moves[-1]
    placed = _place(np.zeros(shape, np.uint8), src_mesh)
    overlapping = dataclasses.replace(
        plan, moves=plan.moves[:-1] + ((dbox, srcs + (srcs[0],)),)
    )
    gapped = dataclasses.replace(plan, moves=plan.moves[:-1] + ((dbox, srcs[:-1]),))
    for bad in (overlapping, gapped):
        with pytest.raises((rs.ReshardError, rs.ReshardPlanError)):
            rd.device_reshard(placed, src_mesh, dst_mesh, plan=bad)


def test_wrong_shape_and_wrong_layout_plans_rejected():
    src_mesh, dst_mesh = _mesh_for("1d"), _mesh_for("2d")
    src_l = rs.MeshLayout.from_mesh(src_mesh)
    dst_l = rs.MeshLayout.from_mesh(dst_mesh)
    good = rs.plan_reshard(
        (SIZE, SIZE), src_l.boxes((SIZE, SIZE)), src_l, dst_l
    )
    placed = _place(np.zeros((SIZE, SIZE), np.uint8), src_mesh)
    # a plan for a different board size
    small = rs.plan_reshard(
        (SIZE // 2, SIZE), src_l.boxes((SIZE // 2, SIZE)), src_l, dst_l
    )
    with pytest.raises(rs.ReshardError):
        rd.device_reshard(placed, src_mesh, dst_mesh, plan=small)
    # a plan whose layouts do not match the meshes it is handed
    with pytest.raises(rs.ReshardError):
        rd.device_reshard(placed, src_mesh, None, plan=good)


# -- worlds stack (the serve live-elasticity hook) ----------------------------


WORLDS_PAIRS = [(1, 4), (4, 1), (2, 8), (8, 2), (2, 4), (4, 2)]


@pytest.mark.parametrize(
    "n_src,n_dst", WORLDS_PAIRS, ids=[f"{a}-to-{b}" for a, b in WORLDS_PAIRS]
)
def test_worlds_stack_bit_equal_across_mesh_sizes(n_src, n_dst):
    from gol_tpu.batch import engines as batch_engines

    rng = np.random.default_rng(n_src * 16 + n_dst)
    stack = (rng.random((8, 16, 64)) < 0.5).astype(np.uint8)

    def mesh_of(n):
        return None if n == 1 else batch_engines.make_batch_mesh(n)

    src_mesh, dst_mesh = mesh_of(n_src), mesh_of(n_dst)
    arr = jax.numpy.asarray(stack)
    placed = (
        jax.device_put(arr, batch_engines.batch_sharding(src_mesh))
        if src_mesh is not None
        else jax.device_put(arr)
    )
    out = rd.device_reshard_worlds(placed, src_mesh, dst_mesh)
    assert np.array_equal(np.asarray(out), stack)
    if dst_mesh is not None:
        assert out.sharding.is_equivalent_to(
            batch_engines.batch_sharding(dst_mesh), out.ndim
        )


def test_worlds_plan_batch_mismatch_rejected():
    from gol_tpu.batch import engines as batch_engines

    stack = jax.numpy.zeros((8, 16, 64), jax.numpy.uint8)
    src_mesh = batch_engines.make_batch_mesh(2)
    dst_mesh = batch_engines.make_batch_mesh(4)
    placed = jax.device_put(stack, batch_engines.batch_sharding(src_mesh))
    wrong = rd.plan_worlds(4, 2, 4)  # a 4-world table for an 8-world stack
    with pytest.raises(rs.ReshardError):
        rd.device_reshard_worlds(placed, src_mesh, dst_mesh, plan=wrong)


# -- trace identity: the planes never reach the compiled exchange -------------


def test_exchange_trace_identical_with_planes_armed():
    src_mesh, dst_mesh = _mesh_for("1d"), _mesh_for("2d")
    src_l = rs.MeshLayout.from_mesh(src_mesh)
    dst_l = rs.MeshLayout.from_mesh(dst_mesh)
    shape = (SIZE, SIZE)
    plan = rs.plan_reshard(shape, src_l.boxes(shape), src_l, dst_l)

    rd._board_program.cache_clear()
    disarmed = rd.lowered_exchange_text(plan, src_mesh, dst_mesh)
    try:
        faults_mod.install(
            faults_mod.FaultPlan.loads(
                json.dumps(
                    {
                        "faults": [
                            {"site": "device.loss", "at": 4, "device": 1},
                            {"site": "rank.slowdown", "at": 2,
                             "delay_s": 5.0},
                        ]
                    }
                )
            )
        )
        from gol_tpu.resilience.health import HealthMonitor

        mon = HealthMonitor(8)
        mon.heartbeat(2, 0.05)
        mon.poll(4)
        rd._board_program.cache_clear()
        armed = rd.lowered_exchange_text(plan, src_mesh, dst_mesh)
    finally:
        faults_mod.clear()
        rd._board_program.cache_clear()
    assert armed == disarmed
