"""PR 9: pipelined depth-k halo exchange — bit-identity + trace pins.

Every new chunk form (depth-k overlap split, cross-chunk pipelined
double buffer) must be bit-identical to the explicit depth-1 path across
tiers × meshes, including remainder chunks, 2-D corner crossings, the
lane-folded narrow-shard Pallas form, and the 3-D packed ring — and the
explicit paths themselves must be untouched (jaxpr byte-identity when
the knob is off; the depth-1 1-D packed overlap keeps its hand-written
program).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gol_tpu.parallel import mesh as mesh_mod
from gol_tpu.parallel import packed, sharded

from tests import oracle

jax.config.update("jax_platforms", "cpu")


def _mesh(kind):
    if kind == "1d":
        return mesh_mod.make_mesh_1d(4, devices=jax.devices()[:4])
    return mesh_mod.make_mesh_2d((2, 2), devices=jax.devices()[:4])


def _place(board, mesh):
    return mesh_mod.place_private(
        jnp.asarray(board), mesh_mod.board_sharding(mesh)
    )


# -- dense tier --------------------------------------------------------------


@pytest.mark.parametrize("mesh_kind", ["1d", "2d"])
@pytest.mark.parametrize("mode", ["overlap", "pipeline"])
@pytest.mark.parametrize(
    "k,steps", [(2, 8), (4, 12), (4, 11), (3, 2)]
)  # incl. remainder chunks and steps < k
def test_dense_deep_modes_match_explicit_depth1(mesh_kind, mode, k, steps):
    board = oracle.random_board(32, 32, seed=k * 100 + steps)
    mesh = _mesh(mesh_kind)
    ref = np.asarray(
        sharded.compiled_evolve(mesh, steps, "explicit", 1)(
            _place(board, mesh)
        )
    )
    np.testing.assert_array_equal(ref, oracle.run_torus(board, steps))
    got = np.asarray(
        sharded.compiled_evolve(mesh, steps, mode, k)(_place(board, mesh))
    )
    np.testing.assert_array_equal(got, ref)


def test_dense_pipeline_glider_corner_crossing():
    """A glider through the 2×2 corner: the pipelined band's corner
    two-hop (phase-i operands extended with earlier phases' NEW bands)
    must deliver the diagonal neighbors one chunk ahead."""
    board = np.zeros((16, 16), np.uint8)
    board[6:9, 6:9] = np.array(
        [[0, 1, 0], [0, 0, 1], [1, 1, 1]], np.uint8
    )
    mesh = _mesh("2d")
    got = np.asarray(
        sharded.compiled_evolve(mesh, 12, "pipeline", 2)(_place(board, mesh))
    )
    np.testing.assert_array_equal(got, oracle.run_torus(board, 12))
    assert got.sum() == 5


# -- bitpack tier ------------------------------------------------------------


@pytest.mark.parametrize("mesh_kind", ["1d", "2d"])
@pytest.mark.parametrize("mode", ["overlap", "pipeline"])
@pytest.mark.parametrize("k,steps", [(2, 8), (4, 11)])
def test_packed_deep_modes_match_oracle(mesh_kind, mode, k, steps):
    # 4 words per shard column on the 2-D mesh (256 // 2 // 32) — the
    # word axis ships k word-columns, so k=4 needs them all.
    board = oracle.random_board(128, 256, seed=k + steps)
    mesh = _mesh(mesh_kind)
    got = np.asarray(
        packed.compiled_evolve_packed(mesh, steps, k, mode=mode)(
            _place(board, mesh)
        )
    )
    np.testing.assert_array_equal(got, oracle.run_torus(board, steps))


def test_packed_depth1_overlap_keeps_handwritten_program():
    """Depth-1 1-D overlap must still route to the hand-written packed
    overlap program — byte-identical to every prior round."""
    from gol_tpu.models.state import Geometry
    from gol_tpu.runtime import GolRuntime

    rt = GolRuntime(
        geometry=Geometry(size=64, num_ranks=1),
        engine="bitpack",
        mesh=mesh_mod.make_mesh_1d(4),
        shard_mode="overlap",
    )
    fn, _, _ = rt._evolve_fn(4)
    assert fn is packed.compiled_evolve_packed_overlap(rt.mesh, 4)


def test_explicit_jaxpr_identical_with_mode_knob_off():
    """Trace stability: the explicit program is byte-identical whether
    built through the default or the explicit `mode` argument, and
    building the deep forms does not perturb it."""
    from gol_tpu.analysis import walker

    mesh = _mesh("1d")
    spec = jax.ShapeDtypeStruct(
        (64, 64), jnp.uint8, sharding=mesh_mod.board_sharding(mesh)
    )

    def explicit_jaxprs():
        return (
            str(walker.trace_jaxpr(
                packed.compiled_evolve_packed(mesh, 6, 2), spec
            )),
            str(walker.trace_jaxpr(
                sharded.compiled_evolve(mesh, 6, "explicit", 2), spec
            )),
        )

    before = explicit_jaxprs()
    assert before == (
        str(walker.trace_jaxpr(
            packed.compiled_evolve_packed(mesh, 6, 2, mode="explicit"), spec
        )),
        str(walker.trace_jaxpr(
            sharded.compiled_evolve(mesh, 6, "explicit", 2), spec
        )),
    )
    # Building + running the deep forms must leave them untouched.
    board = oracle.random_board(64, 64, seed=9)
    packed.compiled_evolve_packed(mesh, 6, 2, mode="pipeline")(
        _place(board, mesh)
    )
    sharded.compiled_evolve(mesh, 6, "overlap", 2)(_place(board, mesh))
    assert explicit_jaxprs() == before


# -- sharded Pallas tier (interpret mode on CPU) -----------------------------


@pytest.mark.parametrize("steps", [16, 19])  # incl. the consume-only tail
def test_pallas_pipeline_1d_matches_oracle(steps):
    board = oracle.random_board(128, 128, seed=steps)
    mesh = _mesh("1d")  # shard 32 rows >= 2*8 + 8
    got = np.asarray(
        packed.compiled_evolve_packed_pallas(mesh, steps, pipeline=True)(
            _place(board, mesh)
        )
    )
    np.testing.assert_array_equal(got, oracle.run_torus(board, steps))


def test_pallas_pipeline_2d_matches_oracle():
    board = oracle.random_board(128, 128, seed=77)
    mesh = _mesh("2d")  # shard 64x64: 2 words wide, edge-strip repair
    got = np.asarray(
        packed.compiled_evolve_packed_pallas(mesh, 16, pipeline=True)(
            _place(board, mesh)
        )
    )
    np.testing.assert_array_equal(got, oracle.run_torus(board, 16))


def test_pallas_pipeline_folded_matches_oracle():
    """Narrow shards run the pipelined loop lane-folded: the carried ring
    ghosts ride unfolded [k, nw] while the group seams' band parts are
    lane-shifted slices of the folded block itself."""
    board = oracle.random_board(1024, 1024, seed=5, density=0.3)
    mesh = mesh_mod.make_mesh_1d(8)  # shard 128x1024: nw=32, fold=4
    got = np.asarray(
        packed.compiled_evolve_packed_pallas(mesh, 16, pipeline=True)(
            _place(board, mesh)
        )
    )
    np.testing.assert_array_equal(got, oracle.run_torus(board, 16))


def test_pallas_overlap_and_pipeline_are_exclusive():
    with pytest.raises(ValueError, match="pick one"):
        packed.compiled_evolve_packed_pallas(
            _mesh("1d"), 8, overlap=True, pipeline=True
        )


# -- 3-D packed ring ---------------------------------------------------------


@pytest.mark.parametrize("mode", ["overlap", "pipeline"])
@pytest.mark.parametrize("steps", [6, 7])  # 7: remainder chunk at k=2
def test_3d_packed_deep_modes_match_explicit(mode, steps):
    from gol_tpu.ops import life3d
    from gol_tpu.parallel import sharded3d

    vol = np.random.default_rng(steps).integers(0, 2, (64, 64, 64), np.uint8)
    mesh = mesh_mod.make_mesh_3d((2, 2, 1), devices=jax.devices()[:4])
    ref = np.asarray(
        sharded3d.evolve_sharded3d_packed(jnp.asarray(vol), steps, mesh)
    )
    np.testing.assert_array_equal(
        ref, np.asarray(life3d.run3d(jnp.asarray(vol), steps))
    )
    got = np.asarray(
        sharded3d.evolve_sharded3d_packed(
            jnp.asarray(vol), steps, mesh, halo_depth=2, mode=mode
        )
    )
    np.testing.assert_array_equal(got, ref)


# -- runtime end to end ------------------------------------------------------


@pytest.mark.parametrize("engine", ["dense", "bitpack"])
def test_runtime_pipeline_end_to_end(engine):
    from gol_tpu.models import patterns
    from gol_tpu.models.state import Geometry
    from gol_tpu.runtime import GolRuntime

    rt = GolRuntime(
        geometry=Geometry(size=64, num_ranks=1),
        engine=engine,
        mesh=mesh_mod.make_mesh_1d(4),
        shard_mode="pipeline",
        halo_depth=4,
    )
    _, state = rt.run(pattern=5, iterations=10)
    board0 = patterns.init_global(5, 64, 1)
    np.testing.assert_array_equal(
        np.asarray(state.board), oracle.run_torus(board0, 10)
    )


def test_runtime_pipeline_depth_exceeding_shard_raises():
    """Seam case: k greater than the shard extent must be rejected — the
    ghost shell would need cells from beyond the ring neighbor."""
    from gol_tpu.models.state import Geometry
    from gol_tpu.runtime import GolRuntime

    with pytest.raises(ValueError, match="exceeds the shard extent"):
        GolRuntime(
            geometry=Geometry(size=64, num_ranks=1),
            engine="dense",
            mesh=mesh_mod.make_mesh_1d(8),  # 8-row shards
            shard_mode="pipeline",
            halo_depth=9,
        )
