"""Trivially-correct NumPy oracles for Game of Life, independent of JAX.

Two semantics are modeled:

- `step_torus`: correct torus GoL (both axes periodic) — what the new
  framework's default engine implements.
- `simulate_reference`: the reference program's *as-implemented* semantics,
  including bug B1 (halo send buffers filled once at t=0 and never refreshed,
  gol-with-cuda.cu:40-47 vs the loop gol-main.c:94-116): each rank's block
  evolves with its top/bottom ghost rows frozen at the neighbors' t=0
  boundary rows, while columns wrap mod W locally.  Used to validate the
  compat engine bit-for-bit.

Written with explicit per-cell loops over shifted views kept deliberately
different in structure from the JAX implementation (8 explicit shifts here
vs. separable roll-sums there) so a shared bug is unlikely.
"""

from __future__ import annotations

import numpy as np


def random_board(
    h: int, w: int, seed: int, density: float = 0.4
) -> np.ndarray:
    """Shared random 0/1 uint8 board fixture used across the test suite."""
    rng = np.random.default_rng(seed)
    return (rng.random((h, w)) < density).astype(np.uint8)


def _neighbors_torus(board: np.ndarray) -> np.ndarray:
    n = np.zeros(board.shape, dtype=np.int32)
    for dy in (-1, 0, 1):
        for dx in (-1, 0, 1):
            if dy == 0 and dx == 0:
                continue
            n += np.roll(np.roll(board, dy, axis=0), dx, axis=1)
    return n


def _apply_rule(board: np.ndarray, n: np.ndarray) -> np.ndarray:
    return ((n == 3) | ((board == 1) & (n == 2))).astype(np.uint8)


def step_torus(board: np.ndarray) -> np.ndarray:
    """One generation, both axes periodic (correct global semantics)."""
    return _apply_rule(board, _neighbors_torus(board))


def run_torus(board: np.ndarray, steps: int) -> np.ndarray:
    for _ in range(steps):
        board = step_torus(board)
    return board


def random_volume(
    d: int, h: int, w: int, seed: int, density: float = 0.3
) -> np.ndarray:
    rng = np.random.default_rng(seed)
    return (rng.random((d, h, w)) < density).astype(np.uint8)


def step_torus3d(
    vol: np.ndarray, birth=frozenset({5}), survive=frozenset({4, 5})
) -> np.ndarray:
    """One 3-D generation, all axes periodic; 26 explicit shifted adds
    (deliberately non-separable, unlike the JAX implementation)."""
    n = np.zeros(vol.shape, dtype=np.int32)
    for dz in (-1, 0, 1):
        for dy in (-1, 0, 1):
            for dx in (-1, 0, 1):
                if dz == dy == dx == 0:
                    continue
                n += np.roll(vol, (dz, dy, dx), axis=(0, 1, 2))
    alive = vol == 1
    born = np.isin(n, sorted(birth)) & ~alive
    stay = np.isin(n, sorted(survive)) & alive
    return (born | stay).astype(np.uint8)


def run_torus3d(vol: np.ndarray, steps: int, **rule) -> np.ndarray:
    for _ in range(steps):
        vol = step_torus3d(vol, **rule)
    return vol


def _step_block_frozen_halos(
    block: np.ndarray, top: np.ndarray, bottom: np.ndarray
) -> np.ndarray:
    """One step of a local block with given ghost rows; columns wrap mod W."""
    ext = np.concatenate([top[None, :], block, bottom[None, :]], axis=0)
    n = np.zeros(block.shape, dtype=np.int32)
    h = block.shape[0]
    for dy in (-1, 0, 1):
        rows = ext[1 + dy : 1 + dy + h]
        for dx in (-1, 0, 1):
            if dy == 0 and dx == 0:
                continue
            n += np.roll(rows, dx, axis=1)
    return _apply_rule(block, n)


def simulate_reference(
    global_board: np.ndarray, num_ranks: int, steps: int
) -> np.ndarray:
    """Evolve with the reference's as-implemented stale-halo semantics (B1).

    Every step, rank r receives rank (r-1)%n's *t=0* last row and rank
    (r+1)%n's *t=0* first row (the send buffers are never refreshed), so the
    blocks are mutually independent after t=0.
    """
    height = global_board.shape[0]
    assert height % num_ranks == 0
    s = height // num_ranks
    blocks = [global_board[r * s : (r + 1) * s].copy() for r in range(num_ranks)]
    top0 = [blocks[(r - 1) % num_ranks][-1].copy() for r in range(num_ranks)]
    bot0 = [blocks[(r + 1) % num_ranks][0].copy() for r in range(num_ranks)]
    for _ in range(steps):
        blocks = [
            _step_block_frozen_halos(blocks[r], top0[r], bot0[r])
            for r in range(num_ranks)
        ]
    return np.concatenate(blocks, axis=0)
