"""The chaos matrix runner (python -m gol_tpu.resilience chaos).

The fast tests pin the runner's own behavior — plan loading, legality
skips being visible, a detection miss reading as FAIL — on a small
sub-grid; the full committed scenario × tier × mesh grid (the
acceptance surface: every cell detected + recovered byte-identically,
illegal cells visibly skipped) runs under ``-m slow``.
"""

from __future__ import annotations

import io

import jax
import pytest

from gol_tpu import compat
from gol_tpu.resilience import chaos, faults

jax.config.update("jax_platforms", "cpu")
compat.set_cpu_device_count(8)


@pytest.fixture(autouse=True)
def _clean_plane():
    faults.clear()
    yield
    faults.clear()


def test_committed_plan_loads_and_covers_the_grid():
    plan = chaos.ChaosPlan.load(chaos.DEFAULT_PLAN_PATH)
    assert set(plan.tiers) == set(chaos.TIERS)
    assert set(plan.meshes) == set(chaos.MESHES)
    names = {s.name for s in plan.scenarios}
    # The fault-site catalog is represented: SDC both flavors, the
    # checkpoint write sites, rot, telemetry, and a process stall.
    assert {
        "sdc-oob", "sdc-inrange", "torn-write", "ckpt-io", "disk-full",
        "snapshot-rot", "telemetry-io", "rank-stall",
    } <= names
    sites = {f["site"] for s in plan.scenarios for f in s.faults}
    assert {
        "board.bitflip", "checkpoint.torn_tmp", "checkpoint.io_error",
        "checkpoint.disk_full", "snapshot.bitflip",
        "telemetry.write_error", "rank.stall",
    } <= sites


def test_bad_scenario_kind_rejected():
    with pytest.raises(ValueError, match="unknown kind"):
        chaos.Scenario(name="x", kind="explode", faults=())


def test_illegal_cells_are_visibly_skipped():
    plan = chaos.ChaosPlan(
        scenarios=(
            chaos.Scenario(
                name="sdc",
                kind="guard",
                faults=(
                    {"site": "board.bitflip", "at": 4, "row": 3,
                     "col": 3, "value": 165},
                ),
            ),
        ),
        tiers=("pallas", "batch"),
        meshes=("1d", "2d"),
        size=64,
        iterations=4,
    )
    out = io.StringIO()
    results = chaos.run_matrix(plan, out=out)
    skips = [r for r in results if r.status == "skip"]
    assert any(
        r.tier == "pallas" and "no sharded path" in r.reason for r in skips
    )
    assert any(
        r.tier == "batch" and r.mesh == "2d" for r in skips
    )
    text = out.getvalue()
    assert "[SKIP]" in text and "no sharded path" in text


def test_small_grid_detects_and_recovers():
    """One guard cell + one contain cell end to end through the runner."""
    plan = chaos.ChaosPlan(
        scenarios=(
            chaos.Scenario(
                name="sdc",
                kind="guard",
                faults=(
                    {"site": "board.bitflip", "at": 6, "row": 3,
                     "col": 3, "value": 165},
                ),
            ),
            chaos.Scenario(
                name="ckpt-io",
                kind="contain",
                faults=(
                    {"site": "checkpoint.io_error", "at": 2, "count": 1},
                ),
            ),
        ),
        tiers=("bitpack",),
        meshes=("none",),
        size=64,
        iterations=6,
    )
    results = chaos.run_matrix(plan, out=io.StringIO())
    assert [r.status for r in results] == ["ok", "ok"], [
        (r.label, r.reason) for r in results
    ]


def test_a_missed_detection_reads_as_fail():
    """An in-range flip with a PLAIN guard (no redundancy) must be
    reported as a FAIL by the matrix — the runner's teeth."""
    plan = chaos.ChaosPlan(
        scenarios=(
            chaos.Scenario(
                name="sdc-inrange-noredundant",
                kind="guard",
                redundant=False,  # deliberately too weak for the fault
                faults=(
                    {"site": "board.bitflip", "at": 6, "row": 3,
                     "col": 3, "value": -1},
                ),
            ),
        ),
        tiers=("dense",),
        meshes=("none",),
        size=64,
        iterations=6,
    )
    results = chaos.run_matrix(plan, out=io.StringIO())
    assert results[0].status == "fail"
    assert "not detected" in results[0].reason


@pytest.mark.slow
def test_full_committed_matrix_is_green():
    """The acceptance grid: every scenario × tier × mesh cell of the
    committed plan either passes (detected + byte-identical recovery)
    or is a visible legality skip — zero failures."""
    plan = chaos.ChaosPlan.load(chaos.DEFAULT_PLAN_PATH)
    out = io.StringIO()
    results = chaos.run_matrix(plan, out=out)
    fails = [r for r in results if r.status == "fail"]
    assert not fails, "\n" + "\n".join(
        f"{r.label}: {r.reason}" for r in fails
    ) + "\n" + out.getvalue()
    assert sum(1 for r in results if r.status == "ok") >= 60
