"""The (engine, shard-mode, halo-depth) legality matrix — message pins.

``gol_tpu/parallel/modes.py`` is the single source of truth the runtime
validates every sharded configuration through; these tests pin each
cell's verdict AND its error text, so the stale-message drift that PR 9
cleaned up (the ``halo_depth > 1 requires shard_mode 'explicit'`` chain
that survived two releases after overlap learned deep bands) cannot
quietly come back.
"""

from __future__ import annotations

import jax
import pytest

from gol_tpu.parallel import mesh as mesh_mod
from gol_tpu.parallel import modes

jax.config.update("jax_platforms", "cpu")


# -- the positive matrix -----------------------------------------------------


@pytest.mark.parametrize("engine,mode", [
    (e, m) for e, ms in modes.ENGINE_MODES.items() for m in ms
])
def test_supported_cells_have_no_rejection(engine, mode):
    assert modes.mode_rejection(engine, mode) is None


@pytest.mark.parametrize("engine,mode,k", [
    ("dense", "explicit", 4),
    ("dense", "overlap", 4),
    ("dense", "pipeline", 4),
    ("bitpack", "explicit", 2),
    ("bitpack", "overlap", 2),
    ("bitpack", "pipeline", 2),
    ("pallas_bitpack", "explicit", 8),
    ("pallas_bitpack", "overlap", 16),
    ("pallas_bitpack", "pipeline", 8),
    ("activity", "explicit", 1),
])
def test_legal_combos_pass_check(engine, mode, k):
    modes.check_combo(engine, mode, k)  # must not raise


# -- per-combo rejection messages --------------------------------------------


@pytest.mark.parametrize("engine,mode,match", [
    ("bitpack", "auto", "no auto-SPMD program"),
    ("pallas_bitpack", "auto", "explicit, overlap and pipeline ring "
                               "programs only"),
    ("activity", "overlap", "explicit ring program only"),
    ("activity", "pipeline", "explicit ring program only"),
    ("activity", "auto", "explicit ring program only"),
])
def test_unsupported_cells_pin_their_message(engine, mode, match):
    assert match in modes.mode_rejection(engine, mode)
    with pytest.raises(ValueError, match=match):
        modes.check_combo(engine, mode, 1)


def test_unknown_mode_rejected():
    with pytest.raises(ValueError, match="unknown shard_mode"):
        modes.check_combo("dense", "psychic", 1)


def test_unknown_engine_passes_through():
    # Engines outside the matrix (e.g. 'pallas' single-device) are not
    # this module's business; the runtime rejects them elsewhere.
    assert modes.mode_rejection("pallas", "explicit") is None


@pytest.mark.parametrize("engine,mode,k,match", [
    ("dense", "explicit", 0, "must be >= 1"),
    ("dense", "auto", 2, "no band to deepen"),
    ("pallas_bitpack", "pipeline", 12, "multiple of 8"),
    ("pallas_bitpack", "explicit", 7, "multiple of 8"),
    ("activity", "explicit", 2, "must be 1"),
])
def test_depth_rules_pin_their_message(engine, mode, k, match):
    with pytest.raises(ValueError, match=match):
        modes.check_combo(engine, mode, k)


@pytest.mark.parametrize("two_d,shard_h,shard_w,k,ok", [
    (False, 8, 1, 8, True),   # 1-D: width extent not a band axis
    (False, 8, 1, 9, False),
    (True, 8, 2, 2, True),
    (True, 8, 2, 3, False),   # 2-D: min extent governs
])
def test_depth_vs_shard_extent(two_d, shard_h, shard_w, k, ok):
    if ok:
        modes.check_depth(k, shard_h, shard_w, two_d)
    else:
        with pytest.raises(ValueError, match="exceeds the shard extent"):
            modes.check_depth(k, shard_h, shard_w, two_d)


# -- the runtime validates THROUGH the matrix --------------------------------


def _rt(**kw):
    from gol_tpu.models.state import Geometry
    from gol_tpu.runtime import GolRuntime

    kw.setdefault("geometry", Geometry(size=64, num_ranks=1))
    return GolRuntime(**kw)


@pytest.mark.parametrize("engine,mode,k,match", [
    ("bitpack", "auto", 1, "no auto-SPMD program"),
    ("dense", "auto", 2, "no band to deepen"),
    ("pallas_bitpack", "pipeline", 12, "multiple of 8"),
    ("activity", "explicit", 2, "must be 1"),
])
def test_runtime_surfaces_canonical_messages(engine, mode, k, match):
    with pytest.raises(ValueError, match=match):
        _rt(
            engine=engine,
            mesh=mesh_mod.make_mesh_1d(4),
            shard_mode=mode,
            halo_depth=k,
        )


def test_runtime_rejects_pipeline_without_mesh():
    with pytest.raises(ValueError, match="pass a mesh"):
        _rt(shard_mode="pipeline")


def test_runtime_accepts_every_dense_cell():
    for mode in modes.ENGINE_MODES["dense"]:
        rt = _rt(
            engine="dense", mesh=mesh_mod.make_mesh_1d(4), shard_mode=mode
        )
        assert rt.shard_mode == mode


# -- the out-of-core row: meshless by construction ---------------------------
#
# Engine 'ooc' (docs/STREAMING.md) streams host-resident bands through
# ONE device; there is no sharded ring program to pick a mode for, so
# every (ooc, mode) cell rejects with one canonical message naming the
# legal alternatives, and the serve/batch tiers refuse it by name.


@pytest.mark.parametrize("mode", sorted(modes.SHARD_MODES))
def test_every_ooc_cell_pins_the_canonical_message(mode):
    msg = modes.mode_rejection("ooc", mode)
    assert "no sharded ring program" in msg
    assert "--engine ooc without a mesh" in msg
    # The rejection must name the engines that DO shard, or the message
    # is a dead end for the user it fires on.
    for alt in ("'dense'", "'bitpack'", "'pallas_bitpack'", "'activity'"):
        assert alt in msg


def test_runtime_surfaces_ooc_mesh_rejection():
    with pytest.raises(ValueError, match="no sharded ring program"):
        _rt(
            engine="ooc",
            mesh=mesh_mod.make_mesh_1d(4),
            shard_mode="explicit",
        )


def test_runtime_surfaces_ooc_mode_rejection_without_mesh():
    # shard_mode is a ring knob; a meshless ooc run still rejects a
    # non-default mode through the same canonical message.
    with pytest.raises(ValueError, match="no sharded ring program"):
        _rt(engine="ooc", shard_mode="overlap", halo_depth=2)


def test_runtime_accepts_meshless_ooc_with_deep_visits():
    # halo_depth doubles as the per-visit generation depth k, so the
    # "temporal blocking needs a mesh" rejection must exempt ooc.
    rt = _rt(engine="ooc", halo_depth=4)
    assert rt._resolved == "ooc" and rt._ooc_plan.depth == 4


def test_serve_rejects_ooc_naming_supported_engines(tmp_path):
    from gol_tpu.serve.scheduler import ServeScheduler, ValidationError

    sched = ServeScheduler(str(tmp_path / "state"), quantum=32)
    try:
        with pytest.raises(ValidationError, match="is not served") as ei:
            sched.submit(
                {"pattern": 4, "size": 32, "generations": 1, "engine": "ooc"}
            )
        assert "supported engines" in str(ei.value)
    finally:
        sched.close()


def test_batch_rejects_ooc_naming_batched_engines():
    import numpy as np

    from gol_tpu.batch import GolBatchRuntime

    with pytest.raises(ValueError, match="streams one bigger-than-device"):
        GolBatchRuntime(
            worlds=[np.zeros((8, 8), dtype=np.uint8)], engine="ooc"
        )


def test_cli_rejects_batch_times_ooc(capsys, tmp_path):
    from gol_tpu import cli

    rc = cli.main(
        ["7", "64", "8", "32", "0", "--engine", "ooc", "--batch", "2",
         "--outdir", str(tmp_path)]
    )
    assert rc == 255
    assert "run it unbatched" in capsys.readouterr().out


def test_cli_rejects_guard_times_ooc(capsys, tmp_path):
    from gol_tpu import cli

    rc = cli.main(
        ["7", "64", "8", "32", "0", "--engine", "ooc", "--guard-every", "2",
         "--outdir", str(tmp_path)]
    )
    assert rc == 255
    assert "guard an in-core engine" in capsys.readouterr().out
