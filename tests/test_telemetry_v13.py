"""Schema v13 (black box, compile cache, shed census) + v1–v12 compat.

Companion to tests/test_telemetry.py (v1) and test_telemetry_v{2..12}.py.
Here:

- the v13 additions round-trip: ``storm`` records a compile-storm
  detection, ``compile`` optionally carries the persistent-cache
  verdict (``cache_hit``/``cache_key``), and a shedding stream leaves
  a ``shed_summary`` census on close (docs/OBSERVABILITY.md);
- the committed v13 fixture is a REAL serve run against a persistent
  compile cache — two warm buckets (hits), three cold ones (misses
  with the written entry's key), and the storm the cold burst tripped
  (hits never count toward the threshold);
- **back-compat**: all TWELVE committed fixtures — PR 2 (v1) through
  PR 18 (v13) — still load, merge, and render in one ``summarize``
  pass (exit 0) with the cache hit-rate line;
- a stream from a FUTURE schema fails loudly ("newer than this reader
  supports", exit 2) instead of KeyError'ing deep in a consumer;
- the ``gol_compile_*`` counters and ``gol_telemetry_shed_total`` are
  fed from the same records/taps the JSONL carries.
"""

from __future__ import annotations

import json
import pathlib
import shutil

import jax
import pytest

from gol_tpu import telemetry
from gol_tpu.telemetry import summarize as summ_mod
from gol_tpu.telemetry.metrics import MetricsRegistry

jax.config.update("jax_platforms", "cpu")

DATA = pathlib.Path(__file__).parent / "data"
FIXTURES = {
    1: DATA / "telemetry_v1" / "pr2run.rank0.jsonl",
    2: DATA / "telemetry_v2" / "pr3run.rank0.jsonl",
    3: DATA / "telemetry_v3" / "pr5run.rank0.jsonl",
    4: DATA / "telemetry_v4" / "pr6run.rank0.jsonl",
    5: DATA / "telemetry_v5" / "pr7run.rank0.jsonl",
    6: DATA / "telemetry_v6" / "pr8run.rank0.jsonl",
    7: DATA / "telemetry_v7" / "pr9run.rank0.jsonl",
    8: DATA / "telemetry_v8" / "pr10run.rank0.jsonl",
    9: DATA / "telemetry_v9" / "pr12run.rank0.jsonl",
    11: DATA / "telemetry_v11" / "pr14run.rank0.jsonl",
    12: DATA / "telemetry_v12" / "pr17run.rank0.jsonl",
    13: DATA / "telemetry_v13" / "pr18run.rank0.jsonl",
}


def _v13_stream(directory, run_id="v13"):
    with telemetry.EventLog(
        str(directory), run_id=run_id, process_index=0
    ) as ev:
        ev.run_header({"driver": "serve", "engine": "auto", "slots": 4})
        ev.compile_event(4, 0.2, 0.8, cache_hit=False, cache_key="k-abc")
        ev.compile_event(4, 0.001, 0.002, cache_hit=True)
        ev.compile_event(4, 0.1, 0.3)  # no cache attached: no stamp
        ev.storm_event("compile", count=3, window_s=10.0, threshold=3)
        return ev.path


def test_v13_roundtrip(tmp_path):
    path = _v13_stream(tmp_path)
    recs = [json.loads(ln) for ln in open(path)]
    assert recs[0]["schema"] == telemetry.SCHEMA_VERSION >= 13
    assert set(telemetry.SUPPORTED_SCHEMAS) >= set(range(1, 14))
    comps = [r for r in recs if r["event"] == "compile"]
    assert [c.get("cache_hit") for c in comps] == [False, True, None]
    assert comps[0]["cache_key"] == "k-abc"
    assert "cache_key" not in comps[2] and "cache_hit" not in comps[2]
    storm = next(r for r in recs if r["event"] == "storm")
    assert storm["kind"] == "compile"
    assert storm["count"] == 3 and storm["threshold"] == 3
    assert storm["window_s"] == 10.0


def test_storm_event_validates_required_fields(tmp_path):
    with telemetry.EventLog(
        str(tmp_path), run_id="bad", process_index=0
    ) as ev:
        ev.run_header({})
        with pytest.raises(telemetry.SchemaError, match="storm"):
            ev.emit("storm", kind="compile")  # no count/window/threshold


def test_committed_fixture_schemas():
    for want, fixture in FIXTURES.items():
        head = json.loads(fixture.open().readline())
        assert head["schema"] == want, fixture


def test_v13_fixture_is_a_real_cached_serve_run():
    """The committed stream came from a real scheduler run against a
    persistent compile cache: warm buckets hit, cold buckets miss with
    the written entry's key, and the cold burst trips the storm."""
    recs = [json.loads(ln) for ln in FIXTURES[13].open()]
    assert recs[0]["config"]["driver"] == "serve"
    comps = [r for r in recs if r["event"] == "compile"]
    hits = [c for c in comps if c["cache_hit"] is True]
    misses = [c for c in comps if c["cache_hit"] is False]
    assert len(hits) == 2 and len(misses) == 3
    # The key is stamped when the entry is written — misses only.
    assert all(
        isinstance(c["cache_key"], str) and c["cache_key"]
        for c in misses
    )
    assert all(c["cache_key"] is None for c in hits)
    # A persistent-cache hit skips the XLA compile: orders faster.
    assert max(c["compile_s"] for c in hits) < min(
        c["compile_s"] for c in misses
    )
    storms = [r for r in recs if r["event"] == "storm"]
    assert len(storms) == 1
    assert storms[0]["kind"] == "compile"
    assert storms[0]["count"] >= storms[0]["threshold"] == 3
    # Every compile names its bucket (schema v4 batch block).
    assert all(c["batch"]["bucket"] for c in comps)


def test_v13_fixture_summarize_renders_cache_line(capsys):
    assert summ_mod.main(
        ["summarize", str(FIXTURES[13].parent)]
    ) == 0
    out = capsys.readouterr().out
    assert "cache: 2/5 hit(s) (40% hit rate)" in out
    assert "[cache hit]" in out and "[cache miss -> " in out
    assert "storm: compile" in out and "admission depth halved" in out


def test_v1_to_v13_merge_renders(tmp_path, capsys):
    for fixture in FIXTURES.values():
        shutil.copy(fixture, tmp_path / fixture.name)
    _v13_stream(tmp_path)
    assert summ_mod.main(["summarize", str(tmp_path)]) == 0
    out = capsys.readouterr().out
    for run_id in (
        "pr2run", "pr3run", "pr5run", "pr6run", "pr7run", "pr8run",
        "pr9run", "pr10run", "pr12run", "pr14run", "pr17run", "pr18run",
        "v13",
    ):
        assert run_id in out
    assert "hit rate" in out


def test_future_schema_fails_loudly_not_keyerror(tmp_path, capsys):
    future = telemetry.SCHEMA_VERSION + 1
    (tmp_path / "fut.rank0.jsonl").write_text(
        json.dumps(
            {
                "event": "run_header", "t": 0.0, "schema": future,
                "run_id": "fut", "process_index": 0, "process_count": 1,
                "config": {},
            }
        )
        + "\n"
    )
    assert summ_mod.main(["summarize", str(tmp_path)]) == 2
    err = capsys.readouterr().err
    assert f"schema v{future} is newer than this reader supports" in err
    assert f"max v{telemetry.SCHEMA_VERSION}" in err


def test_compile_metrics_from_fixture():
    """gol_compile_{hits,misses}_total / gol_compile_seconds_total /
    gol_compile_storms_total are fed from the SAME records the JSONL
    carries — and stay absent until a compile is observed."""
    reg = MetricsRegistry()
    assert "gol_compile" not in reg.render()
    for ln in FIXTURES[13].open():
        reg.observe(json.loads(ln))
    text = reg.render()
    assert "gol_compile_hits_total 2" in text
    assert "gol_compile_misses_total 3" in text
    assert "gol_compile_storms_total 1" in text
    seconds = next(
        float(ln.split()[-1])
        for ln in text.splitlines()
        if ln.startswith("gol_compile_seconds_total ")
    )
    assert seconds > 0.0


def test_shed_census_counter_and_summary(tmp_path, capsys):
    """A shedding stream counts its drops per event type, feeds the
    live gol_telemetry_shed_total tap, and leaves a shed_summary
    degraded record on close that summarize renders as the census."""
    reg = MetricsRegistry()
    ev = telemetry.EventLog(str(tmp_path), run_id="shed", process_index=0)
    ev.observer = reg.observe
    ev.on_shed = reg.count_shed
    ev.run_header({"driver": "test"})
    ev.chunk_event(0, 4, 4, 0.1, 1e6, None)
    ev.request_shed("checkpoint", "disk full: checkpoints win")
    ev.chunk_event(1, 4, 8, 0.1, 1e6, None)
    ev.chunk_event(2, 4, 12, 0.1, 1e6, None)
    ev.stats_event(
        2, 4, 12,
        {"population": 5, "births": 1, "deaths": 1, "changed": 2},
    )
    assert ev.shed_counts == {"chunk": 2, "stats": 1}
    ev.close()

    text = reg.render()
    assert 'gol_telemetry_shed_total{event="chunk"} 2' in text
    assert 'gol_telemetry_shed_total{event="stats"} 1' in text

    recs = [json.loads(ln) for ln in open(ev.path)]
    # The file keeps what landed before the shed plus both stamps.
    assert [r["event"] for r in recs if r["event"] == "chunk"] == ["chunk"]
    summary = recs[-1]
    assert summary["event"] == "degraded"
    assert summary["action"] == "shed_summary"
    assert summary["dropped"] == {"chunk": 2, "stats": 1}
    assert summary["dropped_total"] == 3

    assert summ_mod.main(["summarize", str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "shed 3 record(s) after degrading" in out
    assert "2 chunk" in out and "1 stats" in out


def test_shed_metrics_absent_without_drops():
    assert "gol_telemetry_shed_total" not in MetricsRegistry().render()

def test_compile_storm_halves_admission_depth(tmp_path):
    """K cold compiles inside one window trip the detector: one storm,
    counted on the scheduler, and the admission depth halves until the
    window drains.  A single cold compile is not a storm."""
    from gol_tpu.serve.scheduler import ServeScheduler

    sched = ServeScheduler(
        str(tmp_path / "s"), quantum=32, slots=2, queue_depth=8,
        storm_threshold=2, storm_window_s=60.0,
    )
    try:
        assert sched._effective_queue_depth() == 8
        sched._note_cold_compile()
        assert not sched.storm_active()
        sched._note_cold_compile()
        assert sched.storm_active()
        assert sched.storms_total == 1
        assert sched._effective_queue_depth() == 4
        # Re-tripping inside the same window does not double-count.
        sched._note_cold_compile()
        assert sched.storms_total == 1
    finally:
        sched.close()
