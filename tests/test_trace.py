"""Request tracing plane (docs/OBSERVABILITY.md "Request tracing & SLOs").

PR 17's acceptance criteria, as tests:

- a REAL traced serve run reconstructs complete span trees — one trace
  per request, zero orphans — and the five-phase latency decomposition
  sums to the end-to-end latency within 1% (both the read side,
  rebuilt from spans, and the write side riding the result payload);
- **trace identity**: tracing is host-plane only — the same workload
  with and without a telemetry stream produces bit-identical boards,
  identical fingerprints, an identical compiled-program call sequence,
  and byte-equal jaxprs for the serve drive loop's chunk program;
- multi-rank reconstruction: spans for one trace_id scattered across
  two rank files of the same run merge into one tree;
- the Perfetto export validates against the committed JSON schema
  (docs/schemas/perfetto_trace.schema.json) — the same check
  scripts/validate_trace_export.py gives CI teeth in check.sh;
- journal compaction preserves admit records verbatim, so ``trace_id``
  survives the rewrite and crash-replay can rejoin pre-crash spans
  (the replay side is pinned in test_serve.py);
- the SLO engine turns decompositions into burn rates deterministically.
"""

from __future__ import annotations

import io
import json
import pathlib

import jax
import numpy as np
import pytest

from gol_tpu.serve import journal as journal_mod
from gol_tpu.serve.scheduler import ServeScheduler
from gol_tpu.telemetry import EventLog
from gol_tpu.telemetry import slo as slo_mod
from gol_tpu.telemetry import summarize as summ_mod
from gol_tpu.telemetry import trace as trace_mod

jax.config.update("jax_platforms", "cpu")

REPO = pathlib.Path(__file__).resolve().parent.parent
PERFETTO_SCHEMA = REPO / "docs" / "schemas" / "perfetto_trace.schema.json"

REQS = [
    {"id": "r0", "pattern": 4, "size": 24, "generations": 4},
    {"id": "r1", "pattern": 4, "size": 24, "generations": 6},
    {"id": "r2", "pattern": 6, "size": 32, "generations": 5},
]


def _traced_run(tmp_path, run_id="tr"):
    """Drain REQS through a scheduler with a telemetry stream attached;
    return (results-by-id, telemetry dir)."""
    teldir = str(tmp_path / "tel")
    sched = ServeScheduler(
        str(tmp_path / "state"), quantum=32, slots=2, queue_depth=8,
        chunk=2, telemetry_dir=teldir, run_id=run_id,
    )
    try:
        for r in REQS:
            sched.submit(dict(r))
        sched.run_until_drained()
        results = {r["id"]: sched.get_result(r["id"]).result for r in REQS}
    finally:
        sched.close()
    return results, teldir


# -- span trees + decomposition -----------------------------------------------


def test_traced_run_reconstructs_complete_span_trees(tmp_path):
    results, teldir = _traced_run(tmp_path)
    traces = trace_mod.collect_traces(summ_mod.load_dir(teldir))
    by_req = {tr.request_id: tr for tr in traces.values()}
    assert set(by_req) == {r["id"] for r in REQS}
    for r in REQS:
        tr = by_req[r["id"]]
        assert tr.orphans() == [], f"{r['id']}: orphaned spans"
        assert tr.root() is not None
        assert results[r["id"]]["trace_id"] == tr.trace_id
        names = {s["name"] for s in tr.spans}
        assert {"request", "queue", "chunk", "commit"} <= names
        # Every chunk span carries the utilization/co-residency attrs
        # the interference attribution needs.
        for s in tr.named("chunk"):
            a = s["attrs"]
            assert a["co_resident"] >= 1 and a["take"] >= 1
            assert 0.0 <= a["utilization"] <= 1.0
            assert a["wall_s"] >= 0.0


def test_decomposition_sums_to_e2e_within_1pct(tmp_path):
    results, teldir = _traced_run(tmp_path)
    traces = trace_mod.collect_traces(summ_mod.load_dir(teldir))
    for tr in traces.values():
        d = trace_mod.decompose(tr)
        assert d is not None and d["status"] == "done"
        parts = sum(d[p] for p in trace_mod.PHASES)
        assert parts == pytest.approx(d["e2e_s"], rel=0.01, abs=1e-4)
        # Read side (rebuilt from spans) == write side (the payload).
        payload = results[tr.request_id]
        assert d["e2e_s"] == pytest.approx(
            payload["latency_s"], abs=1e-5
        )
        pd = payload["decomposition"]
        for p in trace_mod.PHASES:
            assert d[p] == pytest.approx(pd[p], abs=1e-4), p
        assert d["chunks"] == len(tr.named("chunk"))


def test_expired_request_gets_a_cancel_span_and_expired_root(tmp_path):
    teldir = str(tmp_path / "tel")
    sched = ServeScheduler(
        str(tmp_path / "state"), quantum=32, slots=2, chunk=2,
        telemetry_dir=teldir, run_id="exp",
    )
    try:
        sched.submit(
            {"id": "late", "pattern": 4, "size": 24, "generations": 4,
             "deadline_s": 0.0}
        )
        sched.run_until_drained()
        assert sched.get_result("late").status == "expired"
    finally:
        sched.close()
    traces = trace_mod.collect_traces(summ_mod.load_dir(teldir))
    (tr,) = traces.values()
    assert tr.orphans() == []
    assert tr.named("cancel") and not tr.named("commit")
    d = trace_mod.decompose(tr)
    assert d["status"] == "expired"
    assert sum(d[p] for p in trace_mod.PHASES) == pytest.approx(
        d["e2e_s"], rel=0.01, abs=1e-4
    )


# -- trace identity -----------------------------------------------------------


def test_tracing_on_off_bit_identical_results(tmp_path, monkeypatch):
    """The tracing plane is host-side bookkeeping after the device
    fences: same boards, same fingerprints, same compiled-program call
    sequence, byte-equal jaxprs — whether or not a stream is attached."""
    from gol_tpu.analysis import walker
    from gol_tpu.batch import engines as batch_engines

    orig = batch_engines.compiled_batch_evolver
    calls: list = []

    def recording(*args):
        calls.append(args)
        return orig(*args)

    monkeypatch.setattr(
        batch_engines, "compiled_batch_evolver", recording
    )

    outs = {}
    for tag in ("off", "on"):
        mark = len(calls)
        kw = (
            dict(telemetry_dir=str(tmp_path / "tel"), run_id="ti")
            if tag == "on"
            else {}
        )
        sched = ServeScheduler(
            str(tmp_path / tag), quantum=32, slots=2, chunk=2, **kw
        )
        try:
            for r in REQS:
                sched.submit(dict(r, engine="dense"))
            sched.run_until_drained()
            outs[tag] = {
                "boards": {
                    r["id"]: sched.result_board(r["id"]) for r in REQS
                },
                "fps": {
                    r["id"]: sched.get_result(r["id"]).result[
                        "fingerprint"
                    ]
                    for r in REQS
                },
                "payload_keys": {
                    r["id"]: sorted(sched.get_result(r["id"]).result)
                    for r in REQS
                },
                "calls": calls[mark:],
            }
        finally:
            sched.close()

    for r in REQS:
        assert np.array_equal(
            outs["off"]["boards"][r["id"]], outs["on"]["boards"][r["id"]]
        ), r["id"]
    assert outs["off"]["fps"] == outs["on"]["fps"]
    # One payload shape regardless of telemetry — the decomposition is
    # not a tracing-only field.
    assert outs["off"]["payload_keys"] == outs["on"]["payload_keys"]
    assert "decomposition" in dict.fromkeys(
        outs["off"]["payload_keys"][REQS[0]["id"]]
    )
    # The drive loop asked for the exact same programs in the exact
    # same order...
    assert outs["off"]["calls"] == outs["on"]["calls"]
    # ...and each program's jaxpr is byte-equal between the two runs
    # (traced once per run from that run's own recorded builder args).
    jaxprs = {}
    for tag in ("off", "on"):
        engine, steps, masked, tile_hint, mesh = outs[tag]["calls"][0]
        assert masked and mesh is None
        fn = orig(engine, steps, masked, tile_hint, mesh)
        stack = jax.ShapeDtypeStruct((2, 32, 32), np.uint8)
        ext = jax.ShapeDtypeStruct((2,), np.int32)
        jaxprs[tag] = str(walker.trace_jaxpr(fn, stack, ext, ext))
    assert jaxprs["off"] == jaxprs["on"]


# -- multi-rank reconstruction ------------------------------------------------


def test_multi_rank_span_tree_reconstruction(tmp_path):
    """Spans for one trace_id split across two rank files of the same
    run — as a multi-host serve deployment writes them — rebuild into a
    single orphan-free tree."""
    _, teldir = _traced_run(tmp_path, run_id="mr")
    before = trace_mod.collect_traces(summ_mod.load_dir(teldir))
    tr0 = next(t for t in before.values() if t.request_id == "r0")
    n0 = len(tr0.spans)
    with EventLog(teldir, run_id="mr", process_index=1) as ev:
        ev.run_header({"driver": "serve", "role": "rank1"})
        ev.span_event(
            tr0.trace_id, "r0", "rank1#1", "chunk", 5.0, 6.0,
            parent_id=trace_mod.ROOT_SPAN_ID,
            attrs={"co_resident": 1, "utilization": 0.25, "take": 2,
                   "wall_s": 1.0},
        )
    after = trace_mod.collect_traces(summ_mod.load_dir(teldir))
    tr = after[tr0.trace_id]
    assert len(tr.spans) == n0 + 1
    assert tr.orphans() == []
    assert any(
        s["span_id"] == "rank1#1" for s in tr.children(trace_mod.ROOT_SPAN_ID)
    )
    # The merged tree still decomposes (the rank-1 chunk lands in the
    # compute/interference phases like any other).
    assert trace_mod.decompose(tr) is not None


# -- perfetto export ----------------------------------------------------------


def test_perfetto_export_validates_against_committed_schema(tmp_path):
    _, teldir = _traced_run(tmp_path, run_id="pf")
    traces = trace_mod.collect_traces(summ_mod.load_dir(teldir))
    out = tmp_path / "export.json"
    trace_mod.export_perfetto(traces, str(out))
    doc = json.loads(out.read_text())
    schema = json.loads(PERFETTO_SCHEMA.read_text())
    assert trace_mod.validate_json_schema(doc, schema) == []
    # One thread-name track per trace, every span on a named track.
    meta = [e for e in doc["traceEvents"] if e["ph"] == "M"]
    spans = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    assert len(meta) == len(traces)
    assert {e["tid"] for e in spans} <= {e["tid"] for e in meta}
    assert all(e["ts"] >= 0 and e["dur"] >= 0 for e in spans)


def test_trace_cli_renders_decomposition_and_slo_tables(tmp_path, capsys):
    _, teldir = _traced_run(tmp_path, run_id="cli")
    out = tmp_path / "pf.json"
    assert (
        summ_mod.main(
            ["trace", teldir, "--perfetto", str(out)]
        )
        == 0
    )
    text = capsys.readouterr().out
    assert "queue" in text and "stall" in text and "burn" in text
    assert out.exists()
    # Request filter narrows the table to one trace.
    buf = io.StringIO()
    assert trace_mod.main_trace(teldir, buf, request="r1") == 0
    assert "r1" in buf.getvalue() and "r0" not in buf.getvalue()


# -- journal compaction -------------------------------------------------------


def test_trace_id_survives_journal_compaction(tmp_path):
    """Compaction rewrites the journal to open intents only, preserving
    admit records verbatim — the trace_id a crash-replay needs to rejoin
    pre-crash spans rides through the rewrite untouched."""
    path = str(tmp_path / "journal.jsonl")
    j = journal_mod.Journal(path)
    req = {"id": "open", "pattern": 4, "size": 24, "generations": 4}
    j.append(
        journal_mod.record(
            "admit", "open", request=req, ordinal=0,
            trace_id="tr-open-cafe0001",
        )
    )
    j.append(
        journal_mod.record(
            "admit", "done", request=dict(req, id="done"), ordinal=1,
            trace_id="tr-done-cafe0002",
        )
    )
    j.append(
        journal_mod.record(
            "complete", "done", fingerprint=1, trace_id="tr-done-cafe0002"
        )
    )
    j.compact(keep_segments=2)
    j.close()
    entries, torn = journal_mod.replay(path)
    assert torn == 0
    assert set(entries) == {"open"}  # completed intent compacted away
    assert entries["open"]["admit"]["trace_id"] == "tr-open-cafe0001"


# -- SLO engine ---------------------------------------------------------------


def _decomp(e2e, queue=0.0, stall=0.0):
    compute = max(e2e - queue - stall, 0.0)
    return {
        "e2e_s": e2e, "queue_s": queue, "compute_s": compute,
        "interference_s": 0.0, "hedge_s": 0.0, "stall_s": stall,
        "status": "done", "chunks": 1,
    }


def test_slo_burn_rates_are_deterministic():
    decomps = [_decomp(0.1) for _ in range(8)] + [
        _decomp(2.0), _decomp(3.0)
    ]
    slo = slo_mod.SLO(
        name="commit_p99", metric="commit_latency_s", target=1.0,
        budget=0.1,
    )
    (row,) = slo_mod.evaluate([slo], decomps)
    assert row["violations"] == 2 and row["requests"] == 10
    assert row["violation_fraction"] == pytest.approx(0.2)
    assert row["burn_rate"] == pytest.approx(2.0)  # 0.2 / 0.1 budget
    assert row["ok"] is False
    # Within budget -> burn <= 1 and ok.
    (ok_row,) = slo_mod.evaluate([slo], [_decomp(0.1)] * 10)
    assert ok_row["burn_rate"] == 0.0 and ok_row["ok"] is True


def test_slo_queue_fraction_metric_and_file_loading(tmp_path):
    decomps = [_decomp(1.0, queue=0.8), _decomp(1.0, queue=0.1)]
    path = tmp_path / "slos.json"
    path.write_text(
        json.dumps(
            [{"name": "qf", "metric": "queue_fraction", "target": 0.5,
              "budget": 0.5, "percentile": 0.99}]
        )
    )
    slos = slo_mod.load_slos(str(path))
    (row,) = slo_mod.evaluate(slos, decomps)
    assert row["observed"] == pytest.approx(0.8)
    assert row["violations"] == 1
    assert slo_mod.load_slos(None) == list(slo_mod.DEFAULT_SLOS)


def test_decomposition_percentiles_shape():
    decomps = [_decomp(float(i + 1)) for i in range(10)]
    pct = trace_mod.decomposition_percentiles(decomps)
    for phase in ("e2e_s",) + trace_mod.PHASES:
        assert set(pct[phase]) == {"p50", "p99"}
        assert pct[phase]["p50"] <= pct[phase]["p99"]
    assert pct["e2e_s"]["p99"] == pytest.approx(10.0)
