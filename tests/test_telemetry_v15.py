"""Schema v15 (out-of-core streaming blocks) + v1–v14 compat.

Companion to tests/test_telemetry.py (v1) and test_telemetry_v{2..14}.py.
Here:

- the v15 addition round-trips: ``chunk`` events of an ``--engine ooc``
  run carry an ``ooc`` block — band count, visits, dead-band skips, the
  chunk's H2D/D2H byte volume, and the measured ``overlap_fraction``
  (docs/STREAMING.md, docs/OBSERVABILITY.md);
- the committed v15 fixture is a REAL streamed session: a Gosper gun on
  a 128×64 board pushed through a 9-band plan at depth 3 — every chunk
  carries the block, dead bands were skipped, and overlap was measured
  (> 0) on every chunk;
- ``summarize`` renders the conditional ``ooc (bands skip h2d/d2h
  ovl%)`` column for streamed runs and omits it otherwise;
- **back-compat**: all FOURTEEN committed fixtures — PR 2 (v1) through
  PR 20 (v15) — still load, merge, and render in one ``summarize``
  pass (exit 0);
- a stream from a FUTURE schema (99) fails loudly ("newer than this
  reader supports", exit 2) instead of KeyError'ing deep in a consumer.
"""

from __future__ import annotations

import json
import pathlib
import shutil

import jax
import pytest

from gol_tpu import telemetry
from gol_tpu.telemetry import summarize as summ_mod

jax.config.update("jax_platforms", "cpu")

DATA = pathlib.Path(__file__).parent / "data"
FIXTURES = {
    1: DATA / "telemetry_v1" / "pr2run.rank0.jsonl",
    2: DATA / "telemetry_v2" / "pr3run.rank0.jsonl",
    3: DATA / "telemetry_v3" / "pr5run.rank0.jsonl",
    4: DATA / "telemetry_v4" / "pr6run.rank0.jsonl",
    5: DATA / "telemetry_v5" / "pr7run.rank0.jsonl",
    6: DATA / "telemetry_v6" / "pr8run.rank0.jsonl",
    7: DATA / "telemetry_v7" / "pr9run.rank0.jsonl",
    8: DATA / "telemetry_v8" / "pr10run.rank0.jsonl",
    9: DATA / "telemetry_v9" / "pr12run.rank0.jsonl",
    11: DATA / "telemetry_v11" / "pr14run.rank0.jsonl",
    12: DATA / "telemetry_v12" / "pr17run.rank0.jsonl",
    13: DATA / "telemetry_v13" / "pr18run.rank0.jsonl",
    14: DATA / "telemetry_v14" / "pr19run.rank0.jsonl",
    15: DATA / "telemetry_v15" / "pr20run.rank0.jsonl",
}

OOC_KEYS = {
    "bands", "visits", "skipped_bands", "bytes_h2d", "bytes_d2h",
    "overlap_fraction",
}


def _v15_stream(directory, run_id="v15"):
    with telemetry.EventLog(
        str(directory), run_id=run_id, process_index=0
    ) as ev:
        ev.run_header({"engine": "ooc", "height": 256, "width": 64})
        ev.chunk_event(
            0, 4, 4, 0.01, 65536, None,
            ooc=dict(
                bands=8, visits=12, skipped_bands=4, bytes_h2d=4096,
                bytes_d2h=3072, overlap_fraction=0.62, sweeps=4,
                h2d_s=0.001, d2h_s=0.002, hidden_s=0.0019,
            ),
        )
        return ev.path


def test_v15_roundtrip(tmp_path):
    path = _v15_stream(tmp_path)
    recs = [json.loads(ln) for ln in open(path)]
    assert recs[0]["schema"] == telemetry.SCHEMA_VERSION >= 15
    assert set(telemetry.SUPPORTED_SCHEMAS) >= set(range(1, 16))
    (chunk,) = [r for r in recs if r["event"] == "chunk"]
    assert OOC_KEYS <= set(chunk["ooc"])
    assert chunk["ooc"]["skipped_bands"] == 4
    assert chunk["ooc"]["overlap_fraction"] == pytest.approx(0.62)


def test_committed_fixture_schemas():
    for want, fixture in FIXTURES.items():
        head = json.loads(fixture.open().readline())
        assert head["schema"] == want, fixture


def test_v15_fixture_is_a_real_streamed_session():
    """The committed stream came from a real ooc run: a Gosper gun on
    128×64 streamed through a 9-band depth-3 plan — every chunk carries
    the block, dead bands moved zero bytes, and the three-deep rotation
    measurably hid transfer behind compute on every chunk."""
    recs = [json.loads(ln) for ln in FIXTURES[15].open()]
    cfg = recs[0]["config"]
    assert cfg["resolved_engine"] == "ooc" and cfg["mesh"] is None
    chunks = [r for r in recs if r["event"] == "chunk"]
    assert chunks and all(OOC_KEYS <= set(c.get("ooc", {})) for c in chunks)
    for c in chunks:
        o = c["ooc"]
        # The gun is band-local: most of the 9 bands are dead and were
        # never fetched — transfer scales with active bands, not area.
        assert o["bands"] == 9 and o["skipped_bands"] >= 1
        assert o["visits"] + o["skipped_bands"] * (
            c["take"] // 3 or 1
        ) >= o["bands"]
        assert o["bytes_h2d"] > 0 and o["bytes_d2h"] > 0
        assert 0.0 < o["overlap_fraction"] <= 1.0
    # The accounting is self-consistent: whole-board transfer would be
    # rows*row_bytes per direction per sweep; the skip kept us under it.
    row_bytes = cfg["width"] // 32 * 4
    whole = cfg["height"] * row_bytes
    assert all(c["ooc"]["bytes_d2h"] < whole for c in chunks)
    # Stats ride along (the host-side fold): same record shape as every
    # in-core --stats run.
    stats = [r for r in recs if r["event"] == "stats"]
    assert len(stats) == len(chunks)
    assert all(s["population"] > 0 for s in stats)


def test_v15_fixture_summarize_renders_ooc_column(capsys):
    assert summ_mod.main(
        ["summarize", str(FIXTURES[15].parent)]
    ) == 0
    out = capsys.readouterr().out
    assert "ooc (bands skip h2d/d2h ovl%)" in out
    assert "9b skip" in out and "ovl" in out


def test_non_ooc_runs_omit_the_column(capsys):
    # v14's fleet fixture has chunkless records; v1's has plain chunks —
    # neither should grow the ooc column.
    assert summ_mod.main(["summarize", str(FIXTURES[1].parent)]) == 0
    out = capsys.readouterr().out
    assert "ooc (" not in out


def test_v1_to_v15_merge_renders(tmp_path, capsys):
    for fixture in FIXTURES.values():
        shutil.copy(fixture, tmp_path / fixture.name)
    _v15_stream(tmp_path)
    assert summ_mod.main(["summarize", str(tmp_path)]) == 0
    out = capsys.readouterr().out
    for run_id in (
        "pr2run", "pr3run", "pr5run", "pr6run", "pr7run", "pr8run",
        "pr9run", "pr10run", "pr12run", "pr14run", "pr17run",
        "pr18run", "pr19run", "pr20run", "v15",
    ):
        assert run_id in out
    assert "ooc (bands skip h2d/d2h ovl%)" in out


def test_future_schema_fails_loudly_not_keyerror(tmp_path, capsys):
    (tmp_path / "fut.rank0.jsonl").write_text(
        json.dumps(
            {
                "event": "run_header", "t": 0.0, "schema": 99,
                "run_id": "fut", "process_index": 0, "process_count": 1,
                "config": {},
            }
        )
        + "\n"
    )
    assert summ_mod.main(["summarize", str(tmp_path)]) == 2
    err = capsys.readouterr().err
    assert "schema v99 is newer than this reader supports" in err
    assert f"max v{telemetry.SCHEMA_VERSION}" in err
