"""The serving tier (docs/SERVING.md): journal durability, continuous
batching, admission control, deadlines, and guard isolation.

Everything here is in-process and fast (tier 1).  The drills that need
real process death — SIGKILL mid-batch under a supervisor, graceful
SIGTERM drain — live in scripts/serve_smoke.py; the crash-replay test
here simulates the same journal path by abandoning one scheduler and
constructing a second over the same state directory.
"""

from __future__ import annotations

import json
import pathlib

import jax
import numpy as np
import pytest

from gol_tpu.models import patterns
from gol_tpu.serve import journal as journal_mod
from gol_tpu.serve.scheduler import (
    Rejected, ServeScheduler, ValidationError,
)
from tests import oracle

jax.config.update("jax_platforms", "cpu")


def _oracle(pattern: int, size: int, gens: int) -> np.ndarray:
    return oracle.run_torus(
        patterns.init_global(pattern, size, 1), gens
    )


def _events(path: pathlib.Path):
    out = []
    for p in sorted(path.glob("*.jsonl")):
        out.extend(json.loads(ln) for ln in open(p))
    return out


# -- journal -------------------------------------------------------------------


def test_journal_roundtrip_and_fold(tmp_path):
    j = journal_mod.Journal(str(tmp_path / "j.jsonl"))
    j.append(journal_mod.record("admit", "a", request={}, ordinal=0))
    j.append(journal_mod.record("start", "a", ordinal=0))
    j.append(journal_mod.record("admit", "b", request={}, ordinal=1))
    j.append(journal_mod.record("complete", "a", fingerprint=7))
    j.close()
    entries, torn = journal_mod.replay(j.path)
    assert torn == 0
    assert entries["a"]["status"] == "completed"
    assert entries["a"]["terminal"]["fingerprint"] == 7
    assert entries["b"]["status"] == "admitted"
    assert list(entries) == ["a", "b"]  # admission order


def test_journal_torn_final_record_is_tolerated(tmp_path):
    """A crash mid-append leaves a half-written last line; the replay
    fold ignores it (it was never acknowledged) and the next append
    self-heals the tail instead of corrupting its own record."""
    path = str(tmp_path / "j.jsonl")
    j = journal_mod.Journal(path)
    j.append(journal_mod.record("admit", "a", request={}, ordinal=0))
    j.append(journal_mod.record("admit", "b", request={}, ordinal=1))
    j.close()
    whole = open(path, "rb").read()
    with open(path, "wb") as f:
        f.write(whole[:-20])  # tear the final record mid-line
    entries, torn = journal_mod.replay(path)
    assert torn == 1
    assert list(entries) == ["a"]  # the torn admit never happened
    j2 = journal_mod.Journal(path)  # reopen over the torn tail
    j2.append(journal_mod.record("admit", "c", request={}, ordinal=2))
    j2.close()
    entries, torn = journal_mod.replay(path)
    assert torn == 1
    assert list(entries) == ["a", "c"]


def test_journal_duplicate_admit_is_idempotent(tmp_path):
    j = journal_mod.Journal(str(tmp_path / "j.jsonl"))
    j.append(
        journal_mod.record("admit", "a", request={"n": 1}, ordinal=0)
    )
    j.append(
        journal_mod.record("admit", "a", request={"n": 2}, ordinal=9)
    )
    j.close()
    entries, _ = journal_mod.replay(j.path)
    assert len(entries) == 1
    assert entries["a"]["admit"]["request"] == {"n": 1}  # first wins


def test_journal_compact_crash_midway_keeps_live_journal(tmp_path,
                                                        monkeypatch):
    """A SIGKILL between compaction's filesystem steps must leave a
    valid live journal.  The rotation is a hard link, not a rename of
    the live file, so the worst crash point (segment linked, new file
    not yet committed) leaves the FULL old journal at the live path —
    a restart replays every open intent instead of forgetting them."""
    path = str(tmp_path / "j.jsonl")
    j = journal_mod.Journal(path)
    j.append(journal_mod.record("admit", "a", request={}, ordinal=0))
    j.append(journal_mod.record("complete", "a", fingerprint=0))
    j.append(journal_mod.record("admit", "open", request={}, ordinal=1))

    def die(src, dst):
        raise OSError("simulated crash before the new journal landed")

    monkeypatch.setattr(journal_mod.os, "replace", die)
    with pytest.raises(OSError):
        j.compact(keep_segments=2)
    monkeypatch.undo()
    entries, torn = journal_mod.replay(path)
    assert torn == 0
    assert entries["a"]["status"] == "completed"
    assert entries["open"]["status"] == "admitted"  # nothing forgotten
    # The journal stays appendable after the failed compact...
    j.append(journal_mod.record("start", "open", ordinal=1))
    j.close()
    # ...and a restart-over-the-same-path (what the supervisor does)
    # sees the full fold, then compacts cleanly.
    j2 = journal_mod.Journal(path)
    entries, _ = journal_mod.replay(path)
    assert entries["open"]["status"] == "started"
    j2.compact(keep_segments=2)
    j2.close()
    entries, _ = journal_mod.replay(path)
    assert sorted(entries) == ["open"]


def test_journal_compaction_gc_keeps_newest_segments(tmp_path):
    """Compaction rewrites the live file to only-open intents, rotates
    history to ``.n`` segments, and keeps only the newest K — the PR 4
    keep-newest retention discipline applied to journal history."""
    path = str(tmp_path / "j.jsonl")
    j = journal_mod.Journal(path)
    for round_ in range(4):
        rid = f"r{round_}"
        j.append(
            journal_mod.record("admit", rid, request={}, ordinal=round_)
        )
        j.append(journal_mod.record("complete", rid, fingerprint=0))
        j.append(
            journal_mod.record(
                "admit", f"open{round_}", request={}, ordinal=100 + round_
            )
        )
        j.compact(keep_segments=2)
    j.close()
    segs = sorted(tmp_path.glob("j.jsonl.*"))
    assert [s.name for s in segs] == ["j.jsonl.3", "j.jsonl.4"]
    entries, _ = journal_mod.replay(path)
    # Completed intents were compacted away; every open one survives.
    assert sorted(entries) == [f"open{r}" for r in range(4)]
    assert all(e["status"] == "admitted" for e in entries.values())


# -- scheduler: continuous batching -------------------------------------------


def test_continuous_refill_bit_equal_to_sequential(tmp_path):
    """Five same-bucket requests through two slots: slots refill as
    worlds finish (continuous batching), and every result is bit-equal
    to the sequential single-world oracle."""
    sched = ServeScheduler(
        str(tmp_path / "state"), quantum=32, slots=2, queue_depth=8,
        chunk=3,
    )
    specs = [(4, 32, 5 + 2 * i) for i in range(5)]  # staggered lengths
    try:
        for i, (pat, size, gens) in enumerate(specs):
            sched.submit(
                {"id": f"w{i}", "pattern": pat, "size": size,
                 "generations": gens}
            )
        assert sched.outstanding() == 5
        sched.run_until_drained()
        for i, (pat, size, gens) in enumerate(specs):
            got = sched.result_board(f"w{i}")
            assert np.array_equal(got, _oracle(pat, size, gens)), f"w{i}"
        assert sched.completed_total == 5
    finally:
        sched.close()


def test_mixed_buckets_and_engines_complete(tmp_path):
    sched = ServeScheduler(
        str(tmp_path / "state"), quantum=32, slots=2, chunk=4,
    )
    reqs = [
        {"id": "dense32", "pattern": 4, "size": 32, "generations": 6,
         "engine": "dense"},
        {"id": "bp32", "pattern": 4, "size": 32, "generations": 6,
         "engine": "bitpack"},
        {"id": "auto48", "pattern": 4, "size": 48, "generations": 9},
    ]
    try:
        for r in reqs:
            sched.submit(r)
        sched.run_until_drained()
        for r in reqs:
            got = sched.result_board(r["id"])
            want = _oracle(r["pattern"], r["size"], r["generations"])
            assert np.array_equal(got, want), r["id"]
    finally:
        sched.close()


def test_duplicate_submit_returns_existing_state(tmp_path):
    sched = ServeScheduler(str(tmp_path / "state"), quantum=32)
    try:
        a = sched.submit(
            {"id": "dup", "pattern": 4, "size": 32, "generations": 3}
        )
        b = sched.submit(
            {"id": "dup", "pattern": 4, "size": 32, "generations": 99}
        )
        assert a is b  # idempotent on the id: no double admission
        sched.run_until_drained()
        assert sched.completed_total == 1
        assert sched.get_result("dup").result["generation"] == 3
    finally:
        sched.close()


def test_validation_rejects_bad_requests(tmp_path):
    sched = ServeScheduler(str(tmp_path / "state"), quantum=32)
    try:
        for bad in (
            {"pattern": 4, "size": 32},  # no generations
            {"pattern": 999, "size": 32, "generations": 1},
            {"pattern": 4, "size": 32, "generations": 1, "rule": "B36/S23"},
            {"pattern": 4, "size": 32, "generations": 1, "engine": "warp"},
            {"pattern": 4, "size": 32, "generations": 1, "id": "../etc"},
            {"pattern": 4, "size": 32, "generations": 1, "bogus": True},
        ):
            with pytest.raises(ValidationError):
                sched.submit(bad)
    finally:
        sched.close()


def test_client_refuses_connect_retries_without_id():
    """Idempotent resubmission keys on a caller-supplied id; without
    one, every retry is a fresh (double-run) request — the client
    refuses that combination up front, before any network call."""
    from gol_tpu.serve.client import SimClient

    c = SimClient("http://127.0.0.1:1")  # never contacted
    with pytest.raises(ValueError, match="caller-supplied 'id'"):
        c.submit(
            {"pattern": 4, "size": 32, "generations": 1},
            connect_retries=2,
        )


# -- admission control ---------------------------------------------------------


def test_backpressure_429_with_retry_after_and_stats_shed(tmp_path):
    """Beyond the bounded queue the scheduler answers an explicit 429
    with a retry hint, and the FIRST backpressure signal sheds stats
    streaming (the PR 10 order: stats before admissions, admissions
    before committed work)."""
    sched = ServeScheduler(
        str(tmp_path / "state"), quantum=32, slots=1, queue_depth=1,
        telemetry_dir=str(tmp_path / "tm"), run_id="bp",
    )
    try:
        sched.submit(
            {"id": "ok", "pattern": 4, "size": 32, "generations": 2}
        )
        with pytest.raises(Rejected) as exc:
            sched.submit(
                {"id": "no", "pattern": 4, "size": 32, "generations": 2}
            )
        assert exc.value.status == 429
        assert exc.value.retry_after is not None
        assert sched.rejected_total == 1
        assert sched.get_result("no") is None  # never half-admitted
        sched.run_until_drained()  # the committed request still lands
        assert sched.get_result("ok").status == "done"
    finally:
        sched.close()
    recs = _events(tmp_path / "tm")
    reject = next(
        r for r in recs
        if r["event"] == "serve" and r["action"] == "reject"
    )
    assert reject["request_id"] == "no"
    assert any(
        r["event"] == "degraded"
        and r["resource"] == "stats"
        and r["action"] == "shed"
        for r in recs
    )


def test_draining_rejects_with_503(tmp_path):
    sched = ServeScheduler(str(tmp_path / "state"), quantum=32)
    try:
        sched.drain()
        with pytest.raises(Rejected) as exc:
            sched.submit(
                {"pattern": 4, "size": 32, "generations": 1}
            )
        assert exc.value.status == 503
    finally:
        sched.close()


# -- deadlines -----------------------------------------------------------------


def test_deadline_cancels_one_request_other_completes_bit_equal(tmp_path):
    """Two requests, one with an already-lapsed deadline: the scheduler
    cancels it at the next chunk boundary (journaled + v10 ``deadline``
    event) and the survivor completes bit-equal to the oracle."""
    sched = ServeScheduler(
        str(tmp_path / "state"), quantum=32, slots=2, chunk=2,
        telemetry_dir=str(tmp_path / "tm"), run_id="dl",
    )
    try:
        sched.submit(
            {"id": "doomed", "pattern": 4, "size": 32,
             "generations": 500, "deadline_s": 0.0}
        )
        sched.submit(
            {"id": "fine", "pattern": 4, "size": 32, "generations": 6}
        )
        sched.run_until_drained()
        doomed = sched.get_result("doomed")
        assert doomed.status == "expired"
        assert doomed.result["status"] == "expired"
        fine = sched.result_board("fine")
        assert np.array_equal(fine, _oracle(4, 32, 6))
    finally:
        sched.close()
    entries, _ = journal_mod.replay(
        str(tmp_path / "state" / "journal.jsonl")
    )
    assert entries["doomed"]["status"] == "cancelled"
    assert entries["fine"]["status"] == "completed"
    recs = _events(tmp_path / "tm")
    assert any(
        r["event"] == "serve"
        and r["action"] == "deadline"
        and r["request_id"] == "doomed"
        for r in recs
    )


def test_deadline_cancels_running_slot_survivor_stays_bit_equal(tmp_path):
    """A deadline that expires while its request is RUNNING in a batch
    slot: cancellation drops the group's device stack, so the
    co-resident survivor's host board must be synced from the stack
    first — otherwise it is rebuilt from a stale board while its
    generation counter keeps the advanced value, and it completes with
    fewer generations than reported (breaking bit-equality)."""
    sched = ServeScheduler(
        str(tmp_path / "state"), quantum=32, slots=2, chunk=2,
    )
    try:
        sched.submit(
            {"id": "doomed", "pattern": 4, "size": 32,
             "generations": 500, "deadline_s": 3600.0}
        )
        # The survivor is an r-pentomino (methuselah): every generation
        # differs for hundreds of steps, so a survivor silently rebuilt
        # from a stale board CANNOT sneak past the oracle comparison.
        sched.submit(
            {"id": "fine", "pattern": 6, "size": 32, "generations": 10}
        )
        sched.run_once()  # both enter slots and step one chunk
        doomed = sched.get_result("doomed")
        assert doomed.status == "running"
        assert doomed.generation > 0
        doomed.submitted_t -= 7200.0  # lapse the deadline mid-flight
        sched.run_until_drained()
        assert doomed.status == "expired"
        # The cancelled request reports the generation it truly reached.
        assert doomed.result["generation"] == doomed.generation > 0
        assert np.array_equal(
            sched.result_board("fine"), _oracle(6, 32, 10)
        )
        assert sched.get_result("fine").result["generation"] == 10
    finally:
        sched.close()


def test_replay_restores_original_admit_time_for_deadlines(tmp_path):
    """Journal replay restores ``submitted_t`` from the admit record's
    ``t`` — a deadlined request must not get a fresh deadline budget on
    every supervised restart (nor undercount ``latency_s``) — and
    restores the v12 ``trace_id`` stamped on the admit, so a replayed
    request keeps its trace identity and the reader can stitch its
    pre-crash spans back on (gol_tpu/telemetry/trace.py)."""
    import os as os_mod
    import time as time_mod

    state_dir = str(tmp_path / "state")
    os_mod.makedirs(state_dir, exist_ok=True)
    j = journal_mod.Journal(os_mod.path.join(state_dir, "journal.jsonl"))
    req = {
        "id": "stale", "pattern": 4, "size": 32, "generations": 500,
        "engine": "auto", "deadline_s": 60.0, "stream_stats": False,
    }
    rec = journal_mod.record(
        "admit", "stale", request=req, ordinal=0,
        trace_id="tr-stale-precrash",
    )
    rec["t"] = time_mod.time() - 120.0  # admitted two minutes ago
    j.append(rec)
    j.close()
    sched = ServeScheduler(state_dir, quantum=32, slots=2, chunk=2)
    try:
        state = sched.get_result("stale")
        assert state is not None
        assert state.submitted_t == rec["t"]  # not restart time
        assert state.trace_id == "tr-stale-precrash"  # original, not fresh
        # The wait epoch restarts at replay: the crash gap must read as
        # stall in the decomposition, never as queue wait.
        assert state.queued_t > rec["t"]
        sched.run_until_drained()  # 60s deadline lapsed 60s ago
        assert state.status == "expired"
        assert state.result["trace_id"] == "tr-stale-precrash"
    finally:
        sched.close()


# -- guard isolation -----------------------------------------------------------


def test_guard_bitflip_replays_only_the_poisoned_bucket(tmp_path):
    """A bitflip injected into one request's world rolls back and
    replays ONLY that request's bucket group; the other bucket's replay
    counter stays zero and both results are bit-equal to the oracle."""
    from gol_tpu.resilience import faults

    faults.install(
        faults.FaultPlan.from_obj(
            [{"site": "board.bitflip", "at": 4, "world": 0,
              "row": 3, "col": 5, "value": 165}]
        )
    )
    sched = ServeScheduler(
        str(tmp_path / "state"), quantum=32, slots=2, chunk=2,
        telemetry_dir=str(tmp_path / "tm"), run_id="iso",
    )
    try:
        sched.submit(  # ordinal 0 — the fault plan's target
            {"id": "hit", "pattern": 4, "size": 32, "generations": 6}
        )
        sched.submit(  # ordinal 1, different bucket (64x64)
            {"id": "bystander", "pattern": 4, "size": 48,
             "generations": 6}
        )
        sched.run_until_drained()
        assert sched.guard_failures >= 1
        groups = {g.label: g for g in sched._groups.values()}
        hit_grp = groups["32x32/bitpack"]
        other = [g for lbl, g in groups.items() if g is not hit_grp]
        assert hit_grp.replays >= 1
        assert all(g.replays == 0 for g in other), (
            "a fault in one request's world replayed another bucket"
        )
        assert np.array_equal(
            sched.result_board("hit"), _oracle(4, 32, 6)
        )
        assert np.array_equal(
            sched.result_board("bystander"), _oracle(4, 48, 6)
        )
    finally:
        faults.clear()
        sched.close()
    recs = _events(tmp_path / "tm")
    bad = [
        r for r in recs
        if r["event"] == "guard_audit" and not r["ok"]
    ]
    assert bad and all(r["request_id"] == "hit" for r in bad)
    assert any(r["event"] == "fault" for r in recs)


# -- crash-safe replay ---------------------------------------------------------


def test_restart_replays_journal_and_completes_exactly_once(tmp_path):
    """Scheduler A admits three requests, steps partway, and is
    abandoned mid-batch (the in-process stand-in for SIGKILL — the real
    supervised drill is scripts/serve_smoke.py).  Scheduler B over the
    same state directory re-admits every unfinished request from the
    journal and completes each exactly once, bit-equal to the oracle."""
    state = str(tmp_path / "state")
    a = ServeScheduler(
        state, quantum=32, slots=2, chunk=2,
        telemetry_dir=str(tmp_path / "tma"), run_id="a",
    )
    for i in range(3):
        a.submit(
            {"id": f"w{i}", "pattern": 4, "size": 32,
             "generations": 8}
        )
    a.run_once()  # partway through the batch, then "die" (no close)
    assert a.outstanding() == 3

    b = ServeScheduler(
        state, quantum=32, slots=2, chunk=2,
        telemetry_dir=str(tmp_path / "tmb"), run_id="b",
    )
    try:
        assert b.outstanding() == 3  # journal replay re-admitted all
        b.run_until_drained()
        want = _oracle(4, 32, 8)
        for i in range(3):
            assert np.array_equal(b.result_board(f"w{i}"), want)
        assert b.completed_total == 3
    finally:
        b.close()
    recs = _events(tmp_path / "tmb")
    requeues = [
        r["request_id"]
        for r in recs
        if r["event"] == "serve" and r["action"] == "requeue"
    ]
    assert sorted(requeues) == ["w0", "w1", "w2"]
    # Exactly once: one complete record per id across the whole journal.
    entries, _ = journal_mod.replay(state + "/journal.jsonl")
    assert all(e["status"] == "completed" for e in entries.values())

    # A third scheduler sees only terminal state: nothing to re-run.
    c = ServeScheduler(state, quantum=32, slots=2, chunk=2)
    try:
        assert c.outstanding() == 0
        assert c.get_result("w0").status == "done"
        assert np.array_equal(c.result_board("w1"), want)
    finally:
        c.close()


# -- the batch-runtime satellite ----------------------------------------------


def test_batch_runtime_on_world_complete_hook(tmp_path):
    """The batch runtime's completion callback — the hook the serve
    scheduler's slot-refill design generalizes — fires once per world
    with the final board."""
    from gol_tpu.batch import GolBatchRuntime

    worlds = [
        patterns.init_global(4, 32, 1),
        patterns.init_global(4, 48, 1),
    ]
    seen = {}
    brt = GolBatchRuntime(
        worlds=worlds,
        engine="auto",
        on_world_complete=lambda i, board, gen: seen.setdefault(
            i, (board.copy(), gen)
        ),
    )
    _, boards = brt.run(4)
    assert sorted(seen) == [0, 1]
    for i, want in enumerate(boards):
        got, gen = seen[i]
        assert gen == 4
        assert np.array_equal(got, np.asarray(want))


# -- live elasticity + readiness (docs/RESILIENCE.md "Live elasticity") --------


def test_retry_after_startup_window_clamps_to_default(tmp_path):
    """Before any completion lands there is no drain rate to estimate:
    the 429 hint must be the documented 0.5s/request default, not a
    division by a junk rate — and a real rate takes over afterwards,
    clamped to the [0.1, 30] window."""
    sched = ServeScheduler(
        str(tmp_path / "state"), quantum=32, slots=1, queue_depth=1,
    )
    req = {"pattern": 4, "size": 32, "generations": 2}

    def rejected():
        with pytest.raises(Rejected) as exc:
            sched.submit(dict(req, id="no"))
        return exc.value.retry_after

    try:
        sched.submit(dict(req, id="ok"))  # fills the bounded queue
        # zero-completions startup window: 1 request ahead x 0.5s default
        assert rejected() == pytest.approx(0.5)
        sched._complete_times.extend([100.0, 102.0])  # 0.5 completions/s
        assert rejected() == pytest.approx(2.0)  # ahead=1 / rate
        sched._complete_times.clear()
        sched._complete_times.extend([0.0, 1000.0])  # glacial rate
        assert rejected() == pytest.approx(30.0)  # clamped to the max
    finally:
        sched.close()


def test_readyz_splits_liveness_from_readiness(tmp_path):
    """/healthz is liveness (always 200, even mid-reshard); /readyz is
    readiness and answers 503 through a live-reshard window or a drain
    so an orchestrator steers traffic away without restarting us."""
    from gol_tpu.serve.client import SimClient
    from gol_tpu.serve.server import ServeServer

    sched = ServeScheduler(str(tmp_path / "state"), quantum=32)
    srv = ServeServer(sched, 0)
    c = SimClient(f"http://127.0.0.1:{srv.port}")
    try:
        assert c.healthz()["ready"] is True
        status, payload = c._call("GET", "/readyz")
        assert status == 200 and payload["ready"] is True

        sched._resharding = True  # the live-reshard window
        status, payload = c._call("GET", "/readyz")
        assert status == 503 and payload["ready"] is False
        hz = c.healthz()  # liveness holds through the window
        assert hz["ok"] is True and hz["ready"] is False
        sched._resharding = False

        sched.drain()
        status, payload = c._call("GET", "/readyz")
        assert status == 503 and payload["draining"] is True
    finally:
        srv.close()
        sched.close()


def test_wait_for_across_live_reshard_never_404(tmp_path):
    """A client polling a request that rides THROUGH a device-loss
    live-reshard sees an uninterrupted 200/202 stream and the bit-exact
    final board — never a 404, never a connection drop.  (wait_for
    raises KeyError on any 404, so its success IS the assertion.)"""
    import threading

    from gol_tpu.resilience import faults as faults_mod
    from gol_tpu.serve.client import SimClient
    from gol_tpu.serve.scheduler import decode_board
    from gol_tpu.serve.server import ServeServer

    faults_mod.install(faults_mod.FaultPlan.loads(
        '[{"site": "device.loss", "at": 4, "device": 1}]'
    ))
    sched = ServeScheduler(
        str(tmp_path / "state"), quantum=32, slots=4, chunk=2,
        mesh_devices=4,
        telemetry_dir=str(tmp_path / "tm"), run_id="elastic",
    )
    srv = ServeServer(sched, 0)
    client = SimClient(f"http://127.0.0.1:{srv.port}")
    try:
        client.submit(
            {"id": "r1", "pattern": 4, "size": 32, "generations": 12}
        )
        driver = threading.Thread(target=sched.run_until_drained)
        driver.start()
        payload = client.wait_for("r1", timeout_s=120.0, poll_s=0.01)
        driver.join(timeout=60.0)
        assert payload["status"] == "done"
        assert np.array_equal(
            decode_board(payload["board"]), _oracle(4, 32, 12)
        )
        assert sched.live_reshards >= 1  # the loss really did reshard
    finally:
        faults_mod.clear()
        srv.close()
        sched.close()
    recs = _events(tmp_path / "tm")
    assert any(
        r["event"] == "health" and r["verdict"] == "device_loss"
        for r in recs
    )
    assert any(r["event"] == "reshard" and r.get("live") for r in recs)


def test_midflight_join_does_not_rewind_residents(tmp_path):
    """A request joining a bucket group whose stack is mid-flight must
    not rewind the residents: the join rebuilds the stack from host
    boards, so the residents' boards have to be synced from the device
    stack first.  (Pattern 4 is periodic at these sizes and masks the
    rewind — pattern 6 actually evolves.)"""
    sched = ServeScheduler(
        str(tmp_path / "state"), quantum=64, slots=4, chunk=2,
    )
    try:
        sched.submit(
            {"id": "resident", "pattern": 6, "size": 32, "generations": 8}
        )
        assert sched.run_once()  # 2 generations alone in the bucket
        sched.submit(  # same 64x64/bitpack bucket: joins the live group
            {"id": "joiner", "pattern": 6, "size": 64, "generations": 8}
        )
        sched.run_until_drained()
        assert np.array_equal(
            sched.result_board("resident"), _oracle(6, 32, 8)
        )
        assert np.array_equal(
            sched.result_board("joiner"), _oracle(6, 64, 8)
        )
    finally:
        sched.close()


# -- lockcheck regressions (docs/ANALYSIS.md "Concurrency matrix") ------------


def test_terminal_status_never_stamped_before_result(tmp_path):
    """Red/green pin on the _finish/_cancel write order.  The HTTP
    handlers snapshot request state via peek(); lockcheck found the old
    _cancel stamped ``status="expired"`` before building its payload,
    so a racing reader could observe a terminal status with
    ``result=None`` and answer 202 forever.  A sentinel subclass
    asserts the ordering at the exact write sites, on both terminal
    paths (deadline expiry and normal completion)."""
    import gol_tpu.serve.scheduler as sched_mod

    torn = []

    class OrderedState(sched_mod.RequestState):
        def __setattr__(self, name, value):
            if (
                name == "status"
                and value in ("done", "expired")
                and getattr(self, "result", None) is None
            ):
                torn.append((self.request.id, value))
            super().__setattr__(name, value)

    real = sched_mod.RequestState
    sched_mod.RequestState = OrderedState
    try:
        sched = ServeScheduler(
            str(tmp_path / "state"), quantum=32, slots=2, chunk=2
        )
        try:
            sched.submit(
                {"id": "doomed", "pattern": 4, "size": 32,
                 "generations": 500, "deadline_s": 0.0}
            )
            sched.submit(
                {"id": "fine", "pattern": 4, "size": 32,
                 "generations": 4}
            )
            sched.run_until_drained()
            assert sched.get_result("doomed").status == "expired"
            assert sched.get_result("fine").status == "done"
        finally:
            sched.close()
    finally:
        sched_mod.RequestState = real
    assert torn == []


def test_peek_takes_the_scheduler_lock(tmp_path):
    """peek() is the locked snapshot the handlers read through; a
    reader blocked behind a held scheduler lock is exactly the
    consistency the old unlocked field reads never had."""
    import threading

    sched = ServeScheduler(str(tmp_path / "state"), quantum=32)
    try:
        sched.submit(
            {"id": "r", "pattern": 4, "size": 32, "generations": 4}
        )
        assert sched.peek("missing") is None
        snap = sched.peek("r")
        assert snap["status"] == "queued" and snap["result"] is None

        acquired, released = threading.Event(), threading.Event()

        def hold():
            with sched._lock:
                acquired.set()
                released.wait(5.0)

        holder = threading.Thread(target=hold)
        holder.start()
        assert acquired.wait(5.0)
        got = []
        reader = threading.Thread(
            target=lambda: got.append(sched.peek("r"))
        )
        reader.start()
        reader.join(0.3)
        assert reader.is_alive(), "peek returned without the lock"
        released.set()
        reader.join(5.0)
        holder.join(5.0)
        assert got and got[0]["id"] == "r"

        sched.run_until_drained()
        snap = sched.peek("r")
        assert snap["status"] == "done" and snap["result"] is not None
    finally:
        sched.close()
