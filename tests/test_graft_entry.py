"""Driver contract: entry() compile-check and dryrun_multichip on CPU mesh."""

import os
import subprocess

import pytest
import sys

import jax

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import __graft_entry__  # noqa: E402


def test_entry_jits_and_runs():
    fn, args = __graft_entry__.entry()
    out = jax.jit(fn)(*args)
    out.block_until_ready()
    assert out.shape == args[0].shape and out.dtype == args[0].dtype


@pytest.mark.slow  # minutes-scale interpret-mode sweep; run with -m slow
def test_dryrun_multichip_8():
    __graft_entry__.dryrun_multichip(8)


@pytest.mark.slow  # minutes-scale interpret-mode sweep; run with -m slow
def test_dryrun_multichip_odd_counts():
    for n in (1, 2, 3, 6):
        __graft_entry__.dryrun_multichip(n)


def test_bench_prints_one_json_line():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        capture_output=True,
        text=True,
        env=env,
        timeout=570,
    )
    assert proc.returncode == 0, proc.stderr
    import json

    lines = [l for l in proc.stdout.splitlines() if l.strip()]
    assert len(lines) == 1
    rec = json.loads(lines[0])
    assert {"metric", "value", "unit", "vs_baseline"} <= set(rec)
    assert rec["value"] > 0
