"""CLI surface parity: arg handling, timing line, dump files, compat mode."""

import os
import re
import subprocess
import sys

import numpy as np

from gol_tpu import cli
from gol_tpu.utils import io as gol_io

from tests import oracle


def run_cli(args, tmp_path):
    """Run the CLI in-process with cwd-style outdir control."""
    return cli.main(list(args) + ["--outdir", str(tmp_path)])


def test_wrong_argc_prints_usage(capsys):
    rc = cli.main(["1", "2", "3"])
    out = capsys.readouterr().out
    assert rc == 255
    assert "5 arguments" in out


def test_atoi_semantics():
    assert cli.atoi("42") == 42
    assert cli.atoi("  -7") == -7
    assert cli.atoi("12abc") == 12
    assert cli.atoi("abc") == 0
    assert cli.atoi("") == 0


def test_unknown_pattern_rejected(capsys, tmp_path):
    # 10 is the first unassigned id (8/9 became the sparse-zoo seeds).
    rc = run_cli(["10", "32", "1", "64", "0"], tmp_path)
    assert rc == 255
    assert "not been implemented" in capsys.readouterr().out


def test_zero_threads_rejected(capsys, tmp_path):
    """Bug B5 (0-block silent no-op) becomes a hard error."""
    rc = run_cli(["0", "32", "1", "0", "0"], tmp_path)
    assert rc == 255
    assert "threads" in capsys.readouterr().out


def test_run_blinker_writes_dump_and_timing(capsys, tmp_path):
    rc = run_cli(["4", "8", "2", "64", "1"], tmp_path)
    assert rc == 0
    out = capsys.readouterr().out
    m = re.search(
        r"^TOTAL DURATION : (\d+\.\d{5}), number of cell updates = (\d+)$",
        out,
        re.M,
    )
    assert m, out
    assert int(m.group(2)) == 1 * 8 * 8 * 2  # numRank*H*W*iters
    assert "running in parallel on a TPU" in out

    path = tmp_path / "Rank_0_of_1.txt"
    assert path.exists()
    row0, block = gol_io.read_rank_file(str(path))
    # Blinker has period 2: after 2 steps the world equals t=0.
    expected = np.zeros((8, 8), np.uint8)
    expected[0, 0] = expected[0, 1] = expected[0, 7] = 1
    np.testing.assert_array_equal(block, expected)


def test_on_off_zero_writes_nothing(capsys, tmp_path):
    rc = run_cli(["4", "8", "1", "64", "0"], tmp_path)
    assert rc == 0
    assert list(tmp_path.iterdir()) == []


def test_multirank_stale_halo_matches_reference_oracle(capsys, tmp_path):
    """End-to-end bit-parity: CLI in compat mode == NumPy reference simulator,
    through the byte-exact per-rank files."""
    size, ranks, iters = 8, 3, 5
    rc = cli.main(
        ["1", str(size), str(iters), "32", "1"]
        + ["--outdir", str(tmp_path), "--ranks", str(ranks), "--halo", "stale_t0"]
    )
    assert rc == 0
    board0 = np.ones((ranks * size, size), np.uint8)
    expected = oracle.simulate_reference(board0, ranks, iters)
    for r in range(ranks):
        row0, block = gol_io.read_rank_file(
            str(tmp_path / f"Rank_{r}_of_{ranks}.txt")
        )
        assert row0 == r * size
        np.testing.assert_array_equal(block, expected[r * size : (r + 1) * size])


def test_bad_resume_path_clean_error(capsys, tmp_path):
    rc = run_cli(["0", "8", "1", "32", "0", "--resume", "/nonexistent.npz"], tmp_path)
    assert rc == 255
    out = capsys.readouterr().out
    assert "Traceback" not in out and "nonexistent" in out


def test_compat_banner(capsys, tmp_path):
    rc = run_cli(["0", "8", "1", "32", "0", "--compat-banner"], tmp_path)
    assert rc == 0
    assert "on a GPU on multiple ranks." in capsys.readouterr().out


def test_module_entrypoint_runs():
    """`python -m gol_tpu` end-to-end in a subprocess (CPU backend)."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, "-m", "gol_tpu", "4", "8", "2", "64", "0"],
        capture_output=True,
        text=True,
        env=env,
        timeout=300,
    )
    assert proc.returncode == 0, proc.stderr
    assert "TOTAL DURATION : " in proc.stdout

def test_rank_files_created_at_startup_before_validation(capsys, tmp_path):
    """Reference lifecycle (gol-main.c:64-73): with on_off=1 the rank files
    are fopen'd "w" right after process init, BEFORE world validation — so
    a run that dies on an unknown pattern still leaves (empty) files, and a
    stale dump from an earlier run is truncated at startup."""
    stale = tmp_path / "Rank_0_of_1.txt"
    stale.write_bytes(b"stale dump from an earlier run\n")
    rc = run_cli(["10", "32", "1", "64", "1"], tmp_path)  # unknown pattern
    assert rc == 255
    assert "not been implemented" in capsys.readouterr().out
    assert stale.exists() and stale.read_bytes() == b""  # created+truncated


def test_rank_file_open_failure_prints_reference_error(capsys, tmp_path):
    """fopen failure prints exactly `ERROR IN RANK %d` (no newline) and
    exits -1 (gol-main.c:68-71).  Induced by squatting a directory on the
    rank-1 filename (root ignores permission bits, so chmod won't do)."""
    os.makedirs(tmp_path / "Rank_1_of_2.txt")
    rc = run_cli(["4", "8", "2", "64", "1", "--ranks", "2"], tmp_path)
    assert rc == 255
    assert capsys.readouterr().out == "ERROR IN RANK 1"


def test_rank_file_outdir_failure_names_rank_zero(capsys, tmp_path):
    squat = tmp_path / "not_a_dir"
    squat.write_bytes(b"")
    rc = cli.main(["4", "8", "2", "64", "1", "--outdir", str(squat)])
    assert rc == 255
    assert capsys.readouterr().out == "ERROR IN RANK 0"


def test_rank_files_precreated_then_filled(capsys, tmp_path):
    """A successful run's startup-created files end up with the dump."""
    rc = run_cli(["4", "8", "2", "64", "1", "--ranks", "2"], tmp_path)
    assert rc == 0
    for r in range(2):
        data = (tmp_path / f"Rank_{r}_of_2.txt").read_bytes()
        assert data.startswith(b"#")
